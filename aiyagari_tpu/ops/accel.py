"""Fixed-point acceleration as pure carry-transformers for lax.while_loop
bodies: windowed Anderson mixing and SQUAREM extrapolation.

Every hot loop in the framework is a plain first-order fixed point x <- F(x):
the EGM policy iteration contracts at rate beta per sweep (~290 cold sweeps
at the shipped calibration), the Young stationary distribution power-iterates
at the chain's subdominant-eigenvalue rate (hundreds to thousands of sweeps
at tol 1e-10), and the Krusell-Smith ALM closes with a damped host update.
Auclert et al. (2021, PAPERS.md) identify exactly these inner fixed points as
the dominant cost of heterogeneous-agent pipelines; this module accelerates
them WITHOUT touching the operator F or the stopping rule, so the solution
and its convergence semantics are unchanged.

Design constraints, in order:

  * The accelerators are CARRY TRANSFORMERS, not loop drivers: a loop body
    computes its plain image gx = F(x) exactly as before (the sweep, the
    distance, the effective tolerance), then asks `accel_step(state, x, gx)`
    what the NEXT iterate should be. One F evaluation per loop iteration for
    both methods, so the solvers' reported `iterations` keep counting sweeps
    and the telemetry stays honest.
  * Everything is traceable and batchable: fixed-size ring-buffer history
    (no dynamic shapes), an [m, m] regularized normal-equations solve (no
    host round trips), and `jnp.where` selection for every safeguard — the
    same code path runs under jit, vmap (equilibrium/batched.py), and
    shard_map (solvers/egm_sharded.py, where `axis` makes the inner products
    and sup-norms global via psum/pmax).
  * SAFEGUARDED by construction: whenever the extrapolated residual fails
    to decrease — grows past `safeguard_growth` times the previous one, the
    tolerance that separates Anderson's normal transient non-monotonicity
    from a genuinely bad proposal — the step falls back to the plain
    (damped) update and the history restarts; non-finite or wild
    extrapolations (sup-norm step beyond any contraction rate's legitimate
    res/(1-rho) jump) fall back without restarting. The first `delay`
    calls take the plain step and record nothing: a kinked operator's early
    trajectory (EGM's moving constraint boundary) poisons the history's
    linear model, and burning it in is measurably cheaper than
    extrapolating through it. `AccelState.trips` counts the fallbacks, so
    tests can assert the safeguard actually engaged on adversarial maps.
  * Iterates with invariants re-project: `project_simplex` (clip negatives,
    renormalize) keeps an accelerated distribution a distribution;
    `project_floor` keeps an accelerated consumption policy strictly
    positive (u'(c) = c^-sigma must stay evaluable).

Anderson (type II, windowed): with residuals f_i = g_i - x_i and
differences taken against the CURRENT iterate, solve the regularized
least-squares problem

    gamma* = argmin_gamma |f_k - dF gamma|^2 + lam |gamma|^2,
    dF[j] = f_k - f_{k-j-1},   lam = regularization * tr(dF dF') + tiny,

via its [m, m] normal equations, then propose

    x_next = (x_k + damping * f_k) - gamma* @ (dX + damping * dF).

With damping=1 this is the classic g_k - gamma @ dG update (the same
formula as the ALM host path, host_anderson_step). SQUAREM (Varadhan &
Roland 2008, scheme S3) runs a two-evaluation cycle through a phase
counter: phase 0 stashes (x0, r = F(x0) - x0) and emits the plain image;
phase 1 forms v = (F(x1) - x1) - r, the steplength
alpha = -max(1, sqrt(<r,r>/<v,v>)), and proposes the squared-extrapolation
iterate x0 - 2 alpha r + alpha^2 v (alpha = -1 reproduces the plain step
exactly, so the clamp IS the minimal-step safeguard).

When to prefer which: Anderson wins when the linearized operator has
clustered or complex spectrum and a short history can interpolate it (EGM,
the ALM coefficients); SQUAREM's scalar steplength is cheaper per sweep,
needs no linear algebra, and is the steadier choice for nonnegative
power-iteration operators (the stationary distribution) where Anderson's
signed extrapolation fights the simplex projection hardest.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "AccelState",
    "accel_init",
    "accel_step",
    "fixed_point_iterate",
    "host_anderson_step",
    "project_floor",
    "project_simplex",
]

# Explosion guard for the device path: a CORRECT accelerated step must move
# ~res/(1-rho) — 25x the residual at the EGM calibration's rho=0.96, 100x at
# a distribution chain's rho=0.99 — so the trust radius has to sit far above
# any contraction rate's legitimate jump and only catch genuinely degenerate
# least-squares extrapolations (it composes with the residual-decrease
# safeguard, which catches merely-bad steps one sweep later).
_WILD_STEP_FACTOR = 1e4
# The ALM host path's tighter trust test (near-affine 4-coefficient G whose
# damped reference update moves slowly; pre-existing behavior, pinned).
_HOST_WILD_STEP_FACTOR = 10.0


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class AccelState:
    """Acceleration carry. For Anderson, hist_x/hist_g are [m, *x.shape]
    ring buffers of past (iterate, image) pairs; for SQUAREM they are
    [1, *x.shape] slots holding (x0, r) of the current two-eval cycle and
    `head` is the cycle phase. `count` is the number of valid history
    entries, `head` the next ring write position, `prev_res` the sup-norm
    residual observed one call earlier (inf before the first), and `trips`
    counts safeguard fallbacks (plain-step reversions)."""

    hist_x: jax.Array
    hist_g: jax.Array
    head: jax.Array       # int32
    count: jax.Array      # int32
    prev_res: jax.Array   # scalar, x.dtype
    trips: jax.Array      # int32
    calls: jax.Array      # int32; accel_step invocations (delay gating)


def _validate(accel) -> None:
    if accel.method not in ("anderson", "squarem"):
        raise ValueError(
            f"unknown AccelConfig.method {accel.method!r}; expected "
            "'anderson' or 'squarem'")
    if accel.method == "anderson" and accel.memory < 1:
        raise ValueError(
            f"AccelConfig.memory must be >= 1, got {accel.memory}")
    if not 0.0 < accel.damping <= 1.0:
        raise ValueError(
            f"AccelConfig.damping must be in (0, 1], got {accel.damping}")
    if accel.method == "squarem" and accel.damping != 1.0:
        # SQUAREM's cycle algebra assumes x1 = F(x0) EXACTLY (r = x1 - x0
        # feeds the curvature estimate); a damped phase-0 emission would
        # silently corrupt alpha. Refuse rather than ignore the knob.
        raise ValueError(
            "AccelConfig.damping applies to Anderson only; SQUAREM's "
            f"two-eval cycle is undamped by construction (got {accel.damping})")
    if accel.regularization < 0.0:
        raise ValueError(
            f"AccelConfig.regularization must be >= 0, got "
            f"{accel.regularization}")
    if accel.delay < 0:
        raise ValueError(f"AccelConfig.delay must be >= 0, got {accel.delay}")
    if accel.safeguard_growth < 1.0:
        raise ValueError(
            f"AccelConfig.safeguard_growth must be >= 1.0, got "
            f"{accel.safeguard_growth}")


def accel_init(x0, accel) -> AccelState:
    """Initial acceleration carry for an iterate shaped like x0. Static in
    everything but x0's shape/dtype, so it traces cleanly inside jit."""
    _validate(accel)
    m = accel.memory if accel.method == "anderson" else 1
    z = jnp.zeros((m,) + x0.shape, x0.dtype)
    return AccelState(
        hist_x=z, hist_g=z, head=jnp.int32(0), count=jnp.int32(0),
        prev_res=jnp.array(jnp.inf, x0.dtype), trips=jnp.int32(0),
        calls=jnp.int32(0))


def project_simplex(x, axis=None):
    """Re-project an (extrapolated) distribution onto the simplex: clip
    negatives, renormalize to unit mass. `axis` names a mapped mesh axis to
    psum the mass over when x is a shard of the full distribution."""
    x = jnp.maximum(x, 0.0)
    total = jnp.sum(x)
    if axis is not None:
        total = jax.lax.psum(total, axis)
    return x / jnp.maximum(total, jnp.finfo(x.dtype).tiny)


def project_floor(floor_scale: float = 1e-8):
    """Positivity projection for consumption-like iterates: clamp at
    floor_scale * max|x| (pmax'd over `axis` when sharded). The floor sits
    orders of magnitude below any interior consumption level, so it never
    moves the fixed point — it only stops a transient Anderson overshoot
    from handing u'(c) = c^-sigma a nonpositive consumption."""

    def project(x, axis=None):
        scale = jnp.max(jnp.abs(x))
        if axis is not None:
            scale = jax.lax.pmax(scale, axis)
        return jnp.maximum(x, floor_scale * scale)

    return project


def _anderson_propose(state: AccelState, xf, gf, ff, accel, psum):
    """The windowed type-II Anderson proposal on flattened iterates.
    Returns (x_acc, step_sup) with invalid history rows masked out; the
    regularized normal equations make the [m, m] solve well-posed at any
    count (count=0 gives gamma=0, i.e. the plain damped step)."""
    m = state.hist_x.shape[0]
    hx = state.hist_x.reshape(m, -1)
    hg = state.hist_g.reshape(m, -1)
    hf = hg - hx
    # Ring validity: the `count` most recently written slots. Slot j's age
    # is (head - 1 - j) mod m; valid iff age < count.
    age = jnp.mod(state.head - 1 - jnp.arange(m), m)
    valid = (age < state.count)[:, None]
    dF = jnp.where(valid, ff[None, :] - hf, 0.0)
    dG = jnp.where(valid, gf[None, :] - hg, 0.0)
    dX = jnp.where(valid, xf[None, :] - hx, 0.0)
    A = psum(dF @ dF.T)                                        # [m, m]
    b = psum(dF @ ff)                                          # [m]
    lam = (jnp.asarray(accel.regularization, A.dtype) * jnp.trace(A)
           + jnp.finfo(A.dtype).tiny)
    gamma = jnp.linalg.solve(A + lam * jnp.eye(m, dtype=A.dtype), b)
    beta = jnp.asarray(accel.damping, xf.dtype)
    x_acc = (xf + beta * ff) - gamma @ (dX + beta * dF)
    return x_acc


def _push(state: AccelState, x, gx, *, restart, write) -> AccelState:
    """Write (x, gx) into the ring at `head`; on restart the pair becomes
    the ONLY valid entry (the history of a different trajectory segment
    must not leak into the next extrapolation). With write=False (the
    burn-in delay) the ring is untouched."""
    m = state.hist_x.shape[0]
    hist_x = jax.lax.dynamic_update_index_in_dim(state.hist_x, x, state.head, 0)
    hist_g = jax.lax.dynamic_update_index_in_dim(state.hist_g, gx, state.head, 0)
    count = jnp.where(restart, jnp.int32(1),
                      jnp.minimum(state.count + 1, jnp.int32(m)))
    head = jnp.mod(state.head + 1, m)
    return dataclasses.replace(
        state,
        hist_x=jnp.where(write, hist_x, state.hist_x),
        hist_g=jnp.where(write, hist_g, state.hist_g),
        head=jnp.where(write, head, state.head),
        count=jnp.where(write, count, state.count))


def _anderson_step(state, x, gx, accel, psum, pmax, project, axis):
    f = gx - x
    res = pmax(jnp.max(jnp.abs(f)))
    xf, gf, ff = x.reshape(-1), gx.reshape(-1), f.reshape(-1)
    beta = jnp.asarray(accel.damping, x.dtype)
    x_plain = xf + beta * ff
    x_acc = _anderson_propose(state, xf, gf, ff, accel, psum)
    active = state.calls >= accel.delay     # burn-in: plain steps, no history

    # Safeguards. (1) Residual fails to decrease — grows past
    # safeguard_growth times the previous one (the PREVIOUS proposal made
    # things genuinely worse, not just transiently non-monotone): take the
    # plain step and restart the history. NaN residuals (the windowed
    # inversion's deliberate escape poison, or genuine divergence) compare
    # False here, so they also select the plain step — and the caller's
    # while_loop exits on the NaN distance exactly as for the unaccelerated
    # solver. (2) Wild/non-finite extrapolation: plain step without a
    # restart (the history is fine; this proposal was not).
    growth = jnp.asarray(accel.safeguard_growth, res.dtype)
    decreased = res < growth * state.prev_res
    restart = ~decreased & (state.count > 0)
    step_sup = pmax(jnp.max(jnp.abs(x_acc - xf)))
    sane = jnp.isfinite(step_sup) & (step_sup <= _WILD_STEP_FACTOR * res)
    use_acc = active & decreased & sane & (state.count > 0)
    x_next = jnp.where(use_acc, x_acc, x_plain).reshape(x.shape)
    if project is not None:
        x_next = project(x_next, axis=axis)

    tripped = active & (state.count > 0) & ~use_acc
    state = _push(state, x, gx, restart=restart, write=active)
    return x_next, dataclasses.replace(
        state, prev_res=res, trips=state.trips + tripped.astype(jnp.int32),
        calls=state.calls + 1)


def _squarem_step(state, x, gx, accel, psum, pmax, project, axis):
    f = gx - x
    res = pmax(jnp.max(jnp.abs(f)))
    active = state.calls >= accel.delay     # burn-in: plain steps, no cycles
    phase1 = state.head > 0       # head doubles as the cycle phase
    x0 = state.hist_x[0]
    r = state.hist_g[0]
    v = f - r
    rr = psum(jnp.sum(r * r))
    vv = psum(jnp.sum(v * v))
    tiny = jnp.finfo(x.dtype).tiny
    alpha = -jnp.sqrt(rr / jnp.maximum(vv, tiny))
    alpha = jnp.minimum(alpha, jnp.asarray(-1.0, x.dtype))
    x_sq = (x0 - 2.0 * alpha * r + alpha * alpha * v).reshape(x.shape)

    # Phase-1 safeguards mirror the Anderson ones: the residual at x1 must
    # not have grown past safeguard_growth times the previous cycle's, the
    # extrapolation must be finite, and a degenerate curvature (vv ~ 0: F
    # is locally affine with slope ~1, nothing to square) falls back to the
    # plain image.
    growth = jnp.asarray(accel.safeguard_growth, res.dtype)
    decreased = res < growth * state.prev_res
    step_sup = pmax(jnp.max(jnp.abs(x_sq - x)))
    sane = (jnp.isfinite(step_sup) & (vv > tiny)
            & (step_sup <= _WILD_STEP_FACTOR * jnp.maximum(res, tiny)))
    extrapolate = phase1 & decreased & sane
    x_next = jnp.where(extrapolate, x_sq, gx)
    if project is not None:
        x_next = project(x_next, axis=axis)

    tripped = phase1 & ~extrapolate
    # Phase 0 stashes this cycle's anchor (x0 = x, r = f); phase 1 clears
    # it. prev_res only updates when a cycle completes, so the comparison
    # is cycle-over-cycle, not the sawtooth within one.
    stash = lambda buf, val: jnp.where(phase1 | ~active, jnp.zeros_like(buf),
                                       val[None].astype(buf.dtype))
    return x_next, dataclasses.replace(
        state,
        hist_x=stash(state.hist_x, x),
        hist_g=stash(state.hist_g, f),
        head=jnp.where(phase1 | ~active, jnp.int32(0), jnp.int32(1)),
        count=state.count,
        prev_res=jnp.where(phase1, res, state.prev_res),
        trips=state.trips + tripped.astype(jnp.int32),
        calls=state.calls + 1)


def accel_step(state: AccelState, x, gx, *, accel, axis=None, project=None):
    """One acceleration update: given the current iterate x and its plain
    fixed-point image gx = F(x), return (x_next, new_state) where x_next is
    the iterate the loop should carry forward.

    Pure and shape-stable: composes inside lax.while_loop bodies, under
    vmap, and under shard_map (pass `axis` so the least-squares inner
    products psum and the safeguard sup-norms pmax over the mapped axis —
    every device then computes the identical extrapolation). `project`
    re-imposes an invariant on the proposed iterate (project_simplex for
    distributions, project_floor for consumption policies); it is applied
    to plain fallback steps too, where it is a no-op by construction.

    The caller's stopping rule is untouched: it keeps measuring
    dist = |gx - x|, the genuine fixed-point residual at the carried
    iterate, so an accelerated solve that stops at dist < tol satisfies
    exactly the same convergence certificate as the plain one.
    """
    _validate(accel)
    psum = (lambda t: jax.lax.psum(t, axis)) if axis is not None else (lambda t: t)
    pmax = (lambda t: jax.lax.pmax(t, axis)) if axis is not None else (lambda t: t)
    if accel.method == "anderson":
        return _anderson_step(state, x, gx, accel, psum, pmax, project, axis)
    return _squarem_step(state, x, gx, accel, psum, pmax, project, axis)


def fixed_point_iterate(step, x0, *, accel=None, tol, max_iter, project=None):
    """Small generic driver: iterate x <- step(x) to a sup-norm fixed point
    under optional acceleration, returning (x, iterations, distance, state).

    This is the reference composition of accel_init/accel_step with a
    lax.while_loop (the pattern the EGM and distribution solvers inline),
    used by tests and available for new loops. `step` must be traceable.
    """
    st0 = accel_init(x0, accel) if accel is not None else None

    def cond(carry):
        _, dist, it, _ = carry
        return (dist >= tol) & (it < max_iter)

    def body(carry):
        x, _, it, st = carry
        gx = step(x)
        dist = jnp.max(jnp.abs(gx - x))
        if accel is None:
            x_next = gx if project is None else project(gx)
            return x_next, dist, it + 1, st
        x_next, st = accel_step(st, x, gx, accel=accel, project=project)
        return x_next, dist, it + 1, st

    x, dist, it, st = jax.lax.while_loop(
        cond, body, (x0, jnp.array(jnp.inf, x0.dtype), jnp.int32(0), st0))
    return x, it, dist, st


def host_anderson_step(Bs: list, Gs: list, damping: float, depth: int) -> np.ndarray:
    """Safeguarded Anderson (type-II) mixing on HOST for small fixed points
    whose map evaluation is a whole device pipeline — the Krusell-Smith ALM
    coefficients B = G(B), where one G is a household solve + cross-section
    simulation + regression (equilibrium/alm.py).

    Solves the least-squares residual combination over the last `depth`
    differences and extrapolates; falls back to the reference's damped update
    when history is short, the LS problem is degenerate, or the extrapolated
    step is wild (>10x the plain residual in sup norm — G is near-affine close
    to the fixed point, so a huge step means the history is still nonlinear).
    The same trust test as the device path's accel_step; NumPy lstsq instead
    of regularized normal equations because a 4-coefficient host problem has
    no conditioning or tracing constraints to design around.
    """
    B_k, G_k = Bs[-1], Gs[-1]
    damped = damping * G_k + (1.0 - damping) * B_k
    m = min(depth, len(Bs) - 1)
    if m < 1:
        return damped
    F = [g - b for b, g in zip(Bs, Gs)]
    dF = np.stack([F[-1] - F[-1 - i] for i in range(1, m + 1)], axis=1)   # [4, m]
    dG = np.stack([G_k - Gs[-1 - i] for i in range(1, m + 1)], axis=1)    # [4, m]
    gamma, *_ = np.linalg.lstsq(dF, F[-1], rcond=None)
    B_next = G_k - dG @ gamma
    res = float(np.max(np.abs(F[-1])))
    if not np.all(np.isfinite(B_next)) or float(np.max(np.abs(B_next - B_k))) > _HOST_WILD_STEP_FACTOR * res:
        return damped
    return B_next
