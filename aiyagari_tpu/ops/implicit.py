"""Implicit-function-theorem adjoints for converged fixed points (ISSUE 17).

Every hot loop in the solver stack — the EGM sweep, the stationary
push-forward, the GE bisection, the transition Newton path — is a
`lax.while_loop` fixed point, and reverse-mode AD cannot flow through a
while_loop. This module provides the one sanctioned way to differentiate
*through* a converged solve: wrap the converged iterate in a
`jax.custom_vjp` whose backward pass solves the ADJOINT system at the
fixed point instead of unrolling the iteration (DESIGN.md §8 has the
memory argument; the fake-news adjoint in transition/jacobian.py is the
in-repo exemplar of the same idea specialized to the transition operator).

Math. Let x* solve x = T(x, θ) with ∂T/∂x a contraction at x*. The IFT
gives dx*/dθ = (I - ∂T/∂x)^{-1} ∂T/∂θ, so for a downstream scalar L the
cotangent v = ∂L/∂x* pulls back through

    λ = v + (∂T/∂x)ᵀ λ          (the adjoint fixed point, solved here by
                                 Neumann iteration — each step is ONE
                                 vjp of the step function, same cost
                                 profile as a forward sweep)
    ∂L/∂θ = (∂T/∂θ)ᵀ λ .

`fixed_point_vjp` implements exactly that for pytree-valued fixed points;
`two_point_root_vjp` is the scalar specialization for root conditions
g(x*, θ) = 0 (the GE interest-rate closure), where the adjoint system is
a single division instead of a Neumann loop.

Primal bit-identity contract: the forward pass returns `x_star` UNCHANGED
(an identity function with a custom backward rule), so wrapping a solve
can never perturb the primal answer — gated bitwise by
tests/test_differentiable.py.

Lint rule AIYA205 (analysis/rules.py) flags `jax.grad`/`jax.jvp` applied
to an unwrapped solver fixed point anywhere outside this module: the
gradient of an unrolled while_loop is a trace-time error at best and a
silent wrong answer at worst, so this module is the only door.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["fixed_point_vjp", "neumann_adjoint", "two_point_root_vjp"]


def _tree_add(a, b):
    return jax.tree_util.tree_map(jnp.add, a, b)


def _tree_sub(a, b):
    return jax.tree_util.tree_map(jnp.subtract, a, b)


def _tree_max_abs(t):
    leaves = [jnp.max(jnp.abs(leaf)) for leaf in jax.tree_util.tree_leaves(t)]
    return functools.reduce(jnp.maximum, leaves)


def neumann_adjoint(vjp_x, v, *, tol, max_iter):
    """Solve λ = v + (∂T/∂x)ᵀ λ by Neumann iteration, where `vjp_x` applies
    (∂T/∂x)ᵀ to a cotangent pytree (the output of `jax.vjp` at the fixed
    point). Returns (λ, iterations, final sup-norm delta).

    The loop exits when the update falls below `tol` OR the iteration cap
    is hit OR the residual goes NaN — the condition `delta > tol` is False
    for NaN (the AIYA107 NaN-exit discipline), so a divergent adjoint
    (spectral radius ≥ 1) terminates and surfaces as a NaN gradient for the
    quarantine mask downstream, instead of spinning the cap.
    """
    delta0 = jnp.full_like(_tree_max_abs(v), jnp.inf)

    def cond(carry):
        _, delta, k = carry
        return (delta > tol) & (k < max_iter)

    def body(carry):
        lam, _, k = carry
        nxt = _tree_add(v, vjp_x(lam)[0])
        delta = _tree_max_abs(_tree_sub(nxt, lam))
        return nxt, delta, k + 1

    lam, delta, iters = lax.while_loop(
        cond, body, (v, delta0, jnp.asarray(0, jnp.int32)))
    return lam, iters, delta


def fixed_point_vjp(step_fn, x_star, params, *, tol=1e-13, max_iter=2000):
    """Differentiable view of a converged fixed point x* = step_fn(x*, θ).

    Forward: returns `x_star` unchanged (bit-identical primal). Backward:
    one Neumann adjoint solve against the converged iterate (see module
    docstring), then a single vjp of step_fn in the θ slot.

    `step_fn(x, params)` must be ONE differentiable sweep of the solver —
    the same operator the solver iterates, with any non-differentiable
    route (Pallas kernels, host callbacks) pinned to its XLA form. `x_star`
    and `params` are pytrees of floating arrays; anything non-differentiable
    (grids of ints, static config) belongs closed over in `step_fn`, not in
    `params`. The caller is responsible for having solved the primal under
    `lax.stop_gradient` so no gradient path tries to enter the solver's own
    while_loop.

    The cotangent returned for the `x_star` argument slot is zero: by the
    IFT the converged iterate is a *function of θ*, not an independent
    input, so all sensitivity is routed to θ.
    """

    @jax.custom_vjp
    def _fp(x, p):
        return x

    def _fwd(x, p):
        return x, (x, p)

    def _bwd(res, v):
        x, p = res
        _, vjp_x = jax.vjp(lambda xx: step_fn(xx, p), x)
        lam, _, _ = neumann_adjoint(vjp_x, v, tol=tol, max_iter=max_iter)
        _, vjp_p = jax.vjp(lambda pp: step_fn(x, pp), p)
        bar_p = vjp_p(lam)[0]
        bar_x = jax.tree_util.tree_map(jnp.zeros_like, x)
        return bar_x, bar_p

    _fp.defvjp(_fwd, _bwd)
    return _fp(lax.stop_gradient(x_star), params)


def two_point_root_vjp(gap_fn, x_star, params):
    """Scalar IFT through a root condition g(x*, θ) = 0 (the GE closure:
    x* is the market-clearing interest rate, g the excess capital supply).

    Forward: returns the converged scalar root unchanged. Backward: for a
    downstream cotangent v, dx*/dθ = -(∂g/∂x)^{-1} ∂g/∂θ gives

        ∂L/∂θ = (∂g/∂θ)ᵀ · (-v / ∂g/∂x),

    computed with ONE vjp of `gap_fn` (which may itself contain
    fixed_point_vjp-wrapped inner solves — their custom rules fire inside
    this pullback). A zero ∂g/∂x (market clearing locally insensitive to
    the rate — a degenerate economy) yields ±inf/NaN that the calibration
    quarantine masks out rather than poisoning the reduction.
    """

    @jax.custom_vjp
    def _root(x, p):
        return x

    def _fwd(x, p):
        return x, (x, p)

    def _bwd(res, v):
        x, p = res
        _, pull = jax.vjp(gap_fn, x, p)
        g_x, _ = pull(jnp.ones_like(v))
        scale = -v / g_x
        _, bar_p = pull(scale)
        return jnp.zeros_like(x), bar_p

    _root.defvjp(_fwd, _bwd)
    return _root(lax.stop_gradient(x_star), params)
