"""Batched golden-section maximization: the trace-friendly replacement for the
reference's 1,600 per-point fminbnd calls (Krusell_Smith_VFI.m:161-165).

Fixed iteration count (no data-dependent convergence), every candidate
evaluation batched over all points at once — one vectorized objective call per
iteration instead of 1,600 scalar optimizations per improvement step.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

__all__ = ["golden_section_max"]

_INVPHI = 0.6180339887498949   # (sqrt(5)-1)/2
_INVPHI2 = 0.3819660112501051  # (3-sqrt(5))/2


def golden_section_max(f: Callable, lo: jnp.ndarray, hi: jnp.ndarray, n_iters: int = 48) -> jnp.ndarray:
    """Maximize a concave-ish scalar objective elementwise over [lo, hi].

    f maps candidate arrays (same shape as lo/hi) to objective values of the
    same shape. After n_iters the bracket width is (hi-lo)*invphi^n_iters
    (n=48 on a width-1000 bracket -> ~1e-7 absolute), tighter than fminbnd's
    default 1e-4 TolX. Returns the bracket midpoint.
    """
    h = hi - lo
    x1 = lo + _INVPHI2 * h
    x2 = lo + _INVPHI * h
    f1 = f(x1)
    f2 = f(x2)

    def body(_, carry):
        lo, hi, x1, x2, f1, f2 = carry
        take_left = f1 > f2
        # Left: [lo, x2] with interior x1 -> new x1 probes lower third.
        new_hi = jnp.where(take_left, x2, hi)
        new_lo = jnp.where(take_left, lo, x1)
        h = new_hi - new_lo
        cand_left = new_lo + _INVPHI2 * h
        cand_right = new_lo + _INVPHI * h
        new_x1 = jnp.where(take_left, cand_left, x2)
        new_x2 = jnp.where(take_left, x1, cand_right)
        probe = jnp.where(take_left, cand_left, cand_right)
        fp = f(probe)
        new_f1 = jnp.where(take_left, fp, f2)
        new_f2 = jnp.where(take_left, f1, fp)
        return new_lo, new_hi, new_x1, new_x2, new_f1, new_f2

    lo, hi, *_ = jax.lax.fori_loop(0, n_iters, body, (lo, hi, x1, x2, f1, f2))
    return 0.5 * (lo + hi)
