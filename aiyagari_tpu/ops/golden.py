"""Batched golden-section maximization: the trace-friendly replacement for the
reference's 1,600 per-point fminbnd calls (Krusell_Smith_VFI.m:161-165).

Fixed iteration count (no data-dependent convergence), every candidate
evaluation batched over all points at once — one vectorized objective call per
iteration instead of 1,600 scalar optimizations per improvement step.
"""

from __future__ import annotations

import math
from typing import Callable

import jax
import jax.numpy as jnp

__all__ = ["golden_section_max", "unimodal_argmax_index"]

_INVPHI = 0.6180339887498949   # (sqrt(5)-1)/2
_INVPHI2 = 0.3819660112501051  # (3-sqrt(5))/2


def golden_section_max(f: Callable, lo: jnp.ndarray, hi: jnp.ndarray, n_iters: int = 48) -> jnp.ndarray:
    """Maximize a concave-ish scalar objective elementwise over [lo, hi].

    f maps candidate arrays (same shape as lo/hi) to objective values of the
    same shape. After n_iters the bracket width is (hi-lo)*invphi^n_iters
    (n=48 on a width-1000 bracket -> ~1e-7 absolute), tighter than fminbnd's
    default 1e-4 TolX. Returns the bracket midpoint.
    """
    h = hi - lo
    x1 = lo + _INVPHI2 * h
    x2 = lo + _INVPHI * h
    f1 = f(x1)
    f2 = f(x2)

    def body(_, carry):
        lo, hi, x1, x2, f1, f2 = carry
        take_left = f1 > f2
        # Left: [lo, x2] with interior x1 -> new x1 probes lower third.
        new_hi = jnp.where(take_left, x2, hi)
        new_lo = jnp.where(take_left, lo, x1)
        h = new_hi - new_lo
        cand_left = new_lo + _INVPHI2 * h
        cand_right = new_lo + _INVPHI * h
        new_x1 = jnp.where(take_left, cand_left, x2)
        new_x2 = jnp.where(take_left, x1, cand_right)
        probe = jnp.where(take_left, cand_left, cand_right)
        fp = f(probe)
        new_f1 = jnp.where(take_left, fp, f2)
        new_f2 = jnp.where(take_left, f1, fp)
        return new_lo, new_hi, new_x1, new_x2, new_f1, new_f2

    lo, hi, *_ = jax.lax.fori_loop(0, n_iters, body, (lo, hi, x1, x2, f1, f2))
    return 0.5 * (lo + hi)


def unimodal_argmax_index(f: Callable, hi_idx: jnp.ndarray, n_knots: int,
                          branch: int = 32, lo_idx=None) -> jnp.ndarray:
    """Batched coarse-to-fine argmax of a unimodal-in-index objective over
    integer indices [lo_idx, hi_idx] (inclusive, elementwise; lo_idx
    defaults to 0).

    f maps int32 index arrays of hi_idx's shape to objective values of the
    same shape (candidate axes are vmapped over it here); it should be
    unimodal in the index at every point — satisfied by the Bellman choice
    objective u(coh - a'_j) + EV_j when u is concave and the continuation
    value is concave in a' (the standard Aiyagari case).

    Each level samples `branch` evenly spaced candidates in the current
    bracket, keeps the best, and shrinks the bracket to +/- one sample
    spacing around it — depth log_{(branch-1)/2}(n) levels, O(na log na)
    work per Bellman sweep instead of the dense search's O(na^2).

    Why value sampling and not bisection on the rising-difference predicate:
    near the optimum the objective is flat below f32 resolution, and a
    predicate chain that only ever compares ADJACENT cells random-walks into
    regions hundreds of ulps below the max (measured: 2.6e-4 value error at
    grid 400, f32 — fatal for a 1e-5 tolerance). Sampling compares actual
    objective values across the whole bracket at every level, so like the
    dense argmax its value error is bounded at the rounding level of single
    evaluations, in f32 and f64 alike.
    """
    if branch < 5:
        # The bracket shrinks to 2*ceil(span/(branch-1)) per level, which is
        # non-contractive for branch <= 4 — the final pass would then cover
        # only `branch` of a still-wide bracket and return garbage.
        raise ValueError(f"branch must be >= 5, got {branch}")
    per_level = max(2, (branch - 1) // 2)
    depth = max(1, int(math.ceil(math.log(max(n_knots, 2)) / math.log(per_level))))
    ks = jnp.arange(branch, dtype=jnp.int32)
    fb = jax.vmap(f, in_axes=-1, out_axes=-1)

    floor = jnp.zeros_like(hi_idx) if lo_idx is None else jnp.broadcast_to(
        lo_idx, hi_idx.shape
    ).astype(hi_idx.dtype)
    hi_idx = jnp.maximum(hi_idx, floor)     # degenerate ranges collapse to floor
    lo = floor
    hi = hi_idx
    for _ in range(depth):
        span = hi - lo                                            # >= 0
        cand = lo[..., None] + (ks * span[..., None]) // (branch - 1)
        vals = fb(cand)
        best = jnp.take_along_axis(
            cand, jnp.argmax(vals, axis=-1)[..., None], axis=-1
        )[..., 0]
        spacing = (span + (branch - 2)) // (branch - 1)           # ceil, >= 0
        lo = jnp.maximum(best - spacing, floor)
        hi = jnp.minimum(best + spacing, hi_idx)
    # Final bracket has width <= 2 spacings of the last level (<= 2 for any
    # depth chosen above); one last dense pass over it.
    cand = jnp.minimum(lo[..., None] + ks, hi[..., None])
    vals = fb(cand)
    return jnp.take_along_axis(
        cand, jnp.argmax(vals, axis=-1)[..., None], axis=-1
    )[..., 0]
