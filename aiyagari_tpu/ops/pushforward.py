"""Scatter-free distribution push-forward: the lottery step's backend layer.

The Young-lottery cross-section update (sim/distribution.distribution_step)
moves asset mass through the policy lottery and then mixes income states.
Its reference formulation is a scatter-add (`.at[].add`) along the asset
axis — and XLA lowers scatters SERIALLY on TPU (and at ~120 ns/element on
the CPU host, BENCHMARKS.md round 7), which is exactly the wrong primitive
for an operator applied thousands of times per solve. This module owns the
equivalent scatter-free formulations and the `DistributionBackend` knob
selecting between them:

  "scatter"   — the reference `.at[].add` route, kept for parity pins.
  "transpose" — the monotone-lottery transpose: when the asset policy is
                monotone in assets, `idx` is sorted within each income row,
                so every scatter bucket is a CONTIGUOUS source segment.
                Segment sums of contiguous segments are cumsum differences:
                two exclusive cumsums + one searchsorted bound table + two
                gathers replace the scatter entirely — O(na log na), fully
                vectorized, exact mass conservation by telescoping.
  "banded"    — the two-leg lottery operator materialized ONCE per policy
                as a dense block band: targets tile into `band_block`-wide
                tiles, each tile's (contiguous, by monotonicity) source
                window pads to a static `band_width`, and each sweep is one
                batched [1, bw] x [bw, tb] matmul per tile — MXU-resident
                work instead of a scatter, amortizing the build across the
                thousands of sweeps of a stationary solve.
  "pallas"    — the fused TPU kernel (ops/pallas_pushforward.py): lottery
                split + segment accumulation + the P' income mixing in one
                VMEM-tiled pass (interpret mode off-TPU, like
                pallas_bellman / pallas_inverse).
  "auto"      — the shipped default: "transpose" (wins or ties the scatter
                wall on every platform measured; the TPU-only routes stay
                opt-in until validated on hardware, the pallas_inverse
                lesson).

Validity and the loud fallback: the transpose and banded routes require the
per-row monotonicity of `idx` (EGM/VFI savings policies are monotone in
assets; clipping preserves it). Monotonicity is a data property, so the
check compiles INTO the program at plan-build time: the plan carries an
`ok` flag and `apply_pushforward` routes through `lax.cond`, falling back
banded -> transpose -> scatter, with a `jax.debug.print` warning emitted
from the traced program when a fallback fires (set
`aiyagari_tpu.ops.pushforward.WARN_ON_FALLBACK = False` to silence it in
adversarial tests). Results are therefore ALWAYS correct — a non-monotone
policy degrades to the reference route instead of corrupting mass.

The adjoint contract: every backend computes the SAME linear operator
L(idx, w_lo, P) — only the summation order differs — so the gather-form
adjoint `sim/distribution.expectation_step` satisfies
`<f, L mu> == <L' f, mu>` against every backend to float roundoff. The
sequence-space fake-news Jacobian (transition/jacobian.py) relies on that
pairing; tests/test_pushforward.py pins it per backend.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

import jax
import jax.numpy as jnp

__all__ = [
    "BACKENDS",
    "DEFAULT_BAND_BLOCK",
    "DEFAULT_BAND_WIDTH",
    "PushforwardPlan",
    "resolve_backend",
    "lottery_scatter",
    "plan_pushforward",
    "apply_pushforward",
    "pushforward_step",
    "shard_banded_plan",
]

BACKENDS = ("auto", "scatter", "transpose", "banded", "pallas")

# Banded-route geometry: targets per tile and the static source-window
# width each tile's (contiguous) source segment pads to. 128 matches the
# MXU tile edge; 2x headroom covers the window spill of a near-45-degree
# policy. Tiles whose true window exceeds band_width invalidate the plan
# (flat policy regions — e.g. a wide borrowing-constrained set mapping one
# bucket — can concentrate many sources on few targets), which routes the
# apply to the transpose fallback instead of truncating mass.
DEFAULT_BAND_BLOCK = 128
DEFAULT_BAND_WIDTH = 256

# Report scatter-free route fallbacks (non-monotone policy / band
# overflow) from the traced program as COUNTED degradation events: an async
# jax.debug.callback increments the process metrics counter
# `aiyagari_pushforward_fallback_total{route=...}` (diagnostics/metrics.py)
# and appends a "degradation" event to the active run ledger
# (diagnostics/ledger.py), so a production solve's degradations are
# scrape-able and diagnosable without rerunning. Module-level so tests that
# build adversarial lotteries on purpose can silence the reporting. Read at
# TRACE time: the flag's value is baked into each compiled program, so set
# it BEFORE the first trace of the plan you care about — flipping it later
# affects newly traced programs only, not jit-cache hits.
WARN_ON_FALLBACK = True

# The old always-on jax.debug.print warning is now OPT-IN (the
# AIYAGARI_DEBUG_LOTTERY pattern): counted events are the production
# signal; the print is a debugging aid that would otherwise spam every
# sweep-level trace of the KS/transition scan paths.
_FALLBACK_DEBUG = bool(os.environ.get("AIYAGARI_DEBUG_PUSHFORWARD", ""))


def _record_fallback(route: str) -> None:
    """Host side of the degradation event (runs on the runtime's async
    callback thread — must never raise into the solve)."""
    try:
        from aiyagari_tpu.diagnostics import ledger, metrics

        metrics.counter("aiyagari_pushforward_fallback_total",
                        route=route).inc()
        ledger.emit("degradation", event="pushforward_fallback", route=route,
                    n=1)
    except Exception:  # pragma: no cover - diagnostics must not kill solves
        pass


def resolve_backend(backend: Optional[str], *, na: Optional[int] = None,
                    dtype=None, f32_sim: bool = False,
                    batched: bool = False) -> str:
    """Validate a DistributionBackend name and resolve "auto".

    The shipped "auto" default is "transpose" on every platform: it is
    scatter-free, needs no per-policy build, wins or ties the scatter wall
    on the CPU host (BENCH_r08), and its TPU lowering is plain
    cumsum/gather HLO. The banded and pallas routes stay explicit opt-ins
    until validated on real hardware (the pallas_inverse round-2 lesson:
    fused TPU routes must be cross-checked on chip before any solver
    defaults to them). With tuning active (tuning/autotuner.py) a
    measured probe for this platform/grid-bucket/dtype — or the roofline
    prior on modeled platforms — wins over the default, and every "auto"
    resolution lands on the active run ledger as a `route_decision`
    event (exactly one per dispatch run and knob).

    f32_sim=True is the Krusell-Smith mixed-mode histogram scan's
    ACCURACY override (equilibrium/alm.py): the transpose route's bucket
    masses are differences of row-prefix cumsums, whose absolute O(eps *
    prefix-mass) error in an f32 scan sits exactly at the ALM stall
    detector's bias floor (measured: ~20% of rounds then fall back to
    f64, forfeiting the dtype split) — so "auto" keeps the scatter form
    there regardless of any measured wall. A correctness constraint, not
    a perf choice; the tuning cache is never consulted for it.

    batched=True is the VMAPPED-program context (the lockstep GE sweep /
    parallel-bracket rounds, equilibrium/batched.py): under vmap the
    transpose route's per-sweep take_along_axis gathers batch onto
    XLA:CPU's generic gather path and run ~5.5x per lane SLOWER than
    solo (measured at the ISSUE 15 ci calibration: 100 sweeps x 6 lanes —
    transpose 39.4 ms vs 6 x 1.2 ms solo; the scatter reference scales
    exactly linearly and wins the batched wall), so batched "auto" pins
    the scatter form on CPU hosts. Accelerators keep the standard
    resolution when no measurement exists — TPU scatter is the
    documented pathology the scatter-free routes exist to avoid. Like
    f32_sim, this is a recorded decision: the ledger explains why a
    sweep's distribution steps scatter on the host. Solo-context tuning
    probes are deliberately NOT consulted for batched programs — a
    measured solo winner is exactly the number the vmapped context
    invalidates. With tuning active the batched context consults its OWN
    measured entries instead (the autotuner's "pushforward_batched" knob
    races the candidates under vmap, ISSUE 16), so the vmapped choice is
    a measurement, not a heuristic, wherever a probe has run.

    `na`/`dtype` are optional resolution context (grid-bucket keying of
    the tuning cache); plan-build call sites pass them, the dispatch
    validation boundary does not.
    """
    if backend is None:
        backend = "auto"
    if backend not in BACKENDS:
        raise ValueError(
            f"unknown distribution backend {backend!r}; expected one of "
            f"{BACKENDS}")
    if backend != "auto":
        return backend
    if batched:
        import jax

        from aiyagari_tpu.tuning.autotuner import (
            _lookup,
            _record_decision,
            load_cache,
            tuning_active,
        )

        if tuning_active():
            # The batched context has its OWN probe (ISSUE 16,
            # autotuner "pushforward_batched": the solo walls are exactly
            # the numbers vmap invalidates) — a measured vmapped-race
            # winner beats both heuristics below. The decision is still
            # recorded under the "pushforward" knob: one knob name per
            # resolution site, so a run's route_decision trail stays one
            # event per site regardless of which context resolved it.
            from aiyagari_tpu.diagnostics import metrics

            entry = _lookup(load_cache(), "pushforward_batched", na, dtype)
            if entry is not None:
                metrics.counter("aiyagari_tuning_cache_hits_total",
                                knob="pushforward_batched").inc()
                _record_decision(
                    "pushforward", entry["choice"], "measured",
                    {"walls_us": entry.get("walls_us", {}),
                     "probe_na": entry.get("na"),
                     "measured_utc": entry.get("utc"),
                     "context": "batched"},
                    na=na, dtype=dtype)
                return entry["choice"]
            metrics.counter("aiyagari_tuning_cache_misses_total",
                            knob="pushforward_batched").inc()
        if jax.default_backend() == "cpu":
            _record_decision(
                "pushforward", "scatter", "default",
                {"constraint": "vmapped transpose gathers batch ~5.5x/lane "
                               "slower than solo on hosts; scatter scales "
                               "linearly (resolve_backend docstring, "
                               "ISSUE 15 measurement)"},
                na=na, dtype=dtype)
            return "scatter"
        # Accelerators with no batched measurement: the shipped
        # scatter-free default — solo-context probe entries are never
        # consulted here (a measured solo winner is exactly the number
        # the vmapped context invalidates; the batched probe above is
        # the sanctioned measurement path).
        _record_decision(
            "pushforward", "transpose", "default",
            {"constraint": "batched context: solo tuning probes not "
                           "consulted (no batched-context measurement "
                           "exists yet)"},
            na=na, dtype=dtype)
        return "transpose"
    if f32_sim:
        # Still a recorded decision — source "default" with the
        # constraint named as evidence, so a K-S mixed-mode run's ledger
        # explains why its histogram scan scatters.
        from aiyagari_tpu.tuning.autotuner import _record_decision

        _record_decision(
            "pushforward", "scatter", "default",
            {"constraint": "f32-sim cumsum bias pins the scatter form "
                           "(resolve_backend docstring)"},
            na=na, dtype=dtype)
        return "scatter"
    from aiyagari_tpu.tuning.autotuner import resolve_route

    return resolve_route("pushforward", "transpose", na=na, dtype=dtype)


def lottery_scatter(mass, idx, w_lo, n_out: Optional[int] = None):
    """The reference scatter-add asset push: split each source cell's mass
    between its bracketing gridpoints. mass/idx/w_lo [N, m] -> [N, n_out]
    (n_out defaults to m). This is the parity-pin route every scatter-free
    backend is checked against — and the shared single-point deposit helper
    (sim/ks_distribution.initial_distribution), so one edge-clipping
    contract covers every lottery entry."""
    n_out = mass.shape[-1] if n_out is None else n_out
    rows = jnp.broadcast_to(jnp.arange(mass.shape[0])[:, None], mass.shape)
    out = jnp.zeros(mass.shape[:-1] + (n_out,), mass.dtype)
    return (out.at[rows, idx].add(mass * w_lo)
               .at[rows, idx + 1].add(mass * (1.0 - w_lo)))


def _segment_bounds(idx, na: int):
    """bounds[i, l] = #{j : idx[i, j] < l} for l = 0..na, one count per row.
    For row-wise sorted idx (monotone policy) the sources scattering into
    bucket l as the LO leg occupy exactly [bounds[l], bounds[l+1]) — the
    contiguous-segment fact the transpose and banded routes are built on.

    Searchsorted method routes through ops/interp.searchsorted_method —
    the bucket_index platform split's ONE resolver (AIYA204 discipline):
    jnp.searchsorted's default 'scan' lowers to log2(na) SERIAL gather
    rounds on accelerators (the documented TPU pathology — and this runs
    per scan STEP in the KS/transition paths, where the plan rebuilds each
    period), so only the CPU host takes 'scan'; accelerators co-sort."""
    from aiyagari_tpu.ops.interp import searchsorted_method

    targets = jnp.arange(na + 1, dtype=idx.dtype)
    method = searchsorted_method(na)
    return jax.vmap(
        lambda row: jnp.searchsorted(row, targets, side="left", method=method)
    )(idx)


def _is_monotone(idx):
    return jnp.all(idx[:, 1:] >= idx[:, :-1])


def _transpose_push(mu, w_lo, bounds):
    """Scatter-free asset push for row-wise sorted idx: per-leg segment
    sums as exclusive-cumsum differences gathered at the bucket bounds.

    Exactly conservative: summing the per-bucket differences telescopes
    back to the full cumsum, so total mass is preserved to the same
    roundoff as the scatter's own accumulation."""
    na = mu.shape[-1]
    zero = jnp.zeros(mu.shape[:-1] + (1,), mu.dtype)
    s_lo = jnp.concatenate([zero, jnp.cumsum(mu * w_lo, axis=-1)], axis=-1)
    s_hi = jnp.concatenate([zero, jnp.cumsum(mu * (1.0 - w_lo), axis=-1)],
                           axis=-1)
    g_lo = jnp.take_along_axis(s_lo, bounds, axis=-1)        # [N, na+1]
    g_hi = jnp.take_along_axis(s_hi, bounds, axis=-1)
    m_lo = g_lo[:, 1:] - g_lo[:, :-1]                        # mass w/ idx == l
    m_hi = g_hi[:, 1:] - g_hi[:, :-1]
    # The HI leg lands one bucket up: bucket l receives the idx == l-1 mass.
    # m_hi[:, na-1] (idx == na-1) cannot occur — bucket_index clips to na-2.
    return m_lo + jnp.concatenate([zero, m_hi[:, :-1]], axis=-1)


def _band_geometry(na: int, band_block: Optional[int], band_width: Optional[int]):
    tb = min(DEFAULT_BAND_BLOCK if band_block is None else int(band_block), na)
    nt = -(-na // tb)
    if nt == 1:
        # Single tile: the band IS the dense per-row transfer operator.
        return tb, 1, na
    bw = DEFAULT_BAND_WIDTH if band_width is None else int(band_width)
    return tb, nt, min(max(bw, tb), na)


def _build_band(idx, w_lo, bounds, tb: int, nt: int, bw: int):
    """Materialize the two-diagonal lottery operator as a dense block band
    [N, nt, bw, tb]: tile t covers targets [t*tb, (t+1)*tb); its sources
    (idx in [t*tb - 1, (t+1)*tb - 1], contiguous under monotonicity) start
    at starts[i, t] and pad to the static width bw. Returns
    (band, starts, fits) with fits the scalar validity flag (every tile's
    true window within bw). Built from gathers and compares only — the one
    place the operator's structure is paid for, amortized across every
    subsequent matmul sweep."""
    N, na = idx.shape
    l0 = jnp.arange(nt, dtype=idx.dtype) * tb                     # [nt]
    # Sources for tile t: idx in [l0-1, l0+tb-1] -> j in
    # [bounds[l0-1], bounds[min(l0+tb, na)]) (bounds[-1] == bounds[0] == 0:
    # no idx < 0 exists, so the clip is exact, not an approximation).
    starts = jnp.take_along_axis(
        bounds, jnp.broadcast_to(jnp.clip(l0 - 1, 0, na)[None, :], (N, nt)),
        axis=-1)                                                  # [N, nt]
    ends = jnp.take_along_axis(
        bounds, jnp.broadcast_to(jnp.clip(l0 + tb, 0, na)[None, :], (N, nt)),
        axis=-1)
    fits = jnp.max(ends - starts) <= bw

    j = starts[:, :, None] + jnp.arange(bw, dtype=idx.dtype)[None, None, :]
    in_range = j < na                                             # [N, nt, bw]
    jc = jnp.minimum(j, na - 1)
    rows = jnp.arange(N)[:, None, None]
    idx_w = idx[rows, jc]                                         # [N, nt, bw]
    wlo_w = w_lo[rows, jc]
    tgt = l0[None, :, None, None] + jnp.arange(tb, dtype=idx.dtype)[None, None, None, :]
    hit_lo = (idx_w[..., None] == tgt) & in_range[..., None]
    hit_hi = (idx_w[..., None] + 1 == tgt) & in_range[..., None]
    band = (jnp.where(hit_lo, wlo_w[..., None], 0.0)
            + jnp.where(hit_hi, 1.0 - wlo_w[..., None], 0.0)).astype(w_lo.dtype)
    return band, starts, fits


def _banded_push(mu, band, starts, precision):
    """Apply the block band: gather each tile's source window and contract
    it against the tile's [bw, tb] operator block — one batched matmul per
    tile, the MXU-resident formulation of the lottery."""
    na = mu.shape[-1]
    return _banded_push_padded(mu, band, starts, precision, na)[:, :na]


@dataclasses.dataclass(frozen=True)
class PushforwardPlan:
    """A lottery (idx, w_lo) compiled for one backend: the per-policy
    precomputation (segment bounds, block band) paid once and reused by
    every `apply_pushforward` sweep. Closed over by the solver loops, never
    carried through them — `kind` stays a static Python string."""

    kind: str
    idx: jax.Array
    w_lo: jax.Array
    bounds: Optional[jax.Array] = None        # [N, na+1] (transpose/banded)
    band: Optional[jax.Array] = None          # [N, nt, bw, tb] (banded)
    starts: Optional[jax.Array] = None        # [N, nt] (banded)
    monotone: Optional[jax.Array] = None      # scalar bool
    ok: Optional[jax.Array] = None            # scalar bool: primary route valid


def _warn_fallback(pred, route: str):
    if not WARN_ON_FALLBACK:
        return

    def fire():
        # ordered=False: the count is a fire-and-forget side effect — the
        # device program never blocks on the host increment. The route name
        # is closed over (debug.callback operands must be array-likes).
        # The __aiyagari_callback_tag__ attribute is the static-analysis
        # whitelist contract (analysis/rules.py CALLBACK_TAG_ATTR): the
        # no-host-sync-in-loop auditor recognizes THIS counted degradation
        # event inside scan/while bodies by its tag — not by string-matching
        # module paths — and flags every untagged callback.
        def _fallback_event(route=route):
            _record_fallback(route)

        _fallback_event.__aiyagari_callback_tag__ = "pushforward-degradation"
        jax.debug.callback(_fallback_event, ordered=False)
        if _FALLBACK_DEBUG:
            jax.debug.print(
                "pushforward: {} route invalid for this policy "
                "(non-monotone lottery or band overflow) — falling back to "
                "the reference formulation for correctness", route)

    jax.lax.cond(pred, fire, lambda: None)


def plan_pushforward(idx, w_lo, *, backend: str = "auto",
                     band_block: Optional[int] = None,
                     band_width: Optional[int] = None) -> PushforwardPlan:
    """Compile a lottery for `backend` (module docstring). The returned
    plan is policy-specific: rebuild it when (idx, w_lo) change (the scan
    paths do this per step; the stationary loop hoists it)."""
    kind = resolve_backend(backend, na=idx.shape[-1], dtype=w_lo.dtype)
    if kind == "scatter":
        return PushforwardPlan("scatter", idx, w_lo)
    if kind == "pallas":
        return PushforwardPlan("pallas", idx, w_lo)
    na = idx.shape[-1]
    bounds = _segment_bounds(idx, na)
    mono = _is_monotone(idx)
    if kind == "transpose":
        _warn_fallback(jnp.logical_not(mono), "transpose")
        return PushforwardPlan("transpose", idx, w_lo, bounds=bounds,
                               monotone=mono, ok=mono)
    tb, nt, bw = _band_geometry(na, band_block, band_width)
    band, starts, fits = _build_band(idx, w_lo, bounds, tb, nt, bw)
    ok = jnp.logical_and(mono, fits)
    _warn_fallback(jnp.logical_not(ok), "banded")
    return PushforwardPlan("banded", idx, w_lo, bounds=bounds, band=band,
                           starts=starts, monotone=mono, ok=ok)


def apply_pushforward(plan: PushforwardPlan, mu, P,
                      precision=jax.lax.Precision.HIGHEST):
    """One cross-section sweep under the plan's backend:
    mu'[m, l] = sum_{i,j} P[i, m] * mu[i, j] * lottery(j -> l).

    Invalid primary routes degrade through lax.cond — banded -> transpose
    -> scatter — so the result is the same operator regardless (the
    branches all compute L mu; only cost differs). The income mixing keeps
    the caller's matmul `precision` exactly as the scatter route always
    did (HIGHEST outside the precision ladder's hot stages) — EXCEPT the
    pallas route, whose fused kernel pins HIGHEST mixing unconditionally:
    the ladder's relaxed hot-stage precision is deliberately not threaded
    into the kernel (mass conservation inside one fused pass is cheaper
    than a renormalizing round trip), so that route is HIGHEST-only."""
    if plan.kind == "pallas":
        from aiyagari_tpu.ops.pallas_pushforward import lottery_step_pallas
        from aiyagari_tpu.ops.pallas_support import pallas_interpret_mode

        return lottery_step_pallas(mu, plan.idx, plan.w_lo, P,
                                   interpret=pallas_interpret_mode())
    if plan.kind == "scatter":
        mu_a = lottery_scatter(mu, plan.idx, plan.w_lo)
    elif plan.kind == "transpose":
        mu_a = jax.lax.cond(
            plan.ok,
            lambda m: _transpose_push(m, plan.w_lo, plan.bounds),
            lambda m: lottery_scatter(m, plan.idx, plan.w_lo),
            mu)
    elif plan.kind == "banded":
        mu_a = jax.lax.cond(
            plan.ok,
            lambda m: _banded_push(m, plan.band, plan.starts, precision),
            lambda m: jax.lax.cond(
                plan.monotone,
                lambda x: _transpose_push(x, plan.w_lo, plan.bounds),
                lambda x: lottery_scatter(x, plan.idx, plan.w_lo),
                m),
            mu)
    else:  # pragma: no cover - plan kinds are produced by plan_pushforward
        raise ValueError(f"unknown plan kind {plan.kind!r}")
    return jnp.matmul(P.T, mu_a, precision=precision)


def pushforward_step(mu, idx, w_lo, P, *, backend: str = "auto",
                     precision=jax.lax.Precision.HIGHEST,
                     band_block: Optional[int] = None,
                     band_width: Optional[int] = None):
    """Plan + apply in one call — the per-step form the scan bodies use
    (KS histogram path, transition forward push), where the policy and
    hence the plan changes every period."""
    plan = plan_pushforward(idx, w_lo, backend=backend,
                            band_block=band_block, band_width=band_width)
    return apply_pushforward(plan, mu, P, precision=precision)


def shard_banded_plan(plan: PushforwardPlan, mesh, P):
    """Grid-axis sharded application of a banded plan: the block band's
    tile axis splits over the mesh's "grid" axis (each device owns nt/D
    target tiles and their [bw, tb] operator blocks), mu and P replicate
    (windows may read across tile boundaries, so the source side cannot
    shard without halos), and each device emits its own target tiles — no
    collective at all until the caller reduces. Built on the
    parallel/mesh.shard_map version shim (jax is pinned at 0.4.x here;
    never import new-API symbols directly).

    Placement goes through the declarative rule matcher
    (parallel/rules.BANDED_PLAN_RULES — the PR 13 idiom), so the SAME
    call serves a 1-D ("grid",) mesh and a 2-D make_mesh_2d
    (scenarios x grid) mesh: on the 2-D mesh the band's tile axis still
    splits over "grid" while the unnamed "scenarios" axis replicates —
    parity-pinned against the 1-D apply by tests/test_pushforward.py. A
    mesh without a "grid" axis is rejected loudly (a silently replicated
    band is exactly the placement bug the rules layer exists to prevent).

    Returns apply(mu) -> mu' with mu' sharded over its asset axis. Valid
    banded plans only (the cond fallback would need the full lottery on
    every device, defeating the sharding) — callers check `plan.ok` via
    a host fetch before opting in."""
    from aiyagari_tpu.parallel.mesh import (
        GRID_AXIS,
        PartitionSpec as Pspec,
        shard_map,
    )
    from aiyagari_tpu.parallel.rules import BANDED_PLAN_RULES, match_rule

    if plan.kind != "banded":
        raise ValueError("shard_banded_plan requires a 'banded' plan")
    if GRID_AXIS not in mesh.shape:
        raise ValueError(
            f"shard_banded_plan needs a mesh with a '{GRID_AXIS}' axis "
            f"(the band's tile axis shards over it); got axes "
            f"{tuple(mesh.axis_names)}")
    na = plan.idx.shape[-1]

    def local(mu, band, starts, Pt):
        out = _banded_push_padded(mu, band, starts,
                                  jax.lax.Precision.HIGHEST, na)
        return jnp.matmul(Pt.T, out, precision=jax.lax.Precision.HIGHEST)

    spec_of = lambda name, leaf: match_rule(  # noqa: E731
        BANDED_PLAN_RULES, name, leaf, mesh=mesh)
    fn = shard_map(
        local, mesh=mesh,
        in_specs=(Pspec(), spec_of("band", plan.band),
                  spec_of("starts", plan.starts), spec_of("P", P)),
        out_specs=Pspec(None, GRID_AXIS),
    )

    def apply(mu):
        out = fn(mu, plan.band, plan.starts, P)
        return out[:, :na]

    return apply


def _banded_push_padded(mu, band, starts, precision, na: int):
    """_banded_push without the trailing [:, :na] slice — the sharded apply
    keeps the tile-padded [N, nt*tb] layout so the output partitions evenly
    over the grid axis; the caller slices after reassembly."""
    N = mu.shape[0]
    _, nt, bw, tb = band.shape
    j = starts[:, :, None] + jnp.arange(bw)[None, None, :]
    # Out-of-range window lanes carry a zero operator column (the build
    # masks them), so the clipped gather duplicates are inert.
    jc = jnp.minimum(j, na - 1)
    mu_w = mu[jnp.arange(N)[:, None, None], jc]
    out = jnp.einsum("itb,itbc->itc", mu_w, band, precision=precision)
    return out.reshape(N, nt * tb)
