"""Mixed-precision solve ladder: the shared policy behind `dtype="mixed"`.

Every hot fixed point in the framework — EGM sweeps, Howard/VFI evaluation,
the Young distribution push-forward, the transition backward/forward scans —
is HBM-bandwidth-bound on TPU (diagnostics/roofline.py: 819 GB/s vs 197 bf16
TFLOP/s on a v5e), so halving bytes-per-element is a direct ~2x on the
memory-bound roofline. The early iterations of a contraction do not need the
final tolerance's precision: a residual at 1e-2 is equally well measured in
f32 and f64, and the iterate they produce is discarded anyway. What the low
dtype CANNOT do is finish — below its own rounding band the sup-norm residual
wanders without converging (the measured f32 noise floor behind
`solvers/_stopping.effective_tolerance`).

The ladder therefore runs each solve as a short STAGE SEQUENCE, one
`lax.while_loop` per stage (never per-step dtype branching — the loop body
stays a single-dtype program XLA can fuse):

  1. hot stage(s): iterate in a narrow dtype (f32 by default; matmul
     contractions at the stage's configured precision — DEFAULT on TPU f32 is
     bf16, which is exactly the MXU-peak regime) until the residual reaches
     that dtype's noise floor, `switch_ulp * eps(dtype) * max|iterate|`
     (or the target tolerance, whichever is larger);
  2. polish stage: cast the carry up ONCE at the stage boundary, restart any
     acceleration history (a stale f32 residual history poisons the f64
     normal equations — ops/accel.py safeguard-restart semantics), and run
     the ordinary full-precision loop to the reference tolerance. The
     polish measures the true residual at the cast iterate, so a laddered
     solve that stops at dist < tol satisfies exactly the same convergence
     certificate as the pure-f64 one.

Why this is safe: the switch criterion is RESIDUAL-based, not iterate-based.
When the hot stage stops at residual ~ floor32, the polish starts from an
iterate whose true f64 residual is at most floor32 + O(eps32 * |x|) — the
f64 stage then walks log(floor/tol)/log(1/rho) sweeps instead of the full
log(d0/tol)/log(1/rho), and every sweep saved by the hot stage ran at the
narrow dtype's bandwidth.

One config (`PrecisionLadderConfig`) and one stage planner (`stage_specs`)
serve all five solver families (solvers/egm.py, solvers/egm_sharded.py,
solvers/vfi.py, sim/distribution.py, transition/mit.py), so the ladder
semantics cannot drift per route. The config is frozen/hashable and rides
jit static args directly, like AccelConfig.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

__all__ = [
    "PrecisionLadderConfig",
    "StageSpec",
    "default_ladder",
    "hot_only",
    "ladder_for_dtype",
    "matmul_precision_of",
    "plan_stages",
    "require_x64",
    "stage_specs",
    "validate_ladder",
]

_STAGE_DTYPES = ("bfloat16", "float32", "float64")
_MATMUL_PRECISIONS = ("default", "high", "highest")
# Widening order for the strictly-widening stage check.
_WIDTH = {"bfloat16": 0, "float32": 1, "float64": 2}


@dataclasses.dataclass(frozen=True)
class PrecisionLadderConfig:
    """Mixed-precision solve ladder policy (module docstring).

    stage_dtypes — the dtype of each stage's while_loop carry, strictly
        widening left to right. The LAST entry is the reference dtype the
        returned solution certifies its tolerance in; earlier entries are
        the inaccuracy-tolerant hot stages. A single-entry ladder runs the
        whole solve at that dtype (no switch) — useful for pinning that a
        hot stage never silently upcasts (tests/test_precision_ladder.py).
    switch_ulp — the switch criterion as a multiple of the stage dtype's
        noise floor: a hot stage stops when its residual reaches
        max(tol, switch_ulp * eps(stage dtype) * max|iterate|). 24 is the
        measured f32 sup-norm wander band at fine grids (6-16 ulp observed;
        solvers/egm.py noise_floor_ulp rationale) — small enough to hand the
        polish a near-converged iterate, large enough that the hot loop
        always exits instead of wandering below its own resolution.
    matmul_precision — per-stage precision for the solver-owned matmul
        contractions (the EGM/Bellman expectation, the distribution
        push-forward): one of "default" / "high" / "highest" per stage.
        "default" in an f32 hot stage is the TPU bf16 MXU path (~3 decimal
        digits below f32 — fine while the residual sits above the switch
        floor; ops/interp.py:194 measured the loss); the polish stage keeps
        "highest" so the certified stage is bit-identical in semantics to
        the pure full-precision solver.
    """

    stage_dtypes: Tuple[str, ...] = ("float32", "float64")
    switch_ulp: float = 24.0
    matmul_precision: Tuple[str, ...] = ("default", "highest")


@dataclasses.dataclass(frozen=True)
class StageSpec:
    """One planned ladder stage: the carry dtype, the noise-floor multiple
    the stage's stopping rule applies (0.0 = strict tol; hot stages carry
    switch_ulp, the final stage the caller's own floor), and the matmul
    precision name for the stage's contractions."""

    dtype: str
    noise_floor_ulp: float
    matmul_precision: str
    is_final: bool


def validate_ladder(ladder: PrecisionLadderConfig) -> None:
    if not ladder.stage_dtypes:
        raise ValueError("PrecisionLadderConfig.stage_dtypes must be non-empty")
    for d in ladder.stage_dtypes:
        if d not in _STAGE_DTYPES:
            raise ValueError(
                f"unknown stage dtype {d!r}; expected one of {_STAGE_DTYPES}")
    widths = [_WIDTH[d] for d in ladder.stage_dtypes]
    if any(b <= a for a, b in zip(widths, widths[1:])):
        raise ValueError(
            "PrecisionLadderConfig.stage_dtypes must be strictly widening "
            f"(narrow hot sweeps -> wide polish); got {ladder.stage_dtypes}")
    if len(ladder.matmul_precision) != len(ladder.stage_dtypes):
        raise ValueError(
            "PrecisionLadderConfig.matmul_precision needs one entry per "
            f"stage; got {len(ladder.matmul_precision)} for "
            f"{len(ladder.stage_dtypes)} stage(s)")
    for p in ladder.matmul_precision:
        if p not in _MATMUL_PRECISIONS:
            raise ValueError(
                f"unknown matmul precision {p!r}; expected one of "
                f"{_MATMUL_PRECISIONS}")
    if not ladder.switch_ulp > 0.0:
        raise ValueError(
            f"PrecisionLadderConfig.switch_ulp must be > 0 (the hot stage "
            f"must stop ABOVE its own rounding band), got {ladder.switch_ulp}")


def default_ladder() -> PrecisionLadderConfig:
    """The shipped `dtype="mixed"` policy: f32 hot sweeps (bf16 matmul on
    TPU via "default"), error-controlled switch at 24 ulp, f64 polish at
    HIGHEST matmul precision."""
    return PrecisionLadderConfig()


def ladder_for_dtype(dtype: str):
    """BackendConfig.dtype -> ladder: "mixed" gets the default ladder,
    every explicit single dtype gets None (no ladder)."""
    return default_ladder() if dtype == "mixed" else None


def require_x64(ladder: PrecisionLadderConfig) -> None:
    """Loud guard for backends/configurations that cannot represent the
    ladder's polish dtype: with jax's x64 mode off, float64 arrays silently
    canonicalize to f32 (with only a UserWarning), and a "mixed" solve would
    then POLISH IN F32 while claiming an f64-certified tolerance. Raise
    instead — the caller should enter config.precision_scope("mixed") (the
    dispatch layer does) or enable x64."""
    import jax.dtypes
    import jax.numpy as jnp

    for d in ladder.stage_dtypes:
        if jax.dtypes.canonicalize_dtype(jnp.dtype(d)) != jnp.dtype(d):
            raise RuntimeError(
                f"precision ladder stage dtype {d!r} is unavailable on this "
                "backend configuration (jax canonicalizes it to "
                f"{jax.dtypes.canonicalize_dtype(jnp.dtype(d))!s}); enable "
                "x64 (config.precision_scope('mixed') does) instead of "
                "silently polishing in a narrower dtype")


def matmul_precision_of(name: str):
    """Map a per-stage matmul-precision name to jax.lax.Precision. "default"
    returns None — the framework's convention for "let the op's own default
    stand" (jnp.matmul(None) = the backend default, bf16-based on TPU f32)."""
    import jax

    return {"default": None,
            "high": jax.lax.Precision.HIGH,
            "highest": jax.lax.Precision.HIGHEST}[name]


def stage_specs(ladder: PrecisionLadderConfig,
                noise_floor_ulp: float = 0.0) -> Tuple[StageSpec, ...]:
    """Plan the ladder's stages for one solve. Hot (non-final) stages stop at
    max(tol, switch_ulp * eps(stage dtype) * max|x|) — the error-controlled
    switch; the final stage applies the CALLER's own noise_floor_ulp (0.0 =
    the strict reference criterion), so a laddered solve certifies exactly
    what the un-laddered solver would. Called at trace time by every ladder
    route; also performs the x64 availability guard, so a polish stage that
    would silently truncate fails loudly everywhere."""
    validate_ladder(ladder)
    require_x64(ladder)
    n = len(ladder.stage_dtypes)
    return tuple(
        StageSpec(
            dtype=d,
            noise_floor_ulp=(float(noise_floor_ulp) if i == n - 1
                             else max(float(noise_floor_ulp),
                                      float(ladder.switch_ulp))),
            matmul_precision=ladder.matmul_precision[i],
            is_final=(i == n - 1),
        )
        for i, d in enumerate(ladder.stage_dtypes)
    )


def plan_stages(ladder, fallback_dtype,
                noise_floor_ulp: float = 0.0) -> Tuple[StageSpec, ...]:
    """stage_specs with a None-ladder fallback: one final stage at
    `fallback_dtype` with the caller's own noise floor and the historical
    "highest" matmul precision — so every solver loop is written ONCE over
    the stage tuple and the un-laddered route stays the exact reference
    program."""
    if ladder is None:
        import jax.numpy as jnp

        return (StageSpec(dtype=jnp.dtype(fallback_dtype).name,
                          noise_floor_ulp=float(noise_floor_ulp),
                          matmul_precision="highest", is_final=True),)
    return stage_specs(ladder, noise_floor_ulp)


def hot_only(ladder):
    """The ladder truncated to its FIRST (hot) stage, as a single-stage
    ladder — what the multiscale warm stages run: their product is a warm
    start for a finer grid, not a certified solution, so polishing it in
    f64 would buy accuracy the prolongation immediately discards. None
    passes through (no ladder anywhere)."""
    if ladder is None or len(ladder.stage_dtypes) == 1:
        return ladder
    return dataclasses.replace(
        ladder, stage_dtypes=ladder.stage_dtypes[:1],
        matmul_precision=ladder.matmul_precision[:1])
