"""Fused TPU (Pallas) kernel for the Young-lottery push-forward: lottery
split + per-bucket segment accumulation + the P' income mixing in one
VMEM-tiled pass (the "pallas" DistributionBackend of ops/pushforward.py;
XLA fallbacks: the scatter/transpose/banded routes there).

Formulation: the output is tiled over target buckets (grid = target tiles
of `block_l` lanes). Each program owns one [N, block_l] accumulator tile in
VMEM scratch and scans the source axis in `block_src`-wide chunks,
accumulating both lottery legs by compare-select — never a scatter, and no
HBM round trip for any intermediate. Before the dense compare, the chunk's
idx min/max gate a @pl.when skip (the pallas_inverse chunk-skipping trick):
for a monotone policy each target tile overlaps only ~(block_src +
block_l)/na of the source axis, so the dense [N, block_src, block_l]
compare-reduce runs on ~2 chunks per program instead of all of them. The
skip is exact for ANY policy — a non-monotone lottery just skips less — so
unlike the transpose/banded XLA routes this kernel needs no monotonicity
fallback at all. The program ends by mixing income states through P' on the
MXU ([N, N] x [N, block_l], HIGHEST precision — the same mass-conservation
contract as the scatter route) and writing the finished tile.

interpret=True runs the Pallas interpreter off-TPU (CPU tests, tier-1
parity pins) exactly like pallas_bellman / pallas_inverse; the route stays
opt-in for solvers until validated on real hardware (the pallas_inverse
round-2 lesson: Mosaic lowerings must be cross-checked on chip first).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["lottery_step_pallas"]


def _kernel(mu_ref, w_ref, idx_ref, P_ref, out_ref, acc_ref, *,
            block_l: int, block_src: int, n_chunks: int):
    t = pl.program_id(0)
    l0 = t * block_l
    acc_ref[...] = jnp.zeros_like(acc_ref)

    # Static unroll over source chunks (Mosaic rejects dynamically indexed
    # sublane loads; the pallas_inverse pattern).
    for c in range(n_chunks):
        sl = slice(c * block_src, (c + 1) * block_src)
        idx_c = idx_ref[:, sl]                       # [N, CH] int32
        lo_c = jnp.min(idx_c)
        hi_c = jnp.max(idx_c) + 1                    # HI-leg bucket reach

        # A chunk touches this target tile iff some idx lands in
        # [l0 - 1, l0 + block_l); everything else skips the dense compare
        # entirely. MUST be @pl.when predication, not lax.cond — cond with
        # vector carries lowers to selects that execute both branches
        # (measured 10x on-chip in the pallas_inverse rewrite).
        @pl.when(jnp.logical_and(hi_c >= l0, lo_c < l0 + block_l))
        def _():
            mu_c = mu_ref[:, sl]
            w_c = w_ref[:, sl]
            tgt = l0 + jax.lax.broadcasted_iota(
                jnp.int32, (1, 1, block_l), 2)
            ic = idx_c[:, :, None]
            lo_leg = jnp.where(ic == tgt, (mu_c * w_c)[:, :, None], 0.0)
            hi_leg = jnp.where(ic + 1 == tgt,
                               (mu_c * (1.0 - w_c))[:, :, None], 0.0)
            acc_ref[...] += jnp.sum(lo_leg + hi_leg, axis=1)

    # Income mixing fused into the same pass: out = P.T @ acc on the MXU,
    # HIGHEST precision (the scatter route's pinned contract — a bf16 pass
    # would leak mass at ~1e-3).
    out_ref[...] = jax.lax.dot_general(
        P_ref[...], acc_ref[...],
        dimension_numbers=(((0,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=acc_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("block_l", "block_src", "interpret"))
def lottery_step_pallas(mu, idx, w_lo, P, *, block_l: int = 256,
                        block_src: int = 256, interpret: bool = False):
    """One fused cross-section sweep, mu'[m, l] = sum_{i,j} P[i, m] *
    mu[i, j] * lottery(j -> l). mu/w_lo [N, na]; idx [N, na] buckets from
    sim/distribution.young_lottery; P [N, N] row-stochastic. Returns
    mu' [N, na], bit-for-bit the same operator as the scatter reference up
    to float summation order (pinned by tests/test_pushforward.py in
    interpret mode)."""
    N, na = mu.shape
    tl = min(block_l, max(na, 1))
    ch = min(block_src, tl)
    if tl % ch:
        raise ValueError(
            f"block_src {block_src} must divide block_l {block_l}")
    nt = -(-na // tl)
    nap = nt * tl

    # Pad: mass/weights with zeros (inert contributions), idx edge-padded
    # so a padded lane never widens a chunk's [min, max] skip gate.
    mu_p = jnp.pad(mu, ((0, 0), (0, nap - na)))
    w_p = jnp.pad(w_lo, ((0, 0), (0, nap - na)))
    idx_p = jnp.pad(idx.astype(jnp.int32), ((0, 0), (0, nap - na)),
                    mode="edge")

    out = pl.pallas_call(
        functools.partial(_kernel, block_l=tl, block_src=ch,
                          n_chunks=nap // ch),
        grid=(nt,),
        in_specs=[
            pl.BlockSpec((N, nap), lambda t: (0, 0)),
            pl.BlockSpec((N, nap), lambda t: (0, 0)),
            pl.BlockSpec((N, nap), lambda t: (0, 0)),
            pl.BlockSpec((N, N), lambda t: (0, 0)),
        ],
        out_specs=pl.BlockSpec((N, tl), lambda t: (0, t)),
        out_shape=jax.ShapeDtypeStruct((N, nap), mu.dtype),
        scratch_shapes=[pltpu.VMEM((N, tl), mu.dtype)],
        interpret=interpret,
    )(mu_p, w_p, idx_p, P.astype(mu.dtype))
    return out[:, :na]
