"""Device-mesh, sharding, and multi-host distributed runtime surface."""

from aiyagari_tpu.parallel.distributed import (
    DistributedContext,
    initialize_distributed,
    process_info,
)
from aiyagari_tpu.parallel.halo import inverse_interp_power_grid_halo
from aiyagari_tpu.parallel.mesh import (
    agents_sharding,
    force_host_device_count,
    grid_sharding,
    make_mesh,
    replicated,
    shard_panel,
)
from aiyagari_tpu.parallel.ring import (
    inverse_interp_power_grid_ring,
    ring_buffer_size,
)

__all__ = [
    "DistributedContext",
    "initialize_distributed",
    "process_info",
    "agents_sharding",
    "force_host_device_count",
    "grid_sharding",
    "inverse_interp_power_grid_halo",
    "inverse_interp_power_grid_ring",
    "make_mesh",
    "replicated",
    "ring_buffer_size",
    "shard_panel",
]
