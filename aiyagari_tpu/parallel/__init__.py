"""Device-mesh, sharding, and multi-host distributed runtime surface."""

from aiyagari_tpu.parallel.distributed import (
    DistributedContext,
    initialize_distributed,
    process_info,
)
from aiyagari_tpu.parallel.mesh import (
    agents_sharding,
    force_host_device_count,
    grid_sharding,
    make_mesh,
    replicated,
    shard_panel,
)

__all__ = [
    "DistributedContext",
    "initialize_distributed",
    "process_info",
    "agents_sharding",
    "force_host_device_count",
    "grid_sharding",
    "make_mesh",
    "replicated",
    "shard_panel",
]
