"""Halo-exchange sharded power-grid inversion: the grid-axis distribution of
the EGM hot operation where the KNOT ARRAY STAYS RESIDENT per device.

Under plain GSPMD, sharding the knot array along the grid axis does not
distribute it: the windowed inversion's data-dependent slab gathers defeat
the compiler's locality analysis and the full row is re-materialized per
device (measured; docs/DESIGN.md §4, tests/test_sim_sharding.py). This
module is the explicit-collective alternative (SURVEY.md §2.4(1)): under
`jax.shard_map`, each device owns one contiguous shard of the knots and of
the query grid, exchanges a fixed-width HALO of boundary knots with its
neighbors over two `lax.ppermute` rounds (ICI neighbor traffic, no
all-gather), and brackets its own queries against [left halo | local shard
| right halo] only.

Why a bounded halo suffices — and exactly: the knots and the query grid
share the power-spacing law and the EGM endogenous grid's knot density is
bounded (the single-device windowed route's 6x envelope), so a query's
bracketing knots lie within a fixed distance of its own shard. Device
edges use SENTINEL halos that make the arithmetic exact rather than
special-cased: device 0 fills its left halo with -inf — every sentinel
counts as "a knot below the query", so the global count base
(shard_start - halo) + (halo sentinels) telescopes to the true count, and
a query below all real knots yields count 0 and x0 = -inf, the exact
"absent bracket" encoding the finish step already handles. The last
device fills its right halo with +inf (never below a query, never a
bracket). Queries whose bracket would lie beyond the halo ESCAPE with the
same NaN-poisoning contract as the single-device windowed route.

The shard-local body is exposed as `halo_bracket_local` so larger
shard_map programs (the distributed EGM sweep, solvers/egm_sharded.py)
can run it inline per sweep instead of crossing a shard_map boundary per
iteration.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from aiyagari_tpu.parallel.mesh import PartitionSpec as P, shard_map as _shard_map

from aiyagari_tpu.ops.interp import _finish_inverse

__all__ = ["inverse_interp_power_grid_halo", "halo_bracket_local"]

# Bounded program caches keyed on mesh VALUE (device ids + axis layout), not
# the Mesh object: equal-valued meshes rebuilt per call site hit the same
# entry, and old meshes' closures/executables are evicted instead of retained
# for the process lifetime.
_PROGRAM_CACHE_MAX = 32


def mesh_fingerprint(mesh, axis: str):
    """Hashable value identity of (mesh, axis) for program caches. Device
    ids alone would collide across backends (CPU and TPU devices are both
    numbered from 0 in one process), handing a CPU call an executable
    compiled for the equal-shaped TPU mesh — so the platform is part of
    the key."""
    return (
        tuple((d.platform, int(d.id)) for d in mesh.devices.flat),
        tuple(mesh.axis_names),
        tuple(int(mesh.shape[a]) for a in mesh.axis_names),
        axis,
    )


def cached_program(cache: dict, key, build):
    """FIFO-bounded build-once cache for jitted shard_map programs."""
    prog = cache.get(key)
    if prog is None:
        if len(cache) >= _PROGRAM_CACHE_MAX:
            cache.pop(next(iter(cache)))
        prog = cache[key] = build()
    return prog


def halo_bracket_local(xl, q, *, axis: str, D: int, n_k: int, n_q: int,
                       lo: float, hi: float, power: float, halo: int):
    """Shard-local body of the halo-exchange inversion — call from INSIDE a
    shard_map over `axis`.

    xl [R, n_k/D] is this device's contiguous sorted-knot shard, q [n_q/D]
    its slice of the analytic power query grid. Returns (out [R, n_q/D],
    escaped int32 scalar) where `out` is already NaN-poisoned and `escaped`
    pmax'd across the axis. Semantics match ops/interp.
    inverse_interp_power_grid (strict-< brackets, below-range extrapolation,
    top truncation, NaN poisoning on escape).
    """
    dev = jax.lax.axis_index(axis)
    dtype = xl.dtype
    neg = jnp.array(-jnp.inf, dtype)
    pos = jnp.array(jnp.inf, dtype)

    # Neighbor halos over ICI: each device sends its tail right and its
    # head left; edge devices receive the circular wrap and overwrite it
    # with the exact sentinels (module docstring).
    fwd = [(i, (i + 1) % D) for i in range(D)]
    bwd = [(i, (i - 1) % D) for i in range(D)]
    left = jax.lax.ppermute(xl[:, -halo:], axis, fwd)    # left nbr's tail
    right = jax.lax.ppermute(xl[:, :halo], axis, bwd)    # right nbr's head
    left = jnp.where(dev == 0, neg, left)
    right = jnp.where(dev == D - 1, pos, right)
    ext = jnp.concatenate([left, xl, right], axis=-1)    # [R, shard+2*halo]

    lt = ext[:, None, :] < q[None, :, None]              # [R, nq_loc, ext]
    cnt_ext = jnp.sum(lt, axis=-1).astype(jnp.int32)
    x0 = jnp.max(jnp.where(lt, ext[:, None, :], neg), axis=-1)
    x1 = jnp.min(jnp.where(lt, pos, ext[:, None, :]), axis=-1)
    # Global count: shard start minus the halo the sentinel/neighbor
    # knots occupy — exact by the sentinel construction.
    base = dev * (n_k // D) - halo
    cnt = base + cnt_ext

    # Escape: a bracket touching the ext edges may continue beyond the
    # halo. Left: every ext knot >= q (cnt_ext == 0) on a device with
    # real knots to its left. Right: every ext knot < q with real knots
    # to the right.
    esc_l = jnp.any((cnt_ext == 0) & (dev > 0))
    esc_r = jnp.any((cnt_ext == ext.shape[-1]) & (dev < D - 1))
    escaped = jax.lax.pmax((esc_l | esc_r).astype(jnp.int32), axis)

    # The finish step needs the FIRST knot pair of the whole array for
    # the below-range extrapolation slope: all-gather the tiny per-shard
    # heads and take device 0's (ppermute cannot broadcast one source).
    head2 = jax.lax.all_gather(xl[:, :2], axis)[0]
    out = jax.vmap(
        lambda c, a0, a1, h2: _finish_inverse(
            c, a0, a1, h2, lo=lo, hi=hi, power=power, n_q=n_q, n_k=n_k,
            q_vals=q,
        )
    )(cnt, x0, x1, head2)
    out = jnp.where(escaped > 0, jnp.nan, out)
    return out, escaped


def inverse_interp_power_grid_halo(mesh, x, lo: float, hi: float, power: float,
                                   n_q: int, *, axis: str = "grid",
                                   halo: int = 3072):
    """Distributed inverse interpolation onto the n_q-point power grid.

    x [..., n_k] sorted knots, sharded (or shardable) along the last axis
    over mesh[axis]; the axis size must divide n_k and n_q. Returns
    (out [..., n_q] sharded along the last axis, escaped scalar bool).
    Semantics match ops/interp.inverse_interp_power_grid (strict-< brackets,
    below-range extrapolation, top truncation, NaN poisoning on escape).
    """
    D = mesh.shape[axis]
    n_k = x.shape[-1]
    if n_k % D or n_q % D:
        raise ValueError(
            f"mesh axis size {D} must divide n_k={n_k} and n_q={n_q}")
    if halo >= n_k // D:
        raise ValueError(f"halo={halo} must be smaller than the shard {n_k // D}")
    lead = x.shape[:-1]
    xr = x.reshape((-1, n_k))
    run = _halo_fn(mesh, axis, n_k, n_q, float(lo), float(hi), float(power),
                   int(halo), jnp.dtype(x.dtype).name)
    out, escaped = run(xr)
    return out.reshape(lead + (n_q,)), escaped > 0


_HALO_PROGRAMS: dict = {}


def _halo_fn(mesh, axis: str, n_k: int, n_q: int, lo: float, hi: float,
             power: float, halo: int, dtype_name: str):
    """Build (and cache per static signature, so per-sweep callers hit jit's
    trace cache instead of re-tracing the shard_map program — the pattern of
    sim/ks_panel._shardmap_panel_fn) the halo-exchange bracket program."""
    D = mesh.shape[axis]
    nq_loc = n_q // D
    dtype = jnp.dtype(dtype_name)
    span = hi - lo

    def build():
        def local(xl):
            dev = jax.lax.axis_index(axis)
            j = dev * nq_loc + jnp.arange(nq_loc)
            q = lo + span * (j.astype(dtype) / (n_q - 1)) ** power
            return halo_bracket_local(xl, q, axis=axis, D=D, n_k=n_k,
                                      n_q=n_q, lo=lo, hi=hi, power=power,
                                      halo=halo)

        return jax.jit(_shard_map(
            local, mesh=mesh,
            in_specs=P(None, axis),
            out_specs=(P(None, axis), P()),
        ))

    key = mesh_fingerprint(mesh, axis) + (n_k, n_q, lo, hi, power, halo,
                                          dtype_name)
    return cached_program(_HALO_PROGRAMS, key, build)
