"""Declarative partition rules: regex path -> PartitionSpec for whole
pytrees, so a ScenarioBatch / solver carry / checkpoint tree is placed on a
(2-D) mesh in ONE call instead of one hand-built NamedSharding per leaf.

The pattern is the match_partition_rules / make_shard_and_gather_fns idiom
of the large-model JAX training stacks (SNIPPETS.md [1]), adapted to this
framework's mesh-shim discipline: every sharding symbol still flows through
parallel/mesh.py (AIYA201), rules name MESH AXES ("scenarios" / "grid" /
None), and an UNMATCHED non-scalar leaf is a loud error — a silently
replicated solver state is exactly the kind of placement bug that shows up
only as a 10x memory or DCN-traffic surprise on a pod.

Rule format: an ordered sequence of (regex, spec) pairs, spec a tuple of
axis names (or None) acceptable to PartitionSpec. Leaf paths are built from
pytree keys joined with "/" ("batch/a_grid", "mu"); the FIRST matching rule
wins (precedence = order), `re.search` semantics like the reference
pattern. Scalars (0-d or single-element leaves) are never partitioned and
match no rule — they place replicated, as in the reference idiom.

Shipped rule sets:

  * SCENARIO_BATCH_RULES — the batched-GE sweep's ScenarioBatch
    (equilibrium/batched.py) on a 2-D (scenarios x grid) mesh: scenario-
    major arrays split over "scenarios", the trailing asset-grid axis of
    a_grid (and any [S, N, na] policy/warm carry) additionally over "grid";
    the income-process arrays ride the scenario axis alone (their trailing
    axes are N-sized, not grid-sized).
  * TRANSITION_SWEEP_RULES — the transition sweep's stationary anchors
    (transition/mit.py): terminal policy / initial distribution / asset
    grid split over "grid" and replicated over "scenarios"; the stacked
    [S, T] parameter paths over "scenarios".

Checkpoint restore shardings route through the same matcher
(io_utils/checkpoint.restore_array(mesh=, rules=)), so a resume onto a
DIFFERENT topology re-derives each array's placement from the rules
instead of a hand-carried NamedSharding per call site.
"""

from __future__ import annotations

import re
from typing import Optional, Sequence, Tuple

import numpy as np

from aiyagari_tpu.parallel.mesh import (
    GRID_AXIS,
    Mesh,
    NamedSharding,
    PartitionSpec,
    SCENARIOS_AXIS,
    named_sharding,
)

__all__ = [
    "PartitionRule",
    "BANDED_PLAN_RULES",
    "SCENARIO_BATCH_RULES",
    "TRANSITION_SWEEP_RULES",
    "tree_paths",
    "match_rule",
    "match_partition_rules",
    "make_shard_and_gather_fns",
    "shard_by_rules",
    "gather_tree",
]

# One rule: (path regex, PartitionSpec axes). The spec tuple may be SHORTER
# than a leaf's rank — PartitionSpec is a prefix, trailing dims replicate —
# which keeps rules rank-agnostic where only leading axes shard.
PartitionRule = Tuple[str, Tuple[Optional[str], ...]]

SCENARIO_BATCH_RULES: Tuple[PartitionRule, ...] = (
    # [S, na]: the per-scenario asset grids — both mesh axes.
    (r"(^|/)a_grid$", (SCENARIOS_AXIS, GRID_AXIS)),
    # [S, N, na] scenario-major policy/value/warm carries and stationary
    # distributions: grid is the TRAILING axis.
    (r"(^|/)(warm|C|mu|policy_\w+|v)$", (SCENARIOS_AXIS, None, GRID_AXIS)),
    # Income-process / labor-grid arrays: trailing axes are N- (or nl-)
    # sized, so only the scenario axis shards.
    (r"(^|/)(s|P|labor_grid)$", (SCENARIOS_AXIS,)),
    # Per-scenario scalars stacked to [S] (sigma/beta/psi/eta/amin/
    # labor_raw) and anything else scenario-major.
    (r".*", (SCENARIOS_AXIS,)),
)

# The banded push-forward plan (ops/pushforward.shard_banded_plan): the
# block band [N, nt, bw, tb] and its per-tile source starts [N, nt] split
# over the TILE axis — each device owns nt/D target tiles and their
# operator blocks — while mu and P replicate (source windows may read
# across tile boundaries, so the source side cannot shard without halos).
# Written full-rank so the specs pass straight into shard_map in_specs;
# on a 2-D (scenarios x grid) mesh the unnamed "scenarios" axis simply
# replicates, which is what routes the banded distribution step onto
# make_mesh_2d meshes (ISSUE 15 satellite; the 1-D grid mesh behavior is
# unchanged — match_rule drops nothing there).
BANDED_PLAN_RULES: Tuple[PartitionRule, ...] = (
    (r"(^|/)band$", (None, GRID_AXIS, None, None)),
    (r"(^|/)starts$", (None, GRID_AXIS)),
    (r"(^|/)(mu|P)$", ()),
)

TRANSITION_SWEEP_RULES: Tuple[PartitionRule, ...] = (
    # The shared stationary anchors: [N, na] policy/distribution, [na]
    # grid — grid-sharded, replicated across scenario lanes.
    (r"(^|/)(policy_c|C_term|mu0?|mu_ss)$", (None, GRID_AXIS)),
    (r"(^|/)a_grid$", (GRID_AXIS,)),
    (r"(^|/)(s|P)$", ()),
    # The stacked [S, T]-family parameter/price paths.
    (r"(^|/)(r_ext|w|beta|sigma|amin|x|\w*_paths?)$", (SCENARIOS_AXIS,)),
)


def _key_str(k) -> str:
    """One pytree key entry as a path segment (DictKey('a') -> 'a',
    GetAttrKey -> name, SequenceKey -> index)."""
    for attr in ("key", "name", "idx"):
        if hasattr(k, attr):
            return str(getattr(k, attr))
    return str(k)


def tree_paths(tree, sep: str = "/"):
    """[(path, leaf)] with paths joined from the pytree keys — the names
    the rule regexes match against."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(sep.join(_key_str(k) for k in path), leaf)
            for path, leaf in flat]


def _is_scalar(leaf) -> bool:
    shape = np.shape(leaf)
    return len(shape) == 0 or int(np.prod(shape)) == 1


def match_rule(rules: Sequence[PartitionRule], name: str, leaf=None,
               mesh: Optional[Mesh] = None) -> PartitionSpec:
    """The PartitionSpec for one named leaf: scalars replicate, otherwise
    the FIRST rule whose regex `re.search`-matches `name` wins. No match is
    LOUD (module docstring). With `mesh`, spec axes absent from the mesh
    are dropped (a 2-D rule set serves a 1-D mesh unchanged) and a spec
    longer than the leaf's rank is rejected here, with the leaf named,
    instead of deep inside device_put."""
    if leaf is not None and _is_scalar(leaf):
        return PartitionSpec()
    for pattern, spec in rules:
        if re.search(pattern, name) is not None:
            if mesh is not None:
                axes = set(mesh.axis_names)
                spec = tuple(a if (a is None or a in axes) else None
                             for a in spec)
            if leaf is not None and len(spec) > len(np.shape(leaf)):
                raise ValueError(
                    f"partition rule {pattern!r} -> {spec} has more axes "
                    f"than leaf {name!r} of shape {np.shape(leaf)}")
            return PartitionSpec(*spec)
    raise ValueError(
        f"no partition rule matches leaf {name!r}; every non-scalar leaf "
        "must be placed deliberately (add a rule, or an explicit catch-all "
        "like (r'.*', ()) for replication)")


def match_partition_rules(rules: Sequence[PartitionRule], tree,
                          mesh: Optional[Mesh] = None):
    """Pytree of PartitionSpec mirroring `tree` (the SNIPPETS.md [1]
    pattern): scalars -> P(), everything else by first-matching rule,
    unmatched leaves loud."""
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    specs = [match_rule(rules, "/".join(_key_str(k) for k in path),
                        leaf, mesh=mesh)
             for path, leaf in flat]
    return jax.tree_util.tree_unflatten(treedef, specs)


def make_shard_and_gather_fns(mesh: Mesh, specs):
    """(shard_fns, gather_fns) pytrees mirroring `specs`: shard places a
    leaf under NamedSharding(mesh, spec) (jax.device_put — committed, so
    jit programs consume the placement instead of re-deciding it); gather
    brings a leaf back replicated (the inverse, for host-side reads and
    resharding boundaries)."""
    import jax

    def shard_fn(spec):
        sharding = NamedSharding(mesh, spec)
        return lambda x: jax.device_put(x, sharding)

    def gather_fn(_spec):
        rep = named_sharding(mesh)
        return lambda x: jax.device_put(x, rep)

    return (jax.tree_util.tree_map(shard_fn, specs,
                                   is_leaf=lambda s: isinstance(s, PartitionSpec)),
            jax.tree_util.tree_map(gather_fn, specs,
                                   is_leaf=lambda s: isinstance(s, PartitionSpec)))


def shard_by_rules(mesh: Mesh, tree, rules: Sequence[PartitionRule]):
    """Place a whole pytree on `mesh` in one call: rule-match every leaf,
    device_put each under its NamedSharding. The one-call placement the
    2-D sweeps use for ScenarioBatch / anchors (module docstring)."""
    import jax

    specs = match_partition_rules(rules, tree, mesh=mesh)
    return jax.tree_util.tree_map(
        lambda spec, x: jax.device_put(x, NamedSharding(mesh, spec)),
        specs, tree,
        is_leaf=lambda s: isinstance(s, PartitionSpec))


def gather_tree(mesh: Mesh, tree):
    """Replicate every leaf of a (possibly sharded) pytree — the gather
    half of the round trip, host-read-ready."""
    import jax

    rep = named_sharding(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, rep), tree)
