"""Device-mesh construction and sharding helpers — the framework's distributed
communication backend surface.

The reference has no distributed machinery at all (single-process MATLAB;
SURVEY.md §2.4). The TPU-native design: axis-named meshes via jax.make_mesh,
NamedSharding annotations on the agent panel ("agents" axis — the DP analogue)
and on value/policy grids ("grid" axis — the TP analogue); XLA lowers the
cross-shard reductions (panel means, sup-norms) onto ICI collectives within a
slice and DCN across slices. Multi-host extends the same mesh via
jax.distributed.initialize without code changes here.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = [
    "Mesh",
    "NamedSharding",
    "PartitionSpec",
    "factor_axis_sizes",
    "make_mesh",
    "make_mesh_2d",
    "named_sharding",
    "agents_sharding",
    "grid_sharding",
    "scenarios_sharding",
    "shard_scenario_arrays",
    "replicated",
    "shard_map",
    "shard_panel",
    "force_host_device_count",
]

# Mesh / NamedSharding / PartitionSpec are RE-EXPORTED on purpose: every
# module outside this file imports sharding symbols from HERE (the
# mesh-shim discipline, enforced by `python -m aiyagari_tpu.analysis`
# rule AIYA201), so a jax upgrade that moves or renames them is a
# one-file fix — the same contract shard_map's version probe below
# already provides.

AGENTS_AXIS = "agents"
GRID_AXIS = "grid"
SCENARIOS_AXIS = "scenarios"

# jax >= 0.6 promotes shard_map to the top-level namespace; earlier releases
# (this image ships 0.4.x) only have the experimental module. Every sharded
# solver imports the symbol from HERE so the version probe lives in one place.
# All call sites use the keyword form (mesh=/in_specs=/out_specs=), which both
# generations accept identically.
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # pragma: no cover - exercised on jax < 0.6 images (like this one)
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, **kwargs):
        # The experimental generation has no replication rule for while_loop
        # (every solver fixed point here is one) unless its static
        # replication CHECK is disabled; the check is advisory — disabling
        # it changes no computed values.
        kwargs.setdefault("check_rep", False)
        return _experimental_shard_map(f, mesh=mesh, in_specs=in_specs,
                                       out_specs=out_specs, **kwargs)


def force_host_device_count(n: int) -> None:
    """Request n virtual host devices (call BEFORE any jax initialization).

    This is the no-hardware test path (SURVEY.md §4.4): an 8-virtual-device CPU
    mesh exercises the same shardings and collectives as a v5e-8 slice.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    flag = f"--xla_force_host_platform_device_count={n}"
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (flags + " " + flag).strip()


def factor_axis_sizes(ndevices: int,
                      sizes: Sequence[Optional[int]]) -> tuple:
    """Resolve a per-axis size request against a device count.

    `sizes` has one entry per mesh axis; `None` entries are FILLED so the
    product equals `ndevices` — one None takes the whole remaining quotient,
    several Nones split it as evenly as the prime factorization allows
    (largest prime factors assigned to the currently-smallest axis, then
    sorted descending, so the FIRST axis gets the larger share — the
    data-parallel-major convention). Every mismatch is loud: a fixed
    request whose product does not divide (or, fully specified, does not
    EQUAL) the device count raises instead of silently truncating to a 1-D
    mesh — the exact degeneration the old `[ndevices, 1, ...]` default
    produced for multi-axis requests."""
    ndevices = int(ndevices)
    if ndevices < 1:
        raise ValueError(f"need at least one device, got {ndevices}")
    fixed = 1
    free = 0
    for s in sizes:
        if s is None:
            free += 1
        elif int(s) < 1:
            raise ValueError(f"mesh axis sizes must be >= 1, got {sizes}")
        else:
            fixed *= int(s)
    if ndevices % fixed:
        raise ValueError(
            f"{ndevices} devices do not factor over the requested axis "
            f"sizes {tuple(sizes)}: the fixed axes multiply to {fixed}, "
            f"which does not divide {ndevices}")
    rem = ndevices // fixed
    if free == 0:
        if rem != 1:
            raise ValueError(
                f"axis sizes {tuple(sizes)} cover only {fixed} of "
                f"{ndevices} devices; sizes must multiply to the device "
                "count (or leave an axis None to derive it)")
        return tuple(int(s) for s in sizes)
    # Balanced split of the remaining quotient over the free axes: peel the
    # prime factors (largest first) onto whichever free axis is currently
    # smallest.
    factors = []
    n, p = rem, 2
    while p * p <= n:
        while n % p == 0:
            factors.append(p)
            n //= p
        p += 1
    if n > 1:
        factors.append(n)
    split = [1] * free
    for f in sorted(factors, reverse=True):
        split[split.index(min(split))] *= f
    split.sort(reverse=True)
    out = []
    it = iter(split)
    for s in sizes:
        out.append(next(it) if s is None else int(s))
    return tuple(out)


def make_mesh(axis_names: Sequence[str] = (AGENTS_AXIS,),
              axis_sizes: Optional[Sequence[int]] = None,
              devices=None) -> Mesh:
    """Build a named mesh over the available devices.

    Default: all devices, split over the named axes by factor_axis_sizes —
    one axis gets every device (the historical behavior); a MULTI-axis
    request with axis_sizes=None is factorized balanced-descending (8
    devices over two axes -> 4 x 2) instead of the old silent
    `[ndevices, 1, ...]` degeneration to a 1-D mesh. axis_sizes entries
    may be None (derived, loud when the device count does not factor);
    fully-explicit sizes pass through unchanged — jax.make_mesh
    legitimately sub-selects the first prod(axis_sizes) devices, the
    mesh_shape=(4,)-on-8-devices idiom. make_mesh_2d adds the strict
    every-device-covered check the sweep meshes want."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    if axis_sizes is None:
        axis_sizes = factor_axis_sizes(len(devices),
                                       (None,) * len(axis_names))
    elif any(s is None for s in axis_sizes):
        axis_sizes = factor_axis_sizes(len(devices), axis_sizes)
    # Fully-explicit sizes pass through: jax.make_mesh legitimately
    # sub-selects the first prod(axis_sizes) devices (the mesh_shape=(4,)
    # on-an-8-device-host idiom tests rely on).
    # Auto axis types: classic GSPMD sharding propagation. (jax 0.9's
    # make_mesh defaults to Explicit sharding-in-types, which rejects gathers
    # whose output sharding is ambiguous.) Older jax (< 0.5) predates
    # AxisType entirely — and is Auto-only, so omitting the argument there
    # selects the same semantics.
    axis_type = getattr(jax.sharding, "AxisType", None)
    kwargs = ({"axis_types": (axis_type.Auto,) * len(axis_names)}
              if axis_type is not None else {})
    return jax.make_mesh(
        tuple(axis_sizes), tuple(axis_names), devices=devices.ravel(), **kwargs
    )


def make_mesh_2d(scenarios: Optional[int] = None,
                 grid: Optional[int] = None,
                 devices=None) -> Mesh:
    """A 2-D ("scenarios", "grid") mesh over all devices — the pod-scale
    composition: the scenario batch splits across the first axis (hosts,
    on a multi-host mesh: jax.make_mesh lays processes out major-first)
    while each scenario's asset grid splits across the second (a host's
    chips, ICI-linked).

    None sizes are derived by factor_axis_sizes: both None -> balanced
    factorization with scenarios getting the larger share (8 devices ->
    4 x 2); one given -> the other is the exact quotient. Unlike the 1-D
    make_mesh passthrough, this mesh must cover EVERY device — a size
    that does not factor the device count raises loudly (a silently
    smaller sweep mesh would leave chips idle while reporting pod-scale
    throughput)."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    sizes = factor_axis_sizes(len(devices), (scenarios, grid))
    return make_mesh((SCENARIOS_AXIS, GRID_AXIS), sizes, devices=devices)


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    """NamedSharding(mesh, PartitionSpec(*spec)) — the one-liner every
    ad-hoc placement (checkpoint restore shardings, replication of a
    process-spanning policy) goes through instead of importing the raw
    jax.sharding classes."""
    return NamedSharding(mesh, PartitionSpec(*spec))


def agents_sharding(mesh: Mesh, batch_axis: int = 0) -> NamedSharding:
    """Shard an agent-panel array along its agent axis."""
    spec = [None] * (batch_axis + 1)
    spec[batch_axis] = AGENTS_AXIS
    return NamedSharding(mesh, PartitionSpec(*spec))


def grid_sharding(mesh: Mesh, grid_axis: int = -1, ndim: int = 2) -> NamedSharding:
    """Shard a value/policy array along its (fine) asset-grid axis."""
    spec: list = [None] * ndim
    spec[grid_axis] = GRID_AXIS
    return NamedSharding(mesh, PartitionSpec(*spec))


def scenarios_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Shard a scenario-major stacked array along its leading scenario axis
    (the batched-GE sweep's data-parallel axis, equilibrium/batched.py:
    each device owns S/D whole economies and the vmapped excess-demand
    kernel needs NO cross-scenario communication at all)."""
    spec: list = [None] * ndim
    spec[0] = SCENARIOS_AXIS
    return NamedSharding(mesh, PartitionSpec(*spec))


def shard_scenario_arrays(mesh: Mesh, count: int, **arrays):
    """Place scenario-major stacked arrays (leading axis = scenario) sharded
    over the mesh's "scenarios" axis, with the divisibility check every
    scenario-batched entry point needs stated ONCE.

    `count` is the scenario-batch size; every array in `arrays` must lead
    with it. Divisibility is against the "scenarios" AXIS size, not the
    total device count — a multi-axis mesh only splits the scenario axis
    that wide (the other axes replicate). Returns the dict with each value
    device_put under scenarios_sharding (rank-aware). Shared by the batched
    GE sweep (equilibrium/batched.stack_scenarios) and the transition-path
    sweep (transition/mit.py)."""
    axis_size = int(mesh.shape[SCENARIOS_AXIS])
    if count % axis_size != 0:
        raise ValueError(
            f"scenario count {count} must divide evenly over the "
            f"{axis_size}-wide '{SCENARIOS_AXIS}' mesh axis")
    return {k: jax.device_put(v, scenarios_sharding(mesh, ndim=v.ndim))
            for k, v in arrays.items()}


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def shard_panel(array, mesh: Mesh, batch_axis: int = 0):
    """Place a panel array with its agent axis sharded across the mesh."""
    return jax.device_put(array, agents_sharding(mesh, batch_axis))
