"""Multi-host distributed runtime surface (SURVEY.md §5.8).

The reference is a single MATLAB process with no communication backend at all
(SURVEY.md §2.4). The TPU-native design scales the same workloads across hosts
by initializing JAX's distributed runtime (one process per host, all devices
visible globally) and building ONE global mesh over every device in the job;
ICI carries intra-slice collectives and DCN inter-slice, both invisible behind
the NamedSharding / shard_map annotations used everywhere else in the
framework. No solver or simulator code changes between single-host and
multi-host — only this initialization step and the mesh construction differ.

Single-process (a laptop, one chip, the CPU test mesh) is the common case, so
`initialize_distributed()` is an explicit no-op there rather than an error.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional, Sequence

import jax

__all__ = [
    "DistributedContext",
    "initialize_distributed",
    "peek_process_topology",
    "process_info",
]


@dataclass(frozen=True)
class DistributedContext:
    """What the runtime looks like after (possible) initialization."""

    initialized: bool          # True iff jax.distributed.initialize() ran
    process_id: int            # this host's index (0 in single-process)
    num_processes: int         # world size (1 in single-process)
    local_device_count: int    # devices attached to this host
    global_device_count: int   # devices across the whole job


def _env_int(name: str) -> Optional[int]:
    v = os.environ.get(name)
    return int(v) if v not in (None, "") else None


def _distributed_is_initialized() -> bool:
    """jax.distributed.is_initialized, with a fallback for jax < 0.5 (this
    image): the runtime's client handle in the global state is the same
    predicate that accessor wraps."""
    fn = getattr(jax.distributed, "is_initialized", None)
    if fn is not None:
        return bool(fn())
    state = getattr(jax.distributed, "global_state", None)
    if state is None:  # pragma: no cover - very old layouts
        from jax._src.distributed import global_state as state
    return getattr(state, "client", None) is not None


def peek_process_topology() -> tuple:
    """(process_index, process_count) WITHOUT initializing a backend.

    jax.process_index()/process_count() force backend initialization on
    first call — too heavy a side effect for the observability layers
    (ledger event stamping, metrics host labels) that only need to know
    whether this is a multi-host job. The distributed runtime's global
    state answers that directly: multi-process requires
    jax.distributed.initialize, whose client handle (the same predicate
    _distributed_is_initialized reads) carries the topology. Single
    process — including every not-yet-initialized interpreter — is
    (0, 1)."""
    state = getattr(jax.distributed, "global_state", None)
    if state is None:  # pragma: no cover - very old layouts
        from jax._src.distributed import global_state as state
    if getattr(state, "client", None) is None:
        return 0, 1
    return (int(getattr(state, "process_id", 0) or 0),
            int(getattr(state, "num_processes", 1) or 1))


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[Sequence[int]] = None,
) -> DistributedContext:
    """Initialize the JAX distributed runtime for a multi-host job.

    Arguments default from the standard environment variables
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID), matching
    how TPU pod launchers pass topology; on Cloud TPU pods
    jax.distributed.initialize also auto-detects everything, so calling with
    no arguments there is correct. When neither arguments nor environment
    describe a multi-process job (num_processes in (None, 1) and no
    coordinator), this is a no-op returning a single-process context — the
    same code path then runs unchanged on one host.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    num_processes = num_processes if num_processes is not None else _env_int(
        "JAX_NUM_PROCESSES"
    )
    process_id = process_id if process_id is not None else _env_int("JAX_PROCESS_ID")

    if _distributed_is_initialized():
        # Idempotent re-entry: a launcher and a library entry point may both
        # call this defensively; a second jax.distributed.initialize raises.
        return process_info(initialized=True)

    multi = (num_processes is not None and num_processes > 1) or (
        coordinator_address is not None
    )
    if multi:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
        )
    return process_info(initialized=multi)


def process_info(initialized: Optional[bool] = None) -> DistributedContext:
    """Snapshot of the current process topology."""
    return DistributedContext(
        initialized=bool(initialized)
        if initialized is not None
        else jax.process_count() > 1,
        process_id=jax.process_index(),
        num_processes=jax.process_count(),
        local_device_count=jax.local_device_count(),
        global_device_count=jax.device_count(),
    )
