"""Ring-redistribution sharded power-grid inversion: the grid-axis
distribution of the EGM hot operation for knot arrays whose brackets lie
ARBITRARILY far from their query's shard — the regime the one-hop halo
exchange (parallel/halo.py) cannot cover.

Why the halo variant is not enough for the EGM solver: the endogenous grid
a_hat is the INVERSE savings policy, and at the dense bottom of the power
grid a household's one-period jump w·s − c maps to an index displacement
that is a constant FRACTION of the grid (measured: bracket lag up to
0.33·n at the shipped Aiyagari calibration, every grid size — the policy
jump in value space is O(1) and the power grid's index density scales with
n). A neighbor halo is bounded by the shard size n/D, so for D ≥ 4 no
legal halo covers the lag. The halo kernel remains correct and shipped for
narrow-lag inversions; this module is the general mechanism.

Design — value-space knot redistribution over a ring, O(n/D) memory:

  1. Every device computes, for ALL devices' first queries (analytic, so
     no communication), the count of its own shard's knots strictly below
     each; one psum yields the exact global bracket start c_e per device.
  2. Each device assembles the contiguous global knot slab
     [c_dev − pad, c_dev − pad + B) that covers its queries' brackets: the
     shards rotate around the ring (D−1 `lax.ppermute` rounds) and each
     visiting shard is aligned into the local buffer with one roll + mask
     (no gathers). Positions outside [0, n_k) take ±inf SENTINELS, making
     the global count telescope exactly (cnt = s_start + buffer count) —
     the same trick as the halo kernel's edge sentinels.
  3. The device then runs the standard two-level windowed compare-reduce
     (ops/interp._bracket_power_grid's geometry: 512-query blocks,
     6×512-knot windows) against its LOCAL buffer only, and finishes with
     the shared _finish_inverse tail, so the sharded and unsharded routes
     cannot drift.

Per-device memory is B = capacity·(n/D) (+ window margin); the measured
slab requirement of the EGM endogenous grids is 1.11·(n/D) (worst device
over sweeps and states at the shipped calibration, both 8k and 40k grids —
the knot count landing in one query shard's value range is bounded by the
endogenous grid's density ratio, not by the bracket LAG, which only sets
where the slab starts). Default capacity 2.0 ≈ 80% headroom. A buffer
overflow — bracket beyond the slab — ESCAPES with the same
NaN-poisoning contract as the windowed route, and host wrappers fall back
to the unsharded solver. Total ring traffic per sweep is one full rotation
of the knot array (the same volume an all-gather would move) — the win is
not bandwidth, it is that no device ever MATERIALIZES more than B knots,
which is what makes grids that overflow one device's memory solvable at
all (SURVEY.md §2.4(1), Aiyagari_EGM.m:95).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from aiyagari_tpu.parallel.mesh import PartitionSpec as P, shard_map as _shard_map

from aiyagari_tpu.ops.interp import (
    _INV_KBLOCK,
    _INV_QBLOCK,
    _INV_WBLOCKS,
    _finish_inverse,
    _finish_monotone,
)
from aiyagari_tpu.parallel.halo import cached_program, mesh_fingerprint

__all__ = ["DEFAULT_CAPACITY", "interp_monotone_power_grid_ring",
           "inverse_interp_power_grid_ring", "ring_inverse_local",
           "ring_interp_local", "ring_buffer_size", "ring_slab_assemble",
           "ring_slab_fits"]

# The default per-device slab capacity (in shards): the measured EGM slab
# requirement is 1.11 shards (module docstring); 2.0 is ~80% headroom.
# Single source of truth for the solver default and the config-level
# soundness gate (equilibrium/bisection.py).
DEFAULT_CAPACITY = 2.0


def ring_slab_fits(n_k: int, D: int,
                   capacity: float = DEFAULT_CAPACITY) -> bool:
    """Whether the per-device slab is geometrically sound: it must not
    exceed the (block-padded) knot row itself, or the window clamp's
    arithmetic inverts and the slab fetch silently duplicates knot blocks.
    The single predicate behind solve_aiyagari_egm_sharded's loud guard and
    the config-level silent degrade (equilibrium/bisection.py)."""
    KB = _INV_KBLOCK
    return ring_buffer_size(n_k, D, capacity) <= -(-n_k // KB) * KB


def ring_buffer_size(n_k: int, D: int, capacity: float) -> int:
    """Static per-device knot-buffer length: capacity·shard plus one window
    of slack, rounded up to the knot-block granularity. The floor (one shard
    or one window, whichever is larger) is what the merge roll and the
    window clamp require; capacities below ~1 degenerate to it and exist
    only to exercise the escape contract."""
    L = n_k // D
    KB, M = _INV_KBLOCK, _INV_WBLOCKS
    B = int(capacity * L) + M * KB
    return max(-(-B // KB) * KB, -(-L // KB) * KB, M * KB)


def ring_slab_assemble(visit, s_start, *, B: int, n_k: int, axis: str,
                       D: int):
    """Step 2 of the ring redistribution, shared by every sharded kernel
    that needs a contiguous global knot slab resident per device: rotate
    the [C, R, L] stacked shard channels around the ring (D-1
    `lax.ppermute` rounds) and align each visiting shard into the [C, R, B]
    buffer with one roll + mask per row. Row r's buffer covers global
    positions [s_start[r], s_start[r] + B); positions outside [0, n_k)
    carry ±inf sentinels (-inf below, +inf at-or-above), which make global
    bracket counts telescope exactly and read as out-of-range knots to
    every downstream kernel. Call from INSIDE a shard_map over `axis`;
    global order: device d owns positions [d*L, (d+1)*L)."""
    C, R, L = visit.shape
    dtype = visit.dtype
    dev = jax.lax.axis_index(axis)
    neg = jnp.array(-jnp.inf, dtype)
    pos = jnp.array(jnp.inf, dtype)
    g0 = s_start[:, None] + jnp.arange(B)[None, :]                  # [R, B]
    buf0 = jnp.where(g0 < 0, neg, pos)
    buf = jnp.broadcast_to(buf0[None], (C, R, B))
    perm = [(i, (i - 1) % D) for i in range(D)]
    bpos = jnp.arange(B)

    def merge_row(bufr, vr, off):
        padded = jnp.concatenate([vr, jnp.full((B - L,), pos)])
        rolled = jnp.roll(padded, off)
        m = (bpos >= off) & (bpos < off + L)
        return jnp.where(m, rolled, bufr)

    merge = jax.vmap(jax.vmap(merge_row), in_axes=(0, 0, None))
    for t in range(D):
        f = (dev + t) % D                       # visiting shard's global id
        off = f * L - s_start                   # [R] buffer offset
        buf = merge(buf, visit, off)
        if t < D - 1:
            visit = jax.lax.ppermute(visit, axis, perm)
    return buf


def _ring_bracket_local(xl, yl, q, *, axis: str, D: int, n_k: int, n_q: int,
                        lo: float, hi: float, power: float,
                        capacity: float, pad: int):
    """Shared slab assembly + windowed bracket of the ring-sharded kernels:
    steps 1-3 of the module docstring for this device's knot shard xl
    [R, n_k/D] and query slice q [n_q/D], optionally carrying a VALUE shard
    yl of the same shape through the identical rotation/merge (the monotone
    value interpolation needs the bracketing values; the inverse
    reconstructs them from the count). Returns (cnt, x0, x1, y0, y1,
    escaped) with y0/y1 None when yl is None — the sharded mirror of
    ops/interp._bracket_power_grid, and the single place the slab geometry
    lives so the inverse and value kernels cannot drift."""
    R, L = xl.shape
    nq_loc = q.shape[-1]
    dtype = xl.dtype
    span = hi - lo
    dev = jax.lax.axis_index(axis)
    neg = jnp.array(-jnp.inf, dtype)
    pos = jnp.array(jnp.inf, dtype)
    B = ring_buffer_size(n_k, D, capacity)
    S, KB, M = _INV_QBLOCK, _INV_KBLOCK, _INV_WBLOCKS
    Lw = M * KB
    nkb_buf = B // KB
    nb = -(-nq_loc // S)
    with_y = yl is not None

    # 1. Exact global bracket starts: every device's first query is analytic,
    # so each device counts its own knots below ALL of them and one psum
    # telescopes the global counts. Strict < matches the bracket convention.
    e = jnp.arange(D)
    q_first_all = lo + span * ((e * (n_q // D)).astype(dtype) / (n_q - 1)) ** power
    cnt_part = jnp.sum(xl[:, None, :] < q_first_all[None, :, None],
                       axis=-1).astype(jnp.int32)                   # [R, D]
    c_all = jax.lax.psum(cnt_part, axis)                            # [R, D]
    s_start = c_all[:, dev] - pad                                   # [R]

    # 2. Assemble the buffer(s): the value shard rides the SAME rotation as
    # a stacked channel (one ppermute per round, not two), and shares the
    # ±inf sentinels: at positions outside [0, n_k) the x sentinel decides
    # the comparison mask and the matching y sentinel keeps the masked
    # max/min reductions unaffected.
    visit = jnp.stack([xl, yl]) if with_y else xl[None]             # [C, R, L]
    buf = ring_slab_assemble(visit, s_start, B=B, n_k=n_k, axis=axis, D=D)
    C = buf.shape[0]

    # 3. Two-level windowed bracket against the local buffer (the geometry
    # of ops/interp._bracket_power_grid's windowed route, buffer-offset).
    jq = jnp.minimum(jnp.arange(nb * S), nq_loc - 1)    # clamp query padding
    qs = q[jq].reshape(nb, S)

    def bracket_row(bufr, byr, s0):
        s_first = jnp.sum(bufr[None, :] < qs[:, :1], axis=1).astype(jnp.int32)
        ab = jnp.minimum(jnp.clip(s_first - 1, 0, B - 1) // KB, nkb_buf - M)
        seg = bufr.reshape(nkb_buf, KB)[ab[:, None] + jnp.arange(M)[None, :]]
        seg = seg.reshape(nb, Lw)
        lt = seg[:, None, :] < qs[:, :, None]                     # [nb, S, Lw]
        cnt_w = jnp.sum(lt, axis=-1).astype(jnp.int32)
        cnt = s0 + ab[:, None] * KB + cnt_w                       # global
        x0 = jnp.max(jnp.where(lt, seg[:, None, :], neg), axis=-1)
        x1 = jnp.min(jnp.where(lt, pos, seg[:, None, :]), axis=-1)
        # Saturated window whose global end is short of the knot top: the
        # bracket may continue beyond it (density overflow within the
        # buffer, or the buffer itself too small) — one uniform escape rule,
        # the buffer-offset form of the unsharded windowed route's.
        esc = jnp.any((cnt_w == Lw) & (s0 + (ab[:, None] + M) * KB < n_k))

        def cut(a):
            return a.reshape(-1)[:nq_loc]

        if not with_y:
            return cut(cnt), cut(x0), cut(x1), cut(x0), cut(x1), esc
        # The y brackets come from the SAME mask: y is monotone (caller's
        # contract, cf. interp_monotone_power_grid), so the masked max/min
        # are exactly the bracket's endpoint values whenever the x bracket
        # is exact (same saturation rule).
        segy = byr.reshape(nkb_buf, KB)[ab[:, None] + jnp.arange(M)[None, :]]
        segy = segy.reshape(nb, Lw)
        y0 = jnp.max(jnp.where(lt, segy[:, None, :], neg), axis=-1)
        y1 = jnp.min(jnp.where(lt, pos, segy[:, None, :]), axis=-1)
        return cut(cnt), cut(x0), cut(x1), cut(y0), cut(y1), esc

    cnt, x0, x1, y0, y1, esc_rows = jax.vmap(bracket_row)(
        buf[0], buf[C - 1], s_start)
    escaped = jax.lax.pmax(jnp.any(esc_rows).astype(jnp.int32), axis)
    return cnt, x0, x1, (y0 if with_y else None), (y1 if with_y else None), \
        escaped


def ring_inverse_local(xl, q, *, axis: str, D: int, n_k: int, n_q: int,
                       lo: float, hi: float, power: float,
                       capacity: float = DEFAULT_CAPACITY, pad: int = 8):
    """Shard-local body of the ring-redistribution inversion — call from
    INSIDE a shard_map over `axis`.

    xl [R, n_k/D] is this device's contiguous sorted-knot shard (global
    order: device d owns indices [d·L, (d+1)·L)), q [n_q/D] its slice of
    the analytic power query grid. Returns (out [R, n_q/D], escaped int32
    scalar pmax'd across the axis), `out` already NaN-poisoned on escape.
    Semantics match ops/interp.inverse_interp_power_grid exactly (strict-<
    brackets, below-range extrapolation, top truncation).
    """
    cnt, x0, x1, _, _, escaped = _ring_bracket_local(
        xl, None, q, axis=axis, D=D, n_k=n_k, n_q=n_q, lo=lo, hi=hi,
        power=power, capacity=capacity, pad=pad)

    # Shared finish (below-range slope needs the global first knot pair:
    # all-gather the tiny per-shard heads, take device 0's).
    head2 = jax.lax.all_gather(xl[:, :2], axis)[0]
    out = jax.vmap(
        lambda c, a0, a1, h2: _finish_inverse(
            c, a0, a1, h2, lo=lo, hi=hi, power=power, n_q=n_q, n_k=n_k,
            q_vals=q,
        )
    )(cnt, x0, x1, head2)
    out = jnp.where(escaped > 0, jnp.nan, out)
    return out, escaped


def ring_interp_local(xl, yl, q, *, axis: str, D: int, n_k: int, n_q: int,
                      lo: float, hi: float, power: float,
                      capacity: float = DEFAULT_CAPACITY, pad: int = 8):
    """Shard-local monotone VALUE interpolation with ring-redistributed
    (knot, value) pairs — call from INSIDE a shard_map over `axis`. The
    sharded form of ops/interp.interp_monotone_power_grid (the labor-EGM
    hot operation, Aiyagari_Endogenous_Labor_EGM.m:90): xl [R, n_k/D] this
    device's sorted-knot shard, yl its monotone value shard (monotonicity
    is the caller's contract, as in the unsharded kernel), q [n_q/D] its
    analytic query slice. Returns (out [R, n_q/D], escaped int32 scalar
    pmax'd across the axis), NaN-poisoned on escape. The value shard rides
    the knot rotation as a stacked channel, so the ring traffic is 2x the
    inversion's — still one O(n/D) slab per device, never the full row.
    """
    cnt, x0, x1, y0, y1, escaped = _ring_bracket_local(
        xl, yl, q, axis=axis, D=D, n_k=n_k, n_q=n_q, lo=lo, hi=hi,
        power=power, capacity=capacity, pad=pad)
    del cnt  # the value kernel reads brackets, not counts

    # Global head pairs for the below-range extrapolation slope: one
    # all-gather of the stacked [2, R, 2] shard heads, take device 0's
    # (its shard starts at global index 0).
    heads = jax.lax.all_gather(jnp.stack([xl[:, :2], yl[:, :2]]), axis)[0]
    out = jax.vmap(
        lambda a0, a1, b0, b1, hx, hy: _finish_monotone(a0, a1, b0, b1,
                                                        hx, hy, q)
    )(x0, x1, y0, y1, heads[0], heads[1])
    out = jnp.where(escaped > 0, jnp.nan, out)
    return out, escaped


_RING_PROGRAMS: dict = {}


def inverse_interp_power_grid_ring(mesh, x, lo: float, hi: float,
                                   power: float, n_q: int, *,
                                   axis: str = "grid",
                                   capacity: float = DEFAULT_CAPACITY,
                                   pad: int = 8):
    """Distributed inverse interpolation onto the n_q-point power grid with
    ring-redistributed knots (module docstring). x [..., n_k] sorted knots,
    sharded (or shardable) along the last axis over mesh[axis]; the axis
    size must divide n_k and n_q. Returns (out [..., n_q] sharded along the
    last axis, escaped scalar bool). Semantics match
    ops/interp.inverse_interp_power_grid.
    """
    D = mesh.shape[axis]
    n_k = x.shape[-1]
    if n_k % D or n_q % D:
        raise ValueError(
            f"mesh axis size {D} must divide n_k={n_k} and n_q={n_q}")
    if not ring_slab_fits(n_k, D, capacity):
        # Slab > padded knot row inverts the window clamp's arithmetic and
        # silently duplicates knot blocks (ring_slab_fits docstring) — the
        # geometry is a hard error at every public entry, not just the EGM
        # solver's.
        raise ValueError(
            f"ring slab does not fit: n_k={n_k} over {D} devices at "
            f"capacity={capacity} needs a {ring_buffer_size(n_k, D, capacity)}"
            f"-knot buffer > the padded knot row; use fewer devices or a "
            f"larger grid (ring_slab_fits)")
    if pad < 1:
        # pad >= 1 keeps each device's first query's LOWER bracketing knot
        # (global index c-1) inside the slab; pad=0 would silently degrade
        # that query to its lower grid value with escaped=False.
        raise ValueError(f"pad must be >= 1, got {pad}")
    lead = x.shape[:-1]
    xr = x.reshape((-1, n_k))
    run = _ring_fn(mesh, axis, n_k, n_q, float(lo), float(hi), float(power),
                   float(capacity), int(pad), jnp.dtype(x.dtype).name)
    out, escaped = run(xr)
    return out.reshape(lead + (n_q,)), escaped > 0


def _ring_fn(mesh, axis: str, n_k: int, n_q: int, lo: float, hi: float,
             power: float, capacity: float, pad: int, dtype_name: str):
    D = mesh.shape[axis]
    nq_loc = n_q // D
    dtype = jnp.dtype(dtype_name)
    span = hi - lo

    def build():
        def local(xl):
            dev = jax.lax.axis_index(axis)
            j = dev * nq_loc + jnp.arange(nq_loc)
            q = lo + span * (j.astype(dtype) / (n_q - 1)) ** power
            return ring_inverse_local(xl, q, axis=axis, D=D, n_k=n_k,
                                      n_q=n_q, lo=lo, hi=hi, power=power,
                                      capacity=capacity, pad=pad)

        return jax.jit(_shard_map(
            local, mesh=mesh,
            in_specs=P(None, axis),
            out_specs=(P(None, axis), P()),
        ))

    key = mesh_fingerprint(mesh, axis) + (n_k, n_q, lo, hi, power, capacity,
                                          pad, dtype_name)
    return cached_program(_RING_PROGRAMS, key, build)


_RING_INTERP_PROGRAMS: dict = {}


def interp_monotone_power_grid_ring(mesh, x, y, lo: float, hi: float,
                                    power: float, n_q: int, *,
                                    axis: str = "grid",
                                    capacity: float = DEFAULT_CAPACITY,
                                    pad: int = 8):
    """Distributed monotone VALUE interpolation onto the n_q-point power
    grid with ring-redistributed (knot, value) pairs — the host-level entry
    over ring_interp_local, mirroring inverse_interp_power_grid_ring.
    x [..., n_k] sorted knots, y same shape with non-decreasing values
    (the caller's monotonicity contract, as in
    ops/interp.interp_monotone_power_grid, whose semantics this matches);
    both sharded (or shardable) along the last axis over mesh[axis].
    Returns (out [..., n_q] sharded along the last axis, escaped bool)."""
    D = mesh.shape[axis]
    n_k = x.shape[-1]
    if x.shape != y.shape:
        raise ValueError(f"x and y must share a shape, got {x.shape} vs {y.shape}")
    if n_k % D or n_q % D:
        raise ValueError(
            f"mesh axis size {D} must divide n_k={n_k} and n_q={n_q}")
    if not ring_slab_fits(n_k, D, capacity):
        raise ValueError(
            f"ring slab does not fit: n_k={n_k} over {D} devices at "
            f"capacity={capacity} needs a {ring_buffer_size(n_k, D, capacity)}"
            f"-knot buffer > the padded knot row; use fewer devices or a "
            f"larger grid (ring_slab_fits)")
    if pad < 1:
        raise ValueError(f"pad must be >= 1, got {pad}")
    lead = x.shape[:-1]
    run = _ring_interp_fn(mesh, axis, n_k, n_q, float(lo), float(hi),
                          float(power), float(capacity), int(pad),
                          jnp.dtype(x.dtype).name)
    out, escaped = run(x.reshape((-1, n_k)), y.reshape((-1, n_k)))
    return out.reshape(lead + (n_q,)), escaped > 0


def _ring_interp_fn(mesh, axis: str, n_k: int, n_q: int, lo: float, hi: float,
                    power: float, capacity: float, pad: int, dtype_name: str):
    D = mesh.shape[axis]
    nq_loc = n_q // D
    dtype = jnp.dtype(dtype_name)
    span = hi - lo

    def build():
        def local(xl, yl):
            dev = jax.lax.axis_index(axis)
            j = dev * nq_loc + jnp.arange(nq_loc)
            q = lo + span * (j.astype(dtype) / (n_q - 1)) ** power
            return ring_interp_local(xl, yl, q, axis=axis, D=D, n_k=n_k,
                                     n_q=n_q, lo=lo, hi=hi, power=power,
                                     capacity=capacity, pad=pad)

        return jax.jit(_shard_map(
            local, mesh=mesh,
            in_specs=(P(None, axis), P(None, axis)),
            out_specs=(P(None, axis), P()),
        ))

    key = mesh_fingerprint(mesh, axis) + (n_k, n_q, lo, hi, power, capacity,
                                          pad, dtype_name)
    return cached_program(_RING_INTERP_PROGRAMS, key, build)
