"""Krusell-Smith (1998) model: aggregate TFP shocks, idiosyncratic employment
risk, and a log-linear aggregate law of motion (ALM) for forecasting K'.

Bundles the discretized primitives derived from a KrusellSmithConfig:
individual/aggregate capital grids, the joint 4-state (z x eps) chain, the
conditional employment-transition matrices used by the shock simulator, and
the (state, K) price tables. Reference: Krusell_Smith_VFI.m:5-135.

State ordering (index s in 0..3): (good, employed), (bad, employed),
(good, unemployed), (bad, unemployed) — see utils.markov.KS_STATE_GRID_ORDER.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from aiyagari_tpu.config import KrusellSmithConfig
from aiyagari_tpu.utils.firm import ks_price_tables
from aiyagari_tpu.utils.grids import ks_k_grid, ks_K_grid
from aiyagari_tpu.utils.markov import (
    KS_STATE_GRID_ORDER,
    ks_conditional_eps_matrices,
    ks_transition_matrix,
)

__all__ = ["KrusellSmithModel", "ks_preset", "state_index"]


def state_index(z_idx, employed):
    """Map (z index 0=good/1=bad, employed flag) -> joint state index,
    replacing the reference's stringly-keyed containers.Map lookup
    (Krusell_Smith_VFI.m:118-126) with integer arithmetic."""
    return z_idx + 2 * (1 - employed)


@dataclasses.dataclass(frozen=True)
class KrusellSmithModel:
    """Discretized K-S economy ready for the solvers/simulator."""

    config: KrusellSmithConfig
    k_grid: jnp.ndarray        # [nk] individual capital grid (power-7)
    K_grid: jnp.ndarray        # [nK] aggregate capital grid
    P: jnp.ndarray             # [4, 4] joint transition matrix
    z_by_state: jnp.ndarray    # [4] TFP level per joint state
    eps_by_state: jnp.ndarray  # [4] employment indicator per joint state
    L_by_state: jnp.ndarray    # [4] aggregate labor per joint state
    w_table: jnp.ndarray       # [4, nK]
    r_table: jnp.ndarray       # [4, nK]
    pz: jnp.ndarray            # [2, 2] aggregate chain
    eps_trans: jnp.ndarray     # [2(z), 2(z'), 2(eps), 2(eps')] conditional chain

    @classmethod
    def from_config(cls, config: KrusellSmithConfig, dtype=jnp.float64) -> "KrusellSmithModel":
        sh = config.shocks
        k_grid = ks_k_grid(config)
        K_grid = ks_K_grid(config)
        P = ks_transition_matrix(sh)

        z_levels = np.array([sh.z_good, sh.z_bad])
        u_rates = np.array([sh.u_good, sh.u_bad])
        z_by_state = np.array([z_levels[zi] for zi, _ in KS_STATE_GRID_ORDER])
        eps_by_state = np.array([float(emp) for _, emp in KS_STATE_GRID_ORDER])
        # Aggregate labor L = l_bar * (1 - u(z)): Krusell_Smith_VFI.m:112.
        L_by_state = np.array([config.l_bar * (1.0 - u_rates[zi]) for zi, _ in KS_STATE_GRID_ORDER])
        w_table, r_table = ks_price_tables(z_by_state, L_by_state, K_grid, config.technology.alpha)

        pgg = 1.0 - 1.0 / sh.z_good_duration
        pbb = 1.0 - 1.0 / sh.z_bad_duration
        pz = np.array([[pgg, 1.0 - pgg], [1.0 - pbb, pbb]])

        mats = ks_conditional_eps_matrices(sh)
        # eps_trans[zi, zj] = 2x2 matrix [eps, eps'] (0=employed, 1=unemployed).
        eps_trans = np.zeros((2, 2, 2, 2))
        for (zi, zj), key in {(0, 0): "gg", (1, 1): "bb", (0, 1): "gb", (1, 0): "bg"}.items():
            eps_trans[zi, zj] = mats[key]

        as_dtype = lambda a: jnp.asarray(a, dtype)
        return cls(
            config=config,
            k_grid=as_dtype(k_grid),
            K_grid=as_dtype(K_grid),
            P=as_dtype(P),
            z_by_state=as_dtype(z_by_state),
            eps_by_state=as_dtype(eps_by_state),
            L_by_state=as_dtype(L_by_state),
            w_table=as_dtype(w_table),
            r_table=as_dtype(r_table),
            pz=as_dtype(pz),
            eps_trans=as_dtype(eps_trans),
        )

    @property
    def dtype(self):
        return self.k_grid.dtype

    @property
    def n_states(self) -> int:
        return 4


def ks_preset(dtype=jnp.float64, **overrides) -> KrusellSmithModel:
    """The reference parameterization (Krusell_Smith_VFI.m:5-13)."""
    return KrusellSmithModel.from_config(KrusellSmithConfig(**overrides), dtype)
