"""Aiyagari (1994) model family: exogenous- and endogenous-labor variants.

Bundles the discretized primitives (income chain, asset grid, labor grids)
derived from an AiyagariConfig, converted once to device arrays of the
backend dtype. Reference parameterizations: Aiyagari_VFI.m:7-14 (exogenous,
rho=0.75, sigma_e=0.75) and Aiyagari_Endogenous_Labor_VFI.m:6-15 (endogenous,
rho=0.6, sigma_e=0.2).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from aiyagari_tpu.config import AiyagariConfig, HouseholdPreferences, IncomeProcess
from aiyagari_tpu.utils.grids import aiyagari_asset_bounds, aiyagari_asset_grid
from aiyagari_tpu.utils.markov import (
    discretize_income,
    normalized_labor,
    stationary_distribution,
)

__all__ = ["AiyagariModel", "aiyagari_preset", "aiyagari_labor_preset"]


@dataclasses.dataclass(frozen=True)
class AiyagariModel:
    """Discretized Aiyagari economy ready for the solvers/simulator."""

    config: AiyagariConfig
    a_grid: jnp.ndarray        # [na] asset grid
    s: jnp.ndarray             # [N] normalized efficiency units
    P: jnp.ndarray             # [N, N] income transition matrix
    pi: jnp.ndarray            # [N] stationary distribution
    labor_grid: jnp.ndarray    # [nl] labor-choice grid (endogenous labor only)
    labor_raw: float           # pre-normalization aggregate labor (demand-curve factor)
    amin: float
    amax: float

    @classmethod
    def from_config(cls, config: AiyagariConfig, dtype=jnp.float64) -> "AiyagariModel":
        l_grid, P = discretize_income(config.income)
        pi = stationary_distribution(P)
        s, labor_raw = normalized_labor(l_grid, pi)
        # Reuse the discretization just built (one discretization per model).
        amin, amax = aiyagari_asset_bounds(config, s_min=float(s[0]))
        a_grid = aiyagari_asset_grid(config, s_min=float(s[0]))
        lo, hi = config.labor_grid_bounds
        labor_grid = np.linspace(lo, hi, config.labor_grid_n)
        return cls(
            config=config,
            a_grid=jnp.asarray(a_grid, dtype),
            s=jnp.asarray(s, dtype),
            P=jnp.asarray(P, dtype),
            pi=jnp.asarray(pi, dtype),
            labor_grid=jnp.asarray(labor_grid, dtype),
            labor_raw=float(labor_raw),
            amin=float(amin),
            amax=float(amax),
        )

    @property
    def preferences(self) -> HouseholdPreferences:
        return self.config.preferences

    @property
    def dtype(self):
        return self.a_grid.dtype


def aiyagari_preset(grid_size: int = 400, dtype=jnp.float64) -> AiyagariModel:
    """The canonical Aiyagari_VFI.m / Aiyagari_EGM.m parameterization."""
    cfg = AiyagariConfig()
    cfg = dataclasses.replace(cfg, grid=dataclasses.replace(cfg.grid, n_points=grid_size))
    return AiyagariModel.from_config(cfg, dtype)


def aiyagari_labor_preset(grid_size: int = 400, dtype=jnp.float64) -> AiyagariModel:
    """The endogenous-labor parameterization (rho=0.6, sigma_e=0.2,
    psi=1, eta=2; Aiyagari_Endogenous_Labor_VFI.m:6-15)."""
    cfg = AiyagariConfig(
        income=IncomeProcess(rho=0.6, sigma_e=0.2),
        endogenous_labor=True,
    )
    cfg = dataclasses.replace(cfg, grid=dataclasses.replace(cfg.grid, n_points=grid_size))
    return AiyagariModel.from_config(cfg, dtype)
