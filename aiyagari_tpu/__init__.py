"""aiyagari_tpu — a TPU-native heterogeneous-agent macroeconomics framework.

Re-designs the capability surface of kostastril/Aiyagari-Replication
(five model configurations x two solution methods x a GE/statistics toolkit;
see SURVEY.md) as an idiomatic JAX/XLA framework: jit+vmap'd Bellman and EGM
kernels over HBM-resident grids, lax.scan panel simulation with explicit PRNG
threading, sharded agent panels over a named device mesh, and host-side outer
equilibrium loops.

Primary entry point: solve(model_config, method=..., backend=...).
"""

from aiyagari_tpu.config import (
    AccelConfig,
    FaultPlan,
    PrecisionLadderConfig,
    RescueConfig,
    SentinelConfig,
    ALMConfig,
    AiyagariConfig,
    BackendConfig,
    EquilibriumConfig,
    GridSpecConfig,
    HouseholdPreferences,
    IncomeProcess,
    KrusellSmithConfig,
    MeshConfig,
    KSShockProcess,
    MITShock,
    SimConfig,
    SolverConfig,
    Technology,
    TelemetryConfig,
    TransitionConfig,
)
from aiyagari_tpu.diagnostics.errors import ConvergenceError, ConvergenceWarning
from aiyagari_tpu.dispatch import solve, solve_transition, sweep, sweep_transitions
from aiyagari_tpu.equilibrium.batched import (
    SweepResult,
    excess_demand_batch,
    solve_equilibrium_batched,
    solve_equilibrium_sweep,
)
from aiyagari_tpu.equilibrium.bisection import (
    EquilibriumResult,
    solve_equilibrium,
    solve_equilibrium_distribution,
    solve_household,
)
from aiyagari_tpu.models.aiyagari import (
    AiyagariModel,
    aiyagari_labor_preset,
    aiyagari_preset,
)
from aiyagari_tpu.transition.mit import TransitionResult, TransitionSweepResult

__version__ = "0.1.0"

__all__ = [
    "solve",
    "sweep",
    "solve_transition",
    "sweep_transitions",
    "MITShock",
    "TransitionConfig",
    "TransitionResult",
    "TransitionSweepResult",
    "ConvergenceError",
    "ConvergenceWarning",
    "solve_equilibrium",
    "solve_equilibrium_distribution",
    "solve_equilibrium_batched",
    "solve_equilibrium_sweep",
    "excess_demand_batch",
    "SweepResult",
    "solve_household",
    "AiyagariModel",
    "aiyagari_preset",
    "aiyagari_labor_preset",
    "EquilibriumResult",
    "AiyagariConfig",
    "KrusellSmithConfig",
    "KSShockProcess",
    "HouseholdPreferences",
    "Technology",
    "IncomeProcess",
    "GridSpecConfig",
    "AccelConfig",
    "PrecisionLadderConfig",
    "SolverConfig",
    "TelemetryConfig",
    "SentinelConfig",
    "FaultPlan",
    "RescueConfig",
    "SimConfig",
    "EquilibriumConfig",
    "ALMConfig",
    "BackendConfig",
    "MeshConfig",
]
