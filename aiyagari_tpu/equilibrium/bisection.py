"""General-equilibrium closure for the Aiyagari family: bisection on the
interest rate until capital supply (household side) equals capital demand
(firm side). Host-side outer loop; each iteration launches two device
programs (household fixed point, panel simulation).

Reference: Aiyagari_VFI.m:133-206. Deviations (both documented in SURVEY.md
§3.6 and deliberate):
  * the wage is recomputed from r every iteration for the EGM methods too —
    the reference's EGM scripts keep the r=0.04 wage inside the bisection
    (Aiyagari_EGM.m:180 updates r but never w, the 'stale wage' quirk);
  * the simulator redraws its initial state per iteration from a fresh key
    instead of silently reusing the previous pass's state (quirk 7).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from aiyagari_tpu.config import EquilibriumConfig, SimConfig, SolverConfig
from aiyagari_tpu.models.aiyagari import AiyagariModel
from aiyagari_tpu.sim.ergodic import PanelSeries, simulate_panel
from aiyagari_tpu.solvers.egm import solve_aiyagari_egm, solve_aiyagari_egm_labor
from aiyagari_tpu.solvers.vfi import solve_aiyagari_vfi, solve_aiyagari_vfi_labor
from aiyagari_tpu.utils.firm import capital_demand, wage_from_r

__all__ = ["EquilibriumResult", "solve_household", "solve_equilibrium"]


@dataclasses.dataclass
class EquilibriumResult:
    """GE solution and per-iteration history (the reference's k_demand /
    k_supply / r_history triple, kept aligned rather than independently
    sorted — quirk 5)."""

    r: float
    w: float
    capital: float
    solution: object                 # VFISolution or EGMSolution at r*
    series: PanelSeries
    r_history: list
    k_supply: list
    k_demand: list
    iterations: int
    converged: bool
    solve_seconds: float
    per_iteration: list              # IterationRecord dicts (diagnostics)


def _initial_consumption_guess(model: AiyagariModel, r: float, w: float):
    """EGM warm start: consume cash-on-hand at mean productivity
    (Aiyagari_EGM.m:64)."""
    mean_s = jnp.mean(model.s)
    base = (1.0 + r) * model.a_grid + w * mean_s
    return jnp.broadcast_to(base[None, :], (model.s.shape[0], model.a_grid.shape[0]))


def solve_household(model: AiyagariModel, r: float, *, solver: SolverConfig = SolverConfig(),
                    warm_start=None, block_size: int = 0):
    """Solve the household problem at interest rate r; returns a VFISolution
    or EGMSolution depending on solver.method. `warm_start` is the previous
    value function (VFI) or consumption policy (EGM)."""
    prefs = model.preferences
    tech = model.config.technology
    w = wage_from_r(r, tech.alpha, tech.delta)
    N, na = model.P.shape[0], model.a_grid.shape[0]

    if solver.method == "vfi":
        v0 = warm_start if warm_start is not None else jnp.zeros((N, na), model.dtype)
        if model.config.endogenous_labor:
            return solve_aiyagari_vfi_labor(
                v0, model.a_grid, model.labor_grid, model.s, model.P, r, w,
                sigma=prefs.sigma, beta=prefs.beta, psi=prefs.psi, eta=prefs.eta,
                tol=solver.tol, max_iter=solver.max_iter, howard_steps=solver.howard_steps,
                relative_tol=solver.relative_tol,
            )
        return solve_aiyagari_vfi(
            v0, model.a_grid, model.s, model.P, r, w,
            sigma=prefs.sigma, beta=prefs.beta, tol=solver.tol,
            max_iter=solver.max_iter, howard_steps=solver.howard_steps,
            block_size=block_size, relative_tol=solver.relative_tol,
            use_pallas=solver.use_pallas,
        )
    if solver.method == "egm":
        C0 = warm_start if warm_start is not None else _initial_consumption_guess(model, r, w)
        if model.config.endogenous_labor:
            return solve_aiyagari_egm_labor(
                C0, model.a_grid, model.s, model.P, r, w, model.amin,
                sigma=prefs.sigma, beta=prefs.beta, psi=prefs.psi, eta=prefs.eta,
                tol=solver.tol, max_iter=solver.max_iter, relative_tol=solver.relative_tol,
            )
        return solve_aiyagari_egm(
            C0, model.a_grid, model.s, model.P, r, w, model.amin,
            sigma=prefs.sigma, beta=prefs.beta, tol=solver.tol, max_iter=solver.max_iter,
            relative_tol=solver.relative_tol,
        )
    raise ValueError(f"unknown method {solver.method!r}; expected 'vfi' or 'egm'")


def _warm_state(solution, method: str):
    return solution.v if method == "vfi" else solution.policy_c


def solve_equilibrium(model: AiyagariModel, *, solver: SolverConfig = SolverConfig(),
                      sim: SimConfig = SimConfig(), eq: EquilibriumConfig = EquilibriumConfig(),
                      on_iteration: Optional[Callable] = None,
                      checkpoint_dir: Optional[str] = None) -> EquilibriumResult:
    """Bisection on r over [r_low, min(r_high, 1/beta - 1)] with <= eq.max_iter
    midpoints; stops when |K_supply - K_demand| < eq.tol (Aiyagari_VFI.m:133-206).

    The household solution is warm-started across bisection iterations (the
    reference carries v_old across its re-solves at :147-171). Supply is the
    time/cross-section average of simulated wealth; demand is the firm FOC
    curve labor*(alpha/(r+delta))^(1/(1-alpha)).

    With checkpoint_dir set, the bisection state (bracket, histories,
    warm-start policy) is persisted atomically every iteration and a restarted
    call resumes from it (SURVEY.md §5.3-5.4; no analogue in the reference).
    """
    prefs = model.preferences
    tech = model.config.technology
    t0 = time.perf_counter()
    key = jax.random.PRNGKey(sim.seed)

    r_low = eq.r_low
    r_high = eq.r_high if eq.r_high is not None else 1.0 / prefs.beta - 1.0

    mgr = None
    resumed = None
    if checkpoint_dir is not None:
        from aiyagari_tpu.io_utils.checkpoint import CheckpointManager, config_fingerprint

        mgr = CheckpointManager(
            checkpoint_dir, f"bisection_{solver.method}",
            fingerprint=config_fingerprint(model.config, solver, sim, eq),
        )
        resumed = mgr.restore()

    r_hist, ks_hist, kd_hist, records = [], [], [], []
    start_it = 0
    if resumed is not None:
        sc, arrays = resumed
        r_low, r_high = sc["r_low"], sc["r_high"]
        r_hist, ks_hist, kd_hist = sc["r_hist"], sc["ks_hist"], sc["kd_hist"]
        records = sc["records"]
        # Re-run at least the final iteration so the returned solution/series
        # are materialized even for a max_iter-exhausted checkpoint; truncate
        # the restored histories to the re-run point so nothing duplicates.
        start_it = min(sc["iteration"] + 1, eq.max_iter - 1)
        r_hist, ks_hist, kd_hist = r_hist[:start_it], ks_hist[:start_it], kd_hist[:start_it]
        records = records[:start_it]
        warm = jnp.asarray(arrays["warm"], model.dtype)
        # Fast-forward the PRNG stream to where the run stopped.
        for _ in range(start_it):
            key, _ = jax.random.split(key)
        sol = None
    else:
        # Warm-start pass at r_init, as the reference does before its loop (:63-129).
        sol = solve_household(model, eq.r_init, solver=solver, warm_start=None)
        warm = _warm_state(sol, solver.method)

    converged = False
    r_mid = eq.r_init
    series = None
    for it in range(start_it, eq.max_iter):
        it_t0 = time.perf_counter()
        r_mid = 0.5 * (r_low + r_high)
        w = float(wage_from_r(r_mid, tech.alpha, tech.delta))
        sol = solve_household(model, r_mid, solver=solver, warm_start=warm)
        warm = _warm_state(sol, solver.method)
        key, sub = jax.random.split(key)
        series = simulate_panel(
            sol.policy_k, sol.policy_c, sol.policy_l, model.a_grid, model.s, model.P,
            r_mid, w, sub, periods=sim.periods, n_agents=sim.n_agents, delta=tech.delta,
        )
        supply = float(jnp.mean(series.k[sim.discard:]))
        demand = float(capital_demand(r_mid, model.labor_raw, tech.alpha, tech.delta))
        r_hist.append(r_mid)
        ks_hist.append(supply)
        kd_hist.append(demand)
        rec = {
            "iteration": it,
            "r": r_mid,
            "k_supply": supply,
            "k_demand": demand,
            "gap": supply - demand,
            "solver_iterations": int(sol.iterations),
            "solver_distance": float(sol.distance),
            "seconds": time.perf_counter() - it_t0,
        }
        records.append(rec)
        if on_iteration is not None:
            on_iteration(rec)
        if abs(supply - demand) < eq.tol:
            converged = True
            break
        if supply > demand:
            r_high = r_mid
        else:
            r_low = r_mid
        if mgr is not None:
            mgr.save(
                scalars={
                    "iteration": it, "r_low": r_low, "r_high": r_high,
                    "r_hist": r_hist, "ks_hist": ks_hist, "kd_hist": kd_hist,
                    "records": records,
                },
                arrays={"warm": np.asarray(warm)},
            )

    if mgr is not None:
        mgr.delete()   # run finished; a later call should start fresh
    w = float(wage_from_r(r_mid, tech.alpha, tech.delta))
    return EquilibriumResult(
        r=r_mid,
        w=w,
        capital=ks_hist[-1],
        solution=sol,
        series=series,
        r_history=r_hist,
        k_supply=ks_hist,
        k_demand=kd_hist,
        iterations=len(r_hist),
        converged=converged,
        solve_seconds=time.perf_counter() - t0,
        per_iteration=records,
    )
