"""General-equilibrium closure for the Aiyagari family: bisection on the
interest rate until capital supply (household side) equals capital demand
(firm side). Host-side outer loop; each iteration launches two device
programs (household fixed point, panel simulation).

Reference: Aiyagari_VFI.m:133-206. Deviations (both documented in SURVEY.md
§3.6 and deliberate):
  * the wage is recomputed from r every iteration for the EGM methods too —
    the reference's EGM scripts keep the r=0.04 wage inside the bisection
    (Aiyagari_EGM.m:180 updates r but never w, the 'stale wage' quirk);
  * the simulator redraws its initial state per iteration from a fresh key
    instead of silently reusing the previous pass's state (quirk 7).
"""

from __future__ import annotations

import dataclasses
import time
from functools import lru_cache
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from aiyagari_tpu.config import EquilibriumConfig, SimConfig, SolverConfig
from aiyagari_tpu.models.aiyagari import AiyagariModel
from aiyagari_tpu.sim.ergodic import PanelSeries, simulate_panel
from aiyagari_tpu.solvers.egm import solve_aiyagari_egm_safe
from aiyagari_tpu.solvers.vfi import solve_aiyagari_vfi, solve_aiyagari_vfi_labor
from aiyagari_tpu.utils.firm import capital_demand, wage_from_r

__all__ = [
    "EquilibriumResult",
    "solve_household",
    "solve_equilibrium",
    "solve_equilibrium_distribution",
]


@dataclasses.dataclass
class EquilibriumResult:
    """GE solution and per-iteration history (the reference's k_demand /
    k_supply / r_history triple, kept aligned rather than independently
    sorted — quirk 5)."""

    r: float
    w: float
    capital: float
    solution: object                 # VFISolution or EGMSolution at r*
    series: PanelSeries
    r_history: list
    k_supply: list
    k_demand: list
    iterations: int
    converged: bool
    solve_seconds: float
    per_iteration: list              # IterationRecord dicts (diagnostics)
    mu: object = None                # [N, na] stationary distribution, when the
                                     # non-stochastic closure produced one
    # Outer-loop flight record (diagnostics/telemetry.py host_telemetry):
    # the per-iteration |K_supply - K_demand| gap trajectory — the residual
    # certificate of the GE fixed point itself. Always populated (host
    # assembly is free; the device recorders stay opt-in).
    telemetry: object = None
    # The FINAL distribution solve's device flight record, when the
    # non-stochastic closure ran with SolverConfig.telemetry set.
    dist_telemetry: object = None
    # Structured failure verdict ("" healthy; "nan"/"stall"/"explode" when
    # the host-side sentinel tripped on the gap trajectory, diagnostics/
    # sentinel.host_verdict — only armed when SolverConfig.sentinel is set).
    verdict: str = ""

    def health(self, model=None) -> dict:
        """The health certificate for this solve (diagnostics/health.py):
        outer/inner residual-trajectory shape, mass defect, monotonicity,
        Euler-error percentiles (pass the AiyagariModel to unlock them)."""
        from aiyagari_tpu.diagnostics.health import health_report

        return health_report(self, model=model)


def _initial_consumption_guess(model: AiyagariModel, r: float, w: float):
    """EGM warm start (Aiyagari_EGM.m:64); delegates to the shared helper."""
    from aiyagari_tpu.solvers.egm import initial_consumption_guess

    return initial_consumption_guess(model.a_grid, model.s, r, w)


def solve_household(model: AiyagariModel, r: float, *, solver: SolverConfig = SolverConfig(),
                    warm_start=None, block_size: int = 0, mesh=None):
    """Solve the household problem at interest rate r; returns a VFISolution
    or EGMSolution depending on solver.method. `warm_start` is the previous
    value function (VFI) or consumption policy (EGM).

    `mesh` (a Mesh with a "grid" axis, from BackendConfig.mesh_axes) routes
    BOTH EGM families through their DISTRIBUTED fixed points with
    ring-redistributed knots (solvers/egm_sharded.py: the exogenous solve
    rings the knot shards, the labor solve rings stacked (knot, value)
    pairs) — O(na/D) per-device memory. Escapes, non-power grids, and the
    VFI family fall back to the single-device routes below."""
    prefs = model.preferences
    tech = model.config.technology
    w = wage_from_r(r, tech.alpha, tech.delta)
    N, na = model.P.shape[0], model.a_grid.shape[0]

    if solver.method == "vfi":
        v0 = warm_start if warm_start is not None else jnp.zeros((N, na), model.dtype)
        if model.config.endogenous_labor:
            return solve_aiyagari_vfi_labor(
                v0, model.a_grid, model.labor_grid, model.s, model.P, r, w,
                sigma=prefs.sigma, beta=prefs.beta, psi=prefs.psi, eta=prefs.eta,
                tol=solver.tol, max_iter=solver.max_iter, howard_steps=solver.howard_steps,
                relative_tol=solver.relative_tol, progress_every=solver.progress_every,
                ladder=solver.ladder, telemetry=solver.telemetry,
                sentinel=solver.sentinel, faults=solver.faults,
            )
        return solve_aiyagari_vfi(
            v0, model.a_grid, model.s, model.P, r, w,
            sigma=prefs.sigma, beta=prefs.beta, tol=solver.tol,
            max_iter=solver.max_iter, howard_steps=solver.howard_steps,
            block_size=block_size, relative_tol=solver.relative_tol,
            use_pallas=solver.use_pallas, progress_every=solver.progress_every,
            ladder=solver.ladder, telemetry=solver.telemetry,
            sentinel=solver.sentinel, faults=solver.faults,
        )
    if solver.method == "egm":
        from aiyagari_tpu.ops.egm import (
            require_xla_egm_kernel,
            resolve_egm_kernel,
        )
        from aiyagari_tpu.parallel.ring import ring_slab_fits
        from aiyagari_tpu.solvers.egm import (
            LADDER_MIN_FINE,
            ladder_warm_start,
            ladder_warm_start_labor,
        )

        if model.config.endogenous_labor:
            # The fused kernel implements the exogenous-labor chain only;
            # fail loudly rather than silently running the XLA sweep
            # (ops/egm.require_xla_egm_kernel rationale).
            require_xla_egm_kernel(solver.egm_kernel,
                                   "the endogenous-labor EGM family")
        if (
            mesh is not None
            # The ring-sharded program has no fused-kernel route: a non-XLA
            # egm_kernel falls through to the single-device solvers below,
            # which honor it — the knob is never silently dropped.
            and resolve_egm_kernel(solver.egm_kernel) == "xla"
            and model.config.grid.power > 0
            and na % int(mesh.shape["grid"]) == 0
            # Slab-geometry soundness: grids too small for the ring slab
            # (the same predicate behind solve_aiyagari_egm_sharded's loud
            # guard) silently use the single-device routes — nothing to
            # distribute there anyway.
            and ring_slab_fits(na, int(mesh.shape["grid"]))
        ):
            from aiyagari_tpu.solvers.egm_sharded import (
                solve_aiyagari_egm_labor_sharded,
                solve_aiyagari_egm_sharded,
            )

            labor = model.config.endogenous_labor
            ladder_C0 = None
            C0 = warm_start
            if C0 is None and solver.grid_sequencing and na > LADDER_MIN_FINE:
                if labor:
                    ladder_C0 = ladder_warm_start_labor(
                        model.a_grid, model.s, model.P, r, w, model.amin,
                        sigma=prefs.sigma, beta=prefs.beta, psi=prefs.psi,
                        eta=prefs.eta, tol=solver.tol,
                        max_iter=solver.max_iter,
                        grid_power=float(model.config.grid.power),
                        relative_tol=solver.relative_tol,
                        accel=solver.accel, ladder=solver.ladder,
                    )
                else:
                    ladder_C0 = ladder_warm_start(
                        model.a_grid, model.s, model.P, r, w, model.amin,
                        sigma=prefs.sigma, beta=prefs.beta, tol=solver.tol,
                        max_iter=solver.max_iter,
                        grid_power=float(model.config.grid.power),
                        relative_tol=solver.relative_tol,
                        accel=solver.accel, ladder=solver.ladder,
                    )
                C0 = ladder_C0
            if C0 is None:
                C0 = _initial_consumption_guess(model, r, w)
            if labor:
                sol = solve_aiyagari_egm_labor_sharded(
                    mesh, C0, model.a_grid, model.s, model.P, r, w, model.amin,
                    sigma=prefs.sigma, beta=prefs.beta, psi=prefs.psi,
                    eta=prefs.eta, tol=solver.tol, max_iter=solver.max_iter,
                    relative_tol=solver.relative_tol,
                    grid_power=model.config.grid.power,
                    accel=solver.accel, ladder=solver.ladder,
                    telemetry=solver.telemetry,
                    sentinel=solver.sentinel, faults=solver.faults,
                )
            else:
                sol = solve_aiyagari_egm_sharded(
                    mesh, C0, model.a_grid, model.s, model.P, r, w, model.amin,
                    sigma=prefs.sigma, beta=prefs.beta, tol=solver.tol,
                    max_iter=solver.max_iter,
                    relative_tol=solver.relative_tol,
                    grid_power=model.config.grid.power,
                    accel=solver.accel, ladder=solver.ladder,
                    telemetry=solver.telemetry,
                    sentinel=solver.sentinel, faults=solver.faults,
                )
            if not bool(sol.escaped):
                return sol
            # Slab overflow: fall through to the single-device routes (the
            # same host-level retry contract as solve_aiyagari_egm_safe),
            # keeping an already-converged ladder warm start so the retry
            # does not pay the coarse stages a second time. A cold initial
            # guess is NOT promoted: with no ladder product the retry should
            # take its own multiscale route below.
            if ladder_C0 is not None:
                warm_start = ladder_C0
        if (
            solver.grid_sequencing
            and warm_start is None
            and na > LADDER_MIN_FINE
            and model.config.grid.power > 0
        ):
            # Cold start on a fine grid: coarse-to-fine stages cut the
            # full-size sweep count ~10x (solve_aiyagari_egm_multiscale
            # docstring). Warm starts (bisection midpoints after the first)
            # are already near the fixed point and skip the stages. Both
            # labor families take a ladder — the labor one prolongs C and
            # re-derives (l, k) per sweep (solve_aiyagari_egm_labor_multiscale).
            if model.config.endogenous_labor:
                from aiyagari_tpu.solvers.egm import solve_aiyagari_egm_labor_multiscale

                return solve_aiyagari_egm_labor_multiscale(
                    model.a_grid, model.s, model.P, r, w, model.amin,
                    sigma=prefs.sigma, beta=prefs.beta, psi=prefs.psi,
                    eta=prefs.eta, tol=solver.tol, max_iter=solver.max_iter,
                    grid_power=model.config.grid.power,
                    relative_tol=solver.relative_tol,
                    progress_every=solver.progress_every,
                    accel=solver.accel, ladder=solver.ladder,
                    telemetry=solver.telemetry,
                    sentinel=solver.sentinel, faults=solver.faults,
                )
            from aiyagari_tpu.solvers.egm import solve_aiyagari_egm_multiscale

            return solve_aiyagari_egm_multiscale(
                model.a_grid, model.s, model.P, r, w, model.amin,
                sigma=prefs.sigma, beta=prefs.beta, tol=solver.tol,
                max_iter=solver.max_iter, grid_power=model.config.grid.power,
                relative_tol=solver.relative_tol,
                progress_every=solver.progress_every,
                egm_kernel=solver.egm_kernel,
                accel=solver.accel, ladder=solver.ladder,
                telemetry=solver.telemetry,
                sentinel=solver.sentinel, faults=solver.faults,
            )
        C0 = warm_start if warm_start is not None else _initial_consumption_guess(model, r, w)
        if model.config.endogenous_labor:
            from aiyagari_tpu.solvers.egm import solve_aiyagari_egm_labor_safe

            return solve_aiyagari_egm_labor_safe(
                C0, model.a_grid, model.s, model.P, r, w, model.amin,
                sigma=prefs.sigma, beta=prefs.beta, psi=prefs.psi, eta=prefs.eta,
                tol=solver.tol, max_iter=solver.max_iter, relative_tol=solver.relative_tol,
                progress_every=solver.progress_every,
                grid_power=model.config.grid.power,
                accel=solver.accel, ladder=solver.ladder,
                telemetry=solver.telemetry,
                sentinel=solver.sentinel, faults=solver.faults,
            )
        return solve_aiyagari_egm_safe(
            C0, model.a_grid, model.s, model.P, r, w, model.amin,
            sigma=prefs.sigma, beta=prefs.beta, tol=solver.tol, max_iter=solver.max_iter,
            relative_tol=solver.relative_tol, progress_every=solver.progress_every,
            # Power-spaced model grids take the scatter-free windowed
            # inversion fast path (identical result to the generic route at
            # f64 resolution, pinned by TestPowerGridInversion; _safe retries
            # on the generic route if the windows escape).
            grid_power=model.config.grid.power,
            egm_kernel=solver.egm_kernel,
            accel=solver.accel, ladder=solver.ladder,
            telemetry=solver.telemetry,
            sentinel=solver.sentinel, faults=solver.faults,
        )
    raise ValueError(f"unknown method {solver.method!r}; expected 'vfi' or 'egm'")


def _warm_state(solution, method: str):
    return solution.v if method == "vfi" else solution.policy_c


class _SimulationAggregator:
    """Capital supply as the Monte-Carlo time/cross-section average of a
    simulated panel (the reference's closure, Aiyagari_VFI.m:94-129,174-188)."""

    checkpoint_tag = ""   # keeps existing checkpoint names stable

    def __init__(self, model: AiyagariModel, sim: SimConfig):
        self.model = model
        self.sim = sim
        self.key = jax.random.PRNGKey(sim.seed)
        self.series = None
        self.mu = None

    def restore(self, start_it: int, scalars: dict, arrays: dict) -> None:
        # Fast-forward the PRNG stream to where the run stopped.
        for _ in range(start_it):
            self.key, _ = jax.random.split(self.key)

    def supply(self, sol, r_mid: float, w: float):
        model, sim = self.model, self.sim
        self.key, sub = jax.random.split(self.key)
        self.series = simulate_panel(
            sol.policy_k, sol.policy_c, sol.policy_l, model.a_grid, model.s,
            model.P, r_mid, w, sub, periods=sim.periods, n_agents=sim.n_agents,
            delta=model.config.technology.delta,
        )
        return float(jnp.mean(self.series.k[sim.discard:])), {}

    def arrays(self) -> dict:
        return {}


@lru_cache(maxsize=None)
def _replicate_program(sharding):
    """Compiled identity with replicated out_shardings, cached per sharding
    (a fresh jit(lambda) per call would re-trace+compile the all-gather on
    EVERY bisection iteration — the _shardmap_panel_fn caching pattern)."""
    return jax.jit(lambda x: x, out_shardings=sharding)


class _DistributionAggregator:
    """Capital supply as E[a] under the Young-histogram stationary
    distribution (sim/distribution.py) — deterministic, no analogue in the
    reference. The distribution is warm-started across bisection steps."""

    checkpoint_tag = "_dist"

    def __init__(self, model: AiyagariModel, dist_tol: float,
                 dist_max_iter: int, accel=None, ladder=None,
                 pushforward: str = "auto", telemetry=None, sentinel=None,
                 faults=None):
        self.model = model
        self.dist_tol = dist_tol
        self.dist_max_iter = dist_max_iter
        self.accel = accel
        self.ladder = ladder
        self.pushforward = pushforward
        self.telemetry = telemetry
        self.sentinel = sentinel
        self.faults = faults
        self.series = None
        self.mu = None
        self.dist_telemetry = None   # the LAST solve's flight record

    def restore(self, start_it: int, scalars: dict, arrays: dict) -> None:
        # The distribution may have been saved per shard (mesh routes, where
        # the GSPMD stationary-distribution output is sharded over the
        # grid); restore_array reassembles either representation. [na] is
        # host-assembled — the tiny 1-D aggregator state, not the [N, na]
        # policy arrays whose no-materialization property matters.
        from aiyagari_tpu.io_utils.checkpoint import restore_array

        mu = restore_array(scalars, arrays, "mu")
        if mu is not None:
            self.mu = jnp.asarray(np.asarray(mu), self.model.dtype)

    def supply(self, sol, r_mid: float, w: float):
        from aiyagari_tpu.sim.distribution import (
            aggregate_capital,
            stationary_distribution,
        )

        # Multi-process mesh runs: the Young histogram is an inherently
        # GLOBAL [na]-sized computation (its lottery buckets the whole
        # policy), and its eager entry ops are refused on process-spanning
        # operands (ShardingTypeError on the searchsorted ravel — found by
        # the 2-process resume test). Replicate the policy first with one
        # compiled all-gather: [N, na] is tiny next to the solver state
        # the per-shard machinery exists for (22 MB even at 400k).
        # Single-process sharded arrays keep the GSPMD route untouched.
        policy_k = sol.policy_k
        if isinstance(policy_k, jax.Array) and not policy_k.is_fully_addressable:
            from aiyagari_tpu.parallel.mesh import named_sharding

            rep = named_sharding(policy_k.sharding.mesh)
            policy_k = _replicate_program(rep)(policy_k)

        dist_sol = stationary_distribution(
            policy_k, self.model.a_grid, self.model.P,
            tol=self.dist_tol, max_iter=self.dist_max_iter, mu_init=self.mu,
            accel=self.accel, ladder=self.ladder,
            pushforward=self.pushforward, telemetry=self.telemetry,
            sentinel=self.sentinel, faults=self.faults,
        )
        self.mu = dist_sol.mu
        self.dist_telemetry = dist_sol.telemetry
        supply = float(aggregate_capital(self.mu, self.model.a_grid))
        return supply, {"distribution_iterations": int(dist_sol.iterations)}

    def arrays(self) -> dict:
        # The raw device array: _pack_arrays np.asarray's it when replicated
        # and packs it per shard when distributed — np.asarray HERE would
        # raise on a process-spanning mu (multi-process mesh runs).
        return {"mu": self.mu}


def _bisect(model: AiyagariModel, aggregator, *, solver: SolverConfig,
            eq: EquilibriumConfig, on_iteration: Optional[Callable],
            checkpoint_dir: Optional[str], checkpoint_configs,
            mesh=None, warm_start=None) -> EquilibriumResult:
    """Shared GE bisection driver (Aiyagari_VFI.m:133-206): bracket r, re-solve
    the household problem warm-started at each midpoint, ask the aggregator for
    capital supply, compare against the firm FOC demand curve. Checkpoint/
    resume persists the bracket, histories, warm start, and any aggregator
    state every iteration."""
    prefs = model.preferences
    tech = model.config.technology
    t0 = time.perf_counter()

    r_low = eq.r_low
    r_high = eq.r_high if eq.r_high is not None else 1.0 / prefs.beta - 1.0

    mgr = None
    resumed = None
    if checkpoint_dir is not None:
        from aiyagari_tpu.io_utils.checkpoint import CheckpointManager, config_fingerprint

        mgr = CheckpointManager(
            checkpoint_dir, f"bisection_{solver.method}{aggregator.checkpoint_tag}",
            fingerprint=config_fingerprint(model.config, solver, *checkpoint_configs, eq),
        )
        resumed = mgr.restore()

    r_hist, ks_hist, kd_hist, records = [], [], [], []
    start_it = 0
    if resumed is not None:
        sc, arrays = resumed
        r_low, r_high = sc["r_low"], sc["r_high"]
        r_hist, ks_hist, kd_hist = sc["r_hist"], sc["ks_hist"], sc["kd_hist"]
        records = sc["records"]
        # Re-run at least the final iteration so the returned solution/series
        # are materialized even for a max_iter-exhausted checkpoint; truncate
        # the restored histories to the re-run point so nothing duplicates.
        start_it = min(sc["iteration"] + 1, eq.max_iter - 1)
        r_hist, ks_hist, kd_hist = r_hist[:start_it], ks_hist[:start_it], kd_hist[:start_it]
        records = records[:start_it]
        # A warm start saved from the mesh route is stored per shard; with
        # the mesh available it is restored shard-by-shard straight onto
        # the devices (io_utils/checkpoint.restore_array), never assembled
        # on host.
        from aiyagari_tpu.io_utils.checkpoint import restore_array

        warm_sharding = None
        if mesh is not None:
            from aiyagari_tpu.parallel.mesh import named_sharding

            warm_sharding = named_sharding(mesh, None, "grid")
        warm = restore_array(sc, arrays, "warm", sharding=warm_sharding,
                             dtype=np.dtype(str(jnp.dtype(model.dtype))))
        if isinstance(warm, np.ndarray):   # meshless restore stays host-side
            warm = jnp.asarray(warm, model.dtype)
        aggregator.restore(start_it, sc, arrays)
        sol = None
    else:
        # Warm-start pass at r_init, as the reference does before its loop
        # (:63-129). `warm_start` (a previous solve's value function / EGM
        # consumption policy — the serve layer's solution cache passes the
        # cached C here) seeds even this first pass; None keeps the
        # reference cold start bit-identical.
        sol = solve_household(model, eq.r_init, solver=solver,
                              warm_start=warm_start, mesh=mesh)
        warm = _warm_state(sol, solver.method)

    converged = False
    verdict = ""
    r_mid = eq.r_init
    for it in range(start_it, eq.max_iter):
        it_t0 = time.perf_counter()
        r_mid = 0.5 * (r_low + r_high)
        w = float(wage_from_r(r_mid, tech.alpha, tech.delta))
        sol = solve_household(model, r_mid, solver=solver, warm_start=warm,
                              mesh=mesh)
        warm = _warm_state(sol, solver.method)
        supply, extras = aggregator.supply(sol, r_mid, w)
        demand = float(capital_demand(r_mid, model.labor_raw, tech.alpha, tech.delta))
        r_hist.append(r_mid)
        ks_hist.append(supply)
        kd_hist.append(demand)
        rec = {
            "iteration": it,
            "r": r_mid,
            "k_supply": supply,
            "k_demand": demand,
            "gap": supply - demand,
            "solver_iterations": int(sol.iterations),
            "solver_distance": float(sol.distance),
            **extras,
            "seconds": time.perf_counter() - it_t0,
        }
        records.append(rec)
        if on_iteration is not None:
            on_iteration(rec)
        if abs(supply - demand) < eq.tol:
            converged = True
            break
        # Host-side failure sentinel on the outer gap trajectory (only
        # armed when SolverConfig.sentinel is set): a NaN supply, an
        # exploding gap, or a stalled bracket exits with a structured
        # verdict instead of burning the remaining bisection rounds on a
        # poisoned household solution.
        if solver.sentinel is not None:
            from aiyagari_tpu.diagnostics.sentinel import host_verdict

            verdict = host_verdict(
                [abs(s - d) for s, d in zip(ks_hist, kd_hist)],
                solver.sentinel)
            if verdict:
                break
        if supply > demand:
            r_high = r_mid
        else:
            r_low = r_mid
        if mgr is not None:
            mgr.save(
                scalars={
                    "iteration": it, "r_low": r_low, "r_high": r_high,
                    "r_hist": r_hist, "ks_hist": ks_hist, "kd_hist": kd_hist,
                    "records": records,
                },
                # `warm` passes through as the device array: if the mesh
                # route left it sharded, save_checkpoint packs it per shard
                # without a host gather (io_utils/checkpoint._pack_arrays).
                arrays={"warm": warm, **aggregator.arrays()},
            )

    if mgr is not None:
        mgr.delete()   # run finished; a later call should start fresh
    w = float(wage_from_r(r_mid, tech.alpha, tech.delta))
    # Outer flight record: the per-iteration market-clearing gap trajectory
    # in the same SolveTelemetry shape the device recorders return, so one
    # report path (diagnostics/health.py) serves both loops.
    from aiyagari_tpu.diagnostics.telemetry import host_telemetry

    return EquilibriumResult(
        r=r_mid,
        w=w,
        capital=ks_hist[-1],
        solution=sol,
        series=aggregator.series,
        r_history=r_hist,
        k_supply=ks_hist,
        k_demand=kd_hist,
        iterations=len(r_hist),
        converged=converged,
        solve_seconds=time.perf_counter() - t0,
        per_iteration=records,
        mu=aggregator.mu,
        telemetry=host_telemetry(
            [abs(s - d) for s, d in zip(ks_hist, kd_hist)]),
        dist_telemetry=getattr(aggregator, "dist_telemetry", None),
        verdict=verdict,
    )


def solve_equilibrium(model: AiyagariModel, *, solver: SolverConfig = SolverConfig(),
                      sim: SimConfig = SimConfig(), eq: EquilibriumConfig = EquilibriumConfig(),
                      on_iteration: Optional[Callable] = None,
                      checkpoint_dir: Optional[str] = None,
                      mesh=None, warm_start=None) -> EquilibriumResult:
    """Bisection on r over [r_low, min(r_high, 1/beta - 1)] with <= eq.max_iter
    midpoints; stops when |K_supply - K_demand| < eq.tol (Aiyagari_VFI.m:133-206).

    The household solution is warm-started across bisection iterations (the
    reference carries v_old across its re-solves at :147-171). Supply is the
    time/cross-section average of simulated wealth; demand is the firm FOC
    curve labor*(alpha/(r+delta))^(1/(1-alpha)).

    With checkpoint_dir set, the bisection state (bracket, histories,
    warm-start policy) is persisted atomically every iteration and a restarted
    call resumes from it (SURVEY.md §5.3-5.4; no analogue in the reference).
    """
    return _bisect(
        model, _SimulationAggregator(model, sim), solver=solver, eq=eq,
        on_iteration=on_iteration, checkpoint_dir=checkpoint_dir,
        checkpoint_configs=(sim,), mesh=mesh, warm_start=warm_start,
    )


def solve_equilibrium_distribution(
    model: AiyagariModel, *, solver: SolverConfig = SolverConfig(),
    eq: EquilibriumConfig = EquilibriumConfig(),
    dist_tol: float = 1e-10, dist_max_iter: int = 10_000,
    on_iteration: Optional[Callable] = None,
    checkpoint_dir: Optional[str] = None,
    mesh=None,
    warm_start=None,
) -> EquilibriumResult:
    """Non-stochastic GE closure: same r-bisection as solve_equilibrium, but
    capital supply is E[a] under the stationary distribution computed by the
    Young (2010) histogram method (sim/distribution.py) instead of a
    Monte-Carlo time average. Deterministic — the bisection sees an exact
    supply curve, not one polluted by simulation noise — and typically far
    faster, since the distribution fixed point is a few hundred fused device
    sweeps rather than a 10,000-step sequential scan.

    No analogue in the reference (its aggregation is the quirk-8 single-
    household time average, Aiyagari_VFI.m:129). Returns an EquilibriumResult
    with `mu` set and `series=None`; distributional statistics come from the
    weighted stats (utils/stats.py: weighted_gini etc.) over (a_grid, mu).
    """
    return _bisect(
        model,
        _DistributionAggregator(model, dist_tol, dist_max_iter,
                                accel=solver.accel, ladder=solver.ladder,
                                pushforward=solver.pushforward,
                                telemetry=solver.telemetry,
                                sentinel=solver.sentinel,
                                faults=solver.faults),
        solver=solver, eq=eq, on_iteration=on_iteration,
        checkpoint_dir=checkpoint_dir,
        checkpoint_configs=(dist_tol, dist_max_iter), mesh=mesh,
        warm_start=warm_start,
    )
