"""Batched general-equilibrium machinery: evaluate the excess-capital-demand
curve at MANY candidate interest rates (or many parameter scenarios) per
device round, instead of the serial one-solve-per-candidate bisection of
equilibrium/bisection.py.

Two entry points, one kernel:

  * solve_equilibrium_batched — a parallel-bracket root finder for ONE
    economy: each outer round evaluates B candidate rates through a single
    vmapped excess-demand program (household fixed point + Young stationary
    distribution + aggregate_capital fused in one jit), shrinking the
    bracket by a factor of (B+1) per round where bisection manages 2. The
    host loop therefore runs ~log2(B+1)-fold fewer sequential device rounds
    for the same root resolution, and each round warm-starts every candidate
    from the NEAREST converged candidate of the previous round (the bracket
    nests, so the two survivors of round k are exactly the closest warm
    states for round k+1's interior points).

  * solve_equilibrium_sweep — many INDEPENDENT scenarios (grids over beta,
    sigma, borrowing limit, shock process, ...) advanced through their own
    bisections in lockstep: the batch axis is the scenario, every round is
    one vmapped kernel call over [S] economies, and the stacked model arrays
    can be sharded over a device mesh "scenarios" axis (parallel/mesh.py),
    making throughput scale with the device count. dispatch.sweep() is the
    user-facing wrapper that builds the scenario batch from parameter grids.

Both build on the vmap-compatibility refactor of the household solvers:
sigma/beta (and psi/eta, amin, r, w) are traced operands of
solvers/vfi.solve_aiyagari_vfi and solvers/egm.solve_aiyagari_egm, so a
whole scenario batch compiles ONCE and maps onto the same program.

The reference has no analogue (its closure is the strictly serial
Aiyagari_VFI.m:133-206 loop); this is the batched-fixed-point pattern the
north star names, applied to the price axis.
"""

from __future__ import annotations

import dataclasses
import math
import time
from functools import lru_cache
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from aiyagari_tpu.config import EquilibriumConfig, SimConfig, SolverConfig
from aiyagari_tpu.diagnostics.progress import heartbeat_stride, sweep_heartbeat
from aiyagari_tpu.equilibrium.bisection import EquilibriumResult
from aiyagari_tpu.models.aiyagari import AiyagariModel
from aiyagari_tpu.utils.firm import capital_demand, wage_from_r

__all__ = [
    "excess_demand_batch",
    "solve_equilibrium_batched",
    "ScenarioBatch",
    "SweepResult",
    "stack_scenarios",
    "solve_equilibrium_sweep",
    "batched_round_bound",
]


def batched_round_bound(serial_iters: int, batch: int) -> int:
    """Upper bound on the rounds the parallel-bracket solver needs to reach
    the bracket width serial bisection reaches in `serial_iters` halvings:
    each round splits the bracket into (batch+1) subintervals, so
    ceil(serial_iters * ln 2 / ln(batch+1)), plus one slack round for the
    tolerance check landing between grid refinements. Pinned by
    tests/test_batched_ge.py's round-count assertion."""
    if batch < 2:
        return serial_iters
    return math.ceil(serial_iters * math.log(2.0) / math.log(batch + 1.0)) + 1


def _knobs(solver: SolverConfig, alpha: float, delta: float, dist_tol: float,
           dist_max_iter: int, sim: SimConfig):
    """The static-knob tuple _ge_round_program destructures — ONE builder so
    the positional contract cannot drift between callers."""
    return (
        solver.tol, solver.max_iter, solver.howard_steps, solver.relative_tol,
        alpha, delta, dist_tol, dist_max_iter,
        sim.periods, sim.n_agents, sim.discard,
        solver.accel, solver.ladder, solver.pushforward, solver.telemetry,
        solver.sentinel, solver.faults, solver.egm_kernel,
    )


def _model_knobs(model: AiyagariModel, solver: SolverConfig,
                 dist_tol: float, dist_max_iter: int, sim: SimConfig):
    tech = model.config.technology
    return _knobs(solver, tech.alpha, tech.delta, dist_tol, dist_max_iter, sim)


@lru_cache(maxsize=None)
def _ge_round_program(method: str, labor: bool, aggregation: str,
                      knobs: tuple, scenario_axes: bool, cold: bool):
    """Build + jit one GE round: (warm selection ->) vmapped household solve
    -> aggregation -> excess demand, for B candidates (or S scenarios) in a
    single device program.

    Cache key = everything that changes the traced program: the solver
    family, the closure, the static solver/sim knobs, whether the model
    arrays carry a leading scenario axis, and whether this is the cold first
    round (no previous candidates to warm-start from). lru_cache'd so every
    outer round of every solve reuses the same compiled executable.
    """
    (tol, max_iter, howard_steps, relative_tol, alpha, delta,
     dist_tol, dist_max_iter, periods, n_agents, discard, accel,
     ladder, pushforward, telemetry, sentinel, faults, egm_kernel) = knobs
    # Resolve the push-forward route in the VMAPPED context (the
    # batched=True split, ops/pushforward.resolve_backend): "auto" pins
    # the scatter form on CPU hosts, where the transpose route's gathers
    # batch catastrophically under vmap (measured — ISSUE 15). Resolved
    # once per cached program build, so the traced program carries the
    # concrete route.
    from aiyagari_tpu.ops.pushforward import resolve_backend

    pushforward = resolve_backend(pushforward, batched=True)
    if method == "egm":
        from aiyagari_tpu.ops.egm import (
            require_xla_egm_kernel,
            resolve_egm_kernel,
        )

        if labor:
            # Loud, not silent: the fused kernel implements the
            # exogenous-labor chain only, so a Pallas route on the labor
            # family must fail here rather than quietly run the XLA sweep
            # (docs/USAGE.md).
            require_xla_egm_kernel(egm_kernel,
                                   "the endogenous-labor EGM family")
        elif resolve_egm_kernel(egm_kernel) == "pallas_inverse":
            # The batched closure pins grid_power=0.0 (its in-jit solves
            # cannot host-retry a window escape — the call-site comment
            # below), and the pallas_inverse route only exists on power
            # grids; running the plain chain under that name would be a
            # silent no-op. The fused route has no such conflict.
            raise ValueError(
                "egm_kernel='pallas_inverse' is not supported by the "
                "batched GE closure: its vmapped solves run grid_power=0 "
                "(no host escape retry mid-program), which the windowed "
                "inversion route requires; use 'auto', 'xla', or "
                "'pallas_fused'")

    def one(warm, mu_warm, r, key, a_grid, s, P, labor_grid, sigma, beta,
            psi, eta, amin, labor_raw):
        from aiyagari_tpu.sim.distribution import (
            aggregate_capital,
            stationary_distribution,
        )

        w = wage_from_r(r, alpha, delta)
        if method == "vfi":
            from aiyagari_tpu.solvers.vfi import (
                solve_aiyagari_vfi,
                solve_aiyagari_vfi_labor,
            )

            if labor:
                sol = solve_aiyagari_vfi_labor(
                    warm, a_grid, labor_grid, s, P, r, w, sigma=sigma,
                    beta=beta, psi=psi, eta=eta, tol=tol, max_iter=max_iter,
                    howard_steps=howard_steps, relative_tol=relative_tol,
                    ladder=ladder, telemetry=telemetry, sentinel=sentinel,
                    faults=faults)
            else:
                sol = solve_aiyagari_vfi(
                    warm, a_grid, s, P, r, w, sigma=sigma, beta=beta,
                    tol=tol, max_iter=max_iter, howard_steps=howard_steps,
                    relative_tol=relative_tol, ladder=ladder,
                    telemetry=telemetry, sentinel=sentinel, faults=faults)
            warm_out = sol.v
        else:
            from aiyagari_tpu.solvers.egm import (
                solve_aiyagari_egm,
                solve_aiyagari_egm_labor,
            )

            # grid_power=0.0: the generic exact inversion route. The windowed
            # fast path's escape contract needs a HOST retry
            # (solve_aiyagari_egm_safe), which a fused batched kernel cannot
            # perform mid-program.
            if labor:
                sol = solve_aiyagari_egm_labor(
                    warm, a_grid, s, P, r, w, amin, sigma=sigma, beta=beta,
                    psi=psi, eta=eta, tol=tol, max_iter=max_iter,
                    relative_tol=relative_tol, grid_power=0.0, accel=accel,
                    ladder=ladder, telemetry=telemetry, sentinel=sentinel,
                    faults=faults)
            else:
                sol = solve_aiyagari_egm(
                    warm, a_grid, s, P, r, w, amin, sigma=sigma, beta=beta,
                    tol=tol, max_iter=max_iter, relative_tol=relative_tol,
                    grid_power=0.0, egm_kernel=egm_kernel, accel=accel,
                    ladder=ladder, telemetry=telemetry, sentinel=sentinel,
                    faults=faults)
            warm_out = sol.policy_c

        out = {"warm": warm_out, "sol": sol,
               "solver_iterations": sol.iterations,
               "solver_distance": sol.distance}
        if aggregation == "distribution":
            # Warm-start the stationary distribution from the previous
            # round's converged mu — the serial _DistributionAggregator
            # has always done this (mu_init=self.mu); without it every
            # lockstep round re-iterated the distribution from uniform,
            # which measured ~2-3x per-lane-round against the serial
            # bisection at small grids (ISSUE 15). Cold first rounds have
            # no previous mu and keep the uniform start.
            dist_sol = stationary_distribution(
                sol.policy_k, a_grid, P, tol=dist_tol, max_iter=dist_max_iter,
                mu_init=(None if cold else mu_warm),
                accel=accel, ladder=ladder, pushforward=pushforward,
                telemetry=telemetry, sentinel=sentinel, faults=faults)
            supply = aggregate_capital(dist_sol.mu, a_grid)
            out["mu"] = dist_sol.mu
            out["dist_telemetry"] = dist_sol.telemetry
        else:
            from aiyagari_tpu.sim.ergodic import simulate_panel

            series = simulate_panel(
                sol.policy_k, sol.policy_c, sol.policy_l, a_grid, s, P, r, w,
                key, periods=periods, n_agents=n_agents, delta=delta)
            supply = jnp.mean(series.k[discard:])
            out["series"] = series
        out["supply"] = supply
        out["demand"] = capital_demand(r, labor_raw, alpha, delta)
        out["gap"] = out["supply"] - out["demand"]
        return out

    mx = 0 if scenario_axes else None       # model arrays / scalars axis
    in_axes = (0, 0, 0, 0, mx, mx, mx, mx, mx, mx, mx, mx, mx, mx)
    batched = jax.vmap(one, in_axes=in_axes)

    def round_fn(r_new, r_prev, warm_prev, mu_prev, keys, a_grid, s, P,
                 labor_grid, sigma, beta, psi, eta, amin, labor_raw):
        B = r_new.shape[0]
        mu = mu_prev
        if cold:
            # First round: no previous candidates. VFI starts at v=0 (the
            # reference's init); EGM at the consume-cash-on-hand guess
            # evaluated at each candidate's own prices (Aiyagari_EGM.m:64).
            # The mu operand is unread (the cold program's distribution
            # starts uniform) but the vmapped call still needs a
            # B-leading placeholder.
            mu = jnp.zeros((B, s.shape[-1], a_grid.shape[-1]), a_grid.dtype)
            if method == "vfi":
                shape = ((B,) + warm_prev.shape[-2:])
                warm = jnp.zeros(shape, a_grid.dtype)
            else:
                from aiyagari_tpu.solvers.egm import initial_consumption_guess

                w_new = wage_from_r(r_new, alpha, delta)
                guess_axes = (None if not scenario_axes else 0, None
                              if not scenario_axes else 0, 0, 0)
                warm = jax.vmap(initial_consumption_guess,
                                in_axes=guess_axes)(a_grid, s, r_new, w_new)
        elif scenario_axes:
            # Sweep mode: one candidate per scenario per round — the nearest
            # previous candidate is the scenario's own last iterate.
            warm = warm_prev
        else:
            # Parallel bracket: warm-start each new candidate from the
            # nearest previous candidate (the round-k survivors bracket
            # round k+1's interior points, so this is the closest converged
            # state available — the serial loop's warm-start carried over).
            # The distribution iterate rides the same nearest-candidate
            # selection.
            j = jnp.argmin(jnp.abs(r_new[:, None] - r_prev[None, :]), axis=1)
            warm = jnp.take(warm_prev, j, axis=0)
            if mu.shape[0] == r_prev.shape[0]:
                mu = jnp.take(mu_prev, j, axis=0)
        if mu.shape[0] != B:
            # The simulation closure carries no mu (out["mu"] never
            # updates the caller's size-1 placeholder): broadcast it to
            # the batch width so the vmapped call is well-formed — the
            # operand is unread there and XLA drops it.
            mu = jnp.broadcast_to(mu, (B,) + mu.shape[1:])
        out = batched(warm, mu, r_new, keys, a_grid, s, P, labor_grid,
                      sigma, beta, psi, eta, amin, labor_raw)
        # One stacked [3, B] host record per round: the driver loop fetches
        # gap/supply/demand as a single device_get instead of three scalar
        # streams (ISSUE 18 satellite — the per-round host sync is the
        # batched loop's only remaining host cost).
        out["record"] = jnp.stack((out["gap"], out["supply"], out["demand"]))
        return out

    return jax.jit(round_fn)


def _model_operands(model: AiyagariModel):
    prefs = model.preferences
    dt = model.dtype
    sc = lambda x: jnp.asarray(x, dt)
    return (model.a_grid, model.s, model.P, model.labor_grid,
            sc(prefs.sigma), sc(prefs.beta), sc(prefs.psi), sc(prefs.eta),
            sc(model.amin), sc(model.labor_raw))


def _round_keys(seed: int, rnd: int, n: int):
    return jax.random.split(jax.random.fold_in(jax.random.PRNGKey(seed), rnd), n)


def excess_demand_batch(model: AiyagariModel, r_batch, *,
                        solver: SolverConfig = SolverConfig(),
                        aggregation: str = "distribution",
                        warm=None, r_warm=None, mu_warm=None,
                        sim: SimConfig = SimConfig(),
                        dist_tol: float = 1e-10, dist_max_iter: int = 10_000,
                        keys=None):
    """Evaluate gap(r) = K_supply(r) - K_demand(r) at every rate in
    `r_batch` as ONE jitted device program: vmapped household solve
    (solvers/vfi.py or solvers/egm.py, per solver.method), stationary
    distribution (sim/distribution.py) or panel simulation (sim/ergodic.py,
    per `aggregation`), and the firm FOC demand curve, fused.

    warm/r_warm (optional, [Bp, N, na] / [Bp]) warm-start each candidate
    from its nearest previous candidate; None cold-starts every candidate.
    Returns (gap [B], aux) with aux carrying supply/demand/warm/sol (all
    batched, still on device).
    """
    if aggregation not in ("distribution", "simulation"):
        raise ValueError(f"unknown aggregation {aggregation!r}")
    B = np.shape(r_batch)[0]
    knobs = _model_knobs(model, solver, dist_tol, dist_max_iter, sim)
    cold = warm is None
    if not cold and r_warm is None:
        raise ValueError("warm states need their candidate rates: pass r_warm")
    fn = _ge_round_program(solver.method, model.config.endogenous_labor,
                           aggregation, knobs, False, cold)
    ops = _model_operands(model)
    r_new = jnp.asarray(r_batch, model.dtype)
    if keys is None:
        keys = _round_keys(sim.seed, 0, B)
    N, na = model.P.shape[0], model.a_grid.shape[0]
    if cold:
        # Shape-only placeholders: the cold program reads nothing but the
        # warm state's trailing (N, na) shape (VFI) — its distribution
        # starts uniform, so the mu operand is never read.
        warm = jnp.zeros((1, N, na), model.dtype)
        mu_warm = jnp.zeros((1, N, na), model.dtype)
        r_warm = r_new
    elif mu_warm is None and aggregation == "distribution":
        # The warm program READS mu_warm as the distribution's starting
        # iterate (a zero mu would satisfy the residual immediately and
        # report zero supply) — loud, like the r_warm check above.
        raise ValueError(
            "warm-started distribution rounds need the previous round's "
            "distributions: pass mu_warm (aux['mu'])")
    elif mu_warm is None:
        mu_warm = jnp.zeros((np.shape(r_batch)[0], 1, 1), model.dtype)
    out = fn(r_new, jnp.asarray(r_warm, model.dtype), warm, mu_warm, keys,
             *ops)
    return out["gap"], out


def solve_equilibrium_batched(
    model: AiyagariModel, *, solver: SolverConfig = SolverConfig(),
    eq: EquilibriumConfig = EquilibriumConfig(batch=8),
    sim: SimConfig = SimConfig(),
    aggregation: str = "distribution",
    dist_tol: float = 1e-10, dist_max_iter: int = 10_000,
    on_iteration: Optional[Callable] = None,
) -> EquilibriumResult:
    """Parallel-bracket GE root finder: same fixed point as the serial
    bisection (equilibrium/bisection.py — the excess-demand curve is
    IDENTICAL; only the query schedule changes), in ~log2(batch+1)-fold
    fewer sequential device rounds.

    Each round places eq.batch candidates at the interior points
    lo + (hi-lo) * i/(B+1), i=1..B, evaluates them through one vmapped
    excess-demand program, and shrinks the bracket to the sign change
    (gap = supply - demand is increasing in r: supply rises toward the
    1/beta - 1 asymptote, the firm FOC demand falls). Convergence criterion
    and bracket semantics match the serial loop: stop when some candidate's
    |gap| < eq.tol; eq.max_iter caps ROUNDS.

    aggregation="distribution" (default here — deterministic supply makes
    the parallel bracket exact) or "simulation" (per-candidate panels with
    per-round PRNG keys split from sim.seed; the bracket then chases the
    same Monte-Carlo noise the serial closure does).

    Returns an EquilibriumResult whose histories carry EVERY evaluated
    candidate (len == rounds * batch, aligned across r/supply/demand) and
    whose `iterations` counts rounds — the device-round metric the batched
    solver exists to shrink.
    """
    if eq.batch < 2:
        raise ValueError(
            f"solve_equilibrium_batched needs eq.batch >= 2, got {eq.batch}; "
            "use equilibrium/bisection.py for the serial loop")
    if aggregation not in ("distribution", "simulation"):
        raise ValueError(f"unknown aggregation {aggregation!r}")
    t0 = time.perf_counter()
    B = int(eq.batch)
    prefs = model.preferences
    tech = model.config.technology
    lo = float(eq.r_low)
    hi = float(eq.r_high if eq.r_high is not None else 1.0 / prefs.beta - 1.0)
    offsets = np.arange(1, B + 1) / (B + 1.0)

    knobs = _model_knobs(model, solver, dist_tol, dist_max_iter, sim)
    labor = model.config.endogenous_labor
    ops = _model_operands(model)
    N, na = model.P.shape[0], model.a_grid.shape[0]

    r_prev = None
    warm_prev = jnp.zeros((1, N, na), model.dtype)
    mu_prev = jnp.zeros((1, N, na), model.dtype)
    out = None
    r_hist, ks_hist, kd_hist, records = [], [], [], []
    converged = False
    verdict = ""
    best = 0
    r_cand = np.array([0.5 * (lo + hi)])
    r_list = r_cand.tolist()
    rounds = 0
    for rnd in range(eq.max_iter):
        it_t0 = time.perf_counter()
        r_cand = lo + (hi - lo) * offsets
        r_dev = jnp.asarray(r_cand, model.dtype)
        keys = _round_keys(sim.seed, rnd, B)
        fn = _ge_round_program(solver.method, labor, aggregation, knobs,
                               False, rnd == 0)
        out = fn(r_dev, r_prev if r_prev is not None else r_dev,
                 warm_prev, mu_prev, keys, *ops)
        # ONE host sync per round: the stacked [3, B] record + the solver
        # iteration counts come back in a single device_get, and the bulk
        # .tolist() conversions replace the old per-element float() loops
        # (ISSUE 18 satellite).
        record, sol_iters = jax.device_get(
            (out["record"], out["solver_iterations"]))
        record = np.asarray(record, np.float64)
        gaps = record[0]
        gaps_l, ks_l, kd_l = (row.tolist() for row in record)
        r_list = np.asarray(r_cand, np.float64).tolist()
        rounds = rnd + 1
        r_hist.extend(r_list)
        ks_hist.extend(ks_l)
        kd_hist.extend(kd_l)
        finite = np.where(np.isfinite(gaps), np.abs(gaps), np.inf)
        best = int(np.argmin(finite))
        rec = {
            "round": rnd,
            "r_candidates": r_list,
            "gaps": gaps_l,
            "bracket": (lo, hi),
            "best_r": r_list[best],
            "best_gap": gaps_l[best],
            "solver_iterations_max": int(np.max(sol_iters)),
            "seconds": time.perf_counter() - it_t0,
        }
        records.append(rec)
        if on_iteration is not None:
            on_iteration(rec)
        if np.isfinite(gaps_l[best]) and abs(gaps_l[best]) < eq.tol:
            converged = True
            break
        # Host-side failure sentinel on the per-round best-gap trajectory
        # (armed by SolverConfig.sentinel, like the serial bisection): an
        # all-NaN round, an exploding gap, or a stalled bracket exits with
        # a structured verdict instead of burning the remaining rounds.
        if solver.sentinel is not None:
            from aiyagari_tpu.diagnostics.sentinel import host_verdict

            verdict = host_verdict([abs(r["best_gap"]) for r in records],
                                   solver.sentinel)
            if verdict:
                break
        # Shrink to the sign change: gap is increasing in r, so the root
        # sits above the last negative candidate and below the first
        # positive one (bracket edges cover the all-one-sign cases).
        neg = gaps < 0.0
        if neg.any():
            i_star = int(np.max(np.nonzero(neg)[0]))
            new_lo = r_list[i_star]
            new_hi = r_list[i_star + 1] if i_star + 1 < B else hi
        else:
            new_lo, new_hi = lo, r_list[0]
        lo, hi = new_lo, new_hi
        r_prev, warm_prev = r_dev, out["warm"]
        if "mu" in out:
            mu_prev = out["mu"]

    take = lambda x: jax.tree_util.tree_map(lambda l: l[best], x)
    sol_best = take(out["sol"])
    series_best = take(out["series"]) if "series" in out else None
    mu_best = out["mu"][best] if "mu" in out else None
    r_star = r_list[best]
    from aiyagari_tpu.diagnostics.telemetry import host_telemetry

    return EquilibriumResult(
        r=r_star,
        w=float(wage_from_r(r_star, tech.alpha, tech.delta)),
        capital=ks_l[best],
        solution=sol_best,
        series=series_best,
        r_history=r_hist,
        k_supply=ks_hist,
        k_demand=kd_hist,
        iterations=rounds,
        converged=converged,
        solve_seconds=time.perf_counter() - t0,
        per_iteration=records,
        mu=mu_best,
        # Outer flight record: the best candidate's |gap| per ROUND — the
        # batched solver's own convergence trajectory.
        telemetry=host_telemetry([abs(r["best_gap"]) for r in records]),
        dist_telemetry=(take(out["dist_telemetry"])
                        if out.get("dist_telemetry") is not None else None),
        verdict=verdict,
    )


@dataclasses.dataclass(frozen=True)
class ScenarioBatch:
    """Stacked device operands for S scenarios sharing one grid geometry:
    every array carries a leading scenario axis, ready for the vmapped GE
    kernel (and for sharding that axis over a device mesh)."""

    a_grid: jax.Array       # [S, na]
    s: jax.Array            # [S, N]
    P: jax.Array            # [S, N, N]
    labor_grid: jax.Array   # [S, nl]
    sigma: jax.Array        # [S]
    beta: jax.Array         # [S]
    psi: jax.Array          # [S]
    eta: jax.Array          # [S]
    amin: jax.Array         # [S]
    labor_raw: jax.Array    # [S]
    alpha: float
    delta: float
    endogenous_labor: bool
    dtype: object
    size: int

    def operands(self):
        return (self.a_grid, self.s, self.P, self.labor_grid, self.sigma,
                self.beta, self.psi, self.eta, self.amin, self.labor_raw)


def stack_scenarios(models: Sequence[AiyagariModel], *, mesh=None) -> ScenarioBatch:
    """Stack per-scenario model primitives into one scenario-major batch.

    All scenarios must share shapes (asset-grid size, income states, labor
    grid), the endogenous_labor flag, and the technology block (alpha/delta
    stay static so the firm curves fold into the compiled program) — exactly
    the invariants the one-compilation contract needs. With `mesh` (carrying
    a "scenarios" axis), the stacked arrays are placed sharded over it, so
    the vmapped kernel runs scenario-parallel across devices.

    A 2-D mesh (a "grid" axis of size > 1 beside "scenarios" —
    parallel/mesh.make_mesh_2d, the dispatch.sweep `mesh=` knob) places
    the batch through the partition-rule matcher (parallel/rules.
    SCENARIO_BATCH_RULES) instead: the scenario axis still splits over
    "scenarios", and every trailing asset-grid axis (a_grid [S, na], the
    GE round's policy/warm carries [S, N, na] by sharding propagation)
    additionally splits over "grid" — so one compiled round program
    composes scenario parallelism ACROSS hosts with grid parallelism
    WITHIN a host. The asset grid must divide the "grid" axis evenly
    (loud, like the scenario-count check).
    """
    if not models:
        raise ValueError("stack_scenarios needs at least one scenario")
    m0 = models[0]
    tech0 = m0.config.technology
    for m in models[1:]:
        if (m.a_grid.shape != m0.a_grid.shape
                or m.P.shape != m0.P.shape
                or m.labor_grid.shape != m0.labor_grid.shape):
            raise ValueError(
                "sweep scenarios must share grid shapes: got "
                f"{m.a_grid.shape}/{m.P.shape} vs {m0.a_grid.shape}/{m0.P.shape}")
        if m.config.endogenous_labor != m0.config.endogenous_labor:
            raise ValueError("sweep scenarios must share endogenous_labor")
        if m.config.technology != tech0:
            raise ValueError(
                "sweep scenarios must share the technology block "
                "(alpha/delta are compiled statically into the firm curves)")
    dt = m0.dtype
    stack = lambda xs: jnp.stack([jnp.asarray(x, dt) for x in xs])
    batch = ScenarioBatch(
        a_grid=stack([m.a_grid for m in models]),
        s=stack([m.s for m in models]),
        P=stack([m.P for m in models]),
        labor_grid=stack([m.labor_grid for m in models]),
        sigma=jnp.asarray([m.preferences.sigma for m in models], dt),
        beta=jnp.asarray([m.preferences.beta for m in models], dt),
        psi=jnp.asarray([m.preferences.psi for m in models], dt),
        eta=jnp.asarray([m.preferences.eta for m in models], dt),
        amin=jnp.asarray([m.amin for m in models], dt),
        labor_raw=jnp.asarray([m.labor_raw for m in models], dt),
        alpha=float(tech0.alpha),
        delta=float(tech0.delta),
        endogenous_labor=bool(m0.config.endogenous_labor),
        dtype=dt,
        size=len(models),
    )
    if mesh is not None:
        from aiyagari_tpu.parallel.mesh import (
            GRID_AXIS,
            SCENARIOS_AXIS,
            shard_scenario_arrays,
        )

        arrays = {f.name: getattr(batch, f.name)
                  for f in dataclasses.fields(batch)
                  if isinstance(getattr(batch, f.name), jax.Array)}
        if GRID_AXIS in mesh.shape and int(mesh.shape[GRID_AXIS]) > 1:
            # 2-D placement through the rule matcher (docstring above).
            from aiyagari_tpu.parallel.rules import (
                SCENARIO_BATCH_RULES,
                shard_by_rules,
            )

            S_ax = int(mesh.shape[SCENARIOS_AXIS])
            G_ax = int(mesh.shape[GRID_AXIS])
            na = int(batch.a_grid.shape[-1])
            if batch.size % S_ax:
                raise ValueError(
                    f"scenario count {batch.size} must divide evenly over "
                    f"the {S_ax}-wide '{SCENARIOS_AXIS}' mesh axis")
            if na % G_ax:
                raise ValueError(
                    f"asset grid of {na} points must divide evenly over "
                    f"the {G_ax}-wide '{GRID_AXIS}' mesh axis")
            batch = dataclasses.replace(
                batch, **shard_by_rules(mesh, arrays, SCENARIO_BATCH_RULES))
        else:
            batch = dataclasses.replace(batch, **shard_scenario_arrays(
                mesh, batch.size, **arrays))
    return batch


@dataclasses.dataclass
class SweepResult:
    """Per-scenario equilibria from one lockstep batched sweep."""

    r: np.ndarray               # [S] equilibrium rates
    w: np.ndarray               # [S] wages at r
    capital: np.ndarray         # [S] K_supply at r
    gap: np.ndarray             # [S] final |supply - demand| signed gap
    converged: np.ndarray       # [S] bool
    rounds: int                 # lockstep device rounds executed
    scenarios: int
    solve_seconds: float
    scenarios_per_sec: float
    solutions: object           # batched household solution pytree (device)
    mu: object = None           # [S, N, na] stationary distributions, if
                                # the distribution closure produced them
    params: Optional[list] = None   # per-scenario parameter dicts (sweep())
    # Outer flight record (host): per-round max |gap| across the still-
    # running scenarios — the lockstep sweep's convergence trajectory.
    telemetry: object = None
    # [S]-leading batched device recorders from the FINAL round's
    # distribution solves, when SolverConfig.telemetry was set (index one
    # scenario down before reading, telemetry_trajectory's contract).
    dist_telemetry: object = None
    # Scenario quarantine (ISSUE 10): lanes whose gap went non-finite were
    # FROZEN (their midpoint pinned, excluded from the done-check) so the
    # rest of the batch completed — partial results instead of an
    # all-or-nothing sweep. `verdicts` names each scenario's outcome:
    # "converged" | "max_iter" | "nan" (quarantined) | "rescued" (dispatch
    # re-solved the lane serially through the rescue ladder).
    quarantined: object = None      # [S] bool
    verdicts: object = None         # list[str], length S
    rescue_attempts: object = None  # {scenario index: [RescueAttempt, ...]}


def solve_equilibrium_sweep(
    batch: ScenarioBatch, *, solver: SolverConfig = SolverConfig(),
    eq: EquilibriumConfig = EquilibriumConfig(),
    sim: SimConfig = SimConfig(),
    aggregation: str = "distribution",
    dist_tol: float = 1e-10, dist_max_iter: int = 10_000,
    quarantine: bool = True,
) -> SweepResult:
    """Advance S independent GE bisections in lockstep: every round solves
    ALL scenarios' midpoint households through one vmapped device program
    (sharded over a "scenarios" mesh axis when `batch` was stacked with
    one). A converged scenario keeps its midpoint pinned while the rest
    finish, so the program shape never changes and rounds stay one compile.

    The per-scenario fixed point is identical to running
    solve_equilibrium_distribution (or solve_equilibrium) scenario by
    scenario — same bracket update, same |gap| < eq.tol criterion — at
    1/S-th the sequential device rounds.

    quarantine (default True) arms the per-scenario failure masks (ISSUE
    10): a lane whose gap goes non-finite is FROZEN — its midpoint pinned,
    its bracket no longer updated, excluded from the all-done check — so
    one NaN-poisoned calibration costs its own lane, not the batch. The
    frozen lane's household solve still runs each round (the lockstep
    program shape never changes; its while_loop exits after one sweep on
    the NaN carry, so the wasted compute is a single sweep per round).
    Quarantined lanes report verdict "nan" on SweepResult.verdicts;
    dispatch.sweep(rescue=...) re-solves them serially through the rescue
    ladder. quarantine=False keeps the pre-quarantine behavior (a NaN lane
    re-runs its frozen bracket until max_iter) for A/B benchmarking.
    """
    if aggregation not in ("distribution", "simulation"):
        raise ValueError(f"unknown aggregation {aggregation!r}")
    t0 = time.perf_counter()
    S = batch.size
    tech_alpha, tech_delta = batch.alpha, batch.delta
    beta_host = np.asarray(jax.device_get(batch.beta), np.float64)
    lo = np.full(S, float(eq.r_low))
    hi = (np.full(S, float(eq.r_high)) if eq.r_high is not None
          else 1.0 / beta_host - 1.0)
    # A NaN scenario parameter (a poisoned calibration) makes the bracket
    # itself NaN; the first round's gap is then NaN and the lane
    # quarantines immediately rather than iterating on a NaN midpoint.
    hi = np.where(np.isfinite(hi), hi, 1.0)
    conv = np.zeros(S, bool)
    quar = np.zeros(S, bool)
    r_mid = 0.5 * (lo + hi)
    gaps = np.full(S, np.inf)
    supplies = np.zeros(S)

    knobs = _knobs(solver, tech_alpha, tech_delta, dist_tol, dist_max_iter,
                   sim)
    warm = jnp.zeros((1,) + tuple(batch.P.shape[-1:]) + tuple(
        batch.a_grid.shape[-1:]), batch.dtype)
    # Per-lane distribution warm start, carried across rounds exactly like
    # the household policy (the serial aggregator's mu_init, lockstepped).
    mu_carry = jnp.zeros_like(warm)
    out = None
    rounds = 0
    gap_hist: list = []
    for rnd in range(eq.max_iter):
        done = conv | quar
        r_mid = np.where(done, r_mid, 0.5 * (lo + hi))
        r_dev = jnp.asarray(r_mid, batch.dtype)
        keys = _round_keys(sim.seed, rnd, S)
        fn = _ge_round_program(solver.method, batch.endogenous_labor,
                               aggregation, knobs, True, rnd == 0)
        out = fn(r_dev, r_dev, warm, mu_carry, keys, *batch.operands())
        warm = out["warm"]
        if "mu" in out:
            mu_carry = out["mu"]
        gaps, supplies = (np.asarray(x, np.float64) for x in
                          jax.device_get((out["gap"], out["supply"])))
        rounds = rnd + 1
        if quarantine:
            # Freeze newly-diverged lanes: non-finite gap on a lane that
            # has not converged. (A lane that converged in an earlier round
            # keeps its verdict — its pinned midpoint may legitimately
            # reproduce a finite gap forever.)
            quar = quar | (~np.isfinite(gaps) & ~conv)
        finite = np.where(np.isfinite(gaps), np.abs(gaps), np.inf)
        done = conv | quar
        gap_hist.append(float(np.max(np.where(done, 0.0, finite),
                                     initial=0.0)))
        newly = ~quar & np.isfinite(gaps) & (np.abs(gaps) < eq.tol)
        conv = conv | newly
        # Pod-observatory heartbeat (diagnostics/progress.py): publish this
        # round's per-scenario state on the active ledger at the configured
        # stride — host code, so the compiled round program is untouched;
        # the stride guard keeps the off path at one function call.
        if heartbeat_stride():
            sweep_heartbeat(
                "aiyagari_sweep", round_index=rnd,
                gap=[float(g) for g in gaps],
                r=[float(v) for v in r_mid],
                converged=[bool(c) for c in conv],
                quarantined=[bool(q) for q in quar],
                dtype=str(out["gap"].dtype))
        if (conv | quar).all():
            break
        step = ~(conv | quar)
        lo = np.where(step & (gaps < 0.0), r_mid, lo)
        hi = np.where(step & (gaps >= 0.0), r_mid, hi)

    wall = time.perf_counter() - t0
    from aiyagari_tpu.diagnostics.telemetry import host_telemetry

    verdicts = ["converged" if c else ("nan" if q else "max_iter")
                for c, q in zip(conv, quar)]
    return SweepResult(
        r=r_mid.copy(),
        w=np.asarray(wage_from_r(r_mid, tech_alpha, tech_delta)),
        capital=supplies,
        gap=gaps,
        converged=conv,
        rounds=rounds,
        scenarios=S,
        solve_seconds=wall,
        scenarios_per_sec=S / wall if wall > 0 else float("inf"),
        solutions=out["sol"],
        mu=out.get("mu"),
        telemetry=host_telemetry(gap_hist),
        dist_telemetry=out.get("dist_telemetry"),
        quarantined=quar,
        verdicts=verdicts,
    )
