"""One-program equilibrium: the WHOLE GE closure as a single XLA program.

The host bisection (equilibrium/bisection.py) and the host parallel-bracket
loop (equilibrium/batched.py) pay one jit dispatch plus one host sync per
outer round: every midpoint fetches `float(...)` scalars, decides the
bracket on host, and re-enters the device. Once the per-sweep kernels are
fast, that dispatch/sync overhead IS the GE wall — the same
dispatch-overhead ceiling the serve layer hammers hardest.

This module moves the outer loop into the program: household fixed point
(EGM or VFI) + Young stationary distribution + market clearing + bracket
update all live inside ONE `lax.while_loop` carry, so an entire equilibrium
is one device program launch. Two shapes:

  * solve_equilibrium_fused — serial bisection in the carry. Each loop
    round solves the household at the bracket midpoint (warm-started from
    the carry), pushes the distribution forward from the carried mu, and
    shrinks [lo, hi] with `jnp.where` on the gap sign — the exact update
    `_bisect` performs on host (`if supply > demand: r_high = r_mid`).
    calibrate/economy.steady_state_map proved this composition; here it
    becomes the production path with histories, telemetry, sentinel, and
    the bisection's adaptive stopping rule (a while_loop on |gap| >= tol,
    not a fixed trip count).

  * solve_equilibrium_fused_batched — the parallel-bracket round of
    equilibrium/batched.py, fused: candidate placement, the vmapped
    excess-demand evaluation, nearest-candidate warm selection, the
    per-round quarantine mask, and the sign-change bracket shrink all run
    inside the while_loop. The host sees one program for the whole solve
    instead of one per round.

Contracts threaded through the fusion (ISSUE 18):

  * precision ladder / Anderson-SQUAREM accel — passed to the inner
    solves unchanged; their stage switches and mixing carries live inside
    the inner while_loops exactly as on the host paths.
  * telemetry rings — the OUTER loop carries its own SolveTelemetry ring
    recording the per-round market-clearing |gap| (the device twin of the
    host loop's host_telemetry), beside the inner solves' own rings.
  * sentinel verdicts — an outer SentinelState watches the gap trajectory
    and early-exits the while_loop on nan/stall/explosion via
    sentinel_cond, the device twin of the host loop's host_verdict check.
  * quarantine masks — the batched round's non-finite lanes are masked out
    of best-candidate selection and reported per round; an ALL-lane-nan
    round exits the loop (the host loop would burn its remaining rounds —
    the fused loop's nan-exit is required by AIYA107 and strictly better).

Buffer donation: the [N, na] warm policy/value state and the [N, na]
(or [B, N, na]) distribution iterate dominate the program's argument
bytes, and the caller never reuses them after the solve — `donate=True`
(the solve_* default) marks them `donate_argnums` so XLA reuses their
buffers for outputs/temps instead of holding both generations live.
A caller-owned warm start (the serve cache's arrays) is defensively
copied before donation so the cache entry survives.

Host-vs-device placement is the SolverConfig.ge_loop knob, routed by
dispatch.solve(); the host loops stay bit-identical as the parity
reference (tests/test_fused_ge.py pins tolerance parity).

Known (documented) deviations from the host reference, all below the
bisection's sign-decision noise floor:
  * EGM runs grid_power=0.0 (exact inversion): the windowed fast path's
    escape contract needs a HOST retry (solve_aiyagari_egm_safe), which a
    fused program cannot perform mid-loop — the batched closure's pin.
  * The distribution warm start enters through mu_init (renormalized)
    where the host's first round passes None (exact uniform); identical
    to ~1 ulp after the first round's contraction.
  * No multiscale grid sequencing (solve_aiyagari_egm_multiscale is a
    host-staged chain); large cold grids should keep ge_loop="host".
"""

from __future__ import annotations

import time
from functools import lru_cache
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from aiyagari_tpu.config import EquilibriumConfig, SolverConfig
from aiyagari_tpu.diagnostics.sentinel import (
    sentinel_cond,
    sentinel_init,
    sentinel_update,
    verdict_name,
)
from aiyagari_tpu.diagnostics.telemetry import (
    host_telemetry,
    telemetry_init,
    telemetry_record,
)
from aiyagari_tpu.equilibrium.bisection import EquilibriumResult
from aiyagari_tpu.models.aiyagari import AiyagariModel
from aiyagari_tpu.utils.firm import capital_demand, wage_from_r

__all__ = [
    "resolve_ge_loop",
    "fused_knobs",
    "fused_ge_program",
    "fused_ge_operands",
    "solve_equilibrium_fused",
    "fused_ge_batched_program",
    "fused_ge_batched_operands",
    "solve_equilibrium_fused_batched",
    "fused_batched_round",
]

# warm/mu positions in the fused program signatures — the donated slots.
_DONATE_SERIAL = (3, 4)     # (lo, hi, r_init, WARM, MU, ...model operands)
_DONATE_BATCHED = (2, 3)    # (lo, hi, WARM, MU, ...model operands)


def resolve_ge_loop(solver: SolverConfig, *, aggregation: str,
                    endogenous_labor: bool, mesh=None) -> str:
    """Resolve SolverConfig.ge_loop to a concrete placement.

    "auto" picks "device" exactly where the fused program exists —
    distribution aggregation, exogenous labor, no device mesh — and falls
    back to "host" elsewhere. An EXPLICIT "device" on an unsupported combo
    is loud (the batched closure's require-style contract), never a silent
    host fallback.
    """
    loop = solver.ge_loop
    if loop == "host":
        return "host"
    supported = (aggregation == "distribution" and not endogenous_labor
                 and mesh is None)
    if loop == "auto":
        return "device" if supported else "host"
    if not supported:
        why = ("simulation aggregation needs per-round PRNG panel runs"
               if aggregation != "distribution" else
               "the endogenous-labor families are host-loop only"
               if endogenous_labor else
               "mesh-sharded solves keep the host loop (per-shard restore)")
        raise ValueError(
            f"SolverConfig(ge_loop='device') is unsupported here: {why}; "
            "use ge_loop='auto' to fall back to the host loop")
    return "device"


def fused_knobs(model: AiyagariModel, solver: SolverConfig,
                eq: EquilibriumConfig, dist_tol: float, dist_max_iter: int):
    """The static-knob tuple the fused program builders destructure — one
    builder so the positional contract cannot drift (the batched _knobs
    idiom)."""
    tech = model.config.technology
    return (
        solver.tol, solver.max_iter, solver.howard_steps,
        solver.relative_tol, tech.alpha, tech.delta,
        float(dist_tol), int(dist_max_iter),
        float(eq.tol), int(eq.max_iter), int(eq.batch),
        solver.accel, solver.ladder, solver.pushforward,
        solver.telemetry, solver.sentinel, solver.faults, solver.egm_kernel,
    )


def _routes(method: str, egm_kernel: str, pushforward: str, batched: bool):
    """Resolve the push-forward and EGM-kernel routes once per cached
    program build (the traced program carries concrete routes), with the
    batched closure's pallas_inverse rejection: the fused solves pin
    grid_power=0 (no host escape retry mid-program)."""
    from aiyagari_tpu.ops.pushforward import resolve_backend

    pushforward = resolve_backend(pushforward, batched=batched)
    if method == "egm":
        from aiyagari_tpu.ops.egm import resolve_egm_kernel

        if resolve_egm_kernel(egm_kernel) == "pallas_inverse":
            raise ValueError(
                "egm_kernel='pallas_inverse' is not supported by the fused "
                "GE loop: its in-program solves run grid_power=0 (no host "
                "escape retry mid-loop), which the windowed inversion route "
                "requires; use 'auto', 'xla', or 'pallas_fused'")
    return pushforward


def _household_closure(method: str, knobs: tuple, *, batched: bool):
    """(hh, round_eval) closures over the static knobs.

    hh(r, warm, a_grid, s, P, sigma, beta, amin) -> (sol, warm_out) is the
    household fixed point alone (the pre-loop warm pass); round_eval adds
    the stationary distribution and market clearing — one outer round.
    """
    (tol, max_iter, howard_steps, relative_tol, alpha, delta,
     dist_tol, dist_max_iter, _eq_tol, _eq_max_iter, _eq_batch,
     accel, ladder, pushforward, telemetry, sentinel, faults,
     egm_kernel) = knobs
    pushforward = _routes(method, egm_kernel, pushforward, batched)

    def hh(r, warm, a_grid, s, P, sigma, beta, amin):
        w = wage_from_r(r, alpha, delta)
        if method == "vfi":
            from aiyagari_tpu.solvers.vfi import solve_aiyagari_vfi

            sol = solve_aiyagari_vfi(
                warm, a_grid, s, P, r, w, sigma=sigma, beta=beta,
                tol=tol, max_iter=max_iter, howard_steps=howard_steps,
                relative_tol=relative_tol, ladder=ladder,
                telemetry=telemetry, sentinel=sentinel, faults=faults)
            return sol, sol.v
        from aiyagari_tpu.solvers.egm import solve_aiyagari_egm

        # grid_power=0.0: the generic exact inversion (module docstring).
        sol = solve_aiyagari_egm(
            warm, a_grid, s, P, r, w, amin, sigma=sigma, beta=beta,
            tol=tol, max_iter=max_iter, relative_tol=relative_tol,
            grid_power=0.0, egm_kernel=egm_kernel, accel=accel,
            ladder=ladder, telemetry=telemetry, sentinel=sentinel,
            faults=faults)
        return sol, sol.policy_c

    def round_eval(r, warm, mu, a_grid, s, P, sigma, beta, amin, labor_raw):
        from aiyagari_tpu.sim.distribution import (
            aggregate_capital,
            stationary_distribution,
        )

        sol, warm_out = hh(r, warm, a_grid, s, P, sigma, beta, amin)
        dist = stationary_distribution(
            sol.policy_k, a_grid, P, tol=dist_tol, max_iter=dist_max_iter,
            mu_init=mu, accel=accel, ladder=ladder, pushforward=pushforward,
            telemetry=telemetry, sentinel=sentinel, faults=faults)
        supply = aggregate_capital(dist.mu, a_grid)
        demand = capital_demand(r, labor_raw, alpha, delta)
        return sol, warm_out, dist, supply, demand

    return hh, round_eval


@lru_cache(maxsize=None)
def _fused_serial(method: str, knobs: tuple, donate: bool):
    """Build + jit the serial fused bisection (module docstring). Cache key
    = everything that changes the traced program plus the donation split —
    the donated and undonated twins are distinct executables."""
    (_tol, _mi, _hs, _rt, alpha, delta, _dtol, _dmi,
     eq_tol, eq_max_iter, _eq_batch, _accel, _ladder, _pf,
     telemetry_cfg, sentinel_cfg, _faults, _ek) = knobs
    hh, round_eval = _household_closure(method, knobs, batched=False)

    def program(lo0, hi0, r0, warm0, mu0, a_grid, s, P, sigma, beta, amin,
                labor_raw):
        dt = a_grid.dtype
        iota = jnp.arange(eq_max_iter, dtype=jnp.int32)

        # Pre-loop warm pass at r_init (the host loop's :63-129 analogue) —
        # also materializes the solution pytree the while carry threads.
        sol0, warm1 = hh(jnp.asarray(r0, dt), warm0, a_grid, s, P, sigma,
                         beta, amin)

        carry = {
            "lo": jnp.asarray(lo0, dt),
            "hi": jnp.asarray(hi0, dt),
            "r": jnp.asarray(r0, dt),
            # +inf, not 0/nan: round one must run (|inf| >= tol) and a
            # nan-poisoned gap must FAIL the cond (|nan| >= tol is False)
            # — the AIYA107 nan-early-exit contract.
            "gap": jnp.asarray(jnp.inf, dt),
            "supply": jnp.asarray(jnp.nan, dt),
            "demand": jnp.asarray(jnp.nan, dt),
            "warm": warm1,
            "mu": mu0,
            "sol": sol0,
            "dist_tele": telemetry_init(telemetry_cfg),
            "it": jnp.asarray(0, jnp.int32),
            "r_hist": jnp.full((eq_max_iter,), jnp.nan, dt),
            "ks_hist": jnp.full((eq_max_iter,), jnp.nan, dt),
            "kd_hist": jnp.full((eq_max_iter,), jnp.nan, dt),
            "si_hist": jnp.zeros((eq_max_iter,), jnp.int32),
            "di_hist": jnp.zeros((eq_max_iter,), jnp.int32),
            "tele": telemetry_init(telemetry_cfg),
            "sent": sentinel_init(sentinel_cfg),
        }

        def cond(c):
            base = (jnp.abs(c["gap"]) >= eq_tol) & (c["it"] < eq_max_iter)
            return sentinel_cond(c["sent"], base)

        def body(c):
            mid = 0.5 * (c["lo"] + c["hi"])
            sol, warm, dist, supply, demand = round_eval(
                mid, c["warm"], c["mu"], a_grid, s, P, sigma, beta, amin,
                labor_raw)
            gap = supply - demand
            # History writes as one-hot selects, not .at[] scatters — the
            # fused program stays scatter-free for the AIYA101 audit.
            sel = iota == c["it"]
            tele = telemetry_record(c["tele"], jnp.abs(gap))
            sent = sentinel_update(c["sent"], jnp.abs(gap),
                                   config=sentinel_cfg)
            return {
                # Host-parity bracket: `if supply > demand: r_high = mid`.
                "lo": jnp.where(gap > 0.0, c["lo"], mid),
                "hi": jnp.where(gap > 0.0, mid, c["hi"]),
                "r": mid,
                "gap": gap,
                "supply": supply,
                "demand": demand,
                "warm": warm,
                "mu": dist.mu,
                "sol": sol,
                "dist_tele": dist.telemetry,
                "it": c["it"] + 1,
                "r_hist": jnp.where(sel, mid, c["r_hist"]),
                "ks_hist": jnp.where(sel, supply, c["ks_hist"]),
                "kd_hist": jnp.where(sel, demand, c["kd_hist"]),
                "si_hist": jnp.where(sel, sol.iterations.astype(jnp.int32),
                                     c["si_hist"]),
                "di_hist": jnp.where(sel, dist.iterations.astype(jnp.int32),
                                     c["di_hist"]),
                "tele": tele,
                "sent": sent,
            }

        out = lax.while_loop(cond, body, carry)
        out["w"] = wage_from_r(out["r"], alpha, delta)
        return out

    donate_argnums = _DONATE_SERIAL if donate else ()
    return jax.jit(program, donate_argnums=donate_argnums)


def fused_ge_program(model: AiyagariModel, *,
                     solver: SolverConfig = SolverConfig(),
                     eq: EquilibriumConfig = EquilibriumConfig(),
                     dist_tol: float = 1e-10, dist_max_iter: int = 10_000,
                     donate: bool = False):
    """The compiled serial fused-GE entry for `model`'s static geometry.
    Call with fused_ge_operands(...); donate=True hands the warm/mu
    argument buffers to XLA (the caller must not reuse them)."""
    if model.config.endogenous_labor:
        raise ValueError("the fused GE loop supports exogenous labor only; "
                         "use ge_loop='host' (resolve_ge_loop routes this)")
    knobs = fused_knobs(model, solver, eq, dist_tol, dist_max_iter)
    return _fused_serial(solver.method, knobs, bool(donate))


def fused_ge_operands(model: AiyagariModel, eq: EquilibriumConfig, *,
                      solver: SolverConfig = SolverConfig(),
                      warm_start=None):
    """Operand tuple for fused_ge_program: (lo, hi, r_init, warm, mu,
    a_grid, s, P, sigma, beta, amin, labor_raw). The warm state follows
    the host loop's seeding — warm_start when given (COPIED, so a donated
    call cannot delete the caller's cache entry), else the VFI zero value
    / EGM cash-on-hand guess; mu starts uniform."""
    prefs = model.preferences
    dt = model.dtype
    lo = jnp.asarray(eq.r_low, dt)
    hi = jnp.asarray(eq.r_high if eq.r_high is not None
                     else 1.0 / prefs.beta - 1.0, dt)
    r0 = jnp.asarray(eq.r_init, dt)
    N, na = model.P.shape[0], model.a_grid.shape[0]
    if warm_start is not None:
        warm = jnp.array(warm_start, dtype=dt, copy=True)
    elif solver.method == "vfi":
        warm = jnp.zeros((N, na), dt)
    else:
        from aiyagari_tpu.solvers.egm import initial_consumption_guess

        warm = initial_consumption_guess(
            model.a_grid, model.s, r0,
            wage_from_r(r0, model.config.technology.alpha,
                        model.config.technology.delta))
    mu = jnp.full((N, na), 1.0 / (N * na), dt)
    sc = lambda x: jnp.asarray(x, dt)
    return (lo, hi, r0, warm, mu, model.a_grid, model.s, model.P,
            sc(prefs.sigma), sc(prefs.beta), sc(model.amin),
            sc(model.labor_raw))


def _result_from_fused(out: dict, *, eq: EquilibriumConfig, t0: float,
                       rounds_are_batches: bool = False) -> EquilibriumResult:
    """ONE device_get of the fused program's scalar/history outputs, then
    the host-shaped EquilibriumResult the dispatch/serve layers consume."""
    small = {k: out[k] for k in
             ("r", "w", "gap", "supply", "demand", "it", "quar",
              "r_hist", "ks_hist", "kd_hist", "si_hist", "di_hist")
             if k in out}
    if out.get("sent") is not None:
        small["verdict_code"] = out["sent"].verdict
    host = jax.device_get(small)
    # Everything below is host numpy from the ONE device_get above — the
    # scalar casts are free, not per-element device fetches.
    it = int(host["it"])  # noqa: AIYA202 — host numpy post-device_get
    gap = float(host["gap"])  # noqa: AIYA202 — host numpy post-device_get
    converged = bool(np.isfinite(gap) and abs(gap) < eq.tol)
    verdict = ""
    code = int(host["verdict_code"]) if "verdict_code" in host else 0  # noqa: AIYA202 — host numpy post-device_get
    if code != 0:
        verdict = verdict_name(code)
    r_hist = np.asarray(host["r_hist"], np.float64)
    ks_hist = np.asarray(host["ks_hist"], np.float64)
    kd_hist = np.asarray(host["kd_hist"], np.float64)
    si_hist = np.asarray(host["si_hist"])
    di_hist = np.asarray(host["di_hist"])
    if rounds_are_batches:
        # [rounds, B] rows -> flat per-candidate histories (the batched
        # host loop's convention), one record per ROUND.
        quar = np.asarray(host.get("quar", np.zeros_like(ks_hist, bool)))
        si_list = np.asarray(si_hist, np.int64).tolist()
        records = []
        for i in range(it):
            gaps_i = ks_hist[i] - kd_hist[i]
            finite = np.where(np.isfinite(gaps_i), np.abs(gaps_i), np.inf)
            b = int(np.argmin(finite))
            row_r = r_hist[i].tolist()
            row_g = gaps_i.tolist()
            records.append({
                "round": i,
                "r_candidates": row_r,
                "gaps": row_g,
                "best_r": row_r[b],
                "best_gap": row_g[b],
                "gap": row_g[b],
                "quarantined": quar[i].tolist(),
                "solver_iterations_max": si_list[i],
            })
        r_list = r_hist[:it].reshape(-1).tolist()
        ks_list = ks_hist[:it].reshape(-1).tolist()
        kd_list = kd_hist[:it].reshape(-1).tolist()
        outer_resid = [abs(r["best_gap"]) for r in records]
    else:
        r_list = r_hist[:it].tolist()
        ks_list = ks_hist[:it].tolist()
        kd_list = kd_hist[:it].tolist()
        si_list = np.asarray(si_hist, np.int64).tolist()
        di_list = np.asarray(di_hist, np.int64).tolist()
        records = [{
            "iteration": i,
            "r": r_list[i],
            "k_supply": ks_list[i],
            "k_demand": kd_list[i],
            "gap": ks_list[i] - kd_list[i],
            "solver_iterations": si_list[i],
            "distribution_iterations": di_list[i],
        } for i in range(it)]
        outer_resid = [abs(s - d) for s, d in zip(ks_list, kd_list)]
    telemetry = (out["tele"] if out.get("tele") is not None
                 else host_telemetry(outer_resid))
    return EquilibriumResult(
        r=float(host["r"]),  # noqa: AIYA202 — host numpy post-device_get
        w=float(host["w"]),  # noqa: AIYA202 — host numpy post-device_get
        capital=float(host["supply"]),  # noqa: AIYA202 — host numpy post-device_get
        solution=out["sol"],
        series=None,
        r_history=r_list,
        k_supply=ks_list,
        k_demand=kd_list,
        iterations=it,
        converged=converged,
        solve_seconds=time.perf_counter() - t0,
        per_iteration=records,
        mu=out["mu"],
        telemetry=telemetry,
        dist_telemetry=out.get("dist_tele"),
        verdict=verdict,
    )


def solve_equilibrium_fused(
    model: AiyagariModel, *, solver: SolverConfig = SolverConfig(),
    eq: EquilibriumConfig = EquilibriumConfig(),
    dist_tol: float = 1e-10, dist_max_iter: int = 10_000,
    warm_start=None, donate: bool = True,
) -> EquilibriumResult:
    """solve_equilibrium_distribution's fixed point, ONE device program:
    the r-bisection runs inside the compiled while_loop (module docstring).
    Same bracket semantics, same |gap| < eq.tol stopping rule; the host
    sees exactly one dispatch and one device_get per equilibrium."""
    t0 = time.perf_counter()
    fn = fused_ge_program(model, solver=solver, eq=eq, dist_tol=dist_tol,
                          dist_max_iter=dist_max_iter, donate=donate)
    ops = fused_ge_operands(model, eq, solver=solver, warm_start=warm_start)
    out = fn(*ops)
    return _result_from_fused(out, eq=eq, t0=t0)


# ---------------------------------------------------------------------------
# Batched candidate rounds inside the same program
# ---------------------------------------------------------------------------


@lru_cache(maxsize=None)
def _fused_batched(method: str, knobs: tuple, donate: bool):
    """Build + jit the fused parallel-bracket loop: B-candidate vmapped
    rounds, nearest-candidate warm selection, quarantine masking, and the
    sign-change bracket shrink, all inside one lax.while_loop."""
    (_tol, _mi, _hs, _rt, alpha, delta, _dtol, _dmi,
     eq_tol, eq_max_iter, eq_batch, _accel, _ladder, _pf,
     telemetry_cfg, sentinel_cfg, _faults, _ek) = knobs
    B = int(eq_batch)
    _hh, round_eval = _household_closure(method, knobs, batched=True)

    def program(lo0, hi0, warm0, mu0, a_grid, s, P, sigma, beta, amin,
                labor_raw):
        dt = a_grid.dtype
        offsets = jnp.asarray(np.arange(1, B + 1) / (B + 1.0), dt)
        iota = jnp.arange(eq_max_iter, dtype=jnp.int32)
        lanes = jnp.arange(B, dtype=jnp.int32)

        batched_eval = jax.vmap(
            lambda warm, mu, r: round_eval(r, warm, mu, a_grid, s, P,
                                           sigma, beta, amin, labor_raw),
            in_axes=(0, 0, 0))

        def shrink(lo, hi, r_cand, gaps):
            # Host-parity sign-change shrink (solve_equilibrium_batched):
            # gap increases in r, the root sits above the LAST negative
            # candidate; nan gaps compare False and act non-negative,
            # exactly as on host.
            neg = gaps < 0.0
            any_neg = jnp.any(neg)
            i_star = jnp.max(jnp.where(neg, lanes, -1))
            lo_neg = jnp.take(r_cand, jnp.clip(i_star, 0, B - 1))
            hi_neg = jnp.where(i_star + 1 < B,
                               jnp.take(r_cand, jnp.clip(i_star + 1, 0,
                                                         B - 1)),
                               hi)
            new_lo = jnp.where(any_neg, lo_neg, lo)
            new_hi = jnp.where(any_neg, hi_neg, r_cand[0])
            return new_lo, new_hi

        def eval_round(r_cand, warm, mu, it, c):
            sol, warm_out, dist, supply, demand = batched_eval(warm, mu,
                                                               r_cand)
            gaps = supply - demand
            # Quarantine mask: non-finite lanes are excluded from the best
            # pick (and reported); the other lanes' values are untouched —
            # vmapped lanes are independent, so a poisoned candidate costs
            # its own lane only (the sweep's lockstep contract).
            quar = ~jnp.isfinite(gaps)
            finite = jnp.where(quar, jnp.inf, jnp.abs(gaps))
            best = jnp.argmin(finite).astype(jnp.int32)
            best_gap = jnp.take(gaps, best)
            sel = (iota == it)[:, None]
            return {
                "lo": c["lo"], "hi": c["hi"],   # shrunk by the caller
                "r_prev": r_cand,
                "best": best,
                "best_r": jnp.take(r_cand, best),
                "best_gap": best_gap,
                "best_supply": jnp.take(supply, best),
                "warm": warm_out,
                "mu": dist.mu,
                "sol": sol,
                "dist_tele": dist.telemetry,
                "it": it + 1,
                "r_hist": jnp.where(sel, r_cand[None, :], c["r_hist"]),
                "ks_hist": jnp.where(sel, supply[None, :], c["ks_hist"]),
                "kd_hist": jnp.where(sel, demand[None, :], c["kd_hist"]),
                "si_hist": jnp.where(
                    iota == it,
                    jnp.max(sol.iterations).astype(jnp.int32), c["si_hist"]),
                "di_hist": jnp.where(
                    iota == it,
                    jnp.max(dist.iterations).astype(jnp.int32), c["di_hist"]),
                "quar": jnp.where(sel, quar[None, :], c["quar"]),
                "tele": telemetry_record(c["tele"], jnp.abs(best_gap)),
                "sent": sentinel_update(c["sent"], jnp.abs(best_gap),
                                        config=sentinel_cfg),
            }, gaps

        shell = {
            "lo": jnp.asarray(lo0, dt),
            "hi": jnp.asarray(hi0, dt),
            "r_hist": jnp.full((eq_max_iter, B), jnp.nan, dt),
            "ks_hist": jnp.full((eq_max_iter, B), jnp.nan, dt),
            "kd_hist": jnp.full((eq_max_iter, B), jnp.nan, dt),
            "si_hist": jnp.zeros((eq_max_iter,), jnp.int32),
            "di_hist": jnp.zeros((eq_max_iter,), jnp.int32),
            "quar": jnp.zeros((eq_max_iter, B), bool),
            "tele": telemetry_init(telemetry_cfg),
            "sent": sentinel_init(sentinel_cfg),
        }
        # Round 0 runs OUTSIDE the while_loop (the host loop's cold round /
        # the serial path's pre-loop pass): warm0/mu0 seed the candidates
        # directly, and the round's outputs give the carry its solution
        # pytree structure.
        r0 = shell["lo"] + (shell["hi"] - shell["lo"]) * offsets
        carry, gaps0 = eval_round(r0, warm0, mu0,
                                  jnp.asarray(0, jnp.int32), shell)
        lo1, hi1 = shrink(carry["lo"], carry["hi"], r0, gaps0)
        carry["lo"], carry["hi"] = lo1, hi1

        def cond(c):
            # |nan| >= tol is False: an all-lane-nan round (best_gap nan)
            # exits the loop — the AIYA107 nan-exit contract (module
            # docstring names the host deviation).
            base = (jnp.abs(c["best_gap"]) >= eq_tol) & (c["it"] < eq_max_iter)
            return sentinel_cond(c["sent"], base)

        def body(c):
            r_cand = c["lo"] + (c["hi"] - c["lo"]) * offsets
            # Nearest-candidate warm selection (the bracket nests, so the
            # previous round's survivors are the closest warm states).
            j = jnp.argmin(jnp.abs(r_cand[:, None] - c["r_prev"][None, :]),
                           axis=1)
            warm = jnp.take(c["warm"], j, axis=0)
            mu = jnp.take(c["mu"], j, axis=0)
            nxt, gaps = eval_round(r_cand, warm, mu, c["it"], c)
            lo, hi = shrink(c["lo"], c["hi"], r_cand, gaps)
            nxt["lo"], nxt["hi"] = lo, hi
            return nxt

        out = lax.while_loop(cond, body, carry)
        out["r"] = out["best_r"]
        out["w"] = wage_from_r(out["best_r"], alpha, delta)
        out["gap"] = out["best_gap"]
        out["supply"] = out["best_supply"]
        out["demand"] = out["best_supply"] - out["best_gap"]
        return out

    donate_argnums = _DONATE_BATCHED if donate else ()
    return jax.jit(program, donate_argnums=donate_argnums)


def fused_ge_batched_program(model: AiyagariModel, *,
                             solver: SolverConfig = SolverConfig(),
                             eq: EquilibriumConfig = EquilibriumConfig(batch=8),
                             dist_tol: float = 1e-10,
                             dist_max_iter: int = 10_000,
                             donate: bool = False):
    """The compiled fused parallel-bracket entry (eq.batch candidates per
    in-program round). Call with fused_ge_batched_operands(...)."""
    if model.config.endogenous_labor:
        raise ValueError("the fused GE loop supports exogenous labor only; "
                         "use ge_loop='host' (resolve_ge_loop routes this)")
    if eq.batch < 2:
        raise ValueError(
            f"fused_ge_batched_program needs eq.batch >= 2, got {eq.batch}")
    knobs = fused_knobs(model, solver, eq, dist_tol, dist_max_iter)
    return _fused_batched(solver.method, knobs, bool(donate))


def fused_ge_batched_operands(model: AiyagariModel, eq: EquilibriumConfig, *,
                              solver: SolverConfig = SolverConfig()):
    """Operand tuple for fused_ge_batched_program: (lo, hi, warm, mu,
    a_grid, s, P, sigma, beta, amin, labor_raw) with [B]-leading warm/mu.
    Cold-start seeding matches the host batched round 0: VFI zeros / EGM
    cash-on-hand guesses at each candidate's own prices, uniform mu."""
    prefs = model.preferences
    tech = model.config.technology
    dt = model.dtype
    B = int(eq.batch)
    lo = float(eq.r_low)
    hi = float(eq.r_high if eq.r_high is not None
               else 1.0 / prefs.beta - 1.0)
    N, na = model.P.shape[0], model.a_grid.shape[0]
    r0 = jnp.asarray(lo + (hi - lo) * np.arange(1, B + 1) / (B + 1.0), dt)
    if solver.method == "vfi":
        warm = jnp.zeros((B, N, na), dt)
    else:
        from aiyagari_tpu.solvers.egm import initial_consumption_guess

        w0 = wage_from_r(r0, tech.alpha, tech.delta)
        warm = jax.vmap(initial_consumption_guess,
                        in_axes=(None, None, 0, 0))(model.a_grid, model.s,
                                                    r0, w0)
    mu = jnp.full((B, N, na), 1.0 / (N * na), dt)
    sc = lambda x: jnp.asarray(x, dt)
    return (jnp.asarray(lo, dt), jnp.asarray(hi, dt), warm, mu,
            model.a_grid, model.s, model.P, sc(prefs.sigma), sc(prefs.beta),
            sc(model.amin), sc(model.labor_raw))


def solve_equilibrium_fused_batched(
    model: AiyagariModel, *, solver: SolverConfig = SolverConfig(),
    eq: EquilibriumConfig = EquilibriumConfig(batch=8),
    dist_tol: float = 1e-10, dist_max_iter: int = 10_000,
    donate: bool = True,
) -> EquilibriumResult:
    """solve_equilibrium_batched's fixed point, ONE device program (module
    docstring): the parallel-bracket rounds run inside the compiled
    while_loop. Histories carry every evaluated candidate; `iterations`
    counts rounds, as on the host path."""
    t0 = time.perf_counter()
    fn = fused_ge_batched_program(model, solver=solver, eq=eq,
                                  dist_tol=dist_tol,
                                  dist_max_iter=dist_max_iter, donate=donate)
    ops = fused_ge_batched_operands(model, eq, solver=solver)
    out = fn(*ops)
    best = int(jax.device_get(out["best"]))
    take = lambda x: jax.tree_util.tree_map(lambda l: l[best], x)
    out = dict(out)
    out["sol"] = take(out["sol"])
    out["mu"] = out["mu"][best]
    if out.get("dist_tele") is not None:
        out["dist_tele"] = take(out["dist_tele"])
    return _result_from_fused(out, eq=eq, t0=t0, rounds_are_batches=True)


@lru_cache(maxsize=None)
def _fused_round(method: str, knobs: tuple):
    """One quarantine-masked candidate round, standalone: the exact vmapped
    evaluation + masking the fused batched loop runs per round, exposed so
    tests can pin quarantined-lane bitwise independence (a poisoned
    candidate must not perturb its neighbors' bits)."""
    _hh, round_eval = _household_closure(method, knobs, batched=True)

    def program(r_cand, warm, mu, a_grid, s, P, sigma, beta, amin,
                labor_raw):
        sol, warm_out, dist, supply, demand = jax.vmap(
            lambda w_, m_, r_: round_eval(r_, w_, m_, a_grid, s, P, sigma,
                                          beta, amin, labor_raw),
            in_axes=(0, 0, 0))(warm, mu, r_cand)
        gaps = supply - demand
        quar = ~jnp.isfinite(gaps)
        return {"gap": gaps, "quarantined": quar, "supply": supply,
                "demand": demand, "warm": warm_out, "mu": dist.mu,
                "sol": sol}

    return jax.jit(program)


def fused_batched_round(model: AiyagariModel, r_cand, *,
                        solver: SolverConfig = SolverConfig(),
                        eq: EquilibriumConfig = EquilibriumConfig(batch=8),
                        dist_tol: float = 1e-10, dist_max_iter: int = 10_000,
                        warm=None, mu=None):
    """Evaluate one fused candidate round at `r_cand` ([B]) with the
    quarantine mask. warm/mu default to the cold-start seeding of
    fused_ge_batched_operands evaluated at r_cand's own prices."""
    knobs = fused_knobs(model, solver, eq, dist_tol, dist_max_iter)
    fn = _fused_round(solver.method, knobs)
    dt = model.dtype
    prefs = model.preferences
    tech = model.config.technology
    r_cand = jnp.asarray(r_cand, dt)
    B = int(r_cand.shape[0])
    N, na = model.P.shape[0], model.a_grid.shape[0]
    if warm is None:
        if solver.method == "vfi":
            warm = jnp.zeros((B, N, na), dt)
        else:
            from aiyagari_tpu.solvers.egm import initial_consumption_guess

            w0 = wage_from_r(r_cand, tech.alpha, tech.delta)
            warm = jax.vmap(initial_consumption_guess,
                            in_axes=(None, None, 0, 0))(model.a_grid,
                                                        model.s, r_cand, w0)
    if mu is None:
        mu = jnp.full((B, N, na), 1.0 / (N * na), dt)
    sc = lambda x: jnp.asarray(x, dt)
    return fn(r_cand, warm, mu, model.a_grid, model.s, model.P,
              sc(prefs.sigma), sc(prefs.beta), sc(model.amin),
              sc(model.labor_raw))
