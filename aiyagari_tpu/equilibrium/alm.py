"""Krusell-Smith outer loop: fixed point on the aggregate law of motion (ALM).

Host-side loop (Krusell_Smith_VFI.m:138-296): each iteration launches the
device-resident household solver (Howard VFI or EGM), the device-resident
panel simulation, and the on-device two-regime OLS, then applies the damped
coefficient update B <- damping*B_new + (1-damping)*B on host. Shock paths are
drawn once up front with explicit PRNG keys (the reference's unseeded rand
panels, :58-94, made reproducible).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from aiyagari_tpu.config import ALMConfig, BackendConfig, KrusellSmithConfig, SolverConfig
from aiyagari_tpu.models.krusell_smith import KrusellSmithModel
from aiyagari_tpu.ops.accel import host_anderson_step
from aiyagari_tpu.ops.regression import alm_regression
from aiyagari_tpu.sim.ks_distribution import (
    distribution_capital_path,
    initial_distribution,
)
from aiyagari_tpu.sim.ks_panel import (
    simulate_aggregate_shocks,
    simulate_capital_path,
    simulate_employment_panel,
)
from aiyagari_tpu.solvers.ks_egm import solve_ks_egm
from aiyagari_tpu.solvers.ks_vfi import solve_ks_vfi

__all__ = ["KSResult", "solve_krusell_smith"]


# The 4-coefficient ALM fixed point's safeguarded Anderson update now lives
# in the shared acceleration layer (ops/accel.host_anderson_step) next to
# the device-side carry transformers, so the host and device safeguard
# semantics cannot drift apart. Same algorithm, behavior pinned by
# tests/test_ks.py and tests/test_accel.py.
_anderson_step = host_anderson_step


@dataclasses.dataclass
class KSResult:
    """Converged K-S economy: ALM coefficients, household solution, and the
    simulated aggregate path."""

    B: np.ndarray                 # [4] = [b0_good, b1_good, b0_bad, b1_bad]
    r2: np.ndarray                # [2] per-regime ALM fit
    solution: object              # KSSolution
    K_ts: np.ndarray              # [T] simulated aggregate capital path
    z_path: np.ndarray            # [T] aggregate state path
    k_population: np.ndarray      # final cross-section of agent capital
                                  # (empty under the histogram closure)
    iterations: int
    converged: bool
    diff_B: float
    solve_seconds: float
    per_iteration: list
    mu: Optional[np.ndarray] = None   # [2, nk] final (employment, capital)
                                      # histogram under closure="histogram"
    k_grid: Optional[np.ndarray] = None   # [nk] capital grid mu lives on
    # Outer flight record (diagnostics/telemetry.py host_telemetry): the
    # per-iteration diff_B trajectory of the ALM fixed point — same
    # SolveTelemetry shape as the device recorders, one report path.
    telemetry: object = None

    def health(self, model=None) -> dict:
        """Health certificate (diagnostics/health.py): ALM residual-
        trajectory shape, convergence verdict, histogram mass defect."""
        from aiyagari_tpu.diagnostics.health import health_report

        return health_report(self, model=model)


def _default_ks_solver_config(method: str) -> SolverConfig:
    # Reference tolerances: Krusell_Smith_VFI.m:12-13 / Krusell_Smith_EGM.m:12.
    return SolverConfig(
        method=method,
        tol=1e-6,
        max_iter=10_000,
        howard_steps=50,
        improve_every=5,
        relative_tol=(method == "vfi"),
    )


def solve_krusell_smith(
    config: KrusellSmithConfig,
    *,
    method: str = "vfi",
    solver: Optional[SolverConfig] = None,
    alm: ALMConfig = ALMConfig(),
    backend: BackendConfig = BackendConfig(),
    on_iteration: Optional[Callable] = None,
    double_alm: bool = False,
    checkpoint_dir: Optional[str] = None,
    closure: str = "panel",
) -> KSResult:
    """Iterate household solve -> cross-section simulation -> ALM regression to
    a fixed point of the forecasting coefficients B (Krusell_Smith_VFI.m:138-296).

    Stops when max|B_new - B| < alm.tol; damped update otherwise. B starts at
    [0, 1, 0, 1] (:99) — a unit-root forecast in each regime.

    `closure` selects how the cross-section is advanced along the aggregate
    path: "panel" (the reference's alm.population Monte-Carlo households) or
    "histogram" (deterministic Young-method distribution on the capital grid,
    sim/ks_distribution.py — exact given the grid, no sampling noise in the
    regression).

    With checkpoint_dir set, (B, value, policy, cross-section, histories) are
    persisted each outer iteration and a restarted call resumes; shocks are
    regenerated deterministically from alm.seed (SURVEY.md §5.3-5.4).
    """
    if closure not in ("panel", "histogram"):
        raise ValueError(f"unknown closure {closure!r}; expected 'panel' or 'histogram'")
    if alm.acceleration not in ("damped", "anderson"):
        raise ValueError(
            f"unknown alm.acceleration {alm.acceleration!r}; expected 'damped' or 'anderson'"
        )
    if backend.dtype not in ("float32", "float64", "mixed"):
        raise ValueError(
            f"unknown backend.dtype {backend.dtype!r}; expected 'float32', "
            "'float64', or 'mixed'"
        )
    # Honor an f64 request even when global x64 is off — without this the
    # arrays silently truncate to f32, whose sub-cell policy jitter compounds
    # through the 1,100-period simulation into an ALM limit cycle at
    # diff_B ~ 5e-2, far above the reference's 1e-6 (precision_scope
    # docstring; measured on a v5e).
    from aiyagari_tpu.config import precision_scope

    with precision_scope(backend.dtype):
        return _solve_krusell_smith_impl(
            config, method=method, solver=solver, alm=alm, backend=backend,
            on_iteration=on_iteration, double_alm=double_alm,
            checkpoint_dir=checkpoint_dir, closure=closure,
        )


def _solve_krusell_smith_impl(
    config: KrusellSmithConfig,
    *,
    method: str,
    solver: Optional[SolverConfig],
    alm: ALMConfig,
    backend: BackendConfig,
    on_iteration: Optional[Callable],
    double_alm: bool,
    checkpoint_dir: Optional[str],
    closure: str,
) -> KSResult:
    use_histogram = closure == "histogram"
    t0 = time.perf_counter()
    # Mixed-precision design (BackendConfig.dtype docstring). Measured on the
    # v5e at the reference scale: the household fixed point costs the SAME in
    # f32 and f64 (~0.09 s warm — it is op-latency-bound at [4,4,100], not
    # FLOP-bound), while the 1,100-step cross-section scan is 18x slower in
    # emulated f64 (1.68 s vs 0.094 s; 120x at k_size=1000). And the f32 ALM
    # blocker is household-side: sub-cell policy jitter (full-f32 limit
    # cycles at diff_B ~ 5e-2; f32-house+f64-sim floors at ~2e-3), whereas
    # the simulation is a DETERMINISTIC map of (policy, shocks) whose f32
    # rounding is a fixed O(eps) bias, not compounding noise. So "mixed"
    # puts f64 where it is free and needed (household solve, regression) and
    # f32 where it pays (the simulation scan): phase 1 advances the
    # cross-section in f32; if diff_B ever stalls above tol (the f32-sim
    # bias floor — not observed at the shipped scales), phase 2 switches the
    # simulation to f64 and polishes. The regression always runs in f64 on
    # the (cast) simulated path.
    mixed = backend.dtype == "mixed"
    master_dtype = jnp.float32 if backend.dtype == "float32" else jnp.float64
    model = KrusellSmithModel.from_config(config, master_dtype)
    dtype = model.dtype                  # household solve always in master dtype
    sim_dtype = jnp.float32 if mixed else master_dtype   # may switch to f64

    def sim_tables():
        # Casts of the master tables at the CURRENT simulation dtype (cast,
        # not rebuilt: the policy is tabulated on the master knots, and a
        # rebuild would shift them by rounding).
        return (model.k_grid.astype(sim_dtype), model.K_grid.astype(sim_dtype),
                model.eps_trans.astype(sim_dtype))

    k_grid_sim, K_grid_sim, eps_trans_sim = sim_tables()
    solver = solver or _default_ks_solver_config(method)
    prefs = config.preferences
    tech = config.technology
    sh = config.shocks

    key = jax.random.PRNGKey(alm.seed)
    k_z, k_eps = jax.random.split(key)
    z_path = simulate_aggregate_shocks(model.pz, k_z, T=alm.T)
    panel_sharding = None
    # Grid-axis mesh (BackendConfig.mesh_axes containing "grid"): the
    # [ns, nK, nk] household fixed point runs DISTRIBUTED over the fine
    # k-axis — ring-assembled knot slabs for EGM (solvers/ks_egm_sharded.py)
    # and the replicated-table/local-candidate program for VFI
    # (solvers/ks_vfi_sharded.py; SURVEY.md §2.4(1)). Unsound geometry (nk
    # not divisible, shards too thin, or — EGM only, whose slab positioning
    # is analytic — a non-power k-grid) silently uses the single-device
    # solver, like the Aiyagari config route.
    grid_mesh = None
    mesh = None
    if backend.mesh_axes:
        from aiyagari_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(backend.mesh_axes, backend.mesh_shape or None)
        if ("grid" in backend.mesh_axes and method in ("egm", "vfi")
                and config.k_size % int(mesh.shape["grid"]) == 0
                and config.k_size // int(mesh.shape["grid"]) >= 16
                and (method == "vfi" or config.k_power > 0)):
            grid_mesh = mesh
    if use_histogram:
        eps_panel = None
    else:
        # Drawn with the MASTER-dtype probabilities: the uniform stream (and
        # so the realized panel) must be identical across dtype policies —
        # under "mixed" an f32 draw would be a different Monte-Carlo sample,
        # shifting B by sampling error O(1e-2), dwarfing any arithmetic
        # difference. One-time cost; the panel itself is int32.
        eps_panel = simulate_employment_panel(
            z_path, model.eps_trans, sh.u_good, sh.u_bad, k_eps, T=alm.T,
            population=alm.population,
        )
        # Device-mesh placement: with backend.mesh_axes containing "agents",
        # the employment panel and the capital cross-section are sharded over
        # the mesh so the per-step policy evaluation data-parallelizes and the
        # K=mean(k) reduction lowers to a psum over ICI (SURVEY.md §2.4).
        if mesh is not None and "agents" in backend.mesh_axes:
            from aiyagari_tpu.parallel.mesh import agents_sharding

            eps_panel = jax.device_put(eps_panel, agents_sharding(mesh, batch_axis=1))
            panel_sharding = agents_sharding(mesh, batch_axis=0)

    ns, nK, nk = model.n_states, config.K_size, config.k_size
    # Initial policy 0.9*k and implied consistent value guess (Krusell_Smith_VFI.m:97-98).
    k_opt = 0.9 * jnp.broadcast_to(model.k_grid[None, None, :], (ns, nK, nk)).astype(dtype)
    value = jnp.log(jnp.maximum(0.1 / 0.9 * k_opt, 1e-12)) / (1.0 - prefs.beta)
    # Initial cross-section at K_grid[0] (:100): Monte-Carlo households for the
    # panel closure, an (employment, capital) histogram for the Young closure.
    if use_histogram:
        # Period-1 unemployment rate: ONE host read of z_path[0], reused by
        # the per-round rescale below (a per-round read costs a transport
        # round trip each iteration; the panel closure never needs it).
        u0_hist = sh.u_good if int(z_path[0]) == 0 else sh.u_bad  # noqa: AIYA202 — documented ONE setup read (comment above)
        cross = initial_distribution(k_grid_sim, K_grid_sim, u0_hist, sim_dtype)
    else:
        cross = jnp.full((alm.population,), float(model.K_grid[0]), sim_dtype)  # noqa: AIYA202 — one-time setup fetch, outside the round loop
        if panel_sharding is not None:
            cross = jax.device_put(cross, panel_sharding)
    B = np.array([0.0, 1.0, 0.0, 1.0])

    records = []
    start_it = 0
    mgr = None
    B_hist: list = []
    G_hist: list = []
    # Mixed-phase switch state (part of the iterate trajectory, like the
    # Anderson history — checkpointed and restored with it).
    best_f32 = np.inf   # best diff_B seen in the mixed f32 phase
    f32_stall = 0       # consecutive rounds without meaningful f32 progress
    f32_in_band = False  # diff_B has entered the near-convergence band
    house_tol_0 = solver.tol  # tightened to alm.tol/10 at the phase switch
    if checkpoint_dir is not None:
        from aiyagari_tpu.io_utils.checkpoint import CheckpointManager, config_fingerprint

        # Panel closure keeps the pre-closure checkpoint name so existing
        # checkpoints still resume (their cross-section key is handled below).
        ckpt_name = f"ks_{solver.method}" if closure == "panel" else f"ks_{solver.method}_histogram"
        mgr = CheckpointManager(
            checkpoint_dir, ckpt_name,
            fingerprint=config_fingerprint(config, solver, alm),
        )
        resumed = mgr.restore()
        if resumed is not None:
            sc, arrays = resumed
            B = np.asarray(sc["B"])
            records = sc["records"]
            start_it = min(sc["iteration"] + 1, alm.max_iter - 1)
            records = records[:start_it]
            # Mixed runs resume into the phase they checkpointed in (a resume
            # mid-polish must not drop back to the f32 sim and re-stall).
            if mixed and sc.get("sim_phase") == "float64":
                sim_dtype = jnp.float64
                k_grid_sim, K_grid_sim, eps_trans_sim = sim_tables()
            # Sharded checkpoints (the mesh routes) restore shard-by-shard
            # straight onto the devices (io_utils/checkpoint.restore_array
            # — no host materialization); plain entries as before.
            from aiyagari_tpu.io_utils.checkpoint import restore_array

            k_sharding = None
            if grid_mesh is not None:
                from aiyagari_tpu.parallel.mesh import named_sharding

                k_sharding = named_sharding(grid_mesh, None, None, "grid")
            def _restore(name, sharding, cast):
                # restore_array handles shard-exact placement, resharding,
                # and device_put of plain entries when a sharding is given;
                # only the meshless case needs the host->device cast here.
                v = restore_array(sc, arrays, name, sharding=sharding,
                                  dtype=np.dtype(str(jnp.dtype(cast))))
                return jnp.asarray(v, cast) if isinstance(v, np.ndarray) else v

            value = _restore("value", k_sharding, dtype)
            k_opt = _restore("k_opt", k_sharding, dtype)
            # legacy checkpoints stored the cross-section as "k_population"
            cross_name = "cross" if ("cross" in arrays or "cross__shard0"
                                     in arrays) else "k_population"
            cross = _restore(cross_name, panel_sharding, sim_dtype)
            # Anderson mixing history (short: depth+1 entries) — persisted so
            # a resume continues extrapolating from the pre-crash trajectory
            # instead of silently re-warming with damped steps. Absent in
            # legacy checkpoints (-> empty, the cold-start behavior).
            B_hist = [np.asarray(b, np.float64) for b in sc.get("B_hist", [])]
            G_hist = [np.asarray(g, np.float64) for g in sc.get("G_hist", [])]
            best_f32 = float(sc.get("best_f32", np.inf))
            f32_stall = int(sc.get("f32_stall", 0))
            f32_in_band = bool(sc.get("f32_in_band", False))
            # A resume mid-finishing-phase must keep the tightened household
            # tolerance (set at the f32 -> f64 switch) — reverting to the
            # loose tol would re-introduce the solver-noise hovering, or
            # accept a B still carrying household-tolerance bias. Absent in
            # legacy checkpoints (-> the configured tol).
            house_tol_0 = float(sc.get("house_tol", solver.tol))

    converged = False
    diff_B = np.inf
    r2 = np.zeros(2)
    sol = None
    house_tol = house_tol_0
    for it in range(start_it, alm.max_iter):
        it_t0 = time.perf_counter()
        phase_switched = False      # set when THIS round triggers f32 -> f64
        B_dev = jnp.asarray(B, dtype)
        if solver.method == "vfi":
            vfi_kw = dict(
                theta=prefs.sigma, beta=prefs.beta, mu=config.mu, l_bar=config.l_bar,
                delta=tech.delta, k_min=config.k_min, k_max=config.k_max,
                tol=house_tol, max_iter=solver.max_iter,
                howard_steps=solver.howard_steps, improve_every=solver.improve_every,
                golden_iters=solver.golden_iters, relative_tol=solver.relative_tol,
            )
            if grid_mesh is not None:
                from aiyagari_tpu.solvers.ks_vfi_sharded import solve_ks_vfi_sharded

                sol = solve_ks_vfi_sharded(
                    grid_mesh, value, k_opt, B_dev, model.k_grid,
                    model.K_grid, model.P, model.r_table, model.w_table,
                    model.eps_by_state, **vfi_kw,
                )
            else:
                sol = solve_ks_vfi(
                    value, k_opt, B_dev, model.k_grid, model.K_grid, model.P,
                    model.r_table, model.w_table, model.eps_by_state,
                    progress_every=solver.progress_every, **vfi_kw,
                )
            value = sol.value
        elif solver.method == "egm":
            egm_kw = dict(
                theta=prefs.sigma, beta=prefs.beta, mu=config.mu, l_bar=config.l_bar,
                delta=tech.delta, k_min=config.k_min, k_max=config.k_max,
                tol=house_tol, max_iter=solver.max_iter, double_alm=double_alm,
            )
            sol = None
            if grid_mesh is not None:
                from aiyagari_tpu.solvers.ks_egm_sharded import solve_ks_egm_sharded

                sol, escaped = solve_ks_egm_sharded(
                    grid_mesh, k_opt, B_dev, model.k_grid, model.K_grid,
                    model.P, model.r_table, model.w_table, model.eps_by_state,
                    model.z_by_state, model.L_by_state, tech.alpha,
                    grid_power=float(config.k_power), **egm_kw,
                )
                if escaped:
                    # Slab overflow: the standard host-level fallback to the
                    # single-device route (solve_aiyagari_egm_safe's contract).
                    sol = None
            if sol is None:
                sol = solve_ks_egm(
                    k_opt, B_dev, model.k_grid, model.K_grid, model.P,
                    model.r_table, model.w_table, model.eps_by_state,
                    model.z_by_state, model.L_by_state, tech.alpha,
                    progress_every=solver.progress_every, **egm_kw,
                )
        else:
            raise ValueError(f"unknown method {solver.method!r}")
        k_opt = sol.k_opt

        # The policy and cross-section enter the simulation in sim_dtype
        # (no-op casts except under "mixed", where the f64 household policy
        # feeds the f32 cross-section scan — see the design note above).
        k_opt_sim = sol.k_opt.astype(sim_dtype)
        cross = cross.astype(sim_dtype)
        if use_histogram:
            # Warm-starting reuses last iteration's capital distribution, but
            # the scan's conditional employment chains assume the employment
            # marginal is u(z_0) at t=0 (the final-period marginal is
            # u(z_{T-1})) — rescale the rows so the exact-u(z_t) invariant
            # holds every iteration. Idempotent on the first pass.
            target = jnp.asarray([1.0 - u0_hist, u0_hist], sim_dtype)
            row_mass = jnp.sum(cross, axis=1, keepdims=True)
            cross = cross * (target[:, None] / jnp.maximum(row_mass, 1e-300))
            # Push-forward backend: an EXPLICIT SolverConfig.pushforward
            # wins; under the "auto" default the route splits on the SIM
            # dtype — resolve_backend's f32_sim override (the cumsum-bias
            # rationale lives on its docstring; the split itself lives
            # THERE per the AIYA204 route-resolution discipline, so this
            # module re-hardcodes nothing).
            from aiyagari_tpu.ops.pushforward import resolve_backend

            pf_knob = resolve_backend(
                solver.pushforward if solver is not None else "auto",
                na=int(k_grid_sim.shape[-1]), dtype=sim_dtype,
                f32_sim=sim_dtype == jnp.float32)
            K_ts, cross_new = distribution_capital_path(
                k_opt_sim, k_grid_sim, K_grid_sim, z_path, eps_trans_sim,
                cross, T=alm.T, pushforward=pf_knob,
            )
        else:
            K_ts, cross_new = simulate_capital_path(
                k_opt_sim, k_grid_sim, K_grid_sim, z_path, eps_panel,
                cross, T=alm.T,
                # The k-grid is power-spaced (config.k_power, reference
                # Krusell_Smith_VFI.m:16) — the panel step takes the
                # analytic-bucket interpolation, 1.34x per step at the
                # reference panel (ops/interp.state_policy_interp_power).
                grid_power=float(config.k_power),
            )
        # Regression always in f64: the closed-form normal-equation sums over
        # ~1,000 log-K terms lose ~3 digits in f32, directly polluting B_new
        # at the 1e-6 tolerance; casting the [T] path costs nothing.
        B_new_dev, r2_dev = alm_regression(K_ts.astype(jnp.float64), z_path, alm.discard)
        # ONE batched host fetch per round. The sequential route — five
        # separate reads (B_new, r2, solver iterations/distance, and the
        # whole [T] path pulled just for its mean) — costs ~0.1 s of
        # transport latency EACH on this image's remote-TPU tunnel, most of
        # the measured 0.65 s marginal round (same lesson as the EGM
        # ladder's _fetch_scalars; BENCHMARKS.md round 3).
        B_new, r2, sol_iters, sol_dist, K_mean = jax.device_get(
            (B_new_dev, r2_dev, sol.iterations, sol.distance,
             jnp.mean(K_ts[alm.discard:])))
        B_new = np.asarray(B_new, np.float64)
        r2 = np.asarray(r2, np.float64)
        r2_good, r2_bad = r2.tolist()
        diff_B = float(np.max(np.abs(B_new - B)))

        rec = {
            "iteration": it,
            "B": B_new.tolist(),
            "diff_B": diff_B,
            "r2_good": r2_good,
            "r2_bad": r2_bad,
            "solver_iterations": int(sol_iters),
            "solver_distance": float(sol_dist),
            "K_mean": float(K_mean),
            "seconds": time.perf_counter() - it_t0,
            "house_dtype": str(np.dtype(dtype)),
            "sim_dtype": str(np.dtype(sim_dtype)),
            # The tolerance THIS round's household solve ran at — tightened
            # to alm.tol/10 by the mixed-phase switch, so switch behavior is
            # observable in the records (and testable across a resume).
            "house_tol": float(house_tol),
        }
        records.append(rec)
        if on_iteration is not None:
            on_iteration(rec)

        if diff_B < alm.tol:
            converged = True
            B = B_new
            cross = cross_new
            break
        if mixed and np.dtype(sim_dtype) == np.float32:
            # Fallback phase switch: if the f32-sim fixed point ever stalls
            # above tol (consecutive rounds within 10% of the best diff so
            # far), finish with the f64 simulation. Not expected at the
            # shipped scales — the f32 sim's rounding is a fixed O(eps)
            # bias, below the 1e-6 tolerance — but a user scale where the
            # bias floor bites must converge, not limit-cycle. Two
            # thresholds: 2 stalled rounds once diff_B < 1e-2 (the normal
            # near-convergence band), 6 above it — a scale where the f32
            # bias floor itself exceeds 1e-2 must still trigger the switch,
            # and the higher count absorbs Anderson's early non-monotone
            # rounds that the 1e-2 gate used to filter (ADVICE round 2).
            if diff_B < 1e-2 and not f32_in_band:
                # First crossing into the band re-anchors the tracker: an
                # early Anderson dip must not carry an above-band stall
                # count (or a transiently low best) into the band, where
                # the stricter 2-round trigger applies.
                f32_in_band, f32_stall, best_f32 = True, 0, diff_B
            else:
                stalled = diff_B >= 0.9 * best_f32
                f32_stall = f32_stall + 1 if stalled else 0
                best_f32 = min(best_f32, diff_B)
            # Trigger threshold follows the CURRENT round's band, not the
            # latch: an Anderson overshoot back above 1e-2 after a dip is
            # normal non-monotone progress and gets the loose 6-count, the
            # same filter those rounds had before any dip.
            if f32_stall >= (2 if diff_B < 1e-2 else 6):
                sim_dtype = jnp.float64
                k_grid_sim, K_grid_sim, eps_trans_sim = sim_tables()
                # The fixed-point map itself just changed (f32 -> f64
                # simulation): Anderson extrapolation across the switch
                # mixes residuals of the two maps — measured 14 hovering
                # rounds at 2e-6..1.4e-5 after an otherwise-clean switch at
                # reference scale. Restart the mixing history AND keep this
                # round's (B, G_f32(B)) pair out of it — G was evaluated
                # under the old map, and appending it would hand the first
                # f64 round a cross-map residual difference anyway. The
                # switch round updates B damped; Anderson re-accelerates on
                # the new map's own residuals from the next round.
                B_hist.clear()
                G_hist.clear()
                phase_switched = True
                # The hovering this phase exists to break is also
                # solver-noise-bound: a household solve at tol injects
                # O(tol) noise into B_new, so with house_tol == alm.tol the
                # finishing phase wanders at 1-7e-6 for ~9 rounds (measured,
                # EGM at reference scale). Tighten the household tolerance
                # an order below the ALM target for the finishing rounds —
                # warm-started solves pay a handful of extra sweeps.
                house_tol = min(house_tol, 0.1 * alm.tol)
        if alm.acceleration == "anderson" and not phase_switched:
            B_hist.append(B.copy())
            G_hist.append(B_new.copy())
            B_hist, G_hist = B_hist[-(alm.anderson_depth + 1):], G_hist[-(alm.anderson_depth + 1):]
            B = _anderson_step(B_hist, G_hist, alm.damping, alm.anderson_depth)
        else:
            B = alm.damping * B_new + (1.0 - alm.damping) * B
        # Reference warm-starts the cross-section across B-iterations by
        # reusing k_population (:100, :246-247); we do the same (for both
        # the agent panel and the histogram).
        cross = cross_new
        if mgr is not None:
            mgr.save(
                scalars={"iteration": it, "B": B.tolist(), "records": records,
                         "B_hist": [b.tolist() for b in B_hist],
                         "G_hist": [g.tolist() for g in G_hist],
                         "sim_phase": str(np.dtype(sim_dtype)),
                         "best_f32": float(best_f32), "f32_stall": f32_stall,
                         "f32_in_band": f32_in_band,
                         "house_tol": float(house_tol)},
                # Device arrays pass through: the mesh routes' sharded
                # value/policy/cross-section are packed PER SHARD by
                # save_checkpoint (no host gather).
                arrays={"value": value, "k_opt": k_opt, "cross": cross},
            )

    if mgr is not None:
        mgr.delete()   # run finished; a later call should start fresh
    from aiyagari_tpu.diagnostics.telemetry import host_telemetry

    K_ts_np = np.asarray(K_ts)
    return KSResult(
        B=B,
        r2=r2,
        solution=sol,
        K_ts=K_ts_np,
        z_path=np.asarray(z_path),
        k_population=(np.asarray([]) if use_histogram else np.asarray(cross)),
        iterations=len(records),
        converged=converged,
        diff_B=diff_B,
        solve_seconds=time.perf_counter() - t0,
        per_iteration=records,
        mu=(np.asarray(cross) if use_histogram else None),
        k_grid=np.asarray(model.k_grid),
        telemetry=host_telemetry([r["diff_B"] for r in records]),
    )
