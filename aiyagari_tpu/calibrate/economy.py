"""Differentiable θ → steady-state map: the forward model of calibration.

The chain, every stage on device and reverse-AD-transparent:

  (β, σ, ρ, σ_e)
    → Rouwenhorst discretization        (traceable port of utils/markov.py)
    → primal GE rate by device bisection (lax.fori_loop, warm-started,
      all inputs stop_gradient — the nondifferentiable primal)
    → scalar IFT through market clearing (ops/implicit.two_point_root_vjp)
    → wrapped household + distribution solves at the differentiable rate
      (solvers/egm.solve_aiyagari_egm_implicit,
       sim/distribution.stationary_distribution_implicit)
    → (r, w, μ, policies, K) with exact gradients to all four parameters.

Frozen by design: the ASSET GRID and the income-state COUNT. A θ-dependent
grid would make array shapes (and the grid's s_min-dependent bounds) move
under the optimizer; calibration therefore fits the economy ON the base
model's grid, which is the same contract a sweep over _SWEEP_PARAMS
scenarios already has (dispatch._scenario_config changes parameters, never
shapes). The income discretization is pinned to method="rouwenhorst"
because its stationary distribution has a CLOSED FORM independent of ρ
when p = q = (1+ρ)/2 — the binomial(n−1, 1/2) weights — so ∂π/∂ρ = 0
analytically and the whole s-normalization stays differentiable without
differentiating an eigenvector solve (tauchen's normal-CDF bin masses
would be differentiable too, but its stationary π needs the lstsq solve
utils/markov.py runs on host).
"""

from __future__ import annotations

from functools import partial
from math import comb

import jax
import jax.numpy as jnp
from jax import lax

from aiyagari_tpu.ops.implicit import two_point_root_vjp
from aiyagari_tpu.sim.distribution import (
    aggregate_capital,
    stationary_distribution,
    stationary_distribution_implicit,
)
from aiyagari_tpu.solvers.egm import (
    initial_consumption_guess,
    solve_aiyagari_egm,
    solve_aiyagari_egm_implicit,
)
from aiyagari_tpu.utils.firm import capital_demand, wage_from_r

__all__ = ["income_process_implicit", "steady_state_map"]


def income_process_implicit(rho, sigma_e, n_states: int):
    """Differentiable Rouwenhorst discretization of log-AR(1) income:
    (ρ, σ_e) → (s [n], P [n,n], π [n], labor_raw), matching
    utils/markov.rouwenhorst + normalized_labor (the numpy reference) at
    float precision while staying traceable.

    The recursive overlay builds P_n from P_{n-1} with four shifted adds
    (unrolled python loop — n_states is a static shape); π is the
    closed-form binomial(n−1, 1/2), exact for p = q and independent of ρ
    (see module docstring). s is normalized so E_π[s] = 1, with the
    pre-normalization aggregate labor_raw carried for the demand curve —
    the same split as utils/markov.normalized_labor.
    """
    p = (1.0 + rho) / 2.0
    P = jnp.stack([jnp.stack([p, 1.0 - p]), jnp.stack([1.0 - p, p])])
    for m in range(3, n_states + 1):
        Pn = jnp.zeros((m, m), P.dtype)
        Pn = Pn.at[:-1, :-1].add(p * P)
        Pn = Pn.at[:-1, 1:].add((1.0 - p) * P)
        Pn = Pn.at[1:, :-1].add((1.0 - p) * P)
        Pn = Pn.at[1:, 1:].add(p * P)
        Pn = Pn.at[1:-1, :].multiply(0.5)
        P = Pn
    psi = sigma_e * jnp.sqrt(n_states - 1.0)
    l_grid = psi * jnp.linspace(-1.0, 1.0, n_states)
    pi = jnp.asarray([comb(n_states - 1, k) for k in range(n_states)],
                     l_grid.dtype) / (2.0 ** (n_states - 1))
    s_raw = jnp.exp(l_grid)
    labor_raw = jnp.dot(s_raw, pi)
    return s_raw / labor_raw, P, pi, labor_raw


@partial(jax.jit, static_argnames=(
    "n_states", "alpha", "delta", "amin", "bisect_iters", "hh_tol",
    "hh_max_iter", "dist_tol", "dist_max_iter", "adjoint_tol",
    "adjoint_max_iter", "r_low"))
def steady_state_map(beta, sigma, rho, sigma_e, a_grid, *, n_states: int,
                     alpha: float, delta: float, amin: float,
                     r_low: float = -0.02, bisect_iters: int = 45,
                     hh_tol: float = 1e-12, hh_max_iter: int = 6000,
                     dist_tol: float = 1e-13, dist_max_iter: int = 40_000,
                     adjoint_tol: float = 1e-13,
                     adjoint_max_iter: int = 5000) -> dict:
    """The differentiable steady state at θ = (β, σ, ρ, σ_e) on a FROZEN
    asset grid. Returns {"r", "w", "K", "mu", "policy_c", "policy_k", "s",
    "P", "labor_raw", "gap"} — all carrying exact gradients to θ via the
    IFT (module docstring has the chain). Fully vmappable over θ lanes:
    the primal bisection is a fixed-trip fori_loop whose household and
    distribution solves warm-start from the previous midpoint.

    `gap` is the residual market-clearing excess at the returned rate —
    the fit's convergence evidence, ~(bracket width) · (supply slope)
    after bisect_iters halvings of the [r_low, 1/β−1] bracket.
    """
    sg = lax.stop_gradient
    dt = jnp.asarray(a_grid).dtype
    s, P, _, labor_raw = income_process_implicit(rho, sigma_e, n_states)
    # The discretization's linspace/binomial constants are strongly-typed
    # f64 under x64 — pin the whole economy to the GRID's dtype so the f32
    # rung of the calibration ladder stays f32 end to end.
    s = s.astype(dt)
    P = P.astype(dt)
    labor_raw = labor_raw.astype(dt)

    # --- primal: device bisection on the frozen-θ economy -------------
    s0, P0 = sg(s), sg(P)
    beta0, sigma0, labor0 = sg(beta), sg(sigma), sg(labor_raw)
    lo0 = jnp.asarray(r_low, dt)
    hi0 = 1.0 / beta0 - 1.0 - jnp.asarray(1e-6, dt)
    mid0 = 0.5 * (lo0 + hi0)
    C_init = initial_consumption_guess(a_grid, s0, mid0,
                                       wage_from_r(mid0, alpha, delta))
    mu_init = jnp.full(C_init.shape, 1.0 / C_init.size, dt)

    def household(r, C_ws, mu_ws):
        w = wage_from_r(r, alpha, delta)
        sol = solve_aiyagari_egm(C_ws, a_grid, s0, P0, r, w, amin,
                                 sigma=sigma0, beta=beta0, tol=hh_tol,
                                 max_iter=hh_max_iter, egm_kernel="xla")
        d = stationary_distribution(sol.policy_k, a_grid, P0, tol=dist_tol,
                                    max_iter=dist_max_iter, mu_init=mu_ws)
        gap = (aggregate_capital(d.mu, a_grid)
               - capital_demand(r, labor0, alpha, delta))
        return gap, sol.policy_c, d.mu

    def body(carry, _):
        lo, hi, C_ws, mu_ws = carry
        mid = 0.5 * (lo + hi)
        gap, C_ws, mu_ws = household(mid, C_ws, mu_ws)
        lo = jnp.where(gap > 0.0, lo, mid)
        hi = jnp.where(gap > 0.0, mid, hi)
        return (lo, hi, C_ws, mu_ws), None

    # scan, not fori_loop: fori's lowered counter is a weak-typed scalar
    # carry (the AIYA106 silent-recompile hazard); the bisection carry
    # here is fully typed and the trip count static.
    (lo, hi, C_ws, mu_ws), _ = lax.scan(
        body, (lo0, hi0, C_init, mu_init), None, length=bisect_iters)
    r_star = 0.5 * (lo + hi)
    C_ws, mu_ws = sg(C_ws), sg(mu_ws)

    # --- scalar IFT through market clearing ---------------------------
    # Every array the gap function needs rides IN the params pytree: a
    # custom_vjp backward rule must not close over tracers, and this whole
    # map runs under jit + vmap (dispatch.calibrate). The warm starts and
    # the grid enter stop_gradient'd — they seed primal solves only.
    theta = {"beta": beta, "sigma": sigma, "s": s, "P": P,
             "labor_raw": labor_raw, "a_grid": a_grid,
             "C_ws": C_ws, "mu_ws": mu_ws}

    def solves_at(r, p):
        w = wage_from_r(r, alpha, delta)
        sol = solve_aiyagari_egm_implicit(
            p["C_ws"], p["a_grid"], p["s"], p["P"], r, w, amin,
            sigma=p["sigma"], beta=p["beta"], tol=hh_tol,
            max_iter=hh_max_iter, adjoint_tol=adjoint_tol,
            adjoint_max_iter=adjoint_max_iter)
        d = stationary_distribution_implicit(
            sol.policy_k, p["a_grid"], p["P"], tol=dist_tol,
            max_iter=dist_max_iter, mu_init=p["mu_ws"],
            adjoint_tol=adjoint_tol, adjoint_max_iter=adjoint_max_iter)
        return sol, d

    def gap_fn(r, p):
        sol, d = solves_at(r, p)
        return (aggregate_capital(d.mu, p["a_grid"])
                - capital_demand(r, p["labor_raw"], alpha, delta))

    r = two_point_root_vjp(gap_fn, r_star, theta)

    # --- differentiable steady state at the differentiable rate -------
    sol, d = solves_at(r, theta)
    K = aggregate_capital(d.mu, a_grid)
    gap = K - capital_demand(r, labor_raw, alpha, delta)
    return {"r": r, "w": wage_from_r(r, alpha, delta), "K": K, "mu": d.mu,
            "policy_c": sol.policy_c, "policy_k": sol.policy_k, "s": s,
            "P": P, "labor_raw": labor_raw, "gap": gap}
