"""On-device moment matching: multi-lane Adam + BFGS polish with per-lane
quarantine, on the precision ladder.

The fit runs L independent lanes (different starting points of the SAME
objective) as one vmapped program — lanes are the batching unit
dispatch.calibrate shards over the scenario mesh axis. Three design rules,
all inherited from the solver stack's failure discipline:

  quarantine, not NaN-poisoning — a lane whose loss or gradient goes
      non-finite (a divergent inner solve, an adjoint past its spectral
      radius) is masked OUT of every subsequent moment/parameter update:
      its Adam moments stop ingesting, its z freezes at the last finite
      iterate, and the vmapped reduction over lanes never sees its NaN.
      The lane stays visible in the result (alive=False) — failure is
      data, not an exception (cf. serve quarantine, AIYA107 NaN-exit).

  precision ladder — the Adam phase runs its early steps in f32 (each
      gradient is a full IFT adjoint chain: ~2× the primal solve cost, so
      halving the bytes matters at scale) and switches to f64 for the
      late steps + the BFGS polish, mirroring ops/precision.py's
      hot-then-polish staging of the primal solves.

  trust the polish, not the trajectory — Adam gets the iterate into the
      basin; the quadratic tail is finished by jax.scipy BFGS, and a
      polish result is accepted PER LANE only when finite and strictly
      better than the Adam iterate it started from.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import jax.scipy.optimize  # noqa: F401  (lazy submodule: explicit import)
import numpy as np

__all__ = ["FitResult", "fit"]


@dataclasses.dataclass(frozen=True)
class FitResult:
    """Host-side fit summary. Arrays are per-lane [L]; `z` is [L, d]."""

    z: np.ndarray
    loss: np.ndarray
    grad_norm: np.ndarray
    alive: np.ndarray           # never quarantined
    converged: np.ndarray       # alive AND inside loss/grad tolerance
    steps: int                  # Adam steps actually taken
    grad_evals: int
    status: str                 # "converged" | "max_iter"
    best_lane: int

    @property
    def best_z(self) -> np.ndarray:
        return self.z[self.best_lane]


def _value_and_grad_batch(fn):
    return jax.jit(jax.vmap(jax.value_and_grad(fn)))


def fit(loss_for, z0, *, steps: int = 40, lr: float = 0.1,
        loss_tol: float = 1e-9, gtol: float = 1e-5,
        stage_dtypes=("float32", "float64"), stage_split: float = 0.4,
        polish: bool = True, polish_maxiter: int = 40,
        on_step=None) -> FitResult:
    """Fit z (lanes × params, [L, d]) against `loss_for`.

    `loss_for(dtype_str)` returns the differentiable per-lane objective
    z[d] → scalar at that dtype — the factory shape lets the ladder
    rebuild the traced program per stage instead of casting inside one.
    `on_step(step, loss [L], alive [L])` fires on the host after every
    Adam step (numpy arrays) — dispatch.calibrate hangs the per-step
    ledger events and gauges on it.
    """
    z = jnp.asarray(z0, jnp.float64)
    if z.ndim != 2:
        raise ValueError(f"z0 must be [lanes, params], got shape {z.shape}")
    lanes = z.shape[0]
    alive = jnp.ones((lanes,), bool)
    b1, b2, eps = 0.9, 0.999, 1e-8
    grad_evals = 0
    taken = 0
    loss = jnp.full((lanes,), jnp.inf)
    gnorm = jnp.full((lanes,), jnp.inf)

    stages = []
    if len(stage_dtypes) > 1:
        hot = int(round(steps * stage_split))
        stages.append((stage_dtypes[0], hot))
        stages.append((stage_dtypes[-1], steps - hot))
    else:
        stages.append((stage_dtypes[0], steps))

    for dtype_str, n_steps in stages:
        if n_steps <= 0:
            continue
        vg = _value_and_grad_batch(loss_for(dtype_str))
        dt = jnp.dtype(dtype_str)
        zs = z.astype(dt)
        m = jnp.zeros_like(zs)
        v = jnp.zeros_like(zs)
        for t in range(1, n_steps + 1):
            loss_s, g = vg(zs)
            grad_evals += lanes
            taken += 1
            finite = jnp.isfinite(loss_s) & jnp.all(jnp.isfinite(g), axis=1)
            alive = alive & finite
            loss = jnp.where(alive, loss_s.astype(jnp.float64), loss)
            gnorm = jnp.where(
                alive,
                jnp.linalg.norm(g.astype(jnp.float64), axis=1), gnorm)
            # Convergence is judged at THIS iterate, before the update: a
            # lane already inside tolerance freezes here, so the returned
            # z is the iterate its reported loss/grad_norm belong to (not
            # one Adam step past it).
            done = ~alive | (loss <= loss_tol) | (gnorm <= gtol)
            upd_mask = (alive & ~done)[:, None]
            g = jnp.where(upd_mask, g, 0.0)
            m = b1 * m + (1.0 - b1) * g
            v = b2 * v + (1.0 - b2) * g * g
            mh = m / (1.0 - b1 ** t)
            vh = v / (1.0 - b2 ** t)
            zs = jnp.where(upd_mask,
                           zs - lr * mh / (jnp.sqrt(vh) + eps), zs)
            if on_step is not None:
                on_step(taken, np.asarray(loss), np.asarray(alive))
            if bool(jnp.all(done)):
                z = zs.astype(jnp.float64)
                break
        z = zs.astype(jnp.float64)
        if bool(jnp.all(~alive | (loss <= loss_tol) | (gnorm <= gtol))):
            break

    if polish and bool(jnp.any(alive & (loss > loss_tol))):
        fn64 = loss_for("float64")

        def _polish_one(z1):
            res = jax.scipy.optimize.minimize(
                fn64, z1, method="BFGS",
                options={"maxiter": polish_maxiter, "gtol": 1e-12})
            return res.x, res.fun

        xs, fs = jax.jit(jax.vmap(_polish_one))(z)
        grad_evals += lanes * polish_maxiter
        better = alive & jnp.isfinite(fs) & (fs < loss) \
            & jnp.all(jnp.isfinite(xs), axis=1)
        z = jnp.where(better[:, None], xs, z)
        loss = jnp.where(better, fs, loss)
        # One last true-gradient read at the accepted iterates.
        loss_s, g = _value_and_grad_batch(fn64)(z)
        grad_evals += lanes
        refreshed = alive & jnp.isfinite(loss_s) \
            & jnp.all(jnp.isfinite(g), axis=1)
        loss = jnp.where(refreshed, loss_s, loss)
        gnorm = jnp.where(refreshed, jnp.linalg.norm(g, axis=1), gnorm)

    converged = alive & ((loss <= loss_tol) | (gnorm <= gtol))
    loss_np = np.asarray(loss)
    best = int(np.argmin(np.where(np.asarray(converged), loss_np, np.inf)))
    if not bool(np.asarray(converged).any()):
        best = int(np.argmin(np.where(np.asarray(alive), loss_np, np.inf)))
    return FitResult(
        z=np.asarray(z), loss=loss_np, grad_norm=np.asarray(gnorm),
        alive=np.asarray(alive), converged=np.asarray(converged),
        steps=taken, grad_evals=grad_evals,
        status="converged" if bool(np.asarray(converged).any())
        else "max_iter",
        best_lane=best)
