"""Parameter transforms + the weighted moment-distance objective.

The optimizer walks an UNCONSTRAINED vector z; the economic parameters are
recovered through smooth bijections that keep every iterate feasible by
construction — no clipping, no barrier terms, no infeasible NaN solves
from an overshooting Adam step:

    β   = sigmoid(z)          ∈ (0, 1)
    σ   = softplus(z)         > 0
    ρ   = tanh(z)             ∈ (−1, 1)
    σ_e = softplus(z)         > 0

The objective is a weighted relative moment distance

    L(z) = Σ_m  w_m · ((m(θ(z)) − target_m) / scale_m)²,

scale_m = max(|target_m|, 0.01) so a near-zero target (an MPC of 0.02)
doesn't blow its term to 1e4× the others, and w_m defaults to 1.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["CALIBRATED_PARAMS", "constrain", "moment_loss", "pack",
           "unconstrain", "unpack"]

# The differentiable parameter set, in canonical z-vector order. This is
# deliberately the IFT-reachable subset of dispatch._SWEEP_PARAMS: grid
# and labor-choice knobs change array shapes (frozen under calibration —
# calibrate/economy.py module docstring), psi/eta belong to the
# endogenous-labor model the differentiable chain doesn't wrap yet.
CALIBRATED_PARAMS = ("beta", "sigma", "rho", "sigma_e")

_MIN_SCALE = 0.01


def _softplus_inv(y):
    # log(expm1(y)), computed as y + log1p(-exp(-y)) for overflow safety.
    return y + jnp.log1p(-jnp.exp(-y))


_TO_PARAM = {
    "beta": jax.nn.sigmoid,
    "sigma": jax.nn.softplus,
    "rho": jnp.tanh,
    "sigma_e": jax.nn.softplus,
}
_TO_Z = {
    "beta": lambda y: jnp.log(y) - jnp.log1p(-y),
    "sigma": _softplus_inv,
    "rho": jnp.arctanh,
    "sigma_e": _softplus_inv,
}


def constrain(name: str, z):
    """Unconstrained z → feasible parameter value."""
    return _TO_PARAM[name](z)


def unconstrain(name: str, value):
    """Feasible parameter value → unconstrained z (the transform inverse)."""
    return _TO_Z[name](jnp.asarray(value))


def pack(theta: dict, names=CALIBRATED_PARAMS):
    """{name: feasible value} → unconstrained z vector [len(names)]."""
    return jnp.stack([unconstrain(n, theta[n]) for n in names])


def unpack(z, names=CALIBRATED_PARAMS) -> dict:
    """Unconstrained z vector → {name: feasible value}."""
    return {n: constrain(n, z[i]) for i, n in enumerate(names)}


def moment_loss(moments: dict, targets: dict, weights=None):
    """Weighted relative moment distance (module docstring). `targets`
    selects which moments enter — keys absent from it cost nothing."""
    weights = weights or {}
    total = jnp.asarray(0.0)
    for name in sorted(targets):
        t = jnp.asarray(targets[name])
        scale = jnp.maximum(jnp.abs(t), _MIN_SCALE)
        w = jnp.asarray(weights.get(name, 1.0))
        total = total + w * ((moments[name] - t) / scale) ** 2
    return total
