"""Gradient-based calibration of Aiyagari economies (ISSUE 17).

Built on the IFT adjoints of ops/implicit.py: `economy.steady_state_map`
is a fully differentiable, vmappable θ → steady state map (differentiable
Rouwenhorst discretization → device bisection for the primal GE rate →
scalar IFT through market clearing → wrapped household/distribution
solves), `moments` computes the calibration targets (wealth Gini, K/Y,
MPC, top-10% share) from the differentiable μ/policy, `loss` maps raw
parameters through constraint-keeping transforms into a weighted moment
distance, and `optimize` fits by Adam (+ BFGS polish) with per-lane
quarantine. The product entry point is dispatch.calibrate; the HTTP front
serves it as POST /calibrate (serve/service.py).
"""

from aiyagari_tpu.calibrate.economy import (
    income_process_implicit,
    steady_state_map,
)
from aiyagari_tpu.calibrate.loss import (
    CALIBRATED_PARAMS,
    constrain,
    moment_loss,
    pack,
    unconstrain,
    unpack,
)
from aiyagari_tpu.calibrate.moments import MOMENTS, model_moments, moments_of
from aiyagari_tpu.calibrate.optimize import FitResult, fit

__all__ = [
    "CALIBRATED_PARAMS",
    "FitResult",
    "MOMENTS",
    "constrain",
    "fit",
    "income_process_implicit",
    "model_moments",
    "moment_loss",
    "moments_of",
    "pack",
    "steady_state_map",
    "unconstrain",
    "unpack",
]
