"""Calibration target moments from the differentiable steady state.

All four moments are smooth (a.e.) functions of (μ, policy, r) computed
with the shared jnp statistics kernels (utils/stats.py — the Lorenz/Gini
machinery is already differentiable: the sort permutation is constant
because the asset grid is sorted and FROZEN under calibration), so
jax.grad flows from a moment distance all the way to (β, σ, ρ, σ_e)
through the IFT adjoints.

  gini        — wealth Gini over the asset marginal μ_a.
  k_y         — capital-to-output ratio at z = 1: Y = K^α · L_raw^(1−α)
                with L_raw the pre-normalization aggregate labor (the same
                labor that scales the demand curve, utils/firm.py).
  mpc         — μ-weighted average marginal propensity to consume out of
                cash-on-hand: forward differences of the consumption
                policy along assets, Δc / ((1+r) Δa) — the (1+r) puts the
                increment in cash-on-hand units so a one-unit windfall
                maps to ∂c/∂coh.
  top10_share — share of wealth held by the top asset decile (Lorenz
                interpolation, utils/stats.weighted_quantile_shares).
"""

from __future__ import annotations

import jax.numpy as jnp

from aiyagari_tpu.utils.stats import weighted_gini, weighted_quantile_shares

__all__ = ["MOMENTS", "model_moments", "moments_of"]

# The serveable target schema (USAGE.md "Gradient-based calibration").
MOMENTS = ("gini", "k_y", "mpc", "top10_share")


def moments_of(state: dict, a_grid, *, alpha: float) -> dict:
    """The four target moments from a steady_state_map state dict (or any
    dict with differentiable "mu", "policy_c", "r", "K", "labor_raw")."""
    mu = state["mu"]
    mu_a = jnp.sum(mu, axis=0)
    K = state["K"]
    Y = K ** alpha * state["labor_raw"] ** (1.0 - alpha)

    C = state["policy_c"]
    da = a_grid[1:] - a_grid[:-1]
    mpc_cells = (C[:, 1:] - C[:, :-1]) / ((1.0 + state["r"]) * da[None, :])
    # Forward differences live on the left knot; the last column reuses the
    # final segment's slope so the weights still sum to 1.
    mpc_grid = jnp.concatenate([mpc_cells, mpc_cells[:, -1:]], axis=1)
    mpc = jnp.sum(mu * mpc_grid)

    shares = weighted_quantile_shares(a_grid, mu_a, n_quantiles=10)
    return {
        "gini": weighted_gini(a_grid, mu_a),
        "k_y": K / Y,
        "mpc": mpc,
        "top10_share": shares[-1] / 100.0,
    }


def model_moments(config, **kwargs) -> dict:
    """Host convenience: the moments of a config's economy at its own
    parameters — the natural way to build a self-consistent target set
    (tests, the planted-recovery bench, USAGE examples). Runs the same
    differentiable chain as the fit, so a calibration against these
    targets starts at zero loss by construction.

    kwargs forward to calibrate.economy.steady_state_map.
    """
    import numpy as np

    from aiyagari_tpu.calibrate.economy import steady_state_map
    from aiyagari_tpu.models.aiyagari import AiyagariModel

    model = AiyagariModel.from_config(config)
    tech = config.technology
    state = steady_state_map(
        jnp.asarray(config.preferences.beta),
        jnp.asarray(config.preferences.sigma),
        jnp.asarray(config.income.rho),
        jnp.asarray(config.income.sigma_e),
        model.a_grid,
        n_states=config.income.n_states, alpha=tech.alpha,
        delta=tech.delta, amin=model.amin, **kwargs)
    moms = moments_of(state, model.a_grid, alpha=tech.alpha)
    return {k: float(np.asarray(v)) for k, v in moms.items()}
