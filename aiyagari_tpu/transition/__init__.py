"""Perfect-foresight transition dynamics (MIT shocks) for the Aiyagari
family: deterministic equilibrium paths after a one-time unanticipated
aggregate shock, truncated at a horizon T where the economy is back at its
stationary equilibrium.

Three layers, bottom-up:

  * path.py      — the path evaluator: backward EGM sweep over time
                   (policies under a given price path) + forward push of the
                   stationary distribution (the implied capital path), as
                   ONE fused device program.
  * jacobian.py  — the sequence-space Jacobian dK/dr at the stationary
                   equilibrium via the fake-news algorithm (one backward
                   jvp pass + one forward expectation pass).
  * mit.py       — solve_transition / solve_transitions_sweep: the outer
                   Newton (or damped) price-path iteration, anchored at the
                   existing stationary solves on both ends.

References: Boppart, Krusell & Mitman (2018) "Exploiting MIT shocks";
Auclert, Bardoczy, Rognlie & Straub (2021) "Using the Sequence-Space
Jacobian" (PAPERS.md). The reference MATLAB scripts have no transition
machinery at all; this subsystem exists because the TPU makes whole-path
evaluation (a T-step lax.scan over HBM-resident grids) and whole-batch
scenario sweeps (vmap over the scenarios mesh axis) cheap.
"""

from aiyagari_tpu.transition.jacobian import (
    fake_news_jacobian,
    newton_jacobian,
)
from aiyagari_tpu.transition.mit import (
    TransitionResult,
    TransitionSweepResult,
    shock_paths,
    solve_transition,
    solve_transitions_sweep,
)
from aiyagari_tpu.transition.path import (
    backward_policies,
    forward_capital,
    transition_path,
)

__all__ = [
    "backward_policies",
    "forward_capital",
    "transition_path",
    "fake_news_jacobian",
    "newton_jacobian",
    "shock_paths",
    "solve_transition",
    "solve_transitions_sweep",
    "TransitionResult",
    "TransitionSweepResult",
]
