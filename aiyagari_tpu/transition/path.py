"""Transition-path evaluator: given a T-period price path, the household
block's whole response as one fused device program.

Two time scans back to back:

  backward_policies — lax.scan (reverse=True) of the dated EGM operator
      (ops/egm.egm_step_transition) from the terminal stationary consumption
      policy: O(T) matmul+interp steps, each identical in shape, so XLA
      compiles ONE loop body for any horizon. Time-varying preferences
      (beta_t, sigma_t) and borrowing limits (amin_t) ride along as [T]
      operand arrays — constant slices when unshocked — so every MIT-shock
      flavor shares the same compiled program.

  forward_capital — lax.scan of the Young-lottery push-forward
      (ops/pushforward.pushforward_step, scatter-free by default; the
      `pushforward` knob selects the backend) from the initial stationary
      distribution, yielding the capital path K_t = E_{mu_t}[a] and the
      end-of-period asset supply A_t = E_{mu_t}[policy_t].

Both are wrapped in `transition_path`, the single jitted entry the outer
solvers (transition/mit.py) and the scenario sweep vmap over. Everything is
a traced operand; the program compiles once per (T, N, na) geometry — and
per dtype: the scans are dtype-generic, so the mixed-precision ladder
(ops/precision.py, routed by transition/mit.py's round loop) evaluates its
hot rounds by handing this module f32-cast anchors/paths (one extra compile,
half the bytes per scan step) and its polish rounds the f64 originals.

Timing conventions (the usual discrete-time Aiyagari dating):
  * budget at t:  c_t + a_{t+1} = (1 + r_t) a_t + w_t s_t
  * Euler at t:   u'_{sigma_t}(c_t) = beta_t (1+r_{t+1}) E_t u'_{sigma_{t+1}}(c_{t+1})
so the price path enters as an EXTENDED rate path r_ext of length T+1 with
r_ext[T] = the terminal stationary rate (the last Euler equation looks one
period past the truncation horizon), while w/amin are length T. sigma is
extended the same way. K_0 is predetermined at the initial stationary
capital; A_{T-1} is the last asset choice the window determines.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from aiyagari_tpu.ops.egm import egm_step_transition
from aiyagari_tpu.ops.pushforward import pushforward_step
from aiyagari_tpu.sim.distribution import young_lottery

__all__ = ["backward_policies", "forward_capital", "transition_path"]


def backward_policies(C_term, a_grid, s, P, r_ext, w_path, beta_path,
                      sigma_ext, amin_path, matmul_precision: str = "highest",
                      egm_kernel: str = "xla"):
    """Backward EGM sweep over t = T-1 .. 0 from the terminal policy.

    C_term [N, na] is the stationary consumption policy the path ends at
    (period-T policy). r_ext/sigma_ext are [T+1] (module docstring);
    w_path/beta_path/amin_path are [T]. Returns (C_ts, k_ts), each
    [T, N, na] in FORWARD time order (C_ts[t] is the period-t policy).
    matmul_precision (static) relaxes the per-step Euler expectation for
    the ladder's hot rounds (ops/egm.egm_step_transition). egm_kernel
    (static) selects the per-step sweep route: "pallas_fused" runs every
    dated sweep of the scan as the fused VMEM-resident Pallas kernel
    (ops/pallas_egm.py), so each of the T backward steps reads the policy
    once from HBM instead of once per op — the same fusion win T-fold on
    every PRIMAL evaluation (round loops, scenario sweeps, final policy
    materialization). The fake-news Jacobian cannot take it: it
    differentiates this function with jax.jvp and pallas_call has no AD
    rule (transition/jacobian.py keeps the XLA chain there).
    """

    def step(C_next, xs):
        r_now, r_next, w_now, beta_now, sig_now, sig_next, amin_now = xs
        C_now, k_now = egm_step_transition(
            C_next, a_grid, s, P, r_next, r_now, w_now, amin_now,
            sigma_now=sig_now, sigma_next=sig_next, beta_now=beta_now,
            matmul_precision=matmul_precision, egm_kernel=egm_kernel)
        return C_now, (C_now, k_now)

    xs = (r_ext[:-1], r_ext[1:], w_path, beta_path,
          sigma_ext[:-1], sigma_ext[1:], amin_path)
    _, (C_ts, k_ts) = jax.lax.scan(step, C_term, xs, reverse=True)
    return C_ts, k_ts


def forward_capital(mu0, k_ts, a_grid, P, pushforward: str = "auto"):
    """Push the initial distribution forward through the time-varying
    policies: mu_{t+1} = Lambda(k_ts[t]) mu_t.

    Returns (K_ts [T+1], A_ts [T], mu_T): K_ts[t] = E_{mu_t}[a] is the
    beginning-of-period capital stock (K_ts[0] is the predetermined initial
    stationary capital), A_ts[t] = E_{mu_t}[k_ts[t]] the end-of-period
    asset supply. Because the Young lottery is mean-preserving for policies
    inside the grid (every k_ts is clipped into it), K_ts[t+1] == A_ts[t]
    exactly — the identity the sequence-space Jacobian relies on, and one
    every DistributionBackend preserves (`pushforward` selects the route;
    scatter-free by default, ops/pushforward.py — the plan rebuilds per
    step because the policy is dated).
    """

    def step(mu, k_t):
        K_t = jnp.sum(mu * a_grid[None, :])
        A_t = jnp.sum(mu * k_t)
        idx, w_lo = young_lottery(k_t, a_grid)
        mu_next = pushforward_step(mu, idx, w_lo, P, backend=pushforward)
        # Renormalize: f32 accumulation must not drift total mass over a
        # long horizon (same policy as stationary_distribution's sweeps).
        mu_next = mu_next / jnp.sum(mu_next)
        return mu_next, (K_t, A_t)

    mu_T, (K_ts, A_ts) = jax.lax.scan(step, mu0, k_ts)
    K_ts = jnp.concatenate([K_ts, jnp.sum(mu_T * a_grid[None, :])[None]])
    return K_ts, A_ts, mu_T


@partial(jax.jit, static_argnames=("matmul_precision", "pushforward",
                                   "egm_kernel"))
def transition_path(C_term, mu0, a_grid, s, P, r_ext, w_path, beta_path,
                    sigma_ext, amin_path, matmul_precision: str = "highest",
                    pushforward: str = "auto", egm_kernel: str = "xla"):
    """Backward sweep + forward push as one jitted program.

    Returns a dict: K_ts [T+1] (capital path, K_ts[0] predetermined),
    A_ts [T] (asset supply), C_ts / k_ts [T, N, na] (dated policies),
    mu_T [N, na] (terminal distribution — should be back near the
    stationary one when T is long enough). The outer solvers compute
    excess demand from K_ts on host (transition/mit.py).
    """
    C_ts, k_ts = backward_policies(C_term, a_grid, s, P, r_ext, w_path,
                                   beta_path, sigma_ext, amin_path,
                                   matmul_precision=matmul_precision,
                                   egm_kernel=egm_kernel)
    K_ts, A_ts, mu_T = forward_capital(mu0, k_ts, a_grid, P,
                                       pushforward=pushforward)
    return {"K_ts": K_ts, "A_ts": A_ts, "C_ts": C_ts, "k_ts": k_ts,
            "mu_T": mu_T}


@partial(jax.jit, static_argnames=("matmul_precision", "pushforward",
                                   "egm_kernel"))
def transition_path_aggregates(C_term, mu0, a_grid, s, P, r_ext, w_path,
                               beta_path, sigma_ext, amin_path,
                               matmul_precision: str = "highest",
                               pushforward: str = "auto",
                               egm_kernel: str = "xla"):
    """transition_path without the [T, N, na] policy stacks in the output.

    The round loops only read K_ts, and jit OUTPUTS cannot be dead-code-
    eliminated — returning the policies would allocate ~T*N*na*2 buffers
    per round purely to be dropped (at the framework's target grids that
    is GBs per sweep round). The full twin above is evaluated ONCE at the
    converged path when the caller wants the policies."""
    _, k_ts = backward_policies(C_term, a_grid, s, P, r_ext, w_path,
                                beta_path, sigma_ext, amin_path,
                                matmul_precision=matmul_precision,
                                egm_kernel=egm_kernel)
    K_ts, A_ts, mu_T = forward_capital(mu0, k_ts, a_grid, P,
                                       pushforward=pushforward)
    return {"K_ts": K_ts, "A_ts": A_ts, "mu_T": mu_T}


@partial(jax.jit, static_argnames=("matmul_precision", "pushforward",
                                   "egm_kernel"))
def transition_path_record(C_term, mu0, a_grid, s, P, r_ext, w_path,
                           beta_path, sigma_ext, amin_path, r64, z64,
                           labor_raw, alpha, delta,
                           matmul_precision: str = "highest",
                           pushforward: str = "auto",
                           egm_kernel: str = "xla"):
    """transition_path_aggregates plus the round's HOST-LOOP fetch record,
    stacked into ONE [3T+1] float64 array: [K_ts (T+1) | D (T) | A_ts (T)].

    The host round loop used to fetch K_ts, recompute the excess demand on
    host, and fetch A_ts again after the loop — one device_get per round
    plus one trailing. This program moves the firm FOC onto the device:
    r64/z64 are the rate path and TFP path as COMMITTED float64 operands
    (under the mixed-precision ladder the path evaluation runs in the hot
    dtype while the excess demand is still formed in f64 against the f64
    candidate path, exactly what the host recompute did), so the loop
    fetches one stacked record per round and nothing after. mu_T stays on
    device (the result carries the array, never fetches it)."""
    _, k_ts = backward_policies(C_term, a_grid, s, P, r_ext, w_path,
                                beta_path, sigma_ext, amin_path,
                                matmul_precision=matmul_precision,
                                egm_kernel=egm_kernel)
    K_ts, A_ts, mu_T = forward_capital(mu0, k_ts, a_grid, P,
                                       pushforward=pushforward)
    T = amin_path.shape[0]
    K64 = K_ts.astype(jnp.float64)
    D = K64[:T] - _capital_demand(r64, labor_raw, alpha, delta, z64)
    record = jnp.concatenate([K64, D, A_ts.astype(jnp.float64)])
    return {"record": record, "mu_T": mu_T}


def _capital_demand(r, labor, alpha, delta, z):
    from aiyagari_tpu.utils.firm import capital_demand

    return capital_demand(r, labor, alpha, delta, z)


_RECORD_BATCH_CACHE: dict = {}


def transition_path_record_batch(C_term, mu0, a_grid, s, P, r_ext_s, w_s,
                                 beta_s, sigma_s, amin_s, r64_s, z64_s,
                                 labor_raw, alpha, delta,
                                 matmul_precision: str = "highest",
                                 pushforward: str = "auto",
                                 egm_kernel: str = "xla"):
    """Scenario-sweep twin of transition_path_record: one [S, 3T+1] f64
    record per round (the lockstep loop's single stacked device_get)."""
    key = (matmul_precision, pushforward, egm_kernel)
    fn = _RECORD_BATCH_CACHE.get(key)
    if fn is None:
        fn = jax.jit(jax.vmap(
            lambda *a: transition_path_record(
                *a, matmul_precision=matmul_precision,
                pushforward=pushforward, egm_kernel=egm_kernel),
            in_axes=(None, None, None, None, None, 0, 0, 0, 0, 0, 0, 0,
                     None, None, None),
        ))
        _RECORD_BATCH_CACHE[key] = fn
    return fn(C_term, mu0, a_grid, s, P, r_ext_s, w_s, beta_s, sigma_s,
              amin_s, r64_s, z64_s, labor_raw, alpha, delta)


# vmapped twin for scenario sweeps: paths carry a leading [S] axis, the
# model arrays and stationary anchors are shared. jit(vmap(...)) compiles
# once per (S, T, N, na) and per matmul precision (the ladder's hot rounds
# relax it); the [S]-axis shards over a "scenarios" mesh axis when the
# stacked paths were placed with parallel/mesh.shard_scenario_arrays.
_PATH_BATCH_CACHE: dict = {}


def transition_path_batch(C_term, mu0, a_grid, s, P, r_ext_s, w_s, beta_s,
                          sigma_s, amin_s, matmul_precision: str = "highest",
                          pushforward: str = "auto",
                          egm_kernel: str = "xla"):
    key = (matmul_precision, pushforward, egm_kernel)
    fn = _PATH_BATCH_CACHE.get(key)
    if fn is None:
        fn = jax.jit(jax.vmap(
            lambda *a: transition_path_aggregates(
                *a, matmul_precision=matmul_precision,
                pushforward=pushforward, egm_kernel=egm_kernel),
            in_axes=(None, None, None, None, None, 0, 0, 0, 0, 0),
        ))
        _PATH_BATCH_CACHE[key] = fn
    return fn(C_term, mu0, a_grid, s, P, r_ext_s, w_s, beta_s, sigma_s,
              amin_s)
