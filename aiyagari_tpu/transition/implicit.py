"""Differentiable transition path via the IFT on the Newton map (ISSUE 17).

The MIT-shock solver (transition/mit.py) finds the T-period rate path by a
HOST Newton loop — r ← r − J⁻¹D(r) with J the fake-news sequence-space
Jacobian — so nothing can `jax.grad` through it. This module wraps the
*converged* path in ops/implicit.fixed_point_vjp using the Newton update
itself as the fixed-point operator:

    Φ(r, θ) = r − J⁻¹ D(r, θ),      Φ(r*, θ) = r*  ⟺  D(r*, θ) = 0,

with J the solver's frozen Newton matrix (a nondifferentiable CONSTANT —
by the IFT the J factors cancel exactly in dr*/dθ, so an approximate J
changes only the adjoint's convergence rate, never its limit; at the
solution ∂Φ/∂r = I − J⁻¹∂D/∂r ≈ 0, so the Neumann adjoint converges in a
handful of iterations). D is re-expressed differentiably from the fused
path programs (transition/path.py): one backward EGM scan + one forward
push — both `lax.scan`s, transparent to reverse AD — with the stationary
anchors (terminal policy, initial distribution, grids) held fixed, exactly
as the solver holds them.

θ here is the SHOCK SIZE — the impulse-response sensitivity d r_path /
d size, the derivative sequence-space estimation consumes (ABRS 2021).
The stationary anchors do not move with the shock size (an MIT shock is
unanticipated and transitory: both endpoints are the SAME stationary
equilibrium for every size), so freezing them is exact, not an
approximation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from aiyagari_tpu.ops.implicit import fixed_point_vjp
from aiyagari_tpu.transition.path import backward_policies, forward_capital
from aiyagari_tpu.utils.firm import capital_demand, wage_from_r

__all__ = ["transition_r_path_implicit"]


def transition_r_path_implicit(size, *, primal, model, shock,
                               adjoint_tol: float = 1e-13,
                               adjoint_max_iter: int = 100):
    """Differentiable [T] interest-rate path as a function of the MIT shock
    size, anchored at a converged primal solve.

    `primal` is the TransitionResult of transition/mit.solve_transition for
    `shock` (method="newton", so primal.jacobian is populated); `size` is
    the traced shock size — pass `size=shock.size` and differentiate with
    jax.grad/jax.vjp. The primal path is returned BIT-IDENTICAL (identity
    forward); the backward pass runs the Neumann adjoint of the Newton map
    above. Gradient accuracy inherits the primal's market-clearing
    residual: solve with a tight trans.tol when comparing against finite
    differences (tests/test_differentiable.py).
    """
    if primal.jacobian is None:
        raise ValueError(
            "transition_r_path_implicit needs the Newton Jacobian on the "
            "primal TransitionResult (solve with TransitionConfig"
            "(method='newton'))")
    sg = jax.lax.stop_gradient
    T = int(primal.T)
    ss = primal.ss
    prefs = model.preferences
    tech = model.config.technology
    alpha, delta = float(tech.alpha), float(tech.delta)
    labor_raw = float(model.labor_raw)
    r_ss = float(primal.r_ss)

    J = sg(jnp.asarray(primal.jacobian, jnp.float64))
    C_term = sg(jnp.asarray(ss.solution.policy_c, jnp.float64))
    mu0 = jnp.asarray(ss.mu, jnp.float64)
    mu0 = sg(mu0 / jnp.sum(mu0))
    a_grid = sg(jnp.asarray(model.a_grid, jnp.float64))
    s = sg(jnp.asarray(model.s, jnp.float64))
    P = sg(jnp.asarray(model.P, jnp.float64))

    decay = shock.rho ** jnp.arange(T, dtype=jnp.float64)
    key = {"tfp": "z", "borrowing_limit": "amin"}.get(shock.param,
                                                      shock.param)

    def newton_map(r_path, p):
        bump = p["size"] * decay
        z_path = jnp.ones(T) + (bump if key == "z" else 0.0)
        beta_path = jnp.full(T, prefs.beta) + (bump if key == "beta" else 0.0)
        sigma_path = jnp.full(T, prefs.sigma) + (bump if key == "sigma"
                                                 else 0.0)
        amin_path = jnp.full(T, model.amin) + (bump if key == "amin" else 0.0)
        w_path = wage_from_r(r_path, alpha, delta, z_path)
        r_ext = jnp.concatenate([r_path, jnp.array([r_ss])])
        sig_ext = jnp.concatenate([sigma_path, jnp.array([prefs.sigma])])
        _, k_ts = backward_policies(C_term, a_grid, s, P, r_ext, w_path,
                                    beta_path, sig_ext, amin_path,
                                    matmul_precision="highest",
                                    egm_kernel="xla")
        K_ts, _, _ = forward_capital(mu0, k_ts, a_grid, P,
                                     pushforward="transpose")
        D = K_ts[:T] - capital_demand(r_path, labor_raw, alpha, delta,
                                      z_path)
        return r_path - jnp.linalg.solve(J, D)

    r_star = jnp.asarray(primal.r_path, jnp.float64)
    return fixed_point_vjp(newton_map, r_star, {"size": size},
                           tol=adjoint_tol, max_iter=adjoint_max_iter)
