"""End-to-end MIT-shock transition solver: perfect-foresight equilibrium
price paths after a one-time unanticipated shock, anchored at the existing
stationary solves on both ends.

The unknown is the T-period interest-rate path (wages ride the firm FOC).
Market clearing every period is the SAME condition the stationary closure
bisects on, dated:

    D_t(r) = K_t(r) - K_d(r_t, z_t) = 0,   t = 0..T-1,

with K_t = E_{mu_t}[a] from the forward push (K_0 predetermined at the
initial stationary capital) and K_d the firm demand curve at the shocked
TFP. Two update rules (TransitionConfig.method):

  "newton" — r <- r - J_D^{-1} D with J_D the sequence-space Jacobian built
      ONCE at the stationary equilibrium by the fake-news algorithm
      (transition/jacobian.py). Converges in a handful of rounds; the
      factorized ss Jacobian is reused across rounds and across every
      scenario of a sweep.
  "damped" — the Boppart-Krusell-Mitman relaxation
      r <- (1-damping) r + damping * r_implied(K), with r_implied the rate
      at which the firm demands exactly the household-supplied capital.
      Slower (geometric) but Jacobian-free; the parity of the two fixed
      points is pinned by tests/test_transition.py.

Every round is ONE fused device program (transition/path.transition_path);
solve_transitions_sweep advances S shock scenarios in lockstep through the
vmapped twin, shardable over a "scenarios" mesh axis.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from aiyagari_tpu.config import (
    AiyagariConfig,
    EquilibriumConfig,
    MITShock,
    SolverConfig,
    TransitionConfig,
)
from aiyagari_tpu.diagnostics.progress import heartbeat_stride, sweep_heartbeat
from aiyagari_tpu.models.aiyagari import AiyagariModel
from aiyagari_tpu.sim.distribution import aggregate_capital
from aiyagari_tpu.transition.jacobian import fake_news_jacobian, newton_jacobian
from aiyagari_tpu.transition.path import (
    transition_path,
    transition_path_record,
    transition_path_record_batch,
)
from aiyagari_tpu.utils.firm import (
    r_from_capital,
    wage_from_r,
)

__all__ = [
    "TransitionResult",
    "TransitionSweepResult",
    "shock_paths",
    "stationary_anchor",
    "transition_jacobian",
    "solve_transition",
    "solve_transitions_sweep",
]

_SHOCK_PARAMS = ("tfp", "beta", "sigma", "borrowing_limit")

# Host-side guard rails on candidate rate paths between rounds: capital
# demand needs r > -delta, and far-above-stationary rates explode the
# backward sweep's cash-on-hand. Transitional rates may legitimately exceed
# the stationary 1/beta - 1 bound, so the ceiling is deliberately loose.
_R_CEIL = 0.9


@dataclasses.dataclass
class TransitionResult:
    """One converged (or round-capped) perfect-foresight transition."""

    r_path: np.ndarray          # [T] equilibrium interest-rate path
    w_path: np.ndarray          # [T] wages along the firm FOC
    K_ts: np.ndarray            # [T+1] capital path (K_ts[0] = initial ss)
    A_ts: np.ndarray            # [T] end-of-period asset supply
    excess: np.ndarray          # [T] final market-clearing residual
    max_excess_history: list    # per-round max |excess|
    rounds: int
    converged: bool
    solve_seconds: float
    method: str
    shock: MITShock
    T: int
    r_ss: float
    K_ss: float
    ss: object                  # the anchoring EquilibriumResult
    policies: object = None     # {"C_ts", "k_ts"} device arrays [T, N, na]
    mu_T: object = None         # terminal distribution (device)
    jacobian: object = None     # the Newton J_D, for reuse
    # Mixed-precision ladder telemetry (ops/precision.py; 0/0.0 when no
    # ladder ran): rounds whose path evaluation ran in a hot dtype —
    # counted whether or not the switch fired, so a round-capped all-hot
    # solve reports them honestly — and the max excess demand at which the
    # dtype switch fired (0.0 = the switch never fired).
    hot_rounds: int = 0
    switch_excess: float = 0.0
    # Outer flight record (diagnostics/telemetry.py host_telemetry): the
    # per-round max-excess-demand trajectory with per-round stage dtypes —
    # the Newton/damped loop's convergence certificate in the same
    # SolveTelemetry shape as the device recorders.
    telemetry: object = None
    # Structured failure verdict ("" healthy; "nan"/"stall"/"explode" when
    # SolverConfig.sentinel armed the host-side round sentinel and it
    # tripped — the loop then returns this instead of raising
    # FloatingPointError, so dispatch's rescue ladder and
    # enforce_convergence's nan verdict own the failure).
    verdict: str = ""

    def health(self, model=None) -> dict:
        """Health certificate (diagnostics/health.py): round-trajectory
        shape (stall/oscillation), convergence verdict."""
        from aiyagari_tpu.diagnostics.health import health_report

        return health_report(self, model=model)


@dataclasses.dataclass
class TransitionSweepResult:
    """S lockstep transitions (one per shock scenario)."""

    r_paths: np.ndarray         # [S, T]
    K_ts: np.ndarray            # [S, T+1]
    max_excess: np.ndarray      # [S] final max |residual| per scenario
    converged: np.ndarray       # [S] bool
    rounds: int                 # lockstep device rounds executed
    scenarios: int
    solve_seconds: float
    transitions_per_sec: float
    shocks: list                # the MITShock per scenario
    method: str
    T: int
    r_ss: float
    ss: object
    jacobian: object = None
    # Mixed-precision ladder telemetry (lockstep: all scenarios share one
    # program dtype, so the switch is global over the batch).
    hot_rounds: int = 0
    switch_excess: float = 0.0
    # Outer flight record: per-round max excess demand across the batch
    # (host_telemetry; one trajectory — the lockstep rounds are shared).
    telemetry: object = None
    # Scenario quarantine (ISSUE 10): lanes whose excess demand went
    # non-finite were frozen (their paths pinned, excluded from the
    # all-converged check) so the batch completed. `verdicts` per scenario:
    # "converged" | "max_iter" | "nan" | "rescued".
    quarantined: object = None      # [S] bool
    verdicts: object = None         # list[str], length S
    rescue_attempts: object = None  # {scenario index: [RescueAttempt, ...]}

    def health(self, model=None) -> dict:
        from aiyagari_tpu.diagnostics.health import health_report

        return health_report(self, model=model)


def shock_paths(model: AiyagariModel, shock: MITShock, T: int) -> dict:
    """Host [T] parameter paths for one MIT shock: the shocked parameter
    follows x_ss + size * rho^t, everything else stays flat. Returns
    {"z", "beta", "sigma", "amin"} float64 arrays, validated loudly."""
    if shock.param not in _SHOCK_PARAMS:
        raise ValueError(
            f"unknown shock param {shock.param!r}; expected one of "
            f"{_SHOCK_PARAMS}")
    if not abs(shock.rho) < 1.0:
        raise ValueError(
            f"MIT shocks must be transitory (|rho| < 1, got {shock.rho}): "
            "the transition starts and ends at the same stationary "
            "equilibrium")
    prefs = model.preferences
    decay = shock.size * shock.rho ** np.arange(T, dtype=np.float64)
    paths = {
        "z": np.ones(T),
        "beta": np.full(T, prefs.beta),
        "sigma": np.full(T, prefs.sigma),
        "amin": np.full(T, model.amin),
    }
    key = {"tfp": "z", "borrowing_limit": "amin"}.get(shock.param,
                                                      shock.param)
    paths[key] = paths[key] + decay
    if np.any(paths["beta"] <= 0.0) or np.any(paths["beta"] >= 1.0):
        raise ValueError(f"beta shock leaves (0, 1): size={shock.size}")
    if np.any(paths["sigma"] <= 0.0):
        raise ValueError(f"sigma shock leaves sigma > 0: size={shock.size}")
    if np.any(paths["z"] <= 0.0):
        raise ValueError(f"TFP shock leaves z > 0: size={shock.size}")
    if np.any(paths["amin"] < model.amin - 1e-12):
        raise ValueError(
            "borrowing-limit shocks can only TIGHTEN the constraint "
            f"(size >= 0, got {shock.size}): the asset grid starts at the "
            "stationary limit, so a looser limit has no gridpoints")
    return paths


def _check_trans(trans: TransitionConfig) -> None:
    if trans.method not in ("newton", "damped"):
        raise ValueError(
            f"unknown method {trans.method!r}; expected 'newton' or 'damped'")
    if trans.max_iter < 1 or trans.T < 2:
        raise ValueError(
            f"TransitionConfig needs max_iter >= 1 and T >= 2; got "
            f"max_iter={trans.max_iter}, T={trans.T}")


def _check_anchor(ss) -> None:
    if getattr(ss, "mu", None) is None:
        raise ValueError(
            "the stationary anchor must carry the Young-histogram "
            "distribution (aggregation='distribution'); got mu=None")
    if getattr(ss.solution, "policy_c", None) is None:
        raise ValueError(
            "the stationary anchor must carry an EGM consumption policy "
            "(solve the anchor with method='egm')")


def _as_model(model: Union[AiyagariModel, AiyagariConfig], dtype):
    if isinstance(model, AiyagariConfig):
        model = AiyagariModel.from_config(model, dtype)
    if model.config.endogenous_labor:
        raise NotImplementedError(
            "transition dynamics currently cover the exogenous-labor "
            "Aiyagari family (aggregate labor must stay constant along "
            "the path)")
    return model


def stationary_anchor(model: AiyagariModel, *,
                      solver: Optional[SolverConfig] = None,
                      eq: Optional[EquilibriumConfig] = None,
                      warm_start=None):
    """The stationary equilibrium both ends of the path are anchored at:
    an EGM solve (the backward sweep needs the consumption policy as its
    terminal condition) closed with the deterministic Young histogram (the
    forward push needs mu_ss as its initial condition). Tighter-than-default
    tolerances: anchor error is a floor on how flat the flat-path identity
    can be.

    `warm_start` seeds the FIRST household solve with a consumption policy
    from a nearby economy (the serve layer's anchor amortization, ISSUE
    16) — a pure iteration-count accelerant: the bisection still certifies
    the same tolerance from the same bracket, so the anchor is exactly as
    converged as a cold one (equilibrium/bisection.py threads warm_start=
    since PR 15)."""
    from aiyagari_tpu.equilibrium.bisection import (
        solve_equilibrium_distribution,
    )

    solver = solver or SolverConfig(method="egm", tol=1e-9, max_iter=5000)
    if solver.method != "egm":
        raise ValueError(
            "transition solves need method='egm' stationary anchors (the "
            "backward sweep iterates the EGM operator from the terminal "
            f"consumption policy); got solver.method={solver.method!r}")
    eq = eq or EquilibriumConfig(max_iter=48, tol=1e-8)
    return solve_equilibrium_distribution(model, solver=solver, eq=eq,
                                          warm_start=warm_start)


def _pushforward_of(solver: Optional[SolverConfig]) -> str:
    """The DistributionBackend the transition programs run their forward
    Young pushes on (ops/pushforward.py), from SolverConfig like accel and
    the ladder; None keeps the shipped scatter-free default."""
    return solver.pushforward if solver is not None else "auto"


def _egm_kernel_of(solver: Optional[SolverConfig]) -> str:
    """The EGM sweep route of the round loops' backward scans (ops/egm.
    EGM_KERNELS): every PRIMAL path evaluation — the per-round aggregates
    program, the final policy materialization, the scenario-sweep batch —
    honors SolverConfig.egm_kernel. The fake-news Jacobian build does NOT:
    it differentiates backward_policies with jax.jvp, and pallas_call has
    no AD rule, so that one-off pass stays on the AD-transparent XLA chain
    regardless (transition/jacobian.py).

    "pallas_inverse" is rejected HERE, before the stationary anchor solve
    runs — egm_step_transition would refuse it anyway (the windowed
    route's host-escape-retry contract cannot ride a fused time scan),
    but only mid-round-loop, after the anchor's work is already spent;
    the hoisted check keeps the dispatch discipline of failing before any
    compile."""
    kernel = solver.egm_kernel if solver is not None else "auto"
    if kernel == "pallas_inverse":
        raise ValueError(
            "transition solves support egm_kernel 'auto'/'xla'/"
            "'pallas_fused' only: the windowed pallas_inverse route needs "
            "a host-level escape retry that a fused time scan cannot "
            "perform (ops/egm.egm_step_transition)")
    return kernel


def transition_jacobian(model: AiyagariModel, ss, T: int,
                        pushforward: str = "auto") -> np.ndarray:
    """The Newton matrix J_D for this (model, stationary anchor, horizon):
    fake-news household Jacobian + firm diagonal (transition/jacobian.py)."""
    tech = model.config.technology
    prefs = model.preferences
    w_ss = float(wage_from_r(ss.r, tech.alpha, tech.delta))
    # dw/dr along the FOC link at the stationary rate.
    w_slope = -tech.alpha / (1.0 - tech.alpha) * w_ss / (ss.r + tech.delta)
    J_A = fake_news_jacobian(
        ss.solution.policy_c, ss.solution.policy_k, ss.mu,
        model.a_grid, model.s, model.P,
        r_ss=ss.r, w_ss=w_ss, w_slope=w_slope,
        sigma=prefs.sigma, beta=prefs.beta, amin=model.amin, T=T,
        pushforward=pushforward)
    return newton_jacobian(J_A, r_ss=ss.r, labor=model.labor_raw,
                           alpha=tech.alpha, delta=tech.delta)


def _device_paths(model: AiyagariModel, r_path, paths, r_ss, dtype=None):
    """(r_ext, w_path, beta_path, sigma_ext, amin_path) device arrays for
    one round's path program, from the host rate path + shock paths.
    `dtype` overrides the model dtype (the mixed-precision ladder's hot
    rounds evaluate the whole path program in the hot dtype)."""
    tech = model.config.technology
    dt = model.dtype if dtype is None else dtype
    w = wage_from_r(r_path, tech.alpha, tech.delta, paths["z"])
    r_ext = np.concatenate([r_path, [r_ss]])
    sig_ext = np.concatenate([paths["sigma"],
                              [model.preferences.sigma]])
    return (jnp.asarray(r_ext, dt), jnp.asarray(w, dt),
            jnp.asarray(paths["beta"], dt), jnp.asarray(sig_ext, dt),
            jnp.asarray(paths["amin"], dt))


def _stage_dtype_names(model: AiyagariModel, ladder) -> tuple:
    """The round loop's dtype schedule: the ladder's stage dtypes, or the
    model dtype alone. The ladder's availability guard runs here (a polish
    stage that would silently truncate must fail loudly)."""
    if ladder is None:
        return (jnp.dtype(model.dtype).name,)
    from aiyagari_tpu.ops.precision import require_x64, validate_ladder

    validate_ladder(ladder)
    require_x64(ladder)
    return tuple(ladder.stage_dtypes)


def _stage_matmul_precision(ladder, stage: int) -> str:
    """The stage's matmul-precision name for the path program's Euler
    expectation (ops/egm.egm_step_transition): the ladder's per-stage
    configuration, or the historical 'highest' pin without a ladder."""
    return "highest" if ladder is None else ladder.matmul_precision[stage]


class _StageAnchors:
    """Per-dtype cache of the stationary anchors (terminal policy, initial
    distribution, model arrays) the path program consumes — cast once per
    ladder stage, with the distribution re-normalized on the simplex at the
    cast (a hot-dtype mass defect must not bias the certified rounds).

    With `mesh` carrying a "grid" axis of size > 1 (the 2-D scenario x
    grid sweep), the anchors are placed through the partition-rule matcher
    (parallel/rules.TRANSITION_SWEEP_RULES): terminal policy / initial
    distribution / asset grid split over "grid" and replicate across the
    scenario lanes, so the vmapped path program's [S, T, N, na] working
    set shards over BOTH axes by propagation."""

    def __init__(self, model: AiyagariModel, ss, mesh=None):
        self.model, self.ss = model, ss
        self.mesh = mesh
        self._cache: dict = {}

    def get(self, dt_name: str):
        if dt_name not in self._cache:
            dt = jnp.dtype(dt_name)
            mu = self.ss.mu.astype(dt)
            mu = mu / jnp.sum(mu)
            anchors = {"policy_c": self.ss.solution.policy_c.astype(dt),
                       "mu": mu,
                       "a_grid": self.model.a_grid.astype(dt),
                       "s": self.model.s.astype(dt),
                       "P": self.model.P.astype(dt)}
            if self.mesh is not None:
                from aiyagari_tpu.parallel.mesh import GRID_AXIS
                from aiyagari_tpu.parallel.rules import (
                    TRANSITION_SWEEP_RULES,
                    shard_by_rules,
                )

                if (GRID_AXIS in self.mesh.shape
                        and int(self.mesh.shape[GRID_AXIS]) > 1):
                    anchors = shard_by_rules(self.mesh, anchors,
                                             TRANSITION_SWEEP_RULES)
            self._cache[dt_name] = tuple(
                anchors[k] for k in ("policy_c", "mu", "a_grid", "s", "P"))
        return self._cache[dt_name]


def solve_transition(
    model: Union[AiyagariModel, AiyagariConfig],
    shock: MITShock,
    *,
    trans: TransitionConfig = TransitionConfig(),
    solver: Optional[SolverConfig] = None,
    eq: Optional[EquilibriumConfig] = None,
    ss=None,
    jacobian: Optional[np.ndarray] = None,
    anchor_warm_start=None,
    keep_policies: bool = True,
    on_iteration: Optional[Callable] = None,
    dtype=jnp.float64,
    ladder=None,
) -> TransitionResult:
    """Solve one perfect-foresight MIT-shock transition (module docstring).

    `ss` (a distribution-closure EquilibriumResult) and `jacobian` (the
    Newton J_D) can be passed in to amortize the anchors across calls —
    solve_transitions_sweep does exactly that. `anchor_warm_start` (a
    consumption policy from a NEARBY economy) instead warm-starts the
    anchor solve itself when ss is None — the serve layer's cross-bucket
    amortization (stationary_anchor); ignored when ss is provided. The per-round max excess
    demand lands in max_excess_history (and flows through on_iteration),
    the acceptance telemetry ISSUE 2 names.

    ladder (a PrecisionLadderConfig) opts the ROUND LOOP into the
    mixed-precision solve ladder (ops/precision.py; dispatch routes
    BackendConfig(dtype="mixed") here): early rounds evaluate the whole
    backward/forward path program (transition/path.py) with anchors, model
    arrays, and price paths cast to the hot dtype — the per-round cost is
    two T-step scans over [N, na] arrays, squarely bandwidth-bound — until
    the max excess demand reaches max(tol, switch_ulp * eps(hot) *
    max|K_ts|), then the SAME candidate path is re-evaluated at the next
    dtype and the loop continues to tol there. Newton/damped updates are
    host-f64 either way; convergence is only ever declared from a
    final-dtype evaluation, so the certificate matches the pure-f64 solve.
    """
    t0 = time.perf_counter()
    model = _as_model(model, dtype)
    _check_trans(trans)
    T = int(trans.T)
    # Route validation BEFORE the anchor solve (the _egm_kernel_of raise).
    pushforward = _pushforward_of(solver)
    egm_kernel = _egm_kernel_of(solver)
    if ss is None:
        ss = stationary_anchor(model, solver=solver, eq=eq,
                               warm_start=anchor_warm_start)
    _check_anchor(ss)
    tech = model.config.technology
    r_ss = float(ss.r)
    K_ss = float(aggregate_capital(ss.mu, model.a_grid))
    paths = shock_paths(model, shock, T)

    if trans.method == "newton" and jacobian is None:
        jacobian = transition_jacobian(model, ss, T, pushforward=pushforward)
    # Hoist the Newton factorization out of the loop: J is the (round-
    # invariant) steady-state linearization, so the per-round update is a
    # [T, T] @ [T] matmul — the same form the fused device loop applies in
    # its carry (transition/fused.py), which pins host/device parity.
    jac_inv = (np.linalg.inv(np.asarray(jacobian, np.float64))
               if trans.method == "newton" else None)

    stage_names = _stage_dtype_names(model, ladder)
    anchors = _StageAnchors(model, ss)
    stage = 0
    hot_rounds = 0
    switch_excess = 0.0

    # Loop-invariant f64 operands of the round-record program: the excess
    # demand is formed ON DEVICE against the f64 candidate path
    # (transition/path.transition_path_record), so each round fetches one
    # stacked [3T+1] record instead of K_ts now and A_ts after the loop.
    z64 = jnp.asarray(paths["z"], jnp.float64)
    labor64 = jnp.asarray(model.labor_raw, jnp.float64)
    alpha64 = jnp.asarray(tech.alpha, jnp.float64)
    delta64 = jnp.asarray(tech.delta, jnp.float64)

    r_path = np.full(T, r_ss)
    out = None
    rec = None
    K_ts = D = None
    hist: list = []
    bits_hist: list = []   # per-round stage dtype width (the ladder record)
    converged = False
    verdict = ""
    sentinel_cfg = solver.sentinel if solver is not None else None
    rounds = 0
    for rnd in range(trans.max_iter):
        it_t0 = time.perf_counter()
        dt_name = stage_names[stage]
        dev = _device_paths(model, r_path, paths, r_ss,
                            dtype=jnp.dtype(dt_name))
        # Record program per round (the update reads K_ts/D alone); the
        # policy stacks are materialized once below, at the final path.
        out = transition_path_record(
            *anchors.get(dt_name), *dev,
            jnp.asarray(r_path, jnp.float64), z64, labor64, alpha64,
            delta64,
            matmul_precision=_stage_matmul_precision(ladder, stage),
            pushforward=pushforward, egm_kernel=egm_kernel)
        # ONE stacked device_get per round: [K_ts (T+1) | D (T) | A_ts (T)].
        rec = np.asarray(jax.device_get(out["record"]), np.float64)
        K_ts = rec[:T + 1]
        D = rec[T + 1:2 * T + 1]
        rounds = rnd + 1
        if stage < len(stage_names) - 1:
            # Telemetry counts every round EVALUATED hot, whether or not
            # the switch ever fires (a round-capped all-hot solve must not
            # report hot_rounds=0).
            hot_rounds = rounds
        max_d = float(np.max(np.abs(D)))
        hist.append(max_d)
        bits_hist.append(int(jnp.finfo(jnp.dtype(dt_name)).bits))
        if on_iteration is not None:
            on_iteration({"round": rnd, "max_excess": max_d,
                          "dtype": dt_name,
                          "seconds": time.perf_counter() - it_t0})
        if stage < len(stage_names) - 1 and np.isfinite(max_d):
            # Error-controlled switch: the hot evaluation has reached its
            # own noise floor (in units of K, the excess-demand scale) —
            # re-evaluate the SAME path at the next dtype before trusting
            # any further comparison against tol.
            floor = (float(ladder.switch_ulp)
                     * float(jnp.finfo(jnp.dtype(dt_name)).eps)
                     * float(np.max(np.abs(K_ts))))
            if max_d < max(trans.tol, floor):
                switch_excess = max_d
                stage += 1
                continue
        if (np.isfinite(max_d) and max_d < trans.tol
                and stage == len(stage_names) - 1):
            converged = True
            break
        if not np.isfinite(max_d):
            if sentinel_cfg is not None:
                # Sentinel-armed: the divergence is a structured outcome,
                # not a crash — the result carries verdict "nan" and the
                # (always-loud) non-finite-distance convergence policy or
                # the rescue ladder owns what happens next.
                verdict = "nan"
                break
            raise FloatingPointError(
                f"transition path diverged at round {rnd} (non-finite "
                "excess demand); try method='damped' or a smaller shock")
        if sentinel_cfg is not None:
            from aiyagari_tpu.diagnostics.sentinel import host_verdict

            verdict = host_verdict(hist, sentinel_cfg)
            if verdict:
                # Stall/explosion on the round trajectory: stop burning
                # rounds on a path update that is not closing the market.
                break
        if rnd == trans.max_iter - 1:
            # Round cap: keep the path the final evaluation actually used —
            # a last update would pair a never-evaluated r_path with this
            # round's K_ts/excess, handing the caller mutually inconsistent
            # diagnostics.
            break
        if trans.method == "newton":
            r_path = r_path - jac_inv @ D
        else:
            r_implied = r_from_capital(
                np.maximum(K_ts[:T], 1e-10), model.labor_raw, tech.alpha,
                tech.delta, paths["z"])
            r_path = ((1.0 - trans.damping) * r_path
                      + trans.damping * r_implied)
        r_path = np.clip(r_path, -tech.delta + 1e-3, _R_CEIL)

    policies = None
    if keep_policies:
        # One full evaluation at the final (already-evaluated) path for the
        # dated policy stacks the round loop deliberately never returns.
        full = transition_path(ss.solution.policy_c, ss.mu, model.a_grid,
                               model.s, model.P,
                               *_device_paths(model, r_path, paths, r_ss),
                               pushforward=pushforward,
                               egm_kernel=egm_kernel)
        policies = {"C_ts": full["C_ts"], "k_ts": full["k_ts"]}
    return TransitionResult(
        r_path=r_path,
        w_path=np.asarray(wage_from_r(r_path, tech.alpha, tech.delta,
                                      paths["z"])),
        K_ts=K_ts,
        A_ts=rec[2 * T + 1:],
        excess=D,
        max_excess_history=hist,
        rounds=rounds,
        converged=converged,
        solve_seconds=time.perf_counter() - t0,
        method=trans.method,
        shock=shock,
        T=T,
        r_ss=r_ss,
        K_ss=K_ss,
        ss=ss,
        policies=policies,
        mu_T=out["mu_T"],
        jacobian=jacobian,
        hot_rounds=hot_rounds,
        switch_excess=switch_excess,
        telemetry=_round_telemetry(hist, bits_hist),
        verdict=verdict,
    )


def _round_telemetry(hist, bits_hist):
    """The round loop's host flight record (one shape with the device
    recorders: diagnostics/telemetry.host_telemetry)."""
    from aiyagari_tpu.diagnostics.telemetry import host_telemetry

    return host_telemetry(hist, bits_hist)


def solve_transitions_sweep(
    model: Union[AiyagariModel, AiyagariConfig],
    shocks: Sequence[MITShock],
    *,
    trans: TransitionConfig = TransitionConfig(),
    solver: Optional[SolverConfig] = None,
    eq: Optional[EquilibriumConfig] = None,
    ss=None,
    jacobian: Optional[np.ndarray] = None,
    anchor_warm_start=None,
    mesh=None,
    on_iteration: Optional[Callable] = None,
    dtype=jnp.float64,
    ladder=None,
    quarantine: bool = True,
) -> TransitionSweepResult:
    """Solve S MIT-shock scenarios in lockstep: every round evaluates ALL
    scenarios' candidate price paths through ONE vmapped backward+forward
    program (transition/path.transition_path_batch).

    Scenarios share the base economy — one stationary anchor, one fake-news
    Jacobian (the ss linearization is shock-independent), S right-hand
    sides per Newton round. They may shock DIFFERENT parameters: each
    scenario is lowered to its four [T] parameter paths, so a
    tfp/beta/sigma/borrowing-limit mix batches through the same compiled
    program. With `mesh` (carrying a "scenarios" axis), the stacked [S, T]
    paths are placed sharded (parallel/mesh.shard_scenario_arrays) and the
    rounds run scenario-parallel across devices. A converged scenario keeps
    its path pinned so the program shape never changes. The per-scenario
    fixed point is identical to running solve_transition one shock at a
    time (pinned by tests/test_transition.py).

    ladder runs the lockstep round loop through the mixed-precision solve
    ladder exactly as in solve_transition, with ONE program dtype for the
    whole batch (the switch is global: it fires when every scenario's max
    excess demand has reached the hot dtype's noise floor, and scenarios
    are only marked converged from final-dtype evaluations).

    quarantine (default True) arms per-scenario failure masks (ISSUE 10):
    a scenario whose excess demand goes non-finite is FROZEN — its rate
    path pinned at the last evaluated candidate, its Newton/damped update
    masked, excluded from the all-converged check — so one diverging shock
    costs its lane, not the batch; the result reports it with verdict
    "nan" and dispatch.sweep_transitions(rescue=...) re-solves it serially
    through the rescue ladder. quarantine=False restores the historical
    all-or-nothing FloatingPointError.
    """
    t0 = time.perf_counter()
    model = _as_model(model, dtype)
    _check_trans(trans)
    shocks = list(shocks)
    if not shocks:
        raise ValueError("solve_transitions_sweep needs at least one shock")
    T = int(trans.T)
    S = len(shocks)
    # Route validation BEFORE the anchor solve (the _egm_kernel_of raise).
    pushforward = _pushforward_of(solver)
    egm_kernel = _egm_kernel_of(solver)
    if ss is None:
        ss = stationary_anchor(model, solver=solver, eq=eq,
                               warm_start=anchor_warm_start)
    _check_anchor(ss)
    tech = model.config.technology
    r_ss = float(ss.r)
    if trans.method == "newton" and jacobian is None:
        jacobian = transition_jacobian(model, ss, T, pushforward=pushforward)
    # Hoisted Newton factorization (single-solve rationale): S right-hand
    # sides per round become one [S, T] @ [T, T] matmul.
    jac_inv = (np.linalg.inv(np.asarray(jacobian, np.float64))
               if trans.method == "newton" else None)

    all_paths = [shock_paths(model, sh, T) for sh in shocks]
    stacked = {k: np.stack([p[k] for p in all_paths])
               for k in ("z", "beta", "sigma", "amin")}

    sig_ext_s = np.concatenate(
        [stacked["sigma"],
         np.full((S, 1), model.preferences.sigma)], axis=1)

    if mesh is not None:
        from aiyagari_tpu.parallel.mesh import GRID_AXIS

        if GRID_AXIS in mesh.shape and int(mesh.shape[GRID_AXIS]) > 1:
            na = int(model.a_grid.shape[0])
            if na % int(mesh.shape[GRID_AXIS]):
                raise ValueError(
                    f"asset grid of {na} points must divide evenly over "
                    f"the {int(mesh.shape[GRID_AXIS])}-wide "
                    f"'{GRID_AXIS}' mesh axis")
    stage_names = _stage_dtype_names(model, ladder)
    anchors = _StageAnchors(model, ss, mesh=mesh)
    stage = 0
    hot_rounds = 0
    switch_excess = 0.0

    def place(x, dt):
        x = jnp.asarray(x, dt)
        if mesh is not None:
            from aiyagari_tpu.parallel.mesh import shard_scenario_arrays

            x = shard_scenario_arrays(mesh, S, x=x)["x"]
        return x

    # Per-stage-dtype cache of the placed scenario parameter paths (the
    # loop-invariant operands; the price paths are re-placed per round).
    _params: dict = {}

    def stage_params(dt_name: str):
        if dt_name not in _params:
            dt = jnp.dtype(dt_name)
            _params[dt_name] = (place(stacked["beta"], dt),
                                place(sig_ext_s, dt),
                                place(stacked["amin"], dt))
        return _params[dt_name]

    # Loop-invariant f64 operands of the batched round-record program
    # (transition/path.transition_path_record_batch): the per-lane excess
    # demand is formed on device, one stacked [S, 3T+1] fetch per round.
    z64_s = place(stacked["z"], jnp.float64)
    labor64 = jnp.asarray(model.labor_raw, jnp.float64)
    alpha64 = jnp.asarray(tech.alpha, jnp.float64)
    delta64 = jnp.asarray(tech.delta, jnp.float64)

    r_paths = np.full((S, T), r_ss)
    conv = np.zeros(S, bool)
    quar = np.zeros(S, bool)
    max_d = np.full(S, np.inf)
    out = None
    rec = None
    rounds = 0
    hist: list = []
    bits_hist: list = []
    for rnd in range(trans.max_iter):
        it_t0 = time.perf_counter()
        dt_name = stage_names[stage]
        dt = jnp.dtype(dt_name)
        beta_dev, sig_dev, amin_dev = stage_params(dt_name)
        w_s = wage_from_r(r_paths, tech.alpha, tech.delta, stacked["z"])
        r_ext_s = np.concatenate([r_paths, np.full((S, 1), r_ss)], axis=1)
        out = transition_path_record_batch(
            *anchors.get(dt_name),
            place(r_ext_s, dt), place(w_s, dt), beta_dev, sig_dev, amin_dev,
            place(r_paths, jnp.float64), z64_s, labor64, alpha64, delta64,
            matmul_precision=_stage_matmul_precision(ladder, stage),
            pushforward=pushforward, egm_kernel=egm_kernel)
        # ONE stacked device_get per round: [S, K_ts (T+1) | D (T) | A_ts].
        rec = np.asarray(jax.device_get(out["record"]), np.float64)
        K_s = rec[:, :T + 1]
        D = rec[:, T + 1:2 * T + 1]
        rounds = rnd + 1
        final_stage = stage == len(stage_names) - 1
        if not final_stage:
            # Count every hot-evaluated round (single-solve rationale).
            hot_rounds = rounds
        max_d = np.max(np.abs(D), axis=1)
        if quarantine:
            # Freeze newly-diverged lanes (non-finite excess on a lane not
            # yet converged): their paths stay pinned, their updates are
            # masked below, and the still-healthy lanes keep iterating.
            quar = quar | (~np.isfinite(max_d) & ~conv)
        live = ~quar
        hist.append(float(np.max(np.where(live, max_d, 0.0), initial=0.0)))
        bits_hist.append(int(jnp.finfo(dt).bits))
        if final_stage:
            # Scenarios are only marked converged from final-dtype
            # evaluations — a hot-stage residual certifies nothing.
            conv = conv | (np.isfinite(max_d) & (max_d < trans.tol) & live)
        if on_iteration is not None:
            on_iteration({"round": rnd,
                          "max_excess": float(np.max(np.where(live, max_d,
                                                              0.0),
                                                     initial=0.0)),
                          "converged": int(np.sum(conv)),
                          "quarantined": int(np.sum(quar)),
                          "dtype": dt_name,
                          "seconds": time.perf_counter() - it_t0})
        # Pod-observatory heartbeat (diagnostics/progress.py): per-scenario
        # round state on the active ledger at the configured stride — host
        # code only, the round program is untouched.
        if heartbeat_stride():
            sweep_heartbeat(
                "mit_transition_sweep", round_index=rnd,
                gap=[float(v) for v in max_d],
                converged=[bool(c) for c in conv],
                quarantined=[bool(q) for q in quar],
                dtype=dt_name)
        if not final_stage and np.all(np.isfinite(max_d[live])):
            floor = (float(ladder.switch_ulp)
                     * float(jnp.finfo(dt).eps)
                     * float(np.max(np.abs(K_s[live]), initial=0.0)))
            if float(np.max(max_d[live], initial=0.0)) < max(trans.tol,
                                                             floor):
                # Global switch: every live scenario's residual is at the
                # hot noise floor — re-evaluate the SAME paths wider.
                switch_excess = float(np.max(max_d[live], initial=0.0))
                stage += 1
                continue
        if (conv | quar).all():
            break
        if not np.all(np.isfinite(max_d[live])):
            bad = [i for i in range(S) if not np.isfinite(max_d[i])]
            raise FloatingPointError(
                f"transition sweep diverged at round {rnd} for scenario(s) "
                f"{bad}; try method='damped' or smaller shocks")
        if rnd == trans.max_iter - 1:
            # Round cap: keep the paths the final evaluation used — the
            # same never-evaluated-update consistency rule as the single
            # solve (converged scenarios are pinned either way).
            break
        if trans.method == "newton":
            step = D @ jac_inv.T                               # [S, T]
        else:
            r_implied = r_from_capital(
                np.maximum(K_s[:, :T], 1e-10), model.labor_raw,
                tech.alpha, tech.delta, stacked["z"])
            step = trans.damping * (r_paths - r_implied)
        # A quarantined lane's step is NaN; the mask pins its path, so the
        # NaN never reaches the carried candidate.
        r_paths = np.where((conv | quar)[:, None], r_paths,
                           np.clip(r_paths - step, -tech.delta + 1e-3,
                                   _R_CEIL))

    wall = time.perf_counter() - t0
    verdicts = ["converged" if c else ("nan" if q else "max_iter")
                for c, q in zip(conv, quar)]
    return TransitionSweepResult(
        r_paths=r_paths,
        K_ts=rec[:, :T + 1],
        max_excess=max_d,
        converged=conv,
        rounds=rounds,
        scenarios=S,
        solve_seconds=wall,
        transitions_per_sec=S / wall if wall > 0 else float("inf"),
        shocks=shocks,
        method=trans.method,
        T=T,
        r_ss=r_ss,
        ss=ss,
        jacobian=jacobian,
        hot_rounds=hot_rounds,
        switch_excess=switch_excess,
        telemetry=_round_telemetry(hist, bits_hist),
        quarantined=quar,
        verdicts=verdicts,
    )
