"""One-program transitions: the WHOLE MIT-shock solve as one XLA program.

The host round loop (transition/mit.py) pays one program launch plus one
host sync per Newton round: every round launches the backward dated-EGM
scan + forward push program, fetches the aggregates, and applies the
Newton/damped update on host. At the ci calibration that is ~4-5 launches
and ~4-5 device->host syncs per solve — and coalesced transition batches
are the single most expensive serve workload (BENCH_r14_serve.json), so
that dispatch overhead IS the wall the serve knee sits on.

This module moves the round loop into the program: the backward
`lax.scan` over dated EGM steps, the forward distribution push, the
per-round max excess demand, and the price-path update all live inside
ONE `lax.while_loop` carry, so an entire transition is one device
program launch and one small device_get. Two shapes:

  * solve_transition_fused — the serial Newton/damped-BKM round loop in
    the carry. Each loop round evaluates the carried candidate path
    (backward_policies + forward_capital, the exact per-round program the
    host loop launches), forms the excess demand against the firm FOC,
    and updates the path: Newton applies the PRECOMPUTED fake-news
    Jacobian inverse as one [T, T] @ [T] MXU matmul in the carry —
    `np.linalg.solve` has no in-loop analogue, so the factorization is
    hoisted to the host once per solve (J is round-invariant: it is the
    steady-state linearization) and the loop pays a matmul, not a solve.
    The damped-BKM update is the same `(1-damping) r + damping r_implied`
    convex combination as the host loop.

  * solve_transitions_sweep_fused — the lockstep scenario round of
    solve_transitions_sweep, fused: the vmapped backward+forward batch
    evaluation, the per-lane excess demand, the quarantine mask for
    non-finite lanes, and the masked Newton/damped update (`jnp.where`
    selects, converged/quarantined lanes pinned) all run inside the same
    while_loop. Healthy lanes stay BITWISE identical to a clean fused
    sweep of the same batch shape (vmapped lanes are independent), the
    quarantine pin tests/test_fused_transition.py holds.

Contracts threaded through the fusion (ISSUE 19, the PR 18 discipline):

  * AIYA107 nan-exit — the serial cond reads the carried max excess
    (init +inf: round one must run, and a NaN round concretely fails
    `max_d >= thr`); the sweep's final-stage cond reads only bool/int
    carries (NaN lanes are quarantined IN THE BODY before the cond sees
    them), and its hot-stage cond's live-lane max is NaN-poisoned to
    False exactly like the serial cond.
  * AIYA101 scatter-free — per-round history records are one-hot
    `jnp.where(iota == it, ...)` selects, never `.at[]` scatters.
  * sentinel / telemetry — the carry threads the in-program residual
    ring and failure sentinel (telemetry_init/record, sentinel_update/
    cond) so the audited artifacts match the GE fused programs.
  * buffer donation — the candidate rate path (and the sweep's [S, T]
    twin), the terminal-policy anchor, and the initial-distribution
    anchor are `donate_argnums`; the anchors are CACHED device arrays
    (_StageAnchors), so the solve wrappers defensively copy them before
    every donated call (the fused-GE warm-start contract).

Host-vs-device placement is the TransitionConfig.loop knob, routed by
dispatch.solve_transition / dispatch.sweep_transitions via
resolve_transition_loop; the host loops stay the parity reference
(tests/test_fused_transition.py pins serial fused-vs-host r-path parity
at <= 1e-10 for unladdered Newton).

Known (documented) deviations from the host reference:

  * Ladder stages chain one while_loop program PER stage dtype (the
    switch threshold `max(tol, switch_ulp * eps(hot) * max|K|)` lives in
    the hot-stage cond); the Newton/damped update of a HOT round runs in
    the hot dtype, where the host loop updates in f64. Convergence is
    still only certified from a final-dtype evaluation against tol, so
    the certificate matches — the hot-path difference is below the
    switch threshold by construction (the ladder band the parity test
    documents).
  * The host sentinel's stall/explosion verdicts use the trailing-window
    host_verdict rule; the fused loop carries the in-program sentinel
    (diagnostics/sentinel.py) instead. The "nan" verdict — the one the
    rescue ladder keys on — is pinned identical.
  * An all-lanes-quarantined hot sweep stage stops immediately; the host
    loop burns one more (pinned, no-op) evaluation in the wider dtype.
"""

from __future__ import annotations

import time
from functools import lru_cache
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from aiyagari_tpu.config import (
    AiyagariConfig,
    EquilibriumConfig,
    MITShock,
    SolverConfig,
    TransitionConfig,
)
from aiyagari_tpu.diagnostics.sentinel import (
    VERDICT_NAMES,
    sentinel_cond,
    sentinel_init,
    sentinel_update,
)
from aiyagari_tpu.diagnostics.telemetry import telemetry_init, telemetry_record
from aiyagari_tpu.models.aiyagari import AiyagariModel
from aiyagari_tpu.transition.mit import (
    _R_CEIL,
    TransitionResult,
    TransitionSweepResult,
    _as_model,
    _check_anchor,
    _check_trans,
    _device_paths,
    _egm_kernel_of,
    _pushforward_of,
    _round_telemetry,
    _stage_dtype_names,
    _stage_matmul_precision,
    _StageAnchors,
    shock_paths,
    stationary_anchor,
    transition_jacobian,
)
from aiyagari_tpu.transition.path import (
    backward_policies,
    forward_capital,
    transition_path,
)
from aiyagari_tpu.utils.firm import capital_demand, r_from_capital, wage_from_r

__all__ = [
    "resolve_transition_loop",
    "fused_transition_knobs",
    "fused_transition_program",
    "fused_transition_operands",
    "solve_transition_fused",
    "fused_transition_sweep_program",
    "fused_transition_sweep_operands",
    "solve_transitions_sweep_fused",
]

# Donated slots in the fused program signatures: the candidate rate path
# and the two [N, na] anchor operands (terminal policy, initial
# distribution). The anchors are loop-INVARIANT (every round restarts the
# backward scan from the stationary policy), so XLA mainly cashes in the
# rate-path carry alias; donation still deletes all three argument
# buffers, which is why the solve wrappers copy the cached anchors.
_DONATE_SERIAL = (0, 1, 2)   # (R0, C_TERM, MU0, ...model/path operands)
_DONATE_SWEEP = (0, 3, 4)    # (R0, conv0, quar0, C_TERM, MU0, ...)


def resolve_transition_loop(trans: TransitionConfig, *,
                            endogenous_labor: bool, mesh=None,
                            on_iteration=None) -> str:
    """Resolve TransitionConfig.loop to a concrete placement.

    "auto" picks "device" exactly where the fused program exists —
    exogenous labor, no scenario mesh, no per-round host callback — and
    falls back to "host" elsewhere. An EXPLICIT "device" on an
    unsupported combo is loud (the resolve_ge_loop contract), never a
    silent host fallback. egm_kernel='pallas_inverse' is rejected for
    EVERY transition path by _egm_kernel_of (the windowed route's
    host-escape-retry cannot ride the dated scan), so it needs no case
    here.
    """
    loop = getattr(trans, "loop", "host")
    if loop == "host":
        return "host"
    supported = (not endogenous_labor and mesh is None
                 and on_iteration is None)
    if loop == "auto":
        return "device" if supported else "host"
    if not supported:
        why = ("the endogenous-labor families are host-loop only"
               if endogenous_labor else
               "mesh-sharded sweeps keep the host lockstep loop "
               "(per-shard placement)"
               if mesh is not None else
               "per-round on_iteration callbacks need the host loop "
               "(one program per round)")
        raise ValueError(
            f"TransitionConfig(loop='device') is unsupported here: {why}; "
            "use loop='auto' to fall back to the host loop")
    return "device"


def fused_transition_knobs(model: AiyagariModel, trans: TransitionConfig,
                           solver: Optional[SolverConfig] = None, *,
                           matmul_precision: str = "highest",
                           floor_scale: float = 0.0):
    """The static-knob tuple the fused program builders destructure (the
    fused_knobs idiom). floor_scale > 0 marks a HOT ladder stage: the
    cond's threshold becomes max(tol, floor_scale * max|K_ts|) — the
    host loop's error-controlled switch criterion — and floor_scale is
    switch_ulp * eps(stage dtype), a static per-stage constant."""
    tech = model.config.technology
    return (
        int(trans.T), int(trans.max_iter), float(trans.tol),
        float(trans.damping), str(trans.method),
        float(tech.alpha), float(tech.delta),
        _pushforward_of(solver), _egm_kernel_of(solver),
        str(matmul_precision), float(floor_scale),
        solver.telemetry if solver is not None else None,
        solver.sentinel if solver is not None else None,
    )


def _round_closure(knobs: tuple, *, batched: bool):
    """(eval_paths, update) closures over the static knobs: one round's
    path evaluation (the exact backward+forward program the host loop
    launches) and the Newton/damped path update."""
    (T, _max_iter, _tol, damping, method, alpha, delta, pushforward,
     egm_kernel, matmul_precision, _floor_scale, _tele, _sent) = knobs
    from aiyagari_tpu.ops.pushforward import resolve_backend

    pushforward = resolve_backend(pushforward, batched=batched)

    def eval_paths(r_ext, w, beta, sigma_ext, amin, C_term, mu0, a_grid,
                   s, P):
        _, k_ts = backward_policies(
            C_term, a_grid, s, P, r_ext, w, beta, sigma_ext, amin,
            matmul_precision=matmul_precision, egm_kernel=egm_kernel)
        return forward_capital(mu0, k_ts, a_grid, P,
                               pushforward=pushforward)

    def update(r, K_head, D, z, labor_raw, jac_inv):
        if method == "newton":
            # The hoisted-factorization Newton step: J^{-1} applied as a
            # matmul in the carry (module docstring). Serial: [T,T]@[T];
            # sweep: [S,T]@[T,T]^T — the host loop's solve(J, D.T).T.
            step = (jac_inv @ D if D.ndim == 1 else D @ jac_inv.T)
            upd = r - step
        else:
            r_implied = r_from_capital(jnp.maximum(K_head, 1e-10),
                                       labor_raw, alpha, delta, z)
            upd = (1.0 - damping) * r + damping * r_implied
        return jnp.clip(upd, -delta + 1e-3, _R_CEIL)

    return eval_paths, update


@lru_cache(maxsize=None)
def _fused_transition(knobs: tuple, donate: bool):
    """Build + jit the serial fused transition round loop. Cache key =
    everything that changes the traced program plus the donation split —
    the donated and undonated twins are distinct executables."""
    (T, max_iter, tol, _damping, method, alpha, delta, _pf, _ek,
     _mp, floor_scale, telemetry_cfg, sentinel_cfg) = knobs
    eval_paths, update = _round_closure(knobs, batched=False)

    def _solve(r0, C_term0, mu0, a_grid, s, P, z, beta, sigma_ext, amin,
               labor_raw, r_ss, rounds_left, jac_inv):
        dt = a_grid.dtype
        iota = jnp.arange(max_iter, dtype=jnp.int32)

        carry = {
            # "cand" is the path the NEXT round evaluates; "r" the path
            # the LAST round evaluated — the round-cap consistency rule
            # (a never-evaluated update must not pair with this round's
            # aggregates) falls out of returning "r".
            "cand": r0,
            "r": r0,
            # +inf, not 0/nan: round one must run (inf >= thr) and a
            # nan-poisoned round must FAIL the cond (nan >= thr is
            # False) — the AIYA107 nan-early-exit contract.
            "max_d": jnp.asarray(jnp.inf, dt),
            # zeros, not nan: the hot-stage cond reads max|K| for its
            # switch floor, and round one must see a finite threshold.
            "K": jnp.zeros((T + 1,), dt),
            "A": jnp.zeros((T,), dt),
            "D": jnp.zeros((T,), dt),
            "mu": mu0,
            "it": jnp.asarray(0, jnp.int32),
            "hist": jnp.full((max_iter,), jnp.nan, dt),
            "tele": telemetry_init(telemetry_cfg),
            "sent": sentinel_init(sentinel_cfg),
        }

        def cond(c):
            if floor_scale:
                # Hot ladder stage: the host loop's error-controlled
                # switch — stop when the residual reaches the hot
                # dtype's noise floor in units of K.
                thr = jnp.maximum(jnp.asarray(tol, dt),
                                  floor_scale * jnp.max(jnp.abs(c["K"])))
            else:
                thr = jnp.asarray(tol, dt)
            base = (c["max_d"] >= thr) & (c["it"] < rounds_left)
            return sentinel_cond(c["sent"], base)

        def body(c):
            r = c["cand"]
            w = wage_from_r(r, alpha, delta, z)
            r_ext = jnp.concatenate([r, r_ss[None]])
            K_ts, A_ts, mu_T = eval_paths(r_ext, w, beta, sigma_ext, amin,
                                          C_term0, mu0, a_grid, s, P)
            D = K_ts[:T] - capital_demand(r, labor_raw, alpha, delta, z)
            max_d = jnp.max(jnp.abs(D))
            cand = update(r, K_ts[:T], D, z, labor_raw, jac_inv)
            # History writes as one-hot selects, not .at[] scatters —
            # the fused program stays scatter-free (AIYA101).
            sel = iota == c["it"]
            return {
                "cand": cand,
                "r": r,
                "max_d": max_d,
                "K": K_ts,
                "A": A_ts,
                "D": D,
                "mu": mu_T,
                "it": c["it"] + 1,
                "hist": jnp.where(sel, max_d, c["hist"]),
                "tele": telemetry_record(c["tele"], max_d),
                "sent": sentinel_update(c["sent"], max_d,
                                        config=sentinel_cfg),
            }

        return lax.while_loop(cond, body, carry)

    if method == "newton":
        def program(r0, C_term0, mu0, a_grid, s, P, z, beta, sigma_ext,
                    amin, labor_raw, r_ss, rounds_left, jac_inv):
            return _solve(r0, C_term0, mu0, a_grid, s, P, z, beta,
                          sigma_ext, amin, labor_raw, r_ss, rounds_left,
                          jac_inv)
    else:
        def program(r0, C_term0, mu0, a_grid, s, P, z, beta, sigma_ext,
                    amin, labor_raw, r_ss, rounds_left):
            return _solve(r0, C_term0, mu0, a_grid, s, P, z, beta,
                          sigma_ext, amin, labor_raw, r_ss, rounds_left,
                          None)

    donate_argnums = _DONATE_SERIAL if donate else ()
    return jax.jit(program, donate_argnums=donate_argnums)


@lru_cache(maxsize=None)
def _fused_transition_sweep(knobs: tuple, S: int, quarantine: bool,
                            donate: bool):
    """Build + jit the lockstep fused scenario sweep: the vmapped
    backward+forward batch round INSIDE the while_loop, quarantine lanes
    masked by select. With quarantine=False the carry threads a "bad"
    flag instead — any non-finite lane exits the loop and the host
    wrapper raises the historical all-or-nothing FloatingPointError."""
    (T, max_iter, tol, _damping, method, alpha, delta, _pf, _ek,
     _mp, floor_scale, telemetry_cfg, sentinel_cfg) = knobs
    eval_paths, update = _round_closure(knobs, batched=True)
    final_stage = not floor_scale

    def _solve(r0, conv0, quar0, C_term0, mu0, a_grid, s, P, z_s, beta_s,
               sig_ext_s, amin_s, labor_raw, r_ss, rounds_left, jac_inv):
        dt = a_grid.dtype
        iota = jnp.arange(max_iter, dtype=jnp.int32)

        def lane(r_ext, w, beta, sig_ext, amin):
            K_ts, _, _ = eval_paths(r_ext, w, beta, sig_ext, amin,
                                    C_term0, mu0, a_grid, s, P)
            return K_ts

        batch_eval = jax.vmap(lane)

        carry = {
            "cand": r0,
            "r": r0,
            "max_d": jnp.full((S,), jnp.inf, dt),
            "K": jnp.zeros((S, T + 1), dt),
            "conv": conv0,
            "quar": quar0,
            "it": jnp.asarray(0, jnp.int32),
            "hist": jnp.full((max_iter,), jnp.nan, dt),
            "tele": telemetry_init(telemetry_cfg),
            "sent": sentinel_init(sentinel_cfg),
        }
        if not quarantine:
            carry["bad"] = jnp.asarray(False)

        def cond(c):
            base = (~jnp.all(c["conv"] | c["quar"])
                    & (c["it"] < rounds_left))
            if not quarantine:
                base = base & ~c["bad"]
            if floor_scale:
                # Global hot-stage switch over the LIVE lanes (the host
                # loop's criterion); a NaN live lane poisons live_max and
                # concretely fails the cond — the AIYA107 contract, and
                # exactly the host loop's skip-the-switch behavior.
                live = ~c["quar"]
                live_max = jnp.max(jnp.where(live, c["max_d"], 0.0))
                kmax = jnp.max(jnp.where(live[:, None],
                                         jnp.abs(c["K"]), 0.0))
                thr = jnp.maximum(jnp.asarray(tol, dt),
                                  floor_scale * kmax)
                base = base & (live_max >= thr)
            return sentinel_cond(c["sent"], base)

        def body(c):
            r = c["cand"]
            w_s = wage_from_r(r, alpha, delta, z_s)
            r_ext_s = jnp.concatenate(
                [r, jnp.broadcast_to(r_ss, (S, 1)).astype(dt)], axis=1)
            K_s = batch_eval(r_ext_s, w_s, beta_s, sig_ext_s, amin_s)
            D = K_s[:, :T] - capital_demand(r, labor_raw, alpha, delta,
                                            z_s)
            max_d = jnp.max(jnp.abs(D), axis=1)
            if quarantine:
                # Freeze newly-diverged lanes: paths pinned, updates
                # masked, excluded from the all-converged check.
                quar = c["quar"] | (~jnp.isfinite(max_d) & ~c["conv"])
            else:
                quar = c["quar"]
            live = ~quar
            live_max = jnp.max(jnp.where(live, max_d, 0.0))
            conv = c["conv"]
            if final_stage:
                # Only final-dtype evaluations certify convergence.
                conv = conv | (jnp.isfinite(max_d) & (max_d < tol) & live)
            cand = update(r, K_s[:, :T], D, z_s, labor_raw, jac_inv)
            # A quarantined lane's step is NaN; the mask pins its path,
            # so the NaN never reaches the carried candidate.
            cand = jnp.where((conv | quar)[:, None], r, cand)
            sel = iota == c["it"]
            out = {
                "cand": cand,
                "r": r,
                "max_d": max_d,
                "K": K_s,
                "conv": conv,
                "quar": quar,
                "it": c["it"] + 1,
                "hist": jnp.where(sel, live_max, c["hist"]),
                "tele": telemetry_record(c["tele"], live_max),
                "sent": sentinel_update(c["sent"], live_max,
                                        config=sentinel_cfg),
            }
            if not quarantine:
                out["bad"] = c["bad"] | jnp.any(~jnp.isfinite(max_d))
            return out

        return lax.while_loop(cond, body, carry)

    if method == "newton":
        def program(r0, conv0, quar0, C_term0, mu0, a_grid, s, P, z_s,
                    beta_s, sig_ext_s, amin_s, labor_raw, r_ss,
                    rounds_left, jac_inv):
            return _solve(r0, conv0, quar0, C_term0, mu0, a_grid, s, P,
                          z_s, beta_s, sig_ext_s, amin_s, labor_raw,
                          r_ss, rounds_left, jac_inv)
    else:
        def program(r0, conv0, quar0, C_term0, mu0, a_grid, s, P, z_s,
                    beta_s, sig_ext_s, amin_s, labor_raw, r_ss,
                    rounds_left):
            return _solve(r0, conv0, quar0, C_term0, mu0, a_grid, s, P,
                          z_s, beta_s, sig_ext_s, amin_s, labor_raw,
                          r_ss, rounds_left, None)

    donate_argnums = _DONATE_SWEEP if donate else ()
    return jax.jit(program, donate_argnums=donate_argnums)


def fused_transition_program(model: AiyagariModel, *,
                             trans: TransitionConfig = TransitionConfig(),
                             solver: Optional[SolverConfig] = None,
                             matmul_precision: str = "highest",
                             floor_scale: float = 0.0,
                             donate: bool = False):
    """The compiled serial fused-transition entry for `model`'s static
    geometry. Call with fused_transition_operands(...); donate=True hands
    the rate-path/anchor argument buffers to XLA (the caller must not
    reuse them)."""
    if model.config.endogenous_labor:
        raise ValueError(
            "the fused transition loop supports exogenous labor only; "
            "use loop='host' (resolve_transition_loop routes this)")
    knobs = fused_transition_knobs(model, trans, solver,
                                   matmul_precision=matmul_precision,
                                   floor_scale=floor_scale)
    return _fused_transition(knobs, bool(donate))


def fused_transition_sweep_program(model: AiyagariModel, S: int, *,
                                   trans: TransitionConfig =
                                   TransitionConfig(),
                                   solver: Optional[SolverConfig] = None,
                                   matmul_precision: str = "highest",
                                   floor_scale: float = 0.0,
                                   quarantine: bool = True,
                                   donate: bool = False):
    """The compiled lockstep fused-sweep entry for S scenarios."""
    if model.config.endogenous_labor:
        raise ValueError(
            "the fused transition sweep supports exogenous labor only; "
            "use loop='host' (resolve_transition_loop routes this)")
    knobs = fused_transition_knobs(model, trans, solver,
                                   matmul_precision=matmul_precision,
                                   floor_scale=floor_scale)
    return _fused_transition_sweep(knobs, int(S), bool(quarantine),
                                   bool(donate))


def fused_transition_operands(model: AiyagariModel, shock: MITShock,
                              trans: TransitionConfig, *,
                              ss=None, jac_inv=None, r_path=None,
                              rounds_left: Optional[int] = None,
                              dtype=None):
    """Operand tuple for fused_transition_program. With `ss` the anchors
    are the stationary terminal policy / initial distribution (COPIED, so
    a donated call cannot delete the cached arrays); without, synthetic
    anchors seed a trace-only call (the registry audit's use). jac_inv
    defaults to the identity for trace-only Newton builds."""
    dt = jnp.dtype(model.dtype if dtype is None else dtype)
    T = int(trans.T)
    paths = shock_paths(model, shock, T)
    N, na = model.P.shape[0], model.a_grid.shape[0]
    if ss is not None:
        r_ss = float(ss.r)
        C_term = jnp.array(ss.solution.policy_c, dtype=dt, copy=True)
        mu0 = jnp.array(ss.mu, dtype=dt, copy=True)
    else:
        r_ss = 0.03
        from aiyagari_tpu.solvers.egm import initial_consumption_guess

        tech = model.config.technology
        C_term = jnp.asarray(initial_consumption_guess(
            model.a_grid, model.s, r_ss,
            wage_from_r(r_ss, tech.alpha, tech.delta)), dt)
        mu0 = jnp.full((N, na), 1.0 / (N * na), dt)
    r0 = (jnp.full((T,), r_ss, dt) if r_path is None
          else jnp.array(r_path, dtype=dt, copy=True))
    sig_ext = np.concatenate([paths["sigma"],
                              [model.preferences.sigma]])
    sc = lambda x: jnp.asarray(x, dt)
    ops = (r0, C_term, mu0, jnp.asarray(model.a_grid, dt),
           jnp.asarray(model.s, dt), jnp.asarray(model.P, dt),
           sc(paths["z"]), sc(paths["beta"]), sc(sig_ext),
           sc(paths["amin"]), sc(model.labor_raw), sc(r_ss),
           jnp.asarray(trans.max_iter if rounds_left is None
                       else rounds_left, jnp.int32))
    if trans.method == "newton":
        ops = ops + (jnp.asarray(np.eye(T) if jac_inv is None else jac_inv,
                                 dt),)
    return ops


def fused_transition_sweep_operands(model: AiyagariModel,
                                    shocks: Sequence[MITShock],
                                    trans: TransitionConfig, *,
                                    ss=None, jac_inv=None,
                                    dtype=None):
    """Operand tuple for fused_transition_sweep_program (trace/bench use;
    solve_transitions_sweep_fused assembles per-stage operands itself)."""
    dt = jnp.dtype(model.dtype if dtype is None else dtype)
    T = int(trans.T)
    S = len(shocks)
    serial = fused_transition_operands(model, shocks[0], trans, ss=ss,
                                       jac_inv=jac_inv, dtype=dtype)
    all_paths = [shock_paths(model, sh, T) for sh in shocks]
    stacked = {k: np.stack([p[k] for p in all_paths])
               for k in ("z", "beta", "sigma", "amin")}
    sig_ext_s = np.concatenate(
        [stacked["sigma"], np.full((S, 1), model.preferences.sigma)],
        axis=1)
    sc = lambda x: jnp.asarray(x, dt)
    r0 = jnp.broadcast_to(serial[0], (S, T)).copy()
    ops = (r0, jnp.zeros((S,), bool), jnp.zeros((S,), bool),
           serial[1], serial[2], serial[3], serial[4], serial[5],
           sc(stacked["z"]), sc(stacked["beta"]), sc(sig_ext_s),
           sc(stacked["amin"]), serial[10], serial[11], serial[12])
    if trans.method == "newton":
        ops = ops + (serial[13],)
    return ops


def _stage_floor_scale(ladder, stage: int, n_stages: int,
                       dt_name: str) -> float:
    """The per-stage switch-floor constant: switch_ulp * eps(hot dtype)
    for hot stages, 0.0 (cond threshold = tol) for the final stage."""
    if ladder is None or stage == n_stages - 1:
        return 0.0
    return float(ladder.switch_ulp) * float(jnp.finfo(jnp.dtype(dt_name)).eps)


def _newton_inverse(trans: TransitionConfig, jacobian) -> Optional[np.ndarray]:
    """The hoisted Newton factorization: J^{-1} computed ONCE per solve on
    host, applied as a matmul in the carry (module docstring)."""
    if trans.method != "newton":
        return None
    return np.linalg.inv(np.asarray(jacobian, np.float64))


def solve_transition_fused(
    model: Union[AiyagariModel, AiyagariConfig],
    shock: MITShock,
    *,
    trans: TransitionConfig = TransitionConfig(),
    solver: Optional[SolverConfig] = None,
    eq: Optional[EquilibriumConfig] = None,
    ss=None,
    jacobian: Optional[np.ndarray] = None,
    anchor_warm_start=None,
    keep_policies: bool = True,
    dtype=jnp.float64,
    ladder=None,
    donate: bool = True,
) -> TransitionResult:
    """solve_transition with the round loop fused on-device: ONE program
    launch and ONE small device_get per ladder stage (one of each for the
    common unladdered solve), against the host loop's launch+sync per
    round. Same signature minus on_iteration (resolve_transition_loop
    gates callbacks to the host loop); same TransitionResult, pinned by
    tests/test_fused_transition.py."""
    t0 = time.perf_counter()
    model = _as_model(model, dtype)
    _check_trans(trans)
    T = int(trans.T)
    # Route validation BEFORE the anchor solve (the _egm_kernel_of raise
    # inside the knob build).
    base_knobs = fused_transition_knobs(model, trans, solver)
    pushforward = base_knobs[7]
    egm_kernel = base_knobs[8]
    if ss is None:
        ss = stationary_anchor(model, solver=solver, eq=eq,
                               warm_start=anchor_warm_start)
    _check_anchor(ss)
    from aiyagari_tpu.sim.distribution import aggregate_capital

    tech = model.config.technology
    r_ss = float(ss.r)
    K_ss = float(aggregate_capital(ss.mu, model.a_grid))
    paths = shock_paths(model, shock, T)
    if trans.method == "newton" and jacobian is None:
        jacobian = transition_jacobian(model, ss, T,
                                       pushforward=pushforward)
    jac_inv = _newton_inverse(trans, jacobian)

    stage_names = _stage_dtype_names(model, ladder)
    n_stages = len(stage_names)
    anchors = _StageAnchors(model, ss)
    sentinel_cfg = solver.sentinel if solver is not None else None
    sig_ext = np.concatenate([paths["sigma"], [model.preferences.sigma]])

    rounds = 0
    hot_rounds = 0
    switch_excess = 0.0
    hist: list = []
    bits_hist: list = []
    converged = False
    verdict = ""
    r_dev = None          # the last evaluated path, carried across stages
    out = None
    host = None
    for stage, dt_name in enumerate(stage_names):
        final = stage == n_stages - 1
        rounds_left = trans.max_iter - rounds
        if rounds_left <= 0:
            break
        dt = jnp.dtype(dt_name)
        floor_scale = _stage_floor_scale(ladder, stage, n_stages, dt_name)
        knobs = fused_transition_knobs(
            model, trans, solver,
            matmul_precision=_stage_matmul_precision(ladder, stage),
            floor_scale=floor_scale)
        fn = _fused_transition(knobs, bool(donate))
        policy_c, mu, a_grid, s_arr, P = anchors.get(dt_name)
        sc = lambda x: jnp.asarray(x, dt)
        args = (
            # Donated slots: a FRESH path buffer and COPIES of the cached
            # anchors (a donated call must not delete the cache entries).
            jnp.full((T,), r_ss, dt) if r_dev is None
            else jnp.array(r_dev, dtype=dt, copy=True),
            jnp.array(policy_c, dtype=dt, copy=True),
            jnp.array(mu, dtype=dt, copy=True),
            a_grid, s_arr, P,
            sc(paths["z"]), sc(paths["beta"]), sc(sig_ext),
            sc(paths["amin"]), sc(model.labor_raw), sc(r_ss),
            jnp.asarray(rounds_left, jnp.int32),
        )
        if trans.method == "newton":
            args = args + (sc(jac_inv),)
        out = fn(*args)
        small = {k: out[k] for k in ("r", "max_d", "K", "A", "D", "it",
                                     "hist")}
        if out["sent"] is not None:
            small["verdict_code"] = out["sent"].verdict
        # ONE device_get per stage program (one per solve unladdered) —
        # everything below is host numpy on the fetched dict.
        host = jax.device_get(small)
        it = int(host["it"])  # noqa: AIYA202 — host numpy post-device_get
        md = float(host["max_d"])  # noqa: AIYA202 — host numpy post-device_get
        hist += [float(v) for v in
                 np.asarray(host["hist"], np.float64)[:it]]
        bits_hist += [int(jnp.finfo(dt).bits)] * it
        rounds += it
        if not final:
            hot_rounds = rounds
        r_dev = out["r"]
        code = 0
        if "verdict_code" in host:
            code = int(host["verdict_code"])  # noqa: AIYA202 — host numpy post-device_get
        if not np.isfinite(md):
            if sentinel_cfg is not None:
                verdict = "nan"
                break
            raise FloatingPointError(
                f"transition path diverged at round {rounds - 1} "
                "(non-finite excess demand); try method='damped' or a "
                "smaller shock")
        if code != 0:
            verdict = VERDICT_NAMES[code]
            break
        if final:
            converged = md < trans.tol
            break
        kmax = float(np.max(np.abs(np.asarray(host["K"], np.float64))))
        if md < max(trans.tol, floor_scale * kmax):
            # The hot stage exited through its switch floor: re-evaluate
            # the SAME path at the next dtype (the host loop's continue).
            switch_excess = md
            continue
        break  # round cap burned inside the hot stage

    r_path = np.asarray(host["r"], np.float64)
    K_ts = np.asarray(host["K"], np.float64)
    D = np.asarray(host["D"], np.float64)
    policies = None
    if keep_policies:
        # One full evaluation at the final (already-evaluated) path for
        # the dated policy stacks the round loop never returns — the
        # host loop's post-loop materialization, unchanged.
        full = transition_path(ss.solution.policy_c, ss.mu, model.a_grid,
                               model.s, model.P,
                               *_device_paths(model, r_path, paths, r_ss),
                               pushforward=pushforward,
                               egm_kernel=egm_kernel)
        policies = {"C_ts": full["C_ts"], "k_ts": full["k_ts"]}
    return TransitionResult(
        r_path=r_path,
        w_path=np.asarray(wage_from_r(r_path, tech.alpha, tech.delta,
                                      paths["z"])),
        K_ts=K_ts,
        A_ts=np.asarray(host["A"], np.float64),
        excess=D,
        max_excess_history=hist,
        rounds=rounds,
        converged=converged,
        solve_seconds=time.perf_counter() - t0,
        method=trans.method,
        shock=shock,
        T=T,
        r_ss=r_ss,
        K_ss=K_ss,
        ss=ss,
        policies=policies,
        mu_T=out["mu"],
        jacobian=jacobian,
        hot_rounds=hot_rounds,
        switch_excess=switch_excess,
        telemetry=_round_telemetry(hist, bits_hist),
        verdict=verdict,
    )


def solve_transitions_sweep_fused(
    model: Union[AiyagariModel, AiyagariConfig],
    shocks: Sequence[MITShock],
    *,
    trans: TransitionConfig = TransitionConfig(),
    solver: Optional[SolverConfig] = None,
    eq: Optional[EquilibriumConfig] = None,
    ss=None,
    jacobian: Optional[np.ndarray] = None,
    anchor_warm_start=None,
    dtype=jnp.float64,
    ladder=None,
    quarantine: bool = True,
    donate: bool = True,
) -> TransitionSweepResult:
    """solve_transitions_sweep with the lockstep round loop fused
    on-device: the vmapped scenario round runs INSIDE one while_loop per
    ladder stage, quarantine masks and all. Same signature minus mesh /
    on_iteration (resolve_transition_loop gates both to the host loop);
    same TransitionSweepResult."""
    t0 = time.perf_counter()
    model = _as_model(model, dtype)
    _check_trans(trans)
    shocks = list(shocks)
    if not shocks:
        raise ValueError(
            "solve_transitions_sweep needs at least one shock")
    T = int(trans.T)
    S = len(shocks)
    base_knobs = fused_transition_knobs(model, trans, solver)
    pushforward = base_knobs[7]
    if ss is None:
        ss = stationary_anchor(model, solver=solver, eq=eq,
                               warm_start=anchor_warm_start)
    _check_anchor(ss)
    tech = model.config.technology
    r_ss = float(ss.r)
    if trans.method == "newton" and jacobian is None:
        jacobian = transition_jacobian(model, ss, T,
                                       pushforward=pushforward)
    jac_inv = _newton_inverse(trans, jacobian)

    all_paths = [shock_paths(model, sh, T) for sh in shocks]
    stacked = {k: np.stack([p[k] for p in all_paths])
               for k in ("z", "beta", "sigma", "amin")}
    sig_ext_s = np.concatenate(
        [stacked["sigma"], np.full((S, 1), model.preferences.sigma)],
        axis=1)

    stage_names = _stage_dtype_names(model, ladder)
    n_stages = len(stage_names)
    anchors = _StageAnchors(model, ss)

    rounds = 0
    hot_rounds = 0
    switch_excess = 0.0
    hist: list = []
    bits_hist: list = []
    conv = np.zeros(S, bool)
    quar = np.zeros(S, bool)
    max_d = np.full(S, np.inf)
    r_dev = None
    out = None
    host = None
    for stage, dt_name in enumerate(stage_names):
        final = stage == n_stages - 1
        rounds_left = trans.max_iter - rounds
        if rounds_left <= 0:
            break
        dt = jnp.dtype(dt_name)
        floor_scale = _stage_floor_scale(ladder, stage, n_stages, dt_name)
        knobs = fused_transition_knobs(
            model, trans, solver,
            matmul_precision=_stage_matmul_precision(ladder, stage),
            floor_scale=floor_scale)
        fn = _fused_transition_sweep(knobs, S, bool(quarantine),
                                     bool(donate))
        policy_c, mu, a_grid, s_arr, P = anchors.get(dt_name)
        sc = lambda x: jnp.asarray(x, dt)
        args = (
            jnp.full((S, T), r_ss, dt) if r_dev is None
            else jnp.array(r_dev, dtype=dt, copy=True),
            jnp.asarray(conv), jnp.asarray(quar),
            jnp.array(policy_c, dtype=dt, copy=True),
            jnp.array(mu, dtype=dt, copy=True),
            a_grid, s_arr, P,
            sc(stacked["z"]), sc(stacked["beta"]), sc(sig_ext_s),
            sc(stacked["amin"]), sc(model.labor_raw), sc(r_ss),
            jnp.asarray(rounds_left, jnp.int32),
        )
        if trans.method == "newton":
            args = args + (sc(jac_inv),)
        out = fn(*args)
        small = {k: out[k] for k in ("r", "max_d", "K", "conv", "quar",
                                     "it", "hist")}
        host = jax.device_get(small)
        it = int(host["it"])  # noqa: AIYA202 — host numpy post-device_get
        max_d = np.asarray(host["max_d"], np.float64)
        conv = np.asarray(host["conv"], bool)
        quar = np.asarray(host["quar"], bool)
        hist += [float(v) for v in
                 np.asarray(host["hist"], np.float64)[:it]]
        bits_hist += [int(jnp.finfo(dt).bits)] * it
        rounds += it
        if not final:
            hot_rounds = rounds
        r_dev = out["r"]
        if not quarantine and not np.all(np.isfinite(max_d)):
            bad = [i for i in range(S) if not np.isfinite(max_d[i])]
            raise FloatingPointError(
                f"transition sweep diverged at round {rounds - 1} for "
                f"scenario(s) {bad}; try method='damped' or smaller "
                "shocks")
        if final or (conv | quar).all():
            break
        live = ~quar
        live_max = float(np.max(np.where(live, max_d, 0.0), initial=0.0))
        kmax = float(np.max(np.abs(np.asarray(host["K"],
                                              np.float64))[live],
                            initial=0.0))
        if live_max < max(trans.tol, floor_scale * kmax):
            switch_excess = live_max
            continue
        break  # round cap burned inside the hot stage

    wall = time.perf_counter() - t0
    verdicts = ["converged" if c else ("nan" if q else "max_iter")
                for c, q in zip(conv, quar)]
    return TransitionSweepResult(
        r_paths=np.asarray(host["r"], np.float64),
        K_ts=np.asarray(host["K"], np.float64),
        max_excess=max_d,
        converged=conv,
        rounds=rounds,
        scenarios=S,
        solve_seconds=wall,
        transitions_per_sec=S / wall if wall > 0 else float("inf"),
        shocks=shocks,
        method=trans.method,
        T=T,
        r_ss=r_ss,
        ss=ss,
        jacobian=jacobian,
        hot_rounds=hot_rounds,
        switch_excess=switch_excess,
        telemetry=_round_telemetry(hist, bits_hist),
        quarantined=quar,
        verdicts=verdicts,
    )
