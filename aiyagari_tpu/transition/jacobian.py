"""Sequence-space Jacobian of the household block at the stationary
equilibrium, by the fake-news algorithm of Auclert, Bardoczy, Rognlie &
Straub (2021).

The object: J[t, s] = dA_t / dr_s — the response of aggregate end-of-period
asset supply at date t to a perfect-foresight interest-rate perturbation at
date s (with the wage moving along the firm FOC, dw_s = w'(r_ss) dr_s, so a
column is a joint (r, w) price shock — the GE-relevant direction). Naively
this is T backward solves x T forward pushes; the fake-news factorization
needs ONE of each:

  backward — a single jax.jvp through the T-step backward EGM scan with the
      shock dated T-1: by stationarity the policy response at date t to a
      shock at date s depends only on the lead u = s - t, so the one pass
      yields every anticipation derivative {dk_u}. (This is where jvp
      earns its keep over finite differences: machine-accurate
      derivatives of a 200-step scan at 2x the primal's cost.)

  forward — the expectation functions E_u = (Lambda')^u k_ss (what an agent
      expects to be saving u periods ahead under stationary dynamics), by
      iterating the adjoint push-forward sim/distribution.expectation_step.

Assembled into the fake-news matrix
      F[0, s] = <mu_ss, dk_s>          (impact response to news at lead s)
      F[t, s] = <E_{t-1}, dD_s>, t>=1  (a date-0 distribution perturbation
                                        dD_s = dLambda_s mu_ss, propagated
                                        t-1 periods and measured)
and accumulated along diagonals, J[t, s] = F[t, s] + J[t-1, s-1].

The T x T assembly runs on host (it is T^2 scalars; trivial next to the
device passes). The Jacobian is built ONCE at the stationary equilibrium
and reused across Newton rounds AND across every scenario of a transition
sweep — the shock only moves the residual, not the ss linearization
(transition/mit.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from aiyagari_tpu.ops.pushforward import pushforward_step
from aiyagari_tpu.sim.distribution import expectation_step, young_lottery
from aiyagari_tpu.transition.path import backward_policies
from aiyagari_tpu.utils.firm import capital_demand_slope

__all__ = ["fake_news_jacobian", "interpolate_jacobians",
           "newton_jacobian"]


def interpolate_jacobians(jacobians, weights) -> np.ndarray:
    """Distance-weighted interpolation of fake-news (or Newton) Jacobians
    from nearby anchor economies — the serve layer's transition
    amortization (ISSUE 16). The license is the near-linearity BKM (2018)
    document: J varies smoothly in the calibration, so a convex blend of
    neighboring anchors' Jacobians is an accurate Newton matrix for an
    economy between them. Correctness never rests on the accuracy —
    Newton's FIXED POINT is independent of the matrix used (the residual,
    not the matrix, defines convergence), so a converged path under an
    interpolated J equals the cold path's answer; a bad blend merely fails
    to converge, and the caller degrades to a cold solve.

    `jacobians` is a non-empty sequence of same-shaped [T, T] host
    matrices; `weights` a matching sequence of non-negative weights
    (normalized here). Returns host np.float64 [T, T]."""
    mats = [np.asarray(j, np.float64) for j in jacobians]
    if not mats:
        raise ValueError("interpolate_jacobians needs >= 1 jacobian")
    shape = mats[0].shape
    if len(shape) != 2 or shape[0] != shape[1]:
        raise ValueError(f"jacobians must be square [T, T], got {shape}")
    for m in mats[1:]:
        if m.shape != shape:
            raise ValueError(
                f"jacobian shape mismatch: {m.shape} vs {shape}")
    w = np.asarray(list(weights), np.float64)
    if w.shape != (len(mats),):
        raise ValueError(
            f"weights must align with jacobians: {w.shape} vs {len(mats)}")
    if np.any(w < 0.0) or not np.isfinite(w).all() or w.sum() <= 0.0:
        raise ValueError("weights must be non-negative, finite, and "
                         "not all zero")
    w = w / w.sum()
    out = np.zeros(shape, np.float64)
    for m, wi in zip(mats, w):
        out += wi * m
    return out


def fake_news_jacobian(C_ss, k_ss, mu_ss, a_grid, s, P, *, r_ss, w_ss,
                       w_slope, sigma, beta, amin, T: int,
                       pushforward: str = "auto") -> np.ndarray:
    """J[t, s] = dA_t/dr_s at the stationary equilibrium (module docstring).

    C_ss/k_ss [N, na] are the stationary consumption/asset policies, mu_ss
    the stationary distribution, (r_ss, w_ss) the stationary prices and
    w_slope = dw/dr along the firm FOC (the price link each column shocks
    jointly). Returns a host np.float64 [T, T] matrix.

    pushforward selects the DistributionBackend of the forward-pass
    push-forward whose jvp builds the distribution perturbations dD_u
    (ops/pushforward.py; the scatter-free routes are jvp-transparent —
    cumsum/gather/matmul primitives all carry exact tangents, and the
    monotonicity cond differentiates through the taken branch). The adjoint
    expectation functions keep the gather-form expectation_step, whose
    pairing <f, L mu> == <L' f, mu> holds against every backend.

    No egm_kernel knob here, deliberately: this pass DIFFERENTIATES
    backward_policies (jax.jvp below), and pallas_call carries no AD rule,
    so the fused sweep route (ops/pallas_egm.py) cannot serve it — the
    Jacobian's one-off T sweeps stay on the AD-transparent XLA chain while
    the round loops' primal path evaluations honor SolverConfig.egm_kernel
    (transition/mit.py _egm_kernel_of).
    """
    dt = a_grid.dtype
    ones = jnp.ones((T,), dt)
    sig_ext = jnp.full((T + 1,), sigma, dt)

    def bw(r_ext_in, w_in):
        # k_ts [T, N, na] under the given price path, ss terminal policy.
        return backward_policies(C_ss, a_grid, s, P, r_ext_in, w_in,
                                 beta * ones, sig_ext, amin * ones)[1]

    r_primal = jnp.full((T + 1,), r_ss, dt)
    w_primal = jnp.full((T,), w_ss, dt)
    # Shock at the LAST in-window date: r_ext[T-1] (r_ext[T] is the terminal
    # anchor and never perturbed), with the wage riding the FOC link.
    dr = jnp.zeros((T + 1,), dt).at[T - 1].set(1.0)
    dw = jnp.zeros((T,), dt).at[T - 1].set(jnp.asarray(w_slope, dt))

    @jax.jit
    def device_half():
        _, dk_ts = jax.jvp(bw, (r_primal, w_primal), (dr, dw))
        # dk_ts[t] = response at date t to the date-(T-1) shock = lead
        # u = T-1-t; flip to index by lead.
        dk_lead = jnp.flip(dk_ts, axis=0)                       # [T, N, na]

        # Impact row: y[u] = <mu_ss, dk_u>. HIGHEST precision like every
        # expectation matmul here: the TPU f32 default is a single bf16
        # pass, and Jacobian error feeds straight into the Newton step.
        y = jnp.einsum("uij,ij->u", dk_lead, mu_ss,
                       precision=jax.lax.Precision.HIGHEST)

        # Distribution perturbations: dD_u = d/dk [Lambda(k) mu_ss] . dk_u,
        # one jvp of the push-forward per lead, vmapped.
        def push(k):
            idx, w_lo = young_lottery(k, a_grid)
            return pushforward_step(mu_ss, idx, w_lo, P,
                                    backend=pushforward)

        dD = jax.vmap(
            lambda tang: jax.jvp(push, (k_ss,), (tang,))[1])(dk_lead)

        # Expectation functions E_0..E_{T-2} under stationary dynamics.
        idx_ss, wlo_ss = young_lottery(k_ss, a_grid)

        def exp_step(E, _):
            return expectation_step(E, idx_ss, wlo_ss, P), E

        _, E_stack = jax.lax.scan(exp_step, k_ss, None, length=T - 1)

        F1 = jnp.einsum("tij,sij->ts", E_stack, dD,
                        precision=jax.lax.Precision.HIGHEST)    # [T-1, T]
        return y, F1

    y, F1 = jax.device_get(device_half())
    F = np.empty((T, T), np.float64)
    F[0, :] = np.asarray(y, np.float64)
    F[1:, :] = np.asarray(F1, np.float64)
    # J[t, s] = F[t, s] + J[t-1, s-1]: accumulate down the diagonals.
    J = F.copy()
    for t in range(1, T):
        J[t, 1:] += J[t - 1, :-1]
    return J


def newton_jacobian(J_A: np.ndarray, *, r_ss: float, labor: float,
                    alpha: float, delta: float) -> np.ndarray:
    """Jacobian of the market-clearing residual D_t = K_t - K_d(r_t)
    (transition/mit.py) from the household-block Jacobian J_A = dA/dr:
    K_{t+1} == A_t (path.forward_capital's mean-preservation identity) puts
    J_A shifted down one row on the household side — row 0 is zero, K_0
    being predetermined — and the firm side contributes the diagonal
    -dK_d/dr at the stationary rate. Factor once, reuse every Newton round
    and every sweep scenario."""
    T = J_A.shape[0]
    J_D = np.zeros((T, T), np.float64)
    J_D[1:, :] = J_A[:-1, :]
    J_D[np.diag_indices(T)] -= float(
        capital_demand_slope(r_ss, labor, alpha, delta))
    return J_D
