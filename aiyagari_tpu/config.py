"""Typed, frozen, pytree-compatible configuration objects.

The reference hardcodes scalar constants at the top of every script with
inconsistent duplicated names across files (see /root/reference/Aiyagari_VFI.m:7-14,
Krusell_Smith_VFI.m:5-13, and the psi/eta vs phi/theta naming clash between
Aiyagari_Endogenous_Labor_VFI.m:14-15 and Aiyagari_Endogenous_Labor_EGM.m:10-11).
Here every model/solver/simulation/backend knob is a frozen dataclass so configs
hash (usable as jit static args) and serialize cleanly.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

# Owned by ops/precision.py (the ladder policy + stage planner live beside
# the kernels they steer); re-exported here because it is user-facing config
# exactly like AccelConfig. The module keeps its jax imports lazy, so
# importing it here does not drag jax into config-module import time.
from aiyagari_tpu.ops.precision import PrecisionLadderConfig

__all__ = [
    "HouseholdPreferences",
    "Technology",
    "IncomeProcess",
    "GridSpecConfig",
    "AiyagariConfig",
    "KSShockProcess",
    "KrusellSmithConfig",
    "AccelConfig",
    "PrecisionLadderConfig",
    "TelemetryConfig",
    "SentinelConfig",
    "FaultPlan",
    "RescueConfig",
    "SolverConfig",
    "SimConfig",
    "EquilibriumConfig",
    "ALMConfig",
    "BackendConfig",
    "MeshConfig",
    "MITShock",
    "TransitionConfig",
]


@dataclasses.dataclass(frozen=True)
class HouseholdPreferences:
    """CRRA preferences with optional additively separable labor disutility.

    u(c, l) = (c^(1-sigma) - 1)/(1-sigma) - psi * l^(1+eta)/(1+eta)

    Reference: sigma at Aiyagari_VFI.m:8; labor disutility psi/eta at
    Aiyagari_Endogenous_Labor_VFI.m:14-15 (called phi/theta in the EGM variant,
    Aiyagari_Endogenous_Labor_EGM.m:10-11 -- same role, unified here).
    """

    beta: float = 0.96
    sigma: float = 5.0
    psi: float = 1.0    # labor-disutility weight (endogenous-labor models only)
    eta: float = 2.0    # labor-disutility curvature (Frisch^-1)


@dataclasses.dataclass(frozen=True)
class Technology:
    """Cobb-Douglas production Y = z K^alpha L^(1-alpha), depreciation delta.

    Reference: Aiyagari_VFI.m:9-10; Krusell_Smith_VFI.m:5.
    """

    alpha: float = 0.36
    delta: float = 0.08


@dataclasses.dataclass(frozen=True)
class IncomeProcess:
    """AR(1) log-productivity discretized by the Tauchen method.

    log s' = rho log s + e,  e ~ N(0, sd^2), sd = sigma_e * sqrt(1-rho^2),
    on a fixed grid l_i = (i - (n+1)/2) * sigma_e  (reference uses n=7 so the
    grid is {-3..+3} * sigma_e; Aiyagari_VFI.m:18-23).

    method selects the discretization: "tauchen" (the reference's scheme) or
    "rouwenhorst" (exact persistence/variance match — preferred for rho near
    1; no analogue in the reference).
    """

    rho: float = 0.75
    sigma_e: float = 0.75
    n_states: int = 7
    method: str = "tauchen"


@dataclasses.dataclass(frozen=True)
class GridSpecConfig:
    """Power-spaced asset grid: amin + (amax-amin) * linspace(0,1,n)^power.

    Reference: quadratic (power=2) 400-point Aiyagari grid at Aiyagari_VFI.m:58;
    power-7 100-point Krusell-Smith grid at Krusell_Smith_VFI.m:16.
    Bounds of None mean "derive from model parameters" (Aiyagari_VFI.m:53-56).
    """

    n_points: int = 400
    power: float = 2.0
    amin: Optional[float] = None
    amax: Optional[float] = None


@dataclasses.dataclass(frozen=True)
class AiyagariConfig:
    """Full parameterization of an Aiyagari-class economy.

    endogenous_labor=False reproduces Aiyagari_VFI.m / Aiyagari_EGM.m;
    True reproduces the Endogenous_Labor variants (10-point labor grid on
    [0.01, 1.5] for VFI per Aiyagari_Endogenous_Labor_VFI.m:62, closed-form
    intratemporal FOC for EGM per Aiyagari_Endogenous_Labor_EGM.m:61-62).
    """

    preferences: HouseholdPreferences = HouseholdPreferences()
    technology: Technology = Technology()
    income: IncomeProcess = IncomeProcess()
    grid: GridSpecConfig = GridSpecConfig()
    borrowing_limit: float = 0.0          # b at Aiyagari_VFI.m:11
    endogenous_labor: bool = False
    labor_grid_n: int = 10                # VFI labor-choice grid size
    labor_grid_bounds: Tuple[float, float] = (0.01, 1.5)


@dataclasses.dataclass(frozen=True)
class KSShockProcess:
    """Krusell-Smith joint (aggregate z x idiosyncratic employment) chain,
    parameterized by duration targets exactly as Krusell_Smith_VFI.m:23-45.
    """

    z_good: float = 1.01
    z_bad: float = 0.99
    u_good: float = 0.04      # unemployment rate in good state (ug)
    u_bad: float = 0.10       # unemployment rate in bad state (ub)
    z_good_duration: float = 8.0
    z_bad_duration: float = 8.0
    u_good_duration: float = 1.5
    u_bad_duration: float = 2.5
    uu_rel_gb2bb: float = 1.25
    uu_rel_bg2gg: float = 0.75


@dataclasses.dataclass(frozen=True)
class KrusellSmithConfig:
    """Full parameterization of the Krusell-Smith economy.

    Reference constants: Krusell_Smith_VFI.m:5-13.
    """

    preferences: HouseholdPreferences = HouseholdPreferences(beta=0.99, sigma=1.0)
    technology: Technology = Technology(alpha=0.36, delta=0.025)
    shocks: KSShockProcess = KSShockProcess()
    k_min: float = 1e-4
    k_max: float = 1000.0
    k_size: int = 100
    k_power: float = 7.0
    K_min: float = 30.0
    K_max: float = 50.0
    K_size: int = 4
    mu: float = 0.0           # home production of the unemployed (mu at :9)

    @property
    def l_bar(self) -> float:
        # Labor endowment normalization 1/(1-ub): Krusell_Smith_VFI.m:10
        return 1.0 / (1.0 - self.shocks.u_bad)


@dataclasses.dataclass(frozen=True)
class AccelConfig:
    """Fixed-point acceleration for the framework's hot iteration loops
    (ops/accel.py): windowed Anderson mixing or SQUAREM extrapolation
    composed INSIDE the existing lax.while_loop bodies as pure carry
    transformers — same operator, same stopping rule, fewer sweeps.

    Opt-in via SolverConfig(accel=AccelConfig(...)): accelerates the EGM
    household solvers (single-device, labor, sharded, every multiscale
    ladder stage) and the Young stationary-distribution power iteration in
    the GE closures. The Krusell-Smith ALM outer loop has its own host-side
    switch (ALMConfig.acceleration), backed by the same module.

    Every step is safeguarded: when the extrapolated residual fails to
    decrease, the update falls back to the plain (damped) step and the
    history restarts, so a pathological operator degrades to the reference
    trajectory instead of diverging. Iterates with invariants re-project
    (distributions: clip negatives + renormalize; consumption: positivity
    floor). Frozen/hashable, so it rides jit static args directly.
    """

    method: str = "anderson"      # {"anderson", "squarem"}
    memory: int = 5               # Anderson history window m (differences kept)
    damping: float = 1.0          # Anderson only: weight on the plain step
                                  # inside the mixed update (1.0 = undamped);
                                  # SQUAREM is undamped by construction and
                                  # rejects any other value loudly
    regularization: float = 1e-8  # relative Tikhonov on the LS normal equations
    delay: int = 10               # plain burn-in sweeps before accelerating —
                                  # the early iterations of a kinked operator
                                  # (EGM's moving constraint boundary) poison
                                  # the history's linear model; measured ~15%
                                  # fewer total sweeps at the reference
                                  # calibration than accelerating from sweep 0
    safeguard_growth: float = 2.0  # residual growth factor tolerated before
                                  # the plain-step fallback + history restart
                                  # engages; 1.0 = strict monotone decrease,
                                  # which restarts on Anderson's normal
                                  # transient non-monotonicity and measurably
                                  # forfeits most of the acceleration


@dataclasses.dataclass(frozen=True)
class TelemetryConfig:
    """Device-resident flight recorder for the hot fixed-point loops
    (diagnostics/telemetry.py): a fixed-length ring buffer carried INSIDE
    each lax.while_loop capturing the per-sweep residual, the sweep's stage
    dtype, accel safeguard trips, and push-forward fallback tallies — no
    host callbacks, no device sync; the buffers come back on the Solution
    as a SolveTelemetry pytree (one recorder per scenario under vmap).

    Opt-in via SolverConfig(telemetry=TelemetryConfig(...)). None (the
    default) compiles the recorder OUT entirely: the recorder calls trace
    to nothing, the loop carries zero extra bytes, and the hot-path program
    is identical to the pre-telemetry one (tests/test_telemetry.py pins
    both the trajectory identity and the jaxpr no-op).

    capacity sizes the ring: the LAST `capacity` sweeps are kept (the tail
    is what the stall/oscillation certificates read; `count` keeps the true
    total, so truncation is visible, never silent). Frozen/hashable — it
    rides jit static args like AccelConfig.
    """

    capacity: int = 256


@dataclasses.dataclass(frozen=True)
class SentinelConfig:
    """Device-resident failure sentinel for the hot fixed-point loops
    (diagnostics/sentinel.py): a tiny state pytree carried INSIDE each
    lax.while_loop that watches the per-sweep residual for non-finite
    values, stalls, and explosions, and EARLY-EXITS the loop with a
    structured verdict ("nan" | "stall" | "explode" | "escape") instead of
    letting a poisoned or stuck solve burn `max_iter` sweeps on garbage.

    Opt-in via SolverConfig(sentinel=SentinelConfig(...)). None (the
    default) compiles the sentinel OUT entirely — the loop condition and
    carry trace to the exact pre-sentinel program (the TelemetryConfig
    zero-cost discipline; pinned by tests/test_resilience.py jaxpr
    assertions). The host-side outer loops (GE bisection rounds, transition
    Newton rounds) apply the same thresholds through
    diagnostics/sentinel.host_verdict when the sentinel is set.

    stall_window: sweeps without a new best residual before the "stall"
    verdict fires (a healthy geometric decay sets a new best nearly every
    sweep, so slow-but-converging solves never trip it; a limit cycle or a
    flat tail does). explode_factor: a residual this many times the FIRST
    recorded residual fires "explode" (divergent operators grow
    geometrically, so the default 1e6 is conservative and unreachable by
    Anderson's transient safeguard spikes). Frozen/hashable — a jit static
    arg like TelemetryConfig.
    """

    stall_window: int = 50
    explode_factor: float = 1e6


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Deterministic fault injection for the resilience machinery
    (diagnostics/faults.py): every field is an opt-in injection point that
    compiles IN a specific, reproducible failure so the recovery path that
    handles it is exercised by CI rather than trusted. The default plan is
    entirely off and every helper is a compile-time no-op for it — but the
    intended usage is passing a NON-default plan explicitly via
    SolverConfig(faults=FaultPlan(...)); production configs never set it.

    Injection points (the catalogue docs/USAGE.md documents):
      nan_sweep        — poison the solver iterate with NaN at this sweep
                         (0-based) inside the EGM/VFI/distribution loops;
                         -1 = off. Exercises the sentinel "nan" verdict and
                         the loop's NaN early-exit contract.
      force_escape     — force the EGM windowed-inversion escape (NaN
                         poisoning + escaped=True) on every sweep.
                         Exercises the "escape" verdict and the safe-route
                         retry wrappers.
      force_fallback   — force the push-forward plan validity flag false so
                         every distribution sweep takes the compiled-in
                         scatter fallback. Exercises the degradation
                         counter/ledger path.
      poison_scenario  — NaN one scenario's preferences in a
                         dispatch.sweep()/sweep_transitions batch; -1 =
                         off. Exercises scenario quarantine.
      fail_stage       — comma-separated rescue-ladder stage names the
                         rescue driver must treat as failed without
                         running. Exercises multi-stage escalation and the
                         attempt-history-carrying exhaustion error.

    The rescue ladder clears `faults` on every rescue stage (a rescue
    attempt re-runs the operator fresh — the injected fault models a
    route/data pathology the escalation replaces), EXCEPT `fail_stage`,
    which targets the driver itself. Frozen/hashable (jit static).
    """

    nan_sweep: int = -1
    force_escape: bool = False
    force_fallback: bool = False
    poison_scenario: int = -1
    fail_stage: str = ""


@dataclasses.dataclass(frozen=True)
class RescueConfig:
    """Host-side rescue ladder for failed solves (diagnostics/rescue.py):
    when the base attempt fails — non-convergence under policy "raise", a
    NaN-poisoned result, a diverged transition path — dispatch re-runs the
    solve through a bounded escalation of progressively more conservative
    configurations, returning the FIRST converged result or raising a
    ConvergenceError that carries the full attempt history.

    stages (each built from the BASE config, not cumulative state):
      "plain"   — acceleration and the fused Pallas routes disabled (the
                  reference first-order trajectory; injected faults
                  cleared, as on every rescue stage).
      "safe"    — plain + the scatter push-forward reference backend; for
                  transition solves also the Jacobian-free damped update.
      "float64" — safe + the mixed-precision ladder bypassed and the
                  backend pinned to full f64 (rules out every low-precision
                  pathology).
      "patient" — float64 + doubled iteration caps (inner and outer) and,
                  for transitions, halved damping — the last-resort
                  slow-but-steady configuration.

    Opt-in via dispatch.solve/sweep/solve_transition/sweep_transitions
    (rescue=RescueConfig()). Every attempt emits a ledger "rescue" event
    and an aiyagari_rescue_attempts_total{stage=...} metrics increment.
    With a rescue ladder attached the exhaustion behavior is always a
    raise (the ladder replaces the warn/ignore policies: a result that
    survived it is converged, anything else is loud)."""

    stages: Tuple[str, ...] = ("plain", "safe", "float64", "patient")


@dataclasses.dataclass(frozen=True)
class SolverConfig:
    """Inner household-solver controls.

    Reference: tol/max_iter at Aiyagari_VFI.m:49-50 (1e-5/1000);
    K-S tol 1e-6, max 10000, 50 Howard sweeps with improvement every 5th
    iteration at Krusell_Smith_VFI.m:12-13,148.
    """

    method: str = "vfi"               # {"vfi", "egm"}
    tol: float = 1e-5
    max_iter: int = 1000
    howard_steps: int = 0             # 0 disables Howard acceleration
    improve_every: int = 5            # policy improvement cadence under Howard
    golden_iters: int = 48            # fixed golden-section iterations (fminbnd analogue)
    relative_tol: bool = False        # K-S VFI uses a relative sup-norm (:195)
    use_pallas: bool = False          # fused VMEM-tiled Bellman kernel (TPU)
    progress_every: int = 0           # in-jit telemetry cadence (0 = off;
                                      # diagnostics.progress host callbacks)
    grid_sequencing: bool = True      # EGM only: cold solves on fine grids
                                      # (>1600 pts) run coarse-to-fine stages
                                      # (solvers/egm.solve_aiyagari_egm_multiscale)
                                      # — same fixed point, ~10x fewer
                                      # full-size sweeps; False forces the
                                      # single-grid reference trajectory at
                                      # any size
    accel: Optional[AccelConfig] = None   # fixed-point acceleration for the
                                      # EGM sweeps and the stationary-
                                      # distribution power iteration
                                      # (AccelConfig docstring); None keeps
                                      # the reference first-order trajectory
    ladder: Optional[PrecisionLadderConfig] = None
                                      # mixed-precision solve ladder
                                      # (ops/precision.py): hot-dtype early
                                      # sweeps with an error-controlled
                                      # switch to a full-precision polish,
                                      # across the EGM/VFI households, the
                                      # stationary distribution, and the
                                      # transition rounds. None = every
                                      # stage at BackendConfig.dtype;
                                      # dispatch.solve() injects the default
                                      # ladder for dtype="mixed".
    egm_kernel: str = "auto"          # EGM sweep kernel route
                                      # (ops/egm.EGM_KERNELS, loudly
                                      # validated like `pushforward`):
                                      # "auto" (platform choice — the XLA
                                      # chain until the fused route is
                                      # chip-validated), "xla" (the
                                      # reference op-by-op sweep),
                                      # "pallas_inverse" (windowed grid
                                      # inversion through its fused Pallas
                                      # kernel), or "pallas_fused" (the
                                      # whole interp→invert→update chain
                                      # as one VMEM-resident kernel,
                                      # ops/pallas_egm.py — reads the
                                      # policy once per sweep instead of
                                      # once per op)
    pushforward: str = "auto"         # DistributionBackend for the Young
                                      # lottery push-forward in every
                                      # cross-section hot path — the
                                      # stationary distribution, the K-S
                                      # histogram closure, and the
                                      # transition forward push
                                      # (ops/pushforward.py): "auto"
                                      # (scatter-free monotone-transpose
                                      # with a compiled-in scatter
                                      # fallback), "scatter" (the `.at[]`
                                      # reference), "banded" (per-policy
                                      # block-band operator applied as
                                      # batched MXU matmuls), or "pallas"
                                      # (the fused TPU kernel,
                                      # ops/pallas_pushforward.py)
    telemetry: Optional[TelemetryConfig] = None
                                      # device-resident flight recorder
                                      # (TelemetryConfig docstring): ring
                                      # buffers of per-sweep residuals /
                                      # stage dtypes / safeguard trips /
                                      # fallback tallies carried inside
                                      # every hot while_loop and returned
                                      # as Solution.telemetry. None (the
                                      # default) compiles the recorder out
                                      # — the hot paths are bit-identical
                                      # and pay zero bytes
    sentinel: Optional[SentinelConfig] = None
                                      # device-resident failure sentinel
                                      # (SentinelConfig docstring): stall /
                                      # explosion / non-finite detection in
                                      # the hot while_loop carries with a
                                      # structured early-exit verdict on
                                      # Solution.sentinel. None (the
                                      # default) compiles it out — loop
                                      # cond and carry are bit-identical
    faults: Optional[FaultPlan] = None
                                      # deterministic fault injection
                                      # (FaultPlan docstring) — CI/test
                                      # harness only, never production
    ge_loop: str = "host"             # GE outer-loop placement
                                      # (equilibrium/fused.py): "host" runs
                                      # the reference Python bisection loop
                                      # (one compiled program per round,
                                      # host scalars between rounds — the
                                      # parity baseline), "device" fuses
                                      # the WHOLE equilibrium (household
                                      # fixed point + stationary
                                      # distribution + market clearing +
                                      # bracket update) into one XLA
                                      # program with the outer loop in a
                                      # lax.while_loop carry, "auto" picks
                                      # "device" where the fused program is
                                      # supported (distribution
                                      # aggregation, jax backend, no mesh)
                                      # and falls back to "host" elsewhere

    def __post_init__(self):
        if self.ge_loop not in ("host", "device", "auto"):
            raise ValueError(
                f"SolverConfig.ge_loop must be 'host', 'device' or 'auto', "
                f"got {self.ge_loop!r}")


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """Simulation controls.

    Reference: 10,000-period single-household ergodic run (Aiyagari_VFI.m:94);
    K-S 10,000-agent x 1,100-period panel with 100 discarded
    (Krusell_Smith_VFI.m:10-11). Unlike the reference's unseeded `rand`
    (irreproducible), seeds are explicit PRNG keys.
    """

    periods: int = 10_000
    n_agents: int = 1
    discard: int = 0
    seed: int = 0


@dataclasses.dataclass(frozen=True)
class EquilibriumConfig:
    """GE closure on the interest rate. Reference: Aiyagari_VFI.m:133-136.

    batch <= 1 (default) runs the reference's serial bisection: one full
    household solve + aggregation per candidate rate, max_iter midpoints.
    batch >= 2 opts into the parallel-bracket root finder
    (equilibrium/batched.py): each outer ROUND evaluates `batch` candidate
    rates through one vmapped excess-demand kernel, shrinking the bracket by
    (batch+1)x per round instead of bisection's 2x — max_iter then caps
    ROUNDS, and the device executes ~log2(batch+1)-fold fewer sequential
    programs for the same root resolution.
    """

    max_iter: int = 10
    tol: float = 1e-5
    r_low: float = -0.05
    r_high: Optional[float] = None    # None -> 1/beta - 1
    r_init: float = 0.04              # warm-start rate (Aiyagari_VFI.m:63)
    batch: int = 1                    # >= 2: candidate rates per device round


@dataclasses.dataclass(frozen=True)
class ALMConfig:
    """Krusell-Smith aggregate-law-of-motion outer loop.

    Reference: max 100 iters, tol 1e-6, damping 0.3 (Krusell_Smith_VFI.m:11-12).
    """

    max_iter: int = 100
    tol: float = 1e-6
    damping: float = 0.3
    T: int = 1100
    population: int = 10_000
    discard: int = 100
    seed: int = 0
    # Outer-loop update rule for the forecasting coefficients: "damped" is
    # the reference's B <- damping*B_new + (1-damping)*B; "anderson" is
    # safeguarded Anderson mixing over the last `anderson_depth` iterates —
    # same fixed point, typically ~3x fewer (solver + simulate + regress)
    # rounds (equilibrium/alm.py).
    acceleration: str = "damped"
    anderson_depth: int = 3


@dataclasses.dataclass(frozen=True)
class MITShock:
    """One-time unanticipated ("MIT") shock with AR(1) reversion: the shocked
    parameter follows x_t = x_ss + size * rho^t over the transition window,
    then is back at its stationary value (Boppart-Krusell-Mitman 2018).

    param selects what is shocked:
      "tfp"             — TFP z_t (z_ss = 1), moving both firm FOC prices;
      "beta"            — the discount factor between t and t+1;
      "sigma"           — CRRA curvature (time-varying marginal utility);
      "borrowing_limit" — the borrowing constraint a' >= amin_t. Only
                          TIGHTENING paths (size >= 0) are representable:
                          the asset grid starts at the stationary limit, so
                          a looser limit would need gridpoints that do not
                          exist (transition/mit.py raises loudly).

    The shock must be transitory (|rho| < 1): the transition starts AND ends
    at the same stationary equilibrium, which anchors both the terminal
    policy of the backward sweep and the initial distribution of the
    forward push.
    """

    param: str = "tfp"
    size: float = 0.01
    rho: float = 0.9


@dataclasses.dataclass(frozen=True)
class TransitionConfig:
    """Perfect-foresight transition-path (MIT shock) solver controls
    (transition/mit.py).

    T is the truncation horizon: prices are assumed back at the stationary
    equilibrium from period T on (choose T so rho^T * size is negligible).
    method selects the price-path update: "newton" uses the sequence-space
    Jacobian built once at the stationary equilibrium by the fake-news
    algorithm (Auclert-Bardoczy-Rognlie-Straub 2021) — typically <= 5
    rounds; "damped" is the Boppart-Krusell-Mitman relaxation
    r <- (1-damping) r + damping * r_implied. tol bounds the max excess
    capital demand along the whole path (units of K, same as the stationary
    closure's |K_s - K_d| criterion).

    loop places the round loop: "host" drives one path-evaluation program
    per Newton/damped round from host (the parity reference); "device"
    fuses the whole round loop into one lax.while_loop program
    (transition/fused.py — one launch and one small fetch per solve) and
    raises loudly where the fused program cannot express the solve
    (endogenous labor, mesh-sharded sweeps, per-round callbacks); "auto"
    picks "device" exactly where it is legal and falls back to "host"
    elsewhere (the SolverConfig.ge_loop contract).
    """

    T: int = 200
    max_iter: int = 30
    tol: float = 1e-6
    damping: float = 0.5
    method: str = "newton"
    loop: str = "host"                # round-loop placement

    def __post_init__(self):
        if self.loop not in ("host", "device", "auto"):
            raise ValueError(
                f"TransitionConfig.loop must be 'host', 'device' or "
                f"'auto', got {self.loop!r}")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """2-D (scenarios x grid) device-mesh request for the sweep entry
    points (dispatch.sweep / dispatch.sweep_transitions, the `mesh=` knob):
    the scenario batch splits over the "scenarios" axis while each
    scenario's asset-grid axis splits over "grid" — one program composing
    both parallelism axes (parallel/mesh.make_mesh_2d; placement by the
    partition-rule matcher, parallel/rules.py).

    None sizes are derived from the device count (both None -> balanced
    factorization, scenarios-major; one given -> the exact quotient), and
    every mismatch — a size that does not factor the devices, a scenario
    count or grid size the axes do not divide — is a loud error at the
    dispatch boundary, never a silent 1-D degeneration. The knob's default
    absence (mesh=None) keeps today's behavior bit-identical: no mesh is
    built and the legacy BackendConfig.mesh_axes path (1-D scenario
    sharding) is untouched. On a multi-host pod the same config shards
    scenarios across hosts (DCN) and the grid within each host (ICI) via
    jax.distributed.initialize — no code change (docs/USAGE.md "Pod-scale
    2-D sharding")."""

    scenarios: Optional[int] = None
    grid: Optional[int] = None
    # Pod observatory (diagnostics/skew.py): time a fenced psum rendezvous
    # per mesh axis around activation, emitting host_skew ledger events +
    # aiyagari_host_skew_seconds{axis=} gauges and a straggler verdict.
    # Off by default — the probe compiles and runs two tiny collectives.
    skew_probe: bool = False

    def __post_init__(self):
        for name in ("scenarios", "grid"):
            v = getattr(self, name)
            if v is not None and (not isinstance(v, int) or v < 1):
                raise ValueError(
                    f"MeshConfig.{name} must be a positive int or None, "
                    f"got {v!r}")
        if not isinstance(self.skew_probe, bool):
            raise ValueError(
                f"MeshConfig.skew_probe must be a bool, got "
                f"{self.skew_probe!r}")


@dataclasses.dataclass(frozen=True)
class BackendConfig:
    """Execution-backend controls: dtype policy and device-mesh shape.

    mesh_shape maps axis names to sizes; ("agents",) shards the K-S panel,
    ("grid",) shards value/policy rows. None = single device.

    dtype policy: the default is float64 and it is HONORED on every backend
    (the solve entry points wrap work in precision_scope, enabling x64
    locally if needed) — the Krusell-Smith ALM fixed point requires f64
    somewhere to reach its 1e-6 reference tolerance (precision_scope
    docstring). On TPU, f64 runs in extended-precision emulation; pass
    dtype="float32" for native-speed solves where f32 accuracy suffices
    (the Aiyagari-family solvers converge to their reference tolerances in
    f32 — pinned by test_precision — and bench.py selects f32 on TPU
    explicitly, as does the CLI).

    dtype="mixed" assigns each component the cheapest precision that
    preserves the reference tolerance, per model family:

      * Aiyagari family (and the transition solver): the mixed-precision
        SOLVE LADDER (ops/precision.py) — every hot fixed point (EGM/VFI
        sweeps, the Young distribution iteration, the transition rounds)
        runs its early, inaccuracy-tolerant iterations in f32 (bf16 matmul
        precision on TPU for the expectation/push-forward contractions),
        detects when the residual reaches that dtype's ulp noise floor
        (solvers/_stopping.effective_tolerance), then switches the carry to
        f64 ONCE and polishes to the reference tolerance. dispatch.solve()
        injects the default ladder into SolverConfig.ladder; pass an
        explicit PrecisionLadderConfig there to tune stage dtypes / switch
        threshold / matmul precision. Backends where x64 cannot be enabled
        reject the ladder loudly instead of silently polishing in f32.

      * Krusell-Smith outer loop: the component policy measured on a v5e
        (equilibrium/alm.py design note): household solve and regression in
        f64 — the solve is op-latency-bound at the reference scale, so f64
        there costs nothing, and it is where the f32 noise (sub-cell policy
        jitter) actually originates — while the 1,100-step cross-section
        scan, 18x slower in emulated f64, runs in native f32 (its rounding
        is a fixed O(eps) bias in a deterministic map, not compounding
        noise). A stall detector falls back to the f64 simulation if the
        bias floor ever exceeds tol.
    """

    backend: str = "jax"              # {"jax", "numpy"}
    dtype: str = "float64"            # {"float32", "float64", "mixed"} — see policy above
    mesh_axes: Tuple[str, ...] = ()
    mesh_shape: Tuple[int, ...] = ()


def precision_scope(dtype: str):
    """Context manager honoring a BackendConfig.dtype="float64" request even
    when jax's global x64 flag is off.

    Without this, jnp.asarray(..., float64) silently canonicalizes to f32
    (with only a UserWarning) — and the Krusell-Smith ALM fixed point then
    never reaches the reference's 1e-6 coefficient tolerance: measured on a
    v5e, the f32 pipeline limit-cycles at diff_B ~ 5e-2 because sub-cell
    policy jitter (the choice objective is flat below f32 resolution)
    compounds over the 1,100-period simulation into O(1e-2) regression
    noise. f64 on the same chip converges in 38 iterations to the same
    coefficients as CPU f64. Use as:

        with precision_scope(backend.dtype):
            ... jax work ...
    """
    import jax

    # "mixed" needs x64 available for its f64 half (the ladder's polish
    # stages on the Aiyagari side, the solve/regression on the K-S side).
    if dtype in ("float64", "mixed") and not jax.config.jax_enable_x64:
        # jax >= 0.6 exposes the scoped switch at top level; 0.4.x only in
        # jax.experimental. Same context manager either way.
        enable = getattr(jax, "enable_x64", None)
        if enable is None:
            from jax.experimental import enable_x64 as enable
        return enable()
    import contextlib

    return contextlib.nullcontext()
