"""End-of-run reports: every figure and printed statistic the reference
produces, generated from solved results and saved to a directory, plus a
machine-readable summary JSON.

Reference output surface (SURVEY.md §1 L7):
  Aiyagari scripts — capital demand/supply vs r cross (Aiyagari_VFI.m:217-229),
  asset policy functions (:231-243), ksdensity densities (:245-279),
  probability histograms (:281-312), Lorenz curves (:314-372), Gini printouts
  (:353-357), quintile wealth shares + bar chart (:374-420).
  K-S scripts — true vs ALM-approximated aggregate capital path and the
  per-regime K'(K) maps vs the 45-degree line (Krusell_Smith_VFI.m:298-325).
"""

from __future__ import annotations

import json
from pathlib import Path

import jax.numpy as jnp
import numpy as np

from aiyagari_tpu.utils.stats import (
    gaussian_kde,
    gini,
    lorenz_curve,
    quantile_shares,
    weighted_gini,
    weighted_lorenz_curve,
    weighted_quantile_shares,
)

__all__ = ["equilibrium_report", "krusell_smith_report"]

_SERIES_LABELS = {
    "k": "Wealth",
    "c": "Consumption",
    "y": "Net Income",
    "gy": "Gross Income",
    "sav": "Savings",
}


def _plt():
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    return plt


def _result_series(result, model, discard: int):
    """(values, weights) per series label. Simulation results yield the panel
    sample with uniform weights (weights=None); distribution results
    (series=None, mu set) yield the gridded policy values weighted by the
    stationary mass — under stationarity (z', a') ~ mu too, so the recorded
    formulas match PanelSeries' accounting (sim/ergodic.py:76-79)."""
    if result.series is not None:
        return {
            name: (np.asarray(getattr(result.series, name))[discard:].ravel(), None)
            for name in _SERIES_LABELS
        }
    if result.mu is None:
        raise ValueError("result has neither a simulated series nor a stationary mu")
    mu = np.asarray(result.mu)
    sol = result.solution
    r, w = result.r, result.w
    delta = model.config.technology.delta
    k = np.broadcast_to(np.asarray(model.a_grid)[None, :], mu.shape)
    c = np.asarray(sol.policy_c)
    l = np.asarray(sol.policy_l)
    s = np.asarray(model.s)[:, None]
    y = r * k + w * s * l
    gy = y + delta * k
    sav = gy - c
    values = {"k": k, "c": c, "y": y, "gy": gy, "sav": sav}
    return {name: (v.ravel(), mu.ravel()) for name, v in values.items()}


def equilibrium_report(result, model, outdir, discard: int = 0) -> dict:
    """Write the Aiyagari figure set + summary.json; returns the summary dict.

    `result` is an EquilibriumResult, `model` the AiyagariModel it came from.
    Works for both closures: simulation results use the panel sample,
    distribution results (aggregation='distribution') use the stationary
    distribution with the weighted statistics.
    """
    plt = _plt()
    out = Path(outdir)
    out.mkdir(parents=True, exist_ok=True)
    series = _result_series(result, model, discard)
    a_grid = np.asarray(model.a_grid)

    # 1. Capital market cross: demand & supply points vs r, with the
    #    complete-markets rate line (Aiyagari_VFI.m:217-229). History kept
    #    aligned (not independently sorted like the reference's :211-213).
    order = np.argsort(result.r_history)
    r_h = np.asarray(result.r_history)[order]
    fig, ax = plt.subplots(figsize=(7, 5))
    ax.plot(np.asarray(result.k_demand)[order], r_h, "r-o", lw=2, label="Capital Demand")
    ax.plot(np.asarray(result.k_supply)[order], r_h, "b--s", lw=2, label="Capital Supply")
    ax.axhline((1 - model.preferences.beta) / model.preferences.beta, color="k", lw=0.8)
    ax.set_xlabel("Total Assets")
    ax.set_ylabel("Interest Rate")
    ax.set_title("Steady State: capital market")
    ax.legend()
    ax.grid(True)
    fig.savefig(out / "capital_market.png", dpi=120)
    plt.close(fig)

    # 2. Asset policy functions for the lowest/highest productivity states
    #    (Aiyagari_VFI.m:231-243).
    pk = np.asarray(result.solution.policy_k)
    fig, ax = plt.subplots(figsize=(7, 5))
    ax.plot(a_grid, pk[0], "r:", lw=2, label="lowest productivity")
    ax.plot(a_grid, pk[-1], "b--", lw=2, label="highest productivity")
    ax.plot(a_grid, a_grid, "k-", lw=0.5)
    ax.set_xlabel("Assets")
    ax.set_ylabel("Next-period assets")
    ax.set_title("Asset policy functions")
    ax.legend()
    ax.grid(True)
    fig.savefig(out / "policies.png", dpi=120)
    plt.close(fig)

    # 3. Densities (the ksdensity analogue; Aiyagari_VFI.m:245-279).
    fig, axes = plt.subplots(1, 2, figsize=(12, 5))

    def _kde(name):
        vals, wts = series[name]
        return gaussian_kde(jnp.asarray(vals),
                            weights=None if wts is None else jnp.asarray(wts))

    xi, f = _kde("k")
    axes[0].plot(np.asarray(xi), np.asarray(f), "b-", lw=2)
    axes[0].set_title("Density of Wealth")
    axes[0].grid(True)
    for name in ("c", "y", "gy", "sav"):
        xi, f = _kde(name)
        axes[1].plot(np.asarray(xi), np.asarray(f), lw=2, label=_SERIES_LABELS[name])
    axes[1].set_title("Densities")
    axes[1].legend()
    axes[1].grid(True)
    fig.savefig(out / "densities.png", dpi=120)
    plt.close(fig)

    # 4. Probability histograms (Aiyagari_VFI.m:281-312).
    fig, axes = plt.subplots(1, 5, figsize=(22, 4))
    for ax, (name, label) in zip(axes, _SERIES_LABELS.items()):
        vals, wts = series[name]
        mass = np.full(vals.size, 1.0 / vals.size) if wts is None else wts / wts.sum()
        ax.hist(vals, bins=50, weights=mass)
        ax.set_title(f"Histogram of {label}")
    fig.savefig(out / "histograms.png", dpi=120)
    plt.close(fig)

    # 5. Lorenz curves for all five series (Aiyagari_VFI.m:359-372).
    fig, ax = plt.subplots(figsize=(7, 6))
    ginis = {}
    for name, label in _SERIES_LABELS.items():
        vals, wts = series[name]
        if wts is None:
            pop, cum = lorenz_curve(jnp.asarray(vals))
            ginis[name] = float(gini(jnp.asarray(vals)))
        else:
            pop, cum = weighted_lorenz_curve(jnp.asarray(vals), jnp.asarray(wts))
            ginis[name] = float(weighted_gini(jnp.asarray(vals), jnp.asarray(wts)))
        ax.plot(np.asarray(pop), np.asarray(cum), lw=2, label=label)
    ax.plot([0, 1], [0, 1], "k--")
    ax.set_xlabel("Cumulative Share of Population")
    ax.set_ylabel("Cumulative Share")
    ax.set_title("Lorenz Curves")
    ax.legend()
    ax.grid(True)
    fig.savefig(out / "lorenz.png", dpi=120)
    plt.close(fig)

    # 6. Quintile wealth shares bar chart (Aiyagari_VFI.m:374-420).
    k_vals, k_wts = series["k"]
    if k_wts is None:
        shares = np.asarray(quantile_shares(jnp.asarray(k_vals), 5))
    else:
        shares = np.asarray(weighted_quantile_shares(jnp.asarray(k_vals),
                                                     jnp.asarray(k_wts), 5))
    fig, ax = plt.subplots(figsize=(7, 5))
    ax.bar(range(1, 6), shares, color="b")
    ax.set_xticks(range(1, 6),
                  ["Bottom 20%", "Next 20%", "Next 20%", "Next 20%", "Top 20%"])
    ax.set_ylabel("Wealth Share (%)")
    ax.set_title("Wealth Distribution Across Quintiles")
    ax.grid(True)
    fig.savefig(out / "quintiles.png", dpi=120)
    plt.close(fig)

    # Off-grid Euler-equation accuracy of the converged policies (Judd's
    # consumption-equivalent E_EE, log10 scale) at unconstrained midpoints —
    # an accuracy standard the reference lacks entirely.
    from aiyagari_tpu.utils.accuracy import euler_equation_errors

    prefs = model.preferences
    log10e, mask = euler_equation_errors(
        result.solution.policy_c, result.solution.policy_k,
        model.a_grid, model.s, model.P, result.r, result.w, model.amin,
        sigma=prefs.sigma, beta=prefs.beta,
    )
    ee = np.asarray(log10e)[np.asarray(mask)]

    summary = {
        "r_star": result.r,
        "wage": result.w,
        "capital": result.capital,
        "savings_rate_percent": 100.0 * model.config.technology.delta
        * model.config.technology.alpha
        / (result.r + model.config.technology.delta),   # Aiyagari_VFI.m:208
        "converged": result.converged,
        "iterations": result.iterations,
        "gini": ginis,
        "quintile_shares_percent": shares.tolist(),
        "euler_error_log10_mean": float(ee.mean()) if ee.size else None,
        "euler_error_log10_max": float(ee.max()) if ee.size else None,
        "solve_seconds": result.solve_seconds,
    }
    (out / "summary.json").write_text(json.dumps(summary, indent=2))
    return summary


def krusell_smith_report(result, outdir, discard: int = 100) -> dict:
    """Write the K-S figure set + summary.json; returns the summary dict.

    `result` is a KSResult. The approximate path recursion mirrors
    compute_approxKprime (Krusell_Smith_VFI.m:367-375).
    """
    plt = _plt()
    out = Path(outdir)
    out.mkdir(parents=True, exist_ok=True)
    K_ts = np.asarray(result.K_ts)
    z = np.asarray(result.z_path)
    B = np.asarray(result.B)

    K_approx = np.empty_like(K_ts)
    K_approx[discard] = K_ts[discard]
    for t in range(discard, len(K_ts) - 1):
        b0, b1 = (B[0], B[1]) if z[t] == 0 else (B[2], B[3])
        K_approx[t + 1] = np.exp(b0 + b1 * np.log(K_approx[t]))

    fig, axes = plt.subplots(2, 1, figsize=(9, 8))
    axes[0].plot(K_ts[discard + 1:], "-r", label="True")
    axes[0].plot(K_approx[discard + 1:], "--b", label="Approximation")
    axes[0].set_title("Aggregate Capital Law of Motion")
    axes[0].set_xlabel("Time")
    axes[0].set_ylabel("K")
    axes[0].legend()

    K_lim = np.linspace(K_ts.min(), K_ts.max(), 100)
    axes[1].plot(K_lim, np.exp(B[0] + B[1] * np.log(K_lim)), "b-", label="Good State")
    axes[1].plot(K_lim, np.exp(B[2] + B[3] * np.log(K_lim)), "r-", label="Bad State")
    axes[1].plot(K_lim, K_lim, "k--", label="45° Line")
    axes[1].set_title("Tomorrow vs Today Aggregate Capital")
    axes[1].set_xlabel("K_t")
    axes[1].set_ylabel("K_{t+1}")
    axes[1].legend()
    fig.tight_layout()
    fig.savefig(out / "alm.png", dpi=120)
    plt.close(fig)

    # Wealth distribution of the final cross-section (bonus over the
    # reference: it never plots the K-S wealth distribution). Under the
    # histogram closure the cross-section IS a distribution on k_grid.
    kpop = np.asarray(result.k_population)
    mu = getattr(result, "mu", None)
    fig, ax = plt.subplots(figsize=(7, 5))
    if kpop.size:
        ax.hist(kpop, bins=60, weights=np.full(kpop.size, 1.0 / kpop.size))
        wealth_gini = float(gini(jnp.asarray(kpop)))
    else:
        k_grid = np.asarray(result.k_grid)
        w = np.asarray(mu).sum(axis=0)
        ax.bar(k_grid, w, width=np.gradient(k_grid), align="center")
        from aiyagari_tpu.utils.stats import weighted_gini

        wealth_gini = float(weighted_gini(jnp.asarray(k_grid), jnp.asarray(w)))
    ax.set_title("Cross-sectional wealth distribution (final period)")
    ax.set_xlabel("k")
    fig.savefig(out / "wealth_cross_section.png", dpi=120)
    plt.close(fig)

    from aiyagari_tpu.utils.accuracy import alm_dynamic_path_error

    err_max, err_mean = alm_dynamic_path_error(K_ts, z, B, discard)
    summary = {
        "B": B.tolist(),
        "r2_good": float(result.r2[0]),
        "r2_bad": float(result.r2[1]),
        "converged": result.converged,
        "iterations": result.iterations,
        "diff_B": result.diff_B,
        "K_mean": float(K_ts[discard:].mean()),
        "alm_path_max_rel_error": err_max,
        "alm_path_mean_rel_error": err_mean,
        "wealth_gini": wealth_gini,
        "solve_seconds": result.solve_seconds,
    }
    (out / "summary.json").write_text(json.dumps(summary, indent=2))
    return summary
