"""Persistent XLA compilation cache.

This image's TPU is reached through a remote-compile transport where first
compiles of the solver fixed points cost tens of seconds to minutes (the K-S
Howard VFI at f64 measured ~80 s; BENCHMARKS.md). JAX's persistent
compilation cache removes that cost for every process after the first —
measured 13.0 s -> 1.4 s on a representative kernel across fresh processes.

The framework enables it from its executables (bench.py, the CLI, the
examples, the driver entry points) rather than at package import, so
importing aiyagari_tpu as a library never mutates global JAX config behind
the caller's back.
"""

from __future__ import annotations

import hashlib
import os

__all__ = [
    "aot_cache_dir",
    "aot_key",
    "enable_compilation_cache",
    "load_serialized",
    "save_serialized",
]


def _host_cpu_tag() -> str:
    """Short stable tag for the host CPU model. XLA:CPU AOT executables are
    compiled for the build host's exact feature set; this image's home
    directory PERSISTS across VM reprovisioning onto different CPU steppings,
    and loading another stepping's artifacts logs a feature-mismatch error
    with a documented SIGILL risk (observed live: a 2.70GHz box's cache
    loaded on a 2.10GHz successor). Keying the directory by CPU model keeps
    each stepping's artifacts separate.

    What this does NOT silence (and cannot): the image routes even XLA:CPU
    compilation through the remote-compile service, which stamps its
    artifacts with the XLA scheduling PREFERENCES +prefer-no-scatter/gather
    in the machine-feature string; the local loader reports those as
    "feature not supported on the host" ERROR lines on every cache hit.
    Measured same-host: fresh dir -> 0 lines on the writing run, 286 on the
    next (loading) run, all exclusively the two pseudo-features — the real
    ISA sets match, the executables run, and the suite is green. That spam
    is cosmetic; driver-facing entry points set TF_CPP_MIN_LOG_LEVEL to
    keep it out of artifacts. Do NOT re-chase it as a correctness bug.

    Keyed on 'model name' + 'stepping' only — NOT the 'flags' line, whose
    content shifts with kernel/microcode updates on identical hardware and
    would silently orphan cache directories (cold recompiles + unbounded
    ~/.cache growth) without any ISA change."""
    model = ""
    try:
        with open("/proc/cpuinfo") as f:
            for ln in f:
                if ln.startswith(("model name", "stepping")):
                    model += ln
                    if model.count("\n") >= 2:
                        break
    except OSError:
        import platform

        model = platform.processor()
    return hashlib.sha256(model.encode()).hexdigest()[:10]


def enable_compilation_cache(cache_dir: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at `cache_dir` and return the
    directory used (None if the running JAX lacks the feature).

    Resolution order: explicit argument, $AIYAGARI_TPU_COMPILE_CACHE, then
    ~/.cache/aiyagari_tpu/xla. Setting $AIYAGARI_TPU_COMPILE_CACHE to the
    empty string disables the cache entirely.
    """
    import jax

    env = os.environ.get("AIYAGARI_TPU_COMPILE_CACHE")
    if env == "":
        # The kill switch wins over everything, including explicit dirs —
        # it exists for bisecting suspected stale-cache miscompiles, where a
        # silently-still-enabled cache would invalidate the bisection.
        return None
    if cache_dir is None:
        cache_dir = env
    # Every path is keyed by the RESOLVED backend (this initializes it — the
    # call sites all touch devices immediately afterwards anyway): a
    # TPU-attached process also compiles XLA:CPU executables with different
    # machine-feature flags (+prefer-no-scatter/-gather) than a pure-CPU
    # process, and loading the other's AOT artifacts triggers
    # feature-mismatch warnings with a documented SIGILL risk. Explicit and
    # env-supplied dirs get the same "-{backend}" suffix — an unsuffixed
    # shared dir would reintroduce exactly that collision the moment a
    # TPU-attached and a CPU-forced process point at it (ADVICE round 2).
    # The requested-platform string would NOT do: it is unset ("auto") both
    # for a TPU-attached default run and for a CPU fallback run when the
    # TPU tunnel is down.
    backend = jax.default_backend()
    suffix = f"{backend}-{_host_cpu_tag()}"
    if cache_dir is None:
        cache_dir = os.path.join(
            os.path.expanduser("~"), ".cache", "aiyagari_tpu", f"xla-{suffix}"
        )
    else:
        cache_dir = f"{cache_dir.rstrip(os.sep)}-{suffix}"
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # Cache every program: the workload is dominated by a handful of
        # solver fixed points whose artifacts are small next to their
        # compile times, so size/time thresholds only cost cache hits.
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except AttributeError:   # older jax without the persistent cache
        return None
    return cache_dir


# -- AOT-serialized executables (jax.export) -------------------------------
#
# The XLA cache above removes the BACKEND compile on restart; these helpers
# remove the TRACE as well. serve/warmup.py exports each warm-pool program
# through jax.export after its first compile and persists the serialized
# bytes beside the XLA cache; the next start deserializes and compiles the
# exported StableHLO directly — no solver-code retrace — so a fleet worker
# (or a crashed one) is serving in seconds (ISSUE 20 tentpole, layer 2).
# Keys carry jax/jaxlib versions and the backend+CPU-stepping suffix:
# serialized StableHLO ages with the lowering exactly like XLA artifacts.


def aot_cache_dir(cache_dir: str | None = None) -> str | None:
    """The AOT executable directory, resolved with the SAME order and kill
    switch as `enable_compilation_cache`: explicit argument,
    $AIYAGARI_TPU_COMPILE_CACHE (empty string disables), then
    ~/.cache/aiyagari_tpu/aot-{backend}-{cpu_tag}."""
    import jax

    env = os.environ.get("AIYAGARI_TPU_COMPILE_CACHE")
    if env == "":
        return None
    if cache_dir is None:
        cache_dir = env
    suffix = f"{jax.default_backend()}-{_host_cpu_tag()}"
    if cache_dir is None:
        return os.path.join(os.path.expanduser("~"), ".cache",
                            "aiyagari_tpu", f"aot-{suffix}")
    return f"{cache_dir.rstrip(os.sep)}-aot-{suffix}"


def aot_key(name: str) -> str:
    """Filename-safe cache key for one exported program: the program name
    plus the jax/jaxlib versions (serialized artifacts do not survive a
    lowering upgrade; the platform is already in the directory suffix)."""
    import jax
    import jaxlib

    digest = hashlib.sha256(
        f"{name}|{jax.__version__}|{jaxlib.__version__}".encode()
    ).hexdigest()[:32]
    return f"{digest}.jaxexport"


def save_serialized(name: str, data: bytes,
                    cache_dir: str | None = None) -> str | None:
    """Atomically persist one serialized executable; returns the path
    written (None when the cache is disabled or the write fails — AOT
    export is an optimization and must never fail a warm pool)."""
    base = aot_cache_dir(cache_dir)
    if base is None:
        return None
    path = os.path.join(base, aot_key(name))
    tmp = f"{path}.{os.getpid()}.tmp"
    try:
        os.makedirs(base, exist_ok=True)
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    return path


def load_serialized(name: str,
                    cache_dir: str | None = None) -> bytes | None:
    """The serialized executable for `name` under the current
    jax/jaxlib/platform key, or None (missing, disabled, unreadable)."""
    base = aot_cache_dir(cache_dir)
    if base is None:
        return None
    try:
        with open(os.path.join(base, aot_key(name)), "rb") as f:
            return f.read()
    except OSError:
        return None
