"""Outer-loop checkpoint/resume.

The reference has no persistence at all (no save/load anywhere; all state is
the MATLAB workspace — SURVEY.md §5.4). Here the tiny outer-loop state
(bisection bracket or ALM coefficients, warm-start policies/value, iteration
counters) is written at every outer iteration so a preempted run resumes
exactly where it stopped — the preemption-tolerance pattern TPU pods require
(SURVEY.md §5.3).

Format: a single .npz per run (atomic replace), arrays + a JSON-encoded
scalar-state blob. Sharded device arrays (the mesh routes' policies and
cross-sections) are packed PER SHARD: each addressable shard is fetched and
stored as its own entry, so no step of save or (sharding-aware) restore
ever materializes the full array on host — the memory-scaling property the
ring-sharded solvers exist to provide (SURVEY.md §5.4, VERDICT round 3 #7).

Multi-process runs (round 5 — the pod-preemption story past the process
boundary, VERDICT round 4 missing #3): under jax.process_count() > 1 every
process writes its OWN file, `<path>.proc{i}of{N}`, holding its
addressable shards (global indices in the shard meta) plus the full scalar
blob; nothing is gathered. Restore reads ALL process files from the shared
checkpoint directory and merges them with three loud completeness checks —
all N files present, matching save-sequence stamps across files (a torn
save, e.g. preemption between two processes' writes, must not restore a
mixed iteration; full-blob comparison is impossible since per-process
wall-time fields legitimately differ), and the merged shards tiling each
full array. Per-shard
placement then proceeds exactly as in the single-process case: each
process's make_array_from_callback serves its addressable shards from the
merged map, so no process ever materializes a full array. Requires the
processes to share (or replicate) the checkpoint directory, the normal pod
arrangement. Pinned end-to-end by
tests/test_sim_sharding.py::test_two_process_interrupted_resume.
"""

from __future__ import annotations

import json
import os
import tempfile
from pathlib import Path
from typing import Any, Optional

import numpy as np

__all__ = ["save_checkpoint", "load_checkpoint", "config_fingerprint",
           "restore_array", "CheckpointManager"]

_SHARD_META_KEY = "__shard_meta__"
_SAVE_SEQ_KEY = "__save_seq__"

# Per-path count of save_checkpoint calls in THIS process — stamped into
# every multi-process file so a torn save (preemption between two
# processes' writes of the same outer iteration) is detectable at merge
# without comparing the full scalar blob, which legitimately differs
# across processes in wall-time fields (per-iteration "seconds" records).
_SAVE_COUNTS: dict = {}


def _is_distributed(v) -> bool:
    """A jax.Array whose sharding actually splits the data (a replicated or
    single-device array round-trips through np.asarray unchanged)."""
    try:
        import jax

        return (isinstance(v, jax.Array)
                and not v.sharding.is_fully_replicated)
    except ImportError:                                  # pragma: no cover
        return False


def _norm_index(index, shape) -> tuple:
    """Canonical ((start, stop), ...) form of a shard's index tuple."""
    out = []
    for sl, dim in zip(index, shape):
        start, stop, step = sl.indices(dim)
        assert step == 1
        out.append((start, stop))
    return tuple(out)


def _process_topology() -> tuple[int, int]:
    """(process_id, process_count) of the running jax cluster; (0, 1)
    without jax or outside a multi-process run."""
    try:
        import jax

        return jax.process_index(), jax.process_count()
    except ImportError:                                  # pragma: no cover
        return 0, 1


def _pack_arrays(arrays: Optional[dict]) -> tuple[dict, dict]:
    """Split distributed jax.Arrays into per-shard entries (name__shard{i})
    plus an index-map meta blob; pass everything else to np.asarray whole.
    The per-shard np.asarray fetches one shard-sized buffer at a time.
    Shards replicated over a second mesh axis (e.g. a ("agents","grid")
    mesh) repeat the same index — deduped here, so the file carries each
    distinct slice once. Multi-process arrays contribute only THIS
    process's addressable shards (global indices in the meta); the
    per-process save files are merged — with completeness checks — at
    load (module docstring)."""
    plain: dict = {}
    meta: dict = {}
    for k, v in (arrays or {}).items():
        if _is_distributed(v):
            by_index = {}
            for sh in v.addressable_shards:
                by_index.setdefault(_norm_index(sh.index, v.shape), sh)
            indices = []
            for i, (idx, sh) in enumerate(sorted(by_index.items())):
                plain[f"{k}__shard{i}"] = np.asarray(sh.data)
                indices.append([list(p) for p in idx])
            meta[k] = {"shape": list(v.shape), "dtype": str(v.dtype),
                       "indices": indices}
        else:
            plain[k] = np.asarray(v)
    return plain, meta


def restore_array(scalars: dict, arrays: dict, name: str, sharding=None,
                  dtype=None, *, mesh=None, rules=None):
    """Reassemble array `name` from a checkpoint's (scalars, arrays) pair.

    Plain entries return as stored. Per-shard entries (written by the
    sharded save path) are restored WITHOUT host materialization when
    `sharding` (a NamedSharding matching the original mesh layout) is
    given: jax.make_array_from_callback places each stored shard directly
    on its device. With a different sharding — or none — the shards are
    assembled into one host array first (the resharding fallback, which
    does materialize; callers resuming a mesh run pass the mesh's
    sharding). Returns None when the name is absent entirely.

    `mesh` + `rules` derive the sharding through the partition-rule
    matcher (parallel/rules.match_rule) by the array's NAME instead of a
    hand-built NamedSharding per call site — so a resume onto a DIFFERENT
    topology (a 2x4 save restored on a 4x2 mesh, or a 1-D save resumed
    under the 2-D sweep mesh) re-derives the placement from the same rule
    set the live sweep used, and the restore stays per-shard wherever the
    stored boxes match the new layout. Mutually exclusive with an
    explicit `sharding`."""
    if mesh is not None or rules is not None:
        if sharding is not None:
            raise ValueError(
                "pass either sharding= or the rule matcher pair "
                "(mesh= + rules=), not both")
        if mesh is None or rules is None:
            raise ValueError(
                "rule-matched restore needs BOTH mesh= and rules=")
        from aiyagari_tpu.parallel.mesh import NamedSharding as _NS
        from aiyagari_tpu.parallel.rules import match_rule

        probe = _restore_shape_probe(scalars, arrays, name)
        if probe is not None:
            # Zero-alloc shape carrier: the matcher only reads shape/size.
            spec = match_rule(rules, name, np.broadcast_to(np.uint8(0),
                                                           probe),
                              mesh=mesh)
            sharding = _NS(mesh, spec)
    meta = (scalars.get(_SHARD_META_KEY) or {}).get(name)
    if meta is None:
        v = arrays.get(name)
        if v is None:
            return None
        if dtype is not None:
            v = np.asarray(v, dtype)
        if sharding is not None:
            # Plain (legacy / unsharded-save) entry resumed under a mesh:
            # place it once here, so callers never pay an implicit
            # full-array reshard inside their first jitted step.
            import jax

            return jax.device_put(v, sharding)
        return v
    shape = tuple(meta["shape"])
    # Index-box -> entry-NAME map; the data itself is fetched per request,
    # so a lazy merged view (_LazyEntries) only reads the shards this
    # process's sharding actually asks for.
    keymap = {tuple(tuple(p) for p in idx): f"{name}__shard{i}"
              for i, idx in enumerate(meta["indices"])}

    def _fetch(kn):
        v = arrays[kn]
        return np.asarray(v, dtype) if dtype is not None else v

    if sharding is not None:
        import jax

        full = None

        def cb(index):
            nonlocal full
            key = _norm_index(index, shape)
            kn = keymap.get(key)
            if kn is not None:
                return _fetch(kn)
            # Mesh geometry changed between save and resume: assemble
            # the stored shards ONCE and serve every request by slice.
            if full is None:
                full = _assemble(
                    {k: _fetch(kn2) for k, kn2 in keymap.items()}, shape)
            return full[tuple(slice(a, b) for a, b in key)]

        return jax.make_array_from_callback(shape, sharding, cb)
    return _assemble({k: _fetch(kn) for k, kn in keymap.items()}, shape)


def _restore_shape_probe(scalars: dict, arrays: dict,
                         name: str) -> Optional[tuple]:
    """The stored full shape of entry `name` (shard meta for per-shard
    entries, the plain array otherwise), or None when absent — what the
    rule-matched restore needs to pick a PartitionSpec before any data
    loads."""
    meta = (scalars.get(_SHARD_META_KEY) or {}).get(name)
    if meta is not None:
        return tuple(meta["shape"])
    v = arrays.get(name)
    return None if v is None else tuple(np.shape(v))


def _assemble(lookup: dict, shape) -> np.ndarray:
    first = next(iter(lookup.values()))
    out = np.empty(shape, dtype=first.dtype)
    covered = np.zeros(shape, dtype=bool)
    for key, data in lookup.items():
        sl = tuple(slice(a, b) for a, b in key)
        out[sl] = data
        covered[sl] = True
    if not covered.all():
        # A gap here means the checkpoint was written by a process that
        # did not hold every shard — surfacing it beats silently returning
        # uninitialized memory as a "restored" array.
        raise ValueError(
            "stored shards do not tile the full array "
            f"(shape {shape}): incomplete (multi-process?) checkpoint")
    return out


def _proc_file(path: Path, pid: int, nproc: int) -> Path:
    return path.with_name(f"{path.name}.proc{pid}of{nproc}")


def _write_npz(path: Path, payload: dict) -> None:
    fd, tmp = tempfile.mkstemp(dir=path.parent, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def save_checkpoint(path, *, scalars: dict, arrays: Optional[dict] = None) -> None:
    """Atomically write scalar state (JSON-serializable) + named arrays.
    Distributed jax.Arrays among `arrays` are stored per shard
    (_pack_arrays) and restored via restore_array. In a multi-process run
    every process must call this with the SAME path and scalars: each
    writes its own `<path>.proc{i}of{N}` file with its addressable shards
    (module docstring). A topology change between runs (single <-> multi,
    or a different process count) is self-healing: each save removes the
    other representations of this path, so a later resume can never read
    a stale pre-change file in preference to newer state."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    packed, shard_meta = _pack_arrays(arrays)
    if shard_meta:
        scalars = {**scalars, _SHARD_META_KEY: shard_meta}
    payload = {"__scalars__": np.frombuffer(json.dumps(scalars).encode(), dtype=np.uint8)}
    for k, v in packed.items():
        payload[k] = v
    pid, nproc = _process_topology()
    if nproc > 1:
        seq = _SAVE_COUNTS.get(str(path), 0) + 1
        _SAVE_COUNTS[str(path)] = seq
        scalars = {**scalars, _SAVE_SEQ_KEY: seq}
        payload["__scalars__"] = np.frombuffer(
            json.dumps(scalars).encode(), dtype=np.uint8)
        # Topology-change cleanup BEFORE the proc write (ADVICE r5 ~:248):
        # a stale single-process file at `path` would SHADOW the proc files
        # at every load (load_checkpoint prefers it), silently regressing
        # the run to the pre-change iteration on each resume; were it
        # removed only AFTER the write, a preemption between the two would
        # leave exactly that shadowing file behind. Unlinking first leaves
        # the worst crash window as "no checkpoint / incomplete proc set" —
        # a fresh start or a LOUD completeness error, never a silent
        # regression. Other-topology proc files would make the file-count
        # completeness check unsatisfiable. Processes only remove files no
        # current process writes; concurrent removal is guarded by
        # missing_ok.
        path.unlink(missing_ok=True)
        for f in path.parent.glob(path.name + ".proc*of*"):
            if not str(f.name).endswith(f"of{nproc}"):
                f.unlink(missing_ok=True)
        _write_npz(_proc_file(path, pid, nproc), payload)
    else:
        _write_npz(path, payload)
        for f in path.parent.glob(path.name + ".proc*of*"):
            f.unlink(missing_ok=True)


def _load_npz(path: Path) -> tuple[dict, dict]:
    with np.load(path) as z:
        scalars = json.loads(bytes(z["__scalars__"]).decode())
        arrays = {k: z[k] for k in z.files if k != "__scalars__"}
    return scalars, arrays


class _LazyEntries(dict):
    """Mapping of entry name -> np.ndarray that opens the backing .npz ONLY
    when an entry is read. The merged multi-process view must not load
    every process's shards into every process's host memory (that would
    transiently materialize the full solver state per host — the exact
    thing the per-shard format exists to avoid); restore_array reads only
    the shards the local sharding requests. Subclasses dict so key
    iteration / membership behave normally; values are (file, entry-name)
    pointers resolved per access.

    Every lazy open RE-VERIFIES the file's save sequence against the one
    the merge was built from (`expected_seq`, ADVICE r5 ~:265): another
    process's save_checkpoint may atomically replace a proc file between
    the merge's eager scalar/meta read and a later lazy shard read, and
    serving the NEWER file's shards against the OLDER merged metadata
    would hand the caller a silently mixed iteration — exactly the torn
    state the merge-time sequence check exists to refuse."""

    expected_seq = None

    def __getitem__(self, k):
        f, orig = super().__getitem__(k)
        with np.load(f) as z:
            if self.expected_seq is not None:
                seq = json.loads(
                    bytes(z["__scalars__"]).decode()).get(_SAVE_SEQ_KEY)
                if seq != self.expected_seq:
                    raise ValueError(
                        f"checkpoint file {f} changed under the merged "
                        f"view (save sequence {seq} != merged "
                        f"{self.expected_seq}): a concurrent save replaced "
                        "it after load_checkpoint merged the process "
                        "files; re-run load_checkpoint for a consistent "
                        "view")
            return z[orig]

    def get(self, k, default=None):
        return self[k] if k in self else default

    def values(self):                                    # pragma: no cover
        return (self[k] for k in self)

    def items(self):
        return ((k, self[k]) for k in self)


def _merge_process_files(path: Path, files: list) -> tuple[dict, dict]:
    """Merge per-process checkpoint files into one (scalars, lazy arrays)
    view, with the three loud completeness checks of the module docstring.
    Only scalar blobs and entry NAMES are read here; shard data loads
    lazily on access (_LazyEntries)."""
    # Group by declared topology: save-time cleanup removes other-topology
    # files, but a preemption mid-cleanup can leave a mixture — prefer the
    # topology matching the CURRENT process count, else require uniqueness.
    by_nproc: dict = {}
    for f in files:
        by_nproc.setdefault(int(str(f.name).rsplit("of", 1)[1]), []).append(f)
    _, cur_nproc = _process_topology()
    if cur_nproc in by_nproc:
        nproc, group = cur_nproc, by_nproc[cur_nproc]
    elif len(by_nproc) == 1:
        (nproc, group), = by_nproc.items()
    else:
        raise ValueError(
            f"multi-process checkpoint at {path} carries files from "
            f"multiple process topologies {sorted(by_nproc)} and none "
            f"matches the current process count {cur_nproc}; delete the "
            "stale topology's files")
    if len(group) != nproc:
        raise ValueError(
            f"incomplete multi-process checkpoint at {path}: found "
            f"{len(group)} of {nproc} process files")
    parts = []          # (file, scalars, entry-names)
    for f in sorted(group):
        with np.load(f) as z:
            sc = json.loads(bytes(z["__scalars__"]).decode())
            names = [k for k in z.files if k != "__scalars__"]
        parts.append((f, sc, names))
    # Consistency marker, not a full-blob comparison: scalar state
    # legitimately differs across processes in wall-time fields (e.g. the
    # bisection records' per-iteration "seconds"), so the torn-save check
    # compares the save SEQUENCE stamped by save_checkpoint (same count of
    # saves on this path in every process) plus the run fingerprint.
    marks = {(s.get(_SAVE_SEQ_KEY), s.get("__fingerprint__"))
             for _, s, _ in parts}
    if len(marks) > 1:
        raise ValueError(
            f"inconsistent multi-process checkpoint at {path}: process "
            "files carry different save sequences (torn save — e.g. "
            "preemption between two processes' writes); delete and restart")
    # Seed this process's save counter from the restored sequence: a
    # resumed run's counters start at 0, and without re-seeding its first
    # post-resume save would stamp seq=1 again — making a later torn save
    # indistinguishable from a pre-resume generation (review round 5).
    restored_seq = parts[0][1].get(_SAVE_SEQ_KEY)
    if isinstance(restored_seq, int):
        _SAVE_COUNTS[str(path)] = max(
            _SAVE_COUNTS.get(str(path), 0), restored_seq)
    scalars = {k: v for k, v in parts[0][1].items() if k != _SAVE_SEQ_KEY}
    meta = scalars.get(_SHARD_META_KEY) or {}
    arrays = _LazyEntries()
    # Pin the merged generation: lazy opens refuse a proc file a concurrent
    # save has since replaced (class docstring).
    arrays.expected_seq = restored_seq
    merged_meta: dict = {}
    for name, m in meta.items():
        # Re-number shards globally, deduping identical index boxes
        # (replication across processes or mesh axes).
        by_index: dict = {}
        for f, s_part, _ in parts:
            part_meta = (s_part.get(_SHARD_META_KEY) or {}).get(name)
            if part_meta is None:
                continue
            for i, idx in enumerate(part_meta["indices"]):
                key = tuple(tuple(p) for p in idx)
                by_index.setdefault(key, (f, f"{name}__shard{i}"))
        indices = []
        for j, (idx, ptr) in enumerate(sorted(by_index.items())):
            dict.__setitem__(arrays, f"{name}__shard{j}", ptr)
            indices.append([list(p) for p in idx])
        shape = tuple(m["shape"])
        covered = sum(
            int(np.prod([b - a for a, b in idx])) for idx in by_index)
        if covered != int(np.prod(shape)):
            raise ValueError(
                f"multi-process checkpoint shards for {name!r} do not tile "
                f"the full array (shape {shape}): {covered} of "
                f"{int(np.prod(shape))} elements covered")
        merged_meta[name] = {**m, "indices": indices}
    # Plain (replicated) entries: identical in every file; take the first.
    for f, _, names in parts:
        for k in names:
            if "__shard" not in k and k not in arrays:
                dict.__setitem__(arrays, k, (f, k))
    if merged_meta:
        scalars = {**scalars, _SHARD_META_KEY: merged_meta}
    return scalars, arrays


def load_checkpoint(path) -> Optional[tuple[dict, dict]]:
    """Returns (scalars, arrays) or None if no checkpoint exists. A
    multi-process checkpoint (per-process files, module docstring) is
    merged with completeness checks; every process sees the same merged
    view and restore_array places only its addressable shards."""
    path = Path(path)
    if path.exists():
        return _load_npz(path)
    files = list(path.parent.glob(path.name + ".proc*of*"))
    if not files:
        return None
    return _merge_process_files(path, files)


def config_fingerprint(*objs: Any) -> str:
    """Stable fingerprint of run configuration (dataclasses or plain values),
    stored with every checkpoint so stale state from a different run setup is
    rejected instead of silently mixed in."""
    import dataclasses
    import hashlib

    def norm(o):
        if dataclasses.is_dataclass(o) and not isinstance(o, type):
            return {"__cls__": type(o).__name__, **{
                k: norm(v) for k, v in dataclasses.asdict(o).items()
            }}
        return o

    blob = json.dumps([norm(o) for o in objs], sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


class CheckpointManager:
    """Per-run checkpoint helper bound to a directory.

    Usage in an outer loop:
        mgr = CheckpointManager(dir, "aiyagari_egm", fingerprint=config_fingerprint(cfg, solver))
        state = mgr.restore()          # None on fresh start or config mismatch
        ...
        mgr.save(scalars={...}, arrays={...})   # each outer iteration
        mgr.delete()                             # on successful completion
    """

    def __init__(self, directory, name: str, fingerprint: Optional[str] = None):
        self.path = Path(directory) / f"{name}.ckpt.npz"
        self.fingerprint = fingerprint

    def restore(self) -> Optional[tuple[dict, dict]]:
        state = load_checkpoint(self.path)
        if state is None:
            return None
        scalars, arrays = state
        if self.fingerprint is not None and scalars.get("__fingerprint__") != self.fingerprint:
            import warnings

            warnings.warn(
                f"checkpoint at {self.path} was written under a different run "
                "configuration; ignoring it and starting fresh",
                stacklevel=2,
            )
            return None
        scalars = {k: v for k, v in scalars.items() if k != "__fingerprint__"}
        return scalars, arrays

    def save(self, *, scalars: dict, arrays: Optional[dict] = None) -> None:
        if self.fingerprint is not None:
            scalars = {**scalars, "__fingerprint__": self.fingerprint}
        save_checkpoint(self.path, scalars=scalars, arrays=arrays)

    def delete(self) -> None:
        # missing_ok on BOTH forms (matching save_checkpoint's guard): in a
        # multi-process run every process calls delete() on the shared
        # directory, and the exists()/unlink() pair — or a glob hit another
        # process already removed — is a TOCTOU race that turned run
        # completion into FileNotFoundError.
        self.path.unlink(missing_ok=True)
        for f in self.path.parent.glob(self.path.name + ".proc*of*"):
            f.unlink(missing_ok=True)
