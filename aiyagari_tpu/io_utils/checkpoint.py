"""Outer-loop checkpoint/resume.

The reference has no persistence at all (no save/load anywhere; all state is
the MATLAB workspace — SURVEY.md §5.4). Here the tiny outer-loop state
(bisection bracket or ALM coefficients, warm-start policies/value, iteration
counters) is written at every outer iteration so a preempted run resumes
exactly where it stopped — the preemption-tolerance pattern TPU pods require
(SURVEY.md §5.3).

Format: a single .npz per run (atomic replace), arrays + a JSON-encoded
scalar-state blob. Sharded device arrays (the mesh routes' policies and
cross-sections) are packed PER SHARD: each addressable shard is fetched and
stored as its own entry, so no step of save or (sharding-aware) restore
ever materializes the full array on host — the memory-scaling property the
ring-sharded solvers exist to provide (SURVEY.md §5.4, VERDICT round 3 #7).
"""

from __future__ import annotations

import json
import os
import tempfile
from pathlib import Path
from typing import Any, Optional

import numpy as np

__all__ = ["save_checkpoint", "load_checkpoint", "config_fingerprint",
           "restore_array", "CheckpointManager"]

_SHARD_META_KEY = "__shard_meta__"


def _is_distributed(v) -> bool:
    """A jax.Array whose sharding actually splits the data (a replicated or
    single-device array round-trips through np.asarray unchanged)."""
    try:
        import jax

        return (isinstance(v, jax.Array)
                and not v.sharding.is_fully_replicated)
    except ImportError:                                  # pragma: no cover
        return False


def _norm_index(index, shape) -> tuple:
    """Canonical ((start, stop), ...) form of a shard's index tuple."""
    out = []
    for sl, dim in zip(index, shape):
        start, stop, step = sl.indices(dim)
        assert step == 1
        out.append((start, stop))
    return tuple(out)


def _pack_arrays(arrays: Optional[dict]) -> tuple[dict, dict]:
    """Split distributed jax.Arrays into per-shard entries (name__shard{i})
    plus an index-map meta blob; pass everything else to np.asarray whole.
    The per-shard np.asarray fetches one shard-sized buffer at a time.
    Shards replicated over a second mesh axis (e.g. a ("agents","grid")
    mesh) repeat the same index — deduped here, so the file carries each
    distinct slice once. Multi-process arrays (shards on non-addressable
    devices) are refused loudly: each process would silently write only
    its shards to the same path and a resume would read a half-empty
    checkpoint — coordinated multi-host checkpointing is an orbax job,
    not this format's."""
    plain: dict = {}
    meta: dict = {}
    for k, v in (arrays or {}).items():
        if _is_distributed(v):
            if not v.is_fully_addressable:
                raise ValueError(
                    f"checkpoint array {k!r} spans multiple processes; "
                    "per-shard npz checkpointing is single-process only — "
                    "gather it or use a coordinated (orbax) checkpointer")
            by_index = {}
            for sh in v.addressable_shards:
                by_index.setdefault(_norm_index(sh.index, v.shape), sh)
            indices = []
            for i, (idx, sh) in enumerate(sorted(by_index.items())):
                plain[f"{k}__shard{i}"] = np.asarray(sh.data)
                indices.append([list(p) for p in idx])
            meta[k] = {"shape": list(v.shape), "dtype": str(v.dtype),
                       "indices": indices}
        else:
            plain[k] = np.asarray(v)
    return plain, meta


def restore_array(scalars: dict, arrays: dict, name: str, sharding=None,
                  dtype=None):
    """Reassemble array `name` from a checkpoint's (scalars, arrays) pair.

    Plain entries return as stored. Per-shard entries (written by the
    sharded save path) are restored WITHOUT host materialization when
    `sharding` (a NamedSharding matching the original mesh layout) is
    given: jax.make_array_from_callback places each stored shard directly
    on its device. With a different sharding — or none — the shards are
    assembled into one host array first (the resharding fallback, which
    does materialize; callers resuming a mesh run pass the mesh's
    sharding). Returns None when the name is absent entirely."""
    meta = (scalars.get(_SHARD_META_KEY) or {}).get(name)
    if meta is None:
        v = arrays.get(name)
        if v is None:
            return None
        if dtype is not None:
            v = np.asarray(v, dtype)
        if sharding is not None:
            # Plain (legacy / unsharded-save) entry resumed under a mesh:
            # place it once here, so callers never pay an implicit
            # full-array reshard inside their first jitted step.
            import jax

            return jax.device_put(v, sharding)
        return v
    shape = tuple(meta["shape"])
    lookup = {tuple(tuple(p) for p in idx): arrays[f"{name}__shard{i}"]
              for i, idx in enumerate(meta["indices"])}
    if dtype is not None:
        lookup = {k: np.asarray(v, dtype) for k, v in lookup.items()}
    if sharding is not None:
        import jax

        full = None

        def cb(index):
            nonlocal full
            key = _norm_index(index, shape)
            hit = lookup.get(key)
            if hit is None:
                # Mesh geometry changed between save and resume: assemble
                # the stored shards ONCE and serve every request by slice.
                if full is None:
                    full = _assemble(lookup, shape)
                hit = full[tuple(slice(a, b) for a, b in key)]
            return hit

        return jax.make_array_from_callback(shape, sharding, cb)
    return _assemble(lookup, shape)


def _assemble(lookup: dict, shape) -> np.ndarray:
    first = next(iter(lookup.values()))
    out = np.empty(shape, dtype=first.dtype)
    covered = np.zeros(shape, dtype=bool)
    for key, data in lookup.items():
        sl = tuple(slice(a, b) for a, b in key)
        out[sl] = data
        covered[sl] = True
    if not covered.all():
        # A gap here means the checkpoint was written by a process that
        # did not hold every shard — surfacing it beats silently returning
        # uninitialized memory as a "restored" array.
        raise ValueError(
            "stored shards do not tile the full array "
            f"(shape {shape}): incomplete (multi-process?) checkpoint")
    return out


def save_checkpoint(path, *, scalars: dict, arrays: Optional[dict] = None) -> None:
    """Atomically write scalar state (JSON-serializable) + named arrays.
    Distributed jax.Arrays among `arrays` are stored per shard
    (_pack_arrays) and restored via restore_array."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    packed, shard_meta = _pack_arrays(arrays)
    if shard_meta:
        scalars = {**scalars, _SHARD_META_KEY: shard_meta}
    payload = {"__scalars__": np.frombuffer(json.dumps(scalars).encode(), dtype=np.uint8)}
    for k, v in packed.items():
        payload[k] = v
    fd, tmp = tempfile.mkstemp(dir=path.parent, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load_checkpoint(path) -> Optional[tuple[dict, dict]]:
    """Returns (scalars, arrays) or None if no checkpoint exists."""
    path = Path(path)
    if not path.exists():
        return None
    with np.load(path) as z:
        scalars = json.loads(bytes(z["__scalars__"]).decode())
        arrays = {k: z[k] for k in z.files if k != "__scalars__"}
    return scalars, arrays


def config_fingerprint(*objs: Any) -> str:
    """Stable fingerprint of run configuration (dataclasses or plain values),
    stored with every checkpoint so stale state from a different run setup is
    rejected instead of silently mixed in."""
    import dataclasses
    import hashlib

    def norm(o):
        if dataclasses.is_dataclass(o) and not isinstance(o, type):
            return {"__cls__": type(o).__name__, **{
                k: norm(v) for k, v in dataclasses.asdict(o).items()
            }}
        return o

    blob = json.dumps([norm(o) for o in objs], sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


class CheckpointManager:
    """Per-run checkpoint helper bound to a directory.

    Usage in an outer loop:
        mgr = CheckpointManager(dir, "aiyagari_egm", fingerprint=config_fingerprint(cfg, solver))
        state = mgr.restore()          # None on fresh start or config mismatch
        ...
        mgr.save(scalars={...}, arrays={...})   # each outer iteration
        mgr.delete()                             # on successful completion
    """

    def __init__(self, directory, name: str, fingerprint: Optional[str] = None):
        self.path = Path(directory) / f"{name}.ckpt.npz"
        self.fingerprint = fingerprint

    def restore(self) -> Optional[tuple[dict, dict]]:
        state = load_checkpoint(self.path)
        if state is None:
            return None
        scalars, arrays = state
        if self.fingerprint is not None and scalars.get("__fingerprint__") != self.fingerprint:
            import warnings

            warnings.warn(
                f"checkpoint at {self.path} was written under a different run "
                "configuration; ignoring it and starting fresh",
                stacklevel=2,
            )
            return None
        scalars = {k: v for k, v in scalars.items() if k != "__fingerprint__"}
        return scalars, arrays

    def save(self, *, scalars: dict, arrays: Optional[dict] = None) -> None:
        if self.fingerprint is not None:
            scalars = {**scalars, "__fingerprint__": self.fingerprint}
        save_checkpoint(self.path, scalars=scalars, arrays=arrays)

    def delete(self) -> None:
        if self.path.exists():
            self.path.unlink()
