"""Outer-loop checkpoint/resume.

The reference has no persistence at all (no save/load anywhere; all state is
the MATLAB workspace — SURVEY.md §5.4). Here the tiny outer-loop state
(bisection bracket or ALM coefficients, warm-start policies/value, iteration
counters) is written at every outer iteration so a preempted run resumes
exactly where it stopped — the preemption-tolerance pattern TPU pods require
(SURVEY.md §5.3).

Format: a single .npz per run (atomic replace), arrays + a JSON-encoded
scalar-state blob. Policies at reference scale are MBs; at scaled-up grids
checkpoint from the sharded representation via orbax instead (the API here is
deliberately the same shape).
"""

from __future__ import annotations

import json
import os
import tempfile
from pathlib import Path
from typing import Any, Optional

import numpy as np

__all__ = ["save_checkpoint", "load_checkpoint", "config_fingerprint", "CheckpointManager"]


def save_checkpoint(path, *, scalars: dict, arrays: Optional[dict] = None) -> None:
    """Atomically write scalar state (JSON-serializable) + named arrays."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    payload = {"__scalars__": np.frombuffer(json.dumps(scalars).encode(), dtype=np.uint8)}
    for k, v in (arrays or {}).items():
        payload[k] = np.asarray(v)
    fd, tmp = tempfile.mkstemp(dir=path.parent, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load_checkpoint(path) -> Optional[tuple[dict, dict]]:
    """Returns (scalars, arrays) or None if no checkpoint exists."""
    path = Path(path)
    if not path.exists():
        return None
    with np.load(path) as z:
        scalars = json.loads(bytes(z["__scalars__"]).decode())
        arrays = {k: z[k] for k in z.files if k != "__scalars__"}
    return scalars, arrays


def config_fingerprint(*objs: Any) -> str:
    """Stable fingerprint of run configuration (dataclasses or plain values),
    stored with every checkpoint so stale state from a different run setup is
    rejected instead of silently mixed in."""
    import dataclasses
    import hashlib

    def norm(o):
        if dataclasses.is_dataclass(o) and not isinstance(o, type):
            return {"__cls__": type(o).__name__, **{
                k: norm(v) for k, v in dataclasses.asdict(o).items()
            }}
        return o

    blob = json.dumps([norm(o) for o in objs], sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


class CheckpointManager:
    """Per-run checkpoint helper bound to a directory.

    Usage in an outer loop:
        mgr = CheckpointManager(dir, "aiyagari_egm", fingerprint=config_fingerprint(cfg, solver))
        state = mgr.restore()          # None on fresh start or config mismatch
        ...
        mgr.save(scalars={...}, arrays={...})   # each outer iteration
        mgr.delete()                             # on successful completion
    """

    def __init__(self, directory, name: str, fingerprint: Optional[str] = None):
        self.path = Path(directory) / f"{name}.ckpt.npz"
        self.fingerprint = fingerprint

    def restore(self) -> Optional[tuple[dict, dict]]:
        state = load_checkpoint(self.path)
        if state is None:
            return None
        scalars, arrays = state
        if self.fingerprint is not None and scalars.get("__fingerprint__") != self.fingerprint:
            import warnings

            warnings.warn(
                f"checkpoint at {self.path} was written under a different run "
                "configuration; ignoring it and starting fresh",
                stacklevel=2,
            )
            return None
        scalars = {k: v for k, v in scalars.items() if k != "__fingerprint__"}
        return scalars, arrays

    def save(self, *, scalars: dict, arrays: Optional[dict] = None) -> None:
        if self.fingerprint is not None:
            scalars = {**scalars, "__fingerprint__": self.fingerprint}
        save_checkpoint(self.path, scalars=scalars, arrays=arrays)

    def delete(self) -> None:
        if self.path.exists():
            self.path.unlink()
