"""Policy-surface surrogate: a cheap ridge regression from the quantized
calibration vector to (r, secant slope, low-rank consumption policy),
trained continuously from the serve layer's own solve stream.

The amortization ladder (ISSUE 16 / ROADMAP "Amortized solving") escalates
warm-start predictors by how far a request sits from the cache's samples:

  exact hit  →  blended neighbors  →  THIS SURROGATE  →  cold solve

The cache's contents are samples of a smooth map calibration → solution
(BKM 2018's near-linearity result in PAPERS.md is exactly why a low-order
polynomial fits it well over a serving session's calibration range). When
no cached neighbor is within `neighbor_radius`, the service asks the
surrogate for a predicted rate + policy and runs the SAME secant polish it
runs on a cache-warm request — so a "cold" request becomes a few Newton
steps. Correctness is owned downstream: the polish must converge and the
result is stored/served like any other solve; a bad prediction degrades to
a true cold solve (a counted `degradation` event), never a wrong answer.

Structure
---------
Observations are keyed by the cache's STRUCTURAL key (grid geometry,
income states, technology — serve/cache._structural_key): policies only
share a shape, and the calibration→r map only stays smooth, within one
structure. Per structure the surrogate keeps a bounded sample ring and
fits, every `fit_every` observations (and from an optional background
thread):

  * features: quadratic polynomial of the standardized 7-parameter
    calibration vector — [1, z_i, z_i z_j (i<=j)] = 36 features,
  * an r head and a slope head: ridge least squares (36x36 host solve),
  * a policy head: rank-k SVD basis of the centered stacked policies with
    ridge-regressed coefficients — predictions are mean + coeffs @ basis.

Training data arrives two ways: in-process (`observe`, called by the
service whenever a converged steady state is stored) and from a persisted
run ledger (`ingest_ledger` replays `serve_request` events that carry
`params`/`r` — the r head survives a server restart; policies are only
available in-process).

Observability: every fit emits a `surrogate_fit` ledger event (sample
count, in-sample r residual, policy rank, wall) plus
`aiyagari_surrogate_fits_total` / `aiyagari_surrogate_samples` series.
All diagnostics are best-effort and can never fail a solve.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

__all__ = ["PolicySurrogate", "SurrogatePrediction"]

_N_PARAMS = 7  # serve/cache.PARAM_FIELDS
_N_FEATURES = 1 + _N_PARAMS + _N_PARAMS * (_N_PARAMS + 1) // 2  # 36


def _features(z: np.ndarray) -> np.ndarray:
    """Quadratic polynomial features of standardized params: [n, 36]."""
    z = np.atleast_2d(np.asarray(z, dtype=np.float64))
    n = z.shape[0]
    cols = [np.ones((n, 1)), z]
    for i in range(_N_PARAMS):
        for j in range(i, _N_PARAMS):
            cols.append((z[:, i] * z[:, j])[:, None])
    return np.concatenate(cols, axis=1)


def _ridge(F: np.ndarray, y: np.ndarray, lam: float) -> np.ndarray:
    """Ridge solve (F'F + lam I) w = F'y; y may be [n] or [n, k]."""
    G = F.T @ F + lam * np.eye(F.shape[1])
    return np.linalg.solve(G, F.T @ y)


class SurrogatePrediction:
    """One prediction: warm-start material, shaped like a cache payload."""

    __slots__ = ("r", "slope", "policy", "samples")

    def __init__(self, r: float, slope: Optional[float],
                 policy: Optional[np.ndarray], samples: int):
        self.r = r
        self.slope = slope
        self.policy = policy
        self.samples = samples


class _Head:
    """Fitted state for one structural key."""

    def __init__(self, max_samples: int, policy_rank: int):
        self.max_samples = max_samples
        self.policy_rank = policy_rank
        self.params: list = []      # [7] rows
        self.rs: list = []          # floats
        self.slopes: list = []      # float or nan
        self.policies: list = []    # flat np arrays (or None)
        self.policy_shape: Optional[Tuple[int, ...]] = None
        self.n_observed = 0
        self.n_at_fit = 0
        # fitted state
        self.mean: Optional[np.ndarray] = None
        self.std: Optional[np.ndarray] = None
        self.w_r: Optional[np.ndarray] = None
        self.w_slope: Optional[np.ndarray] = None
        self.policy_mean: Optional[np.ndarray] = None
        self.policy_basis: Optional[np.ndarray] = None
        self.w_policy: Optional[np.ndarray] = None
        self.r_rms: float = float("nan")

    def push(self, params, r, slope, policy) -> None:
        self.params.append(np.asarray(params, dtype=np.float64))
        self.rs.append(float(r))
        self.slopes.append(float("nan") if slope is None else float(slope))
        if policy is not None:
            pol = np.asarray(policy, dtype=np.float64)
            if self.policy_shape is None:
                self.policy_shape = pol.shape
            if pol.shape != self.policy_shape:
                pol = None  # shape drifted inside one structure: skip
            else:
                pol = pol.reshape(-1)
        self.policies.append(pol if policy is not None else None)
        self.n_observed += 1
        if len(self.params) > self.max_samples:
            self.params.pop(0)
            self.rs.pop(0)
            self.slopes.pop(0)
            self.policies.pop(0)


class PolicySurrogate:
    """Ridge surrogate over the calibration space, one head per structural
    key (module docstring). Thread-safe: the service worker observes and
    predicts while an optional background thread refits."""

    def __init__(self, *, min_samples: int = 12, fit_every: int = 8,
                 max_samples: int = 512, policy_rank: int = 4,
                 ridge_lambda: float = 1e-6):
        if min_samples < 2:
            raise ValueError(f"min_samples must be >= 2, got {min_samples}")
        if fit_every < 1:
            raise ValueError(f"fit_every must be >= 1, got {fit_every}")
        self.min_samples = int(min_samples)
        self.fit_every = int(fit_every)
        self.max_samples = int(max_samples)
        self.policy_rank = int(policy_rank)
        self.ridge_lambda = float(ridge_lambda)
        self._heads: Dict[tuple, _Head] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.fits = 0
        self.predictions = 0

    # -- training stream ---------------------------------------------------

    def observe(self, structural: tuple, params, r: float,
                slope: Optional[float] = None,
                policy=None) -> None:
        """One converged solve: calibration params (PARAM_FIELDS order),
        the equilibrium rate, an optional secant slope, an optional
        consumption policy [n_states, na]. Fits inline every `fit_every`
        observations once `min_samples` have arrived."""
        with self._lock:
            head = self._heads.get(structural)
            if head is None:
                head = _Head(self.max_samples, self.policy_rank)
                self._heads[structural] = head
            head.push(params, r, slope, policy)
            due = (len(head.params) >= self.min_samples
                   and head.n_observed - head.n_at_fit >= self.fit_every)
        if due:
            self.fit(structural)

    def ingest_ledger(self, path, structural: tuple) -> int:
        """Replay a persisted run ledger's `serve_request` stream into the
        head for `structural` (the structure this server runs at — the
        event does not carry grid geometry). Only converged steady-state
        events that recorded `params` and `r` train; policies are not in
        the ledger, so this warms the r/slope heads only. Returns the
        number of observations ingested."""
        from aiyagari_tpu.diagnostics.ledger import read_ledger

        n = 0
        for event in read_ledger(path):
            if event.get("kind") != "serve_request":
                continue
            if event.get("request_kind") != "steady_state":
                continue
            if not event.get("converged"):
                continue
            params, r = event.get("params"), event.get("r")
            if params is None or r is None or len(params) != _N_PARAMS:
                continue
            self.observe(structural, params, float(r),
                         slope=event.get("slope"))
            n += 1
        return n

    # -- fitting -----------------------------------------------------------

    def fit(self, structural: Optional[tuple] = None) -> bool:
        """Refit one head (or every head when structural is None). Returns
        True if at least one head (re)fitted."""
        if structural is None:
            with self._lock:
                keys = list(self._heads)
            return any([self.fit(k) for k in keys])
        t0 = time.perf_counter()
        with self._lock:
            head = self._heads.get(structural)
            if head is None or len(head.params) < self.min_samples:
                return False
            X = np.stack(head.params)
            y_r = np.asarray(head.rs, dtype=np.float64)
            y_s = np.asarray(head.slopes, dtype=np.float64)
            pols = [p for p in head.policies if p is not None]
            P = np.stack(pols) if len(pols) >= self.min_samples else None
            pol_mask = np.asarray([p is not None for p in head.policies])

            mean = X.mean(axis=0)
            std = X.std(axis=0)
            std = np.where(std < 1e-12, 1.0, std)
            F = _features((X - mean) / std)
            lam = self.ridge_lambda
            # Fit EVERY component into locals first, then stamp the head
            # in one block: mean/std and the weights they standardize for
            # must move together. A calibration-driven shift in the
            # parameter range changes mean/std sharply — a head half
            # updated (ridge raising midway, or a policy head left over
            # from a round that no longer receives policies, e.g. ledger
            # replay) would apply OLD weights to NEW standardization.
            w_r = _ridge(F, y_r, lam)
            r_rms = float(np.sqrt(np.mean((F @ w_r - y_r) ** 2)))
            s_mask = np.isfinite(y_s)
            w_slope = (_ridge(F[s_mask], y_s[s_mask], lam)
                       if s_mask.sum() >= self.min_samples else None)
            pmean = basis = w_policy = None
            if P is not None:
                pmean = P.mean(axis=0)
                Pc = P - pmean
                rank = max(1, min(self.policy_rank, P.shape[0] - 1))
                _, _, Vt = np.linalg.svd(Pc, full_matrices=False)
                basis = Vt[:rank]
                coeffs = Pc @ basis.T
                w_policy = _ridge(F[pol_mask], coeffs, lam)
            head.mean, head.std = mean, std
            head.w_r, head.r_rms = w_r, r_rms
            head.w_slope = w_slope
            head.policy_mean = pmean
            head.policy_basis = basis
            head.w_policy = w_policy
            head.n_at_fit = head.n_observed
            self.fits += 1
            samples = len(head.params)
            r_rms = head.r_rms
            rank_out = (head.policy_basis.shape[0]
                        if head.policy_basis is not None else 0)
        self._emit_fit(samples=samples, r_rms=r_rms, policy_rank=rank_out,
                       wall_s=time.perf_counter() - t0)
        return True

    # -- prediction --------------------------------------------------------

    def predict(self, structural: tuple,
                params) -> Optional[SurrogatePrediction]:
        """Warm-start material for one request, or None while the head is
        unfit (below `min_samples` or never fitted) — the caller MUST
        treat None as a cold solve (pinned in tests/test_serve.py). A
        non-finite prediction also returns None: the surrogate never
        hands the polish a poisoned guess."""
        with self._lock:
            head = self._heads.get(structural)
            if head is None or head.w_r is None:
                return None
            x = np.asarray(params, dtype=np.float64)
            f = _features(((x - head.mean) / head.std)[None, :])[0]
            r = float(f @ head.w_r)
            if not np.isfinite(r):
                return None
            slope = None
            if head.w_slope is not None:
                s = float(f @ head.w_slope)
                slope = s if np.isfinite(s) and s < 0.0 else None
            policy = None
            if head.w_policy is not None:
                flat = head.policy_mean + (f @ head.w_policy) @ \
                    head.policy_basis
                if np.all(np.isfinite(flat)):
                    policy = np.maximum(
                        flat.reshape(head.policy_shape), 1e-12)
            self.predictions += 1
            samples = len(head.params)
        self._count_prediction()
        return SurrogatePrediction(r=r, slope=slope, policy=policy,
                                   samples=samples)

    # -- background cadence ------------------------------------------------

    def start_background(self, interval_s: float = 2.0) -> None:
        """Refit every head on a daemon-thread cadence — the 'trained
        continuously' mode for long-lived servers; inline fit_every
        cadence keeps working either way. Idempotent."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.fit(None)
                except Exception:  # pragma: no cover - never kill cadence
                    pass

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="surrogate-refit")
        self._thread.start()

    def stop_background(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            heads = {
                repr(k): {"samples": len(h.params),
                          "observed": h.n_observed,
                          "fitted": h.w_r is not None,
                          "r_rms": None if not np.isfinite(h.r_rms)
                          else round(h.r_rms, 8)}
                for k, h in self._heads.items()}
        return {"heads": len(heads), "fits": self.fits,
                "predictions": self.predictions, "per_head": heads}

    # -- observability (must never fail a solve) ---------------------------

    def _emit_fit(self, **fields) -> None:
        try:
            from aiyagari_tpu.diagnostics import metrics
            from aiyagari_tpu.diagnostics.ledger import active_ledger

            metrics.counter("aiyagari_surrogate_fits_total").inc()
            metrics.gauge("aiyagari_surrogate_samples").set(
                fields.get("samples", 0))
            led = active_ledger()
            if led is not None:
                led.event("surrogate_fit", **{
                    k: (round(v, 6) if isinstance(v, float) else v)
                    for k, v in fields.items()})
        except Exception:  # pragma: no cover - diagnostics are best-effort
            pass

    def _count_prediction(self) -> None:
        try:
            from aiyagari_tpu.diagnostics import metrics

            metrics.counter("aiyagari_surrogate_predictions_total").inc()
        except Exception:  # pragma: no cover - diagnostics are best-effort
            pass
