"""Tiered solution cache (ISSUE 20 tentpole, layer 1): the in-process LRU
(serve/cache.py) stays L1; this module adds a shared, directory-backed L2
so a converged solve stored by worker A becomes warm-start material —
hit-bucket, blend, neighbor, or transition-anchor — on worker B.

The tier's one correctness rule: **L2 never answers a request.** An L2
find is promoted into L1 and returned as outcome "warm", even when the
stored exact calibration matches the request's — so every cross-worker
payload re-enters the PR 16 predictor ladder (secant polish for steady
states, anchor/Jacobian reuse with the non-convergence degrade for
transitions) and the bitwise degrade-to-cold band holds across the tier:
a poisoned, stale, or torn L2 entry can cost wall time, never a wrong
answer (pinned by tests/test_tier.py and `bench.py --metric fleet`).
Only after the request's own solve converges is its result re-stored —
under the request's own key, in both tiers (write-through).

Storage format (mirrors `tuning/autotuner.py`'s cache discipline):

  * One pickle file per quantized bucket key, named by the key's sha256 —
    two workers solving the same bucket converge on the same file.
  * Writes are ATOMIC (unique tmp file + os.replace): a concurrent reader
    never sees a torn document from a well-behaved writer.
  * Every document is stamped with {format version, jax/jaxlib versions,
    platform fingerprint, quantization resolution}. A stamp mismatch —
    another jax lowering, different silicon, a different bucket width —
    makes the entry STALE: it is skipped loudly (warning + `degradation`
    ledger event + counter), never deserialized into a warm start.
  * Torn/corrupt payloads (a killed writer, a disk error) and
    index-said-present-but-gone files (the eviction race between two
    workers) degrade the same way: loud, counted, non-fatal — the lookup
    reports a miss and the request solves cold.
  * The directory is byte-budgeted: after each write, oldest-mtime files
    are evicted until the budget holds, tolerating the racing unlink a
    second worker's eviction pass may win.

Trust model: the L2 directory is a pickle store shared by one fleet's
workers — the same trust domain as the process list itself. Do not point
it at a directory writable by untrusted parties.
"""

from __future__ import annotations

import dataclasses
import hashlib
import math
import os
import pickle
import threading
import time
import warnings
from pathlib import Path
from typing import List, Optional, Tuple

from aiyagari_tpu.serve.cache import (
    CacheEntry,
    SolutionCache,
    payload_nbytes,
)

__all__ = ["L2Tier", "TieredSolutionCache"]

_STAMP_VERSION = 1


@dataclasses.dataclass
class L2Doc:
    """One deserialized L2 entry: the (key, exact, payload) triple a
    promotion adopts into L1, plus the file it came from."""

    key: tuple
    exact: Tuple[float, ...]
    payload: object
    path: Path


class L2Tier:
    """The shared directory tier. Thread-safe within a process; safe
    across processes by construction (atomic writes, stamped reads,
    race-tolerant eviction). All failure paths are loud-but-non-fatal:
    a broken shared cache must never fail a solve."""

    def __init__(self, directory, byte_budget: int = 1 << 30, *,
                 resolution: float = 1e-3, ledger=None):
        if resolution <= 0.0:
            raise ValueError(f"resolution must be > 0, got {resolution}")
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.byte_budget = int(byte_budget)
        self.resolution = float(resolution)
        self._ledger = ledger
        self._lock = threading.RLock()
        # fname -> (mtime_ns, size, key, exact); key is None for files
        # that failed to read at that signature (no re-warn until the
        # file changes).
        self._index: dict = {}
        self._warned: set = set()
        self.hits = 0
        self.misses = 0
        self.writes = 0
        self.evictions = 0
        self.degradations = 0

    # -- identity ----------------------------------------------------------

    def stamp(self) -> dict:
        """The document identity: a mismatch on ANY field makes an entry
        stale (the autotuner cache's invalidation rule — measurements and
        payloads age with the lowering and the silicon; resolution is in
        the stamp because the bucket keys are computed at it)."""
        import jax
        import jaxlib

        from aiyagari_tpu.tuning.autotuner import platform_fingerprint

        return {"version": _STAMP_VERSION, "jax": jax.__version__,
                "jaxlib": jaxlib.__version__,
                "fingerprint": platform_fingerprint(),
                "resolution": self.resolution}

    def path_for(self, key: tuple) -> Path:
        digest = hashlib.sha256(repr(key).encode()).hexdigest()[:40]
        return self.dir / f"{digest}.pkl"

    # -- degradation (loud, counted, non-fatal) ----------------------------

    def _degrade(self, reason: str, path, error: str = "") -> None:
        with self._lock:
            self.degradations += 1
        try:
            from aiyagari_tpu.diagnostics import metrics

            metrics.counter("aiyagari_serve_l2_degradations_total",
                            reason=reason).inc()
        except Exception:  # pragma: no cover - diagnostics are best-effort
            pass
        try:
            from aiyagari_tpu.diagnostics import ledger as ledger_mod

            fields = dict(stage="l2_tier", reason=reason, path=str(path),
                          error=str(error)[:200])
            if self._ledger is not None:
                self._ledger.event("degradation", **fields)
            else:
                ledger_mod.emit("degradation", **fields)
        except Exception:  # pragma: no cover - diagnostics are best-effort
            pass
        warn_key = (str(path), reason)
        if warn_key not in self._warned:
            self._warned.add(warn_key)
            warnings.warn(
                f"L2 solution tier entry {path} degraded ({reason}"
                f"{': ' + str(error)[:120] if error else ''}); treating it "
                "as a miss — the request solves cold",
                RuntimeWarning, stacklevel=3)

    # -- read path ---------------------------------------------------------

    def _read(self, path: Path, *, expected: bool) -> Optional[L2Doc]:
        """Read + validate one entry file. `expected` marks a file the
        in-process index believed present: its disappearance is the
        two-worker eviction race (degradation), while a plain absent
        bucket file is an ordinary miss."""
        try:
            data = path.read_bytes()
        except FileNotFoundError:
            if expected:
                self._degrade("evicted_during_read", path)
            return None
        except OSError as e:
            self._degrade("unreadable", path, str(e))
            return None
        try:
            doc = pickle.loads(data)
            if (not isinstance(doc, dict) or "payload" not in doc
                    or "key" not in doc or "exact" not in doc):
                raise ValueError("document missing key/exact/payload")
        except Exception as e:  # noqa: BLE001 — any torn pickle shape
            self._degrade("torn_payload", path, f"{type(e).__name__}: {e}")
            return None
        if doc.get("stamp") != self.stamp():
            self._degrade("stale_stamp", path,
                          f"stamp {doc.get('stamp')!r}")
            return None
        return L2Doc(key=tuple(doc["key"]), exact=tuple(doc["exact"]),
                     payload=doc["payload"], path=path)

    def _refresh_index(self) -> None:
        """Bring the in-process (fname -> key, exact) index up to date:
        unpickle only new/changed files, drop vanished ones. The index is
        what makes neighbor scans O(entries) host arithmetic instead of
        O(entries) unpickles per lookup."""
        with self._lock:
            seen = set()
            try:
                it = os.scandir(self.dir)
            except OSError:
                return
            with it:
                for de in it:
                    if not de.name.endswith(".pkl"):
                        continue
                    seen.add(de.name)
                    try:
                        st = de.stat()
                    except OSError:
                        continue
                    sig = (st.st_mtime_ns, st.st_size)
                    memo = self._index.get(de.name)
                    if memo is not None and memo[0] == sig[0] \
                            and memo[1] == sig[1]:
                        continue
                    doc = self._read(Path(de.path), expected=False)
                    if doc is None:
                        # Remember the failure at this signature so a
                        # torn file degrades once, not on every scan.
                        self._index[de.name] = (*sig, None, None)
                    else:
                        self._index[de.name] = (*sig, doc.key, doc.exact)
            for name in list(self._index):
                if name not in seen:
                    del self._index[name]

    def _candidates(self, key: tuple,
                    exact: Tuple[float, ...]) -> List[Tuple[float, Path]]:
        """(distance-in-bucket-units, path) for every indexed same-kind /
        same-structure / same-extra entry, nearest first."""
        kind, structural, extra = key[0], key[1], key[3]
        out: List[Tuple[float, Path]] = []
        with self._lock:
            for name, (_, _, k2, e2) in self._index.items():
                if k2 is None or k2[0] != kind or k2[1] != structural \
                        or k2[3] != extra:
                    continue
                d = math.sqrt(sum((a - b) ** 2 for a, b in
                                  zip(e2, exact))) / self.resolution
                out.append((d, self.dir / name))
        out.sort(key=lambda pair: pair[0])
        return out

    def lookup(self, key: tuple, exact: Tuple[float, ...], *,
               radius: float) -> Optional[L2Doc]:
        """The best warm material for this request: the exact bucket file
        if present and valid, else the nearest in-radius neighbor from the
        index (falling through candidates whose files a racing eviction
        already removed — each fall-through is a counted degradation)."""
        path = self.path_for(key)
        with self._lock:
            expected = path.name in self._index \
                and self._index[path.name][2] is not None
        doc = self._read(path, expected=expected)
        if doc is not None:
            with self._lock:
                self.hits += 1
            self._count("hits")
            return doc
        self._refresh_index()
        for d, cand in self._candidates(key, exact):
            if d > radius:
                break
            if cand == path:
                continue        # already tried (and degraded) above
            doc = self._read(cand, expected=True)
            if doc is not None:
                with self._lock:
                    self.hits += 1
                self._count("hits")
                return doc
        with self._lock:
            self.misses += 1
        self._count("misses")
        return None

    def neighbors(self, key: tuple, exact: Tuple[float, ...], *,
                  radius: float, limit: int = 8) -> List[L2Doc]:
        """Up to `limit` valid in-radius entries, nearest first — the
        multi-neighbor material a blend promotion pulls into L1."""
        self._refresh_index()
        out: List[L2Doc] = []
        for d, cand in self._candidates(key, exact):
            if d > radius or len(out) >= limit:
                break
            doc = self._read(cand, expected=True)
            if doc is not None:
                out.append(doc)
        return out

    # -- write path --------------------------------------------------------

    def put(self, key: tuple, exact: Tuple[float, ...], payload) -> bool:
        """Write-through one entry (atomic rename), then evict to budget.
        Unpicklable payloads (exotic result objects) are skipped with a
        counted degradation — the local L1 still holds them."""
        if self.byte_budget <= 0:
            return False
        path = self.path_for(key)
        doc = {"stamp": self.stamp(), "key": tuple(key),
               "exact": tuple(exact), "payload": payload}
        tmp = self.dir / (
            f"{path.name}.{os.getpid()}.{threading.get_ident()}.tmp")
        try:
            data = pickle.dumps(doc, protocol=pickle.HIGHEST_PROTOCOL)
            tmp.write_bytes(data)
            os.replace(tmp, path)
        except Exception as e:  # noqa: BLE001 — a broken shared cache
            # must never fail the solve that tried to share its result
            self._degrade("unwritable", path, f"{type(e).__name__}: {e}")
            try:
                tmp.unlink(missing_ok=True)
            except OSError:
                pass
            return False
        with self._lock:
            self.writes += 1
            try:
                st = path.stat()
                self._index[path.name] = (st.st_mtime_ns, st.st_size,
                                          tuple(key), tuple(exact))
            except OSError:
                pass
        self._count("writes")
        self._evict()
        return True

    def _evict(self) -> None:
        """Delete oldest-mtime entries until the directory fits the byte
        budget. Two workers may run this concurrently — the unlink
        tolerates losing the race."""
        try:
            with os.scandir(self.dir) as it:
                files = []
                for de in it:
                    if not de.name.endswith(".pkl"):
                        continue
                    try:
                        st = de.stat()
                    except OSError:
                        continue
                    files.append((st.st_mtime_ns, st.st_size, de.path,
                                  de.name))
        except OSError:
            return
        total = sum(sz for _, sz, _, _ in files)
        if total <= self.byte_budget:
            return
        files.sort()
        for _, sz, fpath, name in files:
            if total <= self.byte_budget or len(files) <= 1:
                break
            try:
                os.unlink(fpath)
            except FileNotFoundError:
                pass        # the other worker's eviction pass won
            except OSError:
                continue
            total -= sz
            with self._lock:
                self.evictions += 1
                self._index.pop(name, None)
            self._count("evictions")

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        entries = nbytes = 0
        try:
            with os.scandir(self.dir) as it:
                for de in it:
                    if de.name.endswith(".pkl"):
                        entries += 1
                        try:
                            nbytes += de.stat().st_size
                        except OSError:
                            pass
        except OSError:
            pass
        with self._lock:
            return {"dir": str(self.dir), "entries": entries,
                    "bytes": nbytes, "hits": self.hits,
                    "misses": self.misses, "writes": self.writes,
                    "evictions": self.evictions,
                    "degradations": self.degradations}

    @staticmethod
    def _count(outcome: str) -> None:
        try:
            from aiyagari_tpu.diagnostics import metrics

            metrics.counter(f"aiyagari_serve_l2_{outcome}_total").inc()
        except Exception:  # pragma: no cover - diagnostics are best-effort
            pass


class TieredSolutionCache(SolutionCache):
    """L1 (the in-process LRU) over a shared L2 directory. Drop-in for
    `SolutionCache` at the service boundary:

      * `lookup` classifies L1 first under the L1 lock; only on an L1
        miss is the tier consulted (file I/O never blocks HTTP threads'
        peeks on the LRU). An L2 find is promoted into L1 (`put_entry`,
        same lock discipline as a local put) and returned as "warm" —
        NEVER "hit" — so cross-worker payloads always re-enter the
        predictor ladder's polish/degrade machinery.
      * `put` writes through: the converged payload lands in L1 and in
        the shared directory, becoming the other workers' warm material.
      * `neighborhood` promotes up to 8 in-radius L2 entries first, so
        multi-neighbor blends and transition-anchor interpolation see the
        fleet's material, then delegates to the L1 scan.
    """

    def __init__(self, byte_budget: int = 256 * 1024 * 1024, *,
                 resolution: float = 1e-3, neighbor_radius: float = 50.0,
                 l2: L2Tier, ledger=None):
        super().__init__(byte_budget, resolution=resolution,
                         neighbor_radius=neighbor_radius)
        if float(l2.resolution) != float(resolution):
            raise ValueError(
                f"L2 tier resolution {l2.resolution} != cache resolution "
                f"{resolution}: the bucket keys would not line up across "
                "workers")
        self.l2 = l2
        self._tier_ledger = ledger

    def lookup(self, config, *, kind: str = "ss", extra: tuple = ()):
        key = self.key_for(config, kind=kind, extra=extra)
        from aiyagari_tpu.serve.cache import calibration_params

        exact = calibration_params(config)
        with self._lock:
            outcome, entry = self._classify_locked(key, exact)
            if outcome != "miss":
                self._count_outcome_locked(outcome)
                return outcome, entry
        doc = self.l2.lookup(key, exact, radius=self.neighbor_radius)
        if doc is None:
            with self._lock:
                self._count_outcome_locked("miss")
            return "miss", None
        entry = self._promote(doc)
        with self._lock:
            self._count_outcome_locked("warm")
        return "warm", entry

    def _promote(self, doc: L2Doc) -> CacheEntry:
        """Adopt one L2 document into L1 under the L1 lock. If L1 refuses
        it (payload over the whole budget), the material is still handed
        back as a transient entry — warm material is warm material."""
        entry = self.put_entry(doc.key, doc.exact, doc.payload,
                               promoted=True)
        if entry is None:
            entry = CacheEntry(key=doc.key, exact=doc.exact,
                               payload=doc.payload,
                               nbytes=payload_nbytes(doc.payload),
                               stored_at=time.time(), promoted=True)
        self._count_promotion(doc.key[0])
        return entry

    def _count_promotion(self, kind) -> None:
        try:
            from aiyagari_tpu.diagnostics import metrics

            metrics.counter("aiyagari_serve_l2_promotions_total",
                            kind=str(kind)).inc()
        except Exception:  # pragma: no cover - diagnostics are best-effort
            pass
        try:
            from aiyagari_tpu.diagnostics import ledger as ledger_mod

            # Field is named `promotion`, not `kind`: `kind` is the ledger
            # event type itself and would collide with event()'s positional.
            fields = dict(promotion=str(kind))
            if self._tier_ledger is not None:
                self._tier_ledger.event("tier_promote", **fields)
            else:
                ledger_mod.emit("tier_promote", **fields)
        except Exception:  # pragma: no cover - diagnostics are best-effort
            pass

    def put(self, config, payload, *, kind: str = "ss",
            extra: tuple = ()) -> Optional[CacheEntry]:
        entry = super().put(config, payload, kind=kind, extra=extra)
        if entry is not None:
            self.l2.put(entry.key, entry.exact, payload)
        return entry

    def neighborhood(self, config, *, kind: str = "ss",
                     extra: tuple = ()) -> List[Tuple[CacheEntry, float]]:
        key = self.key_for(config, kind=kind, extra=extra)
        from aiyagari_tpu.serve.cache import calibration_params

        exact = calibration_params(config)
        for doc in self.l2.neighbors(key, exact,
                                     radius=self.neighbor_radius):
            with self._lock:
                present = doc.key in self._entries
            if not present:
                self._promote(doc)
        return super().neighborhood(config, kind=kind, extra=extra)

    def stats(self) -> dict:
        out = super().stats()
        out["l2"] = self.l2.stats()
        return out
