"""Synthetic open-loop load driver: the requests/sec + latency-percentile
measurement `bench.py --metric serve` (and `python -m aiyagari_tpu serve
--load N`) runs against an in-process SolveService.

Open loop means arrivals follow the SCHEDULE, not the server: request i is
submitted at t0 + i/rps whether or not earlier requests finished, so queue
buildup shows up as latency (the production-realistic regime — a closed
loop would let a slow server throttle its own offered load and report
flattering percentiles). rps=None degenerates to submit-all-at-once, the
coalescing regime's natural drive.
"""

from __future__ import annotations

import time
from collections import Counter
from typing import Optional, Sequence

import numpy as np

__all__ = ["percentiles", "run_load", "run_ramp", "synthetic_requests"]


def synthetic_requests(base, n: int, *, seed: int = 0,
                       resolution: float = 1e-3, spread: float = 0.02,
                       kind: str = "steady_state", shock=None) -> list:
    """N requests over distinct calibrations of `base`: betas drawn
    uniformly within +/- spread of the base value (clipped into (0, 1)),
    rounded to half-resolution so repeated draws exercise both cache hits
    and near-bucket warm starts."""
    import dataclasses

    from aiyagari_tpu.serve.service import SolveRequest

    rng = np.random.default_rng(seed)
    beta0 = base.preferences.beta
    out = []
    for _ in range(n):
        beta = float(np.clip(beta0 + rng.uniform(-spread, spread),
                             0.80, 0.995))
        beta = round(beta / (0.5 * resolution)) * (0.5 * resolution)
        cfg = dataclasses.replace(
            base, preferences=dataclasses.replace(base.preferences,
                                                  beta=beta))
        out.append(SolveRequest(cfg, kind=kind, shock=shock))
    return out


def percentiles(latencies) -> dict:
    xs = np.asarray(sorted(float(v) for v in latencies), np.float64)
    if xs.size == 0:
        return {"p50_s": None, "p90_s": None, "p99_s": None, "mean_s": None}
    return {
        "p50_s": round(float(np.percentile(xs, 50)), 6),
        "p90_s": round(float(np.percentile(xs, 90)), 6),
        "p99_s": round(float(np.percentile(xs, 99)), 6),
        "mean_s": round(float(xs.mean()), 6),
    }


def run_load(service, requests: Sequence, *, rps: Optional[float] = None,
             closed: bool = False, timeout: float = 600.0) -> dict:
    """Drive `requests` through `service` on the open-loop schedule and
    assemble the latency/throughput report. Latency is client-observed:
    submit -> response, queue wait included (SolveResponse.latency_s).

    closed=True runs a CLOSED loop instead — each request waits for the
    previous response before submitting — which measures pure per-request
    service latency with no queueing (the one-at-a-time regime the serve
    bench's cold/warm percentiles are defined on); `rps` is ignored."""
    t0 = time.perf_counter()
    if closed:
        responses = [service.submit(req).result(timeout)
                     for req in requests]
    else:
        futures = []
        for i, req in enumerate(requests):
            if rps:
                target = t0 + i / float(rps)
                delay = target - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
            futures.append(service.submit(req))
        responses = [f.result(timeout) for f in futures]
    wall = time.perf_counter() - t0
    lat = [r.latency_s for r in responses]
    return {
        "requests": len(responses),
        "wall_s": round(wall, 6),
        "rps": round(len(responses) / wall, 4) if wall > 0 else None,
        "offered_rps": rps,
        **percentiles(lat),
        "statuses": dict(Counter(r.status for r in responses)),
        "cache_outcomes": dict(Counter(r.cache for r in responses)),
        "batch_sizes": sorted({r.batch for r in responses}),
        "max_queue_wait_s": round(max((r.queue_wait_s for r in responses),
                                      default=0.0), 6),
        "warm_sources": dict(Counter(r.warm_source for r in responses)),
        "degraded": sum(1 for r in responses if r.degraded),
    }


def run_ramp(service, make_requests, *, rates: Sequence[float],
             n_per_rate: int, slo_s: float,
             saturation: float = 0.9, timeout: float = 600.0) -> dict:
    """The offered-rps ramp (ISSUE 16): drive escalating OPEN-loop rates
    through the service and report the KNEE — the first offered rate whose
    p99 crosses the latency SLO or whose achieved throughput falls below
    `saturation` x offered (the server can no longer keep the schedule;
    past that point the open loop only measures queue growth). Below the
    knee the loop is effectively closed (the server keeps up); at the knee
    it transitions open — this IS the open→closed-loop boundary a capacity
    plan wants.

    `make_requests(n, step)` builds each step's fresh request list (fresh
    ids; calibration distribution is the caller's choice), so cache state
    carries across steps exactly as production traffic would see it.

    Returns {"steps": [per-rate run_load rows + offered/slo verdicts],
    "knee_rps": the last offered rate that met the SLO (None if the first
    step already missed), "slo_s": slo_s}."""
    if not rates:
        raise ValueError("run_ramp needs at least one offered rate")
    steps = []
    knee = None
    for step, rate in enumerate(rates):
        reqs = make_requests(n_per_rate, step)
        row = run_load(service, reqs, rps=float(rate), timeout=timeout)
        p99 = row.get("p99_s")
        achieved = row.get("rps") or 0.0
        met = (p99 is not None and p99 <= slo_s
               and achieved >= saturation * float(rate))
        row.update(offered_rps=float(rate), slo_met=met)
        steps.append(row)
        if met:
            knee = float(rate)
        else:
            break  # past the knee: further rates only grow the queue
    return {"steps": steps, "knee_rps": knee, "slo_s": slo_s}
