"""Synthetic open-loop load driver: the requests/sec + latency-percentile
measurement `bench.py --metric serve` (and `python -m aiyagari_tpu serve
--load N`) runs against an in-process SolveService.

Open loop means arrivals follow the SCHEDULE, not the server: request i is
submitted at t0 + i/rps whether or not earlier requests finished, so queue
buildup shows up as latency (the production-realistic regime — a closed
loop would let a slow server throttle its own offered load and report
flattering percentiles). rps=None degenerates to submit-all-at-once, the
coalescing regime's natural drive.
"""

from __future__ import annotations

import time
from collections import Counter
from typing import Optional, Sequence

import numpy as np

__all__ = ["HttpServiceClient", "percentiles", "run_load", "run_ramp",
           "synthetic_requests"]


class HttpServiceClient:
    """A `.submit(SolveRequest) -> Future` adapter over the stdlib HTTP
    front (service.py `_http_server`), so `run_load`/`run_ramp` can drive
    the FULL network path with the same driver they use in-process.

    Connections are persistent (ISSUE 18 satellite): each driver thread
    holds ONE keep-alive `http.client.HTTPConnection` — the server speaks
    HTTP/1.1 with Content-Length on every response, so the socket is
    reusable — and the measured SLO knee is solve throughput, not TCP
    setup/teardown per request. A connection that goes stale (server
    restart, idle timeout) is dropped and re-dialed once before the error
    propagates.

    The request's calibration travels as the `params` override the HTTP
    front applies over ITS base config (dispatch._SWEEP_PARAMS), extracted
    by diffing the request's config against `base` — so the client
    composes with `synthetic_requests(base, ...)` unchanged. Responses
    come back as SolveResponse objects; `latency_s` is CLIENT-observed
    (submit -> parsed response, network included), which is the number the
    knee is defined on."""

    def __init__(self, base, port, *, host: str = "127.0.0.1",
                 auth_token: Optional[str] = None, timeout: float = 600.0,
                 workers: int = 8):
        import itertools
        import threading
        from concurrent.futures import ThreadPoolExecutor

        self._base = base
        self._host = host
        # One port drives a single service; a SEQUENCE of ports fans the
        # same open-loop schedule round-robin over multiple base URLs —
        # the multi-worker drive `bench --metric fleet` rides (each driver
        # thread keeps one keep-alive socket PER port).
        self._ports = (tuple(int(p) for p in port)
                       if isinstance(port, (tuple, list)) else (int(port),))
        if not self._ports:
            raise ValueError("HttpServiceClient needs at least one port")
        self._rr = itertools.count()
        self._token = auth_token
        self._timeout = timeout
        self._tls = threading.local()
        self._pool = ThreadPoolExecutor(max_workers=workers,
                                        thread_name_prefix="serve-load")

    def _params_of(self, config) -> dict:
        """The sweep-param overrides that rebuild `config` from the base
        (loud on a config the HTTP front cannot express)."""
        import dataclasses

        from aiyagari_tpu.dispatch import _SWEEP_PARAMS, _scenario_config

        out = {}
        for name, (section, field) in _SWEEP_PARAMS.items():
            holder = config if section is None else getattr(config, section)
            base_holder = (self._base if section is None
                           else getattr(self._base, section))
            v, v0 = getattr(holder, field), getattr(base_holder, field)
            if v != v0:
                out[name] = v
        if _scenario_config(self._base, out) != config:
            raise ValueError(
                "request config differs from the client base outside the "
                f"sweepable params {sorted(_SWEEP_PARAMS)}; the HTTP front "
                "only applies params overrides over its base economy")
        return out

    def _connection(self, port: int):
        import http.client

        conns = getattr(self._tls, "conns", None)
        if conns is None:
            conns = self._tls.conns = {}
        conn = conns.get(port)
        if conn is None:
            conn = http.client.HTTPConnection(self._host, port,
                                              timeout=self._timeout)
            conns[port] = conn
        return conn

    def _post(self, path: str, body: str) -> dict:
        import http.client
        import json

        # Round-robin over the configured base URLs, one pick per request
        # (retries stay on the picked port — a stale socket is not a down
        # server).
        port = self._ports[next(self._rr) % len(self._ports)]
        headers = {"Content-Type": "application/json"}
        if self._token:
            headers["Authorization"] = f"Bearer {self._token}"
        for attempt in (0, 1):
            conn = self._connection(port)
            try:
                conn.request("POST", path, body, headers)
                resp = conn.getresponse()
                data = resp.read()      # drain: keeps the socket reusable
                return json.loads(data)
            except (http.client.HTTPException, OSError):
                # Stale keep-alive socket: drop it and re-dial ONCE.
                conn.close()
                self._tls.conns.pop(port, None)
                if attempt:
                    raise
        raise RuntimeError("unreachable")   # pragma: no cover

    def _roundtrip(self, req):
        import json

        from aiyagari_tpu.serve.service import SolveResponse

        body = {"params": self._params_of(req.config),
                "timeout": self._timeout}
        if req.kind == "transition":
            body["shock"] = {
                "param": req.shock.param, "size": req.shock.size,
                "rho": req.shock.rho}
        t0 = time.perf_counter()
        out = self._post("/solve", json.dumps(body))
        wall = time.perf_counter() - t0
        if "error" in out and "status" not in out:
            raise RuntimeError(f"HTTP solve failed: {out['error']}")
        resp = SolveResponse(
            id=out.get("id", req.id), kind=out.get("kind", req.kind),
            status=out["status"], cache=out.get("cache", "cold"),
            converged=bool(out.get("converged")),
            warm_source=out.get("warm_source", "cold"),
            degraded=bool(out.get("degraded")),
            r=out.get("r"), w=out.get("w"), capital=out.get("capital"),
            gap=out.get("gap"),
            queue_wait_s=out.get("queue_wait_s", 0.0),
            wall_s=out.get("wall_s", 0.0), batch=out.get("batch", 1))
        resp.latency_s = round(wall, 6)
        return resp

    def submit(self, request):
        return self._pool.submit(self._roundtrip, request)

    def close(self) -> None:
        self._pool.shutdown(wait=True)

    def __enter__(self) -> "HttpServiceClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def synthetic_requests(base, n: int, *, seed: int = 0,
                       resolution: float = 1e-3, spread: float = 0.02,
                       kind: str = "steady_state", shock=None) -> list:
    """N requests over distinct calibrations of `base`: betas drawn
    uniformly within +/- spread of the base value (clipped into (0, 1)),
    rounded to half-resolution so repeated draws exercise both cache hits
    and near-bucket warm starts."""
    import dataclasses

    from aiyagari_tpu.serve.service import SolveRequest

    rng = np.random.default_rng(seed)
    beta0 = base.preferences.beta
    out = []
    for _ in range(n):
        beta = float(np.clip(beta0 + rng.uniform(-spread, spread),
                             0.80, 0.995))
        beta = round(beta / (0.5 * resolution)) * (0.5 * resolution)
        cfg = dataclasses.replace(
            base, preferences=dataclasses.replace(base.preferences,
                                                  beta=beta))
        out.append(SolveRequest(cfg, kind=kind, shock=shock))
    return out


def percentiles(latencies) -> dict:
    xs = np.asarray(sorted(float(v) for v in latencies), np.float64)
    if xs.size == 0:
        return {"p50_s": None, "p90_s": None, "p99_s": None, "mean_s": None}
    return {
        "p50_s": round(float(np.percentile(xs, 50)), 6),
        "p90_s": round(float(np.percentile(xs, 90)), 6),
        "p99_s": round(float(np.percentile(xs, 99)), 6),
        "mean_s": round(float(xs.mean()), 6),
    }


def run_load(service, requests: Sequence, *, rps: Optional[float] = None,
             closed: bool = False, timeout: float = 600.0) -> dict:
    """Drive `requests` through `service` on the open-loop schedule and
    assemble the latency/throughput report. Latency is client-observed:
    submit -> response, queue wait included (SolveResponse.latency_s).

    closed=True runs a CLOSED loop instead — each request waits for the
    previous response before submitting — which measures pure per-request
    service latency with no queueing (the one-at-a-time regime the serve
    bench's cold/warm percentiles are defined on); `rps` is ignored."""
    t0 = time.perf_counter()
    if closed:
        responses = [service.submit(req).result(timeout)
                     for req in requests]
    else:
        futures = []
        for i, req in enumerate(requests):
            if rps:
                target = t0 + i / float(rps)
                delay = target - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
            futures.append(service.submit(req))
        responses = [f.result(timeout) for f in futures]
    wall = time.perf_counter() - t0
    lat = [r.latency_s for r in responses]
    return {
        "requests": len(responses),
        "wall_s": round(wall, 6),
        "rps": round(len(responses) / wall, 4) if wall > 0 else None,
        "offered_rps": rps,
        **percentiles(lat),
        "statuses": dict(Counter(r.status for r in responses)),
        "cache_outcomes": dict(Counter(r.cache for r in responses)),
        "batch_sizes": sorted({r.batch for r in responses}),
        "max_queue_wait_s": round(max((r.queue_wait_s for r in responses),
                                      default=0.0), 6),
        "warm_sources": dict(Counter(r.warm_source for r in responses)),
        "degraded": sum(1 for r in responses if r.degraded),
    }


def run_ramp(service, make_requests, *, rates: Sequence[float],
             n_per_rate: int, slo_s: float,
             saturation: float = 0.9, timeout: float = 600.0) -> dict:
    """The offered-rps ramp (ISSUE 16): drive escalating OPEN-loop rates
    through the service and report the KNEE — the first offered rate whose
    p99 crosses the latency SLO or whose achieved throughput falls below
    `saturation` x offered (the server can no longer keep the schedule;
    past that point the open loop only measures queue growth). Below the
    knee the loop is effectively closed (the server keeps up); at the knee
    it transitions open — this IS the open→closed-loop boundary a capacity
    plan wants.

    `make_requests(n, step)` builds each step's fresh request list (fresh
    ids; calibration distribution is the caller's choice), so cache state
    carries across steps exactly as production traffic would see it.

    Returns {"steps": [per-rate run_load rows + offered/slo verdicts],
    "knee_rps": the last offered rate that met the SLO (None if the first
    step already missed), "slo_s": slo_s}."""
    if not rates:
        raise ValueError("run_ramp needs at least one offered rate")
    steps = []
    knee = None
    for step, rate in enumerate(rates):
        reqs = make_requests(n_per_rate, step)
        row = run_load(service, reqs, rps=float(rate), timeout=timeout)
        p99 = row.get("p99_s")
        achieved = row.get("rps") or 0.0
        met = (p99 is not None and p99 <= slo_s
               and achieved >= saturation * float(rate))
        row.update(offered_rps=float(rate), slo_met=met)
        steps.append(row)
        if met:
            knee = float(rate)
        else:
            break  # past the knee: further rates only grow the queue
    return {"steps": steps, "knee_rps": knee, "slo_s": slo_s}
