"""The persistent solve service: warm-pool precompile, deadline
coalescing, a quantized solution cache, and SLO-aware execution on top of
the dispatch layer (ISSUE 15 tentpole).

Architecture (docs/DESIGN.md "Why coalescing sits above dispatch and the
cache below it"):

  * COALESCING sits ABOVE dispatch: the admission queue groups compatible
    incoming requests (same grid shapes, income-state structure, and
    technology — exactly `stack_scenarios`' one-compilation invariants)
    and lowers each group to ONE lockstep `dispatch.sweep()` /
    `dispatch.sweep_transitions()` call on a deadline (`max_wait_s` /
    `max_batch` knobs). PR 10's scenario quarantine is what makes this
    safe to do to strangers' requests: one pathological calibration
    degrades its own lane — with a structured verdict — never its
    batchmates, and the rescue ladder re-solves it serially as the
    server-side retry policy.
  * The SOLUTION CACHE sits BELOW dispatch conceptually: it stores solve
    OUTPUTS (equilibrium scalars, the warm-start policy, the stationary
    anchor + fake-news Jacobian) under quantized calibration fingerprints
    (serve/cache.py), and warm lookups re-enter dispatch as cheaper
    solves — a narrowed secant polish seeded with the cached consumption
    policy for steady states, an anchor/Jacobian reuse (`ss=`/`jacobian=`)
    for transitions — so a typical near-cached request does ~10x less
    work than a cold fixed-point solve, through the SAME observed dispatch
    boundary (route decisions, spans, verdicts all still recorded).

Response statuses reuse the resilience verdict taxonomy (ISSUE 10):
"converged" | "rescued" | "nan" | "stall" | "explode" | "max_iter" |
"error". Every request leaves a ledger trail — `serve_request` (id, cache
outcome, status, queue wait, wall), `cache_hit` (per lookup), `coalesce`
(per batch), plus dispatch's own spans/route_decision/verdict events — and
the metrics registry exports `aiyagari_serve_queue_depth`,
`aiyagari_serve_batch_size`, and `aiyagari_serve_cache_hit_rate` gauges
beside the request counters and latency histogram.

`python -m aiyagari_tpu serve` (serve_main) runs the service standalone:
an stdlib HTTP front (`--port`: POST /solve, GET /metrics /healthz) or the
synthetic open-loop load driver (`--load`, serve/load.py).
"""

from __future__ import annotations

import dataclasses
import threading
import time
import uuid
from concurrent.futures import Future
from typing import Optional, Tuple

import numpy as np

from aiyagari_tpu.config import (
    AiyagariConfig,
    BackendConfig,
    EquilibriumConfig,
    MITShock,
    SolverConfig,
    TransitionConfig,
)

__all__ = [
    "ServeConfig",
    "SolveRequest",
    "SolveResponse",
    "SolveService",
    "serve_main",
]


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """The service's knobs. Frozen like every other config object.

    max_batch / max_wait_s are the deadline-coalescing pair: the worker
    takes the oldest queued request, then holds the batch open for AT MOST
    `max_wait_s` (or until `max_batch` compatible requests joined) before
    dispatching — max_batch=1 disables coalescing (the serial A/B the
    bench measures against). cache_bytes <= 0 disables the solution cache
    (every request solves cold)."""

    method: str = "egm"
    dtype: str = "float64"
    aggregation: str = "distribution"
    max_batch: int = 8
    max_wait_s: float = 0.05
    cache_bytes: int = 256 * 1024 * 1024
    l2_dir: Optional[str] = None       # shared cross-worker L2 solution
                                       # tier (serve/tier.py); None = L1 only
    l2_bytes: int = 1 << 30            # L2 directory byte budget
    resolution: float = 1e-3           # calibration quantization bucket
    neighbor_radius: float = 50.0      # nearest-neighbor radius, in buckets
    polish_steps: int = 8              # secant evaluations before the
                                       # warm path falls back to a cold solve
    rescue: bool = True                # the server-side retry policy
    warm_pool: bool = True             # precompile the kernel zoo at start()
    warm_families: Optional[Tuple[str, ...]] = None
    warm_na: Optional[int] = None      # also precompile sized hot programs
    warm_aot: bool = False             # restore AOT-serialized executables
                                       # instead of retracing, exporting
                                       # fresh compiles for the next start
    blend_neighbors: int = 4           # cached neighbors blended per warm
                                       # start (1 = PR 15 single-neighbor)
    surrogate: bool = True             # the ledger-trained predictor of
                                       # last resort before a cold solve
    surrogate_min_samples: int = 12
    surrogate_fit_every: int = 8
    anchor_warm: bool = True           # warm-start transition anchors from
                                       # cross-bucket neighbors + blend
                                       # their fake-news Jacobians
    pipeline: bool = True              # two-stage worker: a stager thread
                                       # admits/coalesces/pre-stages batch
                                       # k+1 while the executor drives the
                                       # device on batch k (False = the
                                       # PR 15 single-thread worker, the
                                       # serial A/B the bench measures
                                       # against)
    solver: Optional[SolverConfig] = None
    equilibrium: EquilibriumConfig = EquilibriumConfig()
    # loop="auto": coalesced transition batches and anchor-warm solves
    # lower through the fused one-program round loop wherever it is legal
    # (transition/fused.py via dispatch routing), host elsewhere.
    transition: TransitionConfig = TransitionConfig(loop="auto")

    def __post_init__(self):
        if self.method not in ("vfi", "egm"):
            raise ValueError(f"unknown method {self.method!r}")
        if self.aggregation not in ("distribution", "simulation"):
            raise ValueError(f"unknown aggregation {self.aggregation!r}")
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {self.max_batch}")
        if self.max_wait_s < 0:
            raise ValueError(
                f"max_wait_s must be >= 0, got {self.max_wait_s}")
        if self.blend_neighbors < 1:
            raise ValueError(
                f"blend_neighbors must be >= 1, got {self.blend_neighbors}")


@dataclasses.dataclass
class SolveRequest:
    """One admission-queue entry. kind selects the solve family:
    "steady_state" (GE fixed point of `config`) or "transition" (MIT-shock
    path of `config` under `shock`)."""

    config: AiyagariConfig
    kind: str = "steady_state"
    shock: Optional[MITShock] = None
    id: str = dataclasses.field(
        default_factory=lambda: uuid.uuid4().hex[:8])
    submitted: float = 0.0             # stamped by submit()

    def __post_init__(self):
        if self.kind not in ("steady_state", "transition"):
            raise ValueError(f"unknown request kind {self.kind!r}")
        if self.kind == "transition" and self.shock is None:
            raise ValueError("transition requests need a shock=MITShock(...)")


@dataclasses.dataclass
class SolveResponse:
    """One served request's result + its flight-record scalars."""

    id: str
    kind: str
    status: str                        # the verdict taxonomy (module doc)
    cache: str                         # "hit" | "warm" | "cold"
    converged: bool
    warm_source: str = "cold"          # which amortization predictor fed
                                       # the solve: "hit" | "blend" |
                                       # "neighbor" | "surrogate" |
                                       # "anchor" | "anchor_warm" | "cold"
    degraded: bool = False             # a warm guess failed to close and
                                       # the request re-solved cold
    r: Optional[float] = None
    w: Optional[float] = None
    capital: Optional[float] = None
    gap: Optional[float] = None
    r_path: Optional[np.ndarray] = None
    queue_wait_s: float = 0.0
    wall_s: float = 0.0                # service-side solve wall
    latency_s: float = 0.0             # submit -> response, queue included
    batch: int = 1
    error: Optional[str] = None
    result: object = None              # the underlying result object

    def to_json(self) -> dict:
        out = dataclasses.asdict(self)
        out.pop("result")
        if out.get("r_path") is not None:
            out["r_path"] = [float(v) for v in np.asarray(out["r_path"])]
        return out


def _compat_key(req: SolveRequest, cfg: ServeConfig):
    """Requests coalesce iff this matches: steady-state batches need
    stack_scenarios' invariants (shared shapes + technology); transition
    batches share ONE economy (one anchor, one Jacobian), so the whole
    config keys."""
    c = req.config
    if req.kind == "transition":
        return ("transition", c)
    return ("steady_state", c.grid.n_points, c.income.n_states,
            c.endogenous_labor, c.labor_grid_n, c.technology)


def _status_of(result) -> str:
    if getattr(result, "converged", False):
        if getattr(result, "rescue_attempts", None):
            return "rescued"
        return "converged"
    return getattr(result, "verdict", "") or "max_iter"


class SolveService:
    """The persistent solve service (module docstring). Usage:

        svc = SolveService(ServeConfig(max_batch=8), ledger="serve.jsonl")
        svc.start()
        fut = svc.submit(SolveRequest(AiyagariConfig()))
        resp = fut.result()
        svc.stop()

    or as a context manager. `solve(config)` is the synchronous one-liner.
    All device work happens on the single worker thread; submission is
    thread-safe from any number of clients."""

    def __init__(self, config: ServeConfig = ServeConfig(), *,
                 ledger=None):
        from aiyagari_tpu.serve.cache import SolutionCache

        self.config = config
        self._led = self._as_ledger(ledger)
        if config.l2_dir and config.cache_bytes > 0:
            from aiyagari_tpu.serve.tier import L2Tier, TieredSolutionCache

            self.cache = TieredSolutionCache(
                config.cache_bytes, resolution=config.resolution,
                neighbor_radius=config.neighbor_radius,
                l2=L2Tier(config.l2_dir, config.l2_bytes,
                          resolution=config.resolution, ledger=self._led),
                ledger=self._led)
        else:
            self.cache = SolutionCache(
                config.cache_bytes, resolution=config.resolution,
                neighbor_radius=config.neighbor_radius)
        self.surrogate = None
        if config.surrogate:
            from aiyagari_tpu.serve.surrogate import PolicySurrogate

            self.surrogate = PolicySurrogate(
                min_samples=config.surrogate_min_samples,
                fit_every=config.surrogate_fit_every)
        self._queue: list = []          # [(SolveRequest, Future)]
        self._cond = threading.Condition()
        self._running = False
        self._thread: Optional[threading.Thread] = None
        # Pipelined-worker state (config.pipeline): the depth-1 staged
        # slot between the stager (admission/coalescing/route pre-resolve)
        # and the executor (device work), both waiting on self._cond.
        self._staged: list = []         # [batch]; bounded to depth 1
        self._stager: Optional[threading.Thread] = None
        self._stage_done = False
        self.warmup_report: Optional[dict] = None
        # Readiness (ISSUE 20 satellite): False until start() finishes the
        # warm pool (or its AOT restore), so /healthz can 503 and a fleet
        # front / external load balancer never routes to a cold worker.
        self._ready = False
        self.requests_served = 0
        self.warm_sources: dict = {}    # warm_source -> served count
        self.degradations = 0

    def _as_ledger(self, ledger):
        if ledger is None:
            return None
        from aiyagari_tpu.diagnostics.ledger import RunLedger

        if isinstance(ledger, RunLedger):
            return ledger
        return RunLedger(ledger, config=[self.config.equilibrium,
                                         self.config.transition],
                         meta={"entry": "serve"})

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "SolveService":
        if self._running:
            return self
        if self._thread is not None and self._thread.is_alive():
            # A timed-out stop() left the worker draining a long solve:
            # resurrect it instead of spawning a racing second worker.
            with self._cond:
                self._running = True
                self._cond.notify_all()
            if self._thread.is_alive():
                if (self.config.pipeline
                        and (self._stager is None
                             or not self._stager.is_alive())):
                    # The stager drained and exited during the stop — the
                    # executor alone would starve; respawn the front half.
                    self._stage_done = False
                    self._stager = threading.Thread(
                        target=self._stage_loop,
                        name="aiyagari-serve-stager", daemon=True)
                    self._stager.start()
                self._set_ready(True)
                return self
            # The worker exited between the checks — fall through and
            # spawn a fresh one.
            self._thread = None
        if self.config.warm_pool:
            from aiyagari_tpu.serve.warmup import warm_pool

            self.warmup_report = warm_pool(
                self.config.warm_families, na=self.config.warm_na,
                dtype=("float64" if self.config.dtype in ("float64", "mixed")
                       else "float32"),
                aot=self.config.warm_aot,
                ledger=self._led)
        self._running = True
        self._stage_done = False
        if self.config.pipeline:
            self._stager = threading.Thread(
                target=self._stage_loop, name="aiyagari-serve-stager",
                daemon=True)
            self._stager.start()
            self._thread = threading.Thread(
                target=self._exec_loop, name="aiyagari-serve",
                daemon=True)
        else:
            self._thread = threading.Thread(
                target=self._worker, name="aiyagari-serve", daemon=True)
        self._thread.start()
        self._set_ready(True)
        return self

    def stop(self, timeout: float = 60.0) -> None:
        """Drain the queue, then stop the worker. If the worker is still
        mid-solve after `timeout`, the handle is KEPT (a later start()
        resurrects it; a later stop() re-joins) — clearing it would let
        start() spawn a second worker racing the still-draining first.
        The pipelined worker drains front-to-back: the stager stages every
        remaining admission, signals done, and the executor exits once the
        staged slot empties."""
        self._set_ready(False)
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self.surrogate is not None:
            self.surrogate.stop_background()
        deadline = time.perf_counter() + timeout
        if self._stager is not None:
            self._stager.join(max(0.0, deadline - time.perf_counter()))
            if not self._stager.is_alive():
                self._stager = None
        if self._thread is not None:
            self._thread.join(max(0.0, deadline - time.perf_counter()))
            if not self._thread.is_alive():
                self._thread = None

    @property
    def ready(self) -> bool:
        """True once start() has finished warming (pool compile or AOT
        restore) and the worker is accepting work. The HTTP front's
        /healthz readiness split keys off this."""
        return self._ready and self._running

    def _set_ready(self, up: bool) -> None:
        self._ready = bool(up)
        self._gauge("aiyagari_serve_ready", 1.0 if up else 0.0)

    def __enter__(self) -> "SolveService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- submission --------------------------------------------------------

    def submit(self, request: SolveRequest) -> Future:
        if not self._running:
            raise RuntimeError("service not started (call start())")
        request.submitted = time.perf_counter()
        fut: Future = Future()
        with self._cond:
            self._queue.append((request, fut))
            self._gauge_queue_depth()
            self._cond.notify_all()
        return fut

    def solve(self, config: AiyagariConfig, *, kind: str = "steady_state",
              shock: Optional[MITShock] = None,
              timeout: Optional[float] = None) -> SolveResponse:
        return self.submit(
            SolveRequest(config, kind=kind, shock=shock)).result(timeout)

    def calibrate(self, config: AiyagariConfig, targets: dict, *,
                  params=("beta", "sigma", "rho", "sigma_e"),
                  lanes: int = 2, steps: int = 20, lr: float = 0.08,
                  weights: Optional[dict] = None, seed: int = 0,
                  jitter: float = 0.02, polish: bool = True,
                  stage_dtypes=("float64",),
                  ss_kwargs: Optional[dict] = None,
                  timeout: float = 600.0) -> dict:
        """POST /calibrate's engine: fit the economy's deep parameters to
        `targets` by gradient (dispatch.calibrate — IFT adjoints end to
        end) and, when the fit CONVERGES, solve the fitted economy through
        the normal serve path so the solution cache stores it and the
        surrogate trains on it (the fit becomes warm-start material for
        its own neighborhood).

        Runs synchronously on the caller's thread — a calibration is a
        long-lived optimization, not a coalescible solve, so it must not
        occupy the single worker the /solve queue drains through. The
        income discretization is REPLACED with "rouwenhorst" (recorded in
        the response): the differentiable chain exists only for that
        scheme (calibrate/economy.py).

        The response never carries a parameter vector the fit cannot
        certify: a stalled fit returns status "max_iter" with the loss
        evidence and NO "theta"/"moments" keys.
        """
        import uuid as _uuid

        from aiyagari_tpu import dispatch
        from aiyagari_tpu.diagnostics import metrics

        t0 = time.perf_counter()
        rid = _uuid.uuid4().hex[:8]
        if config.income.method != "rouwenhorst":
            config = dataclasses.replace(
                config, income=dataclasses.replace(
                    config.income, method="rouwenhorst"))
        # Step 0 is unconditional: even a fit that dies on its first
        # gradient leaves a calibration trail in the flight record.
        if self._led is not None:
            self._led.event("calibration_step", step=0, id=rid,
                            loss=None, alive=int(lanes), lanes=int(lanes))
        res = dispatch.calibrate(
            config, targets, params, lanes=lanes, steps=steps, lr=lr,
            weights=weights, seed=seed, jitter=jitter, polish=polish,
            stage_dtypes=stage_dtypes, ss_kwargs=ss_kwargs,
            ledger=self._led)
        out = {
            "id": rid, "kind": "calibration", "status": res.status,
            "converged": res.status == "converged",
            "params": list(res.params),
            "targets": {k: float(v) for k, v in res.targets.items()},
            "loss": res.loss, "steps": res.steps, "lanes": res.lanes,
            "grad_evals": res.grad_evals,
            "income_method": "rouwenhorst",
        }
        if res.status == "converged":
            out["theta"] = res.theta
            out["moments"] = res.moments
            from aiyagari_tpu.dispatch import _scenario_config

            fitted = _scenario_config(config, res.theta)
            try:
                resp = self.solve(fitted, timeout=timeout)
                out["fit_solve"] = {"status": resp.status,
                                    "cache": resp.cache,
                                    "r": resp.r}
            except Exception as e:  # noqa: BLE001 — the fit already
                # succeeded; a cache-priming solve failure must not void it
                out["fit_solve"] = {"status": "error",
                                    "error": f"{type(e).__name__}: {e}"[:200]}
        out["wall_s"] = round(time.perf_counter() - t0, 6)
        metrics.counter("aiyagari_serve_requests_total", kind="calibration",
                        status=res.status, cache="cold").inc()
        return out

    @property
    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue)

    def metrics_text(self) -> str:
        from aiyagari_tpu.diagnostics import metrics

        return metrics.render_prometheus()

    # -- worker ------------------------------------------------------------

    def _admit_batch(self):
        """The admission half of a worker turn: pop the oldest request,
        serve it on the spot if it is an exact cache hit (or resolve it if
        the fast path raised), else coalesce a compatible batch to the
        max_wait_s deadline. Returns the assembled batch, or None when the
        turn consumed itself (hit/error/shutdown-with-empty-queue)."""
        with self._cond:
            while not self._queue and self._running:
                self._cond.wait(0.1)
            if not self._queue:
                if not self._running:
                    return None
                return ()               # spurious wake — take another turn
            first = self._queue.pop(0)
            self._gauge_queue_depth()
        try:
            served = self._try_hit(first)
        except Exception as e:  # noqa: BLE001 — the worker must survive
            # A failing fast path (e.g. a ledger write hitting ENOSPC)
            # must resolve the popped request and keep the worker
            # alive — an unhandled raise here would kill the single
            # worker with _running still True and hang every later
            # submit() silently.
            req, fut = first
            if not fut.done():
                fut.set_result(self._finish(req, SolveResponse(
                    id=req.id, kind=req.kind, status="error",
                    cache="cold", converged=False,
                    error=f"{type(e).__name__}: {e}"[:500]), batch=1))
            served = True
        if served:
            return ()
        batch = [first]
        # Deadline coalescing: hold the batch open for compatible
        # requests until max_wait_s from the FIRST pop, or max_batch.
        key = _compat_key(first[0], self.config)
        deadline = time.perf_counter() + self.config.max_wait_s
        while (len(batch) < self.config.max_batch
               and self.config.max_batch > 1):
            remaining = deadline - time.perf_counter()
            if remaining <= 0.0:
                break
            with self._cond:
                idx = next(
                    (i for i, (req, _) in enumerate(self._queue)
                     if _compat_key(req, self.config) == key), None)
                if idx is not None:
                    batch.append(self._queue.pop(idx))
                    self._gauge_queue_depth()
                    continue
                self._cond.wait(min(remaining, 0.005))
        return batch

    def _run_batch(self, batch) -> None:
        """Execute one assembled batch, resolving every future even when
        the solve path raises (the worker must survive)."""
        try:
            self._serve_batch(batch)
        except Exception as e:  # noqa: BLE001 — the worker must survive
            for req, fut in batch:
                if not fut.done():
                    fut.set_result(self._finish(
                        req, SolveResponse(
                            id=req.id, kind=req.kind, status="error",
                            cache="cold", converged=False,
                            error=f"{type(e).__name__}: {e}"[:500]),
                        batch=len(batch)))

    def _worker(self) -> None:
        """The single-thread worker (config.pipeline=False): admission,
        coalescing, and device execution all serialized on one thread —
        the device idles through every coalescing deadline and every
        Python batch-assembly pass."""
        while True:
            batch = self._admit_batch()
            if batch is None:
                return
            if batch:
                self._run_batch(batch)

    def _stage_loop(self) -> None:
        """Stage 1 of the pipelined worker (config.pipeline=True): admit +
        coalesce + pre-stage batch k+1 WHILE the executor drives the
        device on batch k, then hand it through the depth-1 staged slot.
        Exact cache hits are still served here immediately (host-only
        work), so the cheapest requests never wait behind a device batch.
        Steady-state serve throughput is then bounded by device time, not
        by Python batch assembly (ISSUE 18 tentpole)."""
        while True:
            batch = self._admit_batch()
            if batch is None:
                with self._cond:
                    # No admissions left and the service is stopping: the
                    # executor drains the staged slot, then exits.
                    self._stage_done = True
                    self._cond.notify_all()
                return
            if not batch:
                continue
            self._prestage(batch)
            with self._cond:
                # Depth-1 handoff: block while the previous staged batch
                # is still waiting — deeper staging would only add queue
                # latency ahead of an already-busy device.
                while self._staged:
                    self._cond.wait(0.1)
                self._staged.append(batch)
                self._cond.notify_all()

    def _exec_loop(self) -> None:
        """Stage 2 of the pipelined worker: pull assembled batches from
        the staged slot and run the device work. Frees the slot BEFORE
        executing, so the stager assembles batch k+1 during batch k's
        solve."""
        while True:
            with self._cond:
                while not self._staged and not self._stage_done:
                    self._cond.wait(0.1)
                if not self._staged:
                    return              # drained and the stager signed off
                batch = self._staged.pop(0)
                self._cond.notify_all()
            self._run_batch(batch)

    def _prestage(self, batch) -> None:
        """Host-side pre-work on the stager thread: prime the dispatch
        route memo for the batch's geometry (both the solo and the
        vmapped context), so the executor's own _resolve_routes calls
        become memo hits that just replay the recorded decisions
        (dispatch.py). Best-effort — dispatch re-resolves identically if
        any of this fails."""
        try:
            from aiyagari_tpu import dispatch

            req = batch[0][0]
            backend = BackendConfig(dtype=self.config.dtype)
            dt = dispatch._dtype_of(backend)
            na = req.config.grid.n_points
            egm = not req.config.endogenous_labor
            for batched in (False, True):
                dispatch._resolve_routes(self.config.solver, na=na,
                                         dtype=dt, egm=egm,
                                         batched=batched)
        except Exception:  # noqa: BLE001 — pre-staging is an optimization
            pass

    def _try_hit(self, item) -> bool:
        """Serve an exact cache hit IMMEDIATELY, before any coalescing
        wait: a replayed payload needs no batchmates, and holding it to
        the deadline would put the coalescing knob's max_wait_s on the
        cheapest requests' latency floor. Peeks without consuming the
        warm/miss outcome (the real lookup in _serve_batch still counts
        those)."""
        from aiyagari_tpu.diagnostics.ledger import activate

        req, fut = item
        if self.config.cache_bytes <= 0:
            return False
        key_kind = "transition" if req.kind == "transition" else "ss"
        extra = (self._transition_extra(req.shock)
                 if req.kind == "transition" else ())
        # A LOCKED no-mutation peek (cache.peek): HTTP handler threads and
        # the L2 promotion path race on the LRU, so the fast path must not
        # read _entries bare (ISSUE 20 thread-safety satellite).
        if self.cache.peek(req.config, kind=key_kind, extra=extra) is None:
            return False
        with activate(self._led):
            outcome, entry = self._lookup(req, kind=key_kind, extra=extra)
            if outcome != "hit":
                # The peek raced an eviction. In the pipelined worker this
                # CAN happen (the executor's cache.put evicts while the
                # stager peeks): the request must NOT solve here — device
                # work belongs to the executor alone — so it falls through
                # into the coalesced batch, whose _serve_steady lookup
                # handles the warm/miss outcome (one double-counted lookup
                # on this rare race, accepted). The single-thread worker
                # serves it on the spot as before — a warm steady state
                # polishes, anything else solves serially.
                if self.config.pipeline:
                    return False
                if req.kind == "steady_state" and outcome == "warm":
                    fut.set_result(self._finish(
                        req, self._steady_polish(req, entry.payload,
                                                 source="neighbor"),
                        batch=1))
                elif req.kind == "steady_state":
                    fut.set_result(self._finish(
                        req, self._steady_serial(req), batch=1))
                else:
                    self._serve_transitions([item])
                return True
            p = entry.payload
            fut.set_result(self._finish(req, SolveResponse(
                id=req.id, kind=req.kind, status=p["status"], cache="hit",
                converged=bool(p["converged"]), warm_source="hit",
                r=p.get("r"),
                w=p.get("w"), capital=p.get("capital"), gap=p.get("gap"),
                r_path=p.get("r_path"), wall_s=0.0), batch=1))
        return True

    # -- batch serving -----------------------------------------------------

    def _serve_batch(self, batch) -> None:
        from aiyagari_tpu.diagnostics.ledger import activate

        t0 = time.perf_counter()
        n = len(batch)
        self._gauge("aiyagari_serve_batch_size", n)
        waits = [t0 - req.submitted for req, _ in batch]
        if self._led is not None:
            self._led.event(
                "coalesce", batch=n, request_kind=batch[0][0].kind,
                queue_wait_max_s=round(max(waits), 6),
                queue_wait_min_s=round(min(waits), 6),
                requests=[req.id for req, _ in batch])
        with activate(self._led):
            if batch[0][0].kind == "transition":
                self._serve_transitions(batch)
            else:
                self._serve_steady(batch)
        if self._led is not None:
            self._led.span({"name": "serve_batch", "batch": n,
                            "kind": batch[0][0].kind,
                            "seconds": round(time.perf_counter() - t0, 6)})

    def _lookup(self, req: SolveRequest, *, kind: str, extra: tuple = ()):
        if self.config.cache_bytes <= 0:
            return "miss", None
        outcome, entry = self.cache.lookup(req.config, kind=kind,
                                           extra=extra)
        self._gauge("aiyagari_serve_cache_hit_rate", self.cache.hit_rate())
        if self._led is not None:
            self._led.event("cache_hit", id=req.id, request_kind=req.kind,
                            lookup=kind, outcome=outcome)
        return outcome, entry

    def _finish(self, req: SolveRequest, resp: SolveResponse, *,
                batch: int) -> SolveResponse:
        from aiyagari_tpu.diagnostics import metrics

        now = time.perf_counter()
        resp.queue_wait_s = round(
            max(0.0, (now - req.submitted) - resp.wall_s), 6)
        resp.latency_s = round(now - req.submitted, 6)
        resp.batch = batch
        self.requests_served += 1
        source = resp.warm_source
        self.warm_sources[source] = self.warm_sources.get(source, 0) + 1
        metrics.counter("aiyagari_serve_requests_total", kind=req.kind,
                        status=resp.status, cache=resp.cache).inc()
        metrics.histogram("aiyagari_serve_latency_seconds",
                          kind=req.kind).observe(resp.latency_s)
        metrics.counter("aiyagari_serve_warm_source_total",
                        source=source).inc()
        metrics.histogram("aiyagari_serve_warm_source_latency_seconds",
                          source=source).observe(resp.latency_s)
        self._gauge("aiyagari_serve_cold_fraction", self.cold_fraction())
        event = dict(id=req.id, request_kind=req.kind,
                     cache=resp.cache, status=resp.status,
                     converged=resp.converged,
                     warm_source=source, degraded=resp.degraded,
                     queue_wait_s=resp.queue_wait_s,
                     wall_s=round(resp.wall_s, 6),
                     latency_s=resp.latency_s, batch=batch)
        if req.kind == "steady_state" and resp.converged \
                and resp.r is not None:
            # The surrogate's training record: a persisted ledger can
            # replay these into PolicySurrogate.ingest_ledger after a
            # restart (serve/surrogate.py).
            from aiyagari_tpu.serve.cache import calibration_params

            event["params"] = list(calibration_params(req.config))
            event["r"] = float(resp.r)
        if self._led is not None:
            self._led.event("serve_request", **event)
        return resp

    def cold_fraction(self) -> float:
        """Fraction of served requests whose solve ran with no warm-start
        predictor at all (warm_source == "cold"; degraded requests count —
        they paid the cold solve). The number `--metric amortized` drives
        toward zero."""
        total = sum(self.warm_sources.values())
        return self.warm_sources.get("cold", 0) / total if total else 0.0

    # -- steady states -----------------------------------------------------

    def _serve_steady(self, batch) -> None:
        cold, warm = [], []
        n = len(batch)
        for req, fut in batch:
            outcome, entry = self._lookup(req, kind="ss")
            if outcome == "hit":
                p = entry.payload
                fut.set_result(self._finish(req, SolveResponse(
                    id=req.id, kind=req.kind, status=p["status"],
                    cache="hit", converged=bool(p["converged"]),
                    warm_source="hit",
                    r=p["r"], w=p["w"], capital=p["capital"],
                    gap=p["gap"], wall_s=0.0), batch=n))
            elif outcome == "warm":
                warm.append((req, fut, entry))
            else:
                # The predictor of last resort: with no cached neighbor in
                # radius, ask the surrogate for a starting guess — an
                # unfit surrogate returns None and the request stays cold
                # (pinned in tests/test_serve.py).
                guess = self._surrogate_payload(req)
                if guess is not None:
                    warm.append((req, fut, ("surrogate", guess)))
                else:
                    cold.append((req, fut))
        if len(cold) == 1:
            req, fut = cold[0]
            fut.set_result(self._finish(
                req, self._steady_serial(req), batch=n))
        elif cold:
            self._steady_sweep(cold, batch_size=n)
        for req, fut, entry in warm:
            if isinstance(entry, tuple):
                source, payload = entry
            else:
                # Blend EVERY in-radius neighbor, not just the one lookup
                # returned; fall back to that single entry's payload if
                # the neighborhood emptied in between (eviction race).
                source, payload = self._blend_payload(req, fallback=entry)
            fut.set_result(self._finish(
                req, self._steady_polish(req, payload, source=source),
                batch=n))

    def _surrogate_payload(self, req: SolveRequest):
        """A polish-shaped payload dict predicted by the surrogate, or
        None (unfit head / surrogate off / non-finite prediction)."""
        if self.surrogate is None or req.kind != "steady_state":
            return None
        from aiyagari_tpu.serve.cache import (_structural_key,
                                              calibration_params)

        pred = self.surrogate.predict(_structural_key(req.config),
                                      calibration_params(req.config))
        if pred is None:
            return None
        policy = pred.policy
        if policy is not None and self.config.method == "vfi":
            policy = None  # the basis is fitted on whatever `warm` holds;
            #                mixed-method payloads are not worth guarding
        return {"r": pred.r, "slope": pred.slope, "warm": policy}

    def _blend_payload(self, req: SolveRequest, *, fallback,
                       kind: str = "ss", extra: tuple = ()):
        """(source, payload): the distance-weighted blend of every cached
        neighbor in radius — rate, secant slope, and consumption policy
        (structural keying guarantees in-cache neighbors share the
        request's grid, so the policy blend is a weighted sum; the
        mismatched-grid interpolation lives in cache.blend_policies and is
        exercised directly by its tests). Degenerates to the single
        `fallback` entry when only one (or zero — the eviction race)
        neighbor remains."""
        from aiyagari_tpu.serve.cache import (blend_scalar, blend_weights)

        near = self.cache.neighborhood(req.config, kind=kind, extra=extra)
        near = near[:self.config.blend_neighbors]
        if len(near) <= 1:
            entry = near[0][0] if near else fallback
            return "neighbor", entry.payload
        entries = [e for e, _ in near]
        weights = blend_weights([d for _, d in near])
        payload = {
            "r": blend_scalar([float(e.payload["r"]) for e in entries],
                              weights),
            "slope": None, "warm": None,
        }
        slopes = [(e.payload.get("slope"), w)
                  for e, w in zip(entries, weights)
                  if e.payload.get("slope") is not None]
        if slopes:
            wsum = sum(w for _, w in slopes)
            payload["slope"] = sum(s * w for s, w in slopes) / wsum
        warms = [(np.asarray(e.payload["warm"]), w)
                 for e, w in zip(entries, weights)
                 if e.payload.get("warm") is not None]
        if warms and all(w0.shape == warms[0][0].shape
                         for w0, _ in warms):
            wsum = sum(w for _, w in warms)
            payload["warm"] = sum(p * (w / wsum) for p, w in warms)
        return "blend", payload

    def _solve_kwargs(self) -> dict:
        return dict(method=self.config.method, solver=self.config.solver,
                    backend=BackendConfig(dtype=self.config.dtype),
                    aggregation=self.config.aggregation, ledger=self._led)

    def _put_steady(self, config, result, status: str,
                    slope: Optional[float] = None) -> None:
        # Only converged solves are worth memoizing: replaying a failure
        # as a "hit" would pin one bad attempt as the bucket's permanent
        # answer, and its iterate is poor warm-start material.
        if self.config.cache_bytes <= 0 or not result.converged:
            return
        gap = (float(result.k_supply[-1] - result.k_demand[-1])
               if result.k_supply else float("nan"))
        warm_state = None
        sol = getattr(result, "solution", None)
        if sol is not None:
            ws = (sol.v if self.config.method == "vfi"
                  else getattr(sol, "policy_c", None))
            if ws is not None:
                warm_state = np.asarray(ws)
        if slope is None:
            slope = self._slope_from_history(result)
        self.cache.put(config, {
            "r": float(result.r), "w": float(result.w),
            "capital": float(result.capital), "gap": gap,
            "converged": bool(result.converged), "status": status,
            "slope": slope, "warm": warm_state,
        }, kind="ss")
        self._observe_surrogate(config, float(result.r), slope, warm_state)

    def _observe_surrogate(self, config, r: float, slope, warm) -> None:
        """Feed one converged solve into the surrogate's training ring
        (best-effort — training must never fail a solve)."""
        if self.surrogate is None:
            return
        try:
            from aiyagari_tpu.serve.cache import (_structural_key,
                                                  calibration_params)

            self.surrogate.observe(
                _structural_key(config), calibration_params(config), r,
                slope=slope,
                policy=(None if self.config.method == "vfi" else warm))
        except Exception:  # pragma: no cover - diagnostics are best-effort
            pass

    @staticmethod
    def _slope_from_history(result) -> Optional[float]:
        """d(gap)/dr from the solve's last two bisection evaluations —
        the secant seed a later warm polish starts from."""
        try:
            rs = result.r_history
            gaps = [s - d for s, d in zip(result.k_supply, result.k_demand)]
        except (AttributeError, TypeError):
            return None
        for i in range(len(rs) - 1, 0, -1):
            dr = rs[i] - rs[i - 1]
            if dr != 0.0 and np.isfinite(dr):
                s = (gaps[i] - gaps[i - 1]) / dr
                if np.isfinite(s) and s != 0.0:
                    return float(s)
        return None

    def _steady_serial(self, req: SolveRequest) -> SolveResponse:
        from aiyagari_tpu import dispatch
        from aiyagari_tpu.diagnostics.errors import ConvergenceError

        t0 = time.perf_counter()
        try:
            res = dispatch.solve(req.config,
                                 equilibrium=self.config.equilibrium,
                                 on_nonconvergence="ignore",
                                 rescue=(True if self.config.rescue
                                         else None),
                                 **self._solve_kwargs())
        except ConvergenceError as e:
            return SolveResponse(
                id=req.id, kind=req.kind,
                status=(e.verdict or "max_iter"), cache="cold",
                converged=False, error=str(e)[:500],
                wall_s=time.perf_counter() - t0)
        status = _status_of(res)
        self._put_steady(req.config, res, status)
        return SolveResponse(
            id=req.id, kind=req.kind, status=status, cache="cold",
            converged=bool(res.converged), r=float(res.r), w=float(res.w),
            capital=float(res.capital),
            gap=(float(res.k_supply[-1] - res.k_demand[-1])
                 if res.k_supply else None),
            wall_s=time.perf_counter() - t0, result=res)

    def _steady_sweep(self, cold, *, batch_size: int) -> None:
        """The coalesced path: one lockstep dispatch.sweep over every cold
        request — quarantine isolates a poisoned lane, rescue re-solves it
        serially (the server-side retry policy)."""
        from aiyagari_tpu import dispatch

        t0 = time.perf_counter()
        configs = [req.config for req, _ in cold]
        res = dispatch.sweep(configs[0], configs=configs,
                             equilibrium=self.config.equilibrium,
                             quarantine=True,
                             rescue=(True if self.config.rescue else None),
                             **self._solve_kwargs())
        wall = time.perf_counter() - t0
        verdicts = (res.verdicts if res.verdicts is not None
                    else ["converged" if c else "max_iter"
                          for c in res.converged])
        for i, (req, fut) in enumerate(cold):
            status = verdicts[i]
            converged = bool(res.converged[i])
            resp = SolveResponse(
                id=req.id, kind=req.kind, status=status, cache="cold",
                converged=converged, r=float(res.r[i]), w=float(res.w[i]),
                capital=float(res.capital[i]), gap=float(res.gap[i]),
                wall_s=wall, result=res)
            if converged:
                # The lane's converged household policy from the batched
                # solutions pytree: sweep-produced entries must be
                # first-class warm-start material, same as the serial
                # path's (no per-lane secant history exists — the polish
                # bootstraps its slope on first use).
                warm_state = None
                sol = getattr(res, "solutions", None)
                if sol is not None:
                    ws = (getattr(sol, "v", None)
                          if self.config.method == "vfi"
                          else getattr(sol, "policy_c", None))
                    if ws is not None:
                        warm_state = np.asarray(ws[i])
                self.cache.put(req.config, {
                    "r": float(res.r[i]), "w": float(res.w[i]),
                    "capital": float(res.capital[i]),
                    "gap": float(res.gap[i]), "converged": True,
                    "status": status, "slope": None, "warm": warm_state,
                }, kind="ss")
                self._observe_surrogate(req.config, float(res.r[i]), None,
                                        warm_state)
            fut.set_result(self._finish(req, resp, batch=batch_size))

    def _steady_polish(self, req: SolveRequest, payload: dict, *,
                       source: str = "neighbor") -> SolveResponse:
        """The warm path: a short secant polish on the market-clearing
        rate, seeded at the predictor's guess (a blended neighborhood, a
        single cached neighbor, or the surrogate — `source`) and its
        consumption policy as the household warm start — each evaluation
        is one max_iter=1 dispatch.solve at a pinned rate, so the whole
        polish is a handful of warm-started household+distribution solves
        instead of a cold bisection from the full bracket. Falls back to
        the cold path when the polish does not close within polish_steps,
        counted as a `degradation` (correctness never depends on any
        predictor — the degraded answer IS the cold solve's answer)."""
        from aiyagari_tpu import dispatch

        t0 = time.perf_counter()
        eq0 = self.config.equilibrium
        r = float(payload["r"])
        slope = payload.get("slope")
        warm_state = payload.get("warm")
        beta = float(req.config.preferences.beta)
        r_cap = 1.0 / beta - 1.0 - 1e-4
        r_floor = float(eq0.r_low)
        probe = max(4.0 * self.config.resolution, 1e-3)
        pts: list = []
        res = None
        for _ in range(max(1, self.config.polish_steps)):
            r = float(np.clip(r, r_floor, r_cap))
            # batch=1 pinned: the polish evaluation is a single-rate
            # serial pass regardless of the service's configured batched
            # GE (dispatch rejects warm_start= on the batched closure).
            eq = dataclasses.replace(eq0, r_low=r, r_high=r, r_init=r,
                                     max_iter=1, batch=1)
            res = dispatch.solve(req.config, equilibrium=eq,
                                 on_nonconvergence="ignore",
                                 warm_start=warm_state,
                                 **self._solve_kwargs())
            sol = getattr(res, "solution", None)
            if sol is not None:
                ws = (sol.v if self.config.method == "vfi"
                      else getattr(sol, "policy_c", None))
                if ws is not None:
                    warm_state = ws
            gap = float(res.k_supply[-1] - res.k_demand[-1])
            if res.converged:
                status = _status_of(res)
                self._put_steady(req.config, res, status, slope=slope)
                return SolveResponse(
                    id=req.id, kind=req.kind, status=status,
                    cache=("cold" if source == "surrogate" else "warm"),
                    warm_source=source,
                    converged=True, r=float(res.r), w=float(res.w),
                    capital=float(res.capital), gap=gap,
                    wall_s=time.perf_counter() - t0, result=res)
            pts.append((r, gap))
            if len(pts) >= 2:
                dr = pts[-1][0] - pts[-2][0]
                dg = pts[-1][1] - pts[-2][1]
                if dr != 0.0 and dg != 0.0 and np.isfinite(dg / dr):
                    slope = dg / dr
            if not (slope and np.isfinite(slope) and slope != 0.0):
                # No usable slope yet: probe a nearby rate to bootstrap
                # the secant (supply slopes up in r, demand down, so the
                # gap is increasing — step against the gap's sign).
                r = r + (probe if gap < 0.0 else -probe)
                continue
            step = gap / slope
            r = r - step
        # Polish exhausted: the guess was too far (or the slope estimate
        # bad) — DEGRADE to the true cold solve. The answer is therefore
        # bitwise the cold path's answer (pinned in tests/test_serve.py);
        # the cache label keeps the lookup outcome, warm_source reports
        # the request ended up paying a cold solve, and the degradation
        # is a counted ledger event.
        self._degrade(req, source, "steady polish exhausted")
        resp = self._steady_serial(req)
        resp.cache = "cold" if source == "surrogate" else "warm"
        resp.warm_source = "cold"
        resp.degraded = True
        resp.wall_s = time.perf_counter() - t0
        return resp

    def _degrade(self, req: SolveRequest, source: str,
                 reason: str) -> None:
        """One counted degradation: a warm-start predictor's guess did
        not close and the request re-solves cold."""
        self.degradations += 1
        try:
            from aiyagari_tpu.diagnostics import metrics

            metrics.counter("aiyagari_serve_degradations_total",
                            source=source).inc()
        except Exception:  # pragma: no cover - diagnostics are best-effort
            pass
        if self._led is not None:
            self._led.event("degradation", id=req.id, stage="serve",
                            source=source, reason=reason)

    # -- transitions -------------------------------------------------------

    def _transition_extra(self, shock: MITShock) -> tuple:
        t = self.config.transition
        return (t.T, t.method, shock.param, float(shock.size),
                float(shock.rho))

    def _serve_transitions(self, batch) -> None:
        from aiyagari_tpu import dispatch

        n = len(batch)
        todo = []
        for req, fut in batch:
            outcome, entry = self._lookup(
                req, kind="transition", extra=self._transition_extra(req.shock))
            if outcome == "hit":
                p = entry.payload
                fut.set_result(self._finish(req, SolveResponse(
                    id=req.id, kind=req.kind, status=p["status"],
                    cache="hit", converged=bool(p["converged"]),
                    warm_source="hit",
                    r_path=p["r_path"], wall_s=0.0), batch=n))
            else:
                todo.append((req, fut))
        if not todo:
            return
        # The anchor memo: the stationary equilibrium + fake-news Jacobian
        # are shock-independent, so ONE exact-calibration anchor serves
        # every queued shock of this economy (ss reuse across calibration
        # buckets would silently anchor the wrong model — exact hits only).
        cfg = todo[0][0].config
        t_cfg = self.config.transition
        anchor_outcome, anchor = self._lookup(
            todo[0][0], kind="anchor", extra=(t_cfg.T,))
        ss = jacobian = anchor_warm = None
        warm_source = "cold"
        if anchor_outcome == "hit":
            # Exact-calibration anchor: reuse the stationary equilibrium
            # and its fake-news Jacobian outright.
            ss = anchor.payload.get("ss")
            jacobian = anchor.payload.get("jacobian")
            warm_source = "anchor"
        elif self.config.anchor_warm:
            # Cross-bucket amortization (the PR 15 follow-up): warm-start
            # the anchor SOLVE from the nearest cached anchor's household
            # policy, and hand Newton a distance-weighted blend of the
            # neighbors' fake-news Jacobians — BKM (2018) near-linearity
            # is what makes a nearby economy's Jacobian a good Newton
            # matrix, and Newton's fixed point does not depend on the
            # matrix used, so a converged path is exactly as correct as a
            # cold one. Non-convergence degrades to a cold solve below.
            anchor_warm, jacobian = self._anchor_warm_material(cfg, t_cfg)
            if anchor_warm is not None or jacobian is not None:
                warm_source = "anchor_warm"
        cache_label = "warm" if warm_source != "cold" else "cold"
        t0 = time.perf_counter()
        # equilibrium= is deliberately NOT threaded through: with eq=None
        # the anchor solve applies transition/mit.stationary_anchor's own
        # TIGHTER defaults (max_iter=48, tol=1e-8) — anchor error floors
        # the whole path's flatness, so the service's (possibly loosened)
        # steady-state serving tolerance must not degrade it.
        kwargs = dict(transition=t_cfg,
                      backend=BackendConfig(dtype=self.config.dtype),
                      solver=self.config.solver, ledger=self._led,
                      rescue=(True if self.config.rescue else None))
        if ss is not None:
            kwargs.update(ss=ss, jacobian=jacobian)
        elif warm_source == "anchor_warm":
            if anchor_warm is not None:
                kwargs.update(anchor_warm_start=anchor_warm)
            if jacobian is not None:
                kwargs.update(jacobian=jacobian)
        try:
            if len(todo) == 1:
                res = dispatch.solve_transition(
                    cfg, todo[0][0].shock, on_nonconvergence="ignore",
                    **kwargs)
                walls = time.perf_counter() - t0
                responses = [self._transition_response(
                    todo[0][0], res, res.r_path, _status_of(res),
                    bool(res.converged), cache_label, walls,
                    warm_source)]
                new_ss, new_j = res.ss, res.jacobian
            else:
                res = dispatch.sweep_transitions(
                    cfg, [req.shock for req, _ in todo],
                    quarantine=True, **kwargs)
                walls = time.perf_counter() - t0
                verdicts = (res.verdicts if res.verdicts is not None
                            else ["converged" if c else "max_iter"
                                  for c in res.converged])
                responses = [
                    self._transition_response(
                        req, res, np.asarray(res.r_paths[i]), verdicts[i],
                        bool(res.converged[i]), cache_label, walls,
                        warm_source)
                    for i, (req, _) in enumerate(todo)]
                new_ss, new_j = res.ss, res.jacobian
        except Exception as e:  # noqa: BLE001 — per-request error responses
            from aiyagari_tpu.diagnostics.errors import ConvergenceError

            status = ((e.verdict or "max_iter")
                      if isinstance(e, ConvergenceError) else "error")
            if warm_source == "cold":
                for req, fut in todo:
                    fut.set_result(self._finish(req, SolveResponse(
                        id=req.id, kind=req.kind, status=status,
                        cache=cache_label, converged=False,
                        error=f"{type(e).__name__}: {e}"[:500],
                        wall_s=time.perf_counter() - t0), batch=n))
                return
            # A raising warm path (e.g. rescue-ladder exhaustion seeded
            # with warm material) is still just a bad guess: hand every
            # request to the degradation loop below, which re-solves cold.
            walls = time.perf_counter() - t0
            responses = [SolveResponse(
                id=req.id, kind=req.kind, status=status,
                cache=cache_label, converged=False,
                warm_source=warm_source,
                error=f"{type(e).__name__}: {e}"[:500], wall_s=walls)
                for req, _ in todo]
            new_ss = new_j = None
        if warm_source != "cold":
            # The correctness band: a warm-started/interpolated-Jacobian
            # path that did NOT converge degrades to a full cold solve —
            # its final answer is the cold path's answer, bitwise (pinned
            # in tests/test_serve.py). Converged paths need no check:
            # Newton's fixed point is Jacobian-independent.
            cold_kwargs = dict(
                transition=t_cfg,
                backend=BackendConfig(dtype=self.config.dtype),
                solver=self.config.solver, ledger=self._led,
                rescue=(True if self.config.rescue else None))
            for i, ((req, _), resp) in enumerate(zip(todo, responses)):
                if resp.converged:
                    continue
                self._degrade(req, warm_source,
                              "transition warm path did not converge")
                t1 = time.perf_counter()
                try:
                    cold = dispatch.solve_transition(
                        cfg, req.shock, on_nonconvergence="ignore",
                        **cold_kwargs)
                except Exception as e:  # noqa: BLE001 — per-request
                    from aiyagari_tpu.diagnostics.errors import (
                        ConvergenceError)

                    resp.error = f"{type(e).__name__}: {e}"[:500]
                    resp.status = ((e.verdict or "max_iter")
                                   if isinstance(e, ConvergenceError)
                                   else "error")
                    resp.degraded = True
                    resp.wall_s += time.perf_counter() - t1
                    continue
                responses[i] = self._transition_response(
                    req, cold, cold.r_path, _status_of(cold),
                    bool(cold.converged), cache_label, resp.wall_s +
                    (time.perf_counter() - t1), "cold")
                responses[i].degraded = True
                if cold.ss is not None:
                    new_ss, new_j = cold.ss, cold.jacobian
        if self.config.cache_bytes > 0 and new_ss is not None:
            self.cache.put(cfg, {"ss": new_ss, "jacobian": new_j},
                           kind="anchor", extra=(t_cfg.T,))
        for (req, fut), resp in zip(todo, responses):
            if self.config.cache_bytes > 0 and resp.converged:
                self.cache.put(req.config, {
                    "r_path": np.asarray(resp.r_path),
                    "status": resp.status, "converged": True,
                }, kind="transition",
                    extra=self._transition_extra(req.shock))
            fut.set_result(self._finish(req, resp, batch=n))

    def _anchor_warm_material(self, cfg, t_cfg):
        """(anchor_warm_start, blended_jacobian) from the cached anchors
        within neighbor_radius of this economy — (None, None) when the
        neighborhood is empty. The warm start is the NEAREST anchor's
        household consumption policy (the anchor solve re-runs, warm);
        the Jacobian is the distance-weighted interpolation over every
        in-radius anchor that stored one (transition/jacobian.py)."""
        from aiyagari_tpu.serve.cache import blend_weights

        near = self.cache.neighborhood(cfg, kind="anchor",
                                       extra=(t_cfg.T,))
        near = near[:self.config.blend_neighbors]
        if not near:
            return None, None
        warm = None
        sol = getattr(near[0][0].payload.get("ss"), "solution", None)
        if sol is not None:
            pol = getattr(sol, "policy_c", None)
            if pol is not None:
                warm = np.asarray(pol)
        jacobian = None
        with_j = [(e.payload.get("jacobian"), d) for e, d in near
                  if e.payload.get("jacobian") is not None]
        if with_j:
            from aiyagari_tpu.transition.jacobian import (
                interpolate_jacobians)

            jacobian = interpolate_jacobians(
                [j for j, _ in with_j],
                blend_weights([d for _, d in with_j]))
        return warm, jacobian

    def _transition_response(self, req, res, r_path, status, converged,
                             cache, wall,
                             warm_source: str = "cold") -> SolveResponse:
        return SolveResponse(
            id=req.id, kind=req.kind, status=status, cache=cache,
            converged=converged, warm_source=warm_source,
            r_path=np.asarray(r_path), wall_s=wall, result=res)

    # -- metrics helpers ---------------------------------------------------

    def _gauge_queue_depth(self) -> None:
        self._gauge("aiyagari_serve_queue_depth", len(self._queue))

    @staticmethod
    def _gauge(name: str, value) -> None:
        try:
            from aiyagari_tpu.diagnostics import metrics

            metrics.gauge(name).set(float(value))
        except Exception:  # pragma: no cover - diagnostics are best-effort
            pass


# -- the CLI front ---------------------------------------------------------


def _http_server(service: SolveService, base: AiyagariConfig, port: int, *,
                 auth_token: Optional[str] = None,
                 max_body_bytes: int = 1 << 20,
                 max_inflight: int = 8,
                 max_queue_depth: int = 64):
    """Minimal stdlib HTTP front: POST /solve (JSON body with optional
    "params" overrides over the base config, optional "shock"), POST
    /calibrate (same "params" overrides plus required "targets"; see
    SolveService.calibrate and USAGE.md "Gradient-based calibration"),
    GET /metrics (Prometheus text), GET /healthz. No dependencies — the
    container constraint — and the service's own queue provides the
    backpressure. Hardened (ISSUE 16): every POST requires
    `Authorization: Bearer <auth_token>` when a token is configured
    (--auth-token / AIYAGARI_SERVE_TOKEN; 401), rejects bodies over
    `max_body_bytes` (413, body unread), and sheds load with 429 when one
    client holds `max_inflight` concurrent solves or the admission queue
    is `max_queue_depth` deep. /healthz and /metrics stay open — they are
    the scrape surface, and serve no solve."""
    import hmac
    import json
    import threading as _threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from aiyagari_tpu.dispatch import _SWEEP_PARAMS, _scenario_config

    inflight: dict = {}
    inflight_lock = _threading.Lock()

    class Handler(BaseHTTPRequestHandler):
        # HTTP/1.1 => persistent connections: a load driver (or any real
        # client) reuses one TCP connection across requests instead of
        # paying connect/teardown per solve (ISSUE 18 satellite — the SLO
        # knee should measure solve throughput, not TCP setup). Safe only
        # because _send always sets Content-Length.
        protocol_version = "HTTP/1.1"

        def log_message(self, *args):  # quiet: the ledger is the record
            pass

        def _send(self, code: int, body: str,
                  ctype: str = "application/json", headers=()):
            data = body.encode()
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            for k, v in headers:
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def _reject(self, code: int, error: str, headers=()) -> None:
            self._count_rejection(code)
            self._send(code, json.dumps({"error": error}), headers=headers)

        @staticmethod
        def _count_rejection(code: int) -> None:
            try:
                from aiyagari_tpu.diagnostics import metrics

                metrics.counter("aiyagari_serve_http_rejections_total",
                                code=str(code)).inc()
            except Exception:  # pragma: no cover - best-effort
                pass

        def _authorized(self) -> bool:
            if auth_token is None:
                return True
            header = self.headers.get("Authorization", "")
            if not header.startswith("Bearer "):
                return False
            return hmac.compare_digest(header[len("Bearer "):].strip(),
                                       auth_token)

        def do_GET(self):
            if self.path == "/metrics":
                self._send(200, service.metrics_text(),
                           "text/plain; version=0.0.4")
            elif self.path == "/healthz":
                # Readiness split (ISSUE 20): 503 "warming" until the warm
                # pool (or AOT restore) completes, so a fleet front or an
                # external load balancer never routes to a cold worker.
                if not service.ready:
                    self._send(503, json.dumps({
                        "ok": False, "state": "warming"}),
                        headers=(("Retry-After", "1"),))
                    return
                self._send(200, json.dumps({
                    "ok": True, "state": "ready",
                    "queue_depth": service.queue_depth,
                    "requests_served": service.requests_served,
                    "cold_fraction": round(service.cold_fraction(), 4),
                    "cache": service.cache.stats()}))
            else:
                self._send(404, json.dumps({"error": "not found"}))

        def do_POST(self):
            if self.path not in ("/solve", "/calibrate"):
                self._send(404, json.dumps({"error": "not found"}))
                return
            if not self._authorized():
                self._reject(401, "unauthorized",
                             headers=(("WWW-Authenticate", "Bearer"),))
                return
            length = int(self.headers.get("Content-Length", "0"))
            if length > max_body_bytes:
                # The body stays unread: the limit is the defense, not a
                # post-hoc parse failure.
                self._reject(
                    413, f"body {length} bytes > limit {max_body_bytes}")
                return
            client = self.client_address[0]
            with inflight_lock:
                over = (inflight.get(client, 0) >= max_inflight
                        or service.queue_depth >= max_queue_depth)
                if not over:
                    inflight[client] = inflight.get(client, 0) + 1
            if over:
                self._reject(429, "too many concurrent requests",
                             headers=(("Retry-After", "1"),))
                return
            try:
                body = json.loads(self.rfile.read(length) or b"{}")
                params = body.get("params") or {}
                unknown = set(params) - set(_SWEEP_PARAMS)
                if unknown:
                    raise ValueError(f"unknown params {sorted(unknown)}")
                cfg = _scenario_config(base, params)
                if self.path == "/calibrate":
                    targets = body.get("targets")
                    if not isinstance(targets, dict) or not targets:
                        raise ValueError(
                            'calibrate needs "targets": {moment: value} '
                            "(moments: gini, k_y, mpc, top10_share)")
                    fit_kw = dict(body.get("fit") or {})
                    allowed = {"lanes", "steps", "lr", "seed", "jitter",
                               "polish"}
                    bad = set(fit_kw) - allowed
                    if bad:
                        raise ValueError(
                            f"unknown fit option(s) {sorted(bad)}; "
                            f"supported: {sorted(allowed)}")
                    if not service.ready:
                        # Rejections and validation answer even while
                        # warming; ADMISSION does not — a 503 with
                        # Retry-After sends the fleet front (or any load
                        # balancer) to a warm worker until the warm pool
                        # / AOT restore completes.
                        self._reject(503, "warming",
                                     headers=(("Retry-After", "1"),))
                        return
                    out = service.calibrate(
                        cfg, targets,
                        params=tuple(body.get("calibrate")
                                     or ("beta", "sigma", "rho", "sigma_e")),
                        weights=body.get("weights"),
                        ss_kwargs=body.get("ss"),
                        timeout=float(body.get("timeout", 600)),
                        **fit_kw)
                    self._send(200, json.dumps(out))
                    return
                shock = None
                kind = body.get("kind", "steady_state")
                if body.get("shock"):
                    shock = MITShock(**body["shock"])
                    kind = "transition"
                if not service.ready:
                    self._reject(503, "warming",
                                 headers=(("Retry-After", "1"),))
                    return
                resp = service.solve(cfg, kind=kind, shock=shock,
                                     timeout=float(body.get("timeout", 600)))
                self._send(200, json.dumps(resp.to_json()))
            except Exception as e:  # noqa: BLE001 — HTTP boundary
                self._send(400, json.dumps(
                    {"error": f"{type(e).__name__}: {e}"[:500]}))
            finally:
                with inflight_lock:
                    inflight[client] = max(0, inflight.get(client, 1) - 1)

    return ThreadingHTTPServer(("127.0.0.1", port), Handler)


def serve_main(argv) -> int:
    """`python -m aiyagari_tpu serve`: run the service with the HTTP front
    (--port) or drive it with the synthetic open-loop load (--load N)."""
    import argparse
    import json

    from aiyagari_tpu.config import GridSpecConfig

    ap = argparse.ArgumentParser(prog="aiyagari_tpu serve")
    ap.add_argument("--grid", type=int, default=400,
                    help="asset grid points of the base economy")
    ap.add_argument("--method", choices=["vfi", "egm"], default="egm")
    ap.add_argument("--dtype", choices=["float32", "float64", "mixed"],
                    default="float64")
    ap.add_argument("--max-batch", type=int, default=8,
                    help="coalescing batch cap (1 = serial)")
    ap.add_argument("--max-wait", type=float, default=0.05,
                    help="coalescing deadline, seconds")
    ap.add_argument("--cache-mb", type=float, default=256.0,
                    help="solution-cache byte budget (0 disables)")
    ap.add_argument("--l2-dir", default=None,
                    help="shared cross-worker L2 solution-tier directory "
                         "(serve/tier.py); unset = in-process L1 only")
    ap.add_argument("--l2-mb", type=float, default=1024.0,
                    help="L2 tier directory byte budget")
    ap.add_argument("--aot", action="store_true",
                    help="restore AOT-serialized warm-pool executables "
                         "(and export fresh compiles for the next start)")
    ap.add_argument("--warm-families", default=None,
                    help="comma-separated registry families to warm "
                         "('' = only the --grid-sized hot programs; "
                         "default: the whole catalogue)")
    ap.add_argument("--resolution", type=float, default=1e-3,
                    help="calibration quantization bucket width")
    ap.add_argument("--tol", type=float, default=None,
                    help="GE market-clearing tolerance (default: the "
                         "library's EquilibriumConfig.tol; coarse grids "
                         "need a looser tol to converge — see "
                         "BENCHMARKS.md round 14)")
    ap.add_argument("--max-iter", type=int, default=None,
                    help="GE bisection round cap (default: "
                         "EquilibriumConfig.max_iter)")
    ap.add_argument("--no-warm", action="store_true",
                    help="skip the warm-pool precompile at startup")
    ap.add_argument("--no-surrogate", action="store_true",
                    help="disable the policy-surface surrogate predictor")
    ap.add_argument("--no-pipeline", action="store_true",
                    help="single-thread worker (disable the stager/"
                         "executor pipeline)")
    ap.add_argument("--auth-token", default=None,
                    help="require 'Authorization: Bearer <token>' on "
                         "POST /solve (default: $AIYAGARI_SERVE_TOKEN; "
                         "unset = open)")
    ap.add_argument("--max-body-kb", type=float, default=1024.0,
                    help="reject /solve bodies larger than this (413)")
    ap.add_argument("--max-inflight", type=int, default=8,
                    help="per-client concurrent /solve cap (429)")
    ap.add_argument("--max-queue", type=int, default=64,
                    help="admission queue depth before shedding load (429)")
    ap.add_argument("--ledger", default=None,
                    help="append the serving flight record to this JSONL "
                         "ledger (render: python -m aiyagari_tpu report)")
    ap.add_argument("--run-id", default=None,
                    help="fleet: join this run id (the front passes one id "
                         "to every worker so merge_ledgers sees ONE run)")
    ap.add_argument("--worker-index", type=int, default=None,
                    help="fleet: this worker's index — selects the "
                         "host-stamped ledger shard ledger.p<k>.jsonl")
    ap.add_argument("--worker-count", type=int, default=None,
                    help="fleet: total workers under the shared run id")
    ap.add_argument("--port", type=int, default=None,
                    help="HTTP front port (POST /solve, GET /metrics, "
                         "GET /healthz)")
    ap.add_argument("--load", type=int, default=None, metavar="N",
                    help="instead of serving HTTP, drive N synthetic "
                         "open-loop requests and print the latency report")
    ap.add_argument("--rps", type=float, default=None,
                    help="open-loop arrival rate for --load (default: "
                         "as fast as the queue accepts)")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    if args.port is None and args.load is None:
        ap.error("pick a mode: --port (HTTP front) or --load N "
                 "(synthetic load)")

    import jax

    if args.dtype in ("float64", "mixed"):
        jax.config.update("jax_enable_x64", True)
    # Fleet workers are fresh processes: the persistent XLA compile cache
    # (io_utils/compile_cache.py) turns their warm-pool compiles into disk
    # hits populated by earlier runs on this host.
    from aiyagari_tpu.io_utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()
    base = AiyagariConfig(grid=GridSpecConfig(n_points=args.grid))
    eq = EquilibriumConfig()
    if args.tol is not None or args.max_iter is not None:
        eq = dataclasses.replace(
            eq, **{k: v for k, v in (("tol", args.tol),
                                     ("max_iter", args.max_iter))
                   if v is not None})
    cfg = ServeConfig(
        method=args.method, dtype=args.dtype, max_batch=args.max_batch,
        max_wait_s=args.max_wait,
        cache_bytes=int(args.cache_mb * 1024 * 1024),
        l2_dir=args.l2_dir, l2_bytes=int(args.l2_mb * 1024 * 1024),
        resolution=args.resolution, warm_pool=not args.no_warm,
        warm_aot=args.aot,
        warm_families=(None if args.warm_families is None
                       else tuple(f for f in args.warm_families.split(",")
                                  if f)),
        surrogate=not args.no_surrogate,
        pipeline=not args.no_pipeline,
        warm_na=args.grid, equilibrium=eq)
    ledger = args.ledger
    if args.ledger and (args.run_id is not None
                        or args.worker_index is not None):
        # Fleet worker: join the front's ONE run id, write this worker's
        # host-stamped shard (ledger.p<k>.jsonl) — merge_ledgers then
        # reads the whole fleet as a single flight record (PR 14).
        from aiyagari_tpu.diagnostics.ledger import RunLedger

        ledger = RunLedger(
            args.ledger, run_id=args.run_id,
            config=[eq, cfg.transition],
            process_index=args.worker_index,
            process_count=args.worker_count,
            meta={"entry": "serve", "port": args.port})
    service = SolveService(cfg, ledger=ledger)
    if args.port is None:
        service.start()
    else:
        # HTTP mode: bring the socket up FIRST and warm in the background,
        # so /healthz reports 503 "warming" (the readiness split the fleet
        # front polls) instead of connection-refused during the pool
        # compile / AOT restore.
        def _start_and_announce():
            t0 = time.perf_counter()
            try:
                service.start()
            except Exception as e:  # noqa: BLE001 — surfaced via healthz
                print(f"serve: start failed: {type(e).__name__}: {e}")
                return
            if service._led is not None:
                rep = service.warmup_report or {}
                service._led.event(
                    "fleet_worker", port=args.port,
                    worker=args.worker_index, state="ready",
                    warm_seconds=round(time.perf_counter() - t0, 4),
                    warm_programs=rep.get("compiled", 0),
                    warm_restored=rep.get("restored", 0))

        threading.Thread(target=_start_and_announce,
                         name="aiyagari-serve-warm", daemon=True).start()
    if service.surrogate is not None and args.port is not None:
        # Long-lived server: refit the surrogate on a background cadence
        # in addition to the inline fit_every cadence.
        service.surrogate.start_background()
    try:
        if args.load is not None:
            from aiyagari_tpu.serve.load import synthetic_requests, run_load

            reqs = synthetic_requests(base, args.load, seed=args.seed,
                                      resolution=args.resolution)
            report = run_load(service, reqs, rps=args.rps)
            report["cache"] = service.cache.stats()
            if service.warmup_report is not None:
                report["warm_pool"] = {
                    "compiled": service.warmup_report["compiled"],
                    "wall_seconds": service.warmup_report["wall_seconds"]}
            print(json.dumps(report, indent=2))
            return 0
        import os

        token = args.auth_token or os.environ.get("AIYAGARI_SERVE_TOKEN")
        httpd = _http_server(
            service, base, args.port, auth_token=token,
            max_body_bytes=int(args.max_body_kb * 1024),
            max_inflight=args.max_inflight,
            max_queue_depth=args.max_queue)
        print(f"serving on http://127.0.0.1:{args.port}  "
              f"(POST /solve{' [auth]' if token else ''}, GET /metrics, "
              f"GET /healthz)")
        try:
            httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            httpd.server_close()
        return 0
    finally:
        service.stop()
