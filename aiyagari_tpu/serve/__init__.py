"""Persistent solve service (ISSUE 15): the serving layer that cashes in
the operational substrate of PRs 6-13 for measured requests/sec.

Three load-bearing pieces, each usable standalone:

  * `serve.warmup` — the warm pool: pre-lower/compile the kernel zoo
    (analysis/registry.py's ProgramSpec catalogue) through the persistent
    compile cache at startup, so a fresh server's first request is a cache
    hit instead of a cold XLA compile. CLI: `python -m aiyagari_tpu warmup`.
  * `serve.cache` — the solution cache: steady states and sequence-space
    anchors (ss + fake-news Jacobian) memoized under a QUANTIZED
    calibration fingerprint with an LRU byte budget; bucket collisions and
    nearest-neighbor misses return warm-start material, never stale
    results.
  * `serve.service` — the solve service itself: an admission queue that
    coalesces compatible requests into lockstep `dispatch.sweep()` /
    `sweep_transitions` batches on a deadline, warm-starts cache
    neighbors with a short secant ("Newton") polish, runs the rescue
    ladder as the server-side retry policy, and reports through the
    existing ledger/metrics surface. CLI: `python -m aiyagari_tpu serve`.

`serve.load` is the synthetic load driver `bench.py --metric serve`
measures requests/sec with (open-loop, closed-loop, and the offered-rps
ramp that finds the SLO knee).

Amortized solving (ISSUE 16) escalates warm-start predictors per request:
exact hit → multi-neighbor blend (`serve.cache.blend_*`) → the
ledger-trained policy-surface surrogate (`serve.surrogate`) → cold solve,
with every degraded guess re-solved cold (never a wrong answer) and the
cold-solve fraction exported as `aiyagari_serve_cold_fraction`.
"""

from aiyagari_tpu.serve.cache import (
    SolutionCache,
    blend_policies,
    blend_scalar,
    blend_weights,
    calibration_key,
    calibration_params,
    payload_nbytes,
)
from aiyagari_tpu.serve.service import (
    ServeConfig,
    SolveRequest,
    SolveResponse,
    SolveService,
)
from aiyagari_tpu.serve.surrogate import PolicySurrogate
from aiyagari_tpu.serve.warmup import warm_pool

__all__ = [
    "PolicySurrogate",
    "ServeConfig",
    "SolveRequest",
    "SolveResponse",
    "SolveService",
    "SolutionCache",
    "blend_policies",
    "blend_scalar",
    "blend_weights",
    "calibration_key",
    "calibration_params",
    "payload_nbytes",
    "warm_pool",
]
