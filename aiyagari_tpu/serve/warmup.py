"""Warm pool: pre-lower/compile the kernel zoo into the persistent compile
cache, so a fresh server process (or a fresh bench session) pays first-
request latency as a cache HIT instead of a cold XLA compile.

The warm-up list is `analysis/registry.py`'s ProgramSpec catalogue — the
same traceable entry points the jaxpr auditor and the attribution table
already walk, so "what the audit certifies" and "what the server
precompiles" are one list by construction. Each program is lowered and
compiled exactly as `analysis/attribution.attribute_program` does
(`jax.jit(fn).lower(*args).compile()`), under
`io_utils/compile_cache.enable_compilation_cache`, which persists the
executable: the FIRST warm-up on a host does the compiles, every later
process loads them.

The registry traces at fixed small shapes; a server knows its real grid
sizes, so `warm_pool(na=...)` additionally compiles the size-sensitive hot
programs (the EGM sweep and the stationary-distribution family) at the
CONFIGURED grid size and dtype — the shapes its solve requests will
actually hit.

CLI (the satellite): `python -m aiyagari_tpu warmup [--na N --dtype D
--families f1,f2 --json]` runs the same function standalone and reports
per-program compile walls; `SolveService.start()` calls it at boot.
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

__all__ = ["warm_pool", "warmup_main"]


def _sized_builders(na: int, dtype_name: str):
    """(name, build) pairs for the size-sensitive hot programs at the
    caller's OWN grid size — the registry's shapes cover the audit, these
    cover the serve traffic. Mirrors the registry builders (same solver
    entry points, same closure discipline) with na/dtype parameterized."""
    import jax
    import jax.numpy as jnp

    dt = jnp.dtype(dtype_name)
    nz = 7   # the reference income-state count (IncomeProcess.n_states)

    def sds(shape):
        return jax.ShapeDtypeStruct(shape, dt)

    def build_egm():
        from aiyagari_tpu.solvers.egm import solve_aiyagari_egm

        def fn(C, a_grid, s, P, r, w, amin, sigma, beta):
            return solve_aiyagari_egm(C, a_grid, s, P, r, w, amin,
                                      sigma=sigma, beta=beta, tol=1e-6,
                                      max_iter=50)

        return fn, (sds((nz, na)), sds((na,)), sds((nz,)), sds((nz, nz)),
                    sds(()), sds(()), sds(()), sds(()), sds(()))

    def build_stationary():
        from aiyagari_tpu.sim.distribution import stationary_distribution

        def fn(policy_k, a_grid, P):
            return stationary_distribution(policy_k, a_grid, P, tol=1e-8,
                                           max_iter=200)

        return fn, (sds((nz, na)), sds((na,)), sds((nz, nz)))

    def build_step(backend):
        from aiyagari_tpu.sim.distribution import distribution_step

        def fn(mu, idx, w_lo, P):
            return distribution_step(mu, idx, w_lo, P, backend=backend)

        return fn, (sds((nz, na)),
                    jax.ShapeDtypeStruct((nz, na), jnp.int32),
                    sds((nz, na)), sds((nz, nz)))

    return [
        (f"egm/sweep@na{na}", build_egm),
        (f"distribution/stationary@na{na}", build_stationary),
        (f"distribution/step_transpose@na{na}",
         lambda: build_step("transpose")),
        (f"distribution/step_scatter@na{na}",
         lambda: build_step("scatter")),
    ]


def _aot_restore(name: str, aot_dir) -> bool:
    """Load one AOT-serialized executable WITHOUT touching the program's
    own builder or the compiler: the artifact is the pickled
    `jax.experimental.serialize_executable` triple (unloaded executable
    bytes + in/out trees), so restore is a file read + a backend LOAD —
    no solver import, no retrace, no XLA compile (exactly what layer 2 of
    the ISSUE 20 tentpole removes from restart). False = no artifact, or
    a deserialize/load failure (stale lowering, different topology) —
    fall back to fresh."""
    import pickle

    from jax.experimental import serialize_executable as se

    from aiyagari_tpu.io_utils.compile_cache import load_serialized

    data = load_serialized(name, aot_dir)
    if data is None:
        return False
    try:
        payload, in_tree, out_tree = pickle.loads(data)
        se.deserialize_and_load(payload, in_tree, out_tree)
        return True
    except Exception:  # noqa: BLE001 — a stale artifact must never
        return False   # fail a warm pool; the fresh path still works


def _aot_export(name: str, fn, args, aot_dir) -> str:
    """Serialize one freshly-compiled program's EXECUTABLE for the next
    start. Returns the aot status string: "exported", or "unexportable"
    (programs whose executables capture non-serializable state — host
    callbacks, exotic closures — recorded, not fatal)."""
    import pickle

    import jax
    from jax.experimental import serialize_executable as se

    from aiyagari_tpu.io_utils.compile_cache import save_serialized

    def flat_fn(*a):
        # Serialize the FLATTENED-output program: result dataclasses
        # (EGMSolution, ...) are not registered for pytree serialization,
        # and the warm pool never consumes outputs — flattening is host
        # metadata only, the compiled executable is the same computation.
        return jax.tree_util.tree_leaves(fn(*a))

    try:
        compiled = jax.jit(flat_fn).lower(*args).compile()
        data = pickle.dumps(se.serialize(compiled),
                            protocol=pickle.HIGHEST_PROTOCOL)
    except Exception:  # noqa: BLE001 — export is an optimization (e.g.
        # host_callback-bearing programs cannot serialize); recorded,
        # never fatal — the fresh-compile path already ran.
        return "unexportable"
    if save_serialized(name, data, aot_dir) is None:
        return "unexportable"
    return "exported"


def warm_pool(families: Optional[Tuple[str, ...]] = None, *,
              na: Optional[int] = None, dtype: str = "float64",
              cache_dir: Optional[str] = None, aot: bool = False,
              aot_dir: Optional[str] = None, ledger=None) -> dict:
    """Precompile the registry catalogue (plus, with `na`, the sized hot
    programs) into the persistent compile cache. Returns the warm-up
    report: per-program compile walls, skipped programs (environment-
    dependent builders raise ProgramUnavailable, exactly like the audit),
    and the cache directory used.

    With `aot=True` (ISSUE 20 tentpole, layer 2), each program first tries
    an AOT RESTORE — deserialize the `jax.export` artifact persisted
    beside the compile cache and compile it directly, skipping the trace
    entirely — and on a restore miss compiles fresh and exports the
    serialized executable for the next start. Per-program `warmup` ledger
    events carry the restore-vs-compile wall and the aot status
    ("restored" | "exported" | "unexportable" | "off").

    Every compiled program emits a `warmup` ledger event (active ledger
    or the explicit `ledger` argument) and an
    `aiyagari_warmup_compile_seconds{program=}` gauge, so a server's boot
    is a readable flight record, not a silent pause."""
    import jax

    from aiyagari_tpu.analysis.registry import (
        ProgramUnavailable,
        registered_programs,
    )
    from aiyagari_tpu.diagnostics import ledger as ledger_mod, metrics
    from aiyagari_tpu.io_utils.compile_cache import enable_compilation_cache

    t0 = time.perf_counter()
    cache_used = enable_compilation_cache(cache_dir)

    def emit(kind, **fields):
        if ledger is not None:
            ledger.event(kind, **fields)
        else:
            ledger_mod.emit(kind, **fields)

    jobs = [(spec.name, spec.build_off)
            for spec in registered_programs(families)]
    if na is not None:
        if na < 4:
            raise ValueError(f"warm_pool na must be >= 4, got {na}")
        jobs.extend(_sized_builders(int(na), dtype))

    programs: dict = {}
    skipped: list = []
    restored_count = 0
    for name, build in jobs:
        p0 = time.perf_counter()
        if aot and _aot_restore(name, aot_dir):
            wall = time.perf_counter() - p0
            programs[name] = {"compile_seconds": round(wall, 4),
                              "restored": True, "aot": "restored"}
            restored_count += 1
            metrics.gauge("aiyagari_warmup_compile_seconds",
                          program=name).set(wall)
            metrics.counter("aiyagari_warmup_programs_total").inc()
            metrics.counter("aiyagari_warmup_aot_restored_total").inc()
            emit("warmup", program=name, compile_seconds=round(wall, 4),
                 restored=True, aot="restored")
            continue
        try:
            fn, args = build()
            jax.jit(fn).lower(*args).compile()
        except ProgramUnavailable as e:
            skipped.append((name, str(e)))
            emit("warmup", program=name, skipped=str(e)[:200])
            continue
        # compile_seconds is what a cold boot pays (build+trace+compile);
        # the export is the one-time extra the EXPORTING boot pays for
        # the next start's restore, timed separately.
        wall = time.perf_counter() - p0
        e0 = time.perf_counter()
        aot_status = _aot_export(name, fn, args, aot_dir) if aot else "off"
        programs[name] = {"compile_seconds": round(wall, 4),
                          "restored": False, "aot": aot_status,
                          "export_seconds": (
                              round(time.perf_counter() - e0, 4)
                              if aot else None)}
        metrics.gauge("aiyagari_warmup_compile_seconds",
                      program=name).set(wall)
        metrics.counter("aiyagari_warmup_programs_total").inc()
        emit("warmup", program=name, compile_seconds=round(wall, 4),
             restored=False, aot=aot_status)
    return {
        "programs": programs,
        "skipped": skipped,
        "compiled": len(programs),
        "restored": restored_count,
        "cache_dir": cache_used,
        "wall_seconds": round(time.perf_counter() - t0, 4),
    }


def warmup_main(argv) -> int:
    """`python -m aiyagari_tpu warmup [--na ... --dtype ...]`: precompile
    the catalogue standalone and print per-program compile walls (the
    server calls the same warm_pool at startup)."""
    import argparse
    import json

    ap = argparse.ArgumentParser(prog="aiyagari_tpu warmup")
    ap.add_argument("--families", default=None,
                    help="comma-separated registry families to warm "
                         "('' = none — only the --na-sized hot programs; "
                         "default: the whole catalogue)")
    ap.add_argument("--na", type=int, default=None,
                    help="also compile the size-sensitive hot programs "
                         "(EGM sweep, stationary distribution, "
                         "push-forward steps) at this asset-grid size")
    ap.add_argument("--dtype", choices=["float32", "float64"],
                    default="float64",
                    help="dtype for the sized programs (--na)")
    ap.add_argument("--cache-dir", default=None,
                    help="compile-cache directory (default: "
                         "io_utils/compile_cache.py resolution order)")
    ap.add_argument("--aot", action="store_true",
                    help="restore AOT-serialized executables when present; "
                         "export fresh compiles for the next start")
    ap.add_argument("--aot-dir", default=None,
                    help="AOT executable directory (default: beside the "
                         "compile cache — io_utils/compile_cache.py)")
    ap.add_argument("--ledger", default=None,
                    help="append warmup events to this JSONL run ledger")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as one JSON document")
    args = ap.parse_args(argv)

    if args.dtype == "float64":
        import jax

        jax.config.update("jax_enable_x64", True)
    led = None
    if args.ledger:
        from aiyagari_tpu.diagnostics.ledger import RunLedger

        led = RunLedger(args.ledger, meta={"entry": "warmup"})
    families = (None if args.families is None
                else tuple(f for f in args.families.split(",") if f))
    report = warm_pool(families, na=args.na, dtype=args.dtype,
                       cache_dir=args.cache_dir, aot=args.aot,
                       aot_dir=args.aot_dir, ledger=led)
    if args.json:
        print(json.dumps(report, indent=2))
        return 0
    print(f"warm pool: {report['compiled']} program(s) ready "
          f"({report['restored']} AOT-restored) in "
          f"{report['wall_seconds']}s"
          + (f" -> {report['cache_dir']}" if report["cache_dir"] else ""))
    for name, rec in sorted(report["programs"].items(),
                            key=lambda kv: -kv[1]["compile_seconds"]):
        tag = {"restored": " [aot]", "exported": " [exported]"}.get(
            rec.get("aot", "off"), "")
        print(f"  {name:44s} {rec['compile_seconds']:8.3f}s{tag}")
    for name, reason in report["skipped"]:
        print(f"  {name:44s} skipped: {reason[:60]}")
    return 0
