"""Quantized solution cache: steady states and sequence-space anchors
memoized under a calibration fingerprint, LRU-bounded by a byte budget.

The serving story (ROADMAP "persistent solve service") rests on one
economic fact: near a cached steady state, a new request is a short polish,
not a cold fixed-point solve — the sequence-space literature (BKM 2018,
ABRS 2021 in PAPERS.md) makes transition requests cheap by construction
once the stationary anchor and the fake-news Jacobian exist. This module
owns the memo:

  * Keys are QUANTIZED calibration fingerprints: the r-relevant scalars
    (dispatch._SWEEP_PARAMS — beta/sigma/psi/eta/borrowing_limit/rho/
    sigma_e) are bucketed at `resolution`, while every structural knob
    (grid geometry, income-state count, technology, labor flags) keys
    EXACTLY — two economies in one bucket share a warm start only when
    their compiled programs and firm curves are literally identical.
  * A bucket HIT with the same exact parameters replays the cached
    payload ("hit"). A bucket COLLISION (same bucket, different exact
    parameters) or a NEAREST-NEIGHBOR match within `neighbor_radius`
    buckets returns the cached payload as WARM-START MATERIAL only
    ("warm") — the service polishes from it and stores the polished
    result under the request's own key, so a collision can never serve a
    stale answer (tests/test_serve.py pins this).
  * Entries are LRU-evicted against `byte_budget`: payload sizes are
    measured over their array leaves (`payload_nbytes`), the budget is a
    hard ceiling, and every eviction is a counted metric.

Thread-safe (the service's worker and any metrics scraper share it).
Observability: `aiyagari_solution_cache_{hits,warm,misses,evictions}_total`
counters plus `aiyagari_solution_cache_{bytes,entries}` gauges; the
service's per-lookup `cache_hit` ledger events are emitted at the call
site, where the request id is known.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from collections import OrderedDict
from typing import List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "CacheEntry",
    "SolutionCache",
    "blend_policies",
    "blend_scalar",
    "blend_weights",
    "calibration_key",
    "calibration_params",
    "payload_nbytes",
]

# The r-relevant calibration scalars, in fingerprint order — deliberately
# the same vocabulary dispatch.sweep()'s parameter grids accept
# (dispatch._SWEEP_PARAMS), so "what the service quantizes" and "what a
# sweep can vary" stay one concept.
PARAM_FIELDS = ("beta", "sigma", "psi", "eta", "borrowing_limit", "rho",
                "sigma_e")


def calibration_params(config) -> Tuple[float, ...]:
    """The exact r-relevant parameter vector of an AiyagariConfig, in
    PARAM_FIELDS order."""
    p, i = config.preferences, config.income
    return (float(p.beta), float(p.sigma), float(p.psi), float(p.eta),
            float(config.borrowing_limit), float(i.rho), float(i.sigma_e))


def _structural_key(config) -> tuple:
    """Everything that must match EXACTLY for two economies to share a
    warm start: grid geometry, income-state structure, technology (the
    firm curves are compiled statically into the sweep programs —
    equilibrium/batched.stack_scenarios), and the labor flags."""
    g, t, i = config.grid, config.technology, config.income
    return (g.n_points, float(g.power), g.amin, g.amax,
            i.n_states, i.method, float(t.alpha), float(t.delta),
            bool(config.endogenous_labor), config.labor_grid_n,
            tuple(config.labor_grid_bounds))


def calibration_key(config, *, resolution: float = 1e-3,
                    kind: str = "ss", extra: tuple = ()) -> tuple:
    """The quantized cache key of one request: (kind, structural knobs,
    per-parameter buckets, extra). `resolution` is the bucket width in
    NATIVE parameter units (beta and sigma are both macro-calibration
    scalars of order one, so one absolute width serves the whole vector);
    `extra` carries request-shape keys that must match exactly (a
    transition's (T, method), a shock's quantized tuple)."""
    if resolution <= 0.0:
        raise ValueError(f"resolution must be > 0, got {resolution}")
    buckets = tuple(int(math.floor(v / resolution + 0.5))
                    for v in calibration_params(config))
    return (kind, _structural_key(config), buckets, tuple(extra))


def payload_nbytes(payload) -> int:
    """Approximate byte size of a cache payload: array leaves count their
    `.nbytes`, scalars a flat 64-byte overhead. A hand-rolled recursive
    walk rather than jax.tree_util: result objects (EquilibriumResult,
    solver Solutions) are NOT registered pytrees, and tree_leaves would
    price a whole cached anchor — megabytes of mu/policy arrays — as one
    64-byte opaque leaf, so the LRU byte budget would never evict
    (exactly the unbounded-growth bug the budget exists to prevent).
    Containers, dataclasses, and plain __dict__ objects recurse; cycles
    and shared arrays are counted once via the id-visited set."""
    total = 0
    visited: set = set()
    stack = [payload]
    while stack:
        obj = stack.pop()
        if obj is None:
            continue
        oid = id(obj)
        if oid in visited:
            continue
        visited.add(oid)
        nb = getattr(obj, "nbytes", None)
        if nb is not None:
            total += int(nb)
            continue
        if isinstance(obj, dict):
            stack.extend(obj.values())
        elif isinstance(obj, (list, tuple, set, frozenset)):
            stack.extend(obj)
        elif dataclasses.is_dataclass(obj) and not isinstance(obj, type):
            stack.extend(getattr(obj, f.name)
                         for f in dataclasses.fields(obj))
        elif hasattr(obj, "__dict__") and not callable(obj):
            stack.extend(vars(obj).values())
        else:
            total += 64
    return total


# -- blending (pure helpers; the service owns when to call them) -----------


def blend_weights(distances: Sequence[float],
                  eps: float = 1e-9) -> np.ndarray:
    """Inverse-distance weights over a neighborhood, normalized to sum to
    one. A zero-distance neighbor (same exact calibration — possible when
    a bucket collision and an exact twin coexist) takes all the mass, as
    it should: the blend degenerates to that entry."""
    d = np.asarray(list(distances), dtype=np.float64)
    if d.ndim != 1 or d.size == 0:
        raise ValueError("distances must be a non-empty 1-D sequence")
    if np.any(d < 0.0):
        raise ValueError("distances must be non-negative")
    w = 1.0 / (d + eps)
    return w / w.sum()


def blend_scalar(values: Sequence[float], weights: np.ndarray) -> float:
    """Distance-weighted blend of scalars (the warm rate / secant slope)."""
    v = np.asarray(list(values), dtype=np.float64)
    if v.shape != np.shape(weights):
        raise ValueError(
            f"values/weights mismatch: {v.shape} vs {np.shape(weights)}")
    return float(np.dot(v, weights))


def blend_policies(policies: Sequence[np.ndarray],
                   grids: Sequence[np.ndarray],
                   weights: np.ndarray,
                   target_grid: np.ndarray) -> np.ndarray:
    """Distance-weighted blend of consumption policies, each interpolated
    onto the request's own asset grid first. Policies are [n_states, na_i]
    (or [na_i]); grids are the matching asset grids. Structural keying
    means in-cache neighbors always share the request's grid — the interp
    is then the identity — but the helper handles mismatched grids so
    blending stays correct if the keying ever loosens (pinned in
    tests/test_serve.py). Linear interpolation with edge clamping (np.interp
    semantics): consumption policies are monotone and concave-ish in assets,
    so linear-in-assets blending keeps the warm start feasible."""
    if len(policies) != len(grids) or len(policies) != len(weights):
        raise ValueError("policies, grids, and weights must align")
    tg = np.asarray(target_grid, dtype=np.float64)
    out = None
    for pol, grid, w in zip(policies, grids, weights):
        p = np.asarray(pol, dtype=np.float64)
        g = np.asarray(grid, dtype=np.float64)
        if p.ndim == 1:
            p = p[None, :]
        if p.shape[-1] != g.shape[-1]:
            raise ValueError(
                f"policy/grid length mismatch: {p.shape[-1]} vs {g.shape[-1]}")
        if p.shape[-1] == tg.shape[-1] and np.array_equal(g, tg):
            onto = p
        else:
            onto = np.stack([np.interp(tg, g, row) for row in p])
        out = w * onto if out is None else out + w * onto
    return out


@dataclasses.dataclass
class CacheEntry:
    """One memoized solve. `exact` disambiguates bucket collisions: a
    lookup whose exact parameter vector differs gets this entry as
    warm-start material, never as the answer."""

    key: tuple
    exact: Tuple[float, ...]
    payload: object
    nbytes: int
    stored_at: float
    hits: int = 0
    promoted: bool = False     # adopted from the L2 tier (serve/tier.py):
                               # warm-start material only — an exact match
                               # classifies "warm", never "hit", until a
                               # LOCAL converged solve re-stores the key


class SolutionCache:
    """LRU byte-budgeted memo of solve payloads under quantized keys
    (module docstring). `byte_budget <= 0` disables storage entirely
    (every lookup is a miss) — the bench's cold-regime knob."""

    def __init__(self, byte_budget: int = 256 * 1024 * 1024, *,
                 resolution: float = 1e-3, neighbor_radius: float = 50.0):
        if resolution <= 0.0:
            raise ValueError(f"resolution must be > 0, got {resolution}")
        self.byte_budget = int(byte_budget)
        self.resolution = float(resolution)
        self.neighbor_radius = float(neighbor_radius)
        self._entries: "OrderedDict[tuple, CacheEntry]" = OrderedDict()
        self._bytes = 0
        # Re-entrant: stats() reads hit_rate() under the lock, and the
        # thread-safety contract (ISSUE 20 satellite) is that EVERY
        # lookup/put/evict bookkeeping path — including the service's
        # fast-path peek and the L2 promotion path (serve/tier.py), which
        # run on HTTP handler threads concurrent with the worker — holds
        # this one lock around the LRU and its counters.
        self._lock = threading.RLock()
        self.hits = 0
        self.warm = 0
        self.misses = 0
        self.evictions = 0

    # -- keys --------------------------------------------------------------

    def key_for(self, config, *, kind: str = "ss",
                extra: tuple = ()) -> tuple:
        return calibration_key(config, resolution=self.resolution,
                               kind=kind, extra=extra)

    # -- lookup ------------------------------------------------------------

    def lookup(self, config, *, kind: str = "ss", extra: tuple = ()):
        """(outcome, entry): outcome in {"hit", "warm", "miss"}; entry is
        None only on "miss". "hit" = same bucket AND same exact parameter
        vector (replay the payload); "warm" = a bucket collision or the
        nearest neighbor within `neighbor_radius` buckets (polish from the
        payload, then `put` the polished result under this request's own
        key)."""
        key = self.key_for(config, kind=kind, extra=extra)
        exact = calibration_params(config)
        with self._lock:
            outcome, entry = self._classify_locked(key, exact)
            self._count_outcome_locked(outcome)
            return outcome, entry

    def _classify_locked(self, key: tuple, exact: Tuple[float, ...]):
        """The classification half of `lookup` (caller holds the lock, and
        owns the outcome counting): exact hit / bucket-collision warm /
        nearest-neighbor warm / miss. Split out so the tiered cache
        (serve/tier.py) can classify L1 and fall through to L2 without
        double-counting a miss."""
        entry = self._entries.get(key)
        if entry is not None:
            self._entries.move_to_end(key)
            entry.hits += 1
            if entry.exact == exact and not entry.promoted:
                return "hit", entry
            return "warm", entry
        entry = self._nearest_locked(key, exact)
        if entry is not None:
            return "warm", entry
        return "miss", None

    def _count_outcome_locked(self, outcome: str) -> None:
        if outcome == "hit":
            self.hits += 1
            self._count("hits")
        elif outcome == "warm":
            self.warm += 1
            self._count("warm")
        else:
            self.misses += 1
            self._count("misses")

    def peek(self, config, *, kind: str = "ss",
             extra: tuple = ()) -> Optional[CacheEntry]:
        """A LOCKED exact-hit peek that mutates nothing: no LRU reorder,
        no hit counters, no outcome accounting. The service's fast path
        (`_try_hit`) uses this instead of reading `_entries` bare — HTTP
        handler threads and the L2 promotion path race on the LRU, and an
        unlocked OrderedDict read during a concurrent evict/insert is a
        data race (ISSUE 20 satellite)."""
        if self.byte_budget <= 0:
            return None
        key = self.key_for(config, kind=kind, extra=extra)
        exact = calibration_params(config)
        with self._lock:
            entry = self._entries.get(key)
            if (entry is not None and entry.exact == exact
                    and not entry.promoted):
                return entry
        return None

    def _nearest_locked(self, key: tuple, exact: Tuple[float, ...]):
        """The nearest same-kind/same-structure entry within
        `neighbor_radius` (L2 over parameter distance in bucket units).
        Linear scan — the cache holds at most a few thousand entries
        (byte-budgeted), and the scan is pure host arithmetic."""
        kind, structural = key[0], key[1]
        best, best_d = None, float("inf")
        for entry in self._entries.values():
            if entry.key[0] != kind or entry.key[1] != structural \
                    or entry.key[3] != key[3]:
                continue
            d = math.sqrt(sum((a - b) ** 2 for a, b in
                              zip(entry.exact, exact))) / self.resolution
            if d < best_d:
                best, best_d = entry, d
        if best is not None and best_d <= self.neighbor_radius:
            return best
        return None

    def neighborhood(self, config, *, kind: str = "ss",
                     extra: tuple = ()) -> List[Tuple[CacheEntry, float]]:
        """ALL same-kind/same-structure entries within `neighbor_radius`
        of the request, as (entry, distance-in-bucket-units) pairs sorted
        nearest-first. The multi-neighbor generalization of the single
        best entry `lookup` returns: the service distance-weights these
        into one blended warm start (`blend_weights`/`blend_policies`).
        Does NOT touch LRU order or hit counters — it is a read-only peek;
        the classifying `lookup` owns the outcome accounting."""
        key = self.key_for(config, kind=kind, extra=extra)
        exact = calibration_params(config)
        kind_k, structural = key[0], key[1]
        found: List[Tuple[CacheEntry, float]] = []
        with self._lock:
            for entry in self._entries.values():
                if entry.key[0] != kind_k or entry.key[1] != structural \
                        or entry.key[3] != key[3]:
                    continue
                d = math.sqrt(sum((a - b) ** 2 for a, b in
                                  zip(entry.exact, exact))) / self.resolution
                if d <= self.neighbor_radius:
                    found.append((entry, d))
        found.sort(key=lambda pair: pair[1])
        return found

    # -- store -------------------------------------------------------------

    def put(self, config, payload, *, kind: str = "ss",
            extra: tuple = ()) -> Optional[CacheEntry]:
        """Store (or replace) the payload under the request's quantized
        key, then evict least-recently-used entries until the byte budget
        holds. A payload larger than the whole budget is not stored (it
        would evict everything and then itself — the metric records the
        refusal as an eviction)."""
        return self.put_entry(self.key_for(config, kind=kind, extra=extra),
                              calibration_params(config), payload)

    def put_entry(self, key: tuple, exact: Tuple[float, ...],
                  payload, *, promoted: bool = False
                  ) -> Optional[CacheEntry]:
        """`put` under a precomputed (key, exact) pair — the L2 promotion
        path (serve/tier.py) adopts another worker's entry verbatim, so
        the key arrives already quantized and must be inserted under the
        same lock discipline as a local put. `promoted=True` marks the
        entry as cross-worker warm material: exact lookups on it classify
        "warm" (polish, then re-store locally), never "hit"."""
        nbytes = payload_nbytes(payload)
        entry = CacheEntry(key=key, exact=tuple(exact), payload=payload,
                           nbytes=nbytes, stored_at=time.time(),
                           promoted=promoted)
        with self._lock:
            if self.byte_budget <= 0:
                return None
            if nbytes > self.byte_budget:
                self.evictions += 1
                self._count("evictions")
                self._gauges()
                return None
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[key] = entry
            self._bytes += nbytes
            while self._bytes > self.byte_budget and len(self._entries) > 1:
                _, evicted = self._entries.popitem(last=False)
                self._bytes -= evicted.nbytes
                self.evictions += 1
                self._count("evictions")
            self._gauges()
        return entry

    # -- introspection -----------------------------------------------------

    @property
    def nbytes(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def hit_rate(self) -> float:
        """Exact-hit fraction of all lookups (the gauge the service
        exports; warm lookups are counted as non-hits — they still solve)."""
        with self._lock:
            total = self.hits + self.warm + self.misses
            return self.hits / total if total else 0.0

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes,
                    "hits": self.hits, "warm": self.warm,
                    "misses": self.misses, "evictions": self.evictions,
                    "hit_rate": round(self.hit_rate(), 4)}

    # -- observability (must never fail a solve) ---------------------------

    def _count(self, outcome: str) -> None:
        try:
            from aiyagari_tpu.diagnostics import metrics

            metrics.counter(
                f"aiyagari_solution_cache_{outcome}_total").inc()
        except Exception:  # pragma: no cover - diagnostics are best-effort
            pass

    def _gauges(self) -> None:
        try:
            from aiyagari_tpu.diagnostics import metrics

            metrics.gauge("aiyagari_solution_cache_bytes").set(self._bytes)
            metrics.gauge("aiyagari_solution_cache_entries").set(
                len(self._entries))
        except Exception:  # pragma: no cover - diagnostics are best-effort
            pass
