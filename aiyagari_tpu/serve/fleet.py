"""Fleet front (ISSUE 20 tentpole, layer 3): N `SolveService` workers as
separate processes behind one routing HTTP front — `serve/` grows from a
single service into a pod-scale solve fabric.

Topology. Each worker is a full `python -m aiyagari_tpu serve --port P`
process with its own grid-sized warm pool, its own L1 solution cache, and
its own host-stamped ledger shard (`ledger.p<k>.jsonl`) under ONE run id
the front draws and passes to every worker (PR 14's multi-host machinery,
reused verbatim: `merge_ledgers` reads the whole fleet as a single flight
record). Workers share the L2 solution tier (`serve/tier.py`) and the
AOT-serialized warm pool (`serve/warmup.py --aot`), so worker B starts
warm from worker A's compiles and polishes from worker A's solves.

Routing. The front classes each request by GRID-SIZE bucket — a request's
optional top-level `"grid"` field is matched to the nearest worker grid
class — because grid size is the structural key: a worker's warm pool,
its XLA executables, and its cache entries are all sized to its grid, so
right-sizing the route is what makes the fabric's caches compose.
Within a class, ready non-draining workers round-robin.

Delivery record. Every routed request writes a `fleet_route` event
(request id, worker, body) to the front's shard BEFORE the forward, and a
`fleet_ack` after the worker's response went out. The un-acked difference
is exactly the set of requests whose answers never reached a client —
`unacked_from_ledger` computes it, and a graceful drain (POST /drain)
replays it onto the surviving workers after the drained process exits:
admission stops, in-flight requests finish, the process is terminated,
and un-acked work is re-solved so its results exist in the fabric's
tiers even though the original connection is gone.

Observability: `aiyagari_fleet_workers` / `aiyagari_fleet_rps` gauges,
`aiyagari_fleet_{requests,replays,drains}_total` counters, aggregated
worker + L2 state on GET /healthz, `python -m aiyagari_tpu watch` renders
the per-worker table from the merged shards.

CLI: `python -m aiyagari_tpu fleet --workers N [--grids 40,100 ...]`.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time
import uuid
from collections import deque
from http.client import HTTPConnection
from pathlib import Path
from typing import List, Optional, Sequence, Tuple

__all__ = ["Fleet", "fleet_main", "unacked_from_ledger"]


def _free_port() -> int:
    """An OS-assigned free TCP port (bind-to-0 probe). Raceable in
    principle; in practice the worker binds within milliseconds."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def grid_class(grids: Sequence[int], requested: Optional[int]) -> int:
    """The worker grid class serving a requested grid size: the nearest
    available class (ties to the smaller — a too-small warm pool recompiles
    less than a too-big one idles). None = the fleet's first class."""
    classes = sorted(set(int(g) for g in grids))
    if not classes:
        raise ValueError("fleet has no grid classes")
    if requested is None:
        return classes[0]
    return min(classes, key=lambda g: (abs(g - int(requested)), g))


def unacked_from_ledger(events, *, run_id: Optional[str] = None,
                        worker: Optional[int] = None) -> List[dict]:
    """The routed-but-never-acknowledged requests of a fleet run: every
    `fleet_route` event (latest attempt per request id wins) without a
    matching `fleet_ack`. Pure function over ledger event dicts — works on
    one shard or on `merge_ledgers` output; filter by `run_id`/`worker`
    when the file holds more than one run or the drain targets one
    worker's backlog."""
    routed: dict = {}
    acked: set = set()
    for ev in events:
        if run_id is not None and ev.get("run_id") != run_id:
            continue
        kind = ev.get("kind")
        if kind == "fleet_route":
            routed[ev.get("rid")] = ev
        elif kind == "fleet_ack":
            acked.add(ev.get("rid"))
    out = [ev for rid, ev in routed.items() if rid not in acked]
    if worker is not None:
        out = [ev for ev in out if ev.get("worker") == worker]
    out.sort(key=lambda ev: ev.get("seq", 0))
    return out


class _Worker:
    """One spawned serve process and the front's view of it."""

    def __init__(self, index: int, grid: int, port: int,
                 proc: subprocess.Popen):
        self.index = index
        self.grid = grid
        self.port = port
        self.proc = proc
        self.ready = False
        self.draining = False
        self.inflight = 0
        self.served = 0

    def alive(self) -> bool:
        return self.proc.poll() is None


class Fleet:
    """Spawn + front N serve workers (module docstring). Usage:

        fleet = Fleet(workers=2, grids=(40,), ledger="fleet.jsonl",
                      l2_dir="l2/", aot=True)
        fleet.start(ready_timeout=600)
        httpd = fleet.front(port)           # ThreadingHTTPServer
        ...
        fleet.stop()
    """

    def __init__(self, workers: int = 2, *, grids: Sequence[int] = (40,),
                 ledger=None, l2_dir=None, aot: bool = False,
                 method: str = "egm", dtype: str = "float64",
                 max_batch: int = 8, cache_mb: float = 256.0,
                 warm_families: Optional[str] = None,
                 platform: Optional[str] = None,
                 extra_args: Sequence[str] = ()):
        if workers < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        from aiyagari_tpu.diagnostics.ledger import RunLedger, new_run_id

        self.run_id = new_run_id()
        self.n = int(workers)
        self.grids = tuple(int(g) for g in grids) or (40,)
        self.ledger_path = str(ledger) if ledger else None
        self.l2_dir = str(l2_dir) if l2_dir else None
        self.aot = bool(aot)
        self._spawn_opts = dict(
            method=method, dtype=dtype, max_batch=max_batch,
            cache_mb=cache_mb, warm_families=warm_families,
            platform=platform, extra_args=tuple(extra_args))
        self.workers: List[_Worker] = []
        self._led = None
        if self.ledger_path:
            # The front takes shard index n (workers hold 0..n-1): one run
            # id, n+1 host-stamped shards, one merged flight record.
            self._led = RunLedger(
                self.ledger_path, run_id=self.run_id,
                process_index=self.n, process_count=self.n + 1,
                meta={"entry": "fleet_front", "workers": self.n,
                      "grids": list(self.grids)})
        self._lock = threading.Lock()
        self._rr = 0                      # round-robin cursor
        self._times: deque = deque(maxlen=512)   # request timestamps (rps)
        self._health_cache: Tuple[float, dict] = (0.0, {})
        self.drains = 0
        self.replays = 0

    # -- lifecycle ---------------------------------------------------------

    def _spawn(self, index: int, grid: int) -> _Worker:
        port = _free_port()
        o = self._spawn_opts
        cmd = [sys.executable, "-m", "aiyagari_tpu", "serve",
               "--port", str(port), "--grid", str(grid),
               "--method", o["method"], "--dtype", o["dtype"],
               "--max-batch", str(o["max_batch"]),
               "--cache-mb", str(o["cache_mb"])]
        if self.ledger_path:
            cmd += ["--ledger", self.ledger_path,
                    "--run-id", self.run_id,
                    "--worker-index", str(index),
                    "--worker-count", str(self.n + 1)]
        if self.l2_dir:
            cmd += ["--l2-dir", self.l2_dir]
        if self.aot:
            cmd += ["--aot"]
        if o["warm_families"] is not None:
            cmd += ["--warm-families", o["warm_families"]]
        cmd += list(o["extra_args"])
        env = dict(os.environ)
        if o["platform"]:
            env["JAX_PLATFORMS"] = o["platform"]
        proc = subprocess.Popen(cmd, stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL, env=env)
        return _Worker(index, grid, port, proc)

    def start(self, ready_timeout: float = 600.0) -> "Fleet":
        """Spawn every worker, then poll /healthz until each reports 200
        ready (the readiness split: a worker answers 503 "warming" from
        the moment its socket is up until its warm pool / AOT restore
        completes) or the deadline passes."""
        for i in range(self.n):
            self.workers.append(
                self._spawn(i, self.grids[i % len(self.grids)]))
        deadline = time.monotonic() + ready_timeout
        for w in self.workers:
            while time.monotonic() < deadline and w.alive():
                state = self._worker_health(w)
                if state.get("state") == "ready":
                    w.ready = True
                    break
                time.sleep(0.25)
            if self._led is not None:
                self._led.event(
                    "fleet_worker", worker=w.index, port=w.port,
                    grid=w.grid, state="ready" if w.ready else "not_ready",
                    alive=w.alive())
        self._gauge_workers()
        return self

    def stop(self) -> None:
        for w in self.workers:
            if w.alive():
                w.proc.terminate()
        for w in self.workers:
            try:
                w.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                w.proc.kill()
                w.proc.wait(timeout=10)
        if self._led is not None:
            self._led.event("fleet_stop", drains=self.drains,
                            replays=self.replays)

    def __enter__(self) -> "Fleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- worker I/O --------------------------------------------------------

    @staticmethod
    def _worker_health(w: _Worker, timeout: float = 5.0) -> dict:
        try:
            conn = HTTPConnection("127.0.0.1", w.port, timeout=timeout)
            try:
                conn.request("GET", "/healthz")
                r = conn.getresponse()
                body = json.loads(r.read() or b"{}")
                body.setdefault(
                    "state", "ready" if r.status == 200 else "warming")
                return body
            finally:
                conn.close()
        except Exception:  # noqa: BLE001 — down/unreachable = not ready
            return {"state": "down"}

    def _forward(self, w: _Worker, path: str, body: dict,
                 timeout: float) -> Tuple[int, bytes]:
        data = json.dumps(body).encode()
        conn = HTTPConnection("127.0.0.1", w.port, timeout=timeout)
        try:
            conn.request("POST", path, body=data,
                         headers={"Content-Type": "application/json"})
            r = conn.getresponse()
            return r.status, r.read()
        finally:
            conn.close()

    # -- routing -----------------------------------------------------------

    def _eligible(self, cls: int) -> List[_Worker]:
        return [w for w in self.workers
                if w.grid == cls and w.ready and not w.draining
                and w.alive()]

    def route(self, body: dict, *, path: str = "/solve",
              timeout: float = 600.0,
              exclude: Sequence[int] = ()) -> Tuple[int, bytes]:
        """Class the request by grid bucket, pick the next ready worker
        round-robin, forward with a route/ack delivery record, and fail
        over to the class's survivors on transport errors (a worker that
        died mid-request). Raises RuntimeError when no worker can take
        the class."""
        requested = body.pop("grid", None)
        cls = grid_class(self.grids, requested)
        rid = uuid.uuid4().hex[:12]
        last_err: Optional[Exception] = None
        tried: set = set(exclude)
        for _ in range(len(self.workers)):
            with self._lock:
                cands = [w for w in self._eligible(cls)
                         if w.index not in tried]
                if not cands:
                    break
                w = cands[self._rr % len(cands)]
                self._rr += 1
                w.inflight += 1
            tried.add(w.index)
            if self._led is not None:
                self._led.event("fleet_route", rid=rid, worker=w.index,
                                port=w.port, grid_class=cls, path=path,
                                body=json.dumps(body))
            try:
                code, payload = self._forward(w, path, body, timeout)
            except Exception as e:  # noqa: BLE001 — transport failure:
                last_err = e        # the survivors get the request
                continue
            finally:
                with self._lock:
                    w.inflight -= 1
            with self._lock:
                w.served += 1
            if self._led is not None:
                self._led.event("fleet_ack", rid=rid, worker=w.index,
                                code=code)
            self._count("requests")
            with self._lock:
                self._times.append(time.monotonic())
            return code, payload
        raise RuntimeError(
            f"no worker available for grid class {cls}"
            + (f" (last transport error: {last_err})" if last_err else ""))

    # -- drain -------------------------------------------------------------

    def drain(self, index: int, *, inflight_timeout: float = 120.0,
              replay_timeout: float = 600.0) -> dict:
        """Gracefully retire worker `index`: stop admission (draining
        flag), wait for its front-tracked in-flight requests to finish,
        terminate the process, then replay its un-acked requests from the
        ledger onto the surviving workers (their answers never reached a
        client — re-solving parks the results in the fabric's caches)."""
        w = next((x for x in self.workers if x.index == index), None)
        if w is None:
            raise ValueError(f"no worker {index}")
        with self._lock:
            w.draining = True
        deadline = time.monotonic() + inflight_timeout
        while time.monotonic() < deadline:
            with self._lock:
                if w.inflight <= 0:
                    break
            time.sleep(0.05)
        if w.alive():
            w.proc.terminate()
            try:
                w.proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                w.proc.kill()
        w.ready = False
        replayed = failed = 0
        if self._led is not None:
            from aiyagari_tpu.diagnostics.ledger import read_ledger

            events = read_ledger(self._led.path)
            for ev in unacked_from_ledger(events, run_id=self.run_id,
                                          worker=index):
                try:
                    body = json.loads(ev.get("body") or "{}")
                    code, _ = self.route(
                        body, path=ev.get("path", "/solve"),
                        timeout=replay_timeout, exclude=(index,))
                    replayed += 1
                    self._count("replays")
                except Exception:  # noqa: BLE001 — count, keep draining
                    failed += 1
            self.replays += replayed
        self.drains += 1
        self._count("drains")
        self._gauge_workers()
        report = {"worker": index, "replayed": replayed,
                  "replay_failures": failed,
                  "survivors": sum(1 for x in self.workers if x.ready)}
        if self._led is not None:
            self._led.event("fleet_drain", **report)
        return report

    # -- aggregated health -------------------------------------------------

    def health(self, max_age_s: float = 1.0) -> dict:
        """Fleet-wide healthz: per-worker state + aggregated L2/cold
        numbers, memoized for `max_age_s` so a polling front does not
        multiply scrape load onto the workers."""
        with self._lock:
            ts, cached = self._health_cache
            if time.monotonic() - ts < max_age_s and cached:
                return cached
        rows = []
        l2_hits = 0
        ready = 0
        for w in self.workers:
            h = self._worker_health(w) if w.alive() else {"state": "down"}
            state = ("draining" if w.draining else h.get("state", "down"))
            if state == "ready":
                ready += 1
            l2 = (h.get("cache") or {}).get("l2") or {}
            l2_hits += int(l2.get("hits", 0))
            rows.append({
                "worker": w.index, "port": w.port, "grid": w.grid,
                "state": state, "served": w.served,
                "requests_served": h.get("requests_served", 0),
                "cold_fraction": h.get("cold_fraction"),
                "cache": h.get("cache")})
        now = time.monotonic()
        with self._lock:
            while self._times and now - self._times[0] > 30.0:
                self._times.popleft()
            rps = len(self._times) / 30.0
        out = {"ok": ready > 0, "run_id": self.run_id, "workers": rows,
               "ready": ready, "rps": round(rps, 3),
               "l2_hits": l2_hits, "drains": self.drains,
               "replays": self.replays}
        self._gauge("aiyagari_fleet_workers", ready)
        self._gauge("aiyagari_fleet_rps", rps)
        self._gauge("aiyagari_fleet_l2_hits", l2_hits)
        with self._lock:
            self._health_cache = (time.monotonic(), out)
        return out

    def _gauge_workers(self) -> None:
        self._gauge("aiyagari_fleet_workers",
                    sum(1 for w in self.workers if w.ready))

    # -- observability (best-effort) ---------------------------------------

    @staticmethod
    def _gauge(name: str, value) -> None:
        try:
            from aiyagari_tpu.diagnostics import metrics

            metrics.gauge(name).set(float(value))
        except Exception:  # pragma: no cover
            pass

    @staticmethod
    def _count(what: str) -> None:
        try:
            from aiyagari_tpu.diagnostics import metrics

            metrics.counter(f"aiyagari_fleet_{what}_total").inc()
        except Exception:  # pragma: no cover
            pass

    # -- the routing HTTP front --------------------------------------------

    def front(self, port: int):
        """The front's ThreadingHTTPServer: POST /solve (routed), POST
        /drain {"worker": i}, GET /healthz (aggregate), GET /metrics
        (front-process registry). Call serve_forever() on the result."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        fleet = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _send(self, code: int, body: bytes,
                      ctype: str = "application/json"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    h = fleet.health()
                    self._send(200 if h["ok"] else 503,
                               json.dumps(h).encode())
                elif self.path == "/metrics":
                    from aiyagari_tpu.diagnostics import metrics

                    fleet.health()   # refresh the fleet gauges
                    self._send(200, metrics.render_prometheus().encode(),
                               "text/plain; version=0.0.4")
                else:
                    self._send(404, b'{"error": "not found"}')

            def do_POST(self):
                length = int(self.headers.get("Content-Length", "0"))
                try:
                    body = json.loads(self.rfile.read(length) or b"{}")
                except Exception:  # noqa: BLE001 — HTTP boundary
                    self._send(400, b'{"error": "bad json"}')
                    return
                try:
                    if self.path == "/drain":
                        report = fleet.drain(int(body.get("worker", 0)))
                        self._send(200, json.dumps(report).encode())
                    elif self.path in ("/solve", "/calibrate"):
                        code, payload = fleet.route(
                            body, path=self.path,
                            timeout=float(body.get("timeout", 600)) + 30.0)
                        self._send(code, payload)
                    else:
                        self._send(404, b'{"error": "not found"}')
                except Exception as e:  # noqa: BLE001 — HTTP boundary
                    self._send(503, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"[:500]}
                    ).encode())

        return ThreadingHTTPServer(("127.0.0.1", port), Handler)


def fleet_main(argv) -> int:
    """`python -m aiyagari_tpu fleet --workers N`: spawn the workers, wait
    for readiness, and serve the routing front."""
    import argparse

    ap = argparse.ArgumentParser(prog="aiyagari_tpu fleet")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--grids", default="40",
                    help="comma-separated grid classes; workers round-"
                         "robin over them (one right-sized warm pool per "
                         "class)")
    ap.add_argument("--port", type=int, default=8800,
                    help="the routing front's HTTP port")
    ap.add_argument("--method", choices=["vfi", "egm"], default="egm")
    ap.add_argument("--dtype", choices=["float32", "float64", "mixed"],
                    default="float64")
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--cache-mb", type=float, default=256.0)
    ap.add_argument("--l2-dir", default=None,
                    help="shared cross-worker L2 solution tier directory")
    ap.add_argument("--aot", action="store_true",
                    help="workers restore AOT-serialized warm pools")
    ap.add_argument("--warm-families", default=None,
                    help="worker warm-pool families ('' = sized programs "
                         "only)")
    ap.add_argument("--ledger", default=None,
                    help="sharded fleet flight record (one run id; "
                         "render: python -m aiyagari_tpu report/watch)")
    ap.add_argument("--ready-timeout", type=float, default=600.0)
    args = ap.parse_args(argv)

    grids = tuple(int(g) for g in args.grids.split(",") if g)
    fleet = Fleet(args.workers, grids=grids, ledger=args.ledger,
                  l2_dir=args.l2_dir, aot=args.aot, method=args.method,
                  dtype=args.dtype, max_batch=args.max_batch,
                  cache_mb=args.cache_mb,
                  warm_families=args.warm_families)
    fleet.start(ready_timeout=args.ready_timeout)
    ready = sum(1 for w in fleet.workers if w.ready)
    print(f"fleet: {ready}/{fleet.n} worker(s) ready "
          f"(grids {sorted(set(fleet.grids))}, run {fleet.run_id})")
    for w in fleet.workers:
        print(f"  worker {w.index}: grid {w.grid} on 127.0.0.1:{w.port} "
              f"[{'ready' if w.ready else 'NOT READY'}]")
    httpd = fleet.front(args.port)
    print(f"fleet front on http://127.0.0.1:{args.port}  "
          f"(POST /solve, POST /drain, GET /healthz, GET /metrics)")
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
        fleet.stop()
    return 0
