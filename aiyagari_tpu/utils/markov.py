"""Markov-chain construction: Tauchen discretization, stationary distributions,
and the Krusell-Smith duration-targeted joint (z x eps) chain.

TPU-first notes: everything here is tiny, dense linear algebra evaluated once at
setup, so it runs in float64 on host by default; the outputs feed device kernels.
"""

from __future__ import annotations

import numpy as np

from aiyagari_tpu.config import IncomeProcess, KSShockProcess

__all__ = [
    "tauchen",
    "rouwenhorst",
    "discretize_income",
    "stationary_distribution",
    "normalized_labor",
    "ks_transition_matrix",
    "ks_conditional_eps_matrices",
    "KS_STATE_GRID_ORDER",
]


def _norm_cdf(x: np.ndarray) -> np.ndarray:
    from math import sqrt

    from scipy.special import erf  # type: ignore

    return 0.5 * (1.0 + erf(x / sqrt(2.0)))


def tauchen(process: IncomeProcess) -> tuple[np.ndarray, np.ndarray]:
    """Discretize log s' = rho*log s + e, e~N(0, sd^2) on a fixed grid.

    Matches the reference's variant (Aiyagari_VFI.m:18-35): grid points
    l_i = (i - (n+1)/2) * sigma_e with half-integer break intervals
    (..., -1.5, -0.5, 0.5, ...) * sigma_e, and row i of P given by the
    probability mass of N(rho*l_i, sd) in each interval. The reference
    computes the mass by adaptive quadrature of the normal pdf; the closed
    form used here (CDF differences) is the same integral evaluated exactly.

    Returns (l_grid [n], P [n, n]) in float64.
    """
    n = process.n_states
    sigma_e = process.sigma_e
    rho = process.rho
    center = (n + 1) / 2.0
    l_grid = (np.arange(1, n + 1) - center) * sigma_e
    # Break intervals at half-integers times sigma_e, open at the ends.
    edges = (np.arange(1, n) - center + 0.5) * sigma_e
    edges = np.concatenate(([-np.inf], edges, [np.inf]))
    sd = sigma_e * np.sqrt(1.0 - rho**2)
    mu = rho * l_grid[:, None]                      # (n, 1) conditional means
    z = (edges[None, :] - mu) / sd                  # (n, n+1)
    cdf = np.where(np.isneginf(z), 0.0, np.where(np.isposinf(z), 1.0, _norm_cdf(z)))
    P = np.diff(cdf, axis=1)
    return l_grid, P


def rouwenhorst(process: IncomeProcess) -> tuple[np.ndarray, np.ndarray]:
    """Rouwenhorst (1995) discretization of the same AR(1):
    log s' = rho*log s + e, e ~ N(0, sd^2), sd = sigma_e*sqrt(1-rho^2),
    so the stationary standard deviation is sigma_e.

    Grid: n evenly spaced points on [-psi, psi] with psi = sigma_e*sqrt(n-1);
    transition matrix built by the standard recursive construction with
    p = q = (1+rho)/2. Unlike Tauchen (the reference's only method,
    Aiyagari_VFI.m:18-35), Rouwenhorst matches the conditional mean
    (E[l'|l] = rho*l), persistence, and stationary variance of the AR(1)
    EXACTLY for any rho — the method of choice for highly persistent
    processes, where Tauchen's fixed +/-3-sigma grid is badly inaccurate.

    Returns (l_grid [n], P [n, n]) in float64.
    """
    n = process.n_states
    rho, sigma_e = process.rho, process.sigma_e
    if n < 2:
        raise ValueError(f"rouwenhorst needs n_states >= 2, got {n}")
    p = (1.0 + rho) / 2.0
    P = np.array([[p, 1.0 - p], [1.0 - p, p]])
    for m in range(3, n + 1):
        Pn = np.zeros((m, m))
        Pn[:-1, :-1] += p * P
        Pn[:-1, 1:] += (1.0 - p) * P
        Pn[1:, :-1] += (1.0 - p) * P
        Pn[1:, 1:] += p * P
        Pn[1:-1, :] /= 2.0   # interior rows are reached twice in the overlay
        P = Pn
    psi = sigma_e * np.sqrt(n - 1.0)
    l_grid = np.linspace(-psi, psi, n)
    return l_grid, P


def discretize_income(process: IncomeProcess) -> tuple[np.ndarray, np.ndarray]:
    """Dispatch on process.method: 'tauchen' (the reference's scheme) or
    'rouwenhorst'. Returns (l_grid, P)."""
    if process.method == "tauchen":
        return tauchen(process)
    if process.method == "rouwenhorst":
        return rouwenhorst(process)
    raise ValueError(
        f"unknown discretization method {process.method!r}; "
        "expected 'tauchen' or 'rouwenhorst'"
    )


def stationary_distribution(P: np.ndarray) -> np.ndarray:
    """Stationary distribution pi with pi' P = pi', sum(pi)=1.

    Solves the overdetermined system [P' - I; 1'] x = [0; 1] by least squares,
    exactly as the reference's mldivide solve (Aiyagari_VFI.m:39-42).
    """
    n = P.shape[0]
    A = np.vstack([P.T - np.eye(n), np.ones((1, n))])
    b = np.concatenate([np.zeros(n), [1.0]])
    pi, *_ = np.linalg.lstsq(A, b, rcond=None)
    return pi


def normalized_labor(l_grid: np.ndarray, pi: np.ndarray) -> tuple[np.ndarray, float]:
    """Efficiency units s=exp(l) normalized so aggregate labor supply is 1.

    Reference: Aiyagari_VFI.m:43-45. Returns (s_normalized [n], labor_raw).
    `labor_raw` (the pre-normalization aggregate s @ pi) multiplies the
    capital-demand curve (Aiyagari_VFI.m:195).
    """
    s = np.exp(l_grid)
    labor = float(s @ pi)
    return s / labor, labor


def ks_conditional_eps_matrices(shocks: KSShockProcess) -> dict[str, np.ndarray]:
    """The four conditional 2x2 employment-transition matrices, keyed by the
    aggregate transition (gg, bb, gb, bg), built from duration targets.

    Rows/cols ordered [employed, unemployed]. Matches Krusell_Smith_VFI.m:28-45.
    Key 'gb' means aggregate state moved good -> bad.
    """
    ug, ub = shocks.u_good, shocks.u_bad
    p00_gg = 1.0 - 1.0 / shocks.u_good_duration
    p00_bb = 1.0 - 1.0 / shocks.u_bad_duration
    p00_gb = shocks.uu_rel_gb2bb * p00_bb
    p00_bg = shocks.uu_rel_bg2gg * p00_gg

    out = {}
    for key, p00, u_from, u_to in (
        ("gg", p00_gg, ug, ug),
        ("bb", p00_bb, ub, ub),
        ("gb", p00_gb, ug, ub),
        ("bg", p00_bg, ub, ug),
    ):
        p01 = 1.0 - p00
        # Employment-to-unemployment probability pinned down by consistency of
        # the unemployment rate: u' = u*p00 + (1-u)*p10  (Krusell_Smith_VFI.m:39-42).
        p10 = (u_to - u_from * p00) / (1.0 - u_from)
        p11 = 1.0 - p10
        out[key] = np.array([[p11, p10], [p01, p00]])
    return out


# State ordering used throughout: index s in {0,1,2,3} maps to
# (z, eps) = [(good, employed), (bad, employed), (good, unemployed), (bad, unemployed)].
# This is the reference's meshgrid ordering s_grid = [Z(:), Eps(:)]
# with z_grid=[zg, zb], eps_grid=[1, 0] (Krusell_Smith_VFI.m:18-21).
KS_STATE_GRID_ORDER = ((0, 1), (1, 1), (0, 0), (1, 0))  # (z_index, employed_flag)


def ks_transition_matrix(shocks: KSShockProcess) -> np.ndarray:
    """Joint 4x4 transition matrix over (z, eps) states.

    P[s, s'] = Pr(z'|z) * Pr(eps'|eps, z, z'), assembled exactly as
    Krusell_Smith_VFI.m:47-55 under the state ordering KS_STATE_GRID_ORDER.
    """
    pgg = 1.0 - 1.0 / shocks.z_good_duration
    pbb = 1.0 - 1.0 / shocks.z_bad_duration
    pz = np.array([[pgg, 1.0 - pgg], [1.0 - pbb, pbb]])  # [z, z']
    eps_mats = ks_conditional_eps_matrices(shocks)
    key_by_pair = {(0, 0): "gg", (1, 1): "bb", (0, 1): "gb", (1, 0): "bg"}

    P = np.zeros((4, 4))
    for s, (zi, emp) in enumerate(KS_STATE_GRID_ORDER):
        for sp, (zj, emp_p) in enumerate(KS_STATE_GRID_ORDER):
            Peps = eps_mats[key_by_pair[(zi, zj)]]
            row = 0 if emp else 1
            col = 0 if emp_p else 1
            P[s, sp] = pz[zi, zj] * Peps[row, col]
    return P
