"""Cobb-Douglas firm block: factor prices from firm FOCs and the capital
demand curve.

Reference: wage from r at Aiyagari_VFI.m:67; capital demand at :195; the
Krusell-Smith (z, K)-dependent price tables at Krusell_Smith_VFI.m:103-116.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "wage_from_r",
    "capital_demand",
    "capital_demand_slope",
    "r_from_capital",
    "r_from_K",
    "w_from_K",
    "ks_price_tables",
]


def wage_from_r(r, alpha: float, delta: float, z=1.0):
    """w = (1-alpha) * z^(1/(1-alpha)) * (alpha/(r+delta))^(alpha/(1-alpha))
    with L=1 (Aiyagari_VFI.m:67 at the reference's z=1). Eliminating K/L
    between the two firm FOCs keeps the z^(1/(1-alpha)) factor — the channel
    a TFP path moves wages along a transition (transition/path.py). Works on
    scalars or arrays of any backend."""
    return ((1.0 - alpha) * z ** (1.0 / (1.0 - alpha))
            * (alpha / (r + delta)) ** (alpha / (1.0 - alpha)))


def capital_demand(r, labor: float, alpha: float, delta: float, z=1.0):
    """K_d(r) = labor * (alpha z/(r+delta))^(1/(1-alpha)) (Aiyagari_VFI.m:195
    at z=1)."""
    return labor * (alpha * z / (r + delta)) ** (1.0 / (1.0 - alpha))


def capital_demand_slope(r, labor: float, alpha: float, delta: float, z=1.0):
    """dK_d/dr = -K_d / ((1-alpha)(r+delta)) — the firm-side diagonal of the
    transition Newton Jacobian (transition/jacobian.py)."""
    return -capital_demand(r, labor, alpha, delta, z) / (
        (1.0 - alpha) * (r + delta))


def r_from_capital(K, labor: float, alpha: float, delta: float, z=1.0):
    """Inverse of capital_demand: the rate at which the firm demands exactly
    K — the gross marginal product (r_from_K) net of depreciation. The
    implied-rate map of the damped (Boppart-Krusell-Mitman) transition
    update."""
    return r_from_K(K, labor, z, alpha) - delta


def r_from_K(K, L, z, alpha: float):
    """Marginal product of capital r = alpha z K^(alpha-1) L^(1-alpha)
    (Krusell_Smith_VFI.m:114). Note: gross of depreciation, as in the
    reference (consumption uses r + 1 - delta)."""
    return alpha * z * K ** (alpha - 1.0) * L ** (1.0 - alpha)


def w_from_K(K, L, z, alpha: float):
    """Wage w = (1-alpha) z K^alpha L^(-alpha) (Krusell_Smith_VFI.m:113)."""
    return (1.0 - alpha) * z * K**alpha * L ** (-alpha)


def ks_price_tables(z_by_state: np.ndarray, L_by_state: np.ndarray, K_grid: np.ndarray, alpha: float):
    """Precompute w(s, K) and r(s, K) tables over the joint state and the
    aggregate-capital grid (Krusell_Smith_VFI.m:103-116).

    z_by_state/L_by_state have shape [ns]; returns (w_table, r_table) [ns, nK].
    """
    z = np.asarray(z_by_state)[:, None]
    L = np.asarray(L_by_state)[:, None]
    K = np.asarray(K_grid)[None, :]
    w = w_from_K(K, L, z, alpha)
    r = r_from_K(K, L, z, alpha)
    return w, r
