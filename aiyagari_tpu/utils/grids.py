"""Grid construction: power-spaced asset grids and derived model bounds.

Reference: quadratic-spaced 400-point Aiyagari grid (Aiyagari_VFI.m:51-58),
power-7 100-point Krusell-Smith individual grid plus 4-point aggregate grid
(Krusell_Smith_VFI.m:16-21).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from aiyagari_tpu.config import AiyagariConfig, KrusellSmithConfig

__all__ = [
    "power_grid",
    "stage_grid",
    "stage_sizes",
    "aiyagari_asset_bounds",
    "aiyagari_asset_grid",
    "ks_k_grid",
    "ks_K_grid",
]


def power_grid(lo: float, hi: float, n: int, power: float) -> np.ndarray:
    """lo + (hi-lo) * linspace(0,1,n)^power — denser near lo for power>1."""
    return lo + (hi - lo) * np.linspace(0.0, 1.0, n) ** power


@partial(jax.jit, static_argnames=("n", "lo", "hi", "power", "dtype"))
def stage_grid(n: int, lo: float, hi: float, power: float, dtype):
    """power_grid's spacing law built on device in one jitted dispatch — the
    stage-grid builder shared by the EGM and VFI multigrid ladders."""
    t = jnp.linspace(0.0, 1.0, n, dtype=dtype)
    return lo + (hi - lo) * t ** power


def stage_sizes(n_final: int, coarsest: int, refine_factor: int) -> list[int]:
    """Coarse-to-fine grid sizes for multigrid nested iteration, ending at
    n_final: [coarsest, ..., n_final//refine_factor**2, n_final//refine_factor,
    n_final]. The single source of the stage ladder shared by the EGM and
    VFI grid-sequenced solvers (solvers/egm.solve_aiyagari_egm_multiscale,
    solvers/vfi.solve_aiyagari_vfi_multiscale)."""
    if refine_factor < 2:
        # refine_factor=1 would re-insert the same size forever.
        raise ValueError(f"refine_factor must be >= 2, got {refine_factor}")
    sizes = [n_final]
    while sizes[0] > coarsest * refine_factor:
        sizes.insert(0, max(coarsest, sizes[0] // refine_factor))
    if sizes[0] > coarsest:
        sizes.insert(0, coarsest)
    return sizes


def aiyagari_asset_bounds(cfg: AiyagariConfig, s_min: float | None = None) -> tuple[float, float]:
    """Derive [amin, amax] from model parameters as the reference does.

    amin = min(b, wmin*s_min) with wmin the wage at the maximal interest rate
    r = 1/beta - 1; amax = output+undepreciated capital at the golden-rule-like
    kmax = delta^(1/(alpha-1)). Reference: Aiyagari_VFI.m:53-56. With b=0 and
    s_min>0 this gives amin=0.

    Pass the lowest normalized efficiency unit as s_min to reuse an
    already-built income discretization; otherwise it is derived here.
    """
    if cfg.grid.amin is not None and cfg.grid.amax is not None:
        return cfg.grid.amin, cfg.grid.amax
    alpha, delta, beta = cfg.technology.alpha, cfg.technology.delta, cfg.preferences.beta
    if s_min is None and cfg.grid.amin is None:
        from aiyagari_tpu.utils.markov import (
            discretize_income,
            normalized_labor,
            stationary_distribution,
        )

        l_grid, P = discretize_income(cfg.income)
        pi = stationary_distribution(P)
        s, _ = normalized_labor(l_grid, pi)
        s_min = float(s[0])
    wmin = (1 - alpha) * (alpha / ((1 / beta - 1) + delta)) ** (alpha / (1 - alpha))
    amin = min(cfg.borrowing_limit, wmin * s_min) if cfg.grid.amin is None else cfg.grid.amin
    kmax = delta ** (1.0 / (alpha - 1.0))
    amax = kmax**alpha + (1 - delta) * kmax if cfg.grid.amax is None else cfg.grid.amax
    return float(amin), float(amax)


def aiyagari_asset_grid(cfg: AiyagariConfig, s_min: float | None = None) -> np.ndarray:
    amin, amax = aiyagari_asset_bounds(cfg, s_min)
    return power_grid(amin, amax, cfg.grid.n_points, cfg.grid.power)


def ks_k_grid(cfg: KrusellSmithConfig) -> np.ndarray:
    """Individual capital grid, power-spaced with pinned endpoints
    (Krusell_Smith_VFI.m:16-17 pins k_grid(1)=k_min, k_grid(end)=k_max;
    with the lo+(hi-lo)*t^p form those already hold exactly)."""
    g = np.linspace(0.0, 1.0, cfg.k_size) ** cfg.k_power * (cfg.k_max - cfg.k_min) + cfg.k_min
    g[0], g[-1] = cfg.k_min, cfg.k_max
    return g


def ks_K_grid(cfg: KrusellSmithConfig) -> np.ndarray:
    return np.linspace(cfg.K_min, cfg.K_max, cfg.K_size)
