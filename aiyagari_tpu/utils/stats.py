"""Distributional statistics: Lorenz curves, Gini coefficients, quantile
shares, probability-normalized histograms, and a Gaussian-KDE density
(the ksdensity analogue). All device-friendly (sort/cumsum/segment ops).

Reference: Lorenz/Gini at Aiyagari_VFI.m:314-372; quintile shares at :374-410;
ksdensity plots at :245-258; histograms at :281-312.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

__all__ = [
    "lorenz_curve",
    "gini",
    "quantile_shares",
    "probability_histogram",
    "gaussian_kde",
    "weighted_lorenz_curve",
    "weighted_gini",
    "weighted_quantile_shares",
]


def lorenz_curve(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(population share, cumulative value share) of sorted x.

    Matches Aiyagari_VFI.m:317-337: cum = cumsum(sort(x))/sum(x),
    pop = (1..n)/n.
    """
    xs = jnp.sort(x.ravel())
    n = xs.shape[0]
    cum = jnp.cumsum(xs) / jnp.sum(xs)
    pop = jnp.arange(1, n + 1, dtype=xs.dtype) / n
    return pop, cum


def gini(x: jnp.ndarray) -> jnp.ndarray:
    """G = 1 - 2 * trapz(pop, cum) exactly as Aiyagari_VFI.m:340-351."""
    pop, cum = lorenz_curve(x)
    area = jnp.trapezoid(cum, pop)
    return 1.0 - 2.0 * area


def quantile_shares(x: jnp.ndarray, n_quantiles: int = 5) -> jnp.ndarray:
    """Share of total x held by each population quantile (percent).

    Matches the reference's index arithmetic (Aiyagari_VFI.m:383-403):
    boundaries at round(n*q) with sums over half-open index ranges.
    """
    xs = jnp.sort(x.ravel())
    n = xs.shape[0]
    cum = jnp.concatenate([jnp.zeros((1,), xs.dtype), jnp.cumsum(xs)])
    qs = jnp.round(n * jnp.arange(0, n_quantiles + 1) / n_quantiles).astype(jnp.int32)
    shares = (cum[qs[1:]] - cum[qs[:-1]]) / cum[-1]
    return shares * 100.0


def probability_histogram(x: jnp.ndarray, bins: int = 50, lo=None, hi=None):
    """Histogram normalized to sum to 1 ('Normalization','probability',
    Aiyagari_VFI.m:284). Returns (edges [bins+1], probs [bins])."""
    x = x.ravel()
    lo = jnp.min(x) if lo is None else lo
    hi = jnp.max(x) if hi is None else hi
    edges = jnp.linspace(lo, hi, bins + 1)
    idx = jnp.clip(jnp.searchsorted(edges, x, side="right") - 1, 0, bins - 1)
    counts = jnp.zeros((bins,), x.dtype).at[idx].add(1.0)
    return edges, counts / x.shape[0]


def weighted_lorenz_curve(x: jnp.ndarray, w: jnp.ndarray):
    """Lorenz curve of a weighted sample / gridded distribution: (cumulative
    population share, cumulative value share), sorted by value, prepended
    with the (0, 0) origin. Used with the non-stochastic distribution
    (sim/distribution.py), where each gridpoint carries a probability mass —
    the reference's sample-based Lorenz (Aiyagari_VFI.m:317-337) is the
    uniform-weight special case.
    """
    x, w = x.ravel(), w.ravel()
    order = jnp.argsort(x)
    xs, ws = x[order], w[order]
    zero = jnp.zeros((1,), xs.dtype)
    pop = jnp.concatenate([zero, jnp.cumsum(ws)])
    pop = pop / pop[-1]
    cum = jnp.concatenate([zero, jnp.cumsum(ws * xs)])
    cum = cum / cum[-1]
    return pop, cum


def weighted_gini(x: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    """Gini of a weighted sample: 1 - 2 * area under the weighted Lorenz curve."""
    pop, cum = weighted_lorenz_curve(x, w)
    area = jnp.trapezoid(cum, pop)
    return 1.0 - 2.0 * area


def weighted_quantile_shares(x: jnp.ndarray, w: jnp.ndarray,
                             n_quantiles: int = 5) -> jnp.ndarray:
    """Share of total x held by each population quantile (percent), for a
    weighted sample. Quantile boundaries fall at cumulative-weight cutoffs
    q/n_quantiles; the gridpoint straddling a boundary is split between the
    adjacent quantiles in proportion to its mass (the lottery analogue of the
    reference's round(n*q) index arithmetic, Aiyagari_VFI.m:383-403)."""
    pop, cum = weighted_lorenz_curve(x, w)
    qs = jnp.arange(0, n_quantiles + 1, dtype=pop.dtype) / n_quantiles
    cum_at_q = jnp.interp(qs, pop, cum)
    return (cum_at_q[1:] - cum_at_q[:-1]) * 100.0


def gaussian_kde(x: jnp.ndarray, n_points: int = 100, bandwidth=None, weights=None):
    """Gaussian kernel density on an evenly spaced evaluation grid —
    the MATLAB ksdensity analogue (Aiyagari_VFI.m:247-251: normal kernel,
    100 points, normal-reference-rule bandwidth).

    With `weights` (same shape as x, any positive scale), each point
    contributes its probability mass — used for gridded distributions from
    sim/distribution.py; the bandwidth rule then uses Kish's effective sample
    size in place of n. Returns (xi [n_points], f [n_points]) with f a
    proper density.
    """
    x = x.ravel()
    if weights is None:
        n_eff = x.shape[0]
        wn = jnp.full(x.shape, 1.0 / x.shape[0], x.dtype)
        std = jnp.std(x, ddof=1)
        q75, q25 = jnp.quantile(x, 0.75), jnp.quantile(x, 0.25)
    else:
        wn = weights.ravel() / jnp.sum(weights)
        n_eff = 1.0 / jnp.sum(wn**2)
        mean = jnp.sum(wn * x)
        std = jnp.sqrt(jnp.sum(wn * (x - mean) ** 2) * n_eff / jnp.maximum(n_eff - 1.0, 1.0))
        order = jnp.argsort(x)
        cum = jnp.cumsum(wn[order])
        q25 = jnp.interp(0.25, cum, x[order])
        q75 = jnp.interp(0.75, cum, x[order])
    iqr = q75 - q25
    sig = jnp.minimum(std, iqr / 1.349)
    # MATLAB's default: Silverman's normal reference rule.
    h = sig * (4.0 / (3.0 * n_eff)) ** 0.2 if bandwidth is None else bandwidth
    lo = jnp.min(x) - 3.0 * h
    hi = jnp.max(x) + 3.0 * h
    xi = jnp.linspace(lo, hi, n_points)
    z = (xi[:, None] - x[None, :]) / h
    f = (jnp.exp(-0.5 * z**2) * wn[None, :]).sum(axis=1) / (h * jnp.sqrt(2.0 * jnp.pi))
    return xi, f
