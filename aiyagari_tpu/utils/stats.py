"""Distributional statistics: Lorenz curves, Gini coefficients, quantile
shares, probability-normalized histograms, and a Gaussian-KDE density
(the ksdensity analogue). All device-friendly (sort/cumsum/segment ops).

Reference: Lorenz/Gini at Aiyagari_VFI.m:314-372; quintile shares at :374-410;
ksdensity plots at :245-258; histograms at :281-312.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

__all__ = [
    "lorenz_curve",
    "gini",
    "quantile_shares",
    "probability_histogram",
    "gaussian_kde",
]


def lorenz_curve(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(population share, cumulative value share) of sorted x.

    Matches Aiyagari_VFI.m:317-337: cum = cumsum(sort(x))/sum(x),
    pop = (1..n)/n.
    """
    xs = jnp.sort(x.ravel())
    n = xs.shape[0]
    cum = jnp.cumsum(xs) / jnp.sum(xs)
    pop = jnp.arange(1, n + 1, dtype=xs.dtype) / n
    return pop, cum


def gini(x: jnp.ndarray) -> jnp.ndarray:
    """G = 1 - 2 * trapz(pop, cum) exactly as Aiyagari_VFI.m:340-351."""
    pop, cum = lorenz_curve(x)
    area = jnp.trapezoid(cum, pop)
    return 1.0 - 2.0 * area


def quantile_shares(x: jnp.ndarray, n_quantiles: int = 5) -> jnp.ndarray:
    """Share of total x held by each population quantile (percent).

    Matches the reference's index arithmetic (Aiyagari_VFI.m:383-403):
    boundaries at round(n*q) with sums over half-open index ranges.
    """
    xs = jnp.sort(x.ravel())
    n = xs.shape[0]
    cum = jnp.concatenate([jnp.zeros((1,), xs.dtype), jnp.cumsum(xs)])
    qs = jnp.round(n * jnp.arange(0, n_quantiles + 1) / n_quantiles).astype(jnp.int32)
    shares = (cum[qs[1:]] - cum[qs[:-1]]) / cum[-1]
    return shares * 100.0


def probability_histogram(x: jnp.ndarray, bins: int = 50, lo=None, hi=None):
    """Histogram normalized to sum to 1 ('Normalization','probability',
    Aiyagari_VFI.m:284). Returns (edges [bins+1], probs [bins])."""
    x = x.ravel()
    lo = jnp.min(x) if lo is None else lo
    hi = jnp.max(x) if hi is None else hi
    edges = jnp.linspace(lo, hi, bins + 1)
    idx = jnp.clip(jnp.searchsorted(edges, x, side="right") - 1, 0, bins - 1)
    counts = jnp.zeros((bins,), x.dtype).at[idx].add(1.0)
    return edges, counts / x.shape[0]


def gaussian_kde(x: jnp.ndarray, n_points: int = 100, bandwidth=None):
    """Gaussian kernel density on an evenly spaced evaluation grid —
    the MATLAB ksdensity analogue (Aiyagari_VFI.m:247-251: normal kernel,
    100 points, normal-reference-rule bandwidth).

    Returns (xi [n_points], f [n_points]) with f a proper density.
    """
    x = x.ravel()
    n = x.shape[0]
    std = jnp.std(x, ddof=1)
    iqr = jnp.quantile(x, 0.75) - jnp.quantile(x, 0.25)
    sig = jnp.minimum(std, iqr / 1.349)
    # MATLAB's default: Silverman's normal reference rule.
    h = sig * (4.0 / (3.0 * n)) ** 0.2 if bandwidth is None else bandwidth
    lo = jnp.min(x) - 3.0 * h
    hi = jnp.max(x) + 3.0 * h
    xi = jnp.linspace(lo, hi, n_points)
    z = (xi[:, None] - x[None, :]) / h
    f = jnp.exp(-0.5 * z**2).sum(axis=1) / (n * h * jnp.sqrt(2.0 * jnp.pi))
    return xi, f
