"""Solution-accuracy diagnostics: off-grid Euler-equation errors.

The literature's standard check (Judd 1992; Den Haan 2010 for K-S): evaluate
the converged policies *between* gridpoints and measure how far the
intertemporal first-order condition u'(c) = beta (1+r) E[u'(c')] is from
holding, in consumption units, log10 scale. The reference has no accuracy
metric at all beyond eyeballing plots (SURVEY.md §4); here the residuals are
a jitted device computation reported alongside the equilibrium statistics.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from aiyagari_tpu.ops.interp import linear_interp
from aiyagari_tpu.utils.utility import crra_marginal

__all__ = ["alm_dynamic_path_error", "euler_equation_errors"]


def alm_dynamic_path_error(K_ts, z_path, B, discard: int = 100):
    """Den Haan (2010) dynamic-forecast accuracy of a fitted ALM: iterate
    the law of motion K_{t+1} = exp(b0(z_t) + b1(z_t) log K_t) from the
    TRUE path's starting point WITHOUT ever re-anchoring on the realized
    path, and compare against the realized K_ts. This is the statistic
    that certifies the R^2 headline — a one-step R^2 near 1 can coexist
    with a drifting dynamic forecast along a near-unit-root ridge, and the
    multi-step error is what reveals it (the fine-grid identification
    caveat, BENCHMARKS.md). Mirrors compute_approxKprime
    (Krusell_Smith_VFI.m:367-375); shared by io_utils/report.

    Returns (max_rel_error, mean_rel_error) over t > discard."""
    import numpy as np

    K_ts = np.asarray(K_ts, np.float64)
    z = np.asarray(z_path)
    B = np.asarray(B, np.float64)
    K_approx = np.empty_like(K_ts)
    K_approx[discard] = K_ts[discard]
    for t in range(discard, len(K_ts) - 1):
        b0, b1 = (B[0], B[1]) if z[t] == 0 else (B[2], B[3])
        K_approx[t + 1] = np.exp(b0 + b1 * np.log(K_approx[t]))
    err = np.abs(K_approx[discard + 1:] - K_ts[discard + 1:]) / K_ts[discard + 1:]
    return float(err.max()), float(err.mean())


@partial(jax.jit, static_argnames=("sigma", "beta"))
def euler_equation_errors(policy_c, policy_k, a_grid, s, P, r, w, amin, *,
                          sigma: float, beta: float):
    """Unit-free Euler residuals at asset-grid midpoints.

    Returns (log10_errors [N, na-1], unconstrained_mask [N, na-1]) where the
    error is |1 - u'^{-1}(beta (1+r) E[u'(c')]) / c| (consumption-equivalent
    relative error; Judd's E_EE) and the mask marks points where the
    borrowing constraint is slack (a' > amin), the only points at which the
    Euler equation must hold with equality.
    """
    mid = 0.5 * (a_grid[:-1] + a_grid[1:])                       # [na-1]

    c_mid = jax.vmap(lambda row: linear_interp(a_grid, row, mid))(policy_c)
    k_mid = jax.vmap(lambda row: linear_interp(a_grid, row, mid))(policy_k)

    # Next-period consumption at a' = k_mid for EVERY income state m: [N, N, na-1].
    cp = jax.vmap(
        lambda k_row: jax.vmap(lambda crow: linear_interp(a_grid, crow, k_row))(policy_c)
    )(k_mid)
    emu = jnp.einsum("im,imj->ij", P, crra_marginal(cp, sigma))  # [N, na-1]
    c_implied = (beta * (1.0 + r) * emu) ** (-1.0 / sigma)
    err = jnp.abs(1.0 - c_implied / jnp.maximum(c_mid, 1e-300))
    log10_err = jnp.log10(jnp.maximum(err, 1e-16))
    unconstrained = k_mid > amin + 1e-8
    return log10_err, unconstrained
