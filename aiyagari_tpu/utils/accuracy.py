"""Solution-accuracy diagnostics: off-grid Euler-equation errors.

The literature's standard check (Judd 1992; Den Haan 2010 for K-S): evaluate
the converged policies *between* gridpoints and measure how far the
intertemporal first-order condition u'(c) = beta (1+r) E[u'(c')] is from
holding, in consumption units, log10 scale. The reference has no accuracy
metric at all beyond eyeballing plots (SURVEY.md §4); here the residuals are
a jitted device computation reported alongside the equilibrium statistics.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from aiyagari_tpu.ops.interp import linear_interp
from aiyagari_tpu.utils.utility import crra_marginal

__all__ = ["euler_equation_errors"]


@partial(jax.jit, static_argnames=("sigma", "beta"))
def euler_equation_errors(policy_c, policy_k, a_grid, s, P, r, w, amin, *,
                          sigma: float, beta: float):
    """Unit-free Euler residuals at asset-grid midpoints.

    Returns (log10_errors [N, na-1], unconstrained_mask [N, na-1]) where the
    error is |1 - u'^{-1}(beta (1+r) E[u'(c')]) / c| (consumption-equivalent
    relative error; Judd's E_EE) and the mask marks points where the
    borrowing constraint is slack (a' > amin), the only points at which the
    Euler equation must hold with equality.
    """
    mid = 0.5 * (a_grid[:-1] + a_grid[1:])                       # [na-1]

    c_mid = jax.vmap(lambda row: linear_interp(a_grid, row, mid))(policy_c)
    k_mid = jax.vmap(lambda row: linear_interp(a_grid, row, mid))(policy_k)

    # Next-period consumption at a' = k_mid for EVERY income state m: [N, N, na-1].
    cp = jax.vmap(
        lambda k_row: jax.vmap(lambda crow: linear_interp(a_grid, crow, k_row))(policy_c)
    )(k_mid)
    emu = jnp.einsum("im,imj->ij", P, crra_marginal(cp, sigma))  # [N, na-1]
    c_implied = (beta * (1.0 + r) * emu) ** (-1.0 / sigma)
    err = jnp.abs(1.0 - c_implied / jnp.maximum(c_mid, 1e-300))
    log10_err = jnp.log10(jnp.maximum(err, 1e-16))
    unconstrained = k_mid > amin + 1e-8
    return log10_err, unconstrained
