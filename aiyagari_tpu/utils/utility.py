"""CRRA utility, marginal utility and inverses, labor disutility and the
intratemporal first-order condition — all written dtype-generically so they
jit/vmap on device (jnp) and also accept NumPy arrays for the reference backend.

Reference: CRRA with log special case at Aiyagari_VFI.m:74-78; EGM marginal
utility handles at Aiyagari_EGM.m:67-69; labor disutility and its inverse at
Aiyagari_Endogenous_Labor_EGM.m:59-62.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = [
    "crra_utility",
    "crra_marginal",
    "crra_marginal_inverse",
    "labor_disutility",
    "labor_marginal_disutility",
    "labor_foc_inverse",
]


def crra_utility(c, sigma):
    """u(c) = (c^(1-sigma)-1)/(1-sigma), log(c) at sigma==1 (Aiyagari_VFI.m:74-78).

    sigma may be a Python float (the branch resolves at trace time — the
    historical contract) or a traced scalar (the batched-GE / scenario-sweep
    kernels, where sigma varies across a vmapped batch): the traced form
    selects the log case with jnp.where, guarding the generic power form
    against the 0/0 it would produce exactly at sigma == 1.
    """
    if isinstance(sigma, (int, float)):
        if sigma == 1.0:
            return jnp.log(c)
        return (c ** (1.0 - sigma) - 1.0) / (1.0 - sigma)
    is_log = sigma == 1.0
    safe = jnp.where(is_log, 2.0, sigma)
    return jnp.where(is_log, jnp.log(c),
                     (c ** (1.0 - safe) - 1.0) / (1.0 - safe))


def crra_marginal(c, sigma: float):
    """u'(c) = c^(-sigma) (Aiyagari_EGM.m:68)."""
    return c ** (-sigma)


def crra_marginal_inverse(up, sigma: float):
    """(u')^{-1}(x) = x^(-1/sigma) (Aiyagari_EGM.m:69)."""
    return up ** (-1.0 / sigma)


def labor_disutility(l, psi: float, eta: float):
    """v(l) = psi * l^(1+eta)/(1+eta) (Aiyagari_Endogenous_Labor_VFI.m:96)."""
    return psi * l ** (1.0 + eta) / (1.0 + eta)


def labor_marginal_disutility(l, psi: float, eta: float):
    """v'(l) = psi * l^eta (Aiyagari_Endogenous_Labor_EGM.m:61)."""
    return psi * l**eta


def labor_foc_inverse(x, psi: float, eta: float):
    """(v')^{-1}(x) = (x/psi)^(1/eta): the closed-form intratemporal FOC
    l = (w*s*u'(c)/psi)^(1/eta) used by endogenous-labor EGM
    (Aiyagari_Endogenous_Labor_EGM.m:62,86)."""
    return (x / psi) ** (1.0 / eta)
