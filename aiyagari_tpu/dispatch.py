"""The solve(model, method, backend) dispatch boundary (BASELINE.json's north
star): one entry point routing on model family, solution method, and execution
backend.

  solve(AiyagariConfig(...), method="vfi", backend="jax")   -> EquilibriumResult
  solve(AiyagariConfig(...), method="egm", backend="numpy") -> EquilibriumResult
  solve(KrusellSmithConfig(...), method="vfi")              -> KSResult

The "numpy" backend is the framework's own CPU reference implementation — the
measured baseline denominator (BASELINE.md: the reference publishes no
numbers, so speedups are reported against this at the reference's scales).
"""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp

from aiyagari_tpu.diagnostics.errors import enforce_convergence

from aiyagari_tpu.config import (
    ALMConfig,
    AiyagariConfig,
    BackendConfig,
    EquilibriumConfig,
    KrusellSmithConfig,
    SimConfig,
    SolverConfig,
)

__all__ = ["solve"]


def _dtype_of(backend: BackendConfig):
    return jnp.float64 if backend.dtype == "float64" else jnp.float32


def solve(
    model: Union[AiyagariConfig, KrusellSmithConfig],
    *,
    method: Optional[str] = None,
    backend: Union[str, BackendConfig] = "jax",
    solver: Optional[SolverConfig] = None,
    sim: Optional[SimConfig] = None,
    equilibrium: Optional[EquilibriumConfig] = None,
    alm: Optional[ALMConfig] = None,
    aggregation: str = "simulation",
    on_nonconvergence: str = "warn",
):
    """Solve a full model to general equilibrium.

    Aiyagari family -> interest-rate bisection (EquilibriumResult).
    Krusell-Smith   -> aggregate-law-of-motion fixed point (KSResult).

    `on_nonconvergence` is the outer-loop iteration-cap policy (SURVEY.md
    §5.3): "warn" (default — emit ConvergenceWarning and return the last
    iterate, the reference's behavior at Aiyagari_EGM.m:112-116, made typed),
    "raise" (ConvergenceError carrying the last distance), or "ignore".

    The solution method comes from `method` or `solver.method`; passing both
    with different values is an error (never silently overridden). With
    neither, the default is "vfi". When `solver` is omitted, each model
    family supplies its own reference-faithful solver defaults (e.g. the
    Krusell-Smith tolerances/Howard schedule of Krusell_Smith_VFI.m:12-13).

    `aggregation` selects how the cross-section is aggregated: "simulation"
    (the reference's Monte-Carlo household panel — time average for Aiyagari,
    Aiyagari_VFI.m:94-129; agent panel for Krusell-Smith, Krusell_Smith_VFI.m:
    222-248) or "distribution" (deterministic Young histogram — stationary
    distribution for Aiyagari, sim/distribution.py; distribution path along
    the aggregate shocks for Krusell-Smith, sim/ks_distribution.py — jax
    backend only).
    """
    if isinstance(backend, str):
        backend = BackendConfig(backend=backend)
    if backend.backend not in ("jax", "numpy"):
        raise ValueError(
            f"unknown backend {backend.backend!r}; expected 'jax' or 'numpy'"
        )
    if solver is not None and method is not None and solver.method != method:
        raise ValueError(
            f"conflicting methods: method={method!r} but solver.method={solver.method!r}"
        )
    method = method or (solver.method if solver is not None else "vfi")
    if method not in ("vfi", "egm"):
        raise ValueError(f"unknown method {method!r}; expected 'vfi' or 'egm'")

    if aggregation not in ("simulation", "distribution"):
        raise ValueError(
            f"unknown aggregation {aggregation!r}; expected 'simulation' or 'distribution'"
        )
    if on_nonconvergence not in ("ignore", "warn", "raise"):
        raise ValueError(
            f"unknown on_nonconvergence {on_nonconvergence!r}; "
            "expected 'ignore', 'warn', or 'raise'"
        )

    if isinstance(model, AiyagariConfig):
        if backend.dtype == "mixed":
            raise ValueError(
                "dtype='mixed' applies to the Krusell-Smith outer loop only; "
                "Aiyagari solves converge natively in f32 (test_precision)"
            )
        solver = solver or SolverConfig(method=method)
        sim = sim or SimConfig()
        equilibrium = equilibrium or EquilibriumConfig()
        if backend.backend == "numpy":
            if aggregation != "simulation":
                raise ValueError("aggregation='distribution' requires backend='jax'")
            from aiyagari_tpu.solvers.numpy_backend import solve_equilibrium_numpy

            result = solve_equilibrium_numpy(model, solver=solver, sim=sim, eq=equilibrium)
        else:
            from aiyagari_tpu.config import precision_scope
            from aiyagari_tpu.equilibrium.bisection import (
                solve_equilibrium,
                solve_equilibrium_distribution,
            )
            from aiyagari_tpu.models.aiyagari import AiyagariModel

            # Honor dtype="float64" even when global x64 is off (see
            # precision_scope — without it the request silently truncates).
            # Grid-axis mesh (BackendConfig.mesh_axes containing "grid"):
            # the EGM household solves run DISTRIBUTED with the knots
            # ring-redistributed across the mesh (solvers/egm_sharded.py).
            mesh = None
            if "grid" in backend.mesh_axes:
                from aiyagari_tpu.parallel.mesh import make_mesh

                mesh = make_mesh(backend.mesh_axes, backend.mesh_shape or None)
            with precision_scope(backend.dtype):
                m = AiyagariModel.from_config(model, dtype=_dtype_of(backend))
                if aggregation == "distribution":
                    result = solve_equilibrium_distribution(
                        m, solver=solver, eq=equilibrium, mesh=mesh)
                else:
                    result = solve_equilibrium(
                        m, solver=solver, sim=sim, eq=equilibrium, mesh=mesh)
        gap = (
            abs(result.k_supply[-1] - result.k_demand[-1])
            if result.k_supply else float("inf")
        )
        enforce_convergence(
            result.converged, on_nonconvergence, "Aiyagari GE bisection",
            # the numpy-backend result has no iterations field; its bisection
            # history is one entry per outer iteration
            iterations=getattr(result, "iterations", len(result.r_history)),
            distance=gap, tol=equilibrium.tol, detail={"r": result.r},
        )
        return result

    if isinstance(model, KrusellSmithConfig):
        if aggregation == "distribution" and backend.backend != "jax":
            raise ValueError("aggregation='distribution' requires backend='jax'")
        alm = alm or ALMConfig()
        from aiyagari_tpu.equilibrium.alm import solve_krusell_smith

        # solver=None lets the KS loop apply its own reference defaults
        # (tol 1e-6, Howard 50/improve-every-5) rather than the generic ones.
        # aggregation="distribution" advances the cross-section as a Young
        # histogram along the aggregate path (sim/ks_distribution.py) instead
        # of the reference's Monte-Carlo agent panel.
        result = solve_krusell_smith(
            model, method=method, solver=solver, alm=alm, backend=backend,
            closure=("histogram" if aggregation == "distribution" else "panel"),
        )
        enforce_convergence(
            result.converged, on_nonconvergence, "Krusell-Smith ALM fixed point",
            iterations=result.iterations, distance=result.diff_B, tol=alm.tol,
            detail={"B": [round(float(b), 6) for b in result.B]},
        )
        return result

    raise TypeError(f"unknown model config type: {type(model).__name__}")
