"""The solve(model, method, backend) dispatch boundary (BASELINE.json's north
star): one entry point routing on model family, solution method, and execution
backend.

  solve(AiyagariConfig(...), method="vfi", backend="jax")   -> EquilibriumResult
  solve(AiyagariConfig(...), method="egm", backend="numpy") -> EquilibriumResult
  solve(KrusellSmithConfig(...), method="vfi")              -> KSResult

The "numpy" backend is the framework's own CPU reference implementation — the
measured baseline denominator (BASELINE.md: the reference publishes no
numbers, so speedups are reported against this at the reference's scales).
"""

from __future__ import annotations

import contextlib
import dataclasses
import itertools
from typing import Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np

from aiyagari_tpu.diagnostics.errors import enforce_convergence

from aiyagari_tpu.config import (
    ALMConfig,
    AiyagariConfig,
    BackendConfig,
    EquilibriumConfig,
    KrusellSmithConfig,
    MITShock,
    SimConfig,
    SolverConfig,
    TransitionConfig,
)

__all__ = ["CalibrationResult", "calibrate", "solve", "sweep",
           "solve_transition", "sweep_transitions"]


def _as_ledger(ledger, *configs, entry: str):
    """Resolve the `ledger` argument: None (off), a RunLedger (used as-is),
    or a path (a fresh RunLedger is opened there with the configs'
    fingerprint as its run_start event)."""
    if ledger is None:
        return None
    from aiyagari_tpu.diagnostics.ledger import RunLedger

    if isinstance(ledger, RunLedger):
        return ledger
    return RunLedger(ledger, config=[c for c in configs if c is not None],
                     meta={"entry": entry})


@contextlib.contextmanager
def _observe(led, name: str, **attrs):
    """The entry-point observability scope: the run ledger becomes the
    ACTIVE ledger (async degradation events — push-forward fallbacks —
    route to it), and the whole solve runs under a named wall-clock span
    (diagnostics/trace.py, device-profiler-annotated) written to the ledger
    on exit. A no-op shell when led is None except the span annotation.

    Spans flush in a finally: a solve that RAISES is exactly the run the
    flight record exists to explain, so its wall-clock spans (trace.span
    completes the record on unwind) and an "error" event land in the
    ledger before the exception propagates."""
    from aiyagari_tpu.diagnostics.ledger import activate
    from aiyagari_tpu.diagnostics.trace import collect_spans, span

    run_id = led.run_id if led is not None else None
    with activate(led), collect_spans(run_id=run_id) as spans:
        try:
            with span(name, **attrs) as rec:
                yield rec
        except BaseException as e:
            if led is not None:
                led.event("error", context=name, error_type=type(e).__name__,
                          error=str(e)[:500])
            raise
        finally:
            if led is not None:
                for s in spans:
                    led.span(s)


def _ledger_result(led, context: str, result, *, converged, iterations,
                   distance, tol) -> None:
    """Write the solve's verdict + every flight-record summary it carries."""
    if led is None:
        return
    led.verdict(context, converged=converged, iterations=iterations,
                distance=distance, tol=tol)
    sol = getattr(result, "solution", None)
    for name, tele in (
        ("outer", getattr(result, "telemetry", None)),
        ("household", getattr(sol, "telemetry", None) if sol is not None
         else None),
        ("distribution", getattr(result, "dist_telemetry", None)),
    ):
        if tele is None:
            continue
        try:
            led.telemetry(name, tele)
        except ValueError:
            # Batched recorders ([S]-leading leaves) have no single summary;
            # the full buffers stay on the result for per-scenario reads.
            pass


def _dtype_of(backend: BackendConfig):
    # "mixed" builds the model in f64: the ladder's polish stage is the
    # certified reference dtype, and the hot stages cast DOWN per stage
    # (ops/precision.py) — building in f32 would clamp the whole ladder.
    return jnp.float64 if backend.dtype in ("float64", "mixed") else jnp.float32


def _with_ladder(solver: Optional[SolverConfig], method: str,
                 backend: BackendConfig) -> SolverConfig:
    """Resolve the solver config's precision ladder against the backend
    dtype: dtype="mixed" injects the default ladder (ops/precision.
    ladder_for_dtype, the single owner of that mapping) unless the caller
    already set SolverConfig.ladder explicitly."""
    from aiyagari_tpu.ops.precision import ladder_for_dtype

    from aiyagari_tpu.ops.egm import resolve_egm_kernel
    from aiyagari_tpu.ops.pushforward import resolve_backend

    solver = solver or SolverConfig(method=method)
    # Reject DistributionBackend / EGM-kernel typos HERE, before any
    # compile: both knobs are jit static args deep inside the closures,
    # where an unknown name would otherwise surface as a mid-solve trace
    # error.
    resolve_backend(solver.pushforward)
    resolve_egm_kernel(solver.egm_kernel)
    if solver.ladder is None:
        ladder = ladder_for_dtype(backend.dtype)
        if ladder is not None:
            solver = dataclasses.replace(solver, ladder=ladder)
    return solver


# Route-resolution memo: (pf_in, ek_in, na, dtype, egm, batched,
# tuning-active, tuning-cache stamp) -> (pf, ek, captured decisions).
# Process-lifetime by design — entries are invalidated by the stamp
# moving, and the key space is tiny (route knobs x geometries).
_route_memo: dict = {}


def _resolve_routes(solver: Optional[SolverConfig], *,
                    na: Optional[int] = None, dtype=None,
                    egm: bool = True,
                    batched: bool = False) -> Optional[SolverConfig]:
    """Resolve the contested route knobs ("auto" pushforward /
    egm_kernel / searchsorted method) at the dispatch boundary, INSIDE
    the _observe scope, so every solve/sweep run records exactly one
    `route_decision` ledger event per knob (tuning/autotuner.py dedupes
    per activation) — jit caching makes the deep trace-time resolutions
    unreliable as a per-run record (a cache-hit run never re-traces).
    `na`/`dtype` are the run's OWN grid size and solve dtype: the
    boundary and the deep trace-time resolvers then consult the same
    tuning-cache cell, so the recorded decision is the executed one.

    With tuning ACTIVE the resolved concrete routes are threaded back
    into the SolverConfig, so the jit static args key on the measured
    choice instead of the literal "auto" (a mid-process cache refresh can
    then never serve a stale "auto"-keyed executable). A None solver
    cannot carry the threading — its runs still record decisions, and the
    deep resolvers reach the same choice from the same cache, but a
    mid-process cache refresh can leave an already-compiled "auto"-keyed
    executable on the old route (the staleness caveat the threading
    exists to remove; pass a SolverConfig to get it). With tuning off
    the config is returned untouched — the exact historical object, same
    jit keys, bit-identical programs (the PR 6 zero-cost discipline
    applied to decisions; pinned by tests/test_tuning.py).

    egm=False skips the egm_kernel knob (the endogenous-labor family
    routes through require_xla_egm_kernel, a constraint rather than a
    decision — a measured fused-route winner must not be recorded, let
    alone applied, for a chain the fused kernel does not implement).

    batched=True is the vmapped-program context (sweeps and the batched
    GE): the push-forward decision then goes through resolve_backend's
    batched split — scatter on CPU hosts, where the transpose route's
    gathers batch catastrophically under vmap (measured, ISSUE 15) — so
    the recorded decision matches what the round programs execute
    (equilibrium/batched._ge_round_program resolves with the same
    context). The resolved route is deliberately NOT threaded back into
    the SolverConfig here: the deep resolver applies the identical
    context-aware default, and threading a batched-only route into a
    config that may also drive serial re-solves (quarantine rescue)
    would pin the wrong route there.

    Resolutions are MEMOIZED per (route-relevant config fingerprint,
    tuning-cache stamp): repeated serve requests hitting the same
    geometry stop re-reading the tuning cache and re-walking the
    resolver chain on every call (ISSUE 18 satellite). A memo hit
    REPLAYS the captured decisions through the autotuner's recorder, so
    each activation scope still carries exactly one route_decision event
    per knob; a probe run that rewrites the cache moves the stamp and
    invalidates the memo."""
    from aiyagari_tpu.tuning.autotuner import (
        capture_decisions,
        replay_decisions,
        tuning_active,
        tuning_cache_stamp,
    )

    pf_in = solver.pushforward if solver is not None else "auto"
    ek_in = solver.egm_kernel if solver is not None else "auto"
    active = tuning_active()
    key = (pf_in, ek_in, na, None if dtype is None else str(np.dtype(dtype)),
           egm, batched, active, tuning_cache_stamp() if active else None)
    hit = _route_memo.get(key)
    if hit is not None:
        pf, ek, decisions = hit
        replay_decisions(decisions)
    else:
        from aiyagari_tpu.ops.egm import resolve_egm_kernel
        from aiyagari_tpu.ops.interp import searchsorted_method
        from aiyagari_tpu.ops.pushforward import resolve_backend

        with capture_decisions() as decisions:
            pf = resolve_backend(pf_in, na=na, dtype=dtype, batched=batched)
            ek = (resolve_egm_kernel(ek_in, na=na, dtype=dtype)
                  if egm else ek_in)
            # The searchsorted split has no SolverConfig knob but every
            # push-forward plan build exercises it (_segment_bounds):
            # resolving it here records the run's decision even when jit
            # caching skips the trace-time resolver.
            searchsorted_method(na)
        _route_memo[key] = (pf, ek, tuple(decisions))
    if (solver is not None and active and not batched
            and (pf, ek) != (pf_in, ek_in)):
        solver = dataclasses.replace(solver, pushforward=pf, egm_kernel=ek)
    return solver


def _observe_mesh(m, led, *, entry: str) -> None:
    """The mesh-activation flight record (ISSUE 13 satellite, the PR 12
    route_decision pattern applied to placement): one `mesh_topology`
    ledger event naming every axis and its size plus the process topology,
    and an aiyagari_mesh_axis_size{axis=} gauge per axis — so a sweep's
    artifact says WHAT topology ran, not just how fast. Rendered by
    `python -m aiyagari_tpu report`."""
    import jax

    from aiyagari_tpu.diagnostics import metrics

    axes = {name: int(m.shape[name]) for name in m.axis_names}
    for name, size in axes.items():
        metrics.gauge("aiyagari_mesh_axis_size", axis=name).set(size)
    if led is not None:
        led.event("mesh_topology", entry=entry, axes=axes,
                  devices=int(m.devices.size),
                  processes=int(jax.process_count()))


def _sweep_mesh(backend: BackendConfig, mesh, led, *, entry: str):
    """Resolve the sweep entry points' device mesh. `mesh` is the new 2-D
    knob: a MeshConfig requesting a (scenarios x grid) mesh
    (parallel/mesh.make_mesh_2d; placement through the partition-rule
    matcher downstream) — validated loudly here, at the dispatch boundary.
    Without it, the legacy 1-D BackendConfig.mesh_axes path is untouched,
    and mesh=None with no mesh_axes builds nothing: the default is today's
    behavior bit-identical (no mesh object, no event, same programs)."""
    from aiyagari_tpu.config import MeshConfig

    if mesh is not None:
        if not isinstance(mesh, MeshConfig):
            raise TypeError(
                f"mesh must be a MeshConfig (or None), got "
                f"{type(mesh).__name__}")
        if backend.backend != "jax":
            raise ValueError("mesh=MeshConfig(...) requires backend='jax'")
        if backend.mesh_axes:
            raise ValueError(
                "pass either mesh=MeshConfig(...) or BackendConfig."
                "mesh_axes, not both (the MeshConfig owns both axes)")
        from aiyagari_tpu.parallel.mesh import make_mesh_2d

        m = make_mesh_2d(scenarios=mesh.scenarios, grid=mesh.grid)
        _observe_mesh(m, led, entry=entry)
        return m
    if "scenarios" in backend.mesh_axes:
        from aiyagari_tpu.parallel.mesh import make_mesh

        m = make_mesh(backend.mesh_axes, backend.mesh_shape or None)
        _observe_mesh(m, led, entry=entry)
        return m
    return None


def _probe_skew(m, mesh_cfg, led, *, price: Optional[dict] = None) -> None:
    """The pod observatory's mesh rendezvous probe (ISSUE 14): when the
    activated MeshConfig asked for it, time one fenced per-axis barrier
    probe HERE — at the dispatch boundary, once per mesh activation, never
    inside the solve loop (DESIGN.md "Why skew probes live at the dispatch
    boundary") — emitting `host_skew` ledger events, per-axis
    aiyagari_host_skew_seconds gauges, a straggler verdict, and (when the
    sweep's sizes are known) the reconciliation row against
    roofline.mesh2d_collective_cost. Runs INSIDE the _observe scope so the
    events carry the run's id."""
    from aiyagari_tpu.config import MeshConfig

    if m is None or not isinstance(mesh_cfg, MeshConfig) \
            or not mesh_cfg.skew_probe:
        return
    from aiyagari_tpu.diagnostics.skew import probe_mesh_skew

    if price is not None:
        price = {**price, "scenarios": int(m.shape["scenarios"]),
                 "grid": int(m.shape["grid"])}
    probe_mesh_skew(m, price=price, ledger=led)


def _resolve_rescue(rescue):
    """Normalize the `rescue` argument: None (off), True (the default
    ladder), or a RescueConfig."""
    if rescue is None or rescue is False:
        return None
    from aiyagari_tpu.config import RescueConfig

    if rescue is True:
        return RescueConfig()
    if not isinstance(rescue, RescueConfig):
        raise TypeError(
            f"rescue must be a RescueConfig (or True/None), got "
            f"{type(rescue).__name__}")
    return rescue


def solve(
    model: Union[AiyagariConfig, KrusellSmithConfig],
    *,
    method: Optional[str] = None,
    backend: Union[str, BackendConfig] = "jax",
    solver: Optional[SolverConfig] = None,
    sim: Optional[SimConfig] = None,
    equilibrium: Optional[EquilibriumConfig] = None,
    alm: Optional[ALMConfig] = None,
    aggregation: str = "simulation",
    on_nonconvergence: str = "warn",
    ledger=None,
    rescue=None,
    warm_start=None,
):
    """Solve a full model to general equilibrium.

    Aiyagari family -> interest-rate bisection (EquilibriumResult).
    Krusell-Smith   -> aggregate-law-of-motion fixed point (KSResult).

    `on_nonconvergence` is the outer-loop iteration-cap policy (SURVEY.md
    §5.3): "warn" (default — emit ConvergenceWarning and return the last
    iterate, the reference's behavior at Aiyagari_EGM.m:112-116, made typed),
    "raise" (ConvergenceError carrying the last distance), or "ignore".

    The solution method comes from `method` or `solver.method`; passing both
    with different values is an error (never silently overridden). With
    neither, the default is "vfi". When `solver` is omitted, each model
    family supplies its own reference-faithful solver defaults (e.g. the
    Krusell-Smith tolerances/Howard schedule of Krusell_Smith_VFI.m:12-13).

    `aggregation` selects how the cross-section is aggregated: "simulation"
    (the reference's Monte-Carlo household panel — time average for Aiyagari,
    Aiyagari_VFI.m:94-129; agent panel for Krusell-Smith, Krusell_Smith_VFI.m:
    222-248) or "distribution" (deterministic Young histogram — stationary
    distribution for Aiyagari, sim/distribution.py; distribution path along
    the aggregate shocks for Krusell-Smith, sim/ks_distribution.py — jax
    backend only).

    SolverConfig(accel=AccelConfig(...)) opts the hot fixed points into
    safeguarded Anderson/SQUAREM acceleration (ops/accel.py): every EGM
    household route and the stationary-distribution iteration inside the GE
    closures — same fixed points and stopping rules, measured ~2.5x fewer
    EGM sweeps and ~5x fewer distribution sweeps at default tolerances
    (docs/USAGE.md "Fixed-point acceleration"). The Krusell-Smith ALM outer
    loop's analogue is ALMConfig(acceleration="anderson").

    BackendConfig(dtype="mixed") opts the Aiyagari family into the
    mixed-precision solve ladder (ops/precision.py; docs/USAGE.md "Mixed
    precision"): f32 hot sweeps with an error-controlled switch to an f64
    polish across the household solvers and the stationary distribution,
    final results parity-pinned to the pure-f64 reference
    (tests/test_precision_ladder.py). Tune it via SolverConfig(
    ladder=PrecisionLadderConfig(...)); backends without x64 reject it
    loudly. For Krusell-Smith, "mixed" keeps the measured component policy
    (BackendConfig docstring).

    Observability (docs/USAGE.md "Observability"):
    SolverConfig(telemetry=TelemetryConfig(...)) carries a device-resident
    flight recorder through every hot fixed-point loop — per-sweep residual
    rings returned on the solutions (diagnostics/telemetry.py); off by
    default with zero cost. `ledger` (a diagnostics.ledger.RunLedger or a
    JSONL path) makes the solve write its traceable run record: config
    fingerprint, wall-clock spans, telemetry summaries, the convergence
    verdict, and any degradation events (push-forward fallbacks) — render
    it with `python -m aiyagari_tpu report <ledger>`. Every result exposes
    `.health()` (diagnostics/health.py), the Den-Haan-style certificate.

    Resilience (docs/USAGE.md "Resilient solves & fault injection"):
    SolverConfig(sentinel=SentinelConfig()) arms the device-resident
    failure sentinels — every hot while_loop early-exits on a non-finite /
    stalled / exploding residual with a structured verdict instead of
    burning max_iter. `rescue` (a RescueConfig, or True for the default
    ladder; Aiyagari family, jax backend) retries a failed solve through
    the host-side escalation ladder (plain → safe → float64 → patient),
    returning the first converged result or raising a ConvergenceError
    that carries the full attempt history — with a rescue ladder attached
    the exhaustion behavior is always a raise, regardless of
    `on_nonconvergence`.

    `warm_start` seeds the bisection's initial household solve with a
    previous solve's state (the VFI value function or the EGM consumption
    policy — the serve layer's solution cache passes its memoized
    neighbor here, docs/USAGE.md "Persistent solve service"); Aiyagari
    family on the jax serial paths only, None is bit-identical to the
    historical cold start.
    """
    if isinstance(backend, str):
        backend = BackendConfig(backend=backend)
    # The method/solver.method conflict is rejected BEFORE the rescue
    # branch too: the rescue attempts run on solver.method alone, and a
    # conflicting method= silently overridden there would break the
    # "never silently overridden" contract below.
    if solver is not None and method is not None and solver.method != method:
        raise ValueError(
            f"conflicting methods: method={method!r} but solver.method={solver.method!r}"
        )
    rescue = _resolve_rescue(rescue)
    if rescue is not None:
        if not isinstance(model, AiyagariConfig) or backend.backend != "jax":
            raise ValueError(
                "rescue ladders cover the Aiyagari family on the jax "
                "backend (the escalation stages transform its solver "
                "routes); drop rescue= for this solve")
        from aiyagari_tpu.diagnostics.rescue import run_rescue

        solver_r = solver or SolverConfig(method=method or "vfi")
        eq_r = equilibrium or EquilibriumConfig()
        led = _as_ledger(ledger, model, solver_r, eq_r, entry="solve")

        def attempt(s2, b2, o2):
            return solve(model, backend=b2, solver=s2, sim=sim,
                         equilibrium=o2, alm=alm, aggregation=aggregation,
                         on_nonconvergence="raise", ledger=led, rescue=None,
                         warm_start=warm_start)

        return run_rescue(attempt, rescue=rescue, solver=solver_r,
                          backend=backend, outer=eq_r,
                          context="Aiyagari GE rescue", tol=eq_r.tol,
                          ledger=led)
    if backend.backend not in ("jax", "numpy"):
        raise ValueError(
            f"unknown backend {backend.backend!r}; expected 'jax' or 'numpy'"
        )
    method = method or (solver.method if solver is not None else "vfi")
    if method not in ("vfi", "egm"):
        raise ValueError(f"unknown method {method!r}; expected 'vfi' or 'egm'")

    if aggregation not in ("simulation", "distribution"):
        raise ValueError(
            f"unknown aggregation {aggregation!r}; expected 'simulation' or 'distribution'"
        )
    if on_nonconvergence not in ("ignore", "warn", "raise"):
        raise ValueError(
            f"unknown on_nonconvergence {on_nonconvergence!r}; "
            "expected 'ignore', 'warn', or 'raise'"
        )

    if warm_start is not None and (
            not isinstance(model, AiyagariConfig)
            or backend.backend != "jax"
            or (equilibrium is not None and equilibrium.batch >= 2)):
        raise ValueError(
            "warm_start= covers the Aiyagari family's serial bisection on "
            "the jax backend (the seeded pass is the bisection's r_init "
            "household solve); drop it for this solve")
    if isinstance(model, AiyagariConfig):
        solver = _with_ladder(solver, method, backend)
        sim = sim or SimConfig()
        equilibrium = equilibrium or EquilibriumConfig()
        led = _as_ledger(ledger, model, solver, equilibrium, entry="solve")
        with _observe(led, "aiyagari_ge", method=method,
                      backend=backend.backend, aggregation=aggregation):
            if backend.backend == "numpy":
                if backend.dtype == "mixed" or solver.ladder is not None:
                    raise ValueError(
                        "the mixed-precision solve ladder (dtype='mixed' / "
                        "SolverConfig.ladder) requires backend='jax'; the numpy "
                        "reference backend is single-dtype by design")
                if solver.pushforward not in ("auto", "scatter"):
                    raise ValueError(
                        "SolverConfig.pushforward scatter-free backends require "
                        "backend='jax'; the numpy reference backend has only "
                        "the scatter formulation")
                if solver.egm_kernel not in ("auto", "xla"):
                    raise ValueError(
                        "SolverConfig.egm_kernel Pallas routes require "
                        "backend='jax'; the numpy reference backend has only "
                        "the op-by-op sweep")
                if aggregation != "simulation":
                    raise ValueError("aggregation='distribution' requires backend='jax'")
                if equilibrium.batch >= 2:
                    raise ValueError(
                        "EquilibriumConfig.batch >= 2 (batched GE) requires "
                        "backend='jax'")
                from aiyagari_tpu.solvers.numpy_backend import solve_equilibrium_numpy

                result = solve_equilibrium_numpy(model, solver=solver, sim=sim, eq=equilibrium)
            else:
                from aiyagari_tpu.config import precision_scope
                from aiyagari_tpu.equilibrium.bisection import (
                    solve_equilibrium,
                    solve_equilibrium_distribution,
                )
                from aiyagari_tpu.models.aiyagari import AiyagariModel

                # Route observatory: record this run's "auto" decisions
                # (one route_decision ledger event per knob) and, with
                # tuning active, thread the measured routes into the
                # solver config (jax backend only — the numpy reference
                # implements the scatter/XLA routes alone).
                solver = _resolve_routes(
                    solver, na=model.grid.n_points,
                    dtype=_dtype_of(backend),
                    egm=not model.endogenous_labor,
                    batched=equilibrium.batch >= 2)

                # Honor dtype="float64" even when global x64 is off (see
                # precision_scope — without it the request silently truncates).
                # Grid-axis mesh (BackendConfig.mesh_axes containing "grid"):
                # the EGM household solves run DISTRIBUTED with the knots
                # ring-redistributed across the mesh (solvers/egm_sharded.py).
                mesh = None
                if "grid" in backend.mesh_axes:
                    from aiyagari_tpu.parallel.mesh import make_mesh

                    mesh = make_mesh(backend.mesh_axes, backend.mesh_shape or None)
                    _observe_mesh(mesh, led, entry="solve")
                with precision_scope(backend.dtype):
                    if solver.ladder is not None:
                        # Loud guard, BEFORE any solve: a backend configuration
                        # that cannot represent the polish dtype must reject the
                        # ladder instead of silently polishing in f32
                        # (ops/precision.require_x64; precision_scope has
                        # already enabled x64 where that is possible).
                        from aiyagari_tpu.ops.precision import require_x64

                        require_x64(solver.ladder)
                    m = AiyagariModel.from_config(model, dtype=_dtype_of(backend))
                    # One-program equilibrium (equilibrium/fused.py): the
                    # ge_loop knob decides whether the GE outer loop runs
                    # as the host reference loop or fused on device inside
                    # one lax.while_loop program. "auto" falls back to
                    # host wherever the fused program does not exist;
                    # explicit "device" on an unsupported combo is loud.
                    from aiyagari_tpu.equilibrium.fused import resolve_ge_loop

                    ge_loop = resolve_ge_loop(
                        solver, aggregation=aggregation,
                        endogenous_labor=model.endogenous_labor, mesh=mesh)
                    if equilibrium.batch >= 2:
                        # Opt-in batched GE (equilibrium/batched.py): B candidate
                        # rates per device round through one vmapped excess-demand
                        # kernel, same fixed point as the serial bisection below
                        # in ~log2(B+1)-fold fewer rounds. Incompatible with the
                        # grid-axis mesh routes (the batch axis IS the
                        # parallelism); both closures are supported.
                        if mesh is not None:
                            raise ValueError(
                                "EquilibriumConfig.batch >= 2 cannot be combined "
                                "with a grid-axis device mesh; drop 'grid' from "
                                "BackendConfig.mesh_axes or use the serial path")
                        if ge_loop == "device":
                            from aiyagari_tpu.equilibrium.fused import (
                                solve_equilibrium_fused_batched,
                            )

                            result = solve_equilibrium_fused_batched(
                                m, solver=solver, eq=equilibrium)
                        else:
                            from aiyagari_tpu.equilibrium.batched import (
                                solve_equilibrium_batched,
                            )

                            result = solve_equilibrium_batched(
                                m, solver=solver, eq=equilibrium, sim=sim,
                                aggregation=aggregation)
                    elif aggregation == "distribution":
                        if ge_loop == "device":
                            from aiyagari_tpu.equilibrium.fused import (
                                solve_equilibrium_fused,
                            )

                            result = solve_equilibrium_fused(
                                m, solver=solver, eq=equilibrium,
                                warm_start=warm_start)
                        else:
                            result = solve_equilibrium_distribution(
                                m, solver=solver, eq=equilibrium, mesh=mesh,
                                warm_start=warm_start)
                    else:
                        result = solve_equilibrium(
                            m, solver=solver, sim=sim, eq=equilibrium,
                            mesh=mesh, warm_start=warm_start)
        # The solver's own stopping quantity: the batched rounds stop on the
        # round's BEST candidate gap (per_iteration "best_gap"), the serial
        # bisection on its single candidate ("gap"); the last-candidate
        # fallback covers the numpy backend's record-free result.
        per_it = getattr(result, "per_iteration", None)
        if per_it:
            last = per_it[-1]
            gap = abs(last.get("best_gap", last.get("gap", float("inf"))))
        else:
            gap = (
                abs(result.k_supply[-1] - result.k_demand[-1])
                if result.k_supply else float("inf")
            )
        iters = getattr(result, "iterations", len(result.r_history))
        _ledger_result(led, "Aiyagari GE bisection", result,
                       converged=result.converged, iterations=iters,
                       distance=gap, tol=equilibrium.tol)
        enforce_convergence(
            result.converged, on_nonconvergence, "Aiyagari GE bisection",
            # the numpy-backend result has no iterations field; its bisection
            # history is one entry per outer iteration
            iterations=iters,
            distance=gap, tol=equilibrium.tol, detail={"r": result.r},
            telemetry=getattr(result, "telemetry", None),
            verdict=getattr(result, "verdict", "") or None,
        )
        return result

    if isinstance(model, KrusellSmithConfig):
        if aggregation == "distribution" and backend.backend != "jax":
            raise ValueError("aggregation='distribution' requires backend='jax'")
        if solver is not None:
            # Same loud DistributionBackend typo rejection as the Aiyagari
            # branch — the knob reaches the histogram closure's jit static
            # args (equilibrium/alm.py).
            from aiyagari_tpu.ops.pushforward import resolve_backend

            resolve_backend(solver.pushforward)
        alm = alm or ALMConfig()
        led = _as_ledger(ledger, model, solver, alm, entry="solve")
        from aiyagari_tpu.equilibrium.alm import solve_krusell_smith

        # solver=None lets the KS loop apply its own reference defaults
        # (tol 1e-6, Howard 50/improve-every-5) rather than the generic ones.
        # aggregation="distribution" advances the cross-section as a Young
        # histogram along the aggregate path (sim/ks_distribution.py) instead
        # of the reference's Monte-Carlo agent panel.
        with _observe(led, "krusell_smith", method=method,
                      aggregation=aggregation):
            # Route observatory, KS flavor: the pushforward decision is
            # recorded by the ALM loop itself (equilibrium/alm.py
            # resolves with the sim-dtype context dispatch does not
            # have, exactly once per activation); the searchsorted knob
            # has no config surface, so record it HERE where jit caching
            # cannot skip it (the trace-time resolver never re-runs on a
            # warm executable). egm_kernel has no KS route and stays
            # unrecorded.
            from aiyagari_tpu.ops.interp import searchsorted_method

            searchsorted_method(model.k_size)
            result = solve_krusell_smith(
                model, method=method, solver=solver, alm=alm, backend=backend,
                closure=("histogram" if aggregation == "distribution" else "panel"),
            )
        _ledger_result(led, "Krusell-Smith ALM fixed point", result,
                       converged=result.converged,
                       iterations=result.iterations,
                       distance=result.diff_B, tol=alm.tol)
        enforce_convergence(
            result.converged, on_nonconvergence, "Krusell-Smith ALM fixed point",
            iterations=result.iterations, distance=result.diff_B, tol=alm.tol,
            detail={"B": [round(float(b), 6) for b in result.B]},
            telemetry=getattr(result, "telemetry", None),
        )
        return result

    raise TypeError(f"unknown model config type: {type(model).__name__}")


# Parameter-grid keys sweep() knows how to thread into an AiyagariConfig:
# name -> (config section, field). All are r-relevant economics: preferences
# move the supply curve, the borrowing limit moves the grid, the income
# process moves both the chain and the normalized labor endowment.
_SWEEP_PARAMS = {
    "beta": ("preferences", "beta"),
    "sigma": ("preferences", "sigma"),
    "psi": ("preferences", "psi"),
    "eta": ("preferences", "eta"),
    "borrowing_limit": (None, "borrowing_limit"),
    "rho": ("income", "rho"),
    "sigma_e": ("income", "sigma_e"),
}


def _scenario_config(base: AiyagariConfig, assignment: dict) -> AiyagariConfig:
    cfg = base
    for name, value in assignment.items():
        section, field = _SWEEP_PARAMS[name]
        if section is None:
            cfg = dataclasses.replace(cfg, **{field: value})
        else:
            sub = dataclasses.replace(getattr(cfg, section), **{field: value})
            cfg = dataclasses.replace(cfg, **{section: sub})
    return cfg


def sweep(
    base: AiyagariConfig,
    *,
    method: Optional[str] = None,
    backend: Union[str, BackendConfig] = "jax",
    solver: Optional[SolverConfig] = None,
    sim: Optional[SimConfig] = None,
    equilibrium: Optional[EquilibriumConfig] = None,
    aggregation: str = "distribution",
    configs: Optional[Sequence[AiyagariConfig]] = None,
    ledger=None,
    rescue=None,
    quarantine: bool = True,
    mesh=None,
    **param_grids,
):
    """Solve MANY Aiyagari economies to general equilibrium as one batched
    device program (equilibrium/batched.py).

    Scenarios come either from `configs` (an explicit list of
    AiyagariConfigs sharing grid shapes and technology) or from the
    cartesian product of parameter grids passed as keyword lists over the
    r-relevant scalars: beta, sigma, psi, eta, borrowing_limit, rho,
    sigma_e. Example:

        res = sweep(AiyagariConfig(),
                    beta=[0.94, 0.95, 0.96],
                    sigma=[2.0, 3.0, 5.0])      # 9 scenarios
        res.r                                    # [9] equilibrium rates
        res.params[4]                            # {"beta": 0.95, "sigma": 3.0}

    Every scenario advances its own interest-rate bisection in lockstep: one
    round = one vmapped excess-demand kernel call over all S scenarios (the
    vmap-compatible solver entry points make sigma/beta traced operands, so
    the whole batch compiles once). With BackendConfig.mesh_axes containing
    "scenarios", the scenario axis is sharded across the device mesh —
    scenarios/sec then scales with the device count; the result records
    `scenarios_per_sec` either way.

    `mesh` (a MeshConfig — docs/USAGE.md "Pod-scale 2-D sharding") opts
    into the 2-D (scenarios x grid) mesh instead: the scenario batch
    splits over the "scenarios" axis (hosts, on a pod) while every
    scenario's asset-grid axis splits over "grid" (a host's chips), in the
    SAME compiled round program — placement by the partition-rule matcher
    (parallel/rules.py), sizes derived/validated loudly
    (parallel/mesh.make_mesh_2d), results within reassociation noise
    (<= 1e-12) of the unsharded sweep, quarantine still per-lane. Each
    activated mesh (1-D or 2-D) is recorded: a `mesh_topology` ledger
    event plus aiyagari_mesh_axis_size{axis=} gauges. mesh=None (default)
    is today's behavior bit-identical.

    aggregation="distribution" (default) closes each scenario with the
    deterministic Young-histogram supply; "simulation" uses per-scenario
    Monte-Carlo panels. Returns a SweepResult ([S]-arrays of r/w/K plus the
    batched household solutions, still on device).

    Scenario quarantine (default on): a lane whose excess demand goes
    non-finite is frozen so the batch completes with per-scenario verdicts
    (SweepResult.quarantined / .verdicts) — partial results instead of an
    all-or-nothing sweep. With `rescue` (a RescueConfig, or True), each
    quarantined scenario is then re-solved SERIALLY through the rescue
    ladder (diagnostics/rescue.py) and its scalars spliced back into the
    result (verdict "rescued"); scenarios the ladder cannot save keep
    their "nan" verdict and the attempt history lands on
    SweepResult.rescue_attempts. quarantine=False restores the historical
    frozen-lane-until-max_iter behavior (benchmark A/B only).
    """
    if isinstance(backend, str):
        backend = BackendConfig(backend=backend)
    if backend.backend != "jax":
        raise ValueError("sweep() requires backend='jax'")
    if solver is not None and method is not None and solver.method != method:
        raise ValueError(
            f"conflicting methods: method={method!r} but solver.method={solver.method!r}"
        )
    method = method or (solver.method if solver is not None else "vfi")
    if method not in ("vfi", "egm"):
        raise ValueError(f"unknown method {method!r}; expected 'vfi' or 'egm'")
    solver = _with_ladder(solver, method, backend)
    sim = sim or SimConfig()
    equilibrium = equilibrium or EquilibriumConfig()
    if aggregation not in ("simulation", "distribution"):
        raise ValueError(
            f"unknown aggregation {aggregation!r}; expected 'simulation' or 'distribution'"
        )

    params: Optional[list] = None
    if configs is None:
        unknown = set(param_grids) - set(_SWEEP_PARAMS)
        if unknown:
            raise ValueError(
                f"unknown sweep parameter(s) {sorted(unknown)}; supported: "
                f"{sorted(_SWEEP_PARAMS)}")
        if not param_grids:
            raise ValueError(
                "sweep() needs scenarios: pass parameter grids "
                "(e.g. beta=[...]) or an explicit configs=[...] list")
        names = sorted(param_grids)
        grids = [list(param_grids[n]) for n in names]
        params = [dict(zip(names, combo))
                  for combo in itertools.product(*grids)]
        configs = [_scenario_config(base, p) for p in params]
    elif param_grids:
        raise ValueError("pass either configs=[...] or parameter grids, not both")

    from aiyagari_tpu.config import precision_scope
    from aiyagari_tpu.equilibrium.batched import (
        solve_equilibrium_sweep,
        stack_scenarios,
    )
    from aiyagari_tpu.models.aiyagari import AiyagariModel

    rescue = _resolve_rescue(rescue)
    led = _as_ledger(ledger, base, solver, equilibrium, entry="sweep")
    mesh_cfg = mesh
    mesh = _sweep_mesh(backend, mesh, led, entry="sweep")
    with _observe(led, "aiyagari_sweep", scenarios=len(configs),
                  method=method, aggregation=aggregation):
        solver = _resolve_routes(solver, na=base.grid.n_points,
                                 dtype=_dtype_of(backend),
                                 egm=not base.endogenous_labor,
                                 batched=True)
        with precision_scope(backend.dtype):
            if solver.ladder is not None:
                from aiyagari_tpu.ops.precision import require_x64

                require_x64(solver.ladder)
            models = [AiyagariModel.from_config(c, dtype=_dtype_of(backend))
                      for c in configs]
            batch = stack_scenarios(models, mesh=mesh)
            _probe_skew(mesh, mesh_cfg, led, price={
                "S": batch.size, "N": int(batch.P.shape[-1]),
                "na": int(batch.a_grid.shape[-1])})
            # Injected poisoned scenario (diagnostics/faults.py): one
            # lane's labor endowment is NaN'd AFTER stacking, so that
            # lane's excess demand is NaN every round — the per-scenario
            # config stays healthy, so the quarantine's serial re-solve
            # recovers it, which is exactly the contract the CI battery
            # certifies. (The demand-side operand is the deterministic
            # poison: a NaN preference can be silently masked by the EGM
            # constraint region's NaN-false comparisons.)
            from aiyagari_tpu.diagnostics.faults import poison_scenario_index

            pi = poison_scenario_index(solver.faults)
            if pi is not None:
                if not 0 <= pi < batch.size:
                    raise ValueError(
                        f"FaultPlan.poison_scenario={pi} outside the "
                        f"{batch.size}-scenario batch")
                batch = dataclasses.replace(
                    batch, labor_raw=batch.labor_raw.at[pi].set(jnp.nan))
            result = solve_equilibrium_sweep(
                batch, solver=solver, eq=equilibrium, sim=sim,
                aggregation=aggregation, quarantine=quarantine)
    result.params = params
    import numpy as _np

    if (rescue is not None and result.quarantined is not None
            and _np.any(result.quarantined)):
        _rescue_quarantined_sweep(
            result, configs, backend=backend, solver=solver,
            sim=sim, equilibrium=equilibrium, aggregation=aggregation,
            rescue=rescue, ledger=led)
    live = (~result.quarantined if result.quarantined is not None
            else _np.ones(result.scenarios, bool))
    finite_gap = _np.abs(_np.where(live, result.gap, 0.0))
    _ledger_result(led, "Aiyagari GE sweep", result,
                   converged=bool(_np.all(result.converged)),
                   iterations=result.rounds,
                   distance=float(_np.max(finite_gap, initial=0.0)),
                   tol=equilibrium.tol)
    if led is not None and result.quarantined is not None:
        for i in _np.nonzero(result.quarantined)[0]:
            led.event("quarantine", context="Aiyagari GE sweep",
                      scenario=int(i), verdict=result.verdicts[int(i)])
    return result


def _rescue_quarantined_sweep(result, configs, *, backend, solver,
                              sim, equilibrium, aggregation, rescue,
                              ledger):
    """Re-solve each quarantined sweep lane SERIALLY through the rescue
    ladder and splice the recovered scalars (r/w/capital/gap/converged)
    back into the SweepResult. The batched device pytrees (solutions, mu)
    keep their lockstep values — the quarantined lane's entries there are
    NaN-poisoned and callers should index them by verdict. Lanes the
    ladder cannot save keep verdict "nan"; every attempt history lands on
    result.rescue_attempts."""
    import numpy as _np

    from aiyagari_tpu.diagnostics.errors import ConvergenceError
    from aiyagari_tpu.diagnostics import metrics

    result.rescue_attempts = {}
    # Device-fetched arrays can be read-only views; the splice writes them.
    for name in ("r", "w", "capital", "gap", "converged"):
        setattr(result, name, _np.array(getattr(result, name)))
    # The serial re-solve must not re-apply batch-level faults: the
    # poisoned-scenario injection lives at the stack_scenarios level, and
    # device-fault plans are cleared so the lane gets a genuinely fresh
    # solve (rescue stages would clear them anyway; the base attempt
    # should too, or an injected nan_sweep re-fails it pointlessly).
    solver_clean = dataclasses.replace(solver, faults=None)
    for i in _np.nonzero(result.quarantined)[0]:
        i = int(i)
        try:
            res_i = solve(configs[i], backend=backend, solver=solver_clean,
                          sim=sim, equilibrium=equilibrium,
                          aggregation=aggregation, ledger=ledger,
                          rescue=rescue)
        except ConvergenceError as e:
            result.rescue_attempts[i] = e.attempts
            metrics.counter("aiyagari_quarantine_total",
                            outcome="unrecovered").inc()
            continue
        result.rescue_attempts[i] = res_i.rescue_attempts
        result.r[i] = res_i.r
        result.w[i] = res_i.w
        result.capital[i] = res_i.capital
        result.gap[i] = res_i.k_supply[-1] - res_i.k_demand[-1]
        result.converged[i] = True
        result.verdicts[i] = "rescued"
        metrics.counter("aiyagari_quarantine_total",
                        outcome="rescued").inc()


def _transition_backend(backend: Union[str, BackendConfig]) -> BackendConfig:
    if isinstance(backend, str):
        backend = BackendConfig(backend=backend)
    if backend.backend != "jax":
        raise ValueError("transition solves require backend='jax' (the "
                         "path evaluator is a fused device scan)")
    return backend


def _transition_ladder(backend: BackendConfig, solver: Optional[SolverConfig]):
    """The ROUND-LOOP ladder for a transition solve: dtype='mixed' (or an
    explicit SolverConfig.ladder) hands transition/mit.py the ladder; the
    stationary anchoring solve inherits it through `solver` as usual."""
    from aiyagari_tpu.ops.egm import resolve_egm_kernel
    from aiyagari_tpu.ops.precision import ladder_for_dtype, require_x64
    from aiyagari_tpu.ops.pushforward import resolve_backend

    if solver is not None:
        resolve_backend(solver.pushforward)   # loud typo rejection pre-solve
        resolve_egm_kernel(solver.egm_kernel)
    ladder = solver.ladder if solver is not None else None
    if ladder is None:
        ladder = ladder_for_dtype(backend.dtype)
    if ladder is not None:
        require_x64(ladder)
    return ladder


def solve_transition(
    model: AiyagariConfig,
    shock: MITShock,
    *,
    transition: TransitionConfig = TransitionConfig(),
    backend: Union[str, BackendConfig] = "jax",
    solver: Optional[SolverConfig] = None,
    equilibrium: Optional[EquilibriumConfig] = None,
    on_nonconvergence: str = "warn",
    ledger=None,
    rescue=None,
    **kwargs,
):
    """Solve a perfect-foresight MIT-shock transition path to general
    equilibrium (transition/mit.py; ISSUE 2 tentpole).

        res = solve_transition(AiyagariConfig(),
                               MITShock(param="tfp", size=0.01, rho=0.9),
                               transition=TransitionConfig(T=200))
        res.r_path, res.K_ts          # equilibrium price / capital paths
        res.max_excess_history        # per-round max excess demand

    The path starts at the stationary equilibrium of `model` (its Young
    histogram is the initial distribution) and ends back at it (its EGM
    consumption policy is the terminal condition); transition.method picks
    the Newton (sequence-space Jacobian) or damped update. `solver` /
    `equilibrium` tune the anchoring stationary solve; extra kwargs (`ss`,
    `jacobian`, `anchor_warm_start`, `keep_policies`, `on_iteration`) pass
    through to transition/mit.solve_transition.
    """
    backend = _transition_backend(backend)
    from aiyagari_tpu.config import precision_scope
    from aiyagari_tpu.diagnostics.errors import enforce_convergence
    from aiyagari_tpu.transition.mit import solve_transition as _solve

    rescue = _resolve_rescue(rescue)
    if rescue is not None:
        from aiyagari_tpu.diagnostics.rescue import run_rescue

        solver_r = solver or SolverConfig(method="egm", tol=1e-9,
                                          max_iter=5000)
        led = _as_ledger(ledger, model, shock, transition, solver_r,
                         entry="solve_transition")

        def attempt(s2, b2, o2):
            return solve_transition(model, shock, transition=o2, backend=b2,
                                    solver=s2, equilibrium=equilibrium,
                                    on_nonconvergence="raise", ledger=led,
                                    rescue=None, **kwargs)

        return run_rescue(attempt, rescue=rescue, solver=solver_r,
                          backend=backend, outer=transition,
                          context="MIT-shock transition rescue",
                          tol=transition.tol, ledger=led)

    led = _as_ledger(ledger, model, shock, transition, solver,
                     entry="solve_transition")
    with _observe(led, "mit_transition", method=transition.method,
                  T=transition.T):
        solver = _resolve_routes(solver, na=model.grid.n_points,
                                 dtype=_dtype_of(backend))
        from aiyagari_tpu.transition.fused import resolve_transition_loop

        t_loop = resolve_transition_loop(
            transition, endogenous_labor=model.endogenous_labor,
            on_iteration=kwargs.get("on_iteration"))
        with precision_scope(backend.dtype):
            if t_loop == "device":
                from aiyagari_tpu.transition.fused import (
                    solve_transition_fused,
                )

                # An explicit on_iteration=None routed here; the fused
                # signature has no callback slot.
                kwargs.pop("on_iteration", None)
                result = solve_transition_fused(
                    model, shock, trans=transition, solver=solver,
                    eq=equilibrium, dtype=_dtype_of(backend),
                    ladder=_transition_ladder(backend, solver), **kwargs)
            else:
                result = _solve(model, shock, trans=transition,
                                solver=solver, eq=equilibrium,
                                dtype=_dtype_of(backend),
                                ladder=_transition_ladder(backend, solver),
                                **kwargs)
    distance = (result.max_excess_history[-1]
                if result.max_excess_history else float("inf"))
    _ledger_result(led, "MIT-shock transition path", result,
                   converged=result.converged, iterations=result.rounds,
                   distance=distance, tol=transition.tol)
    enforce_convergence(
        result.converged, on_nonconvergence, "MIT-shock transition path",
        iterations=result.rounds,
        distance=distance,
        tol=transition.tol,
        detail={"method": result.method, "T": result.T},
        telemetry=getattr(result, "telemetry", None),
        verdict=getattr(result, "verdict", "") or None,
    )
    return result


def sweep_transitions(
    model: AiyagariConfig,
    shocks=None,
    *,
    transition: TransitionConfig = TransitionConfig(),
    backend: Union[str, BackendConfig] = "jax",
    solver: Optional[SolverConfig] = None,
    equilibrium: Optional[EquilibriumConfig] = None,
    params: Optional[Sequence[str]] = None,
    sizes: Optional[Sequence[float]] = None,
    rhos: Optional[Sequence[float]] = None,
    ledger=None,
    rescue=None,
    quarantine: bool = True,
    mesh=None,
    **kwargs,
):
    """Solve MANY MIT-shock scenarios of one economy in lockstep, every
    round one vmapped device program (transition/mit.solve_transitions_sweep).

    Scenarios come either from an explicit `shocks=[MITShock(...), ...]`
    list — which may mix shocked parameters (tfp/beta/sigma/
    borrowing_limit) — or from the cartesian product of `params` x `sizes`
    x `rhos`:

        res = sweep_transitions(AiyagariConfig(),
                                params=["tfp", "beta"],
                                sizes=[0.005, 0.01], rhos=[0.8, 0.95])
        res.r_paths                   # [8, T] equilibrium rate paths
        res.transitions_per_sec       # the throughput metric bench.py records

    One stationary anchor and ONE fake-news Jacobian serve every scenario
    (the ss linearization is shock-independent); with
    BackendConfig(mesh_axes=("scenarios",)) the stacked shock paths shard
    across the device mesh and rounds run scenario-parallel. `mesh` (a
    MeshConfig) opts into the 2-D (scenarios x grid) mesh instead: the
    stacked [S, T] paths split over "scenarios" while the shared
    stationary anchors (terminal policy, initial distribution, asset
    grid) split over "grid" through the partition-rule matcher
    (parallel/rules.TRANSITION_SWEEP_RULES) — one program, both axes; a
    `mesh_topology` ledger event + per-axis gauges record the activated
    topology. mesh=None (default) keeps today's behavior bit-identical.
    """
    backend = _transition_backend(backend)
    if shocks is None:
        if not (params and sizes):
            raise ValueError(
                "sweep_transitions needs scenarios: pass shocks=[...] or "
                "params=[...] plus sizes=[...] (and optionally rhos=[...])")
        shocks = [MITShock(param=p, size=sz, rho=rh)
                  for p in params for sz in sizes
                  for rh in (rhos if rhos else [MITShock().rho])]
    elif params or sizes or rhos:
        raise ValueError(
            "pass either shocks=[...] or params/sizes/rhos grids, not both")

    from aiyagari_tpu.config import precision_scope
    from aiyagari_tpu.transition.mit import solve_transitions_sweep as _sweep

    rescue = _resolve_rescue(rescue)
    led = _as_ledger(ledger, model, transition, solver,
                     entry="sweep_transitions")
    mesh_cfg = mesh
    mesh = _sweep_mesh(backend, mesh, led, entry="sweep_transitions")
    # Injected poisoned scenario (diagnostics/faults.py): one scenario's
    # shock is replaced with an untempered unit TFP drop whose path
    # evaluation overflows — the quarantine freezes that lane, and the
    # serial rescue re-solves the ORIGINAL shock from the shocks list.
    shocks_run = list(shocks)
    pi = None
    if solver is not None:
        from aiyagari_tpu.diagnostics.faults import poison_scenario_index

        pi = poison_scenario_index(solver.faults)
    if pi is not None:
        if not 0 <= pi < len(shocks_run):
            raise ValueError(
                f"FaultPlan.poison_scenario={pi} outside the "
                f"{len(shocks_run)}-scenario batch")
        shocks_run[pi] = MITShock(param="tfp", size=float("nan"), rho=0.0)
    with _observe(led, "mit_transition_sweep", scenarios=len(shocks),
                  method=transition.method, T=transition.T):
        _probe_skew(mesh, mesh_cfg, led)
        solver = _resolve_routes(solver, na=model.grid.n_points,
                                 dtype=_dtype_of(backend))
        from aiyagari_tpu.transition.fused import resolve_transition_loop

        t_loop = resolve_transition_loop(
            transition, endogenous_labor=model.endogenous_labor,
            mesh=mesh, on_iteration=kwargs.get("on_iteration"))
        with precision_scope(backend.dtype):
            if t_loop == "device":
                from aiyagari_tpu.transition.fused import (
                    solve_transitions_sweep_fused,
                )

                kwargs.pop("on_iteration", None)
                result = solve_transitions_sweep_fused(
                    model, shocks_run, trans=transition, solver=solver,
                    eq=equilibrium, dtype=_dtype_of(backend),
                    ladder=_transition_ladder(backend, solver),
                    quarantine=quarantine, **kwargs)
            else:
                result = _sweep(model, shocks_run, trans=transition,
                                solver=solver, eq=equilibrium, mesh=mesh,
                                dtype=_dtype_of(backend),
                                ladder=_transition_ladder(backend, solver),
                                quarantine=quarantine,
                                **kwargs)
    import numpy as _np

    result.shocks = list(shocks)
    if (rescue is not None and result.quarantined is not None
            and _np.any(result.quarantined)):
        from aiyagari_tpu.diagnostics.errors import ConvergenceError
        from aiyagari_tpu.diagnostics import metrics

        result.rescue_attempts = {}
        result.r_paths = _np.array(result.r_paths)
        result.max_excess = _np.array(result.max_excess)
        result.converged = _np.array(result.converged)
        solver_clean = (dataclasses.replace(solver, faults=None)
                        if solver is not None else None)
        for i in _np.nonzero(result.quarantined)[0]:
            i = int(i)
            try:
                res_i = solve_transition(
                    model, shocks[i], transition=transition, backend=backend,
                    solver=solver_clean, equilibrium=equilibrium,
                    ledger=led, rescue=rescue,
                    ss=result.ss, jacobian=result.jacobian)
            except ConvergenceError as e:
                result.rescue_attempts[i] = e.attempts
                metrics.counter("aiyagari_quarantine_total",
                                outcome="unrecovered").inc()
                continue
            result.rescue_attempts[i] = res_i.rescue_attempts
            result.r_paths[i] = res_i.r_path
            result.max_excess[i] = float(_np.max(_np.abs(res_i.excess)))
            result.converged[i] = True
            result.verdicts[i] = "rescued"
            metrics.counter("aiyagari_quarantine_total",
                            outcome="rescued").inc()
    live = (~result.quarantined if result.quarantined is not None
            else _np.ones(result.scenarios, bool))
    _ledger_result(led, "MIT-shock transition sweep", result,
                   converged=bool(_np.all(result.converged)),
                   iterations=result.rounds,
                   distance=float(_np.max(
                       _np.where(live, result.max_excess, 0.0),
                       initial=0.0)),
                   tol=transition.tol)
    if led is not None and result.quarantined is not None:
        for i in _np.nonzero(result.quarantined)[0]:
            led.event("quarantine", context="MIT-shock transition sweep",
                      scenario=int(i), verdict=result.verdicts[int(i)])
    return result


@dataclasses.dataclass
class CalibrationResult:
    """dispatch.calibrate's host-side summary.

    `theta` is populated ONLY when status == "converged" — a stalled fit
    returns the evidence (per-lane losses, alive mask, the full FitResult)
    but never a parameter vector it cannot certify, the same refusal
    discipline serve's /calibrate endpoint inherits verbatim.
    """

    status: str                      # "converged" | "max_iter"
    params: tuple                    # calibrated parameter names, z order
    theta: Optional[dict]            # fitted values (floats), converged only
    moments: Optional[dict]          # model moments at theta, converged only
    loss: float                      # best-lane final loss
    lanes: int
    steps: int                       # Adam steps taken
    grad_evals: int
    fit: object                      # calibrate.optimize.FitResult
    targets: dict


def calibrate(
    base: AiyagariConfig,
    targets: dict,
    params: Sequence[str] = ("beta", "sigma", "rho", "sigma_e"),
    *,
    backend: Union[str, BackendConfig] = "jax",
    lanes: int = 2,
    steps: int = 40,
    lr: float = 0.1,
    weights: Optional[dict] = None,
    loss_tol: float = 1e-9,
    gtol: float = 1e-5,
    stage_dtypes=("float32", "float64"),
    stage_split: float = 0.4,
    polish: bool = True,
    jitter: float = 0.05,
    seed: int = 0,
    mesh=None,
    ledger=None,
    on_step=None,
    ss_kwargs: Optional[dict] = None,
) -> CalibrationResult:
    """Fit an economy's deep parameters to target moments by gradient.

    The forward model is the fully differentiable steady-state chain
    (calibrate/economy.steady_state_map: Rouwenhorst -> EGM fixed point ->
    stationary distribution -> GE rate, every stage an IFT-wrapped adjoint
    from ops/implicit.py); the objective is the weighted relative moment
    distance (calibrate/loss.moment_loss) over `targets`, a dict keyed by
    calibrate.moments.MOMENTS names ("gini", "k_y", "mpc", "top10_share").
    Optimization is multi-lane Adam + BFGS polish on the f32->f64
    precision ladder with per-lane quarantine (calibrate/optimize.fit);
    lane 0 starts at `base`'s own parameters, lanes 1..L-1 at jittered
    copies, and the L lanes run as ONE vmapped device program over the
    scenario axis — `mesh` (a MeshConfig) shards that axis exactly as
    sweep() does, recorded by the same mesh_topology event.

    Calibration requires income.method == "rouwenhorst": the
    differentiable discretization's closed-form stationary weights exist
    only for that scheme (calibrate/economy.py module docstring). The
    asset grid and state count are frozen at `base`'s shapes.

    Every Adam step lands a `calibration_step` ledger event (step, best
    loss, live lanes); the final verdict + aiyagari_calibration_* metrics
    record the fit outcome. `on_step(step, loss[L], alive[L])` is the
    caller's per-step hook (serve streams gauges through it).
    """
    import numpy as np

    from aiyagari_tpu.calibrate.economy import steady_state_map
    from aiyagari_tpu.calibrate.loss import (
        CALIBRATED_PARAMS,
        moment_loss,
        pack,
        unpack,
    )
    from aiyagari_tpu.calibrate.moments import MOMENTS, moments_of
    from aiyagari_tpu.calibrate.optimize import fit as run_fit
    from aiyagari_tpu.diagnostics import metrics
    from aiyagari_tpu.models.aiyagari import AiyagariModel

    if isinstance(backend, str):
        backend = BackendConfig(backend=backend)
    if backend.backend != "jax":
        raise ValueError("calibrate() requires backend='jax'")
    params = tuple(params)
    unknown = set(params) - set(CALIBRATED_PARAMS)
    if unknown:
        raise ValueError(
            f"unknown calibration parameter(s) {sorted(unknown)}; "
            f"supported: {sorted(CALIBRATED_PARAMS)}")
    if not params:
        raise ValueError("calibrate() needs at least one parameter to fit")
    bad = set(targets) - set(MOMENTS)
    if bad:
        raise ValueError(
            f"unknown target moment(s) {sorted(bad)}; supported: "
            f"{sorted(MOMENTS)}")
    if not targets:
        raise ValueError(
            f"calibrate() needs target moments: a dict over {sorted(MOMENTS)}")
    if base.income.method != "rouwenhorst":
        raise ValueError(
            "calibrate() requires income.method='rouwenhorst' (the "
            "differentiable discretization with closed-form stationary "
            f"weights); got {base.income.method!r}. Replace the income "
            "config: dataclasses.replace(cfg, income=dataclasses.replace("
            "cfg.income, method='rouwenhorst')).")
    if base.endogenous_labor:
        raise ValueError(
            "calibrate() does not support endogenous_labor models yet "
            "(the differentiable chain wraps the exogenous-labor EGM)")
    if lanes < 1:
        raise ValueError(f"lanes must be >= 1, got {lanes}")

    model = AiyagariModel.from_config(base, dtype=jnp.float64)
    tech = base.technology
    n_states = base.income.n_states
    amin = float(model.amin)
    base_theta = {
        "beta": base.preferences.beta,
        "sigma": base.preferences.sigma,
        "rho": base.income.rho,
        "sigma_e": base.income.sigma_e,
    }
    ss_kwargs = dict(ss_kwargs or {})

    # f32 stage: inner tolerances below f32 resolution would run every
    # household/distribution solve to max_iter — relax them to the hot
    # stage's own precision unless the caller pinned values.
    _F32_TOLS = {"hh_tol": 1e-6, "dist_tol": 1e-7, "adjoint_tol": 1e-6}

    def loss_for(dtype_str: str):
        dt = jnp.dtype(dtype_str)
        ag = model.a_grid.astype(dt)
        tgt = {k: jnp.asarray(float(v), dt) for k, v in targets.items()}
        kw = dict(ss_kwargs)
        if dt == jnp.float32:
            for k, v in _F32_TOLS.items():
                kw.setdefault(k, v)

        def objective(z):
            th = {k: jnp.asarray(v, dt) for k, v in base_theta.items()}
            th.update(unpack(z.astype(dt), params))
            state = steady_state_map(
                th["beta"], th["sigma"], th["rho"], th["sigma_e"], ag,
                n_states=n_states, alpha=tech.alpha, delta=tech.delta,
                amin=amin, **kw)
            return moment_loss(moments_of(state, ag, alpha=tech.alpha),
                               tgt, weights)

        return objective

    z_base = np.asarray(pack({k: base_theta[k] for k in params}, params),
                        np.float64)
    rng = np.random.RandomState(seed)
    z0 = np.tile(z_base, (lanes, 1))
    if lanes > 1:
        z0[1:] += jitter * rng.standard_normal((lanes - 1, z_base.size))

    led = _as_ledger(ledger, base, entry="calibrate")
    mesh_cfg = mesh
    mesh = _sweep_mesh(backend, mesh, led, entry="calibrate")
    with _observe(led, "aiyagari_calibrate", lanes=lanes,
                  params=list(params), moments=sorted(targets)):
        _probe_skew(mesh, mesh_cfg, led)
        z0_dev = jnp.asarray(z0)
        if mesh is not None and lanes % int(mesh.shape["scenarios"]) == 0:
            import jax as _jax

            from aiyagari_tpu.parallel.mesh import named_sharding

            z0_dev = _jax.device_put(
                z0_dev, named_sharding(mesh, "scenarios", None))

        def _on_step(step, loss_np, alive_np):
            live = loss_np[alive_np] if alive_np.any() else loss_np
            best = float(np.min(live)) if live.size else float("nan")
            if led is not None:
                led.event("calibration_step", step=int(step), loss=best,
                          alive=int(alive_np.sum()), lanes=int(lanes))
            metrics.gauge("aiyagari_calibration_last_loss").set(best)
            metrics.gauge("aiyagari_calibration_steps").set(int(step))
            if on_step is not None:
                on_step(step, loss_np, alive_np)

        result = run_fit(
            loss_for, z0_dev, steps=steps, lr=lr, loss_tol=loss_tol,
            gtol=gtol, stage_dtypes=stage_dtypes, stage_split=stage_split,
            polish=polish, on_step=_on_step)

        theta = None
        moments = None
        if result.status == "converged":
            theta = {k: float(np.asarray(v))
                     for k, v in unpack(jnp.asarray(result.best_z),
                                        params).items()}
            full = dict(base_theta)
            full.update(theta)
            state = steady_state_map(
                jnp.asarray(full["beta"]), jnp.asarray(full["sigma"]),
                jnp.asarray(full["rho"]), jnp.asarray(full["sigma_e"]),
                model.a_grid, n_states=n_states, alpha=tech.alpha,
                delta=tech.delta, amin=amin, **ss_kwargs)
            moments = {k: float(np.asarray(v)) for k, v in
                       moments_of(state, model.a_grid,
                                  alpha=tech.alpha).items()}
        best_loss = float(result.loss[result.best_lane])
        metrics.counter("aiyagari_calibration_fits_total",
                        status=result.status).inc()
        metrics.gauge("aiyagari_calibration_last_loss").set(best_loss)
        metrics.gauge("aiyagari_calibration_steps").set(int(result.steps))
        if led is not None:
            led.verdict("calibration",
                        converged=result.status == "converged",
                        iterations=int(result.steps), distance=best_loss,
                        tol=loss_tol)
    return CalibrationResult(
        status=result.status, params=params, theta=theta, moments=moments,
        loss=best_loss, lanes=lanes, steps=int(result.steps),
        grad_evals=int(result.grad_evals), fit=result, targets=dict(targets))
