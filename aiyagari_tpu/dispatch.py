"""The solve(model, method, backend) dispatch boundary (BASELINE.json's north
star): one entry point routing on model family, solution method, and execution
backend.

  solve(AiyagariConfig(...), method="vfi", backend="jax")   -> EquilibriumResult
  solve(AiyagariConfig(...), method="egm", backend="numpy") -> EquilibriumResult
  solve(KrusellSmithConfig(...), method="vfi")              -> KSResult

The "numpy" backend is the framework's own CPU reference implementation — the
measured baseline denominator (BASELINE.md: the reference publishes no
numbers, so speedups are reported against this at the reference's scales).
"""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp

from aiyagari_tpu.config import (
    ALMConfig,
    AiyagariConfig,
    BackendConfig,
    EquilibriumConfig,
    KrusellSmithConfig,
    SimConfig,
    SolverConfig,
)

__all__ = ["solve"]


def _dtype_of(backend: BackendConfig):
    return jnp.float64 if backend.dtype == "float64" else jnp.float32


def solve(
    model: Union[AiyagariConfig, KrusellSmithConfig],
    *,
    method: Optional[str] = None,
    backend: Union[str, BackendConfig] = "jax",
    solver: Optional[SolverConfig] = None,
    sim: Optional[SimConfig] = None,
    equilibrium: Optional[EquilibriumConfig] = None,
    alm: Optional[ALMConfig] = None,
    aggregation: str = "simulation",
):
    """Solve a full model to general equilibrium.

    Aiyagari family -> interest-rate bisection (EquilibriumResult).
    Krusell-Smith   -> aggregate-law-of-motion fixed point (KSResult).

    The solution method comes from `method` or `solver.method`; passing both
    with different values is an error (never silently overridden). With
    neither, the default is "vfi". When `solver` is omitted, each model
    family supplies its own reference-faithful solver defaults (e.g. the
    Krusell-Smith tolerances/Howard schedule of Krusell_Smith_VFI.m:12-13).

    `aggregation` selects the Aiyagari capital-supply closure: "simulation"
    (the reference's Monte-Carlo time average, Aiyagari_VFI.m:94-129) or
    "distribution" (deterministic Young-histogram stationary distribution,
    sim/distribution.py — jax backend only).
    """
    if isinstance(backend, str):
        backend = BackendConfig(backend=backend)
    if backend.backend not in ("jax", "numpy"):
        raise ValueError(
            f"unknown backend {backend.backend!r}; expected 'jax' or 'numpy'"
        )
    if solver is not None and method is not None and solver.method != method:
        raise ValueError(
            f"conflicting methods: method={method!r} but solver.method={solver.method!r}"
        )
    method = method or (solver.method if solver is not None else "vfi")
    if method not in ("vfi", "egm"):
        raise ValueError(f"unknown method {method!r}; expected 'vfi' or 'egm'")

    if aggregation not in ("simulation", "distribution"):
        raise ValueError(
            f"unknown aggregation {aggregation!r}; expected 'simulation' or 'distribution'"
        )

    if isinstance(model, AiyagariConfig):
        solver = solver or SolverConfig(method=method)
        sim = sim or SimConfig()
        equilibrium = equilibrium or EquilibriumConfig()
        if backend.backend == "numpy":
            if aggregation != "simulation":
                raise ValueError("aggregation='distribution' requires backend='jax'")
            from aiyagari_tpu.solvers.numpy_backend import solve_equilibrium_numpy

            return solve_equilibrium_numpy(model, solver=solver, sim=sim, eq=equilibrium)
        from aiyagari_tpu.equilibrium.bisection import (
            solve_equilibrium,
            solve_equilibrium_distribution,
        )
        from aiyagari_tpu.models.aiyagari import AiyagariModel

        m = AiyagariModel.from_config(model, dtype=_dtype_of(backend))
        if aggregation == "distribution":
            return solve_equilibrium_distribution(m, solver=solver, eq=equilibrium)
        return solve_equilibrium(m, solver=solver, sim=sim, eq=equilibrium)

    if isinstance(model, KrusellSmithConfig):
        if aggregation != "simulation":
            raise ValueError(
                "aggregation='distribution' is not available for Krusell-Smith "
                "models: the ALM closure is defined over a simulated aggregate "
                "path (Krusell_Smith_VFI.m:250-296)"
            )
        alm = alm or ALMConfig()
        from aiyagari_tpu.equilibrium.alm import solve_krusell_smith

        # solver=None lets the KS loop apply its own reference defaults
        # (tol 1e-6, Howard 50/improve-every-5) rather than the generic ones.
        return solve_krusell_smith(
            model, method=method, solver=solver, alm=alm, backend=backend
        )

    raise TypeError(f"unknown model config type: {type(model).__name__}")
