"""Measured route selection for the framework's `"auto"` knobs.

The route observatory's decision half (ISSUE 12): `tuning/autotuner.py`
runs short interleaved measured probes per (platform fingerprint,
grid-size bucket, dtype) for each contested knob — the push-forward
backend, the EGM sweep kernel, the searchsorted method split — persists
the winners in a JSON tuning cache beside the XLA compile cache, and
feeds the `"auto"` resolvers (`ops/pushforward.resolve_backend`,
`ops/egm.resolve_egm_kernel`, `ops/interp.bucket_index`) from data
instead of hardcoded constants. Every resolution lands on the run ledger
as a `route_decision` event with the evidence behind it.

Off by default: with tuning disabled and no cache, every resolver
returns today's exact defaults (the PR 6 zero-cost discipline applied to
decisions; pinned by tests/test_tuning.py).
"""

from aiyagari_tpu.tuning.autotuner import (  # noqa: F401
    KNOBS,
    autotune,
    configure,
    explain,
    resolve_route,
    tuning_active,
    tuning_cache_path,
)

__all__ = [
    "KNOBS",
    "autotune",
    "configure",
    "explain",
    "resolve_route",
    "tuning_active",
    "tuning_cache_path",
]
