"""The `"auto"`-knob autotuner: measured probes, a stepping-safe tuning
cache, and the `route_decision` ledger trail behind every resolution.

The framework carries competing routes at every layer whose winners are
platform-dependent (PR 4 measured a 20x CPU/TPU split in one searchsorted
call; BENCH_r08 shows a 24x spread between push-forward routes), yet each
`"auto"` used to resolve to a hardcoded constant. This module turns that
assertion into an audited measurement:

  * `autotune()` runs short INTERLEAVED probes per contested knob — the
    candidates race round-robin so host drift hits every side equally
    (the PR 6/10 rotated-variant timing lesson), fenced through
    `diagnostics/profiler.fence` — and persists the winners in a JSON
    cache keyed by (knob, grid-size bucket, dtype) inside a document
    stamped with the jax version and the platform fingerprint
    (`io_utils/compile_cache._host_cpu_tag`, the same stepping-safe
    keying as the XLA compile cache next to which the file lives).
  * `resolve_route(knob, default, ...)` is what the three sanctioned
    resolvers (`ops/pushforward.resolve_backend`,
    `ops/egm.resolve_egm_kernel`, `ops/interp.bucket_index` via
    `searchsorted_method`) call on the `"auto"` path. With tuning ON it
    consults the cache (source `"measured"`), falls back to the roofline
    prior on modeled platforms (source `"prior"`,
    `diagnostics/roofline.py` pricing each candidate against the chip
    peaks), and otherwise — and ALWAYS with tuning off — returns the
    caller's default unchanged (source `"default"`).
  * Every `"auto"` resolution emits one `route_decision` event
    `{knob, choice, source, evidence}` on the active run ledger plus an
    `aiyagari_route_decisions_total{knob=,choice=,source=}` counter,
    deduplicated per activation scope so a `dispatch.solve`/`sweep` run
    carries exactly one decision per knob (the dedup set resets when
    `diagnostics/ledger.activate` enters).

Zero-cost discipline: tuning is OFF unless `AIYAGARI_TPU_TUNING=1` (or
`configure(enabled=True)`); the off path never touches the filesystem and
returns bit-identical defaults, so solve programs and results are
unchanged (jaxpr/result-pinned by tests/test_tuning.py).

Cache hygiene: a document whose jax version or platform fingerprint no
longer matches is invalidated wholesale (counted in
`aiyagari_tuning_cache_invalidated_total`); a torn/corrupt file warns
loudly, emits a ledger degradation event, and is treated as empty rather
than killing the solve; every consult lands in
`aiyagari_tuning_cache_{hits,misses}_total`.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import math
import os
import time
import warnings
from pathlib import Path
from typing import Callable, Dict, Optional, Sequence, Tuple

__all__ = [
    "KNOBS",
    "KnobSpec",
    "autotune",
    "capture_decisions",
    "configure",
    "explain",
    "grid_bucket",
    "load_cache",
    "platform_fingerprint",
    "probe_knob",
    "replay_decisions",
    "resolve_route",
    "save_cache",
    "tune_main",
    "tuning_active",
    "tuning_cache_path",
    "tuning_cache_stamp",
]

_CACHE_VERSION = 1
_ENV_ENABLED = "AIYAGARI_TPU_TUNING"
_ENV_CACHE = "AIYAGARI_TPU_TUNING_CACHE"

# Module override state (configure()); None defers to the environment.
_enabled_override: Optional[bool] = None
_cache_path_override: Optional[str] = None
# Paths whose torn-file warning already fired (warn once per process, not
# per resolution — the loud-but-non-fatal contract must not spam a sweep).
_torn_warned: set = set()
# load_cache memo keyed by path -> ((mtime_ns, size), validated doc):
# resolution sites run inside per-round host loops (the K-S ALM loop) and
# must not re-read + re-parse an unchanged file every round. A re-written
# file changes its stat signature and refreshes the memo.
_doc_memo: dict = {}


def _platform() -> str:
    """The resolved jax backend — one seam so tests can exercise the
    TPU-only prior path without hardware."""
    import jax

    return jax.default_backend()


def tuning_active() -> bool:
    """Whether resolvers may consult the cache/prior. Off by default."""
    if _enabled_override is not None:
        return _enabled_override
    return os.environ.get(_ENV_ENABLED, "") not in ("", "0")


def platform_fingerprint() -> str:
    """backend + host-CPU-stepping tag — the cache document's identity
    (the compile cache's keying, reused so the two caches age together)."""
    from aiyagari_tpu.io_utils.compile_cache import _host_cpu_tag

    return f"{_platform()}-{_host_cpu_tag()}"


def tuning_cache_path() -> Optional[Path]:
    """Resolve the cache file: configure() override, then
    $AIYAGARI_TPU_TUNING_CACHE, then
    ~/.cache/aiyagari_tpu/tuning-{backend}-{cpu_tag}.json (beside the XLA
    compile cache directories). An empty env value disables persistence
    entirely (returns None) — the compile cache's kill-switch semantics."""
    if _cache_path_override is not None:
        # The empty string disables persistence, exactly like the env
        # kill switch below — Path("") would silently mean the cwd.
        return Path(_cache_path_override) if _cache_path_override else None
    env = os.environ.get(_ENV_CACHE)
    if env == "":
        return None
    if env:
        return Path(env)
    return Path(os.path.expanduser("~")) / ".cache" / "aiyagari_tpu" / (
        f"tuning-{platform_fingerprint()}.json")


@contextlib.contextmanager
def configure(*, enabled: Optional[bool] = None,
              cache_path: Optional[str] = None):
    """Scope the tuner's state (tests, the `tune` CLI): `enabled`
    overrides the env gate, `cache_path` the cache file. Restores the
    previous state on exit."""
    global _enabled_override, _cache_path_override
    prev = (_enabled_override, _cache_path_override)
    if enabled is not None:
        _enabled_override = enabled
    if cache_path is not None:
        _cache_path_override = str(cache_path)
    try:
        yield
    finally:
        _enabled_override, _cache_path_override = prev


def grid_bucket(na: Optional[int]) -> str:
    """Pow-2 grid-size bucket ("b512") — probe walls generalize across
    nearby sizes but not across orders of magnitude; "any" when the
    resolution site has no grid in hand (dispatch-boundary validation)."""
    if na is None:
        return "any"
    return f"b{1 << max(int(na) - 1, 1).bit_length()}"


def _dtype_name(dtype) -> str:
    if dtype is None:
        return "any"
    import numpy as np

    return str(np.dtype(dtype))


def _entry_key(knob: str, bucket: str, dtype_name: str) -> str:
    return f"{knob}|{bucket}|{dtype_name}"


# -- cache I/O --------------------------------------------------------------


def _fresh_doc() -> dict:
    import jax

    return {"version": _CACHE_VERSION, "jax_version": jax.__version__,
            "fingerprint": platform_fingerprint(), "entries": {}}


def load_cache(path=None) -> dict:
    """Load + validate the tuning cache document. Missing file -> fresh
    empty doc. Torn/corrupt file -> LOUD warning + ledger degradation
    event + fresh doc (non-fatal: a broken cache must never kill a
    solve). Stale identity (jax version / platform fingerprint changed)
    -> invalidated wholesale, counted."""
    from aiyagari_tpu.diagnostics import ledger, metrics

    p = Path(path) if path is not None else tuning_cache_path()
    if p is None or not p.exists():
        return _fresh_doc()
    try:
        st = p.stat()
        sig = (st.st_mtime_ns, st.st_size)
        memo = _doc_memo.get(str(p))
        if memo is not None and memo[0] == sig:
            return memo[1]
    except OSError:
        sig = None
    try:
        doc = json.loads(p.read_text())
        if not isinstance(doc, dict) or "entries" not in doc:
            raise ValueError("tuning cache document has no 'entries'")
    except (json.JSONDecodeError, ValueError, OSError) as e:
        metrics.counter("aiyagari_tuning_cache_torn_total").inc()
        ledger.emit("degradation", event="tuning_cache_torn", path=str(p),
                    error=str(e)[:200])
        if str(p) not in _torn_warned:
            _torn_warned.add(str(p))
            warnings.warn(
                f"tuning cache {p} is torn/corrupt ({e}); ignoring it — "
                "re-run `python -m aiyagari_tpu tune` to rebuild",
                RuntimeWarning, stacklevel=2)
        return _fresh_doc()
    fresh = _fresh_doc()
    if (doc.get("version") != _CACHE_VERSION
            or doc.get("jax_version") != fresh["jax_version"]
            or doc.get("fingerprint") != fresh["fingerprint"]):
        # The measurements were taken under a different jax lowering or
        # on different silicon — both move route walls, so the whole
        # document is stale, not just one entry.
        metrics.counter("aiyagari_tuning_cache_invalidated_total").inc()
        doc = fresh
    if sig is not None:
        _doc_memo[str(p)] = (sig, doc)
    return doc


def save_cache(doc: dict, path=None) -> Optional[Path]:
    p = Path(path) if path is not None else tuning_cache_path()
    if p is None:
        return None
    p.parent.mkdir(parents=True, exist_ok=True)
    tmp = p.with_suffix(p.suffix + ".tmp")
    tmp.write_text(json.dumps(doc, indent=2, sort_keys=True) + "\n")
    os.replace(tmp, p)   # atomic: a concurrent reader never sees a torn doc
    return p


def _lookup(doc: dict, knob: str, na: Optional[int], dtype) -> Optional[dict]:
    """Best matching cache entry for (knob, grid bucket, dtype): exact
    bucket+dtype first, then same-dtype nearest bucket, then any-dtype
    nearest bucket. Nearness is log2 bucket distance — probe walls drift
    smoothly in size, so the nearest measurement beats no measurement."""
    entries = {k: v for k, v in doc.get("entries", {}).items()
               if len(k.split("|")) == 3 and k.split("|")[0] == knob
               and isinstance(v, dict) and v.get("choice")}
    if not entries:
        return None
    want_b, want_d = grid_bucket(na), _dtype_name(dtype)
    exact = entries.get(_entry_key(knob, want_b, want_d))
    if exact is not None:
        return exact

    def bucket_dist(key: str) -> float:
        b = key.split("|")[1]
        if want_b == "any" or b == "any":
            return 0.5
        try:
            return abs(math.log2(int(b[1:])) - math.log2(int(want_b[1:])))
        except ValueError:   # hand-edited bucket tag: neutral distance
            return 0.5

    def score(item):
        key, _ = item
        d = key.split("|")[2]
        dtype_penalty = 0.0 if (want_d == "any" or d == want_d) else 10.0
        return dtype_penalty + bucket_dist(key)

    return min(entries.items(), key=score)[1]


# -- the roofline prior -----------------------------------------------------


def _predicted_seconds(cost, peaks) -> float:
    """Roofline time estimate: the binding resource's transfer time."""
    return max(cost.mxu_flops / peaks.matmul_flops,
               cost.vpu_ops / peaks.vpu_ops,
               cost.hbm_bytes / peaks.hbm_bytes)


def _prior_choice(knob: str, na: Optional[int], dtype,
                  platform: str) -> Optional[Tuple[str, dict]]:
    """Price each candidate with the analytic roofline models against the
    platform's chip peaks and pick the cheapest. Only platforms with a
    chip model (CHIP_PEAKS) have a prior — elsewhere the resolver keeps
    the shipped default. Returns (choice, evidence) or None."""
    from aiyagari_tpu.diagnostics.roofline import (
        CHIP_PEAKS,
        distribution_sweep_cost,
        dtype_itemsize,
        egm_fused_sweep_cost,
        egm_sweep_cost,
    )

    peaks = CHIP_PEAKS.get(platform)
    if peaks is None or na is None:
        return None
    item = dtype_itemsize(dtype) if dtype is not None else 4
    nz = 7   # the reference income-state count; route ordering is nz-robust
    if knob == "pushforward":
        costs = {rt: distribution_sweep_cost(nz, int(na), item, route=rt)
                 for rt in ("scatter", "transpose", "banded", "pallas")}
    elif knob == "egm_kernel":
        costs = {"xla": egm_sweep_cost(nz, int(na), item),
                 "pallas_fused": egm_fused_sweep_cost(nz, int(na), item)}
    else:
        return None   # no analytic model for the searchsorted split
    pred = {rt: _predicted_seconds(c, peaks) * 1e6 for rt, c in costs.items()}
    choice = min(pred, key=pred.get)
    return choice, {"predicted_us": {k: round(v, 3) for k, v in pred.items()}}


# -- decision recording -----------------------------------------------------

# Stack of armed capture buffers (capture_decisions): every decision that
# flows through _record_decision is ALSO appended to the innermost buffer,
# whether or not a ledger was active to receive the event. The dispatch
# boundary memoizes route resolutions per (config fingerprint, cache stamp)
# and replays the captured decisions on memo hits, so the exactly-one
# route_decision-per-activation contract survives the caching.
_decision_capture: list = []


@contextlib.contextmanager
def capture_decisions():
    """Collect every _record_decision call in this scope as replayable
    (knob, choice, source, evidence, na, dtype) tuples — armed by
    dispatch._resolve_routes around a memo MISS so later hits can replay
    the identical decisions without re-running the resolvers."""
    buf: list = []
    _decision_capture.append(buf)
    try:
        yield buf
    finally:
        _decision_capture.pop()


def replay_decisions(decisions) -> None:
    """Re-emit previously captured decisions into the CURRENT activation
    scope (dispatch memo hits). Goes through _record_decision, so the
    per-activation dedup set still guarantees one event per knob."""
    for knob, choice, source, evidence, na, dtype in decisions:
        _record_decision(knob, choice, source, evidence, na=na, dtype=dtype)


def tuning_cache_stamp():
    """Identity of the tuning-cache state route resolutions depend on:
    (path, mtime_ns, size) of the cache document, (path, None) when the
    file is absent, or None when persistence is disabled. A probe run
    rewrites the cache atomically (save_cache's os.replace), moving the
    stamp — so memoized route resolutions invalidate exactly when the
    measured decisions could change, and never sooner."""
    p = tuning_cache_path()
    if p is None:
        return None
    try:
        st = p.stat()
    except OSError:
        return (str(p), None)
    return (str(p), st.st_mtime_ns, st.st_size)


def _record_decision(knob: str, choice: str, source: str, evidence: dict,
                     *, na: Optional[int], dtype) -> None:
    """Emit the route_decision event + counter for one `"auto"`
    resolution, deduplicated per ledger-activation scope and knob so a
    dispatch.solve/sweep run carries exactly one decision per knob (the
    dedup set is cleared on ledger.activate entry). No active ledger ->
    no event, no counter — resolution stays free for library users who
    opted into neither observability nor tuning. An armed capture buffer
    (capture_decisions) records the decision regardless, so memoized
    resolutions can replay it into later activation scopes."""
    from aiyagari_tpu.diagnostics import ledger, metrics

    if _decision_capture:
        _decision_capture[-1].append((knob, choice, source, evidence, na,
                                      dtype))
    led = ledger.active_ledger()
    if led is None:
        return
    emitted = led.__dict__.setdefault("_route_decisions_emitted", set())
    if knob in emitted:
        return
    emitted.add(knob)
    led.event("route_decision", knob=knob, choice=choice, source=source,
              evidence=evidence, bucket=grid_bucket(na),
              dtype=_dtype_name(dtype))
    metrics.counter("aiyagari_route_decisions_total", knob=knob,
                    choice=choice, source=source).inc()


def resolve_route(knob: str, default: str, *, na: Optional[int] = None,
                  dtype=None) -> str:
    """Resolve one `"auto"` knob: measured cache entry -> roofline prior
    -> the caller's default, in that order — the first two only with
    tuning active. Records the decision (see _record_decision) and
    returns the chosen route name. The off path returns `default`
    untouched, so disabled-tuning resolution is bit-identical to the
    historical constants."""
    from aiyagari_tpu.diagnostics import metrics

    choice, source, evidence = default, "default", {}
    if tuning_active():
        entry = _lookup(load_cache(), knob, na, dtype)
        if entry is not None:
            metrics.counter("aiyagari_tuning_cache_hits_total",
                            knob=knob).inc()
            choice, source = entry["choice"], "measured"
            evidence = {"walls_us": entry.get("walls_us", {}),
                        "probe_na": entry.get("na"),
                        "measured_utc": entry.get("utc")}
        else:
            metrics.counter("aiyagari_tuning_cache_misses_total",
                            knob=knob).inc()
            prior = _prior_choice(knob, na, dtype, _platform())
            if prior is not None:
                choice, source = prior[0], "prior"
                evidence = prior[1]
    _record_decision(knob, choice, source, evidence, na=na, dtype=dtype)
    return choice


# -- measured probes --------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class KnobSpec:
    """One contested knob: its shipped default and the probe building the
    per-candidate timed closures at a (na, dtype) workload."""

    name: str
    default: Callable[[], str]
    candidates: Callable[[], Tuple[str, ...]]
    build_probe: Callable[[int, object], Dict[str, Callable]]


def _interleaved_walls(fns: Dict[str, Callable], reps: int) -> Dict[str, float]:
    """Best-of-`reps` walls (µs) with the candidates raced ROUND-ROBIN:
    one warm fenced call each (compile excluded), then every rep times
    all candidates back to back so host drift lands on each side equally
    — ratios need both sides sampled under the same drift (the PR 6/10
    rotated-variant lesson, bench.py timed_pair)."""
    from aiyagari_tpu.diagnostics.profiler import fence

    for fn in fns.values():
        fence(fn())
    keys = list(fns)
    best = {k: float("inf") for k in keys}
    for r in range(max(int(reps), 1)):
        # Rotate the start position per rep (the PR 10 quarantine-overhead
        # fix): a fixed order would time the same candidate last every
        # rep, so position-correlated drift (thermal ramp, a periodic
        # background burst) biases its best-of-reps wall.
        for k in keys[r % len(keys):] + keys[:r % len(keys)]:
            t0 = time.perf_counter()
            fence(fns[k]())
            best[k] = min(best[k], time.perf_counter() - t0)
    return {k: round(v * 1e6, 3) for k, v in best.items()}


def _probe_pushforward(na: int, dtype) -> Dict[str, Callable]:
    import jax
    import jax.numpy as jnp

    from aiyagari_tpu.ops.pushforward import pushforward_step

    nz = 7
    # A monotone near-45-degree lottery — the savings-policy shape every
    # route sees in production, so the banded window fits and no fallback
    # cond fires mid-probe.
    idx = jnp.broadcast_to(jnp.clip(jnp.arange(na, dtype=jnp.int32) - 1,
                                    0, na - 2)[None, :], (nz, na))
    w_lo = jnp.full((nz, na), 0.5, dtype)
    mu = jnp.full((nz, na), 1.0 / (nz * na), dtype)
    P = jnp.full((nz, nz), 1.0 / nz, dtype)
    candidates = ["scatter", "transpose", "banded"]
    if _platform() == "tpu":
        candidates.append("pallas")   # interpreted off-TPU: never a winner,
        # and minutes-slow at probe sizes — racing it would poison nothing
        # but waste the whole probe budget (bench r08 times it separately).

    def make(rt):
        step = jax.jit(lambda m, i, w, p: pushforward_step(m, i, w, p,
                                                           backend=rt))
        return lambda: step(mu, idx, w_lo, P)

    return {rt: make(rt) for rt in candidates}


def _probe_pushforward_batched(na: int, dtype) -> Dict[str, Callable]:
    """The VMAPPED-context push-forward race (ISSUE 16): the same
    monotone-lottery workload as `_probe_pushforward`, but vmapped over a
    sweep's worth of lanes — the program shape the lockstep GE sweep and
    parallel-bracket rounds actually run. Solo walls do NOT transfer (the
    ISSUE 15 measurement: vmapped transpose gathers ~5.5x/lane slower on
    hosts while scatter scales linearly), which is exactly why this is a
    separate knob with its own measured entries."""
    import jax
    import jax.numpy as jnp

    from aiyagari_tpu.ops.pushforward import pushforward_step

    nz, lanes = 7, 6   # the serve/ci sweep width the 5.5x split was seen at
    idx = jnp.broadcast_to(jnp.clip(jnp.arange(na, dtype=jnp.int32) - 1,
                                    0, na - 2)[None, None, :],
                           (lanes, nz, na))
    w_lo = jnp.full((lanes, nz, na), 0.5, dtype)
    mu = jnp.full((lanes, nz, na), 1.0 / (nz * na), dtype)
    P = jnp.full((nz, nz), 1.0 / nz, dtype)
    candidates = ["scatter", "transpose", "banded"]
    if _platform() == "tpu":
        candidates.append("pallas")   # same exclusion logic as the solo probe

    def make(rt):
        step = jax.jit(jax.vmap(
            lambda m, i, w: pushforward_step(m, i, w, P, backend=rt)))
        return lambda: step(mu, idx, w_lo)

    return {rt: make(rt) for rt in candidates}


def _probe_egm_kernel(na: int, dtype) -> Dict[str, Callable]:
    from aiyagari_tpu.models.aiyagari import aiyagari_preset
    from aiyagari_tpu.ops.egm import egm_step
    from aiyagari_tpu.solvers.egm import initial_consumption_guess
    from aiyagari_tpu.utils.firm import wage_from_r

    model = aiyagari_preset(grid_size=na, dtype=dtype)
    r = 0.04
    w = float(wage_from_r(r, model.config.technology.alpha,
                          model.config.technology.delta))
    C0 = initial_consumption_guess(model.a_grid, model.s, r, w)
    candidates = ("xla", "pallas_fused") if _platform() == "tpu" else ("xla",)
    # Off-TPU the fused route runs the Pallas INTERPRETER — a correctness
    # vehicle whose wall says nothing about the Mosaic artifact, so it is
    # never raced into the cache there (the pallas_inverse round-2
    # lesson: TPU routes are validated on chip, not simulated).

    def make(rt):
        return lambda: egm_step(C0, model.a_grid, model.s, model.P, r, w,
                                model.amin, sigma=model.preferences.sigma,
                                beta=model.preferences.beta, egm_kernel=rt)

    return {rt: make(rt) for rt in candidates}


def _probe_bucket_index(na: int, dtype) -> Dict[str, Callable]:
    import jax
    import jax.numpy as jnp

    # The split only engages above the compare-all cutoff; probe at least
    # there so the measured walls describe the contested regime.
    n = max(int(na), 2048)
    x = jnp.linspace(0.0, 100.0, n, dtype=dtype)
    q = jnp.linspace(-1.0, 101.0, n, dtype=dtype)

    def make(method):
        fn = jax.jit(lambda xx, qq: jnp.searchsorted(
            xx, qq, side="right", method=method))
        return lambda: fn(x, q)

    return {m: make(m) for m in ("scan", "sort")}


KNOBS: Dict[str, KnobSpec] = {
    "pushforward": KnobSpec(
        name="pushforward",
        default=lambda: "transpose",
        candidates=lambda: ("scatter", "transpose", "banded") + (
            ("pallas",) if _platform() == "tpu" else ()),
        build_probe=_probe_pushforward),
    "pushforward_batched": KnobSpec(
        name="pushforward_batched",
        default=lambda: "scatter" if _platform() == "cpu" else "transpose",
        candidates=lambda: ("scatter", "transpose", "banded") + (
            ("pallas",) if _platform() == "tpu" else ()),
        build_probe=_probe_pushforward_batched),
    "egm_kernel": KnobSpec(
        name="egm_kernel",
        default=lambda: "xla",
        candidates=lambda: (("xla", "pallas_fused")
                            if _platform() == "tpu" else ("xla",)),
        build_probe=_probe_egm_kernel),
    "bucket_index": KnobSpec(
        name="bucket_index",
        default=lambda: "scan" if _platform() == "cpu" else "sort",
        candidates=lambda: ("scan", "sort"),
        build_probe=_probe_bucket_index),
}


def probe_knob(knob: str, *, na: int, dtype, reps: int = 3) -> dict:
    """Run one knob's measured probe and return its cache entry (not yet
    persisted): winner + per-candidate interleaved walls."""
    spec = KNOBS[knob]
    walls = _interleaved_walls(spec.build_probe(na, dtype), reps)
    return {
        "choice": min(walls, key=walls.get),
        "source": "measured",
        "walls_us": walls,
        "na": int(na),
        "reps": int(reps),
        "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }


def autotune(knobs: Optional[Sequence[str]] = None, *, na: int = 4096,
             dtype=None, reps: int = 3, cache_path=None) -> dict:
    """Probe every (requested) contested knob at the (na, dtype) workload
    and persist the winners into the tuning cache. Returns
    {entry_key: entry}. dtype defaults to the platform's solver dtype
    (f32 on TPU, f64 elsewhere — the bench convention)."""
    import jax.numpy as jnp

    from aiyagari_tpu.diagnostics import ledger, metrics

    if dtype is None:
        dtype = jnp.float32 if _platform() == "tpu" else jnp.float64
    names = list(knobs) if knobs is not None else list(KNOBS)
    unknown = set(names) - set(KNOBS)
    if unknown:
        raise ValueError(f"unknown tuning knob(s) {sorted(unknown)}; "
                         f"known: {sorted(KNOBS)}")
    import copy

    # Deep copy: load_cache memoizes the parsed doc by file signature,
    # and the entries merged below must not alias into that memo before
    # save_cache stamps a new signature.
    doc = copy.deepcopy(load_cache(cache_path))
    out = {}
    for name in names:
        entry = probe_knob(name, na=na, dtype=dtype, reps=reps)
        key = _entry_key(name, grid_bucket(na), _dtype_name(dtype))
        doc["entries"][key] = entry
        out[key] = entry
        metrics.counter("aiyagari_tuning_probes_total", knob=name).inc()
        ledger.emit("tuning_probe", knob=name, key=key,
                    choice=entry["choice"], walls_us=entry["walls_us"],
                    na=int(na), dtype=_dtype_name(dtype))
    save_cache(doc, cache_path)
    return out


# -- the decision table (tune CLI / --explain) ------------------------------


def explain(cache_path=None) -> list:
    """The decision table: one row per knob and cached measurement (plus
    a default row for knobs with no measurement), each reproducing the
    choice the resolvers would make from the evidence on file — probe
    walls re-argmin'd, never trusted blindly (a hand-edited cache whose
    stored winner disagrees with its own walls is surfaced, not
    replayed)."""
    doc = load_cache(cache_path)
    rows = []
    for name, spec in KNOBS.items():
        entries = {k: v for k, v in doc.get("entries", {}).items()
                   if k.split("|", 1)[0] == name}
        for key, entry in sorted(entries.items()):
            walls = entry.get("walls_us", {})
            if not isinstance(walls, dict):
                walls = {}
            # Re-argmin over the NUMERIC walls only: hand-edited entries
            # with malformed values must render as inconsistent rows, not
            # crash the renderer (the whole point of --explain).
            numeric = {k: v for k, v in walls.items()
                       if isinstance(v, (int, float))}
            reproduced = min(numeric, key=numeric.get) if numeric else None
            rows.append({
                "knob": name,
                "bucket": key.split("|")[1],
                "dtype": key.split("|")[2],
                "choice": entry.get("choice"),
                "source": "measured",
                "reproduced_choice": reproduced,
                "consistent": reproduced == entry.get("choice"),
                "evidence": {"walls_us": walls,
                             "na": entry.get("na"),
                             "measured_utc": entry.get("utc")},
            })
        if not entries:
            rows.append({
                "knob": name, "bucket": "any", "dtype": "any",
                "choice": spec.default(), "source": "default",
                "reproduced_choice": spec.default(), "consistent": True,
                "evidence": {"note": "no measurement cached; shipped "
                                     "default applies"},
            })
    return rows


def _render_rows(rows: list) -> str:
    lines = [f"{'knob':<14}{'bucket':<8}{'dtype':<10}{'choice':<16}"
             f"{'source':<10}evidence"]
    for r in rows:
        walls = r["evidence"].get("walls_us")
        if walls:
            def fmt(v):
                return (f"{v:.1f}us" if isinstance(v, (int, float))
                        else f"{v!r} (malformed)")

            ev = "  ".join(
                f"{k}={fmt(v)}" for k, v in
                sorted(walls.items(),
                       key=lambda kv: (not isinstance(kv[1], (int, float)),
                                       kv[1] if isinstance(kv[1],
                                                           (int, float))
                                       else 0.0)))
        else:
            ev = r["evidence"].get("note", "-")
        mark = "" if r.get("consistent", True) else "  !! stored choice " \
            "disagrees with its own walls"
        # str() everywhere: --explain is the debugging tool for exactly
        # the hand-edited caches whose entries may be malformed (a None
        # choice must render as a row, not crash the renderer).
        lines.append(f"{r['knob']:<14}{str(r['bucket']):<8}"
                     f"{str(r['dtype']):<10}{str(r['choice']):<16}"
                     f"{str(r['source']):<10}{ev}{mark}")
    return "\n".join(lines)


def tune_main(argv) -> int:
    """`python -m aiyagari_tpu tune [--explain]`: run the measured probes
    (or just render the cached decision table) — the CLI face of the
    route observatory (docs/USAGE.md "Route observatory & autotuning")."""
    import argparse

    ap = argparse.ArgumentParser(prog="aiyagari_tpu tune")
    ap.add_argument("--explain", action="store_true",
                    help="render the decision table from the cached probe "
                         "data without re-measuring")
    ap.add_argument("--na", type=int, default=4096,
                    help="grid size the probes run at (default 4096)")
    ap.add_argument("--dtype", choices=["float32", "float64"], default=None,
                    help="probe dtype (default: platform solver dtype)")
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--knobs", default=None,
                    help=f"comma-separated subset of {sorted(KNOBS)}")
    ap.add_argument("--cache", default=None,
                    help="tuning-cache file (default: "
                         "~/.cache/aiyagari_tpu/tuning-<fingerprint>.json)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    import jax

    if jax.default_backend() != "tpu":
        jax.config.update("jax_enable_x64", True)
    if not args.explain:
        from aiyagari_tpu.io_utils.compile_cache import enable_compilation_cache

        enable_compilation_cache()
        dtype = None
        if args.dtype:
            import jax.numpy as jnp

            dtype = jnp.float32 if args.dtype == "float32" else jnp.float64
        knobs = args.knobs.split(",") if args.knobs else None
        autotune(knobs, na=args.na, dtype=dtype, reps=args.reps,
                 cache_path=args.cache)
    rows = explain(args.cache)
    if args.json:
        print(json.dumps(rows, indent=2))
    else:
        path = args.cache or tuning_cache_path()
        print(f"tuning cache: {path}")
        print(_render_rows(rows))
    return 0
