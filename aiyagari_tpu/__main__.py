"""Command-line runner: reproduces each of the reference's six script
workflows end-to-end (solve -> simulate -> statistics -> figures).

  python -m aiyagari_tpu aiyagari --method vfi          # Aiyagari_VFI.m
  python -m aiyagari_tpu aiyagari --method egm          # Aiyagari_EGM.m
  python -m aiyagari_tpu aiyagari-labor --method vfi    # Aiyagari_Endogenous_Labor_VFI.m
  python -m aiyagari_tpu aiyagari-labor --method egm    # Aiyagari_Endogenous_Labor_EGM.m
  python -m aiyagari_tpu ks --method vfi                # Krusell_Smith_VFI.m
  python -m aiyagari_tpu ks --method egm                # Krusell_Smith_EGM.m

Defaults reproduce the reference problem scales (BASELINE.md); outputs land in
--outdir as figures + summary.json + run log (JSONL).

Observability (diagnostics/ledger.py + health.py + watch.py):

  python -m aiyagari_tpu report <ledger.jsonl> [...]    # render a run ledger
                                                        # (host shards merged)
  python -m aiyagari_tpu watch <ledger|shard-glob>      # live-merge + tail a
                                                        # running sweep's
                                                        # shards into a
                                                        # per-scenario/per-host
                                                        # table

Route observatory (tuning/autotuner.py; docs/USAGE.md "Route observatory
& autotuning"):

  python -m aiyagari_tpu tune                # measure the "auto" knobs,
                                             # persist the tuning cache
  python -m aiyagari_tpu tune --explain      # render the decision table
                                             # from the cached probe data

Persistent solve service (serve/; docs/USAGE.md "Persistent solve
service"):

  python -m aiyagari_tpu warmup [--na N]     # precompile the kernel zoo
                                             # into the compile cache
  python -m aiyagari_tpu serve --port 8799   # HTTP front: POST /solve,
                                             # GET /metrics, GET /healthz
  python -m aiyagari_tpu serve --load 32     # synthetic open-loop load
  python -m aiyagari_tpu fleet --workers 2   # N workers + routing front
                                             # (grid-class buckets, shared
                                             # L2 tier, graceful drain)
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    # `report` is a host-only subcommand (no model solve, no device use):
    # render a run ledger's events — runs, spans, verdicts, telemetry
    # summaries, degradations (diagnostics/health.report_main). Importing
    # it still pays the package __init__ (and thus jax import) — the early
    # return just skips the solver argument parsing below.
    if argv[:1] == ["report"]:
        from aiyagari_tpu.diagnostics.health import report_main

        return report_main(argv[1:])
    # `tune` runs the measured route probes (or, with --explain, renders
    # the cached decision table) — the route-observatory CLI
    # (tuning/autotuner.tune_main).
    if argv[:1] == ["tune"]:
        from aiyagari_tpu.tuning.autotuner import tune_main

        return tune_main(argv[1:])
    # `watch` tails + live-merges ledger shards into a per-scenario /
    # per-host progress table (diagnostics/watch.watch_main) — the pod
    # observatory's live view.
    if argv[:1] == ["watch"]:
        from aiyagari_tpu.diagnostics.watch import watch_main

        return watch_main(argv[1:])
    # `warmup` precompiles the registry catalogue (plus --na sized hot
    # programs) into the persistent compile cache and reports per-program
    # compile walls — the standalone warm pool (serve/warmup.warm_pool;
    # the server runs the same function at startup).
    if argv[:1] == ["warmup"]:
        from aiyagari_tpu.serve.warmup import warmup_main

        return warmup_main(argv[1:])
    # `serve` runs the persistent solve service (serve/service.py): the
    # HTTP front (--port: POST /solve, GET /metrics, GET /healthz) or the
    # synthetic open-loop load driver (--load N).
    if argv[:1] == ["serve"]:
        from aiyagari_tpu.serve.service import serve_main

        return serve_main(argv[1:])
    # `fleet` spawns N serve workers as separate processes behind a
    # grid-class routing front with graceful drain (serve/fleet.py) —
    # the pod-scale solve fabric.
    if argv[:1] == ["fleet"]:
        from aiyagari_tpu.serve.fleet import fleet_main

        return fleet_main(argv[1:])
    ap = argparse.ArgumentParser(prog="aiyagari_tpu", description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("model", choices=["aiyagari", "aiyagari-labor", "ks"])
    ap.add_argument("--method", choices=["vfi", "egm"], default="vfi")
    ap.add_argument("--outdir", default=None, help="default: runs/<model>_<method>")
    ap.add_argument("--platform", choices=["cpu", "tpu"], default=None,
                    help="force a jax platform (JAX_PLATFORMS env is overridden "
                         "by this image's TPU plugin; use this flag)")
    ap.add_argument("--f64", action="store_true", help="force float64")
    ap.add_argument("--dtype", choices=["float32", "float64", "mixed"], default=None,
                    help="dtype policy (overrides --f64): 'mixed' runs the "
                         "Aiyagari family through the mixed-precision solve "
                         "ladder (f32 hot sweeps, error-controlled f64 polish "
                         "— ops/precision.py) and Krusell-Smith through the "
                         "measured component split (f64 solve + regression, "
                         "native-f32 cross-section scan)")
    ap.add_argument("--grid", type=int, default=400, help="asset grid points (Aiyagari)")
    ap.add_argument("--periods", type=int, default=10_000, help="simulation length (Aiyagari)")
    ap.add_argument("--agents", type=int, default=1, help="simulated households (Aiyagari)")
    ap.add_argument("--k-size", type=int, default=100, help="individual capital grid (K-S)")
    ap.add_argument("--population", type=int, default=10_000, help="agent panel size (K-S)")
    ap.add_argument("--T", type=int, default=1100, help="panel length (K-S)")
    ap.add_argument("--alm-iters", type=int, default=100, help="max ALM iterations (K-S)")
    ap.add_argument("--acceleration", choices=["damped", "anderson"], default="damped",
                    help="ALM outer-loop update (K-S): the reference's damped "
                         "step or Anderson mixing (~2.5x fewer rounds)")
    ap.add_argument("--closure", choices=["panel", "histogram"], default="panel",
                    help="K-S cross-section: Monte-Carlo agent panel "
                         "(reference-faithful) or deterministic Young histogram")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--checkpoint-dir", default=None, help="enable checkpoint/resume")
    ap.add_argument("--mesh-agents", action="store_true",
                    help="shard the K-S agent panel over all local devices")
    ap.add_argument("--quiet", action="store_true")
    ap.add_argument("--ledger", default=None,
                    help="append this run's flight record (config "
                         "fingerprint, spans, telemetry, verdicts) to a "
                         "JSONL run ledger; render it later with "
                         "`python -m aiyagari_tpu report <path>` (on a "
                         "multi-host pod each host writes its own "
                         "<path>.p{k}.jsonl shard)")
    ap.add_argument("--heartbeat", type=int, default=0, metavar="N",
                    help="live-watch cadence: emit every Nth solver "
                         "progress record to the ledger as a `heartbeat` "
                         "event (requires --ledger; tail the run with "
                         "`python -m aiyagari_tpu watch <ledger>`). Also "
                         "sets progress_every=N, compiling the in-jit "
                         "progress callback into the solve — only the "
                         "ledger stride itself is program-neutral (a run "
                         "with progress already on pays nothing extra)")
    args = ap.parse_args(argv)
    if args.heartbeat and not args.ledger:
        # Without a ledger the stride has nowhere to land, yet
        # progress_every would still compile host callbacks into the
        # solve — a silent cost with zero output. Refuse loudly.
        ap.error("--heartbeat requires --ledger (heartbeat events land "
                 "on the run ledger)")

    if args.platform:
        import jax

        # Verbatim so --platform tpu errors loudly if the TPU backend is
        # unavailable instead of silently auto-detecting onto CPU.
        jax.config.update("jax_platforms", args.platform)
    import jax

    # After the platform choice (the cache dir is keyed by it — a CPU-forced
    # run must not share AOT artifacts with TPU-attached runs), and after
    # argparse so --help stays instant.
    from aiyagari_tpu.io_utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()

    from aiyagari_tpu.config import (
        ALMConfig,
        AiyagariConfig,
        BackendConfig,
        EquilibriumConfig,
        GridSpecConfig,
        IncomeProcess,
        KrusellSmithConfig,
        SimConfig,
        SolverConfig,
    )
    from aiyagari_tpu.diagnostics.logging import ConsoleSink, JSONLSink, multiplex

    outdir = args.outdir or f"runs/{args.model}_{args.method}"
    sink = multiplex(
        None if args.quiet else ConsoleSink(prefix=f"[{args.model}/{args.method}] "),
        JSONLSink(f"{outdir}/iterations.jsonl"),
    )
    # Aiyagari family: f64 by default on CPU, f32 on TPU (its solvers hit the
    # reference tolerances in f32 — test_precision). Krusell-Smith: f64
    # everywhere — its ALM fixed point limit-cycles in f32 (BENCHMARKS.md);
    # the solve entry points enable x64 locally via config.precision_scope.
    use_f64 = args.f64 or (jax.default_backend() == "cpu") or args.model == "ks"
    dtype = args.dtype or ("float64" if use_f64 else "float32")
    if dtype in ("float64", "mixed"):
        jax.config.update("jax_enable_x64", True)
    backend = BackendConfig(dtype=dtype)

    led = None
    if args.ledger:
        from aiyagari_tpu.diagnostics.ledger import RunLedger

        led = RunLedger(args.ledger,
                        meta={"entry": f"{args.model}/{args.method}",
                              "outdir": outdir})
    if args.heartbeat:
        # Host-side only: the stride gates which delivered records reach
        # the ledger; the traced programs depend on progress_every alone.
        from aiyagari_tpu.diagnostics.progress import configure_heartbeat

        configure_heartbeat(args.heartbeat)
    from aiyagari_tpu.dispatch import _ledger_result, _observe

    if args.model in ("aiyagari", "aiyagari-labor"):
        import jax.numpy as jnp

        from aiyagari_tpu.equilibrium.bisection import solve_equilibrium
        from aiyagari_tpu.io_utils.report import equilibrium_report
        from aiyagari_tpu.models.aiyagari import AiyagariModel

        if args.model == "aiyagari":
            cfg = AiyagariConfig(grid=GridSpecConfig(n_points=args.grid))
        else:
            cfg = AiyagariConfig(
                income=IncomeProcess(rho=0.6, sigma_e=0.2),
                endogenous_labor=True,
                grid=GridSpecConfig(n_points=args.grid),
            )
        # "mixed" = the mixed-precision solve ladder (ops/precision.py):
        # the model is built at the f64 reference dtype and the solvers run
        # f32 hot stages with an error-controlled f64 polish.
        from aiyagari_tpu.ops.precision import ladder_for_dtype

        ladder = ladder_for_dtype(backend.dtype)
        model = AiyagariModel.from_config(
            cfg, jnp.float32 if backend.dtype == "float32" else jnp.float64
        )
        with _observe(led, "aiyagari_ge", method=args.method):
            res = solve_equilibrium(
                model,
                solver=SolverConfig(method=args.method, ladder=ladder,
                                    progress_every=args.heartbeat),
                sim=SimConfig(periods=args.periods, n_agents=args.agents, seed=args.seed),
                eq=EquilibriumConfig(),
                on_iteration=sink,
                checkpoint_dir=args.checkpoint_dir,
            )
        _ledger_result(led, "Aiyagari GE bisection", res,
                       converged=res.converged, iterations=res.iterations,
                       distance=(abs(res.k_supply[-1] - res.k_demand[-1])
                                 if res.k_supply else float("inf")),
                       tol=EquilibriumConfig().tol)
        summary = equilibrium_report(res, model, outdir)
    else:
        from aiyagari_tpu.equilibrium.alm import solve_krusell_smith
        from aiyagari_tpu.io_utils.report import krusell_smith_report

        if args.mesh_agents:
            backend = dataclasses.replace(backend, mesh_axes=("agents",))
        alm_cfg = ALMConfig(T=args.T, population=args.population,
                            max_iter=args.alm_iters, seed=args.seed,
                            acceleration=args.acceleration)
        with _observe(led, "krusell_smith", method=args.method):
            import dataclasses as _dc

            from aiyagari_tpu.equilibrium.alm import _default_ks_solver_config

            res = solve_krusell_smith(
                KrusellSmithConfig(k_size=args.k_size),
                method=args.method,
                # The reference-tolerance KS solver config, with only the
                # heartbeat progress stride overridden (progress_every=0
                # keeps it identical to the historical default).
                solver=_dc.replace(_default_ks_solver_config(args.method),
                                   progress_every=args.heartbeat),
                alm=alm_cfg,
                backend=backend,
                on_iteration=sink,
                checkpoint_dir=args.checkpoint_dir,
                closure=args.closure,
            )
        _ledger_result(led, "Krusell-Smith ALM fixed point", res,
                       converged=res.converged, iterations=res.iterations,
                       distance=res.diff_B, tol=alm_cfg.tol)
        summary = krusell_smith_report(res, outdir, discard=min(100, args.T // 4))

    print(json.dumps(summary, indent=2))
    print(f"figures + summary.json written to {outdir}/")
    return 0


if __name__ == "__main__":
    sys.exit(main())
