"""The kernel zoo: every hot program the jaxpr auditor certifies, as a
registry of TRACEABLE entry points.

Each `ProgramSpec` knows how to build (fn, abstract_args) pairs for
`jax.make_jaxpr` — `jax.ShapeDtypeStruct` inputs wherever the entry point
accepts them (an eval_shape-style trace: nothing solves, nothing big
allocates, so the audit is CPU-deterministic and adds seconds, not
minutes, to tier-1), with tiny CONCRETE host arrays only where an entry
point requires trace-time concreteness (power-grid bounds, model
closures). Shapes are deliberately small and mutually distinct from the
telemetry sentinel capacity below.

Registering a new program
-------------------------
Add a `ProgramSpec` to `_build_registry()`:

    ProgramSpec(
        name="my_family/my_program",       # stable, shows up in findings
        family="my_family",
        build_off=<() -> (fn, args)>,      # telemetry OFF (or N/A)
        build_on=<() -> (fn, args)>,       # same program, recorder ON
                                           # (omit when not wired)
        scatter_free=True,                 # AIYA101 applies
        stage_dtype="float32",             # AIYA102 stage declaration
    )

`build_off` must trace without devices beyond the default CPU backend;
raise `ProgramUnavailable("reason")` for environment-dependent programs
(e.g. the ring-sharded EGM sweep needs >= 2 mesh devices) — the run
reports them as skipped instead of failing.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Callable, List, Optional, Tuple

__all__ = [
    "ProgramSpec",
    "ProgramUnavailable",
    "TELEMETRY_SENTINEL_CAPACITY",
    "registered_programs",
]

# The recorder ring is traced at this capacity for the telemetry-noop
# check. Prime and far from every registry shape dimension, so a
# sentinel-sized dimension in a telemetry-off jaxpr can only be recorder
# residue, never a model array.
TELEMETRY_SENTINEL_CAPACITY = 193

# Registry trace shapes (small: tracing cost only, nothing iterates).
_NZ = 3     # income states
_NA = 16    # asset gridpoints
_T = 5      # transition horizon


class ProgramUnavailable(RuntimeError):
    """This program cannot be traced in the current environment (the run
    records it as skipped, with this reason)."""


@dataclasses.dataclass(frozen=True)
class ProgramSpec:
    name: str
    family: str
    build_off: Callable[[], tuple]
    build_on: Optional[Callable[[], tuple]] = None
    scatter_free: bool = False
    stage_dtype: Optional[str] = None

    @property
    def supports_telemetry(self) -> bool:
        return self.build_on is not None


def _sds(shape, dtype):
    import jax

    return jax.ShapeDtypeStruct(shape, dtype)


def _f(shape=()):
    import jax.numpy as jnp

    return _sds(shape, jnp.float64)


def _f32(shape=()):
    import jax.numpy as jnp

    return _sds(shape, jnp.float32)


def _i32(shape=()):
    import jax.numpy as jnp

    return _sds(shape, jnp.int32)


def _telemetry_cfg():
    from aiyagari_tpu.config import TelemetryConfig

    return TelemetryConfig(capacity=TELEMETRY_SENTINEL_CAPACITY)


# -- builders ---------------------------------------------------------------
# Each returns (fn, args) with fn closing over every static knob, so
# jax.make_jaxpr(fn)(*args) is the whole trace recipe.


def _egm_args(dtype_fn):
    return (dtype_fn((_NZ, _NA)), dtype_fn((_NA,)), dtype_fn((_NZ,)),
            dtype_fn((_NZ, _NZ)), dtype_fn(), dtype_fn(), dtype_fn(),
            dtype_fn(), dtype_fn())


def _build_egm(telemetry=None, ladder=None, dtype_fn=_f, sentinel=None,
               egm_kernel="xla"):
    from aiyagari_tpu.solvers.egm import solve_aiyagari_egm

    def fn(C, a_grid, s, P, r, w, amin, sigma, beta):
        return solve_aiyagari_egm(C, a_grid, s, P, r, w, amin, sigma=sigma,
                                  beta=beta, tol=1e-6, max_iter=50,
                                  ladder=ladder, telemetry=telemetry,
                                  sentinel=sentinel, egm_kernel=egm_kernel)

    return fn, _egm_args(dtype_fn)


def _sentinel_cfg():
    from aiyagari_tpu.config import SentinelConfig

    return SentinelConfig()


def _build_egm_labor(telemetry=None):
    from aiyagari_tpu.solvers.egm import solve_aiyagari_egm_labor

    def fn(C, a_grid, s, P, r, w, amin, sigma, beta):
        return solve_aiyagari_egm_labor(
            C, a_grid, s, P, r, w, amin, sigma=sigma, beta=beta, psi=1.0,
            eta=2.0, tol=1e-6, max_iter=50, telemetry=telemetry)

    return fn, _egm_args(_f)


def _build_vfi(telemetry=None):
    from aiyagari_tpu.solvers.vfi import solve_aiyagari_vfi

    def fn(v, a_grid, s, P, r, w, sigma, beta):
        return solve_aiyagari_vfi(v, a_grid, s, P, r, w, sigma=sigma,
                                  beta=beta, tol=1e-6, max_iter=50,
                                  telemetry=telemetry)

    return fn, (_f((_NZ, _NA)), _f((_NA,)), _f((_NZ,)), _f((_NZ, _NZ)),
                _f(), _f(), _f(), _f())


def _build_distribution_step(backend: str):
    from aiyagari_tpu.sim.distribution import distribution_step

    def fn(mu, idx, w_lo, P):
        return distribution_step(mu, idx, w_lo, P, backend=backend)

    return fn, (_f((_NZ, _NA)), _i32((_NZ, _NA)), _f((_NZ, _NA)),
                _f((_NZ, _NZ)))


def _build_stationary(telemetry=None, pushforward: str = "auto"):
    from aiyagari_tpu.sim.distribution import stationary_distribution

    def fn(policy_k, a_grid, P):
        return stationary_distribution(policy_k, a_grid, P, tol=1e-8,
                                       max_iter=200, pushforward=pushforward,
                                       telemetry=telemetry)

    return fn, (_f((_NZ, _NA)), _f((_NA,)), _f((_NZ, _NZ)))


def _build_egm_sharded(telemetry=None):
    import jax

    import numpy as np

    if len(jax.devices()) < 2:
        raise ProgramUnavailable(
            "the ring-sharded EGM sweep needs a >= 2-device mesh (run "
            "under XLA_FLAGS=--xla_force_host_platform_device_count=8, as "
            "tier-1 does, to audit it on a CPU host)")
    from aiyagari_tpu.parallel.mesh import GRID_AXIS, make_mesh
    from aiyagari_tpu.solvers.egm_sharded import _egm_program
    from aiyagari_tpu.utils.grids import power_grid

    D = 2
    na = 64  # big enough for the ring slab at capacity 2.0 on 2 devices
    mesh = make_mesh((GRID_AXIS,), (D,), devices=np.array(jax.devices()[:D]))
    grid = power_grid(0.0, 20.0, na, 2.0)
    lo, hi = float(grid[0]), float(grid[-1])
    run = _egm_program(mesh, GRID_AXIS, _NZ, na, lo, hi, 2.0, 2.0, 1,
                       0.9, 0.96, 1e-6, 50, False, 0.0, "float64",
                       None, None, telemetry)

    def fn(C, a_grid, s, P, r, w, amin):
        return run(C, a_grid, s, P, r, w, amin)

    return fn, (_f((_NZ, na)), _f((na,)), _f((_NZ,)), _f((_NZ, _NZ)),
                _f(), _f(), _f())


def _build_egm_sweep_2d(telemetry=None, sentinel=None):
    import jax

    import numpy as np

    if len(jax.devices()) < 4:
        raise ProgramUnavailable(
            "the 2-D (scenarios x grid) sweep needs a >= 4-device mesh "
            "(2 x 2 minimum; run under "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8, as tier-1 "
            "does, to audit it on a CPU host)")
    from aiyagari_tpu.parallel.mesh import GRID_AXIS, SCENARIOS_AXIS, make_mesh_2d
    from aiyagari_tpu.solvers.egm_sharded import _egm_sweep_2d_program
    from aiyagari_tpu.utils.grids import power_grid

    S, na = 2, 64   # trace-only shapes; the entry point's slab-fit guard
                    # is a runtime-geometry gate, not a trace constraint
    mesh = make_mesh_2d(scenarios=2, grid=2,
                        devices=np.array(jax.devices()[:4]))
    grid = power_grid(0.0, 20.0, na, 2.0)
    lo, hi = float(grid[0]), float(grid[-1])
    run = _egm_sweep_2d_program(
        mesh, SCENARIOS_AXIS, GRID_AXIS, _NZ, na, lo, hi, 2.0, 2.0, 1,
        0.9, 0.96, 1e-6, 50, False, 0.0, "float64",
        telemetry=telemetry, sentinel=sentinel)

    def fn(C, a_grid, s, P, r, w, amin):
        return run(C, a_grid, s, P, r, w, amin)

    return fn, (_f((S, _NZ, na)), _f((na,)), _f((_NZ,)), _f((_NZ, _NZ)),
                _f((S,)), _f((S,)), _f((S,)))


def _build_ge_round():
    import jax.numpy as jnp
    import numpy as np

    from aiyagari_tpu.config import SolverConfig
    from aiyagari_tpu.equilibrium.batched import excess_demand_batch
    from aiyagari_tpu.models.aiyagari import aiyagari_preset

    model = aiyagari_preset(grid_size=_NA, dtype=jnp.float64)
    # The push-forward route is PINNED to the scatter-free transpose
    # form: the batched "auto" resolution is platform-contextual (CPU
    # hosts take the scatter form under vmap — resolve_backend's batched
    # split, ISSUE 15), and this audit certifies the accelerator-shaped
    # artifact (AIYA101 scatter-free) deterministically regardless of
    # the tracing host.
    solver = SolverConfig(method="egm", tol=1e-6, max_iter=50,
                          pushforward="transpose")

    def fn(r_batch):
        gap, _ = excess_demand_batch(model, r_batch, solver=solver,
                                     dist_tol=1e-8, dist_max_iter=200)
        return gap

    return fn, (np.array([0.02, 0.03]),)


def _build_ge_fused(telemetry=None, sentinel=None, batched=False):
    import jax.numpy as jnp

    from aiyagari_tpu.config import EquilibriumConfig, SolverConfig
    from aiyagari_tpu.equilibrium.fused import (
        fused_ge_batched_operands,
        fused_ge_batched_program,
        fused_ge_operands,
        fused_ge_program,
    )
    from aiyagari_tpu.models.aiyagari import aiyagari_preset

    model = aiyagari_preset(grid_size=_NA, dtype=jnp.float64)
    # Push-forward pinned to the scatter-free transpose form (same
    # rationale as _build_ge_round). donate=False: the audit executes the
    # paired telemetry on/off traces of ONE builder output repeatedly, and
    # donated operands would be deleted after the first call.
    solver = SolverConfig(method="egm", tol=1e-6, max_iter=50,
                          pushforward="transpose", telemetry=telemetry,
                          sentinel=sentinel)
    eq = EquilibriumConfig(max_iter=8, tol=1e-6,
                           batch=2 if batched else 1)
    if batched:
        fn = fused_ge_batched_program(model, solver=solver, eq=eq,
                                      dist_tol=1e-8, dist_max_iter=200,
                                      donate=False)
        args = fused_ge_batched_operands(model, eq, solver=solver)
    else:
        fn = fused_ge_program(model, solver=solver, eq=eq, dist_tol=1e-8,
                              dist_max_iter=200, donate=False)
        args = fused_ge_operands(model, eq, solver=solver)
    return fn, args


def _build_transition_round():
    from aiyagari_tpu.transition.path import transition_path_aggregates

    def fn(C_term, mu0, a_grid, s, P, r_ext, w_path, beta_path, sigma_ext,
           amin_path):
        return transition_path_aggregates(
            C_term, mu0, a_grid, s, P, r_ext, w_path, beta_path, sigma_ext,
            amin_path)

    return fn, (_f((_NZ, _NA)), _f((_NZ, _NA)), _f((_NA,)), _f((_NZ,)),
                _f((_NZ, _NZ)), _f((_T + 1,)), _f((_T,)), _f((_T,)),
                _f((_T + 1,)), _f((_T,)))


def _build_transition_fused(telemetry=None, sentinel=None, sweep=False):
    import jax.numpy as jnp

    from aiyagari_tpu.config import MITShock, SolverConfig, TransitionConfig
    from aiyagari_tpu.models.aiyagari import aiyagari_preset
    from aiyagari_tpu.transition.fused import (
        fused_transition_operands,
        fused_transition_program,
        fused_transition_sweep_operands,
        fused_transition_sweep_program,
    )

    model = aiyagari_preset(grid_size=_NA, dtype=jnp.float64)
    # Push-forward pinned to the scatter-free transpose form and
    # donate=False, for the same reasons as _build_ge_fused: the audit
    # re-executes one builder output across paired traces, and the
    # AIYA101 verdict must not depend on the tracing host.
    solver = SolverConfig(method="egm", tol=1e-6, max_iter=50,
                          pushforward="transpose", telemetry=telemetry,
                          sentinel=sentinel)
    trans = TransitionConfig(T=_T, max_iter=4, tol=1e-6, method="newton")
    if sweep:
        shocks = [MITShock(size=-0.01), MITShock(size=-0.02)]
        fn = fused_transition_sweep_program(model, len(shocks),
                                            trans=trans, solver=solver,
                                            donate=False)
        args = fused_transition_sweep_operands(model, shocks, trans)
    else:
        fn = fused_transition_program(model, trans=trans, solver=solver,
                                      donate=False)
        args = fused_transition_operands(model, MITShock(size=-0.01),
                                         trans)
    return fn, args


def _build_egm_vjp():
    import jax
    import jax.numpy as jnp

    from aiyagari_tpu.solvers.egm import solve_aiyagari_egm_implicit

    def fn(C, a_grid, s, P, r, w, amin, sigma, beta):
        def obj(b):
            sol = solve_aiyagari_egm_implicit(
                C, a_grid, s, P, r, w, amin, sigma=sigma, beta=b,
                tol=1e-6, max_iter=50, adjoint_tol=1e-8, adjoint_max_iter=50)
            return jnp.sum(sol.policy_c)

        return jax.grad(obj)(beta)

    return fn, _egm_args(_f)


def _build_distribution_adjoint():
    import jax

    from aiyagari_tpu.sim.distribution import (
        aggregate_capital,
        stationary_distribution_implicit,
    )

    def fn(policy_k, a_grid, P):
        def obj(pol):
            d = stationary_distribution_implicit(
                pol, a_grid, P, tol=1e-8, max_iter=200,
                adjoint_tol=1e-8, adjoint_max_iter=50)
            return aggregate_capital(d.mu, a_grid)

        return jax.grad(obj)(policy_k)

    return fn, (_f((_NZ, _NA)), _f((_NA,)), _f((_NZ, _NZ)))


def _build_ge_ift():
    import jax

    from aiyagari_tpu.calibrate.economy import steady_state_map

    def fn(beta, sigma, rho, sigma_e, a_grid):
        def obj(b, sg, rh, se):
            st = steady_state_map(
                b, sg, rh, se, a_grid, n_states=_NZ, alpha=0.36,
                delta=0.08, amin=0.0, bisect_iters=8, hh_tol=1e-6,
                hh_max_iter=50, dist_tol=1e-8, dist_max_iter=200,
                adjoint_tol=1e-8, adjoint_max_iter=50)
            return st["r"]

        return jax.grad(obj, argnums=(0, 1, 2, 3))(beta, sigma, rho, sigma_e)

    return fn, (_f(), _f(), _f(), _f(), _f((_NA,)))


def _build_ks_step():
    from aiyagari_tpu.sim.ks_distribution import distribution_capital_path

    nk, nK = _NA, 4

    def fn(k_opt, k_grid, K_grid, z_path, eps_trans, mu_init):
        return distribution_capital_path(k_opt, k_grid, K_grid, z_path,
                                         eps_trans, mu_init, T=_T)

    return fn, (_f((4, nK, nk)), _f((nk,)), _f((nK,)), _i32((_T + 1,)),
                _f((2, 2, 2, 2)), _f((2, nk)))


def _build_registry() -> List[ProgramSpec]:
    tele = _telemetry_cfg

    def egm_f32_ladder():
        from aiyagari_tpu.ops.precision import PrecisionLadderConfig

        # Single-stage f32 ladder: the documented way to pin that a hot
        # stage never silently upcasts (ops/precision.py docstring).
        return PrecisionLadderConfig(stage_dtypes=("float32",),
                                     matmul_precision=("default",))

    return [
        ProgramSpec(
            name="egm/sweep", family="egm",
            build_off=partial(_build_egm),
            build_on=lambda: _build_egm(telemetry=tele()),
            stage_dtype="float64"),
        ProgramSpec(
            name="egm/sweep_f32_stage", family="egm",
            build_off=lambda: _build_egm(ladder=egm_f32_ladder(),
                                         dtype_fn=_f32),
            stage_dtype="float32"),
        # The sentinel-carrying sweep is its own audited artifact: the
        # failure sentinel changes the loop CONDITION (verdict == 0 ANDed
        # in), so AIYA107 must certify the sentinel route NaN-exits too,
        # and the dead-carry/stable-carry rules must accept the sentinel
        # state slots (ISSUE 10 satellite). stage_dtype stays undeclared:
        # the sentinel watches residuals in f32 REGARDLESS of the solve
        # dtype (diagnostics/sentinel.py _DT — the same cross-stage-
        # boundary rationale as the telemetry ring), which is a sanctioned
        # diagnostic cast, not a precision leak; AIYA102 coverage of this
        # operator lives on the sentinel-free egm/sweep entries.
        ProgramSpec(
            name="egm/sweep_sentinel", family="egm",
            build_off=lambda: _build_egm(sentinel=_sentinel_cfg())),
        # The fused Pallas sweep is a separately audited artifact: its
        # while_loop body carries one pallas_call instead of the op chain,
        # and AIYA101-107 certify the fused program structurally — no
        # scatter anywhere (declared scatter_free, unlike the XLA sweep,
        # whose generic inversion route gathers), no precision leak inside
        # the kernel, the same NaN-exiting cond, and the telemetry ring
        # compiled out when off. Registered through the same solver entry
        # (egm_kernel="pallas_fused"), so the audit covers the route users
        # actually run, not a bare kernel call. Traced with the interpreter
        # (the registry runs on the default CPU backend), which is also the
        # artifact tier-1 parity pins — the chip-compiled Mosaic artifact
        # stays a hardware-validation item (docs/USAGE.md).
        ProgramSpec(
            name="egm/sweep_fused", family="egm",
            build_off=lambda: _build_egm(egm_kernel="pallas_fused"),
            build_on=lambda: _build_egm(telemetry=tele(),
                                        egm_kernel="pallas_fused"),
            scatter_free=True, stage_dtype="float64"),
        ProgramSpec(
            name="egm/sweep_fused_f32_stage", family="egm",
            build_off=lambda: _build_egm(ladder=egm_f32_ladder(),
                                         dtype_fn=_f32,
                                         egm_kernel="pallas_fused"),
            scatter_free=True, stage_dtype="float32"),
        ProgramSpec(
            name="egm/sweep_labor", family="egm",
            build_off=partial(_build_egm_labor),
            build_on=lambda: _build_egm_labor(telemetry=tele()),
            stage_dtype="float64"),
        # solve_aiyagari_egm_safe is a host-level retry wrapper around the
        # same device program (its docstring); the traced artifact IS
        # egm/sweep, so "safe" needs no separate entry.
        ProgramSpec(
            name="egm/sweep_sharded", family="egm",
            build_off=partial(_build_egm_sharded),
            build_on=lambda: _build_egm_sharded(telemetry=tele()),
            stage_dtype="float64"),
        # The 2-D (scenarios x grid) sweep program (ISSUE 13): scenario
        # lanes vmapped over the ring-sharded grid solve inside one 2-D
        # shard_map. AIYA101-107 certify the COMPOSED artifact — the
        # batched while_loop still NaN-exits per lane (AIYA107), the
        # telemetry ring stays compiled out when off (AIYA104), and the
        # grid-axis collectives live in the same audited sub-jaxprs as the
        # 1-D program (the body IS _make_egm_local). The per-lane sentinel
        # variant is traced through the same builder; <4-device hosts
        # report it skipped (ProgramUnavailable), like egm/sweep_sharded.
        ProgramSpec(
            name="egm/sweep_2d", family="egm",
            build_off=partial(_build_egm_sweep_2d),
            build_on=lambda: _build_egm_sweep_2d(telemetry=tele()),
            stage_dtype="float64"),
        ProgramSpec(
            name="egm/sweep_2d_sentinel", family="egm",
            build_off=lambda: _build_egm_sweep_2d(sentinel=_sentinel_cfg())),
        ProgramSpec(
            name="vfi/step", family="vfi",
            build_off=partial(_build_vfi),
            build_on=lambda: _build_vfi(telemetry=tele()),
            stage_dtype="float64"),
        ProgramSpec(
            name="distribution/step_scatter", family="distribution",
            build_off=lambda: _build_distribution_step("scatter"),
            scatter_free=False, stage_dtype="float64"),
        ProgramSpec(
            name="distribution/step_transpose", family="distribution",
            build_off=lambda: _build_distribution_step("transpose"),
            scatter_free=True, stage_dtype="float64"),
        ProgramSpec(
            name="distribution/step_banded", family="distribution",
            build_off=lambda: _build_distribution_step("banded"),
            scatter_free=True, stage_dtype="float64"),
        ProgramSpec(
            name="distribution/stationary", family="distribution",
            build_off=partial(_build_stationary),
            build_on=lambda: _build_stationary(telemetry=tele()),
            scatter_free=True, stage_dtype="float64"),
        ProgramSpec(
            name="equilibrium/ge_round_batched", family="equilibrium",
            build_off=_build_ge_round,
            scatter_free=True, stage_dtype="float64"),
        # The one-program equilibrium (ISSUE 18 tentpole): the WHOLE GE
        # closure — household fixed point, stationary distribution, market
        # clearing, bracket update — inside one lax.while_loop. AIYA107
        # certifies the outer cond NaN-exits (the gap carry starts +inf,
        # so |NaN| >= tol is concretely False); AIYA101 that the bracket/
        # history carries stay scatter-free (one-hot selects, not .at[]);
        # AIYA104 that the telemetry ring is compiled out of the OFF
        # trace. The sentinel variant audits the verdict-ANDed cond, like
        # egm/sweep_sentinel. The batched entry wraps the vmapped
        # candidate round + quarantine mask in the same loop.
        ProgramSpec(
            name="equilibrium/ge_fused", family="equilibrium",
            build_off=partial(_build_ge_fused),
            build_on=lambda: _build_ge_fused(telemetry=tele()),
            scatter_free=True, stage_dtype="float64"),
        ProgramSpec(
            name="equilibrium/ge_fused_sentinel", family="equilibrium",
            build_off=lambda: _build_ge_fused(sentinel=_sentinel_cfg())),
        ProgramSpec(
            name="equilibrium/ge_fused_batched", family="equilibrium",
            build_off=lambda: _build_ge_fused(batched=True),
            build_on=lambda: _build_ge_fused(telemetry=tele(),
                                             batched=True),
            scatter_free=True, stage_dtype="float64"),
        ProgramSpec(
            name="transition/round", family="transition",
            build_off=_build_transition_round,
            scatter_free=True, stage_dtype="float64"),
        # The one-program transitions (ISSUE 19 tentpole): the WHOLE
        # MIT-shock solve — backward dated-EGM scan, forward push,
        # excess demand, Newton/damped price-path update — inside one
        # lax.while_loop. AIYA107 certifies the outer cond NaN-exits
        # (max excess demand starts +inf; |NaN| >= tol is concretely
        # False); AIYA101 that the convergence-history carry stays
        # scatter-free (one-hot selects); AIYA104 that the telemetry
        # ring is compiled out of the OFF trace. The sentinel variant
        # audits the verdict-ANDed cond; the sweep entry wraps the
        # vmapped lockstep round + quarantine mask in the same loop.
        ProgramSpec(
            name="transition/fused", family="transition",
            build_off=partial(_build_transition_fused),
            build_on=lambda: _build_transition_fused(telemetry=tele()),
            scatter_free=True, stage_dtype="float64"),
        ProgramSpec(
            name="transition/fused_sentinel", family="transition",
            build_off=lambda: _build_transition_fused(
                sentinel=_sentinel_cfg())),
        ProgramSpec(
            name="transition/fused_sweep", family="transition",
            build_off=lambda: _build_transition_fused(sweep=True),
            build_on=lambda: _build_transition_fused(telemetry=tele(),
                                                     sweep=True),
            scatter_free=True, stage_dtype="float64"),
        ProgramSpec(
            name="ks/distribution_step", family="ks",
            build_off=_build_ks_step,
            scatter_free=True, stage_dtype="float64"),
        # The differentiable solve stack (ISSUE 17): the reverse-mode
        # artifacts users actually compile when they jax.grad through the
        # implicit wrappers. Each trace contains BOTH the stop_gradient'd
        # primal while_loop (already audited via its forward entry above)
        # AND the Neumann adjoint loop of ops/implicit.py — AIYA107 must
        # certify the adjoint cond's NaN-exit (`delta > tol` is False for
        # NaN), and the dead/stable-carry rules its (lambda, delta, k)
        # carry. NOT declared scatter_free: the cotangent of the gather-
        # based interpolation/pushforward is a scatter-add by
        # construction — the adjoint pays it once per backward solve, off
        # the forward hot path.
        ProgramSpec(
            name="egm/sweep_vjp", family="egm",
            build_off=_build_egm_vjp,
            stage_dtype="float64"),
        ProgramSpec(
            name="distribution/adjoint", family="distribution",
            build_off=_build_distribution_adjoint,
            stage_dtype="float64"),
        ProgramSpec(
            name="equilibrium/ge_ift", family="equilibrium",
            build_off=_build_ge_ift,
            stage_dtype="float64"),
    ]


_REGISTRY: Optional[List[ProgramSpec]] = None


def registered_programs(families: Optional[Tuple[str, ...]] = None
                        ) -> List[ProgramSpec]:
    """The kernel zoo (built once per process; builders stay lazy)."""
    global _REGISTRY
    if _REGISTRY is None:
        _REGISTRY = _build_registry()
    if families is None:
        return list(_REGISTRY)
    return [p for p in _REGISTRY if p.family in families]
