"""Static analysis of the kernel zoo: jaxpr-level invariant auditing
(analysis/jaxpr_audit.py over the traceable entry points in
analysis/registry.py) and source-level lint (analysis/lint.py), under one
declarative rule catalogue (analysis/rules.py).

    python -m aiyagari_tpu.analysis [--format json|text] [--rules ...]

`run_analysis()` is the library entry the CLI, `bench.py --preset ci`,
and tier-1 (tests/test_static_analysis.py) all share. Findings emit into
the PR 6 observability surface: an `analysis` ledger event with per-rule
counts on the active run ledger, and
`aiyagari_analysis_findings_total{rule=...}` metrics counters.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import List, Optional, Sequence, Tuple

from aiyagari_tpu.analysis.rules import (
    RULES,
    Finding,
    Rule,
    findings_by_rule,
    rule_by_name,
)

__all__ = [
    "AnalysisReport",
    "RULES",
    "Finding",
    "Rule",
    "default_baseline_path",
    "load_baseline",
    "run_analysis",
]


_BASELINE_FILE = "baseline.json"


def default_baseline_path() -> Path:
    return Path(__file__).resolve().parent / _BASELINE_FILE


def load_baseline(path=None) -> set:
    """The checked-in findings baseline: a set of Finding.baseline_key()
    strings that predate their rule and are tolerated (reported as
    suppressed). Shipped empty — the tree is clean."""
    p = Path(path) if path is not None else default_baseline_path()
    if not p.exists():
        return set()
    data = json.loads(p.read_text())
    return set(data.get("findings", []))


def write_baseline(findings: Sequence[Finding], path=None) -> Path:
    """Regenerate the baseline from a run's findings: every ACTIVE finding
    plus every finding the PREVIOUS baseline was suppressing (it still
    exists in the tree — dropping it would resurface it as a gate failure
    on the next run). noqa-suppressed findings are never imported: their
    suppression lives in the source line."""
    p = Path(path) if path is not None else default_baseline_path()
    keys = sorted({f.baseline_key() for f in findings
                   if not f.suppressed or f.suppressed_by == "baseline"})
    p.write_text(json.dumps({"version": 1, "findings": keys}, indent=2)
                 + "\n")
    return p


@dataclasses.dataclass(frozen=True)
class AnalysisReport:
    findings: Tuple[Finding, ...]
    programs_audited: Tuple[str, ...]
    programs_skipped: Tuple[tuple, ...]   # (name, reason)
    files_linted: int
    wall_seconds: float

    @property
    def active(self) -> Tuple[Finding, ...]:
        return tuple(f for f in self.findings if not f.suppressed)

    @property
    def active_count(self) -> int:
        return len(self.active)

    def rule_counts(self) -> dict:
        return findings_by_rule(self.findings)

    def to_json(self) -> dict:
        return {
            "findings": [f.to_json() for f in self.findings],
            "active_findings": self.active_count,
            "rule_counts": self.rule_counts(),
            "programs_audited": list(self.programs_audited),
            "programs_skipped": [{"program": n, "reason": r}
                                 for n, r in self.programs_skipped],
            "files_linted": self.files_linted,
            "wall_seconds": round(self.wall_seconds, 3),
        }

    def render_text(self) -> str:
        lines = []
        for f in self.findings:
            mark = "suppressed " if f.suppressed else ""
            lines.append(f"{f.location()}: {mark}{f.rule.id} "
                         f"[{f.rule.name}] {f.message}")
        for name, reason in self.programs_skipped:
            lines.append(f"{name}: skipped ({reason})")
        lines.append(
            f"{self.active_count} finding(s) "
            f"({len(self.findings) - self.active_count} suppressed) over "
            f"{len(self.programs_audited)} program(s), "
            f"{self.files_linted} file(s), "
            f"{self.wall_seconds:.1f}s")
        return "\n".join(lines)


def _emit_observability(report: AnalysisReport) -> None:
    """Record the run on the PR 6 surface. Ledger: one `analysis` event
    (active run ledger only — a no-op otherwise). Metrics: per-rule
    finding counters, zero-filled so a clean run still exports the
    series."""
    try:
        from aiyagari_tpu.diagnostics import ledger, metrics

        counts = report.rule_counts()
        for rule_name, n in counts.items():
            # inc(0) registers the zero series: a clean run still exports
            # one aiyagari_analysis_findings_total{rule=...} per rule, so
            # dashboards can tell "clean" from "never ran".
            metrics.counter("aiyagari_analysis_findings_total",
                            rule=rule_name).inc(n)
        ledger.emit("analysis", findings=report.active_count,
                    rules=counts,
                    programs_audited=len(report.programs_audited),
                    programs_skipped=[n for n, _ in report.programs_skipped],
                    files_linted=report.files_linted,
                    wall_seconds=round(report.wall_seconds, 3))
    except Exception:  # pragma: no cover - diagnostics must not fail runs
        pass


def run_analysis(*, rules: Optional[Sequence[str]] = None,
                 levels: Sequence[str] = ("jaxpr", "source"),
                 baseline=None) -> AnalysisReport:
    """Run the selected rules over the kernel zoo and the source tree.

    rules   — rule names/ids to run (None = all).
    levels  — which layers to run ("jaxpr", "source").
    baseline — a baseline path, a pre-loaded key set, or None for the
        checked-in default.
    """
    import time

    t0 = time.perf_counter()
    selected = None if rules is None else [rule_by_name(r) for r in rules]

    findings: List[Finding] = []
    audited: List[str] = []
    skipped: List[tuple] = []
    files_linted = 0

    if "jaxpr" in levels and (
            selected is None or any(r.level == "jaxpr" for r in selected)):
        from aiyagari_tpu.analysis.jaxpr_audit import audit_program
        from aiyagari_tpu.analysis.registry import (
            ProgramUnavailable,
            registered_programs,
        )

        jaxpr_rules = (None if selected is None
                       else [r for r in selected if r.level == "jaxpr"])
        for spec in registered_programs():
            try:
                findings.extend(audit_program(spec, rules=jaxpr_rules))
                audited.append(spec.name)
            except ProgramUnavailable as e:
                skipped.append((spec.name, str(e)))

    if "source" in levels and (
            selected is None or any(r.level == "source" for r in selected)):
        from aiyagari_tpu.analysis.lint import iter_package_files, lint_file

        want = (None if selected is None
                else {r.id for r in selected if r.level == "source"})
        for path, rel in iter_package_files():
            files_linted += 1
            for f in lint_file(path, rel):
                if want is None or f.rule.id in want:
                    findings.append(f)

    base = (baseline if isinstance(baseline, set)
            else load_baseline(baseline))
    findings = [
        dataclasses.replace(f, suppressed=True, suppressed_by="baseline")
        if (not f.suppressed and f.baseline_key() in base) else f
        for f in findings
    ]

    report = AnalysisReport(
        findings=tuple(findings),
        programs_audited=tuple(audited),
        programs_skipped=tuple(skipped),
        files_linted=files_linted,
        wall_seconds=time.perf_counter() - t0,
    )
    _emit_observability(report)
    return report
