"""The declarative rule catalogue of the static-analysis layer.

Every invariant this framework ships is stated here ONCE, as a `Rule`
record with a stable id (`AIYA###`) — the jaxpr auditor
(analysis/jaxpr_audit.py) and the source lint (analysis/lint.py) implement
the checks, but the catalogue is the contract: rule ids are what `# noqa:`
suppressions, the findings baseline, the CLI `--rules` filter, the ledger's
per-rule counts, and the tier-1 adversarial fixtures all key on, so an id
is never reused or renumbered.

Numbering: AIYA1xx are jaxpr-level rules (checked on the traced program of
every registered hot entry point, analysis/registry.py); AIYA2xx are
source-level rules (checked on the package's AST). The split matters: a
jaxpr rule certifies the COMPILED artifact (what actually runs on the
chip), a source rule certifies the code discipline that keeps the
artifacts auditable (e.g. the jax-0.4.x shim boundary).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

__all__ = [
    "CALLBACK_TAG_ATTR",
    "CALLBACK_WHITELIST",
    "Finding",
    "Rule",
    "RULES",
    "rule_by_name",
    "findings_by_rule",
]

# Device callbacks that are ALLOWED inside hot loop bodies tag their host
# function with this attribute (the value names the event stream). The
# emitting module sets the dunder literally — no import of this package —
# so the contract is the attribute name, stated here and at the emit site
# (ops/pushforward._warn_fallback).
CALLBACK_TAG_ATTR = "__aiyagari_callback_tag__"

# The recognized tags. "pushforward-degradation" is the PR 6 counted
# degradation event: an async, fire-and-forget jax.debug.callback that
# increments a process metrics counter — the device program never blocks
# on it, so it is a sanctioned exception to no-host-sync-in-loop.
CALLBACK_WHITELIST = frozenset({"pushforward-degradation", "progress"})


@dataclasses.dataclass(frozen=True)
class Rule:
    """One checked invariant. `level` is "jaxpr" or "source"."""

    id: str
    name: str
    level: str
    description: str


RULES: Tuple[Rule, ...] = (
    Rule(
        id="AIYA101",
        name="no-scatter",
        level="jaxpr",
        description=(
            "A program whose registry entry declares a scatter-free "
            "DistributionBackend must contain no scatter-add primitive on "
            "its unconditional hot path. Scatter-adds inside lax.cond "
            "branches are the compiled-in validity fallback "
            "(ops/pushforward.py) and are allowed."),
    ),
    Rule(
        id="AIYA102",
        name="no-precision-leak",
        level="jaxpr",
        description=(
            "A declared-f32 ladder stage must contain no "
            "convert_element_type to float64 (and a declared-f64 program "
            "none to float32) — a silent cast defeats the mixed-precision "
            "ladder's bandwidth win or its accuracy certificate "
            "(ops/precision.py). Mixed-float-dtype dot_general operands "
            "are flagged in every program."),
    ),
    Rule(
        id="AIYA103",
        name="no-host-sync-in-loop",
        level="jaxpr",
        description=(
            "No io_callback / infeed / outfeed / untagged debug_callback "
            "inside a while_loop or scan body: a host round trip per sweep "
            "serializes the hot loop on the host link (~100 ms per trip on "
            "this image's remote TPU transport). Callbacks whose host "
            "function carries a whitelisted "
            "__aiyagari_callback_tag__ (the counted degradation events) "
            "are allowed."),
    ),
    Rule(
        id="AIYA104",
        name="telemetry-noop",
        level="jaxpr",
        description=(
            "A telemetry-off trace must contain no recorder artifacts (no "
            "ring-buffer-shaped value anywhere in the program), and the "
            "telemetry-on trace of the same program must contain them — "
            "the compile-time no-op contract of "
            "diagnostics/telemetry.py, generalized from the PR 6 jaxpr "
            "pin to every registered program."),
    ),
    Rule(
        id="AIYA105",
        name="dead-carry",
        level="jaxpr",
        description=(
            "No while_loop carry slot that is written every iteration but "
            "never read — not by the loop condition, not by any other "
            "carry slot, and not by the enclosing program. A dead carry "
            "pays HBM traffic per sweep for a value nobody observes."),
    ),
    Rule(
        id="AIYA106",
        name="stable-carry",
        level="jaxpr",
        description=(
            "while_loop / scan carry leaves must have fixed shape/dtype "
            "and must not be weak-typed: a weak-typed carry (a bare "
            "Python scalar in the init) re-specializes the program "
            "whenever the caller's literal changes — the silent recompile "
            "hazard."),
    ),
    Rule(
        id="AIYA107",
        name="nan-exit",
        level="jaxpr",
        description=(
            "Every while_loop whose condition reads a floating-point "
            "carry slot (a residual loop) must EXIT when those slots go "
            "non-finite: the condition, evaluated concretely with every "
            "float carry input NaN (loop-invariant inputs finite, "
            "iteration counters mid-range), must return False. This "
            "certifies the NaN early-exit contract structurally — a "
            "condition written `~(dist < tol)` keeps a NaN-poisoned "
            "solve iterating to max_iter on garbage; `dist >= tol` (the "
            "framework's discipline) and the sentinel-carrying conds "
            "(diagnostics/sentinel.py) both exit. Fixed-count loops "
            "(integer-only conditions) are exempt."),
    ),
    Rule(
        id="AIYA201",
        name="mesh-shim-discipline",
        level="source",
        description=(
            "No direct jax.sharding / jax.experimental.shard_map imports "
            "or attribute references outside parallel/mesh.py: jax is "
            "pinned at 0.4.x here and every new-API symbol goes through "
            "the one version-probe shim (ROADMAP discipline). Raw "
            "PartitionSpec(...) construction outside parallel/ is flagged "
            "too (ISSUE 13): ad-hoc specs bypass the declarative "
            "partition-rule matcher (parallel/rules.py); the shard_map "
            "in-spec alias idiom (`import PartitionSpec as P` from the "
            "shim) stays sanctioned."),
    ),
    Rule(
        id="AIYA202",
        name="no-host-scalar-in-hot-module",
        level="source",
        description=(
            "In the hot modules (solvers/, ops/, sim/, transition/): no "
            ".item() and no float()/int()/bool() of an indexed array — "
            "each is an eager per-element device fetch (~100 ms per round "
            "trip on the remote TPU transport; the _cached_grid_bounds / "
            "_fetch_scalars batched-device_get pattern is the sanctioned "
            "route). Host-side numpy after an explicit jax.device_get is "
            "fine — suppress those lines with `# noqa: AIYA202`."),
    ),
    Rule(
        id="AIYA203",
        name="no-bare-debug-print",
        level="source",
        description=(
            "No bare jax.debug.print: production signals route through "
            "the counted degradation-event path (metrics counter + ledger "
            "event, ops/pushforward._record_fallback); a debug print is "
            "allowed only behind an opt-in env-gated flag (an enclosing "
            "`if <...DEBUG...>:` guard, the AIYAGARI_DEBUG_* pattern)."),
    ),
    Rule(
        id="AIYA204",
        name="route-resolution-discipline",
        level="source",
        description=(
            "Literal \"auto\"-resolution fallbacks and platform-split "
            "route choices may live ONLY in the sanctioned resolver "
            "functions (ops/pushforward.resolve_backend, "
            "ops/egm.resolve_egm_kernel / require_xla_egm_kernel, "
            "ops/interp.bucket_index / searchsorted_method) and the "
            "tuning layer itself (tuning/): no other module may map "
            "\"auto\" — or a jax.default_backend() test — onto a "
            "concrete route literal. A re-hardcoded route silently "
            "bypasses the measured tuning cache, the roofline prior, and "
            "the route_decision ledger trail those resolvers emit "
            "(tuning/autotuner.py), turning an audited decision back "
            "into an unexplained constant."),
    ),
    Rule(
        id="AIYA205",
        name="ift-differentiation-discipline",
        level="source",
        description=(
            "No jax.grad / value_and_grad / vjp / jvp / jacfwd / jacrev / "
            "hessian applied DIRECTLY to an unwrapped solver fixed point "
            "(solve_aiyagari_egm*, solve_aiyagari_vfi, "
            "stationary_distribution, solve_equilibrium*, "
            "solve_transition): their lax.while_loop primals are not "
            "reverse-differentiable — a trace-time error at best, a "
            "silently wrong unrolled gradient at worst. Differentiate "
            "through the implicit wrappers instead "
            "(solve_aiyagari_egm_implicit, "
            "stationary_distribution_implicit, "
            "calibrate/economy.steady_state_map, "
            "transition/implicit.transition_r_path_implicit — all built "
            "on ops/implicit.fixed_point_vjp / two_point_root_vjp): the "
            "IFT adjoint at the converged point is the one sanctioned "
            "door (ISSUE 17)."),
    ),
)

_BY_NAME = {r.name: r for r in RULES}
_BY_ID = {r.id: r for r in RULES}


def rule_by_name(key: str) -> Rule:
    """Look a rule up by name ("no-scatter") or id ("AIYA101")."""
    r = _BY_NAME.get(key) or _BY_ID.get(key)
    if r is None:
        known = ", ".join(f"{r.id}/{r.name}" for r in RULES)
        raise KeyError(f"unknown rule {key!r}; known rules: {known}")
    return r


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation. `where` is a program name (jaxpr level) or a
    repo-relative path (source level); `line` is set for source findings.
    `suppressed` marks findings neutralized by a `# noqa: AIYA###` comment
    or a baseline entry — reported, but not counted against the gate.
    `suppressed_by` records WHICH mechanism ("noqa" or "baseline"):
    baseline regeneration must keep re-writing baseline-suppressed
    findings (they still exist in the tree) while never importing noqa'd
    ones."""

    rule: Rule
    where: str
    message: str
    line: Optional[int] = None
    suppressed: bool = False
    suppressed_by: Optional[str] = None

    def location(self) -> str:
        return f"{self.where}:{self.line}" if self.line else self.where

    def to_json(self) -> dict:
        return {
            "rule": self.rule.id,
            "name": self.rule.name,
            "level": self.rule.level,
            "where": self.where,
            "line": self.line,
            "message": self.message,
            "suppressed": self.suppressed,
            "suppressed_by": self.suppressed_by,
        }

    def baseline_key(self) -> str:
        """The identity a baseline entry matches on. Line numbers are
        deliberately excluded — unrelated edits above a known finding must
        not un-baseline it."""
        return f"{self.rule.id}:{self.where}"


def findings_by_rule(findings) -> dict:
    """{rule name: active (unsuppressed) count} over every catalogued rule
    — the shape the ledger's `analysis` event and the metrics counters
    record, zero-filled so a clean run still names each rule."""
    counts = {r.name: 0 for r in RULES}
    for f in findings:
        if not f.suppressed:
            counts[f.rule.name] += 1
    return counts
