"""CLI for the static-analysis layer.

    python -m aiyagari_tpu.analysis                      # full run, text
    python -m aiyagari_tpu.analysis --format json        # machine-readable
    python -m aiyagari_tpu.analysis --rules no-scatter,mesh-shim-discipline
    python -m aiyagari_tpu.analysis --level source       # lint only (no jax traces)
    python -m aiyagari_tpu.analysis --list-rules
    python -m aiyagari_tpu.analysis --write-baseline     # accept current findings

Exit code: 0 when every finding is suppressed (noqa or baseline), 1
otherwise — the CI contract `bench.py --preset ci` and tier-1 gate on.

The jaxpr level traces the kernel zoo with abstract (ShapeDtypeStruct)
inputs, so the run is deterministic on any host: the CLI pins
JAX_PLATFORMS=cpu by default (override with --platform) and never needs
an accelerator.
"""

from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    ap = argparse.ArgumentParser(prog="aiyagari_tpu.analysis",
                                 description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--format", choices=["text", "json"], default="text")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule names or ids to run "
                         "(default: all)")
    ap.add_argument("--level", choices=["all", "jaxpr", "source"],
                    default="all")
    ap.add_argument("--baseline", default=None,
                    help="findings-baseline path (default: the checked-in "
                         "analysis/baseline.json)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current unsuppressed findings into the "
                         "baseline (then exit 0): the escape hatch for "
                         "landing a new rule against a not-yet-clean tree")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--platform", choices=["cpu", "tpu"], default="cpu",
                    help="jax platform for the trace step (default cpu: "
                         "the audit traces, never executes, so it needs "
                         "no accelerator and stays deterministic)")
    args = ap.parse_args(argv)

    if args.list_rules:
        from aiyagari_tpu.analysis.rules import RULES

        for r in RULES:
            print(f"{r.id}  {r.name:28s} [{r.level}]  {r.description}")
        return 0

    # Platform pin BEFORE any jax initialization (the analysis package
    # import itself is jax-free; the registry builders import lazily).
    os.environ.setdefault("JAX_PLATFORMS", args.platform)
    import jax

    jax.config.update("jax_platforms", args.platform)
    # The zoo's reference programs are f64; without x64 they would
    # silently canonicalize and the precision-leak declarations would lie.
    jax.config.update("jax_enable_x64", True)

    from aiyagari_tpu.analysis import run_analysis, write_baseline

    levels = (("jaxpr", "source") if args.level == "all" else (args.level,))
    rules = (None if args.rules is None
             else [s.strip() for s in args.rules.split(",") if s.strip()])
    report = run_analysis(rules=rules, levels=levels, baseline=args.baseline)

    if args.write_baseline:
        path = write_baseline(report.findings, args.baseline)
        print(f"baseline written: {path} "
              f"({report.active_count} finding(s) accepted)")
        return 0

    if args.format == "json":
        import json

        print(json.dumps(report.to_json()))
    else:
        print(report.render_text())
    return 1 if report.active_count else 0


if __name__ == "__main__":
    sys.exit(main())
