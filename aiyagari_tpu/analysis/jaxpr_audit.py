"""Level-1 static analysis: walk the traced jaxpr of every registered hot
program and enforce the compiled-artifact invariants (analysis/rules.py,
AIYA1xx).

Why the jaxpr and not the source or a parity test: the properties being
certified — scatter-free hot paths, no precision leaks inside a ladder
stage, no host sync per sweep, zero-cost telemetry-off — are properties of
the PROGRAM XLA receives, produced by tracing through layers of Python
(solver -> ops -> backend dispatch -> version shims). A source grep cannot
see through that composition, and a parity test only certifies the inputs
it ran; the jaxpr is the one structural object that certifies every path
the program can take (the same move the sequence-space literature makes
for model correctness: one structural object, checked once, covers all
shocks).

Programs are traced with `jax.make_jaxpr` on `jax.ShapeDtypeStruct`
abstract inputs supplied by the registry (analysis/registry.py) — an
eval_shape-style trace: no solve runs, (almost) nothing is allocated, so
the audit is deterministic under JAX_PLATFORMS=cpu and runs on hosts with
no accelerator at all.

The walker recurses into every sub-jaxpr a primitive carries (while/scan
bodies, cond branches, pjit/shard_map/remat/custom_* calls), tracking two
context bits the rules need: the LOOP DEPTH (host-sync and scatter checks
care whether an equation re-executes per sweep) and whether the equation
sits inside a `cond` BRANCH (the compiled-in validity fallbacks of
ops/pushforward.py put the reference scatter there on purpose — a
conditional degradation path, not a hot-path regression).
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional, Tuple

from aiyagari_tpu.analysis.rules import (
    CALLBACK_TAG_ATTR,
    CALLBACK_WHITELIST,
    Finding,
    rule_by_name,
)

__all__ = [
    "EqnContext",
    "walk_jaxpr",
    "audit_program",
    "audit_closed_jaxpr",
]

# Primitives that move mass through a scatter. "scatter-add" is the
# `.at[].add` lowering the push-forward backends replace; plain "scatter"
# (`.at[].set`) rides along — a set inside a hot sweep has the same serial
# lowering.
_SCATTER_PRIMS = frozenset({"scatter-add", "scatter", "scatter-mul",
                            "scatter-min", "scatter-max"})

# Host-synchronizing primitives never allowed inside a loop body.
_HOST_SYNC_PRIMS = frozenset({"io_callback", "infeed", "outfeed"})

_FLOAT32 = "float32"
_FLOAT64 = "float64"


@dataclasses.dataclass(frozen=True)
class EqnContext:
    """Where an equation sits in the recursion."""

    loop_depth: int = 0          # nesting count of while/scan bodies
    in_cond_branch: bool = False  # inside any lax.cond branch
    path: Tuple[str, ...] = ()    # primitive names from the root

    def describe(self) -> str:
        return "/".join(self.path) if self.path else "<top>"


def _sub_jaxprs(eqn):
    """Yield (sub_jaxpr, context_kind) for every jaxpr carried in an
    equation's params, generically: any param value that IS a jaxpr (or a
    tuple/list of them) recurses, so new jaxpr-carrying primitives are
    covered without a registry of param names. context_kind is "loop"
    (while/scan bodies and conditions — re-executed per iteration),
    "branch" (cond branches), or "call" (everything else)."""
    import jax.core as jcore

    prim = eqn.primitive.name

    def kind_for(param_name: str) -> str:
        if prim == "while" and param_name in ("body_jaxpr", "cond_jaxpr"):
            return "loop"
        if prim == "scan" and param_name == "jaxpr":
            return "loop"
        if prim == "cond" and param_name == "branches":
            return "branch"
        return "call"

    for name, value in eqn.params.items():
        values = value if isinstance(value, (tuple, list)) else (value,)
        for v in values:
            if isinstance(v, jcore.ClosedJaxpr):
                yield v.jaxpr, kind_for(name)
            elif isinstance(v, jcore.Jaxpr):
                yield v, kind_for(name)


def walk_jaxpr(jaxpr, ctx: EqnContext = EqnContext()) -> Iterator[tuple]:
    """Yield (eqn, ctx) for every equation reachable from `jaxpr`,
    recursing into all sub-jaxprs with the context updated per kind."""
    for eqn in jaxpr.eqns:
        yield eqn, ctx
        for sub, kind in _sub_jaxprs(eqn):
            sub_ctx = EqnContext(
                loop_depth=ctx.loop_depth + (1 if kind == "loop" else 0),
                in_cond_branch=ctx.in_cond_branch or kind == "branch",
                path=ctx.path + (eqn.primitive.name,),
            )
            yield from walk_jaxpr(sub, sub_ctx)


# -- callback identification ------------------------------------------------


def _callback_tag(obj, depth: int = 0) -> Optional[str]:
    """Find a CALLBACK_TAG_ATTR on a callback object or anything it closes
    over. jax wraps the user's function (partial -> _flat_callback closure
    on jax 0.4.x), so the tag is discovered by a bounded structural search:
    the object itself, functools.partial fields, __wrapped__, and closure
    cell contents."""
    if depth > 4 or obj is None:
        return None
    tag = getattr(obj, CALLBACK_TAG_ATTR, None)
    if isinstance(tag, str):
        return tag
    # functools.partial
    for attr in ("func",):
        inner = getattr(obj, attr, None)
        if inner is not None and inner is not obj:
            tag = _callback_tag(inner, depth + 1)
            if tag:
                return tag
    wrapped = getattr(obj, "__wrapped__", None)
    if wrapped is not None:
        tag = _callback_tag(wrapped, depth + 1)
        if tag:
            return tag
    closure = getattr(obj, "__closure__", None)
    if closure:
        for cell in closure:
            try:
                content = cell.cell_contents
            except ValueError:  # pragma: no cover - empty cell
                continue
            if callable(content):
                tag = _callback_tag(content, depth + 1)
                if tag:
                    return tag
    return None


def _eqn_callback_tag(eqn) -> Optional[str]:
    for value in eqn.params.values():
        if callable(value):
            tag = _callback_tag(value)
            if tag:
                return tag
    return None


# -- per-rule checks --------------------------------------------------------


def _select_guarded(eqn, users, depth: int = 0) -> bool:
    """True when a scatter's value is consumed ONLY by the select_n that
    arbitrates the compiled-in validity fallback. Under vmap, a
    `lax.cond(plan.ok, scatter_free, scatter)` with a batched predicate
    batches to both branches + `select_n` — the scatter is still the
    guarded fallback, just in its residual batched form, so it must not
    trip the rule (only an UNguarded scatter is a hot-path regression).
    Chained scatters (the two-leg lottery) recurse."""
    if depth > 4:
        return False
    for ov in eqn.outvars:
        consumers = users.get(_var_key(ov), [])
        if not consumers:
            return False
        for c in consumers:
            name = c.primitive.name
            if name == "select_n":
                continue
            if name in _SCATTER_PRIMS and _select_guarded(c, users,
                                                          depth + 1):
                continue
            return False
    return True


def _check_no_scatter(jaxpr, program: str) -> List[Finding]:
    import jax.core as jcore

    rule = rule_by_name("no-scatter")
    out: List[Finding] = []

    def visit(jx, ctx: EqnContext):
        users: dict = {}
        for eqn in jx.eqns:
            for v in eqn.invars:
                if isinstance(v, jcore.Var):
                    users.setdefault(_var_key(v), []).append(eqn)
        for eqn in jx.eqns:
            if (eqn.primitive.name in _SCATTER_PRIMS
                    and not ctx.in_cond_branch
                    and not _select_guarded(eqn, users)):
                out.append(Finding(
                    rule, program,
                    f"{eqn.primitive.name} on the unconditional path "
                    f"(at {ctx.describe()}) of a program declared "
                    "scatter-free; only the validity-fallback branch "
                    "(lax.cond, or its select_n residual under vmap) may "
                    "scatter"))
            for sub, kind in _sub_jaxprs(eqn):
                visit(sub, EqnContext(
                    loop_depth=ctx.loop_depth + (1 if kind == "loop" else 0),
                    in_cond_branch=ctx.in_cond_branch or kind == "branch",
                    path=ctx.path + (eqn.primitive.name,)))

    visit(jaxpr, EqnContext())
    return out


def _check_precision_leak(jaxpr, program: str,
                          stage_dtype: Optional[str]) -> List[Finding]:
    rule = rule_by_name("no-precision-leak")
    out = []
    for eqn, ctx in walk_jaxpr(jaxpr):
        name = eqn.primitive.name
        if name == "convert_element_type" and stage_dtype is not None:
            import numpy as np

            new = np.dtype(eqn.params["new_dtype"])
            old_aval = getattr(eqn.invars[0], "aval", None)
            old = np.dtype(old_aval.dtype) if old_aval is not None else None
            if (old is not None
                    and np.issubdtype(new, np.floating)
                    and np.issubdtype(old, np.floating)
                    and new != old):
                leak = ((stage_dtype == _FLOAT32 and new == np.float64)
                        or (stage_dtype == _FLOAT64 and new == np.float32))
                if leak:
                    out.append(Finding(
                        rule, program,
                        f"convert_element_type {old} -> {new} inside a "
                        f"declared-{stage_dtype} stage "
                        f"(at {ctx.describe()})"))
        elif name == "dot_general":
            import numpy as np

            dts = [np.dtype(v.aval.dtype) for v in eqn.invars
                   if getattr(v, "aval", None) is not None]
            floats = [d for d in dts if np.issubdtype(d, np.floating)]
            if len(set(floats)) > 1:
                out.append(Finding(
                    rule, program,
                    f"dot_general with mixed float operand dtypes "
                    f"{sorted(str(d) for d in set(floats))} "
                    f"(at {ctx.describe()})"))
    return out


def _check_host_sync(jaxpr, program: str) -> List[Finding]:
    rule = rule_by_name("no-host-sync-in-loop")
    out = []
    for eqn, ctx in walk_jaxpr(jaxpr):
        if ctx.loop_depth < 1:
            continue
        name = eqn.primitive.name
        if name in _HOST_SYNC_PRIMS:
            out.append(Finding(
                rule, program,
                f"{name} inside a loop body (at {ctx.describe()})"))
        elif name == "debug_callback":
            tag = _eqn_callback_tag(eqn)
            if tag not in CALLBACK_WHITELIST:
                label = f"tagged {tag!r}" if tag else "untagged"
                out.append(Finding(
                    rule, program,
                    f"{label} debug_callback inside a loop body "
                    f"(at {ctx.describe()}); route it through the counted "
                    "degradation-event path and tag the host function "
                    f"with {CALLBACK_TAG_ATTR}"))
    return out


def _all_avals(jaxpr):
    seen = set()
    for v in list(jaxpr.constvars) + list(jaxpr.invars) + list(jaxpr.outvars):
        aval = getattr(v, "aval", None)
        if aval is not None:
            seen.add((getattr(aval, "shape", ()), str(getattr(aval, "dtype", ""))))
    for eqn, _ in walk_jaxpr(jaxpr):
        for v in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(v, "aval", None)
            if aval is not None:
                seen.add((getattr(aval, "shape", ()),
                          str(getattr(aval, "dtype", ""))))
    return seen


def _check_telemetry_noop(off_jaxpr, on_jaxpr, program: str,
                          sentinel: int) -> List[Finding]:
    """The PR 6 zero-cost pin, generalized: the recorder ring is traced
    with a sentinel capacity no model dimension shares, so ANY
    sentinel-sized value in the telemetry-off program is recorder residue;
    and the telemetry-ON program must actually carry the ring (otherwise
    the wiring regressed and the off-check is vacuous)."""
    rule = rule_by_name("telemetry-noop")
    out = []

    def has_sentinel(jaxpr):
        return any(sentinel in shape for shape, _ in _all_avals(jaxpr))

    if has_sentinel(off_jaxpr):
        out.append(Finding(
            rule, program,
            f"telemetry-off trace still carries a ring-buffer-shaped "
            f"value (a dimension of {sentinel}); the recorder must "
            "compile out entirely when TelemetryConfig is None"))
    if on_jaxpr is not None and not has_sentinel(on_jaxpr):
        out.append(Finding(
            rule, program,
            f"telemetry-on trace carries NO ring buffer (no dimension of "
            f"{sentinel}): the recorder wiring is broken, so the "
            "telemetry-off no-op check certifies nothing"))
    return out


def _var_key(v):
    return id(v)


def _outvar_root_deps(jaxpr, n_skip_invars: int = 0):
    """For each jaxpr outvar: the set of invar indices (counted after
    skipping the first `n_skip_invars` const invars) it transitively
    depends on. Equations are treated as opaque — every output depends on
    every input — which can only over-report reads (a conservative
    direction for dead-carry: never a false positive)."""
    import jax.core as jcore

    roots = {}
    for i, v in enumerate(jaxpr.invars):
        if i >= n_skip_invars:
            roots[_var_key(v)] = frozenset({i - n_skip_invars})
    for eqn in jaxpr.eqns:
        dep = frozenset()
        for v in eqn.invars:
            if isinstance(v, jcore.Var):
                dep |= roots.get(_var_key(v), frozenset())
        for ov in eqn.outvars:
            roots[_var_key(ov)] = dep
    out = []
    for v in jaxpr.outvars:
        if isinstance(v, jcore.Var):
            out.append(roots.get(_var_key(v), frozenset()))
        else:
            out.append(frozenset())
    return out


def _used_invar_slots(jaxpr, n_skip_invars: int = 0):
    """Invar indices (post-skip) referenced by any equation or outvar."""
    import jax.core as jcore

    slot = {_var_key(v): i - n_skip_invars
            for i, v in enumerate(jaxpr.invars) if i >= n_skip_invars}
    used = set()
    for eqn in jaxpr.eqns:
        for v in eqn.invars:
            if isinstance(v, jcore.Var) and _var_key(v) in slot:
                used.add(slot[_var_key(v)])
    for v in jaxpr.outvars:
        if isinstance(v, jcore.Var) and _var_key(v) in slot:
            used.add(slot[_var_key(v)])
    return used


def _check_dead_carry(jaxpr, program: str) -> List[Finding]:
    """Flag while_loop carry slots that are written but never read: the
    loop condition ignores them, no OTHER carry slot reads them, and the
    enclosing program drops the loop's corresponding output. Requires
    use-site knowledge of each while eqn's outputs, so this walks each
    jaxpr level explicitly instead of using the flat iterator."""
    import jax.core as jcore

    rule = rule_by_name("dead-carry")
    out: List[Finding] = []

    def visit(jx, path: Tuple[str, ...]):
        # Vars consumed by LATER equations or by the jaxpr's outputs.
        used_here = set()
        for eqn in jx.eqns:
            for v in eqn.invars:
                if isinstance(v, jcore.Var):
                    used_here.add(_var_key(v))
        for v in jx.outvars:
            if isinstance(v, jcore.Var):
                used_here.add(_var_key(v))

        for eqn in jx.eqns:
            if eqn.primitive.name == "while":
                body = eqn.params["body_jaxpr"].jaxpr
                cond = eqn.params["cond_jaxpr"].jaxpr
                n_body_consts = eqn.params.get("body_nconsts", 0)
                n_cond_consts = eqn.params.get("cond_nconsts", 0)
                n_carry = len(body.outvars)
                body_deps = _outvar_root_deps(body, n_body_consts)
                cond_reads = _used_invar_slots(cond, n_cond_consts)
                for i in range(n_carry):
                    if i in cond_reads:
                        continue
                    read_by_other = any(i in body_deps[j]
                                        for j in range(n_carry) if j != i)
                    if read_by_other:
                        continue
                    ov = eqn.outvars[i]
                    if (not isinstance(ov, jcore.DropVar)
                            and _var_key(ov) in used_here):
                        continue
                    # Written vs pure self-passthrough: a slot whose next
                    # value IS its own invar is carried unchanged; anything
                    # else recomputes it every iteration.
                    body_in = (body.invars[n_body_consts + i]
                               if n_body_consts + i < len(body.invars)
                               else None)
                    passthrough = (isinstance(body.outvars[i], jcore.Var)
                                   and body.outvars[i] is body_in)
                    written = not passthrough
                    kind = ("written every iteration but read by nothing"
                            if written else
                            "carried unchanged and read by nothing")
                    where = "/".join(path) if path else "<top>"
                    out.append(Finding(
                        rule, program,
                        f"while_loop carry slot {i} "
                        f"({body.outvars[i].aval.str_short()}) is {kind} "
                        f"— not the loop condition, not another carry "
                        f"slot, and the enclosing program drops it "
                        f"(at {where})"))
            for sub, _ in _sub_jaxprs(eqn):
                visit(sub, path + (eqn.primitive.name,))

    visit(jaxpr, ())
    return out


def _check_nan_exit(jaxpr, program: str) -> List[Finding]:
    """AIYA107: every residual while_loop's cond must exit on a non-finite
    residual. Certified by CONCRETE evaluation, not pattern matching: the
    cond sub-jaxpr is a tiny pure function, so it is executed once with
    every float carry input NaN (loop-invariant/const inputs finite 1.0,
    integer inputs 0 for carries — counters start there — and a large
    value for consts, so an `it < max_iter` guard stays True and cannot
    mask the NaN question; bools False for carries / True for consts, the
    keep-running direction). A True output means a NaN-poisoned iterate
    would keep the loop running — the burn-max_iter-on-garbage failure the
    resilience layer exists to prevent. Conds reading no float carry
    (fixed-count loops) are exempt; conds the evaluator cannot execute
    (exotic primitives) are skipped conservatively."""
    import numpy as np

    import jax

    rule = rule_by_name("nan-exit")
    out: List[Finding] = []
    for eqn, ctx in walk_jaxpr(jaxpr):
        if eqn.primitive.name != "while":
            continue
        closed = eqn.params["cond_jaxpr"]
        cjx = closed.jaxpr
        n_consts = eqn.params.get("cond_nconsts", 0)
        used = _used_invar_slots(cjx, n_consts)
        float_read = any(
            np.issubdtype(np.dtype(cjx.invars[n_consts + i].aval.dtype),
                          np.floating)
            for i in used
            if n_consts + i < len(cjx.invars))
        if not float_read:
            continue
        args = []
        for k, v in enumerate(cjx.invars):
            aval = v.aval
            dt = np.dtype(aval.dtype)
            shape = tuple(getattr(aval, "shape", ()))
            const = k < n_consts
            if np.issubdtype(dt, np.floating):
                val = np.ones(shape, dt) if const else np.full(shape, np.nan,
                                                               dt)
            elif dt == np.bool_:
                val = np.full(shape, const)
            elif np.issubdtype(dt, np.integer):
                val = np.full(shape, 2 ** 20 if const else 0, dt)
            else:
                val = np.zeros(shape, dt)
            args.append(val)
        try:
            res = jax.core.eval_jaxpr(cjx, closed.consts, *args)
        except Exception:   # pragma: no cover - un-evaluable cond: skip
            continue
        if res and bool(np.any(np.asarray(res[0]))):
            out.append(Finding(
                rule, program,
                "while_loop condition stays True when every float carry "
                "input is NaN (at "
                f"{ctx.describe()}): a NaN-poisoned iterate runs to "
                "max_iter instead of early-exiting; write the residual "
                "test as `dist >= tol` (NaN-exiting) or carry the "
                "failure sentinel (diagnostics/sentinel.py)"))
    return out


def _check_stable_carry(jaxpr, program: str) -> List[Finding]:
    rule = rule_by_name("stable-carry")
    out = []
    for eqn, ctx in walk_jaxpr(jaxpr):
        name = eqn.primitive.name
        if name == "while":
            body = eqn.params["body_jaxpr"].jaxpr
            n_consts = eqn.params.get("body_nconsts", 0)
            carry_in = body.invars[n_consts:]
            carry_out = body.outvars
        elif name == "scan":
            body = eqn.params["jaxpr"].jaxpr
            n_consts = eqn.params.get("num_consts", 0)
            n_carry = eqn.params.get("num_carry", 0)
            carry_in = body.invars[n_consts:n_consts + n_carry]
            carry_out = body.outvars[:n_carry]
        else:
            continue
        for i, (vi, vo) in enumerate(zip(carry_in, carry_out)):
            ai, ao = vi.aval, getattr(vo, "aval", None)
            if ao is not None and (ai.shape != ao.shape
                                   or ai.dtype != ao.dtype):
                out.append(Finding(
                    rule, program,
                    f"{name} carry slot {i} changes aval across "
                    f"iterations: {ai.str_short()} -> {ao.str_short()} "
                    f"(at {ctx.describe()})"))
            elif getattr(ai, "weak_type", False):
                out.append(Finding(
                    rule, program,
                    f"{name} carry slot {i} ({ai.str_short()}) is "
                    f"weak-typed (at {ctx.describe()}): a bare Python "
                    "scalar in the carry init re-specializes the program "
                    "per caller literal; wrap it in jnp.asarray with an "
                    "explicit dtype"))
    return out


# -- program-level driver ---------------------------------------------------


def audit_closed_jaxpr(closed, program: str, *, scatter_free: bool = False,
                       stage_dtype: Optional[str] = None,
                       rules=None) -> List[Finding]:
    """Run the jaxpr-level rules (minus telemetry-noop, which needs a
    paired trace — audit_program handles it) on one ClosedJaxpr."""
    jaxpr = getattr(closed, "jaxpr", closed)
    selected = None if rules is None else {r.name for r in rules}

    def want(name):
        return selected is None or name in selected

    findings: List[Finding] = []
    if scatter_free and want("no-scatter"):
        findings += _check_no_scatter(jaxpr, program)
    if want("no-precision-leak"):
        findings += _check_precision_leak(jaxpr, program, stage_dtype)
    if want("no-host-sync-in-loop"):
        findings += _check_host_sync(jaxpr, program)
    if want("dead-carry"):
        findings += _check_dead_carry(jaxpr, program)
    if want("stable-carry"):
        findings += _check_stable_carry(jaxpr, program)
    if want("nan-exit"):
        findings += _check_nan_exit(jaxpr, program)
    return findings


def audit_program(spec, rules=None) -> List[Finding]:
    """Trace one registered program (telemetry off) and run every
    applicable jaxpr rule; when the program wires a telemetry recorder,
    also run the paired on/off telemetry-noop check."""
    import jax

    selected = None if rules is None else {r.name for r in rules}

    def want(name):
        return selected is None or name in selected

    fn, args = spec.build_off()
    closed = jax.make_jaxpr(fn)(*args)
    findings = audit_closed_jaxpr(
        closed, spec.name, scatter_free=spec.scatter_free,
        stage_dtype=spec.stage_dtype, rules=rules)

    if spec.supports_telemetry and want("telemetry-noop"):
        from aiyagari_tpu.analysis.registry import TELEMETRY_SENTINEL_CAPACITY

        fn_on, args_on = spec.build_on()
        closed_on = jax.make_jaxpr(fn_on)(*args_on)
        findings += _check_telemetry_noop(
            closed.jaxpr, closed_on.jaxpr, spec.name,
            TELEMETRY_SENTINEL_CAPACITY)
    return findings
