"""Level-2 static analysis: AST lint over the package source (AIYA2xx).

The jaxpr auditor certifies the compiled artifacts; this lint certifies
the source DISCIPLINE that keeps them auditable and fast:

  * mesh-shim-discipline (AIYA201) — jax is pinned at 0.4.x on this image
    and every sharding symbol goes through the one version probe in
    parallel/mesh.py. A direct `from jax.sharding import ...` elsewhere
    compiles today and breaks on the next jax bump — the exact class of
    breakage PR 1 spent 39 test failures un-doing. Since the partition-
    rule module landed (ISSUE 13), raw `PartitionSpec(...)` CONSTRUCTION
    outside parallel/ is flagged too: ad-hoc specs bypass the rule
    matcher (parallel/rules.py) that keeps placements declarative and
    topology-portable — build them via match_partition_rules /
    mesh.named_sharding, or use the shard_map in-spec alias idiom
    (`from ...mesh import PartitionSpec as P`), which stays sanctioned
    for shard-local program specs.
  * no-host-scalar-in-hot-module (AIYA202) — `.item()` and
    `float(x[i])`-style element fetches cost one ~100 ms host round trip
    EACH on the remote TPU transport (solvers/egm._cached_grid_bounds
    measured them at 45% of a 400k solve); hot modules batch through
    jax.device_get instead.
  * no-bare-debug-print (AIYA203) — production signals are counted
    degradation events (metrics + ledger, PR 6); a jax.debug.print is a
    debugging aid and must sit behind an env-gated `if *DEBUG*:` guard.
  * route-resolution-discipline (AIYA204) — a conditional that maps the
    literal "auto" (or a jax.default_backend() platform test) onto a
    concrete route literal ("transpose", "xla", "sort", ...) may live
    only in the sanctioned resolver functions and tuning/ — anywhere
    else it re-hardcodes a route choice behind the autotuner's back and
    escapes the route_decision ledger trail.
  * ift-differentiation-discipline (AIYA205) — jax.grad / vjp / jvp /
    jacfwd / jacrev / hessian aimed directly at an unrolled while_loop
    solver (solve_aiyagari_egm, stationary_distribution,
    solve_transition, ...) is flagged everywhere except ops/implicit.py:
    the IFT wrappers (ISSUE 17) are the one sanctioned way to
    differentiate through a converged solve.

Suppression: a `# noqa: AIYA###` comment on the flagged line (multiple
ids comma-separated) marks a deliberate exception; suppressed findings
are still reported, with `suppressed: true`. The checked-in findings
baseline (analysis/baseline.json) plays the same role for findings that
predate a new rule — the shipped baseline is EMPTY: the tree is clean.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Iterable, List, Optional

from aiyagari_tpu.analysis.rules import Finding, rule_by_name

__all__ = ["lint_file", "lint_tree", "hot_module", "iter_package_files"]

# Modules exempt from mesh-shim-discipline: the shim itself.
_MESH_SHIM = "parallel/mesh.py"

# Raw-PartitionSpec-construction scope (the ISSUE 13 extension of
# AIYA201): the whole parallel/ layer owns spec construction — the shim,
# the rule matcher, and the ring/halo programs it backs.
_PARALLEL_DIR = "parallel/"

# Hot-module scope of AIYA202: the directories whose code runs per sweep
# or per solve. numpy_backend.py is the HOST reference implementation
# (plain numpy end to end) — float() there is arithmetic, not a sync.
# equilibrium/ joined the scope with the fused device loop (ISSUE 18):
# its outer rounds are now in-program, so a host scalar pull there is a
# per-round sync exactly like one in a solver sweep; the host-loop
# reference paths carry documented per-line noqa where they fetch their
# bracket scalars by design.
_HOT_DIRS = ("solvers/", "ops/", "sim/", "transition/", "equilibrium/")
_HOT_EXEMPT = ("solvers/numpy_backend.py",)

_NOQA_RE = re.compile(r"#\s*noqa:\s*([A-Z]{4}\d{3}(?:\s*,\s*[A-Z]{4}\d{3})*)")

_FORBIDDEN_MODULES = ("jax.sharding", "jax.experimental.shard_map")

# AIYA204 scope: the sanctioned resolver functions (per file) and the
# tuning layer. Everything else that conditions on the "auto" literal or
# a default_backend() test and binds/returns a route literal re-hardcodes
# a route choice.
_ROUTE_RESOLVER_FUNCS = {
    "ops/pushforward.py": {"resolve_backend"},
    "ops/egm.py": {"resolve_egm_kernel", "require_xla_egm_kernel"},
    "ops/interp.py": {"bucket_index", "searchsorted_method"},
}
_ROUTE_EXEMPT_DIRS = ("tuning/",)

# AIYA205 scope: the while_loop fixed-point entry points that reverse-mode
# AD must never touch directly, and the autodiff operators that would do
# so. ops/implicit.py is the sanctioned door (its custom_vjp rules ARE the
# gradients of these solves); everything else differentiates the *_implicit
# wrappers.
_IFT_EXEMPT = ("ops/implicit.py",)
_UNROLLED_SOLVER_ENTRYPOINTS = frozenset({
    "solve_aiyagari_egm", "solve_aiyagari_egm_labor", "solve_aiyagari_vfi",
    "stationary_distribution", "solve_equilibrium",
    "solve_equilibrium_distribution", "solve_transition",
    # The fused one-program loops (ISSUE 18): the whole GE while_loop in
    # one trace — differentiating them unrolls EVERY outer round.
    "solve_equilibrium_fused", "solve_equilibrium_fused_batched",
    "fused_ge_program", "fused_ge_batched_program",
    # The fused transition round loops (ISSUE 19): same rationale — the
    # whole Newton/damped round loop lives in one while_loop trace, and
    # path sensitivities come from the fake-news linearization
    # (transition/jacobian.py), never from differentiating the loop.
    "solve_transition_fused", "solve_transitions_sweep_fused",
    "fused_transition_program", "fused_transition_sweep_program",
})
_AUTODIFF_OPERATORS = frozenset({
    "grad", "value_and_grad", "vjp", "jvp", "jacfwd", "jacrev", "hessian",
})

# The route names a resolution binds (ops/pushforward.BACKENDS,
# ops/egm.EGM_KERNELS, the searchsorted methods) — kept literal here so
# the lint needs no jax import; membership is exact-match, which keeps
# dtype strings and error messages out of scope.
_ROUTE_LITERALS = frozenset({
    "scatter", "transpose", "banded", "pallas",
    "xla", "pallas_inverse", "pallas_fused",
    "scan", "sort",
})


def hot_module(rel_path: str) -> bool:
    rel = rel_path.replace("\\", "/")
    if any(rel.endswith(e) for e in _HOT_EXEMPT):
        return False
    return any(f"/{d}" in f"/{rel}" for d in _HOT_DIRS)


def _noqa_ids(source_lines, lineno: int) -> set:
    if 1 <= lineno <= len(source_lines):
        m = _NOQA_RE.search(source_lines[lineno - 1])
        if m:
            return {s.strip() for s in m.group(1).split(",")}
    return set()


def _attr_chain(node) -> Optional[str]:
    """'jax.sharding.PartitionSpec' for nested ast.Attribute, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _Linter(ast.NodeVisitor):
    def __init__(self, rel_path: str, source: str, *, hot: Optional[bool],
                 mesh_exempt: Optional[bool]):
        self.rel = rel_path
        self.lines = source.splitlines()
        self.hot = hot_module(rel_path) if hot is None else hot
        rel_norm = rel_path.replace("\\", "/")
        exempt = rel_norm.endswith(_MESH_SHIM)
        self.mesh_exempt = exempt if mesh_exempt is None else mesh_exempt
        # Raw PartitionSpec construction is sanctioned in all of
        # parallel/ (shim + rule matcher + sharded programs); fixtures
        # linted with an explicit mesh_exempt follow that flag.
        in_parallel = f"/{_PARALLEL_DIR}" in f"/{rel_norm}"
        self.spec_exempt = (self.mesh_exempt or in_parallel
                            if mesh_exempt is None else mesh_exempt)
        # AIYA204 scope for this file: the sanctioned resolver functions
        # (when this IS one of the resolver modules) and the tuning layer.
        self.route_exempt = any(f"/{d}" in f"/{rel_norm}"
                                for d in _ROUTE_EXEMPT_DIRS)
        self.ift_exempt = any(rel_norm.endswith(e) for e in _IFT_EXEMPT)
        self._route_allowed_funcs = set()
        for suffix, funcs in _ROUTE_RESOLVER_FUNCS.items():
            if rel_norm.endswith(suffix):
                self._route_allowed_funcs |= funcs
        self._func_stack: List[str] = []
        self.findings: List[Finding] = []
        # Env-gated-debug context: names of If-tests containing "DEBUG"
        # we are currently inside of (AIYA203's sanctioned pattern).
        self._debug_guard_depth = 0

    # -- helpers ------------------------------------------------------------

    def _emit(self, rule_name: str, node, message: str):
        rule = rule_by_name(rule_name)
        line = getattr(node, "lineno", None)
        suppressed = bool(line and rule.id in _noqa_ids(self.lines, line))
        self.findings.append(Finding(
            rule, self.rel, message, line=line, suppressed=suppressed,
            suppressed_by="noqa" if suppressed else None))

    # -- AIYA201: mesh-shim discipline --------------------------------------

    def visit_Import(self, node: ast.Import):
        if not self.mesh_exempt:
            for alias in node.names:
                if any(alias.name == m or alias.name.startswith(m + ".")
                       for m in _FORBIDDEN_MODULES):
                    self._emit(
                        "mesh-shim-discipline", node,
                        f"direct `import {alias.name}`; route sharding "
                        "symbols through aiyagari_tpu.parallel.mesh")
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom):
        mod = node.module or ""
        if not self.mesh_exempt:

            def forbidden(path: str) -> bool:
                return any(path == m or path.startswith(m + ".")
                           for m in _FORBIDDEN_MODULES)

            if forbidden(mod):
                names = ", ".join(a.name for a in node.names)
                self._emit(
                    "mesh-shim-discipline", node,
                    f"direct `from {mod} import {names}`; import from "
                    "aiyagari_tpu.parallel.mesh instead (it re-exports "
                    "PartitionSpec/NamedSharding/Mesh and owns the "
                    "shard_map version probe)")
            else:
                # The parent-module forms — `from jax import sharding`,
                # `from jax.experimental import shard_map` — bind the
                # forbidden module itself to a local name; catching only
                # the full-path form would make the rule trivially
                # bypassable.
                for alias in node.names:
                    if forbidden(f"{mod}.{alias.name}" if mod
                                 else alias.name):
                        self._emit(
                            "mesh-shim-discipline", node,
                            f"direct `from {mod} import {alias.name}`; "
                            "import from aiyagari_tpu.parallel.mesh "
                            "instead")
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute):
        if not self.mesh_exempt:
            chain = _attr_chain(node)
            if chain and any(chain == m or chain.startswith(m + ".")
                             for m in _FORBIDDEN_MODULES):
                self._emit(
                    "mesh-shim-discipline", node,
                    f"direct attribute reference `{chain}`; go through "
                    "aiyagari_tpu.parallel.mesh")
                # Do not recurse: the inner `jax.sharding` node of
                # `jax.sharding.X` would double-report the same reference.
                return
        self.generic_visit(node)

    # -- AIYA204: route-resolution discipline --------------------------------

    @staticmethod
    def _binds_route_literal(branch) -> bool:
        """Whether a conditional branch binds or returns one of the route
        literals. `branch` is a statement list (ast.If arm) or a bare
        expression (ast.IfExp arm). Only Return/assignment VALUES are
        searched — raise messages mentioning a route name are guidance,
        not a choice."""
        if isinstance(branch, list):
            values = []
            for stmt in branch:
                for n in ast.walk(stmt):
                    if isinstance(n, (ast.Return, ast.Assign, ast.AnnAssign,
                                      ast.AugAssign, ast.NamedExpr)):
                        if n.value is not None:
                            values.append(n.value)
        else:
            values = [branch]
        return any(isinstance(c, ast.Constant) and c.value in _ROUTE_LITERALS
                   for v in values for c in ast.walk(v))

    def _check_route_resolution(self, node, test, branches):
        if self.route_exempt or any(f in self._route_allowed_funcs
                                    for f in self._func_stack):
            return
        if any(isinstance(n, ast.Constant) and n.value == "auto"
               for n in ast.walk(test)):
            trigger = '"auto"'
        elif any(isinstance(n, ast.Call)
                 and ((isinstance(n.func, ast.Attribute)
                       and n.func.attr == "default_backend")
                      or (isinstance(n.func, ast.Name)
                          and n.func.id == "default_backend"))
                 for n in ast.walk(test)):
            trigger = "jax.default_backend()"
        else:
            return
        if any(self._binds_route_literal(b) for b in branches):
            self._emit(
                "route-resolution-discipline", node,
                f"conditional on {trigger} binds a concrete route literal "
                "outside the sanctioned resolvers; route this choice "
                "through ops/pushforward.resolve_backend / "
                "ops/egm.resolve_egm_kernel / ops/interp."
                "searchsorted_method so the tuning cache and the "
                "route_decision ledger trail see it")

    def visit_FunctionDef(self, node: ast.FunctionDef):
        self._func_stack.append(node.name)
        self.generic_visit(node)
        self._func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_IfExp(self, node: ast.IfExp):
        self._check_route_resolution(node, node.test,
                                     [node.body, node.orelse])
        self.generic_visit(node)

    # -- AIYA202 / AIYA203 --------------------------------------------------

    def visit_If(self, node: ast.If):
        self._check_route_resolution(node, node.test,
                                     [node.body, node.orelse])
        guard = any(isinstance(n, ast.Name) and "DEBUG" in n.id
                    for n in ast.walk(node.test))
        self.visit(node.test)
        # Only the TRUE branch of an `if *DEBUG*:` is the opt-in debug
        # path; the else branch is the production path taken when the
        # flag is off, so a debug print there is exactly as bare as one
        # with no guard at all.
        if guard:
            self._debug_guard_depth += 1
        for child in node.body:
            self.visit(child)
        if guard:
            self._debug_guard_depth -= 1
        for child in node.orelse:
            self.visit(child)

    def visit_Call(self, node: ast.Call):
        func = node.func
        # AIYA201 extension (ISSUE 13): raw PartitionSpec construction
        # outside parallel/. The bare-Name form is the ad-hoc spec the
        # rule matcher exists to replace; attribute forms whose chain is
        # a forbidden jax module are already flagged by visit_Attribute
        # (no double report). The `as P` shard_map in-spec alias stays
        # sanctioned (module docstring).
        if not self.spec_exempt:
            raw = (isinstance(func, ast.Name)
                   and func.id == "PartitionSpec")
            if not raw and isinstance(func, ast.Attribute):
                chain = _attr_chain(func)
                raw = (chain is not None
                       and chain.endswith(".PartitionSpec")
                       and not any(chain.startswith(m + ".")
                                   for m in _FORBIDDEN_MODULES))
            if raw:
                self._emit(
                    "mesh-shim-discipline", node,
                    "raw PartitionSpec(...) construction outside "
                    "parallel/; build placements through the rule "
                    "matcher (parallel/rules.match_partition_rules) or "
                    "mesh.named_sharding — ad-hoc specs bypass the "
                    "declarative placement layer")
        if self.hot:
            if (isinstance(func, ast.Attribute) and func.attr == "item"
                    and not node.args):
                self._emit(
                    "no-host-scalar-in-hot-module", node,
                    ".item() is a per-element device fetch; batch scalars "
                    "through one jax.device_get")
            if (isinstance(func, ast.Name)
                    and func.id in ("float", "int", "bool")
                    and len(node.args) == 1
                    and isinstance(node.args[0], ast.Subscript)
                    # x.shape[-1] / mesh.shape[axis] index a host tuple,
                    # not a device array — no fetch, no finding.
                    and not (isinstance(node.args[0].value, ast.Attribute)
                             and node.args[0].value.attr == "shape")):
                self._emit(
                    "no-host-scalar-in-hot-module", node,
                    f"{func.id}(<indexed array>) eagerly fetches one "
                    "element per call (~100 ms per round trip on the "
                    "remote TPU transport); use the batched "
                    "jax.device_get pattern (_cached_grid_bounds / "
                    "_fetch_scalars)")
        # AIYA205: reverse/forward-mode AD aimed straight at an unrolled
        # while_loop solver. Only the direct-reference form is detectable
        # statically (jax.grad(solve_aiyagari_egm) / grad(solve_transition,
        # ...)); a lambda wrapper calling the solver inside still fails at
        # trace time — the lint catches the honest spelling, the runtime
        # catches the rest.
        if not self.ift_exempt:
            op = None
            if isinstance(func, ast.Name) and func.id in _AUTODIFF_OPERATORS:
                op = func.id
            elif isinstance(func, ast.Attribute):
                ch = _attr_chain(func)
                if ch and ch.split(".")[-1] in _AUTODIFF_OPERATORS:
                    op = ch
            if op is not None and node.args:
                tgt = node.args[0]
                name = None
                if isinstance(tgt, ast.Name):
                    name = tgt.id
                elif isinstance(tgt, ast.Attribute):
                    name = tgt.attr
                if name in _UNROLLED_SOLVER_ENTRYPOINTS:
                    self._emit(
                        "ift-differentiation-discipline", node,
                        f"`{op}({name}, ...)` differentiates an unrolled "
                        "while_loop fixed point; use the implicit wrapper "
                        f"({name}_implicit / steady_state_map / "
                        "transition_r_path_implicit — "
                        "ops/implicit.fixed_point_vjp is the one "
                        "sanctioned door)")
        chain = _attr_chain(func) if isinstance(func, ast.Attribute) else None
        if chain and chain.split(".")[-2:] == ["debug", "print"]:
            if self._debug_guard_depth == 0:
                self._emit(
                    "no-bare-debug-print", node,
                    f"bare `{chain}(...)`: route production signals "
                    "through the counted degradation-event path "
                    "(ops/pushforward._record_fallback) or gate the "
                    "print behind an env-derived *DEBUG* flag")
        self.generic_visit(node)


def lint_file(path, rel_path: Optional[str] = None, *,
              hot: Optional[bool] = None,
              mesh_exempt: Optional[bool] = None) -> List[Finding]:
    """Lint one file. `hot`/`mesh_exempt` override the path-based scoping
    (the adversarial fixtures live outside the package tree and declare
    their scope explicitly)."""
    path = Path(path)
    rel = rel_path or str(path)
    source = path.read_text()
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as e:  # pragma: no cover - package must parse
        rule = rule_by_name("mesh-shim-discipline")
        return [Finding(rule, rel, f"file does not parse: {e}",
                        line=e.lineno)]
    linter = _Linter(rel, source, hot=hot, mesh_exempt=mesh_exempt)
    linter.visit(tree)
    return linter.findings


def iter_package_files() -> Iterable[tuple]:
    """(abs_path, package-relative path) for every .py file of the
    installed aiyagari_tpu package."""
    root = Path(__file__).resolve().parent.parent
    for p in sorted(root.rglob("*.py")):
        yield p, str(p.relative_to(root))


def lint_tree() -> List[Finding]:
    """Run every source rule over the whole package."""
    findings: List[Finding] = []
    for path, rel in iter_package_files():
        findings.extend(lint_file(path, rel))
    return findings
