"""Compiled-cost attribution: reconcile XLA's own cost accounting of every
registered program against the analytic roofline price — the route
observatory's measurement half (ISSUE 12).

The roofline models (diagnostics/roofline.py) are analytic LOWER bounds on
the work an algorithm specifies; XLA's `cost_analysis()` /
`memory_analysis()` report what the compiler actually emitted for the
same program (FLOPs, bytes accessed, argument/output/temp bytes). Joining
the two per `ProgramSpec` (analysis/registry.py) gives a modeled-vs-
compiled attribution table with a structural interpretation:

  * compiled/modeled byte ratios near 1-10x are the normal price of
    padding, rematerialization, and tile round-up;
  * a ratio drifting far above its historical band means an op chain
    STOPPED FUSING — the compiler now materializes intermediates the
    model assumed fused away. That is a fusion regression, detectable
    without a device or a timer: the attribution is a property of the
    compiled artifact, deterministic under JAX_PLATFORMS=cpu like the
    jaxpr audit beside it (tests/test_bench_ci.py gates the band for the
    audited EGM + push-forward programs).

Programs whose compiled artifact is NOT the production artifact on this
host are joined but never flagged: the Pallas-fused programs compile the
INTERPRETER off-TPU (its bytes say nothing about the Mosaic kernel), and
the ring-sharded sweep pads and replicates per-device buffers the
single-device model deliberately does not price.

Each run lands on the PR 6 observability surface: one `attribution`
ledger event per program on the active run ledger, plus
`aiyagari_attribution_{compiled,modeled}_bytes{program=}` /
`aiyagari_attribution_byte_ratio{program=}` Prometheus gauges and an
`aiyagari_attribution_flagged_total` counter. `bench.py --metric
attribution` freezes the table into BENCH_r11_attribution.json.

Like the registry traces, attribution compiles at the registry's tiny
shapes (nothing solves, nothing big allocates): XLA counts a while-loop
BODY once — trip counts are dynamic — so the compiled numbers are
per-sweep quantities, directly comparable to the per-sweep roofline
models.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, Optional, Tuple

__all__ = [
    "AttributionReport",
    "DEFAULT_FLAG_RATIO",
    "attribute_program",
    "modeled_cost",
    "run_attribution",
]

# Compiled bytes above this multiple of the modeled (lower-bound) bytes
# flag a structural fusion regression. The shipped tree measures 1.7-6.3x
# on the gated programs (CPU f64, registry shapes — frozen in
# BENCH_r11_attribution.json); a chain that stops fusing and
# materializes its broadcasts lands at 10-100x.
DEFAULT_FLAG_RATIO = 25.0

# Registry trace shapes (analysis/registry.py) — the shapes the analytic
# prices below are evaluated at.
_NZ = 3
_NA = 16


def _sharded_na() -> int:
    # egm/sweep_sharded traces at na=64 on a 2-device mesh (registry).
    return 64


def _model_prices() -> Dict[str, Tuple[Optional[Callable], Optional[float]]]:
    """program name -> (cost thunk | None, flag ratio | None).

    None cost: no analytic model applies (multi-solve GE/transition
    rounds compose several operators; pricing them as one sweep would be
    a fiction). None flag ratio: joined for the record but never flagged
    (interpreted Pallas artifacts off-TPU, the mesh-padded sharded
    sweep)."""
    import jax

    from aiyagari_tpu.diagnostics.roofline import (
        distribution_sweep_cost,
        egm_fused_sweep_cost,
        egm_sweep_cost,
        vfi_sweep_cost,
    )

    on_tpu = jax.default_backend() == "tpu"
    fused_flag = DEFAULT_FLAG_RATIO if on_tpu else None
    return {
        "egm/sweep": (lambda: egm_sweep_cost(_NZ, _NA, 8),
                      DEFAULT_FLAG_RATIO),
        "egm/sweep_f32_stage": (lambda: egm_sweep_cost(_NZ, _NA, 4),
                                DEFAULT_FLAG_RATIO),
        "egm/sweep_sentinel": (lambda: egm_sweep_cost(_NZ, _NA, 8),
                               DEFAULT_FLAG_RATIO),
        "egm/sweep_fused": (lambda: egm_fused_sweep_cost(_NZ, _NA, 8),
                            fused_flag),
        "egm/sweep_fused_f32_stage": (
            lambda: egm_fused_sweep_cost(_NZ, _NA, 4), fused_flag),
        "egm/sweep_labor": (lambda: egm_sweep_cost(_NZ, _NA, 8),
                            DEFAULT_FLAG_RATIO),
        "egm/sweep_sharded": (lambda: egm_sweep_cost(_NZ, _sharded_na(), 8),
                              None),
        # The 2-D (scenarios x grid) sweep: S=2 lanes of the grid-sharded
        # operator (registry traces at na=64 on a 2x2 mesh). Mesh-padded
        # like the 1-D sharded program — joined, never flagged.
        "egm/sweep_2d": (lambda: 2 * egm_sweep_cost(_NZ, _sharded_na(), 8),
                         None),
        "egm/sweep_2d_sentinel": (
            lambda: 2 * egm_sweep_cost(_NZ, _sharded_na(), 8), None),
        "vfi/step": (lambda: vfi_sweep_cost(_NZ, _NA, 8),
                     DEFAULT_FLAG_RATIO),
        "distribution/step_scatter": (
            lambda: distribution_sweep_cost(_NZ, _NA, 8, route="scatter"),
            DEFAULT_FLAG_RATIO),
        "distribution/step_transpose": (
            lambda: distribution_sweep_cost(_NZ, _NA, 8, route="transpose"),
            DEFAULT_FLAG_RATIO),
        "distribution/step_banded": (
            # The registry grid is a single tile, so the band geometry
            # collapses to the dense per-row operator (band_width = na).
            lambda: distribution_sweep_cost(_NZ, _NA, 8, route="banded",
                                            band_width=_NA),
            DEFAULT_FLAG_RATIO),
        "distribution/stationary": (
            # The stationary loop runs the "auto" default route.
            lambda: distribution_sweep_cost(_NZ, _NA, 8, route="transpose"),
            DEFAULT_FLAG_RATIO),
        "equilibrium/ge_round_batched": (None, None),
        # Fused one-program GE (equilibrium/fused.py): the whole outer loop
        # in one trace — rounds-per-solve is data-dependent, so a per-call
        # price would have to guess the iteration count. roofline.ge_fused
        # _cost prices one ROUND for the bench; joined here, never flagged.
        "equilibrium/ge_fused": (None, None),
        "equilibrium/ge_fused_sentinel": (None, None),
        "equilibrium/ge_fused_batched": (None, None),
        "transition/round": (None, None),
        # Fused one-program transitions (transition/fused.py): same story —
        # the whole MIT-shock Newton/damped round loop lives in one
        # while_loop, rounds are data-dependent.  roofline.transition_fused
        # _round_cost prices one ROUND for the bench; joined, never flagged.
        "transition/fused": (None, None),
        "transition/fused_sentinel": (None, None),
        "transition/fused_sweep": (None, None),
        "ks/distribution_step": (None, None),
    }


def modeled_cost(program: str):
    """The analytic roofline price of one registered program at its
    registry trace shapes, or None when no model applies."""
    thunk, _ = _model_prices().get(program, (None, None))
    return thunk() if thunk is not None else None


def _first(d, *keys):
    for k in keys:
        v = d.get(k)
        if v is not None:
            return float(v)
    return None


def attribute_program(spec) -> dict:
    """Lower + compile one ProgramSpec's telemetry-off entry point and
    join XLA's cost accounting against the roofline price. Raises
    ProgramUnavailable (from the builder) for environment-dependent
    programs, exactly like the jaxpr audit."""
    import jax

    fn, args = spec.build_off()
    compiled = jax.jit(fn).lower(*args).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    ca = dict(ca or {})
    rec = {
        "program": spec.name,
        "family": spec.family,
        "compiled": {
            "flops": _first(ca, "flops"),
            "transcendentals": _first(ca, "transcendentals"),
            "bytes_accessed": _first(ca, "bytes accessed", "bytes_accessed"),
        },
    }
    try:
        ma = compiled.memory_analysis()
    except Exception:   # pragma: no cover - optional on some backends
        ma = None
    if ma is not None:
        rec["compiled"].update(
            argument_bytes=int(getattr(ma, "argument_size_in_bytes", 0)),
            output_bytes=int(getattr(ma, "output_size_in_bytes", 0)),
            temp_bytes=int(getattr(ma, "temp_size_in_bytes", 0)),
            peak_bytes=int(getattr(ma, "argument_size_in_bytes", 0)
                           + getattr(ma, "output_size_in_bytes", 0)
                           + getattr(ma, "temp_size_in_bytes", 0)),
        )

    thunk, flag_ratio = _model_prices().get(spec.name, (None, None))
    if thunk is None:
        rec["modeled"] = None
        rec["byte_ratio"] = None
        rec["flop_ratio"] = None
        rec["flagged"] = False
        return rec
    cost = thunk()
    rec["modeled"] = {"mxu_flops": cost.mxu_flops, "vpu_ops": cost.vpu_ops,
                      "hbm_bytes": cost.hbm_bytes}
    cb = rec["compiled"]["bytes_accessed"]
    cf = rec["compiled"]["flops"]
    rec["byte_ratio"] = (round(cb / cost.hbm_bytes, 3)
                         if cb and cost.hbm_bytes else None)
    # Model FLOPs = MXU + VPU ops: XLA's flop count includes the
    # elementwise work the split model books on the VPU.
    ops = cost.mxu_flops + cost.vpu_ops
    rec["flop_ratio"] = round(cf / ops, 3) if cf and ops else None
    rec["flag_ratio"] = flag_ratio
    rec["flagged"] = bool(flag_ratio is not None
                          and rec["byte_ratio"] is not None
                          and rec["byte_ratio"] > flag_ratio)
    return rec


@dataclasses.dataclass(frozen=True)
class AttributionReport:
    records: Tuple[dict, ...]
    skipped: Tuple[tuple, ...]      # (program, reason)
    wall_seconds: float

    @property
    def flagged(self) -> Tuple[dict, ...]:
        return tuple(r for r in self.records if r.get("flagged"))

    def by_program(self) -> Dict[str, dict]:
        return {r["program"]: r for r in self.records}


def _emit_observability(report: AttributionReport) -> None:
    """Per-program ledger events + Prometheus gauges (the analysis
    layer's _emit_observability pattern — diagnostics must never fail a
    run)."""
    try:
        from aiyagari_tpu.diagnostics import ledger, metrics

        for rec in report.records:
            cb = rec["compiled"].get("bytes_accessed")
            if cb is not None:
                metrics.gauge("aiyagari_attribution_compiled_bytes",
                              program=rec["program"]).set(cb)
            if rec.get("modeled") is not None:
                metrics.gauge("aiyagari_attribution_modeled_bytes",
                              program=rec["program"]).set(
                    rec["modeled"]["hbm_bytes"])
            if rec.get("byte_ratio") is not None:
                metrics.gauge("aiyagari_attribution_byte_ratio",
                              program=rec["program"]).set(rec["byte_ratio"])
            if rec.get("flagged"):
                metrics.counter("aiyagari_attribution_flagged_total",
                                program=rec["program"]).inc()
            ledger.emit("attribution", program=rec["program"],
                        family=rec["family"], compiled=rec["compiled"],
                        modeled=rec.get("modeled"),
                        byte_ratio=rec.get("byte_ratio"),
                        flop_ratio=rec.get("flop_ratio"),
                        flagged=rec.get("flagged", False))
    except Exception:   # pragma: no cover - diagnostics must not fail runs
        pass


def run_attribution(families: Optional[Tuple[str, ...]] = None
                    ) -> AttributionReport:
    """Compile every (selected) registry program and assemble the
    modeled-vs-compiled attribution table. Environment-dependent
    programs report as skipped, like the jaxpr audit."""
    from aiyagari_tpu.analysis.registry import (
        ProgramUnavailable,
        registered_programs,
    )

    t0 = time.perf_counter()
    records = []
    skipped = []
    for spec in registered_programs(families):
        try:
            records.append(attribute_program(spec))
        except ProgramUnavailable as e:
            skipped.append((spec.name, str(e)))
    report = AttributionReport(
        records=tuple(records), skipped=tuple(skipped),
        wall_seconds=time.perf_counter() - t0)
    _emit_observability(report)
    return report
