"""Telemetry, profiling, and structured failure reporting."""

from aiyagari_tpu.diagnostics.errors import (
    ConvergenceError,
    ConvergenceWarning,
    enforce_convergence,
)
from aiyagari_tpu.diagnostics.logging import (
    CollectSink,
    ConsoleSink,
    JSONLSink,
    multiplex,
)

__all__ = [
    "ConvergenceError",
    "ConvergenceWarning",
    "enforce_convergence",
    "CollectSink",
    "ConsoleSink",
    "JSONLSink",
    "multiplex",
]
