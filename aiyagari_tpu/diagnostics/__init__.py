"""Telemetry, profiling, tracing, metrics, and structured failure reporting."""

from aiyagari_tpu.diagnostics.errors import (
    ConvergenceError,
    ConvergenceWarning,
    enforce_convergence,
)
from aiyagari_tpu.diagnostics.logging import (
    CollectSink,
    ConsoleSink,
    JSONLSink,
    coerce_record,
    multiplex,
)
from aiyagari_tpu.diagnostics.progress import (
    capture_progress,
    configure_heartbeat,
    device_progress,
    heartbeat_stride,
    subscribe,
)

__all__ = [
    "capture_progress",
    "configure_heartbeat",
    "device_progress",
    "heartbeat_stride",
    "subscribe",
    "ConvergenceError",
    "ConvergenceWarning",
    "enforce_convergence",
    "CollectSink",
    "ConsoleSink",
    "JSONLSink",
    "coerce_record",
    "multiplex",
    # Heavier observability layers import on demand (they pull in jax or
    # filesystem machinery the light users of this package don't need):
    #   diagnostics.telemetry — device-resident flight recorders
    #   diagnostics.ledger    — append-only JSONL run ledger
    #   diagnostics.trace     — nested wall-clock spans
    #   diagnostics.metrics   — process-wide counter/gauge/histogram registry
    #   diagnostics.health    — health certificates + report CLI
    #   diagnostics.skew      — mesh rendezvous / straggler probes
    #   diagnostics.watch     — live sweep watch CLI (shard tail + merge)
    #   diagnostics.bench_history — frozen-bench regression watchdog
    #   diagnostics.sentinel  — device-resident failure sentinels
    #   diagnostics.faults    — deterministic fault injection (CI harness)
    #   diagnostics.rescue    — the host-side rescue ladder
]
