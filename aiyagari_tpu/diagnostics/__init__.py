"""Telemetry, profiling, and structured failure reporting."""

from aiyagari_tpu.diagnostics.errors import (
    ConvergenceError,
    ConvergenceWarning,
    enforce_convergence,
)
from aiyagari_tpu.diagnostics.logging import (
    CollectSink,
    ConsoleSink,
    JSONLSink,
    multiplex,
)
from aiyagari_tpu.diagnostics.progress import (
    capture_progress,
    device_progress,
    subscribe,
)

__all__ = [
    "capture_progress",
    "device_progress",
    "subscribe",
    "ConvergenceError",
    "ConvergenceWarning",
    "enforce_convergence",
    "CollectSink",
    "ConsoleSink",
    "JSONLSink",
    "multiplex",
]
